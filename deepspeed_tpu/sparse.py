"""Row-sparse (CSR-style) gradient representation.

Parity port of /root/reference/deepspeed/pt/deepspeed_csr_tensor.py
(`CSRTensor`, à la TF IndexedSlices): nonzero-row ``indices`` + ``values``,
``to_dense`` scatter-add, ``add`` by concatenation.  The reference engine
routes ``nn.Embedding`` gradients through an allgather of (indices, values)
instead of a dense allreduce (deepspeed_light.py:884-940) because embedding
grads on commodity interconnects are bandwidth-bound and row-sparse.

On TPU the trade-off is explicit: ``sparse_psum`` below is the jit-native
version of that reduction — a STATICALLY bounded gather of (indices, values)
with a dense-psum fallback — and the engine routes gradients of leaves a
model marks via ``sparse_grad_specs`` through it when the
``sparse_gradients`` config flag is on (engine.py ``_make_step_local``).
The win condition is a big table with few touched rows per step
(``world * max_rows << rows``); when the bound can't beat the dense psum the
function statically degrades to it.  ``CSRTensor``/``csr_allreduce`` keep
the reference's host-side API for gradient inspection and parity tests.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np


class CSRTensor:
    """Row-sparse tensor: ``indices`` (nonzero row ids) + ``values`` (those
    rows).  Reference: deepspeed_csr_tensor.py:11-59."""

    def __init__(self, dense=None):
        self.orig_dense_size = None
        self.indices = None
        self.values = None
        if dense is not None:
            dense = jnp.asarray(dense)
            self.orig_dense_size = tuple(dense.shape)
            row_nnz = jnp.any(dense != 0, axis=tuple(range(1, dense.ndim)))
            idx = jnp.nonzero(row_nnz)[0]
            self.indices = idx
            self.values = dense[idx]

    @classmethod
    def type(cls):
        return "deepspeed_tpu.sparse.CSRTensor"

    @classmethod
    def from_parts(cls, indices, values, dense_size) -> "CSRTensor":
        t = cls()
        t.indices = jnp.asarray(indices)
        t.values = jnp.asarray(values)
        t.orig_dense_size = tuple(dense_size)
        return t

    @property
    def dense_size(self):
        return self.orig_dense_size

    def add(self, other: "CSRTensor") -> None:
        """Sparse accumulate by concatenation (duplicate rows resolved by the
        scatter-add in ``to_dense``).  Reference :45-57."""
        assert self.orig_dense_size == other.orig_dense_size, (
            "Cannot add tensors of different dense sizes")
        self.indices = jnp.concatenate([self.indices, other.indices])
        self.values = jnp.concatenate([self.values, other.values])

    def scale(self, factor) -> "CSRTensor":
        return CSRTensor.from_parts(self.indices, self.values * factor,
                                    self.orig_dense_size)

    def to_dense(self) -> jnp.ndarray:
        """Scatter-add back to dense (reference :29-43)."""
        out = jnp.zeros(self.orig_dense_size,
                        self.values.dtype if self.values is not None
                        else jnp.float32)
        if self.indices is None or self.indices.size == 0:
            return out
        return out.at[self.indices].add(self.values)

    def sparse_size(self):
        nnz = int(self.indices.size) * int(np.prod(self.values.shape[1:]))
        return nnz, int(np.prod(self.orig_dense_size))


def sparse_psum(g: jnp.ndarray,
                axis_name: str,
                world_size: int,
                max_rows: int,
                fp32_allreduce: bool = False,
                prescale_gradients: bool = False,
                gradient_predivide_factor: float = 1.0) -> jnp.ndarray:
    """Row-sparse DP reduction of a dense local gradient, inside shard_map.

    The engine-integrated analog of the reference's sparse_allreduce
    (deepspeed_light.py:884-940): each shard extracts its touched rows as
    (indices, values) with a STATIC bound ``max_rows``, all-gathers both over
    the axis, and scatter-adds back to dense — moving
    ``world * max_rows * (H+1)`` elements instead of ``V * H``.  When any
    shard touches more than ``max_rows`` rows (agreed via a pmax so every
    shard takes the same branch) the reduction falls back to the dense psum,
    so results are always exact.  Scaling knobs match
    ``comm.allreduce_grads``."""
    from deepspeed_tpu.parallel import comm

    rows = g.shape[0]
    max_rows = int(min(max_rows, rows))
    if world_size * max_rows >= rows:
        # the gather would move at least as much as the dense all-reduce
        # (world * max_rows rows vs ~2 * rows) — statically take the psum,
        # also skipping the per-step mask/top_k/scatter work
        return comm.scaled_reduce(
            g, lambda x: jax.lax.psum(x, axis_name), world_size,
            fp32_allreduce=fp32_allreduce,
            prescale_gradients=prescale_gradients,
            gradient_predivide_factor=gradient_predivide_factor)

    def reduce_fn(g):
        mask = jnp.any(g != 0, axis=tuple(range(1, g.ndim)))
        nnz = jnp.sum(mask.astype(jnp.int32))
        nnz_max = jax.lax.pmax(nnz, axis_name)

        def sparse_branch(g):
            # top_k over the 0/1 mask = touched-row indices first, O(V) vs
            # a full argsort
            _, idx = jax.lax.top_k(mask.astype(jnp.int32), max_rows)
            valid = mask[idx]
            bshape = (-1,) + (1,) * (g.ndim - 1)
            vals = jnp.where(valid.reshape(bshape), g[idx], 0)
            idx = jnp.where(valid, idx, 0)              # padded rows add 0s
            idx_all = jax.lax.all_gather(idx, axis_name, axis=0, tiled=True)
            vals_all = jax.lax.all_gather(vals, axis_name, axis=0,
                                          tiled=True)
            return jnp.zeros_like(g).at[idx_all].add(vals_all)

        def dense_branch(g):
            return jax.lax.psum(g, axis_name)

        return jax.lax.cond(nnz_max <= max_rows, sparse_branch, dense_branch,
                            g)

    return comm.scaled_reduce(
        g, reduce_fn, world_size,
        fp32_allreduce=fp32_allreduce,
        prescale_gradients=prescale_gradients,
        gradient_predivide_factor=gradient_predivide_factor)


def csr_allreduce(shards: List[CSRTensor],
                  world_size: Optional[int] = None) -> jnp.ndarray:
    """Reference csr_allreduce semantics (deepspeed_light.py:884-940): each
    rank's (indices, values) are pre-divided by world size, all-gathered,
    concatenated and densified.  Host-level helper: ``shards`` is the gathered
    list; returns the averaged dense gradient."""
    world = world_size if world_size is not None else len(shards)
    total = shards[0].scale(1.0 / world)
    for s in shards[1:]:
        total.add(s.scale(1.0 / world))
    return total.to_dense()
