"""Synthetic heavy-traffic driver + serve telemetry.

The driver generates a deterministic request trace (seeded prompt/length
mix — the "millions of users" stand-in every serving bench and CI smoke
run replays identically), runs it through a scheduler, and reports the
serving headline numbers: tokens/s/chip and p50/p99 time-to-first-token
and inter-token latency.

Telemetry rides the PR 7/9 machinery unchanged: window events
(``dstpu.telemetry.serve`` v1, one line per window of decode
iterations) and the cold-start startup event
(``dstpu.telemetry.startup`` v2, carrying ``restore_seconds`` and
compile-cache hit/miss counters exactly like the training event) are
emitted through :class:`~deepspeed_tpu.observability.registry.JsonlSink`
and validated by the same ``python -m deepspeed_tpu.observability``
CLI (schema.py is version-aware across all four schemas).
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional

import jax
import numpy as np

from deepspeed_tpu.inference.scheduler import (ContinuousScheduler, Request,
                                               latency_samples_ms,
                                               latency_summary, percentile)

logger = logging.getLogger(__name__)


def synthetic_requests(n: int, *, vocab: int, seed: int = 0,
                       prompt_min: int = 4, prompt_max: int = 24,
                       new_min: int = 4, new_max: int = 24,
                       eos_id: Optional[int] = None) -> List[Request]:
    """Deterministic mixed-length trace: uniform prompt lengths and
    token budgets — the variance is what makes continuous batching win
    (uniform-length traffic would let static batching tie)."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        plen = int(rng.integers(prompt_min, prompt_max + 1))
        prompt = rng.integers(0, vocab, size=plen).astype(int).tolist()
        reqs.append(Request(
            rid=i, prompt=prompt,
            max_new_tokens=int(rng.integers(new_min, new_max + 1)),
            eos_id=eos_id))
    return reqs


class ServeTelemetry:
    """Windowed serve-event emitter: every ``window_iters`` scheduler
    iterations fold into one ``dstpu.telemetry.serve`` line; the startup
    event goes out once, at the first token (when restore latency and the
    compile-cache counters are all known facts)."""

    def __init__(self, engine, jsonl_path: Optional[str] = None,
                 window_iters: int = 8):
        if window_iters < 1:
            raise ValueError("window_iters must be >= 1")
        self.engine = engine
        self.window_iters = int(window_iters)
        self.sink = None
        if jsonl_path:
            from deepspeed_tpu.observability.registry import JsonlSink
            self.sink = JsonlSink(jsonl_path)
        self._startup_emitted = False
        self._window = 0
        self._reset_window()
        self.last_event = None

    def _reset_window(self):
        self._iters = 0
        self._tokens = 0
        self._admitted = 0
        self._active_sum = 0
        self._queue_depth = 0
        self._t0 = time.perf_counter()

    def _emit(self, event: dict):
        self.last_event = event
        if self.sink is not None:
            self.sink.emit(event)

    def on_iteration(self, sched, stats: dict):
        """Scheduler hook (``ContinuousScheduler(on_event=...)``)."""
        if not self._startup_emitted and self.engine.first_token_ts:
            self._startup_emitted = True
            self._emit(self.engine.startup_event())
        self._iters += 1
        self._tokens += stats["tokens_out"]
        self._admitted += stats["admitted"]
        self._active_sum += stats["active"]
        self._queue_depth = stats["queue_depth"]
        if self._iters >= self.window_iters:
            self.flush(sched)

    def flush(self, sched):
        """Emit the current (possibly partial) window; final partial
        windows are part of the record, like the training spool's."""
        if self._iters == 0:
            return
        from deepspeed_tpu.observability import schema
        from deepspeed_tpu.resilience import COUNTERS
        elapsed = time.perf_counter() - self._t0
        # percentiles are CUMULATIVE over the run's completed requests
        # (bench/CI traces are bounded and short traces need every
        # sample for a stable tail; a long-lived replica would swap in
        # reservoir sampling here to bound the per-window cost)
        ttft, itl = latency_samples_ms(sched.results)
        self._window += 1
        spec = self.engine.cache_spec
        from deepspeed_tpu.inference import kvcache
        event = {
            "schema": schema.SERVE_SCHEMA_ID,
            "version": schema.SERVE_SCHEMA_VERSION,
            "ts": time.time(),
            "window": self._window,
            "decode_iters": self._iters,
            "tokens_out": self._tokens,
            "admitted": self._admitted,
            "evicted": sched.evicted,
            "active_slots_mean": round(self._active_sum
                                       / max(1, self._iters), 3),
            "queue_depth": self._queue_depth,
            "slots": spec.slots,
            "kv_cache_gb": round(kvcache.cache_bytes(spec) / 2 ** 30, 6),
            "tokens_per_sec": (round(self._tokens / elapsed, 3)
                               if elapsed > 0 else None),
            "ttft_p50_ms": percentile(ttft, 50),
            "ttft_p99_ms": percentile(ttft, 99),
            "itl_p50_ms": percentile(itl, 50),
            "itl_p99_ms": percentile(itl, 99),
            # ---- v2: prefix reuse + speculative decoding (cumulative
            # over the scheduler's lifetime, like `evicted`)
            "prefix_hits": int(getattr(sched, "prefix_hits", 0)),
            "prefix_tokens_reused": int(getattr(sched,
                                                "prefix_tokens_reused", 0)),
            "spec_proposed": int(getattr(sched, "spec_proposed", 0)),
            "spec_accepted": int(getattr(sched, "spec_accepted", 0)),
            "counters": COUNTERS.as_dict(),
        }
        self._emit(event)
        self._reset_window()

    def close(self):
        if self.sink is not None:
            self.sink.close()


def run_serve(engine, requests, *, jsonl_path: Optional[str] = None,
              window_iters: int = 8, sampler=None) -> dict:
    """Run ``requests`` through continuous batching with telemetry;
    returns ``{"results", "summary"}`` where summary is
    :func:`~deepspeed_tpu.inference.scheduler.latency_summary` plus the
    scheduler's utilization counters."""
    from deepspeed_tpu.inference.scheduler import greedy_sampler
    tel = ServeTelemetry(engine, jsonl_path=jsonl_path,
                         window_iters=window_iters)
    sched = ContinuousScheduler(engine, sampler=sampler or greedy_sampler,
                                on_event=tel.on_iteration)
    t0 = time.perf_counter()
    results = sched.run(requests)
    elapsed = time.perf_counter() - t0
    tel.flush(sched)
    tel.close()
    summary = latency_summary(results, elapsed,
                              n_chips=len(engine.mesh.devices.flat))
    prompt_tokens = sum(r.prompt_len for r in results)
    summary.update({
        "decode_iters": sched.decode_iters,
        "admitted": sched.admitted,
        "evicted": sched.evicted,
        "slots": engine.num_slots,
        "quantize": engine.quantize,
        "dtype": str(np.dtype(engine.compute_dtype)),
        "mp": engine.mp_world_size,
        "platform": jax.devices()[0].platform,
        "device_kind": jax.devices()[0].device_kind,
        # prefix reuse: hit rate over admissions, prompt tokens whose
        # prefill was served from shared pages instead of recomputed
        "prefix_hit_rate": (round(sched.prefix_hits
                                  / sched.admitted, 4)
                            if sched.admitted else None),
        "prefill_tokens_saved": sched.prefix_tokens_reused,
        "prefill_tokens_total": prompt_tokens,
        "admission_refusals": sched.admission_refusals,
        # speculative decoding: accepted draft proposals / proposed
        "spec_accept_rate": (round(sched.spec_accepted
                                   / sched.spec_proposed, 4)
                             if sched.spec_proposed else None),
        "spec_proposed": sched.spec_proposed,
        "spec_accepted": sched.spec_accepted,
        "draft_params": (_count_tree_params(engine.draft_params)
                         if engine.draft_params is not None else None),
    })
    return {"results": results, "summary": summary}


def _count_tree_params(tree) -> int:
    import jax as _jax
    leaves = _jax.tree_util.tree_leaves(tree)
    return int(sum(np.asarray(l).size for l in leaves))
