"""int8 weight-only quantization at checkpoint load (serving).

Weights quantize on the HOST, on the GLOBAL tree, BEFORE device
placement: per-output-channel symmetric int8 (``q = round(w / s)``,
``s = max|w| / 127`` over the contraction axis), so the scale of every
output channel is identical on every tensor-parallel rank — which is
what lets the row-parallel matmul dequantize per shard and still psum
correctly (models/layers.py ``row_parallel_linear``).

A quantized leaf becomes a ``{"q": int8, "s": compute-dtype keepdims
scale}`` subtree; the model's linear primitives detect it
(``layers.is_quantized``) and dispatch through the matmul-dequant table
(``layers.quant_matmul_plan``, env ``DSTPU_QUANT_MATMUL``).  Exactness
contract (docs/inference.md "Quantization"): int8 serving is NOT
bit-exact — the pinned guarantee is relative logit error within the
documented tolerance vs the same-dtype unquantized engine, and
"scaled" vs "dequant" impls agreeing within float rounding.

What quantizes (GPT-2 family): the four block matmuls (``qkv_w``,
``proj_w``, ``fc_w``, ``fc2_w``; per-layer per-output-channel scales on
the stacked [L, ...] leaves) and the tied embedding/LM-head ``wte``
(per-row scales — the row is both the embedding output channel and the
logit output channel).  LayerNorms, biases and ``wpe`` stay in the
serving compute dtype: they are O(hidden) bytes, and int8 there buys
nothing.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import layers as L

#: engine-protocol leaf name -> contraction axis reduced by the scale
#: (the OTHER >=1-sized axis is the output channel).  Stacked block
#: leaves carry a leading layer axis the scale keeps.
GPT2_QUANT_PLAN = {
    "qkv_w": 1,      # [L, in, out] -> scale [L, 1, out]
    "proj_w": 1,
    "fc_w": 1,
    "fc2_w": 1,
    "wte": 1,        # [vocab, hid] -> scale [vocab, 1] (per-row)
}


def quantize_leaf(w, reduce_axis: int, compute_dtype):
    """Symmetric per-channel int8: returns ``{"q", "s"}`` with the scale
    keepdims-shaped (broadcast-ready) in the COMPUTE dtype — dequant
    lands directly in the serving dtype with no extra cast."""
    w32 = np.asarray(w, np.float32)
    amax = np.max(np.abs(w32), axis=reduce_axis, keepdims=True)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w32 / scale), -127, 127).astype(np.int8)
    return {"q": q, "s": scale.astype(jnp.dtype(compute_dtype))}


def quantize_tree(params, compute_dtype, plan=None):
    """Quantize every leaf whose NAME is in ``plan`` (host trees only).
    Returns the mixed tree: quantized subtrees + untouched leaves."""
    plan = GPT2_QUANT_PLAN if plan is None else plan

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for name, sub in node.items():
            if isinstance(sub, dict):
                out[name] = walk(sub)
            elif name in plan:
                out[name] = quantize_leaf(sub, plan[name], compute_dtype)
            else:
                out[name] = sub
        return out

    if not isinstance(params, dict):
        raise ValueError(
            "int8 quantization expects a dict-shaped param tree (the "
            "engine-protocol model family)")
    return walk(params)


def quantize_specs(specs, plan=None):
    """PartitionSpec tree matching :func:`quantize_tree`'s output: the
    int8 payload keeps the weight's spec; the keepdims scale keeps the
    spec with the REDUCED dim unsharded (its size is 1)."""
    plan = GPT2_QUANT_PLAN if plan is None else plan

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        for name, sub in node.items():
            if isinstance(sub, dict):
                out[name] = walk(sub)
            elif name in plan and isinstance(sub, P):
                axis = plan[name]
                entries = list(sub) + [None] * max(0, axis + 1 - len(sub))
                entries[axis] = None
                out[name] = {"q": sub, "s": P(*entries)}
            else:
                out[name] = sub
        return out

    return walk(specs)


# re-export the dispatch-table surface next to the quantizer
is_quantized = L.is_quantized
quant_matmul_plan = L.quant_matmul_plan
