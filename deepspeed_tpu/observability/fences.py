"""Host-fence accounting — the choke point every deliberate device sync
goes through.

A "fence" is any host-side wait on device data: ``block_until_ready``,
``device_get``/``np.asarray`` of a device array, or a ``bool()``/``float()``
read of a device scalar.  Each one serializes host dispatch with device
execution — a fixed per-step cost gradient accumulation cannot amortize
(WALLCLOCK §7) — so the telemetry layer's whole design goal is to keep them
off the per-step path: metrics spool through a device ring buffer and drain
once per report window (observability/spool.py).

Every fence the engine takes ON PURPOSE routes through this module
(``utils.timer._fence``, the boundary overflow read, the spool flush), so
the regression contract "zero fences off report steps" is a COUNTER the
tests pin (tests/test_observability.py), not a code-review convention.
"""

from __future__ import annotations

#: process-wide count of deliberate host fences (monotonic; tests snapshot
#: around a region and assert the delta)
FENCE_COUNT = 0


def count_fence(n: int = 1) -> None:
    """Record ``n`` deliberate host fences (called by the sites that wait)."""
    global FENCE_COUNT
    FENCE_COUNT += n


def fence_on(sync_on) -> None:
    """``block_until_ready`` every array leaf of ``sync_on`` (None = no-op),
    counting ONE fence for the whole pytree — it is one host wait, however
    many leaves drain behind it."""
    if sync_on is None:
        return
    import jax
    leaves = [l for l in jax.tree_util.tree_leaves(sync_on)
              if hasattr(l, "block_until_ready")]
    if not leaves:
        return
    count_fence()
    for leaf in leaves:
        leaf.block_until_ready()


def read_scalar(x):
    """Fetch one device scalar to host (a fence) and return the Python
    value.  The engine's boundary overflow read routes through here."""
    import numpy as np
    if hasattr(x, "block_until_ready") or hasattr(x, "addressable_shards"):
        count_fence()
    return np.asarray(x).item()


def read_arrays(*xs):
    """Fetch device arrays to host numpy (one counted fence for the batch).
    The spool's synchronous flush routes through here."""
    import numpy as np
    if any(hasattr(x, "block_until_ready") for x in xs):
        count_fence()
    return tuple(np.asarray(x) for x in xs)
