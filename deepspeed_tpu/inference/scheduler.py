"""Request scheduling: continuous batching vs the static baseline.

Continuous batching (the serving default): every decode iteration runs
ALL active slots as one compiled step, and between iterations the
scheduler admits queued requests into whatever slots just freed
(EOS / max-token eviction) — no slot ever idles waiting for the longest
request in a "batch" to finish.  The static scheduler is the honest
baseline the bench compares against: it forms fixed batches in arrival
order and decodes each batch until its LAST member finishes, so short
requests burn decode iterations producing nothing and later batches
queue behind the stragglers.

Per-slot bookkeeping is position/length arithmetic only — the KV cache
itself lives on device (inference/kvcache.py) and each slot's attention
is masked strictly by its own position, so a slot's output stream is
IDENTICAL whether it shares iterations with 0 or ``slots-1`` neighbours
(the batching-invariance pin in tests/test_inference.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

import numpy as np


@dataclasses.dataclass
class Request:
    """One generation request."""
    rid: int
    prompt: List[int]                 # prompt token ids (non-empty)
    max_new_tokens: int = 16
    eos_id: Optional[int] = None      # stop token (None = length-only)

    def __post_init__(self):
        if not self.prompt:
            raise ValueError(f"request {self.rid}: empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"request {self.rid}: max_new_tokens must be >= 1")


@dataclasses.dataclass
class RequestResult:
    """Completed request: generated tokens + the whole lifecycle as
    numbers (seconds, measured host-side at token delivery) — the
    per-request record the summary percentiles and the
    ``dstpu.telemetry.request`` events are derived from."""
    rid: int
    tokens: List[int]
    finish_reason: str                # "eos" | "length"
    ttft_s: Optional[float] = None    # enqueue -> first token
    itl_s: List[float] = dataclasses.field(default_factory=list)
    prompt_len: int = 0
    # ---- lifecycle breakdown (PR 14): submit -> admit -> first token
    # -> eviction, plus the admission's page-table facts
    queue_wait_s: Optional[float] = None   # submit -> admission dispatch
    prefill_s: Optional[float] = None      # admission dispatch -> 1st token
    finished_ts: Optional[float] = None    # completion wall time
    slot: Optional[int] = None             # decode slot served in
    prefix_hit: bool = False               # admission reused shared pages
    reused_tokens: int = 0                 # prompt tokens not re-prefilled
    pages_mapped: int = 0                  # page-table entries mapped

    @property
    def decode_s(self) -> Optional[float]:
        """First token -> last token (sum of inter-token gaps); None on a
        one-token request."""
        return sum(self.itl_s) if self.itl_s else None

    @property
    def itl_mean_s(self) -> Optional[float]:
        """The request's mean inter-token gap — its ONE ITL sample in the
        per-request percentiles.  Robust under fused decode: within a
        D-block all but the first gap are honestly ~0, but the mean is
        total decode time over tokens, comparable across D."""
        return (sum(self.itl_s) / len(self.itl_s)) if self.itl_s else None


def greedy_sampler(logits_row: np.ndarray) -> int:
    """Deterministic argmax over the full-vocab logits row — the decode
    oracle's sampler (docs/inference.md)."""
    return int(np.argmax(logits_row))


def percentile(xs, p: float) -> Optional[float]:
    """Nearest-rank percentile (None on empty) — shared by the latency
    report and the bench leg."""
    if not xs:
        return None
    xs = sorted(xs)
    k = max(0, min(len(xs) - 1, int(round(p / 100.0 * (len(xs) - 1)))))
    return float(xs[k])


def latency_samples_ms(results):
    """``(ttft_ms, itl_ms)`` pooled sample lists over completed results
    (every per-token gap is one ITL sample).  Kept for the pooled
    ``itl_mean_ms`` and raw-sample consumers; the summary PERCENTILES
    come from :func:`request_latency_ms` — pooled per-token percentiles
    are degenerate under fused decode (D-1 of every D gaps are ~0, so
    the per-token p50 honestly collapses to 0 at D>1)."""
    return ([r.ttft_s * 1e3 for r in results if r.ttft_s is not None],
            [dt * 1e3 for r in results for dt in r.itl_s])


def request_latency_ms(results):
    """``(ttft_ms, itl_ms, queue_wait_ms)`` PER-REQUEST sample lists —
    one sample per completed request (a request's ITL sample is its mean
    inter-token gap, :attr:`RequestResult.itl_mean_s`).  The one owner
    of the summary/telemetry percentile inputs: percentiles over these
    stay meaningful at any ``decode_iters_per_dispatch``."""
    return ([r.ttft_s * 1e3 for r in results if r.ttft_s is not None],
            [r.itl_mean_s * 1e3 for r in results
             if r.itl_mean_s is not None],
            [r.queue_wait_s * 1e3 for r in results
             if r.queue_wait_s is not None])


def latency_summary(results, elapsed_s: float, n_chips: int = 1) -> dict:
    """tokens/s(/chip) + p50/p99 TTFT / inter-token latency / queue wait
    over a completed trace (milliseconds, like the telemetry events).

    Percentiles are PER-REQUEST (:func:`request_latency_ms`): each
    completed request contributes one TTFT, one queue-wait and one
    mean-ITL sample, so the tail measures slow REQUESTS — and stays
    comparable across ``decode_iters_per_dispatch`` (the old pooled
    per-token p50 read 0 at D>1).  ``itl_mean_ms`` remains the pooled
    per-token mean, the cross-D throughput-per-token number
    (docs/inference.md "Fused decode")."""
    ttft, itl_req, queue_wait = request_latency_ms(results)
    _, itl_pooled = latency_samples_ms(results)
    tokens = sum(len(r.tokens) for r in results)
    tps = tokens / elapsed_s if elapsed_s > 0 else None
    return {
        "requests": len(results),
        "tokens_out": tokens,
        "elapsed_s": round(elapsed_s, 4),
        "tokens_per_sec": None if tps is None else round(tps, 2),
        "tokens_per_sec_per_chip": (None if tps is None
                                    else round(tps / max(1, n_chips), 2)),
        "ttft_p50_ms": percentile(ttft, 50),
        "ttft_p99_ms": percentile(ttft, 99),
        "itl_p50_ms": percentile(itl_req, 50),
        "itl_p99_ms": percentile(itl_req, 99),
        "itl_mean_ms": (round(float(np.mean(itl_pooled)), 4)
                        if itl_pooled else None),
        "queue_wait_p50_ms": percentile(queue_wait, 50),
        "queue_wait_p99_ms": percentile(queue_wait, 99),
    }


def _stops(req: Request, tok: int, n_generated: int) -> bool:
    return ((req.eos_id is not None and tok == req.eos_id)
            or n_generated >= req.max_new_tokens)


def _check_request(engine, req: Request) -> None:
    """Submit-time admission checks: a bad request must be rejected
    BEFORE it enters a drain, not explode mid-iteration and discard
    every in-flight neighbour's work.  Two budgets: the prefill bucket
    (prompt length) and the engine's total-token budget
    (``max_total_tokens``: position-embedding range, plus paged-cache
    capacity — past either, decode would silently clamp and the
    exactness contract would break)."""
    if len(req.prompt) > engine.prefill_bucket:
        raise ValueError(
            f"request {req.rid}: prompt of {len(req.prompt)} tokens "
            f"exceeds the prefill bucket ({engine.prefill_bucket}) — "
            f"raise inference.prefill_bucket/max_tokens")
    budget = engine.max_total_tokens()
    if budget is not None and len(req.prompt) + req.max_new_tokens > budget:
        raise ValueError(
            f"request {req.rid}: prompt ({len(req.prompt)}) + "
            f"max_new_tokens ({req.max_new_tokens}) exceeds the "
            f"per-request token budget ({budget} = min of paged cache "
            f"capacity and the model's max_seq_len); shorten the "
            f"request, raise inference.max_tokens, or use the ring "
            f"layout's sliding window (docs/inference.md)")


@dataclasses.dataclass
class KVHandoff:
    """A prefilled request in flight between pools: the prefill
    replica's output (first token + the slot's written KV rows) plus
    the lifecycle timestamps the decode replica must PRESERVE — TTFT
    was measured when the prefill produced the first token, and queue
    wait keeps anchoring at the user's original submit
    (docs/inference.md "Fleet serving")."""
    req: Request
    prompt: List[int]            # the full prompt (page hashing + admit)
    first_token: int             # sampled from the prefill's logits row
    k: "np.ndarray"              # [L, n_tokens, kv_heads(global), d]
    v: "np.ndarray"
    n_tokens: int                # rows written (== len(prompt))
    t_enqueue: float             # the user's ORIGINAL submit time
    t_admit: float               # prefill admission dispatch start
    t_first_token: float         # first-token sample time (TTFT anchor)
    path: Optional[str] = None   # sealed artifact file (router cleanup)


class _Slot:
    """Host-side mirror of one decode slot."""

    __slots__ = ("req", "generated", "last_token", "t_enqueue", "t_last",
                 "ttft", "itl", "queue_wait", "prefill_s", "prefix_hit",
                 "reused_tokens", "pages_mapped")

    def __init__(self, req: Request, first_token: int, t_enqueue: float,
                 now: float, t_admit: Optional[float] = None,
                 reused_tokens: int = 0, pages_mapped: int = 0):
        self.req = req
        self.generated = [first_token]
        self.last_token = first_token
        self.t_enqueue = t_enqueue
        self.t_last = now
        self.ttft = now - t_enqueue
        self.itl = []
        # lifecycle breakdown: queue wait ends when the admission
        # dispatch starts; prefill is dispatch -> first token
        self.queue_wait = (t_admit - t_enqueue
                           if t_admit is not None else None)
        self.prefill_s = now - t_admit if t_admit is not None else None
        self.prefix_hit = reused_tokens > 0
        self.reused_tokens = int(reused_tokens)
        self.pages_mapped = int(pages_mapped)


class ContinuousScheduler:
    """Admit-into-free-slots continuous batching over one
    :class:`~deepspeed_tpu.inference.engine.InferenceEngine`.

    ``step()`` is one scheduler iteration: admission (prefill each newly
    admitted request — its first token counts as TTFT), then ONE decode
    program dispatch covering every active slot, then eviction.  Run to
    drain with :meth:`run`."""

    def __init__(self, engine, sampler: Callable = greedy_sampler,
                 on_event: Optional[Callable] = None,
                 on_complete: Optional[Callable] = None):
        self.engine = engine
        self.sampler = sampler
        self.on_event = on_event          # telemetry hook (driver.py)
        self.on_complete = on_complete    # per-request record hook:
                                          # called with each RequestResult
                                          # at eviction (request events)
        self.queue: List[tuple] = []      # (request, t_enqueue)
        self.handoffs: List[KVHandoff] = []   # prefilled, awaiting import
        self.slots: List[Optional[_Slot]] = [None] * engine.num_slots
        self.results: List[RequestResult] = []
        self.decode_iters = 0
        self.admitted = 0
        self.evicted = 0
        # prefix-reuse / speculative telemetry (serve schema v2 columns)
        self.prefix_hits = 0
        self.prefix_tokens_reused = 0
        self.admission_refusals = 0
        self.spec_proposed = 0
        self.spec_accepted = 0

    # ------------------------------------------------------------- intake
    def submit(self, request: Request, now: Optional[float] = None):
        """Queue a request.  ``now`` overrides the enqueue timestamp —
        a fleet router resubmitting a request displaced by replica
        death passes the ORIGINAL arrival time, so queue-wait/TTFT
        percentiles keep measuring from the user's submit instead of
        silently resetting (the :meth:`evacuate` contract)."""
        _check_request(self.engine, request)
        self.queue.append((request, time.perf_counter()
                           if now is None else now))

    def submit_handoff(self, handoff: KVHandoff) -> None:
        """Queue a PREFILLED request (KV handed off from a prefill
        replica): admission imports the rows into a free slot instead
        of dispatching prefill — the decode pool's intake
        (docs/inference.md "Fleet serving").  The request must fit this
        engine's budgets exactly like a fresh submit."""
        _check_request(self.engine, handoff.req)
        self.handoffs.append(handoff)

    def _admit_handoffs(self) -> int:
        """Import queued handoffs into free slots; returns tokens landed
        (each handoff arrives WITH its first token).  A pool refusal
        keeps the remaining handoffs queued — transient, like the
        regular admission path."""
        admitted_tokens = 0
        for i in range(len(self.slots)):
            if not self.handoffs or self.slots[i] is not None:
                continue
            h = self.handoffs[0]
            grant = self.engine.import_kv(
                i, h.prompt, h.k, h.v, h.req.max_new_tokens)
            if grant is None:
                self.admission_refusals += 1
                break            # pool exhausted: no later slot differs
            self.handoffs.pop(0)
            if grant.reused_tokens:
                self.prefix_hits += 1
                self.prefix_tokens_reused += grant.reused_tokens
            # lifecycle bookkeeping PRESERVES the prefill-side times:
            # TTFT anchored at the prefill's first-token sample, queue
            # wait at the user's original submit
            self.slots[i] = _Slot(
                h.req, h.first_token, h.t_enqueue, h.t_first_token,
                t_admit=h.t_admit, reused_tokens=grant.reused_tokens,
                pages_mapped=len(self.engine.pool.slot_pages(i)))
            self.admitted += 1
            admitted_tokens += 1
            if _stops(h.req, h.first_token, 1):
                self._evict(i)
        return admitted_tokens

    @property
    def active(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    @property
    def pending(self) -> int:
        return len(self.queue) + len(self.handoffs)

    def evacuate(self) -> List[tuple]:
        """Pull every in-flight AND queued request back out as
        ``(request, t_enqueue)`` pairs, releasing their engine slots —
        the replica-eviction path (docs/inference.md "Fleet serving").

        The pairs carry each request's ORIGINAL arrival timestamp: a
        request displaced by replica death must re-enter the surviving
        replica's queue via ``submit(req, now=t_enqueue)``, so its queue
        wait and TTFT keep accruing from the user's submit.  Resubmitting
        with a fresh timestamp would silently reset TTFT at the exact
        moment the fleet is slowest — the tail percentiles would lie.
        Partial generations are discarded: greedy decode re-derives the
        identical token stream from the prompt (the exactness contract),
        so nothing is lost but the wasted iterations.

        In-flight requests come first (they arrived before anything
        still queued), each pool page they held is released, and the
        scheduler is left empty and reusable."""
        pairs = []
        for i, s in enumerate(self.slots):
            if s is None:
                continue
            self.slots[i] = None
            self.engine.release(i)
            pairs.append((s.req, s.t_enqueue))
        # un-imported handoffs re-enter as plain requests (the surviving
        # replica re-prefills; greedy identity makes that loss-free)
        pairs.extend((h.req, h.t_enqueue) for h in self.handoffs)
        self.handoffs = []
        pairs.extend(self.queue)
        self.queue = []
        return pairs

    # ------------------------------------------------------------ stepping
    def step(self) -> dict:
        """One scheduler iteration; returns the iteration's stats."""
        eng = self.engine
        # 0) handed-off prefills land first: they arrived before
        # anything still queued and their KV is already paid for
        admitted_now = self._admit_handoffs() if self.handoffs else 0
        # 1) admission: fill free slots from the queue (every queued
        # request already passed the submit-time budget checks).  A
        # prefix-cache hit maps the prompt's page-aligned prefix to
        # shared pages and prefills only the tail; a page-pool refusal
        # (capacity exhausted) keeps the request QUEUED — active slots
        # release pages as they finish, so the refusal is transient
        for i in range(len(self.slots)):
            if not self.queue or self.slots[i] is not None:
                continue
            req, t_enq = self.queue[0]
            t_admit = time.perf_counter()
            res = eng.admit(i, req.prompt, req.max_new_tokens)
            if res is None:
                self.admission_refusals += 1
                break            # pool exhausted: no later slot differs
            self.queue.pop(0)
            logits, reused = res
            now = time.perf_counter()
            if reused:
                self.prefix_hits += 1
                self.prefix_tokens_reused += reused
            tok = self.sampler(logits)
            pool = getattr(eng, "pool", None)
            self.slots[i] = _Slot(
                req, tok, t_enq, now, t_admit=t_admit,
                reused_tokens=reused,
                pages_mapped=(len(pool.slot_pages(i)) if pool is not None
                              else 0))
            self.admitted += 1
            admitted_now += 1
            if _stops(req, tok, 1):
                self._evict(i)

        # 2) decode over every active slot: ONE iteration per dispatch,
        # or — with inference.decode_iters_per_dispatch > 1 and the
        # greedy sampler — D iterations fused into one dispatch
        # (admission/eviction every D tokens; docs/inference.md "Fused
        # decode").  A custom sampler cannot ride the fused path (the
        # token feedback closes on device via argmax), so it falls back
        # loudly to the per-iteration loop.
        tokens_out = admitted_now
        active_idx = [i for i, s in enumerate(self.slots) if s is not None]
        d = int(getattr(eng, "decode_iters_per_dispatch", 1))
        j = int(getattr(eng, "spec_draft_tokens", 0))
        fused = d > 1
        spec = j > 0
        if (fused or spec) and self.sampler is not greedy_sampler:
            eng.note_fused_decode_fallback(
                "the scheduler's sampler is not the greedy sampler (the "
                "fused program closes the token loop with argmax)")
            fused = spec = False
        if active_idx and spec:
            # speculative iteration: ONE dispatch = J draft proposals +
            # target verify + acceptance; up to J+1 tokens land per
            # active slot, token-identical to target-only greedy decode
            # (docs/inference.md "Speculative decoding")
            n = len(self.slots)
            feed = np.zeros((n,), np.int32)
            active = np.zeros((n,), bool)
            eos_ids = np.full((n,), -1, np.int32)
            remaining = np.zeros((n,), np.int32)
            for i in active_idx:
                s = self.slots[i]
                feed[i] = s.last_token
                active[i] = True
                if s.req.eos_id is not None:
                    eos_ids[i] = s.req.eos_id
                remaining[i] = s.req.max_new_tokens - len(s.generated)
            toks, emitted = eng.spec_decode(feed, active, eos_ids,
                                            remaining)
            now = time.perf_counter()
            self.decode_iters += 1
            self.spec_proposed += j * len(active_idx)
            for it in range(toks.shape[0]):
                for i in active_idx:
                    if not emitted[it, i]:
                        continue
                    s = self.slots[i]
                    tok = int(toks[it, i])
                    s.generated.append(tok)
                    s.itl.append(now - s.t_last)
                    s.t_last = now
                    s.last_token = tok
                    tokens_out += 1
                    if it > 0:
                        # tokens past the first are ACCEPTED draft
                        # proposals (the first is the target's own)
                        self.spec_accepted += 1
            for i in active_idx:
                s = self.slots[i]
                if _stops(s.req, s.last_token, len(s.generated)):
                    self._evict(i)
        elif active_idx and fused:
            n = len(self.slots)
            feed = np.zeros((n,), np.int32)
            active = np.zeros((n,), bool)
            eos_ids = np.full((n,), -1, np.int32)
            remaining = np.zeros((n,), np.int32)
            for i in active_idx:
                s = self.slots[i]
                feed[i] = s.last_token
                active[i] = True
                if s.req.eos_id is not None:
                    eos_ids[i] = s.req.eos_id
                remaining[i] = s.req.max_new_tokens - len(s.generated)
            toks, emitted = eng.decode_many(feed, active, eos_ids,
                                            remaining)
            now = time.perf_counter()
            self.decode_iters += d
            for it in range(toks.shape[0]):
                for i in active_idx:
                    if not emitted[it, i]:
                        continue
                    s = self.slots[i]
                    tok = int(toks[it, i])
                    s.generated.append(tok)
                    s.itl.append(now - s.t_last)
                    s.t_last = now
                    s.last_token = tok
                    tokens_out += 1
            for i in active_idx:
                s = self.slots[i]
                if _stops(s.req, s.last_token, len(s.generated)):
                    self._evict(i)
        elif active_idx:
            feed = np.zeros((len(self.slots),), np.int32)
            for i in active_idx:
                feed[i] = self.slots[i].last_token
            active = np.zeros((len(self.slots),), bool)
            active[active_idx] = True
            logits = eng.decode(feed, active)
            now = time.perf_counter()
            self.decode_iters += 1
            for i in active_idx:
                s = self.slots[i]
                tok = self.sampler(logits[i])
                s.generated.append(tok)
                s.itl.append(now - s.t_last)
                s.t_last = now
                s.last_token = tok
                tokens_out += 1
                if _stops(s.req, tok, len(s.generated)):
                    self._evict(i)

        return {
            "admitted": admitted_now,
            "tokens_out": tokens_out,
            "active": len(active_idx),
            "queue_depth": len(self.queue) + len(self.handoffs),
        }

    def _evict(self, slot_idx: int):
        s = self.slots[slot_idx]
        reason = ("eos" if s.req.eos_id is not None
                  and s.generated[-1] == s.req.eos_id else "length")
        self.slots[slot_idx] = None
        self.evicted += 1
        # refcount-- on every page the slot mapped: shared pages survive
        # for their other readers / the LRU prefix cache
        self.engine.release(slot_idx)
        result = RequestResult(
            rid=s.req.rid, tokens=list(s.generated), finish_reason=reason,
            ttft_s=s.ttft, itl_s=list(s.itl),
            prompt_len=len(s.req.prompt),
            queue_wait_s=s.queue_wait, prefill_s=s.prefill_s,
            finished_ts=time.time(), slot=slot_idx,
            prefix_hit=s.prefix_hit, reused_tokens=s.reused_tokens,
            pages_mapped=s.pages_mapped)
        self.results.append(result)
        if self.on_complete is not None:
            self.on_complete(result)

    def run(self, requests=None, max_iters: int = 100000) -> list:
        """Drain: submit ``requests`` (optional) and iterate until every
        slot and the queue are empty.  Returns results in completion
        order."""
        for r in (requests or []):
            self.submit(r)
        it = 0
        while self.queue or self.handoffs or self.active:
            stats = self.step()
            if self.on_event is not None:
                self.on_event(self, stats)
            it += 1
            if it >= max_iters:
                raise RuntimeError(
                    f"scheduler did not drain in {max_iters} iterations "
                    f"({self.active} active, {len(self.queue)} queued)")
        return self.results


class StaticScheduler:
    """The baseline: fixed batches in arrival order, each decoded until
    its LAST request finishes (finished slots keep burning iterations;
    their extra tokens are discarded).  Shares the engine, sampler and
    result shape with :class:`ContinuousScheduler` so the bench compares
    exactly the same trace."""

    def __init__(self, engine, sampler: Callable = greedy_sampler):
        self.engine = engine
        self.sampler = sampler
        self.decode_iters = 0
        self.results: List[RequestResult] = []

    def run(self, requests) -> list:
        eng = self.engine
        n_slots = eng.num_slots
        for r in requests:
            _check_request(eng, r)
        t0 = time.perf_counter()
        enq = {r.rid: t0 for r in requests}
        for start in range(0, len(requests), n_slots):
            batch = requests[start:start + n_slots]
            slots = {}
            for i, req in enumerate(batch):
                t_admit = time.perf_counter()
                logits = eng.prefill(i, req.prompt)
                now = time.perf_counter()
                tok = self.sampler(logits)
                slots[i] = _Slot(req, tok, enq[req.rid], now,
                                 t_admit=t_admit)
            done = {i: _stops(s.req, s.last_token, 1)
                    for i, s in slots.items()}
            while not all(done.values()):
                feed = np.zeros((n_slots,), np.int32)
                active = np.zeros((n_slots,), bool)
                for i, s in slots.items():
                    feed[i] = s.last_token
                    active[i] = True      # finished slots still decode —
                    # the static baseline's waste is the point
                logits = eng.decode(feed, active)
                now = time.perf_counter()
                self.decode_iters += 1
                for i, s in slots.items():
                    if done[i]:
                        continue
                    tok = self.sampler(logits[i])
                    s.generated.append(tok)
                    s.itl.append(now - s.t_last)
                    s.t_last = now
                    s.last_token = tok
                    if _stops(s.req, tok, len(s.generated)):
                        done[i] = True
            for i, s in slots.items():
                reason = ("eos" if s.req.eos_id is not None
                          and s.generated[-1] == s.req.eos_id else "length")
                self.results.append(RequestResult(
                    rid=s.req.rid, tokens=list(s.generated),
                    finish_reason=reason, ttft_s=s.ttft,
                    itl_s=list(s.itl), prompt_len=len(s.req.prompt),
                    queue_wait_s=s.queue_wait, prefill_s=s.prefill_s,
                    finished_ts=time.time(), slot=i))
        return self.results
