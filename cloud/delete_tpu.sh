#!/usr/bin/env bash
# Tear the slice down (reference analog: azure/shutdown_vms.sh).
source "$(dirname "$0")/common.sh"

${GC} delete "${TPU_NAME}" "${GFLAGS[@]}" --quiet
echo "deleted ${TPU_NAME}"
