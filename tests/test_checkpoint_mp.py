"""Checkpointing under tensor parallelism: one model-states file per MP rank,
written from addressable shards (never a global gather of model-sharded
arrays), ZeRO optim shards keyed by (dp, mp), cross-MP-degree restore.

Reference layout: per-MP-rank model states files
(/root/reference/deepspeed/pt/deepspeed_light.py:949-967); the reference
requires save/load MP degrees to match — the reassembly here lifts that for
model states and keeps the restriction (with a loud error) for ZeRO flat
partitions.
"""

import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import checkpoint as ckpt_mod
from deepspeed_tpu.models import GPT2
from deepspeed_tpu.parallel.topology import make_mesh

# composition tier: 30-85 s of shard_map compiles per test — runs in the
# full suite/CI, excluded from `-m fast` (VERDICT r2 weak #6)
pytestmark = pytest.mark.slow


VOCAB, SEQ = 64, 16


def make_engine(mp, zero=False, seed=0, **cfg_over):
    cfg = {
        "train_batch_size": 8,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "fp16": {"enabled": True, "initial_scale_power": 8},
        "zero_optimization": zero,
    }
    cfg.update(cfg_over)
    model = GPT2.from_size("tiny", vocab_size=VOCAB, max_seq_len=SEQ,
                           num_layers=2, hidden_size=32, num_heads=4)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(seed)),
        mesh=make_mesh(model_parallel_size=mp))
    return engine


def train(engine, steps, data_seed=0):
    rng = np.random.default_rng(data_seed)
    losses = []
    for _ in range(steps):
        toks = rng.integers(0, VOCAB, size=(8, SEQ)).astype(np.int32)
        labels = np.roll(toks, -1, axis=1)
        labels[:, -1] = -1
        loss = engine(toks, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def tree_equal(a, b, rtol=0.0, atol=0.0):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


def test_one_model_states_file_per_mp_rank(tmpdir):
    e = make_engine(2)
    train(e, 3)
    e.save_checkpoint(str(tmpdir), tag="t")
    f0 = ckpt_mod.model_file(str(tmpdir), "t", 0)
    f1 = ckpt_mod.model_file(str(tmpdir), "t", 1)
    assert os.path.exists(f0) and os.path.exists(f1)

    # each file holds LOCAL slices: model-sharded leaves are half-size,
    # and the two files differ (proof the split is real, not a broadcast)
    s0, s1 = ckpt_mod._load_obj(f0), ckpt_mod._load_obj(f1)
    leaves0 = jax.tree_util.tree_leaves(s0["module"])
    leaves_g = jax.tree_util.tree_leaves(e.params)
    sharded = [(l0, lg) for l0, lg in zip(leaves0, leaves_g)
               if l0.shape != lg.shape]
    assert sharded, "expected at least one model-sharded leaf"
    for l0, lg in sharded:
        assert l0.size * 2 == lg.size
    assert any(not np.array_equal(a, b)
               for a, b in zip(leaves0,
                               jax.tree_util.tree_leaves(s1["module"])))


@pytest.mark.parametrize("zero", [False, True])
def test_mp2_roundtrip_bit_exact(tmpdir, zero):
    e1 = make_engine(2, zero=zero)
    train(e1, 6)
    e1.save_checkpoint(str(tmpdir), client_state={"epoch": 1})

    e2 = make_engine(2, zero=zero, seed=99)
    path, client = e2.load_checkpoint(str(tmpdir))
    assert path is not None and client["epoch"] == 1
    tree_equal(e1.params, e2.params)
    if zero:
        tree_equal(e1.master_flat, e2.master_flat)
    else:
        tree_equal(e1.master, e2.master)
    tree_equal(e1.opt_state, e2.opt_state)

    l1 = train(e1, 4, data_seed=5)
    l2 = train(e2, 4, data_seed=5)
    np.testing.assert_allclose(l1, l2, rtol=0, atol=0)


def test_cross_mp_restore_model_states(tmpdir):
    """Save under mp=2, restore under mp=1 and mp=4: per-rank local slices
    reassemble to the global tree and re-shard for the new mesh."""
    e1 = make_engine(2)
    train(e1, 4)
    e1.save_checkpoint(str(tmpdir))

    for mp in (1, 4):
        e2 = make_engine(mp, seed=99)
        path, _ = e2.load_checkpoint(str(tmpdir))
        assert path is not None
        tree_equal(e1.params, e2.params)
        tree_equal(e1.master, e2.master)
        l1 = train(make_engine(2, seed=1), 0)  # noop, keep shapes honest
        # continued training stays finite and consistent with the source
        l2 = train(e2, 3, data_seed=5)
        assert all(np.isfinite(l2))


def test_zero_mp_mismatch_errors(tmpdir):
    e1 = make_engine(2, zero=True)
    train(e1, 3)
    e1.save_checkpoint(str(tmpdir))

    e2 = make_engine(4, zero=True, seed=9)
    with pytest.raises(ValueError, match="model_parallel_size"):
        e2.load_checkpoint(str(tmpdir))
    # weights-only restore is the documented escape hatch
    path, _ = e2.load_checkpoint(str(tmpdir), load_optimizer_states=False)
    assert path is not None
    tree_equal(e1.params, e2.params)


def test_zero_mp2_shard_files_per_dp_and_mp(tmpdir):
    e = make_engine(2, zero=True)
    train(e, 3)
    e.save_checkpoint(str(tmpdir), tag="t")
    dp = e.dp_world_size
    for m in range(2):
        for r in range(dp):
            f = ckpt_mod.zero_file(str(tmpdir), "t", r, m)
            assert os.path.exists(f), f
            shard = ckpt_mod._load_obj(f)
            assert shard["mp_rank"] == m
            assert shard["partition_id"] == r


def test_restricted_unpickler_rejects_code(tmpdir):
    import pickle

    class Evil:
        def __reduce__(self):
            return (os.system, ("true",))

    p = os.path.join(str(tmpdir), "evil.pt")
    with open(p, "wb") as f:
        pickle.dump({"module": Evil()}, f)
    with pytest.raises(pickle.UnpicklingError, match="forbidden"):
        ckpt_mod._load_obj(p)
