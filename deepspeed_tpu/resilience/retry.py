"""Retry-with-backoff for transient storage errors.

Checkpoint saves/loads on preemptible pods hit transient filesystem and
object-store errors (EIO, ESTALE, throttling surfaced as OSError); a single
flake must not kill a run the rest of the subsystem works hard to keep
alive.  Per-file checkpoint writes are already atomic (temp + ``os.replace``,
checkpoint._ChunkedWriter), so re-running a whole save/load is safe.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Callable, Tuple, Type

from deepspeed_tpu.resilience.counters import COUNTERS

logger = logging.getLogger(__name__)

#: transient storage failures worth retrying; ValueError/TypeError style
#: logic errors are NOT — retrying those only delays the real traceback
IO_EXCEPTIONS: Tuple[Type[BaseException], ...] = (OSError,)


def io_retry(fn: Callable, retries: int = 3, base_delay_s: float = 0.05,
             max_delay_s: float = 5.0, exceptions=IO_EXCEPTIONS,
             what: str = "storage op"):
    """Run ``fn()`` with up to ``retries`` retries on ``exceptions``.

    Backoff is exponential with full jitter:
    ``min(max_delay_s, base_delay_s * 2**attempt) * uniform(0.5, 1.5)`` —
    jitter so a pod's worth of workers retrying a shared filesystem do not
    re-stampede in lockstep.  Every retry increments
    ``COUNTERS.io_retries``; the final failure re-raises the last error.
    """
    retries = max(0, int(retries))
    for attempt in range(retries + 1):
        try:
            return fn()
        except exceptions as e:
            if attempt == retries:
                logger.error("%s failed after %d retries: %s",
                             what, retries, e)
                raise
            COUNTERS.io_retries += 1
            delay = (min(max_delay_s, base_delay_s * (2.0 ** attempt))
                     * (0.5 + random.random()))
            logger.warning("%s failed (%s); retry %d/%d in %.2fs",
                           what, e, attempt + 1, retries, delay)
            time.sleep(delay)
