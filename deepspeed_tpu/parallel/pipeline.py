"""Pipeline parallelism: a GPipe schedule over the ``pipe`` mesh axis.

Beyond-reference component (the reference v0.1.0 has no pipeline engine —
SURVEY.md §0 lists it as explicitly absent; this is the TPU-native shape of
one).  Layer-stacked parameters shard their leading (layer) dimension over
``pipe`` so each stage owns ``L / pp`` consecutive blocks.  Execution is SPMD:
every stage runs the same program; micro-batches stream through a
``lax.scan`` over ``m + pp - 1`` ticks, each tick applying the stage's local
blocks and handing the activation to the next stage with a ``ppermute``.
Autodiff through ``ppermute`` (its transpose is the reverse permute) yields
the exact pipelined backward — the 1F1B-style memory optimisation is left to
rematerialisation of the stage blocks.

The finished micro-batches exist on the LAST stage; ``collect`` masks other
stages to zero and ``psum``s over ``pipe``, so downstream (head/loss) math is
replicated and uniform across stages — gradients of stage-replicated
parameters then arrive as per-stage partial contributions that the engine
sums over ``pipe`` (same rule as model-axis-replicated leaves).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.topology import PIPE_AXIS


def pipeline_apply(x_micro: jnp.ndarray,
                   stage_fn: Callable[[jnp.ndarray], jnp.ndarray],
                   axis: str = PIPE_AXIS) -> jnp.ndarray:
    """Run the GPipe schedule.

    x_micro:  [m, mb, ...] micro-batched activations, replicated over
              ``axis`` (every stage holds them; only stage 0 injects).
    stage_fn: applies THIS stage's local blocks to one [mb, ...] activation.

    Returns [m, mb, ...] outputs, replicated over ``axis`` (psum-collected
    from the last stage).  Must run inside shard_map over a mesh with
    ``axis``.
    """
    pp = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    m = x_micro.shape[0]
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    is_first = (stage == 0)
    is_last = (stage == pp - 1)

    def tick(carry, t):
        buf, outputs = carry
        # stage 0 ingests micro-batch t (clipped re-injections past the end
        # never reach the last stage within the scan — wasted, not wrong)
        inject = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        cur = jnp.where(is_first, inject, buf)
        y = stage_fn(cur)
        # the last stage's y at tick t is finished micro t - (pp - 1)
        out_t = t - (pp - 1)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, y.astype(outputs.dtype), jnp.clip(out_t, 0, m - 1),
            axis=0)
        outputs = jnp.where(out_t >= 0, updated, outputs)
        # hand off to the next stage (the wrap edge pp-1 -> 0 carries only
        # garbage that stage 0 immediately overwrites with its injection)
        buf = jax.lax.ppermute(y, axis, perm)
        return (buf, outputs), None

    buf0 = jnp.zeros_like(x_micro[0])
    out0 = jnp.zeros_like(x_micro)
    (_, outputs), _ = jax.lax.scan(tick, (buf0, out0),
                                   jnp.arange(m + pp - 1))
    # only the last stage holds real outputs; make them uniform
    outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
    return jax.lax.psum(outputs, axis)


def mask_to_last_stage(value: jnp.ndarray, axis: str = PIPE_AXIS):
    """Zero ``value`` except on the last stage, then psum — the loss-side
    collection rule: keeps the loss (and therefore every replicated-leaf
    gradient) a SUM of per-stage contributions, exactly one of which is
    nonzero."""
    pp = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    masked = jnp.where(stage == pp - 1, value, jnp.zeros_like(value))
    return jax.lax.psum(masked, axis)


def pipe_sharded_loss(x: jnp.ndarray, labels: jnp.ndarray, head_fn,
                      axis: str = PIPE_AXIS) -> jnp.ndarray:
    """Head + loss with the O(V·H) work SHARDED over the pipe stages.

    Each stage runs ``head_fn`` (LN → logits → per-token CE, returning the
    masked ``(loss_sum, valid_count)`` pair) on ITS 1/pp slice of the batch
    and the partial sums psum over ``axis`` — the per-stage head cost drops
    from O(B·T·V·H) replicated (VERDICT r2 weak #1) to O(B·T·V·H / pp),
    and the returned scalar equals the full-batch masked mean bit-for-bit
    up to reduction order.

    Gradient shape: the loss stays pipe-uniform (a psum of per-stage
    partials), so the engine's uniform-pp-factor correction and
    replicated-leaf pipe-psum rules apply unchanged.
    """
    pp = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    B = x.shape[0]
    if B % pp:
        # per-shard batch doesn't split across the stages: fall back to the
        # replicated head masked to the last stage — same gradients, head
        # cost replicated pp x (correct for any B, just not sharded)
        loss_sum, count = head_fn(x, labels)
        val = (jnp.asarray(loss_sum, jnp.float32)
               / jnp.maximum(jnp.asarray(count, jnp.float32), 1.0))
        return mask_to_last_stage(val, axis)
    sl = B // pp
    xs = jax.lax.dynamic_slice_in_dim(x, stage * sl, sl, axis=0)
    ys = jax.lax.dynamic_slice_in_dim(labels, stage * sl, sl, axis=0)
    loss_sum, count = head_fn(xs, ys)
    loss_sum = jax.lax.psum(jnp.asarray(loss_sum, jnp.float32), axis)
    count = jax.lax.psum(jnp.asarray(count, jnp.float32), axis)
    return loss_sum / jnp.maximum(count, 1.0)
