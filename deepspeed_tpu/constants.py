"""Config keys and defaults.

TPU-native analog of the reference's ``deepspeed/pt/deepspeed_constants.py``
(see /root/reference/deepspeed/pt/deepspeed_constants.py:17-245).  Keys keep the
reference's JSON spelling so existing DeepSpeed config files parse unchanged;
TPU-only additions (``bf16``, mesh shape) are new keys that default off/auto.
"""

#############################################
# Routes (reference deepspeed_constants.py:1-15)
#############################################
ROUTE_TRAIN = "train"
ROUTE_EVAL = "eval"
ROUTE_PREDICT = "predict"
ROUTE_ENCODE = "encode"

#############################################
# Batch size (reference deepspeed_constants.py:17-73)
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_BATCH_SIZE_DEFAULT = None

TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None

GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None

#############################################
# Optimizer / scheduler sections
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE = "type"
OPTIMIZER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"

SCHEDULER = "scheduler"
SCHEDULER_TYPE = "type"
SCHEDULER_PARAMS = "params"

# Optimizer names understood by the engine (reference deepspeed_config.py:12-15).
ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
SGD_OPTIMIZER = "sgd"
DEEPSPEED_OPTIMIZERS = [ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, SGD_OPTIMIZER]
# Optimizers whose ZeRO interaction has been validated (reference
# deepspeed_light.py:450-457 restricts ZeRO to Adam).
ZERO_SUPPORTED_OPTIMIZERS = [ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER]

#############################################
# Steps
#############################################
STEPS_PER_PRINT = "steps_per_print"
STEPS_PER_PRINT_DEFAULT = 10

# K optimizer steps fused into ONE compiled dispatch
# (engine.train_many — the on-device multi-step driver,
# docs/features.md "Multi-step driver").  1 = the per-step train_batch
# path.  Env escape hatch DSTPU_MULTISTEP overrides ("off"/"1"
# disables, an integer sets K).  With the metric spool on,
# observability.report_window must be a multiple of K (window drains
# align with K-block edges; enforced at config time).
TRAIN_STEPS_PER_DISPATCH = "train_steps_per_dispatch"
TRAIN_STEPS_PER_DISPATCH_DEFAULT = 1

#############################################
# Training options
#############################################
DISABLE_ALLGATHER = "disable_allgather"
DISABLE_ALLGATHER_DEFAULT = False

SPARSE_GRADIENTS = "sparse_gradients"
SPARSE_GRADIENTS_DEFAULT = False
# static per-step bound on touched embedding rows for the sparse (indices,
# values) gather; above it the reduction falls back to a dense psum.  TPU
# extension knob — the reference's sparse path has no bound because torch
# sparse tensors are dynamically sized, XLA programs are not.
SPARSE_GRADIENTS_MAX_ROWS = "sparse_gradients_max_rows"
SPARSE_GRADIENTS_MAX_ROWS_DEFAULT = 2048

#############################################
# FP16 support (reference deepspeed_constants.py:84-118)
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_ENABLED_DEFAULT = False

FP16_LOSS_SCALE = "loss_scale"
FP16_LOSS_SCALE_DEFAULT = 0  # 0 => dynamic

FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_INITIAL_SCALE_POWER_DEFAULT = 32

FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000

FP16_HYSTERESIS = "hysteresis"
FP16_HYSTERESIS_DEFAULT = 2

FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_MIN_LOSS_SCALE_DEFAULT = 1

#############################################
# BF16 (TPU-native addition; no reference analog — bf16 needs no loss scaling)
#############################################
BF16 = "bf16"
BF16_ENABLED = "enabled"
BF16_ENABLED_DEFAULT = False

#############################################
# Gradient clipping (reference deepspeed_constants.py:120-128)
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
GRADIENT_CLIPPING_DEFAULT = 0.0

#############################################
# ZeRO optimization (reference deepspeed_constants.py:137-146; boolean in v0.1.0)
#############################################
ZERO_OPTIMIZATION = "zero_optimization"
ZERO_OPTIMIZATION_DEFAULT = False

#############################################
# Communication options (reference deepspeed_constants.py:148-182)
#############################################
ALLGATHER_SIZE = "allgather_size"
ALLGATHER_SIZE_DEFAULT = 500000000

FP32_ALLREDUCE = "fp32_allreduce"
FP32_ALLREDUCE_DEFAULT = False

PRESCALE_GRADIENTS = "prescale_gradients"
PRESCALE_GRADIENTS_DEFAULT = False

GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0

#############################################
# Logging / dumps (reference deepspeed_constants.py:184-223)
#############################################
DUMP_STATE = "dump_state"
DUMP_STATE_DEFAULT = False

WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
WALL_CLOCK_BREAKDOWN_DEFAULT = False

MEMORY_BREAKDOWN = "memory_breakdown"
MEMORY_BREAKDOWN_DEFAULT = False

# TPU-specific: per-block activation rematerialisation (the analog of
# Megatron's --checkpoint-activations the reference trains against,
# tests/model/Megatron_GPT2/ds_gpt2_test.sh).  None = leave the model's own
# setting; true/false overrides it.  Accepts {"enabled": bool} too.
ACTIVATION_CHECKPOINTING = "activation_checkpointing"
ACTIVATION_CHECKPOINTING_DEFAULT = None

#############################################
# Graph lint (TPU-native: jaxpr static analysis of the step programs —
# collective consistency, precision flow, transfer/recompile lint, shard
# specs; docs/analysis.md).  No reference analog: torch graphs only exist
# at runtime, jaxprs exist before any chip executes.
#############################################
GRAPH_LINT = "graph_lint"
GRAPH_LINT_MODE = "mode"
GRAPH_LINT_MODE_DEFAULT = "off"       # "off" | "warn" | "error"
GRAPH_LINT_SUPPRESS = "suppress"      # list of rule-code prefixes
GRAPH_LINT_SUPPRESS_DEFAULT = ()

#############################################
# Capacity planner (TPU-native: static per-device peak-HBM + bytes-on-wire
# analysis of the step programs — analysis/memplan.py, analysis/commplan.py,
# docs/analysis.md "Capacity planner".  No reference analog: predicting the
# fit of a config before compile needs the jaxpr, which torch never has.)
#############################################
ANALYSIS = "analysis"
ANALYSIS_MODE = "mode"
ANALYSIS_MODE_DEFAULT = "off"         # "off" | "warn" | "error"
# per-device peak-HBM budget in GiB; "error" mode raises MemoryPlanError
# when the predicted peak exceeds it.  None + no profile = report-only.
ANALYSIS_MEMORY_BUDGET_GB = "memory_budget_gb"
ANALYSIS_MEMORY_BUDGET_GB_DEFAULT = None
# backend profile name (analysis/profiles.py: "v4-8", "v5e-8", "v5p-8",
# "cpu-8"); supplies the budget when memory_budget_gb is unset and the
# link-bandwidth table for predicted wire time
ANALYSIS_PROFILE = "profile"
ANALYSIS_PROFILE_DEFAULT = None
# rule-code prefixes to suppress (memory.*/comm.* families), same
# exact/dotted-prefix semantics as graph_lint.suppress
ANALYSIS_SUPPRESS = "suppress"
ANALYSIS_SUPPRESS_DEFAULT = ()
# host-concurrency lint (analysis/concurrency.py): AST lock-order +
# blocking-under-lock + thread-role pass over the serving control plane,
# gated at FleetRouter build.  {"mode": off|warn|error, "suppress":
# [...]}; a bare string is mode shorthand, like graph_lint
ANALYSIS_CONCURRENCY = "concurrency"
ANALYSIS_CONCURRENCY_MODE_DEFAULT = "off"
ANALYSIS_CONCURRENCY_SUPPRESS_DEFAULT = ()

#############################################
# Profiler (TPU-native: jax.profiler trace over a step window — the
# tracing analog of wall_clock_breakdown, SURVEY §5 row 1)
#############################################
PROFILE = "profile"
PROFILE_ENABLED = "enabled"
PROFILE_ENABLED_DEFAULT = False
PROFILE_START_STEP = "start_step"
PROFILE_START_STEP_DEFAULT = 10
PROFILE_END_STEP = "end_step"
PROFILE_END_STEP_DEFAULT = 12
PROFILE_OUTPUT_PATH = "output_path"
PROFILE_OUTPUT_PATH_DEFAULT = "/tmp/dstpu_profile"

#############################################
# Observability (TPU-native telemetry layer — deepspeed_tpu/observability/,
# docs/observability.md.  Reference analog: deepspeed_timer.py fenced the
# host with torch.cuda.synchronize on every span; here metrics spool
# through a device-side ring buffer drained once per report window, so the
# per-step path carries ZERO host fences.)
#############################################
OBSERVABILITY = "observability"
# boundaries per metric window: >= 1 enables the MetricSpool (device ring
# buffer + one batched drain callback per window); 0 keeps the legacy
# per-boundary reporting paths
OBSERVABILITY_REPORT_WINDOW = "report_window"
OBSERVABILITY_REPORT_WINDOW_DEFAULT = 0
# schema-versioned JSONL event log, one line per window (process 0);
# validated by `python -m deepspeed_tpu.observability <path>`
OBSERVABILITY_JSONL_PATH = "jsonl_path"
OBSERVABILITY_JSONL_PATH_DEFAULT = None
# jax.profiler capture destination (env fallback DSTPU_TRACE_DIR — how
# `dst --trace_dir` hands it to every worker); also where watchdog hang
# captures land
OBSERVABILITY_TRACE_DIR = "trace_dir"
OBSERVABILITY_TRACE_DIR_DEFAULT = None
OBSERVABILITY_TRACE_START_STEP = "trace_start_step"
OBSERVABILITY_TRACE_START_STEP_DEFAULT = 10
# > 0 schedules a [start, start + num) capture window (supersedes the
# legacy `profile` section; configuring both is a config error)
OBSERVABILITY_TRACE_NUM_STEPS = "trace_num_steps"
OBSERVABILITY_TRACE_NUM_STEPS_DEFAULT = 0
# record a short trace when the resilience watchdog fires (needs trace_dir)
OBSERVABILITY_HANG_CAPTURE = "hang_capture"
OBSERVABILITY_HANG_CAPTURE_DEFAULT = True
OBSERVABILITY_HANG_CAPTURE_S = "hang_capture_s"
OBSERVABILITY_HANG_CAPTURE_S_DEFAULT = 1.0
# report the capacity planner's predicted peak-HBM / boundary wire time
# next to measurement in every window event (drift columns)
OBSERVABILITY_PLANNER_DRIFT = "planner_drift"
OBSERVABILITY_PLANNER_DRIFT_DEFAULT = True
# fwd+bwd matmul FLOPs per sample (model-specific; bench.py's accounting)
# — enables the per-window MFU column together with peak_tflops_per_chip
OBSERVABILITY_FLOPS_PER_SAMPLE = "flops_per_sample"
OBSERVABILITY_FLOPS_PER_SAMPLE_DEFAULT = None
OBSERVABILITY_PEAK_TFLOPS = "peak_tflops_per_chip"
OBSERVABILITY_PEAK_TFLOPS_DEFAULT = None
# fleet observability (docs/observability.md "Fleet view"): ship each
# host's window report out-of-band to rank 0 (coordination-service KV
# store — NEVER a device collective) and emit one dstpu.telemetry.fleet
# event per window with per-host spreads + straggler/anomaly flags
OBSERVABILITY_FLEET = "fleet"
OBSERVABILITY_FLEET_DEFAULT = False
# per-window aggregation deadline: hosts missing after this long are
# listed in missing_hosts (itself a hang precursor) instead of blocking
OBSERVABILITY_FLEET_WAIT_S = "fleet_wait_s"
OBSERVABILITY_FLEET_WAIT_S_DEFAULT = 30.0
# a host whose host-side time exceeds this multiple of the fleet median
# is flagged as a straggler
OBSERVABILITY_STRAGGLER_FACTOR = "straggler_factor"
OBSERVABILITY_STRAGGLER_FACTOR_DEFAULT = 2.0
# window loss/grad-norm beyond this multiple of the rolling median is a
# spike anomaly
OBSERVABILITY_SPIKE_FACTOR = "spike_factor"
OBSERVABILITY_SPIKE_FACTOR_DEFAULT = 5.0
# data-loader wait above this fraction of window step time flags
# data starvation
OBSERVABILITY_STARVATION_FRAC = "starvation_frac"
OBSERVABILITY_STARVATION_FRAC_DEFAULT = 0.5
# > 0 serves /healthz, /status and /metrics (Prometheus text) on
# base_port + process_index; env fallback DSTPU_HEALTH_PORT
# (dst --health_port); 0 disables
OBSERVABILITY_HEALTH_PORT = "health_port"
OBSERVABILITY_HEALTH_PORT_DEFAULT = 0
# host-side flight-recorder ring size (entries; 0 disables) — dumped on
# watchdog fire, preemption drain and crash exit
OBSERVABILITY_FLIGHT_RECORDER = "flight_recorder"
OBSERVABILITY_FLIGHT_RECORDER_DEFAULT = 256
# dump destination (default: the JSONL log's directory, else trace_dir,
# else cwd; env fallback DSTPU_FLIGHTREC_DIR)
OBSERVABILITY_FLIGHT_RECORDER_DIR = "flight_recorder_dir"
OBSERVABILITY_FLIGHT_RECORDER_DIR_DEFAULT = None

#############################################
# Inference serving (TPU-native: deepspeed_tpu/inference/,
# docs/inference.md.  No reference analog: v0.1.0 is training-only —
# an inference engine is on its "explicitly absent" list.)
#############################################
INFERENCE = "inference"
# concurrent decode slots (continuous batching width); 0 = auto-size
# against the analysis profile's HBM after weights (kvcache.plan_slots)
INFERENCE_MAX_SLOTS = "max_slots"
INFERENCE_MAX_SLOTS_DEFAULT = 4
# per-slot KV-cache token capacity (page-rounded); 0 = the model's
# max_seq_len
INFERENCE_MAX_TOKENS = "max_tokens"
INFERENCE_MAX_TOKENS_DEFAULT = 0
# fixed prompt padding bucket of the prefill program (one executable
# serves every prompt); 0 = the cache capacity
INFERENCE_PREFILL_BUCKET = "prefill_bucket"
INFERENCE_PREFILL_BUCKET_DEFAULT = 0
# "paged" (exact up to capacity) | "ring" (sliding window: the cache row
# wraps — approximate beyond capacity, documented in docs/inference.md)
INFERENCE_KV_LAYOUT = "kv_layout"
INFERENCE_KV_LAYOUT_DEFAULT = "paged"
# cache allocation granularity in tokens
INFERENCE_PAGE_TOKENS = "page_tokens"
INFERENCE_PAGE_TOKENS_DEFAULT = 128
# serving compute dtype: "bfloat16" (default) | "float16" | "float32"
INFERENCE_DTYPE = "dtype"
INFERENCE_DTYPE_DEFAULT = "bfloat16"
# weight quantization at load: null | "int8" (per-output-channel scales,
# matmul-dequant dispatch table — inference/quant.py)
INFERENCE_QUANTIZE = "quantize"
INFERENCE_QUANTIZE_DEFAULT = None
# D decode iterations fused into ONE compiled dispatch (greedy sampling
# on-device; admission/eviction every D tokens — docs/inference.md
# "Fused decode").  1 = the per-iteration path.  Env escape hatch
# DSTPU_DECODE_ITERS overrides ("off"/"1" disables, an integer sets D).
INFERENCE_DECODE_ITERS_PER_DISPATCH = "decode_iters_per_dispatch"
INFERENCE_DECODE_ITERS_PER_DISPATCH_DEFAULT = 1
# prefix KV reuse over the refcounted page table (docs/inference.md
# "Prefix reuse"): hash page-aligned prompt prefixes, map hits to shared
# pages, prefill only the tail.  Outputs stay byte-identical to the
# no-reuse path (same weights + same tokens ⇒ the same page bytes).
INFERENCE_PREFIX_REUSE = "prefix_reuse"
INFERENCE_PREFIX_REUSE_DEFAULT = True
# page-pool size in PAGES; 0 = slots * pages_per_slot (no overcommit).
# Fewer pages than the worst case is legal — admission refuses (queues)
# when the pool is exhausted instead of OOMing.
INFERENCE_POOL_PAGES = "pool_pages"
INFERENCE_POOL_PAGES_DEFAULT = 0
# padding bucket of the TAIL prefill program (a prefix hit forwards only
# the uncached tail; a narrower bucket makes the FLOP saving real);
# 0 = page_tokens.  Tails longer than the bucket fall back to the full
# prefill program (same numerics, no saving).
INFERENCE_TAIL_BUCKET = "tail_bucket"
INFERENCE_TAIL_BUCKET_DEFAULT = 0
# speculative decoding (docs/inference.md "Speculative decoding"):
# draft_tokens = J proposals per fused draft+verify dispatch (0 = off).
# The draft model comes from draft_size (a models/gpt2.py GPT2_SIZES
# key, built on the target's vocab/seq) or the InferenceEngine
# draft_model= argument; draft_checkpoint/draft_tag stream its weights
# through a second checkpoint.load_params_only pass.
INFERENCE_SPECULATIVE = "speculative"
INFERENCE_SPEC_DRAFT_TOKENS = "draft_tokens"
INFERENCE_SPEC_DRAFT_TOKENS_DEFAULT = 0
INFERENCE_SPEC_DRAFT_SIZE = "draft_size"
INFERENCE_SPEC_DRAFT_SIZE_DEFAULT = None
INFERENCE_SPEC_DRAFT_CHECKPOINT = "draft_checkpoint"
INFERENCE_SPEC_DRAFT_CHECKPOINT_DEFAULT = None
INFERENCE_SPEC_DRAFT_TAG = "draft_tag"
INFERENCE_SPEC_DRAFT_TAG_DEFAULT = None
# replica observability (docs/observability.md "Serving view"): the
# serving analog of the top-level "observability" section — per-request
# lifecycle events, live /healthz /status /metrics endpoints, a hang
# watchdog armed around every prefill/decode dispatch, and the serve
# anomaly detectors.  All host-side: zero effect on the compiled
# programs, the greedy-output contract, or the fence counter.
INFERENCE_OBSERVABILITY = "observability"
# decode iterations folded into one dstpu.telemetry.serve window event
INFERENCE_OBS_WINDOW_ITERS = "window_iters"
INFERENCE_OBS_WINDOW_ITERS_DEFAULT = 8
# serve telemetry JSONL path (window + startup + request events share
# the stream; the run_serve jsonl_path argument beats it)
INFERENCE_OBS_JSONL_PATH = "jsonl_path"
INFERENCE_OBS_JSONL_PATH_DEFAULT = None
# emit one dstpu.telemetry.request line per completed request
INFERENCE_OBS_REQUEST_EVENTS = "request_events"
INFERENCE_OBS_REQUEST_EVENTS_DEFAULT = True
# > 0 serves /healthz /status /metrics on port + process_index (env
# fallback DSTPU_HEALTH_PORT via dst --health_port / serve_gpt2.py
# --health_port, same resolution as observability.health_port)
INFERENCE_OBS_HEALTH_PORT = "health_port"
INFERENCE_OBS_HEALTH_PORT_DEFAULT = 0
# > 0 arms a hang watchdog around every prefill/decode dispatch (the
# deadline scales by decode_iters_per_dispatch / draft_tokens+1 for the
# fused programs); a fire marks the replica unhealthy (/healthz 503)
# and dumps stacks + the flight-recorder ring
INFERENCE_OBS_WATCHDOG_TIMEOUT_S = "watchdog_timeout_s"
INFERENCE_OBS_WATCHDOG_TIMEOUT_S_DEFAULT = 0.0
# abort the process (exit 44) after a watchdog fire, like
# resilience.watchdog_abort
INFERENCE_OBS_WATCHDOG_ABORT = "watchdog_abort"
INFERENCE_OBS_WATCHDOG_ABORT_DEFAULT = False
# flight-recorder dump destination (default: the JSONL log's directory,
# else cwd; env fallback DSTPU_FLIGHTREC_DIR)
INFERENCE_OBS_FLIGHT_RECORDER_DIR = "flight_recorder_dir"
INFERENCE_OBS_FLIGHT_RECORDER_DIR_DEFAULT = None
# admission-starvation detector: flag a window where requests waited
# the whole window (queue non-empty, zero admissions, refusals grew)
INFERENCE_OBS_STARVATION_WINDOWS = "starvation_windows"
INFERENCE_OBS_STARVATION_WINDOWS_DEFAULT = 1
# speculative accept-rate collapse floor (windows with enough proposals
# whose accept rate falls below it are flagged); 0 disables
INFERENCE_OBS_ACCEPT_FLOOR = "accept_floor"
INFERENCE_OBS_ACCEPT_FLOOR_DEFAULT = 0.25
# page-pool thrash detector: flag a window reclaiming at least this
# many published LRU pages AND more than it served prefix hits
# (the prefix cache churning faster than it helps); 0 disables
INFERENCE_OBS_THRASH_RECLAIMS = "thrash_reclaims"
INFERENCE_OBS_THRASH_RECLAIMS_DEFAULT = 8

# fleet serving (docs/inference.md "Fleet serving"): the router layer
# over N InferenceEngine replicas — least-loaded admission off the
# replica /metrics gauges, /healthz-503 eviction with resubmission, and
# optional prefill/decode disaggregation with KV handoff
# (deepspeed_tpu/inference/router.py)
INFERENCE_FLEET = "fleet"
# serving replicas the router drives (0 = no fleet; serve_gpt2.py
# --fleet / FleetRouter(replicas=...) override)
INFERENCE_FLEET_REPLICAS = "replicas"
INFERENCE_FLEET_REPLICAS_DEFAULT = 0
# of those, how many form the PREFILL pool (0 = mixed pool, no
# disaggregation; > 0 requires disaggregate: true)
INFERENCE_FLEET_PREFILL_REPLICAS = "prefill_replicas"
INFERENCE_FLEET_PREFILL_REPLICAS_DEFAULT = 0
# build + gate the KV export/import programs (the handoff path); the
# engine refuses export_kv/import_kv without it so the exactly-N
# executables promise stays a checked invariant
INFERENCE_FLEET_DISAGGREGATE = "disaggregate"
INFERENCE_FLEET_DISAGGREGATE_DEFAULT = False
# > 0 serves the ROUTER's own /healthz /status /metrics here (replica
# endpoints ride inference.observability.health_port + replica index)
INFERENCE_FLEET_HEALTH_PORT = "health_port"
INFERENCE_FLEET_HEALTH_PORT_DEFAULT = 0
# router health/metrics poll + telemetry-window cadence (seconds)
INFERENCE_FLEET_POLL_S = "poll_s"
INFERENCE_FLEET_POLL_S_DEFAULT = 0.05
# route requests to the replica whose page-hash index already holds
# the prompt's page-aligned prefix (PR 13 reuse at fleet scale)
INFERENCE_FLEET_AFFINITY = "affinity"
INFERENCE_FLEET_AFFINITY_DEFAULT = True
# KV handoff artifact directory (disaggregation; default: a tempdir)
INFERENCE_FLEET_HANDOFF_DIR = "handoff_dir"
INFERENCE_FLEET_HANDOFF_DIR_DEFAULT = None
# router telemetry JSONL (dstpu.telemetry.router windows; the
# FleetRouter jsonl_path argument beats it)
INFERENCE_FLEET_JSONL_PATH = "jsonl_path"
INFERENCE_FLEET_JSONL_PATH_DEFAULT = None

#############################################
# Checkpoint IO (TPU-native: background writer thread + parallel streaming
# restore — checkpoint.py, docs/resilience.md "Time to resume".  No
# reference analog: v0.1.0 saves/loads synchronously through torch.save.)
#############################################
CHECKPOINT = "checkpoint"
# write container files on a background thread; the training stall is the
# device→host snapshot only
CHECKPOINT_ASYNC_SAVE = "async_save"
CHECKPOINT_ASYNC_SAVE_DEFAULT = False
# restore reader-pool width: 0 = auto (2 readers per core, capped at 8),
# 1 = serial fallback (same plan executed inline — bitwise identical)
CHECKPOINT_RESTORE_THREADS = "restore_threads"
CHECKPOINT_RESTORE_THREADS_DEFAULT = 0
# bound on in-flight read results beyond the leaf being placed — the
# restore's peak host RAM is one window + one leaf, not the state tree
CHECKPOINT_RESTORE_READAHEAD_MB = "restore_readahead_mb"
CHECKPOINT_RESTORE_READAHEAD_MB_DEFAULT = 256.0

#############################################
# Persistent compilation cache (TPU-native: jax_compilation_cache_dir wired
# through config so a relaunched/preempted worker reuses the prior
# attempt's compiled step programs — time-to-first-step after a restart
# becomes restore + cache READ instead of restore + full recompile.)
#############################################
COMPILE_CACHE = "compile_cache"
# cache directory (shared across restart attempts; the launcher propagates
# it to relaunched workers via DSTPU_COMPILE_CACHE_DIR).  None = disabled
# unless the env var is set.
COMPILE_CACHE_DIR = "dir"
COMPILE_CACHE_DIR_DEFAULT = None
# skip caching executables smaller than this (tiny programs recompile
# faster than they deserialize; 0 = cache everything)
COMPILE_CACHE_MIN_ENTRY_SIZE_BYTES = "min_entry_size_bytes"
COMPILE_CACHE_MIN_ENTRY_SIZE_BYTES_DEFAULT = 0

#############################################
# Resilience (TPU-native: preemption-safe training, hang watchdog, NaN
# sentinel, storage retry — deepspeed_tpu/resilience/, docs/resilience.md.
# No reference analog: v0.1.0 assumes every host survives the run.)
#############################################
RESILIENCE = "resilience"
# take an emergency checkpoint (tag "emergency/...") before a preemption
# drain exits with RESUME_EXIT_CODE
RESILIENCE_PREEMPT_SAVE = "preempt_save"
RESILIENCE_PREEMPT_SAVE_DEFAULT = True
# launcher relaunch budget after RESUME/WATCHDOG exit codes (the engine
# records it; deepspeed_tpu.launcher --max_restarts consumes it via CLI)
RESILIENCE_MAX_RESTARTS = "max_restarts"
RESILIENCE_MAX_RESTARTS_DEFAULT = 0
# hang watchdog deadline over each blocking step/checkpoint call;
# 0 disables the watchdog
RESILIENCE_WATCHDOG_TIMEOUT_S = "watchdog_timeout_s"
RESILIENCE_WATCHDOG_TIMEOUT_S_DEFAULT = 0.0
# after the stack dump, abort the process with WATCHDOG_EXIT_CODE so the
# restart path takes over (default: dump only)
RESILIENCE_WATCHDOG_ABORT = "watchdog_abort"
RESILIENCE_WATCHDOG_ABORT_DEFAULT = False
# retry-with-backoff budget for checkpoint save/load storage errors
RESILIENCE_IO_RETRIES = "io_retries"
RESILIENCE_IO_RETRIES_DEFAULT = 3
# extend the fp16 skip-on-overflow contract to bf16/fp32: a non-finite
# gradient skips the optimizer boundary (master/moments unchanged) instead
# of poisoning the parameters
RESILIENCE_NAN_SENTINEL = "nan_sentinel"
RESILIENCE_NAN_SENTINEL_DEFAULT = False

#############################################
# TensorBoard (reference deepspeed_constants.py:225-245)
#############################################
TENSORBOARD = "tensorboard"
TENSORBOARD_ENABLED = "enabled"
TENSORBOARD_ENABLED_DEFAULT = False
TENSORBOARD_OUTPUT_PATH = "output_path"
TENSORBOARD_OUTPUT_PATH_DEFAULT = ""
TENSORBOARD_JOB_NAME = "job_name"
TENSORBOARD_JOB_NAME_DEFAULT = "DeepSpeedJobName"

#############################################
# MXU alignment: the reference warns when vocab size isn't a multiple of 8 for
# tensor cores (deepspeed_config.py:402-407).  TPU MXU tiles are 128-wide.
#############################################
MXU_ALIGN_SIZE = 128

#############################################
# Mesh / parallelism (TPU-native additions)
#############################################
MESH = "mesh"
MESH_DATA_AXIS = "data"
MESH_MODEL_AXIS = "model"
MODEL_PARALLEL_SIZE = "model_parallel_size"
MODEL_PARALLEL_SIZE_DEFAULT = 1
MESH_SEQ_AXIS = "seq"
CONTEXT_PARALLEL_SIZE = "context_parallel_size"
CONTEXT_PARALLEL_SIZE_DEFAULT = 1
MESH_PIPE_AXIS = "pipe"
PIPELINE_PARALLEL_SIZE = "pipeline_parallel_size"
PIPELINE_PARALLEL_SIZE_DEFAULT = 1
PIPELINE_SCHEDULE = "pipeline_schedule"
PIPELINE_SCHEDULE_DEFAULT = None          # None | "gpipe" | "1f1b"
SEQUENCE_PARALLEL_IMPL = "sequence_parallel_impl"
SEQUENCE_PARALLEL_IMPL_DEFAULT = None     # None | "ring" | "ulysses"

ZERO_PARAMETER_PARALLEL_SIZE = "parameter_parallel_size"
ZERO_PARAMETER_PARALLEL_SIZE_DEFAULT = None

# Comm/compute overlap: the boundary collectives (reduce-scatter / weight
# all-gather, and the plain-DP grad psum) split into lane-aligned buckets so
# XLA's async collectives can overlap each other and the shard-local update
# (docs/scaling.md "Communication/compute overlap").  Bucketing only re-tiles
# the same elementwise math, so it is bit-exact with the serial path;
# DSTPU_OVERLAP=off restores the monolithic programs.
ZERO_OVERLAP_COMM = "overlap_comm"
ZERO_OVERLAP_COMM_DEFAULT = True
ZERO_COMM_BUCKET_MB = "comm_bucket_mb"
ZERO_COMM_BUCKET_MB_DEFAULT = 32.0
