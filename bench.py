"""Headline benchmark: BERT-large pretrain throughput, samples/sec/chip.

Reference number: 200 samples/s on one V100 at seq-len 128
(/root/reference/docs/_tutorials/bert-pretraining.md:308-320); the driver's
BASELINE.json tracks samples/sec/chip, so ``vs_baseline = value / 200``.

Runs the real engine (bf16 + LAMB, the reference's BERT recipe) through the
fused ``train_batch`` path — one XLA program per optimizer step (lax.scan
over gas micro-batches), buffers donated, "selective" remat (save qkv +
pre-GELU ffn; backward replays no matmuls).  The MLM head uses the standard
masked-positions format (max_predictions_per_seq=20), like the reference's
BingBert pipeline.  gas=16 with micro-batch 96 mirrors the large-batch LAMB
recipe (bert-pretraining.md: 16K global batch) and amortises the optimizer
update.  Steps are queued asynchronously and timed against one final device
sync, so no host round-trip sits inside the measured region.

Prints ONE json line: {"metric","value","unit","vs_baseline","mfu",...}.
Env knobs: BENCH_SIZE/BENCH_SEQ/BENCH_BATCH/BENCH_STEPS/BENCH_REMAT/
BENCH_GAS/BENCH_MAXPRED/BENCH_PALLAS, BENCH_PEAK_TFLOPS (MFU denominator,
auto-detected from the device kind when unset), BENCH_SWEEP=1 for a
batch x remat sweep (rows on stderr, best on stdout), BENCH_OUT=<path> to
also write the JSON line to a file (committed sweep artifacts),
BENCH_PP_SWEEP=1 with BENCH_PP_SCHEDULES=gpipe,1f1b for the pipeline
schedule sweep, BENCH_ATTN_SWEEP=1 for the attention-kernel sweep,
BENCH_HEAD=1 for the MLM-head sparse-vs-dense microbench (CPU-safe),
BENCH_OVERLAP=1 for the ZeRO boundary comm/compute-overlap microbench
(CPU-safe: parity + bucket-count evidence; see bench_overlap.json),
BENCH_SERVE=1 for the serving bench (continuous vs static batching,
tokens/s/chip + p50/p99 TTFT/ITL -> bench_serve.json),
BENCH_RESUME=1 for the time-to-first-step-after-relaunch bench (serial vs
parallel streaming restore + cold vs warm persistent compile cache;
CPU-safe; see bench_resume.json),
BENCH_DEVICE_TIMEOUT (default 600 s; <= 0 disables) to fail crisply
instead of hanging when the device tunnel is wedged.

Calibration note (v5e, measured): the published 197 bf16 TFLOP/s peak is
reachable only at large contraction dims (K >= 4096).  BERT-large's body
matmuls contract over hidden=1024, where a chained same-shape matmul
microbenchmark tops out at ~93 TFLOP/s ([12288,1024]x[1024,4096]); the full
train step achieves ~99 TFLOP/s — i.e. ~0.50 MFU against nameplate is
~1.0 of the shape-adjusted ceiling, and the remaining headroom at this
model shape is measurement noise, not schedule waste.
"""

import json
import os
import sys
import time

import numpy as np


def _flatten_leaves(obj, prefix=""):
    """``(numeric, other)`` dotted-key maps over every leaf of a bench
    row (lists included, by index): numbers are threshold-compared,
    everything else — booleans (the acceptance gates like
    ``observability_overhead_ok``), strings, nulls — is
    identity-compared, so a flipped gate always warns."""
    nums, other = {}, {}
    if isinstance(obj, dict):
        items = obj.items()
    elif isinstance(obj, list):
        items = enumerate(obj)
    else:
        key = prefix[:-1]
        if isinstance(obj, (int, float)) and not isinstance(obj, bool):
            nums[key] = float(obj)
        else:
            other[key] = obj
        return nums, other
    for k, v in items:
        n, o = _flatten_leaves(v, f"{prefix}{k}.")
        nums.update(n)
        other.update(o)
    return nums, other


def _load_bench_rows(path):
    """Bench artifacts are one JSON object per line (most files hold
    exactly one); rows key by their ``metric`` tag."""
    rows = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            rows[row.get("metric", f"row{len(rows)}")] = row
    if not rows:
        raise SystemExit(f"bench --diff: {path!r} contains no rows")
    return rows


def run_bench_diff(old_path, new_path, threshold=0.10, strict=False):
    """``bench.py --diff old.json new.json`` — compare two committed
    platform-tagged bench artifacts column by column and WARN on any
    numeric column moving more than ``threshold`` (relative).  The
    regression guard for PRs that touch a measured path: commit the
    refreshed artifact, diff it against HEAD's, read the warnings.
    Exit 0 unless ``--strict`` and something moved."""
    old_rows, new_rows = _load_bench_rows(old_path), _load_bench_rows(new_path)
    warnings = 0
    for metric in sorted(set(old_rows) & set(new_rows)):
        old, new = old_rows[metric], new_rows[metric]
        if old.get("platform") != new.get("platform") \
                or old.get("device_kind") != new.get("device_kind"):
            print(f"WARNING [{metric}] platform: "
                  f"{old.get('platform')!r}/{old.get('device_kind')!r} "
                  f"-> {new.get('platform')!r}/"
                  f"{new.get('device_kind')!r} — cross-rig numbers do "
                  f"not compare")
            warnings += 1
        o, other_o = _flatten_leaves(old)
        n, other_n = _flatten_leaves(new)
        # non-numeric columns (acceptance-gate booleans, notes, nulls):
        # any change warns — a flipped observability_overhead_ok or
        # continuous_beats_static must never slide through the diff
        for key in sorted(set(other_o) & set(other_n)):
            if other_o[key] != other_n[key] \
                    and key not in ("platform", "device_kind"):
                print(f"WARNING [{metric}] {key}: {other_o[key]!r} -> "
                      f"{other_n[key]!r}")
                warnings += 1
        for key in sorted(set(o) & set(n)):
            if o[key] == n[key]:
                continue
            if o[key] == 0:
                rel = float("inf")
            else:
                rel = n[key] / o[key] - 1.0
            marker = "WARNING" if abs(rel) > threshold else "ok"
            line = (f"{marker} [{metric}] {key}: {o[key]:g} -> "
                    f"{n[key]:g} ({rel:+.1%})")
            if marker == "WARNING":
                warnings += 1
                print(line)
            elif os.environ.get("BENCH_DIFF_VERBOSE") == "1":
                print(line)
        gone = sorted((set(o) | set(other_o)) - set(n) - set(other_n))
        added = sorted((set(n) | set(other_n)) - set(o) - set(other_o))
        if gone:
            print(f"note [{metric}] columns dropped: {gone}")
        if added:
            print(f"note [{metric}] columns added: {added}")
    only_old = sorted(set(old_rows) - set(new_rows))
    only_new = sorted(set(new_rows) - set(old_rows))
    if only_old:
        print(f"note: rows only in {old_path}: {only_old}")
    if only_new:
        print(f"note: rows only in {new_path}: {only_new}")
    print(f"bench --diff: {warnings} column(s) moved past "
          f"{threshold:.0%} ({old_path} -> {new_path})")
    return 1 if (strict and warnings) else 0


def _emit(obj):
    """Print the one-line JSON; also write it to $BENCH_OUT when set (the
    committed-artifact path, e.g. bench_attn_sweep.json)."""
    line = json.dumps(obj)
    print(line)
    out = os.environ.get("BENCH_OUT")
    if out:
        with open(out, "w") as f:
            f.write(line + "\n")


def _count_params(tree):
    import jax
    return sum(int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(tree))


def _train_flops_per_sample(n_params, cfg, seq, n_pred, remat):
    """Approximate matmul FLOPs per sample for one fwd+bwd pass.

    Standard accounting: 6*N_body per token for parameter matmuls (2N fwd +
    4N bwd) + 12*L*S*H per token for attention score/value matmuls.  The
    tied vocab projection (V*H) runs only over the n_pred gathered MLM
    positions.  Full remat replays the forward (+2N_body + 4*L*S*H per
    token); "selective" replays only the attention einsums (+4*L*S*H).
    """
    V, H, Lyr = cfg.vocab_size, cfg.hidden_size, cfg.num_layers
    n_body = n_params - V * H
    attn_tok = 12.0 * Lyr * seq * H
    per_sample = seq * (6.0 * n_body + attn_tok) + n_pred * 6.0 * V * H
    if remat is True or remat == "full":
        per_sample += seq * (2.0 * n_body + 4.0 * Lyr * seq * H) \
            + n_pred * 2.0 * V * H
    elif remat == "selective":
        per_sample += seq * 4.0 * Lyr * seq * H
    return per_sample


def _env_pallas():
    v = os.environ.get("BENCH_PALLAS", "")
    return None if v == "" else v == "1"


# published peak bf16 matmul TFLOP/s by device kind (MFU denominator)
_PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def _peak_tflops():
    import jax
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env)
    kind = jax.devices()[0].device_kind
    return _PEAK_BF16_TFLOPS.get(kind, 459.0)


def _plan_predictions(engine, batch, micro_n):
    """Static capacity-planner columns for a bench row: predicted
    per-device peak HBM of the fused train_batch program and the
    predicted ZeRO-boundary wire time (docs/analysis.md "Capacity
    planner") — prediction sits next to measurement in the committed
    artifact so the next chip session can fit a goodput factor.
    $BENCH_PROFILE picks the profile (default v4-8, the headline chip);
    best-effort: the planner must never take down a bench run."""
    try:
        from deepspeed_tpu.analysis import profiles
        prof = profiles.resolve(os.environ.get("BENCH_PROFILE", "v4-8"))
        fused = engine.plan_capacity(batch, train=True, fused=True,
                                     profile=prof)
        micro = tuple(a[:micro_n] for a in batch)
        split = engine.plan_capacity(micro, train=True, fused=False,
                                     profile=prof)
        boundary_ms = (split.boundary_comm.predicted_time_ms()
                       if split.boundary_comm is not None else None)
        return {
            "predicted_peak_hbm_gb": round(fused.peak_bytes / 2**30, 4),
            "predicted_boundary_ms": (round(boundary_ms, 4)
                                      if boundary_ms is not None else None),
            "predicted_profile": prof.name,
        }
    except Exception as e:  # pragma: no cover - defensive
        print(f"capacity-plan columns skipped: {e}", file=sys.stderr)
        return {}


def _measure_boundary(engine, batch, micro_n, repeats=None):
    """MEASURED boundary time: the split-API step program (the same
    collectives+update the planner's ``predicted_boundary_ms`` prices)
    executed fenced ``repeats`` times on real gradients.  The fenced
    timing is deliberate — this is a microbench of one program, not the
    pipelined training path.  Best-effort (None on failure): a
    measurement column must never take down a bench run."""
    import time as _time

    import jax

    try:
        micro = tuple(a[:micro_n] for a in batch)
        fwdbwd = engine._ensure_fwdbwd(micro)
        _, grads = fwdbwd(engine.params,
                          engine.loss_scale_state.cur_scale, micro)
        if engine._step_fn is None:
            engine._step_fn = engine._build_step()
        repeats = repeats or int(os.environ.get("BENCH_OBS_REPEATS", "5"))
        # the step program DONATES master/opt-state/grads/loss-scale; an
        # outer non-donating jit keeps the engine's live buffers intact
        # (donation only binds at the top-level executable).  Call tuple
        # via the protocol owner — hand-rolled copies drift silently.
        from deepspeed_tpu import analysis
        step_fn = jax.jit(lambda *a: engine._step_fn(*a))

        def once():
            outs = step_fn(*analysis.step_args(engine, grads))
            jax.block_until_ready(outs)
            return outs

        once()                                  # compile + warmup
        t0 = _time.perf_counter()
        for _ in range(repeats):
            once()
        return (_time.perf_counter() - t0) / repeats * 1000.0
    except Exception as e:  # pragma: no cover - defensive
        print(f"measured_boundary_ms skipped: {e}", file=sys.stderr)
        return None


def run_config(size, seq, batch_per_chip, steps, remat, gas=1,
               warmup=2, obs_window=0, jsonl_path=None,
               measure_boundary=None, obs_fleet=False):
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import BertForPreTraining
    from deepspeed_tpu.parallel.topology import make_mesh

    n_chips = jax.device_count()
    over = {}
    if os.environ.get("BENCH_LAYER_OVERRIDE"):
        # ablation hook (run_mfu_breakdown): same geometry, fewer layers
        over["num_layers"] = int(os.environ["BENCH_LAYER_OVERRIDE"])
    model = BertForPreTraining.from_size(size, max_seq_len=max(seq, 128),
                                         **over)
    vocab = model.config.vocab_size

    cfg = {
        "train_batch_size": batch_per_chip * n_chips * gas,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "Lamb",
                      "params": {"lr": 4e-3, "max_coeff": 0.5,
                                 "min_coeff": 0.08,
                                 "use_pallas": _env_pallas()}},
        "bf16": {"enabled": True},
        "activation_checkpointing": (
            {"enabled": True, "policy": remat} if isinstance(remat, str)
            else bool(remat)),
        "steps_per_print": 10 ** 9,
    }
    if obs_window:
        # BENCH_OBS leg: metrics spool through the device ring buffer and
        # drain per window (docs/observability.md) — the run must be no
        # slower than the PR 1 window-timer baseline
        obs = {"report_window": int(obs_window)}
        if jsonl_path:
            obs["jsonl_path"] = jsonl_path
        if obs_fleet:
            # fleet aggregation rides the same leg (fleet-of-1 here; the
            # aggregation/detector path is identical to multi-host) —
            # the fences_per_run == 1 contract must hold with it ON
            obs["fleet"] = True
        cfg["observability"] = obs
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg,
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=make_mesh(model_parallel_size=1))

    n_params = _count_params(engine.params)

    # masked-positions MLM batch: the standard BERT pretraining format
    # (max_predictions_per_seq=20 at seq 128, the reference recipe's shape —
    # bert-pretraining.md data pipeline)
    n_pred = int(os.environ.get("BENCH_MAXPRED",
                                "80" if seq >= 512 else "20"))
    rng = np.random.default_rng(0)
    B = batch_per_chip * n_chips * gas
    ids = rng.integers(0, vocab, size=(B, seq)).astype(np.int32)
    mask = np.ones((B, seq), np.int32)
    tt = np.zeros((B, seq), np.int32)
    positions = np.stack([rng.choice(seq, size=n_pred, replace=False)
                          for _ in range(B)]).astype(np.int32)
    mlm_ids = np.take_along_axis(ids, positions, axis=1)
    weights = np.ones((B, n_pred), np.float32)
    batch = (ids, mask, tt, positions, mlm_ids, weights)

    # compile + warmup (forced to completion by the loss read)
    for _ in range(warmup):
        loss = engine.train_batch(batch)
    first_loss = float(loss)

    measured_boundary = None
    if measure_boundary is None:
        # BENCH_OBS_COLUMNS=1 adds the columns to any leg (e.g. the
        # headline recipe) without re-dispatching main; callers that know
        # (run_obs_bench) pass the flag explicitly
        measure_boundary = os.environ.get("BENCH_OBS_COLUMNS", "0") == "1"
    if measure_boundary:
        # measured boundary next to PR 6's prediction — BEFORE the timed
        # loop (the fenced microbench drains the device, so the timing
        # region below starts clean) and BEFORE any window drains still
        # to come, so with the spool on every subsequent JSONL event
        # carries measured_boundary_ms + boundary_drift
        measured_boundary = _measure_boundary(engine, batch,
                                              batch_per_chip * n_chips)
        if measured_boundary is not None and engine.telemetry is not None:
            engine.telemetry.measured_boundary_ms = measured_boundary

    # timed: queue all steps, sync once at the end (the final loss read
    # forces the whole dispatch chain; per-step host reads would serialize)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch)
    last_loss = float(loss)
    dt = time.perf_counter() - t0

    if not (np.isfinite(first_loss) and np.isfinite(last_loss)):
        raise RuntimeError(
            f"bench loss not finite: first={first_loss} last={last_loss}")

    if obs_window:
        engine.flush_telemetry()    # the final partial window is evidence

    samples_per_sec = B * steps / dt
    per_chip = samples_per_sec / n_chips
    flops = _train_flops_per_sample(n_params, model.config, seq, n_pred,
                                    remat)
    peak = _peak_tflops() * 1e12
    mfu = per_chip * flops / peak
    res = {
        "per_chip": per_chip,
        "mfu": mfu,
        "achieved_tflops": per_chip * flops / 1e12,
        "loss": last_loss,
        "n_params": n_params,
        "measured_boundary_ms": (round(measured_boundary, 4)
                                 if measured_boundary is not None else None),
        "predicted_drift": None,
        **_plan_predictions(engine, batch, batch_per_chip * n_chips),
    }
    pred = res.get("predicted_boundary_ms")
    if measured_boundary is not None and pred:
        # the drift ratio that makes planner rot visible
        res["predicted_drift"] = round(measured_boundary / pred, 4)
    return res


def _pp_body_tok_flops(hidden, seq):
    """Fwd matmul FLOPs per token for one transformer layer body."""
    return 2.0 * 12 * hidden * hidden + 4.0 * seq * hidden


def _pp_head_tok_flops(hidden, vocab):
    """Fwd matmul FLOPs per token for the vocab head."""
    return 2.0 * vocab * hidden


def _pp_analytic_row(pp, schedule, m, layers, hidden, seq, vocab):
    """Exact per-device cost model of one optimizer step of the committed
    schedules (VERDICT r4 weak #1: the virtual-CPU wall-clock sweep was
    noise; these counts are derived from the programs in
    parallel/pipeline.py and are deterministic and hardware-independent).

    Units: one "body unit" = one stage body application (layers/pp layers)
    on one micro-batch; one "head unit" = one head forward on one
    micro-batch (LN -> vocab logits -> CE sum; its VJP pull costs ~2
    more).  SPMD means EVERY stage executes every tick's full program —
    bubble ticks burn the same FLOPs as live ones.

    GPipe (pipeline_apply + scan autodiff): m+pp-1 forward ticks (1 body)
    + m+pp-1 backward ticks (2 body; residuals saved, no recompute); the
    head runs OUTSIDE the schedule on the psum-collected [m] outputs
    through pipe_sharded_loss (each stage takes a 1/pp batch slice) =
    3·m/pp head units per device.  Activation residency: m+pp-1 saved
    stage inputs (scan residuals).

    1F1B (_run_1f1b): m+2(pp-1) ticks, each = 1 body forward + a
    recompute-from-ring VJP (1 forward replay + 2 pull) = 4 body units,
    PLUS the in-schedule head.  Since r5 the head is 1/pp-SHARDED over
    the micro-batch (broadcast yb from the last stage, per-stage slice
    VJP, psum-reassembled dy — mirroring pipe_sharded_loss), so it
    costs 3/pp head units + 2 activation psums per tick instead of the
    3 fully-replicated units the r4 sweep measured.  Activation
    residency: the min(m, 2pp-1) input ring — the memory win the
    schedule exists for.
    """
    body_tok = _pp_body_tok_flops(hidden, seq)
    head_tok = _pp_head_tok_flops(hidden, vocab)
    psums = 0       # full-activation psums (gpipe's output collect is
    # counted once; 1f1b's per-tick head broadcast/gather dominate)
    if pp == 1:
        ticks, body_units, head_units = m, 3.0 * m, 3.0 * m
        ppermutes, ring = 0, m
    elif schedule == "gpipe":
        ticks = m + pp - 1
        body_units = 3.0 * ticks            # 1 fwd + 2 bwd per tick
        head_units = 3.0 * m / pp           # sharded (pipe_sharded_loss)
        ppermutes = 2 * ticks
        psums = 1                           # the [m, mb, ...] collect
        ring = ticks                        # scan residuals
    else:                                   # 1f1b
        ticks = m + 2 * (pp - 1)
        body_units = 4.0 * ticks            # fwd + recompute + 2 pull
        head_units = 3.0 * ticks / pp       # sharded in-schedule head
        ppermutes = 2 * ticks
        psums = 2 * ticks                   # yb broadcast + dy gather,
        ring = min(m, 2 * pp - 1)           # full-activation each
    # per-device fwd-FLOPs per step per (micro-batch token): bubbles and
    # masked head work included — this is what the device EXECUTES
    flops = (body_units * (layers / pp) * body_tok
             + head_units * head_tok)
    return {"pp": pp, "schedule": schedule, "ticks": ticks,
            "body_units": body_units, "head_units": head_units,
            "ppermutes_per_step": ppermutes,
            "activation_psums_per_step": psums,
            "activation_ring_slots": ring,
            "device_flops_per_micro_token": round(flops, 0),
            "theory_bubble_eff": round(m / (m + pp - 1), 3)}


def run_pipeline_sweep(steps=4, warmup=2):
    """pp ∈ {1, 2, 4, ...} GPT-2 schedule sweep at constant global batch.

    Primary output is ANALYTIC (deterministic tick/FLOP/collective counts
    from the committed schedule programs — see _pp_analytic_row), with
    ``analytic_eff_vs_pp1`` = executed-flops(pp=1)/executed-flops(pp) per
    device.  Optional measured wall-clock (BENCH_PP_MEASURE=1) reports
    median ± IQR over BENCH_PP_REPEATS repeats and is flagged
    ``hardware_true`` only on a real TPU mesh — on the virtual CPU mesh
    all 8 devices share one host core, so wall-time there is contention
    noise, not schedule cost (the r4 sweep's negative bubble fractions;
    VERDICT r4 weak #1)."""
    import jax

    n = jax.device_count()
    if n < 2:
        raise RuntimeError(
            "pipeline sweep needs >= 2 devices; set JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "PALLAS_AXON_POOL_IPS= for a virtual mesh")
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    m = int(os.environ.get("BENCH_PP_MICRO", "8"))
    # per-chip batch a multiple of m so the pp=1 baseline's per-shard batch
    # still splits into m micro-batches
    bpc = int(os.environ.get("BENCH_BATCH", str(m)))
    layers = int(os.environ.get("BENCH_PP_LAYERS", "8"))
    hidden = int(os.environ.get("BENCH_PP_HIDDEN", "256"))
    vocab = 50257
    if bpc % m:
        raise RuntimeError(
            f"BENCH_BATCH ({bpc}) must be a multiple of BENCH_PP_MICRO "
            f"({m}) so the pp=1 baseline runs (eff_vs_pp1 is relative to "
            f"it)")
    B = bpc * n  # constant global batch across pp configs

    schedules = [s.strip() for s in
                 os.environ.get("BENCH_PP_SCHEDULES",
                                "gpipe,1f1b").split(",") if s.strip()]
    bad = [s for s in schedules if s not in ("gpipe", "1f1b")]
    if bad or not schedules:
        raise RuntimeError(
            f"BENCH_PP_SCHEDULES entries must be 'gpipe' or '1f1b', "
            f"got {bad or schedules}")

    measure = os.environ.get("BENCH_PP_MEASURE", "0") == "1"
    repeats = int(os.environ.get("BENCH_PP_REPEATS", "5"))
    configs, pp = [], 1
    while pp <= n:
        if (B * pp // n) % m == 0 and layers % pp == 0:
            for schedule in (("gpipe",) if pp == 1 else schedules):
                configs.append((pp, schedule))
        pp *= 2

    rows = [_pp_analytic_row(pp, s, m, layers, hidden, seq, vocab)
            for pp, s in configs]
    # per-chip efficiency at constant global batch: a pp-deep dp-shard
    # processes pp x the per-device batch of pp=1 (mb scales with pp), so
    # wall ∝ device_flops_per_micro_token x pp
    base_flops = rows[0]["device_flops_per_micro_token"]
    for r in rows:
        r["analytic_eff_vs_pp1"] = round(
            base_flops / (r["device_flops_per_micro_token"] * r["pp"]), 3)

    if measure:
        import deepspeed_tpu
        from deepspeed_tpu.models import GPT2Pipelined
        from deepspeed_tpu.parallel.topology import make_mesh

        rng = np.random.default_rng(0)
        toks = rng.integers(0, vocab, size=(B, seq)).astype(np.int32)
        labels = np.roll(toks, -1, axis=1)
        labels[:, -1] = -1
        for row, (pp, schedule) in zip(rows, configs):
            model = GPT2Pipelined.from_size(
                "tiny", num_micro_batches=m, schedule=schedule,
                vocab_size=vocab, max_seq_len=seq,
                num_layers=layers, hidden_size=hidden,
                num_heads=max(4, hidden // 64))
            engine, _, _, _ = deepspeed_tpu.initialize(
                config={"train_batch_size": B, "steps_per_print": 10 ** 9,
                        "optimizer": {"type": "Adam",
                                      "params": {"lr": 1e-4}},
                        "bf16": {"enabled": True}},
                model=model,
                model_parameters=model.init_params(jax.random.PRNGKey(0)),
                mesh=make_mesh(pipeline_parallel_size=pp))
            for _ in range(warmup):
                loss = engine.train_batch((toks, labels))
            float(loss)
            times = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                for _ in range(steps):
                    loss = engine.train_batch((toks, labels))
                float(loss)
                times.append((time.perf_counter() - t0) / steps)
            q1, med, q3 = np.percentile(times, [25, 50, 75])
            row["measured_ms_per_step"] = round(med * 1000, 1)
            row["measured_iqr_ms"] = round((q3 - q1) * 1000, 1)
            row["measured_per_chip"] = round(B / med / n, 2)
            print(f"pp={pp} {schedule}: {med*1000:.0f} ms/step "
                  f"(IQR {1000*(q3-q1):.0f} ms)", file=sys.stderr)

    pp_max = max(pp for pp, _ in configs)
    head_ratio = _pp_head_tok_flops(hidden, vocab) / (
        _pp_body_tok_flops(hidden, seq) * (layers / pp_max))
    gpipe_max = [r for r in rows if r["pp"] == pp_max
                 and r["schedule"] == "gpipe"]
    f1b_max = [r for r in rows if r["pp"] == pp_max
               and r["schedule"] == "1f1b"]
    ratio = (gpipe_max[0]["analytic_eff_vs_pp1"]
             / f1b_max[0]["analytic_eff_vs_pp1"]
             if gpipe_max and f1b_max else float("nan"))
    out = {"metric": "gpt2_pipeline_sweep",
           "unit": "analytic per-device cost model (+ optional timing)",
           "num_micro_batches": m, "layers": layers, "hidden": hidden,
           "hardware_true": bool(measure
                                 and jax.devices()[0].platform == "tpu"),
           "rows": rows,
           "note": ("1F1B trades compute for memory BY DESIGN: 4 body "
                    "units/tick (activation recompute) over m+2(pp-1) "
                    "ticks vs GPipe's 3 over m+pp-1.  Its in-schedule "
                    "head VJP is 1/pp-SHARDED since r5 (broadcast yb, "
                    "per-stage slice, psum dy) — before that it ran "
                    "replicated on every stage every tick, which at this "
                    "toy shape (head %.0fx the per-stage body at pp=%d) "
                    "was the r4 'pp=8 collapse': structural head "
                    "domination, not a scheduler bug.  Post-fix analytic "
                    "gpipe/1f1b ratio at pp=%d: %.1fx (body recompute + "
                    "extra ticks remain — the price of the min(m,2pp-1) "
                    "activation ring vs GPipe's m+pp-1 scan residuals; "
                    "prefer 1F1B when activations, not FLOPs, bound the "
                    "config)."
                    % (head_ratio, pp_max, pp_max, ratio))}
    _emit(out)
    return 0


def run_attention_sweep(steps=10, warmup=3):
    """GPT-2 long-sequence throughput with the streaming Pallas attention
    kernel vs the XLA einsum path (VERDICT r2 #7).  The dispatch env is
    read at trace time, so each mode builds its own engine.  Rows on
    stderr, one JSON summary on stdout."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2

    if jax.default_backend() != "tpu":
        raise RuntimeError(
            "BENCH_ATTN_SWEEP needs a TPU backend: the kernel dispatch in "
            "models/layers.py is TPU-gated, so off-TPU both rows would run "
            "the XLA path and the reported speedup would be meaningless")
    T = int(os.environ.get("BENCH_SEQ", "1024"))
    B = int(os.environ.get("BENCH_BATCH", "8"))
    rng = np.random.default_rng(0)
    toks = rng.integers(0, 50304, size=(B, T)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    labels[:, -1] = -1

    rows = []
    # "0" = XLA einsum path, "1" = streaming kernel FORCED (the auto
    # dispatch would silently fall back to XLA below STREAM_AUTO_MIN and
    # the "speedup" would compare XLA with itself)
    for mode in ("0", "1"):
        os.environ["DSTPU_FUSED_ATTN"] = mode
        model = GPT2.from_size("tiny", vocab_size=50304, max_seq_len=T,
                               num_layers=12, hidden_size=768, num_heads=12)
        engine, _, _, _ = deepspeed_tpu.initialize(
            config={"train_batch_size": B, "steps_per_print": 10 ** 9,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "bf16": {"enabled": True},
                    "activation_checkpointing": {"enabled": True,
                                                 "policy": "selective"}},
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(0)))
        for _ in range(warmup):
            loss = engine.train_batch((toks, labels))
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch((toks, labels))
        float(loss)
        dt = (time.perf_counter() - t0) / steps
        rows.append({"attn": "xla" if mode == "0" else "stream-pallas",
                     "ms_per_step": round(dt * 1000, 1),
                     "samples_per_sec": round(B / dt, 2)})
        os.environ.pop("DSTPU_FUSED_ATTN", None)
        print(f"attn={rows[-1]['attn']}: {rows[-1]['ms_per_step']} ms/step",
              file=sys.stderr)
    speedup = rows[0]["ms_per_step"] / rows[1]["ms_per_step"]
    _emit({"metric": f"gpt2_seq{T}_attention_kernel_speedup",
           "value": round(speedup, 3), "unit": "x vs XLA path",
           "rows": rows})
    return 0


def run_mfu_breakdown():
    """Account for the headline step's chip time by ENGINE-LEVEL ablation
    (VERDICT r4 weak #2: MFU 0.554 with no committed breakdown).

    Per-op microbenches are not trustworthy on this rig: the axon
    platform's ``block_until_ready`` returns before the chip finishes
    (a 1.1 TFLOP matmul "completes" in 0.07 ms) and per-dispatch tunnel
    overhead inflates chained small ops ~50x — only the fenced
    ``train_batch`` + final-loss-read methodology gives real times.  So
    every number here IS a full fenced engine run, and components come
    from differencing configs:

      base           L=24 layers, gas=G, maxpred=20   (headline shape)
      half_layers    L=12                             -> per-layer cost
      double_gas     gas=2G                           -> per-micro vs fixed
      maxpred80      maxpred=80                       -> MLM-head cost
      seq256         seq=256, mb halved (same tokens) -> attention growth

    Derived per-optimizer-step seconds:
      body+attn+ln (24 layers) = 2 x (base - half_layers)
      per-step fixed (LAMB update + dispatch) = base - G x per_micro
      mlm head (20 preds) = (maxpred80 - base) / 3
      attention(seq128 portion): seq256 doubles attention score/value
        FLOPs per token but keeps matmul FLOPs constant ->
        attn ~= (seq256 - base) adjusted by the remat replay share
      residual = base - (sum of attributed components) — reported, not
        hidden (VERDICT asks >= 90% accounted).
    One JSON line."""
    import gc

    G = int(os.environ.get("BENCH_GAS", "12"))
    mb = int(os.environ.get("BENCH_BATCH", "24"))
    steps = int(os.environ.get("BENCH_STEPS", "6"))

    def step_s(seq=128, layers=None, gas=None, maxpred=None, batch=None):
        over = {}
        if layers is not None:
            os.environ["BENCH_LAYER_OVERRIDE"] = str(layers)
        if maxpred is not None:
            os.environ["BENCH_MAXPRED"] = str(maxpred)
        try:
            res = run_config("large", seq, batch or mb, steps, "selective",
                             gas=gas or G)
        finally:
            os.environ.pop("BENCH_LAYER_OVERRIDE", None)
            os.environ.pop("BENCH_MAXPRED", None)
        gc.collect()
        B = (batch or mb) * (gas or G)
        return B / res["per_chip"], res

    base_s, base_res = step_s()
    half_layers_s, _ = step_s(layers=12)
    double_gas_s, _ = step_s(gas=2 * G)
    maxpred80_s, _ = step_s(maxpred=80)
    seq256_s, _ = step_s(seq=256, batch=mb // 2)

    per_micro = (double_gas_s - base_s) / G
    fixed = base_s - G * per_micro                 # LAMB + per-step misc
    body_attn_ln = 2.0 * (base_s - half_layers_s)  # all 24 layers, / step
    head20 = (maxpred80_s - base_s) / 3.0
    # seq256 at half mb: same matmul FLOPs/step, attention score/value
    # FLOPs double, remat replays them again in the backward
    attn_total = seq256_s - base_s                 # extra attention = 1x
    embed_and_misc = base_s - body_attn_ln - head20 - fixed

    comps = {
        "body_24_layers_matmul_attn_ln": round(body_attn_ln, 4),
        "attention_portion_of_body": round(attn_total, 4),
        "mlm_head_20_preds": round(head20, 4),
        "per_step_fixed_lamb_dispatch": round(fixed, 4),
        "embedding_residual": round(embed_and_misc, 4),
    }
    attributed = body_attn_ln + head20 + fixed
    accounted_pct = attributed / base_s * 100
    _emit({"metric": "bert_large_seq128_mfu_breakdown",
           "value": round(accounted_pct, 1),
           "unit": "% of measured step attributed by engine ablations "
                   "(residual reported separately)",
           "measured_step_s": round(base_s, 4),
           "gas": G, "batch_per_chip": mb,
           "per_chip": round(base_res["per_chip"], 2),
           "mfu": round(base_res["mfu"], 4),
           # planner prediction next to measurement: diff these against
           # the measured step/boundary next chip session
           "predicted_peak_hbm_gb": base_res.get("predicted_peak_hbm_gb"),
           "predicted_boundary_ms": base_res.get("predicted_boundary_ms"),
           "predicted_profile": base_res.get("predicted_profile"),
           "ablation_step_s": {
               "base": round(base_s, 4),
               "half_layers": round(half_layers_s, 4),
               "double_gas": round(double_gas_s, 4),
               "maxpred80": round(maxpred80_s, 4),
               "seq256_halfbatch": round(seq256_s, 4)},
           "components_s": comps,
           "components_pct": {k: round(v / base_s * 100, 1)
                              for k, v in comps.items()}})
    return 0


def run_data_bench(steps=4, warmup=2):
    """Real-data input-path throughput at the headline config (VERDICT r4
    weak #4): REAL text (the repo's own docs) → wordpiece tokenize →
    masked-LM arrays → FileDataset on disk → memmap + native row-gather →
    producer-thread collation + double-buffered device placement →
    engine.train_batch.  Compared against the synthetic in-memory batch
    the headline uses.  Done-bar: within 3% of synthetic."""
    import gc
    import glob
    import shutil
    import tempfile

    import jax

    import deepspeed_tpu
    from deepspeed_tpu import tokenization as tok
    from deepspeed_tpu.data import DeepSpeedDataLoader, FileDataset
    from deepspeed_tpu.models import BertForPreTraining
    from deepspeed_tpu.parallel.topology import make_mesh

    on_tpu = jax.devices()[0].platform == "tpu"
    mb = int(os.environ.get("BENCH_BATCH", "24" if on_tpu else "4"))
    gas = int(os.environ.get("BENCH_GAS", "48" if on_tpu else "2"))
    seq, n_pred = 128, 20
    size = os.environ.get("BENCH_SIZE", "large" if on_tpu else "tiny")

    # -- synthetic leg (the headline methodology)
    res = run_config(size, seq, mb, steps, "selective", gas=gas,
                     warmup=warmup)
    synth = res["per_chip"]
    gc.collect()

    # -- build the on-disk corpus from real repo text
    texts = []
    for path in sorted(glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "docs", "*.md"))):
        with open(path) as f:
            texts.append(f.read())
    corpus = "\n".join(texts)
    # the docs mention the special tokens literally — dedup against them
    words = sorted(set(w for w in corpus.split() if w)
                   - set(tok.SPECIAL_TOKENS))
    vocab = tok.Vocab(list(tok.SPECIAL_TOKENS) + words)
    tokenizer = tok.BertTokenizer(vocab)
    B = mb * jax.device_count() * gas
    need = (steps + warmup) * B + B
    reps = []
    n_have = 0
    while n_have < need * (seq - 2):        # rough token budget
        reps.append(corpus)
        n_have += len(corpus.split())       # >= 1 token per word
    fields = tok.build_mlm_arrays(reps, tokenizer, seq_len=seq,
                                  max_predictions=n_pred,
                                  n_samples=need)
    d = tempfile.mkdtemp(prefix="dstpu_mlm_")
    FileDataset.save(d, **fields)

    # -- file-backed leg: fresh engine (the synthetic one was freed),
    #    loader streams from disk with producer-side device placement.
    #    The MODEL must match the synthetic leg exactly (standard vocab;
    #    the small test vocab's ids index into it fine)
    model = BertForPreTraining.from_size(size, max_seq_len=seq)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": B,
                "gradient_accumulation_steps": gas,
                "optimizer": {"type": "Lamb",
                              "params": {"lr": 4e-3, "max_coeff": 0.5,
                                         "min_coeff": 0.08}},
                "bf16": {"enabled": True},
                "activation_checkpointing": {"enabled": True,
                                             "policy": "selective"},
                "steps_per_print": 10 ** 9},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=make_mesh(model_parallel_size=1))
    loader = DeepSpeedDataLoader(FileDataset(d), batch_size=B,
                                 mesh=engine.mesh, num_workers=1,
                                 prefetch_depth=2, device_prefetch=True)
    it = iter(loader)
    for _ in range(warmup):
        loss = engine.train_batch(next(it))
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(next(it))
    last = float(loss)
    dt = time.perf_counter() - t0
    per_chip = B * steps / dt / jax.device_count()
    shutil.rmtree(d, ignore_errors=True)
    if not np.isfinite(last):
        raise RuntimeError(f"real-data bench loss not finite: {last}")

    _emit({"metric": "bert_%s_seq%d_realdata_vs_synthetic" % (size, seq),
           "value": round(per_chip / synth, 4),
           "unit": "x of synthetic throughput (1.0 = no input bottleneck)",
           "realdata_per_chip": round(per_chip, 2),
           "synthetic_per_chip": round(synth, 2),
           "predicted_peak_hbm_gb": res.get("predicted_peak_hbm_gb"),
           "predicted_boundary_ms": res.get("predicted_boundary_ms"),
           "predicted_profile": res.get("predicted_profile"),
           "n_samples_on_disk": int(fields["input_ids"].shape[0]),
           "vocab": len(vocab)})
    return 0


def run_opt_bench(repeats=30):
    """Optimizer-kernel microbench (VERDICT r4 weak #5 / item 8): the
    Pallas LAMB/Adam kernels vs XLA's fused update, ON CHIP, in the two
    layouts the engine actually runs — the per-leaf BERT-large tree and
    the single ZeRO-style flat fp32 buffer (for Adam the flat buffer is
    one leaf, so the Pallas row IS the batched flat-buffer kernel).  One
    JSON line; the committed artifact decides should_use_pallas."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.models import BertForPreTraining
    from deepspeed_tpu.ops import optim as optim_mod

    model = BertForPreTraining.from_size("large", max_seq_len=128)
    params = jax.tree_util.tree_map(
        lambda l: l.astype(jnp.float32),
        model.init_params(jax.random.PRNGKey(0)))
    grads = jax.tree_util.tree_map(
        lambda p: jnp.full(p.shape, 1e-4, jnp.float32), params)
    n = _count_params(params)

    def timed(opt, p, g, s):
        """Chained executions + one readback: block_until_ready does not
        fence on the axon platform (see run_mfu_breakdown.timed)."""
        def step(eps, p, g, s):
            p2 = jax.tree_util.tree_map(lambda x: x + eps, p)
            new_p, _ = opt.update(p2, g, s)
            return sum(jnp.sum(l).astype(jnp.float32) * 1e-9
                       for l in jax.tree_util.tree_leaves(new_p))
        upd = jax.jit(step)
        float(upd(jnp.zeros(()), p, g, s))
        acc = jnp.zeros(())
        t0 = time.perf_counter()
        for _ in range(repeats):
            acc = upd(acc * 1e-30, p, g, s)
        float(acc)
        return (time.perf_counter() - t0) / repeats

    import gc

    rows = []
    for layout in ("per_leaf_tree", "flat_buffer"):
        if layout == "per_leaf_tree":
            p, g = params, grads
        else:
            # free the tree layout first — chip HBM holds only one layout
            # (+ its optimizer state) at a time
            params = grads = None
            gc.collect()
            p = zero_flat_like(model.init_params(jax.random.PRNGKey(0)))
            g = jnp.full_like(p, 1e-4)
        for name, mk in (("lamb", lambda up: optim_mod.Lamb(
                              lr=4e-3, use_pallas=up)),
                         ("adam", lambda up: optim_mod.Adam(
                              lr=1e-4, use_pallas=up))):
            if layout == "flat_buffer" and name == "lamb":
                # a flat-buffer LAMB computes ONE global trust ratio —
                # different numerics from the per-leaf reference; the
                # engine never runs it, so don't bench it
                continue
            res = {}
            for mode, up in (("xla", False), ("pallas", True)):
                opt = mk(up)
                state = opt.init(p)
                res[mode] = timed(opt, p, g, state)
                state = None
                gc.collect()
            rows.append({"layout": layout, "opt": name,
                         "xla_ms": round(res["xla"] * 1000, 3),
                         "pallas_ms": round(res["pallas"] * 1000, 3),
                         "pallas_vs_xla": round(
                             res["xla"] / res["pallas"], 3)})
            print(f"{layout} {name}: xla {res['xla']*1e3:.2f} ms, "
                  f"pallas {res['pallas']*1e3:.2f} ms", file=sys.stderr)
        p = g = None
        gc.collect()
    _emit({"metric": "optimizer_kernel_microbench",
           "unit": "ms per update, %d params" % n,
           "n_params": n, "rows": rows})
    return 0


def zero_flat_like(params):
    """One fp32 flat buffer with the tree's total (128-lane padded) size —
    the ZeRO stage-1/2 master layout."""
    import jax.numpy as jnp
    n = _count_params(params)
    padded = ((n + 127) // 128) * 128
    return jnp.zeros((padded,), jnp.float32) + 1e-2


def run_head_bench(repeats=None):
    """MLM-head microbench (the phase-2 seq-512 maxpred-80 suspect,
    bench_mfu_breakdown.json): dense [B,T,H]→vocab head vs the sparse
    masked-position paths, fwd+grad, jitted, chained-execution timing.

    Legs: ``dense`` (full [B, T, vocab] logits + masked CE), ``sparse``
    (dense-labels format with mlm_gather_budget — top_k select + gather),
    ``maskedpos_take`` / ``maskedpos_onehot`` (the standard BingBert
    positions/ids/weights format with the two gather impls —
    DSTPU_MLM_GATHER).  CPU-safe (shapes shrink off-TPU); the committed
    artifact records the platform, so CPU rows are never mistaken for
    chip numbers.  One JSON line."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.models import BertForPreTraining
    from deepspeed_tpu.models import layers as L_mod
    from deepspeed_tpu.parallel.topology import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    T = int(os.environ.get("BENCH_SEQ", "512"))
    n_pred = int(os.environ.get("BENCH_MAXPRED", "80"))
    B = int(os.environ.get("BENCH_BATCH", "24" if on_tpu else "4"))
    H = 1024 if on_tpu else 128
    V = 30528 if on_tpu else 4096
    reps = repeats or int(os.environ.get("BENCH_STEPS",
                                         "20" if on_tpu else "3"))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, T, H)).astype(np.float32),
                    jnp.bfloat16 if on_tpu else jnp.float32)
    dense_labels = np.full((B, T), -1, np.int32)
    positions = np.stack([np.sort(rng.choice(T, size=n_pred, replace=False))
                          for _ in range(B)]).astype(np.int32)
    mlm_ids = rng.integers(0, V, size=(B, n_pred)).astype(np.int32)
    np.put_along_axis(dense_labels, positions, mlm_ids, axis=1)
    weights = np.ones((B, n_pred), np.float32)

    mesh = make_mesh(model_parallel_size=1)
    model = BertForPreTraining.from_size(
        "tiny", vocab_size=V, max_seq_len=T, hidden_size=H,
        num_heads=max(4, H // 64), num_layers=1)
    params = model.init_params(jax.random.PRNGKey(0))
    head_keys = ("mlm_dense_w", "mlm_dense_b", "mlm_ln_s", "mlm_ln_b",
                 "mlm_bias", "wte")
    head_params = {k: params[k] for k in head_keys}

    def head_loss(kind):
        def dense(hp, h):
            logits = model._mlm_head(hp, h)
            tok = L_mod.vocab_parallel_cross_entropy(
                logits, jnp.asarray(dense_labels))
            return L_mod.masked_mean_loss(tok, jnp.asarray(dense_labels) >= 0)

        def sparse(hp, h):
            maskf = (jnp.asarray(dense_labels) >= 0).astype(jnp.float32)
            w, pos = jax.lax.top_k(maskf, n_pred)
            ids = jnp.clip(jnp.take_along_axis(
                jnp.asarray(dense_labels), pos, axis=1), 0, None)
            h_m = L_mod.gather_positions(h, pos)
            tok = L_mod.vocab_parallel_cross_entropy(
                model._mlm_head(hp, h_m), ids)
            return jnp.sum(tok * w) / jnp.maximum(jnp.sum(w), 1.0)

        def maskedpos(hp, h):
            h_m = L_mod.gather_positions(h, jnp.asarray(positions))
            tok = L_mod.vocab_parallel_cross_entropy(
                model._mlm_head(hp, h_m), jnp.asarray(mlm_ids))
            w = jnp.asarray(weights)
            return jnp.sum(tok * w) / jnp.maximum(jnp.sum(w), 1.0)

        body = {"dense": dense, "sparse": sparse,
                "maskedpos": maskedpos}[kind]

        def local(hp, h):
            # grads wrt head params AND the backbone activation (the real
            # training pullback — the scatter-vs-matmul VJP is the point)
            return jax.value_and_grad(
                lambda hp_, h_: jnp.asarray(body(hp_, h_), jnp.float32),
                argnums=(0, 1))(hp, h)

        specs = jax.tree_util.tree_map(lambda _: P(), head_params)
        return jax.jit(jax.shard_map(
            local, mesh=mesh, in_specs=(specs, P()),
            out_specs=(P(), (specs, P())), check_vma=False))

    def timed(fn):
        out = fn(head_params, x)
        jax.tree_util.tree_leaves(out)[0].block_until_ready()
        acc = jnp.zeros((), jnp.float32)
        t0 = time.perf_counter()
        for _ in range(reps):
            loss, _ = fn(head_params, x)
            acc = acc + loss
        float(acc)
        return (time.perf_counter() - t0) / reps

    rows = []
    for leg, gather in (("dense", None), ("sparse", "auto"),
                        ("maskedpos", "take"), ("maskedpos", "onehot")):
        if gather:
            os.environ["DSTPU_MLM_GATHER"] = gather
        try:
            dt = timed(head_loss(leg.split("_")[0]))
        finally:
            os.environ.pop("DSTPU_MLM_GATHER", None)
        name = leg if gather in (None, "auto") else f"{leg}_{gather}"
        rows.append({"leg": name, "ms_per_step": round(dt * 1000, 2)})
        print(f"head {name}: {dt * 1e3:.2f} ms", file=sys.stderr)

    dense_ms = rows[0]["ms_per_step"]
    sparse_ms = rows[1]["ms_per_step"]
    _emit({"metric": "bert_mlm_head_sparse_vs_dense",
           "value": round(dense_ms / max(sparse_ms, 1e-6), 3),
           "unit": "x dense-head cost vs sparse masked-position gather "
                   "(fwd+grad)",
           "platform": jax.default_backend(),
           "seq": T, "n_pred": n_pred, "batch": B, "hidden": H, "vocab": V,
           "rows": rows,
           "note": ("CPU rows establish the algorithmic ratio only; "
                    "re-measure on chip with BENCH_HEAD=1 python bench.py "
                    "(the gather-VJP scatter the onehot path removes is "
                    "TPU-specific, so the chip ratio is LARGER)")})
    return 0


def run_overlap_bench():
    """Boundary comm/compute-overlap microbench (overlap_comm): ZeRO-1 and
    ZeRO-3 engines stepped with the bucketed/pipelined boundary vs the
    serial monolithic path (DSTPU_OVERLAP=off program shape).

    CPU evidence (what this run can prove off-chip): (1) PARITY — after
    ``steps`` fused train_batch steps the two engines' parameters are
    bitwise identical (bucketing only re-tiles the same elementwise math);
    (2) DISPATCH — the overlap step program really issues K independent
    reduce-scatter / all-gather collectives where the serial program
    issues one of each (counted in the traced jaxpr).  Wall-clock overlap
    needs real ICI ∥ MXU concurrency — on the virtual CPU mesh all
    devices share host cores, so ms/step here is contention noise; the
    artifact records the platform and the chip re-measurement command
    (WALLCLOCK.md §8).  One JSON line -> bench_overlap.json."""
    import jax

    from deepspeed_tpu.analysis import graph as G

    n = jax.device_count()
    if n < 2:
        raise RuntimeError(
            "overlap bench needs >= 2 devices; set JAX_PLATFORMS=cpu "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "PALLAS_AXON_POOL_IPS= for a virtual mesh")
    import deepspeed_tpu
    from deepspeed_tpu.models import GPT2
    from deepspeed_tpu.parallel.topology import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    seq = int(os.environ.get("BENCH_SEQ", "128" if on_tpu else "32"))
    hidden = int(os.environ.get("BENCH_OVERLAP_HIDDEN",
                                "1024" if on_tpu else "128"))
    layers = int(os.environ.get("BENCH_OVERLAP_LAYERS",
                                "24" if on_tpu else "4"))
    vocab = 50304 if on_tpu else 2048
    bucket_mb = float(os.environ.get("BENCH_OVERLAP_BUCKET_MB",
                                     "32" if on_tpu else "0.05"))
    bpc = int(os.environ.get("BENCH_BATCH", "8" if on_tpu else "2"))
    steps = int(os.environ.get("BENCH_STEPS", "20" if on_tpu else "4"))
    B = bpc * n

    rng = np.random.default_rng(0)
    toks = rng.integers(0, vocab, size=(B, seq)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    labels[:, -1] = -1

    def build(stage, overlap):
        model = GPT2.from_size(
            "tiny", vocab_size=vocab, max_seq_len=seq, num_layers=layers,
            hidden_size=hidden, num_heads=max(4, hidden // 64))
        engine, _, _, _ = deepspeed_tpu.initialize(
            config={"train_batch_size": B, "steps_per_print": 10 ** 9,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "bf16": {"enabled": True},
                    "zero_optimization": {
                        "stage": stage, "overlap_comm": overlap,
                        "comm_bucket_mb": bucket_mb}},
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(0)),
            mesh=make_mesh())
        return engine

    def collective_counts(engine):
        """reduce-scatter / all-gather equation counts of the fused step
        program (the dispatch/bucket-count evidence)."""
        from deepspeed_tpu import analysis

        jaxpr = analysis.trace_train_batch(engine, (toks, labels))
        counts = {"reduce_scatter": 0, "all_gather": 0, "psum": 0}
        for eqn, _ in G.walk(jaxpr.jaxpr):
            name = eqn.primitive.name
            if name == "psum_scatter":      # spelling varies by jax version
                name = "reduce_scatter"
            if name in counts:
                counts[name] += 1
        return counts

    rows = []
    final_params = {}
    for stage in (1, 3):
        for overlap in (True, False):
            engine = build(stage, overlap)
            loss = engine.train_batch((toks, labels))   # compile + step 1
            float(loss)
            t0 = time.perf_counter()
            for _ in range(steps):
                loss = engine.train_batch((toks, labels))
            float(loss)
            dt = (time.perf_counter() - t0) / steps
            counts = collective_counts(engine)
            buckets = (len(engine._comm_buckets() or ()) if engine.zero_flat
                       else None)
            rows.append({
                "stage": stage, "overlap": overlap,
                "ms_per_step": round(dt * 1000, 2),
                "buckets": buckets, **counts})
            final_params[(stage, overlap)] = jax.tree_util.tree_map(
                np.asarray, engine.params)
            print(f"zero-{stage} overlap={overlap}: {dt*1e3:.1f} ms/step "
                  f"buckets={buckets} {counts}", file=sys.stderr)

    parity = {}
    for stage in (1, 3):
        diffs = [float(np.max(np.abs(
            np.asarray(a, np.float32) - np.asarray(b, np.float32))))
            for a, b in zip(
                jax.tree_util.tree_leaves(final_params[(stage, True)]),
                jax.tree_util.tree_leaves(final_params[(stage, False)]))]
        parity[f"zero{stage}_max_abs_param_diff"] = max(diffs)

    r = {(row["stage"], row["overlap"]): row for row in rows}
    _emit({
        "metric": "boundary_overlap_microbench",
        "unit": "ms/step (+ per-program collective counts)",
        "platform": jax.default_backend(),
        "hardware_true": on_tpu,
        "seq": seq, "hidden": hidden, "layers": layers,
        "comm_bucket_mb": bucket_mb, "batch_per_chip": bpc,
        "zero1_buckets_overlap": r[(1, True)]["buckets"],
        "zero1_scatter_ops": [r[(1, True)]["reduce_scatter"],
                              r[(1, False)]["reduce_scatter"]],
        "zero1_gather_ops": [r[(1, True)]["all_gather"],
                             r[(1, False)]["all_gather"]],
        "zero3_gather_ops": [r[(3, True)]["all_gather"],
                             r[(3, False)]["all_gather"]],
        **{k: v for k, v in parity.items()},
        "rows": rows,
        "note": ("CPU rows prove bit-exact parity and the bucketed "
                 "dispatch structure only — virtual CPU devices share "
                 "host cores, so ms/step is contention noise, not "
                 "overlap.  Re-measure on chip: "
                 "BENCH_OVERLAP=1 BENCH_OUT=bench_overlap.json "
                 "python bench.py, then BENCH_SEQ=512 BENCH_GAS=32 "
                 "python bench.py with DSTPU_OVERLAP=off vs on for the "
                 "recipe-step delta (WALLCLOCK.md §8)")})
    return 0


def run_obs_bench():
    """Observability overhead + predicted-vs-measured leg (BENCH_OBS=1).

    Two identical runs of the headline recipe shape: the PR 1
    window-timer baseline (spool OFF — the fence cadence this PR
    replaces) and the spooled run (device ring buffer + one batched drain
    per window + JSONL event log).  The acceptance contract is
    samples/s(spool) >= samples/s(baseline): telemetry must be free on
    the hot path.  Also measures the boundary program directly and
    reports it against the capacity planner's prediction as
    ``predicted_drift`` — the same columns every spooled run now carries
    per window.  One JSON line -> bench_obs.json."""
    import tempfile

    import jax

    from deepspeed_tpu.observability import fences, schema

    on_tpu = jax.devices()[0].platform == "tpu"
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    size = os.environ.get("BENCH_SIZE", "large" if on_tpu else "tiny")
    bpc = int(os.environ.get("BENCH_BATCH", "24" if on_tpu else "8"))
    steps = int(os.environ.get("BENCH_STEPS", "16" if on_tpu else "6"))
    gas = int(os.environ.get("BENCH_GAS", "48" if on_tpu else "1"))
    window = int(os.environ.get("BENCH_OBS_WINDOW", "4" if on_tpu else "3"))
    remat = "selective"

    # each leg runs BENCH_OBS_REPEAT times and keeps its best samples/s
    # (min-time estimator): on a contended CPU a single short run's ratio
    # is noise; the best-of comparison isolates the dispatch-path cost
    # the leg exists to measure
    repeat = int(os.environ.get("BENCH_OBS_REPEAT", "1" if on_tpu else "2"))

    def best(runs):
        return max(runs, key=lambda r: r["per_chip"])

    # baseline leg: spool off AND no boundary microbench — it must time
    # exactly the PR 1 window-timer path
    base = best([run_config(size, seq, bpc, steps, remat, gas=gas,
                            measure_boundary=False)
                 for _ in range(repeat)])

    tmp = tempfile.mkdtemp(prefix="dstpu_obs_")
    f0 = fences.FENCE_COUNT
    spool_runs = []
    for r in range(repeat):
        path = os.path.join(tmp, f"telemetry_{r}.jsonl")
        # fleet mode ON (BENCH_OBS_FLEET=0 opts out): the aggregation /
        # detector / fleet-event path must be free on the hot path too —
        # the fences_per_run contract below gates it
        spool_runs.append((run_config(
            size, seq, bpc, steps, remat, gas=gas,
            obs_window=window, jsonl_path=path, measure_boundary=True,
            obs_fleet=os.environ.get("BENCH_OBS_FLEET", "1") == "1"),
            path))
    # one deliberate fence per run: the final flush (pinned exactly by
    # tests/test_observability.py; bench divides to stay robust to repeat)
    spool_fences = (fences.FENCE_COUNT - f0) // repeat
    spool, jsonl = max(spool_runs, key=lambda t: t[0]["per_chip"])

    problems = schema.validate_jsonl(jsonl)
    by_schema = schema.count_by_schema(jsonl)
    windows = by_schema.get(schema.SCHEMA_ID, 0)
    fleet_events = by_schema.get(schema.FLEET_SCHEMA_ID, 0)
    startup_events = by_schema.get(schema.STARTUP_SCHEMA_ID, 0)

    ratio = spool["per_chip"] / base["per_chip"] if base["per_chip"] else None
    _emit({
        "metric": "observability_overhead",
        "unit": "samples/s/chip (spooled vs window-timer baseline)",
        "platform": jax.devices()[0].platform,
        "hardware_true": on_tpu,
        "size": size, "seq": seq, "batch_per_chip": bpc, "gas": gas,
        "steps": steps, "report_window": window,
        "samples_per_sec_per_chip_baseline": round(base["per_chip"], 2),
        "samples_per_sec_per_chip_spooled": round(spool["per_chip"], 2),
        "spooled_over_baseline": round(ratio, 4) if ratio else None,
        "runs_per_leg": repeat,
        # deliberate engine fences PER spooled run: ONLY the telemetry
        # flush — zero from the per-step path (the bench's own float(loss)
        # reads are caller-side and uncounted; the counter regression is
        # pinned by tests/test_observability.py)
        "spooled_fences_per_run": spool_fences,
        "fleet_mode": os.environ.get("BENCH_OBS_FLEET", "1") == "1",
        "jsonl_windows": windows,
        "jsonl_fleet_events": fleet_events,
        "jsonl_startup_events": startup_events,
        "jsonl_schema_valid": not problems,
        "measured_boundary_ms": spool.get("measured_boundary_ms"),
        "predicted_boundary_ms": spool.get("predicted_boundary_ms"),
        "predicted_drift": spool.get("predicted_drift"),
        "predicted_peak_hbm_gb": spool.get("predicted_peak_hbm_gb"),
        "predicted_profile": spool.get("predicted_profile"),
        "note": ("CPU rows prove overhead-freedom of the spool dispatch "
                 "path and the drift wiring only; wall-clock deltas and "
                 "true boundary/HBM drift need a chip.  Re-measure: "
                 "BENCH_OBS=1 BENCH_OUT=bench_obs.json python bench.py; "
                 "the headline recipe picks up measured_boundary_ms + "
                 "predicted_drift columns with BENCH_OBS_COLUMNS=1"),
    })
    rc = 0
    if problems:
        for line_no, msg in problems:
            print(f"telemetry jsonl invalid at {line_no}: {msg}",
                  file=sys.stderr)
        rc = 1
    if spool_fences != 1:
        # the deterministic half of the acceptance contract: exactly one
        # deliberate fence per spooled run (the flush).  Anything else
        # means a per-step fence crept back into a counted path — a hard
        # failure, unlike the ratio below which is wall-clock noise on a
        # contended virtual-CPU mesh
        print(f"spooled run took {spool_fences} deliberate fences "
              f"(expected exactly 1: the flush)", file=sys.stderr)
        rc = 1
    if ratio is not None and ratio < 1.0:
        print(f"WARNING: spooled/baseline samples/s = {ratio:.4f} < 1 — "
              f"re-measure on an idle machine / a chip before reading "
              f"this as telemetry overhead", file=sys.stderr)
    return rc


def run_ckpt_bench(tmpdir=None):
    """Checkpoint save-stall measurement (VERDICT r4 weak #3): BERT-large
    (the headline model) through engine.save_checkpoint in sync and async
    modes.  Reports the training stall of each — for async that is the
    device→host snapshot only; the container writes overlap the next
    steps — plus restore time and a resume-parity check.  One JSON line."""
    import shutil
    import tempfile

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import BertForPreTraining

    size = os.environ.get("BENCH_SIZE",
                          "large" if jax.default_backend() == "tpu"
                          else "tiny")
    model = BertForPreTraining.from_size(size, max_seq_len=128)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 8, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True}},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    n_params = _count_params(engine.params)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.config.vocab_size, size=(8, 128))
    positions = np.stack([rng.choice(128, size=20, replace=False)
                          for _ in range(8)]).astype(np.int32)
    batch = (ids.astype(np.int32), np.ones((8, 128), np.int32),
             np.zeros((8, 128), np.int32), positions,
             np.take_along_axis(ids, positions, axis=1).astype(np.int32),
             np.ones((8, 20), np.float32))
    float(engine.train_batch(batch))      # compile + settle

    d = tmpdir or tempfile.mkdtemp(prefix="dstpu_ckpt_bench_")
    rows = {}
    t0 = time.perf_counter()
    float(engine.train_batch(batch))
    rows["baseline_step_s"] = round(time.perf_counter() - t0, 3)

    # COLD sync save: the step above replaced every device array, so this
    # pays device→host transfer AND the container write
    t0 = time.perf_counter()
    engine.save_checkpoint(d, tag="sync")
    rows["sync_save_stall_s"] = round(time.perf_counter() - t0, 3)
    # WARM sync save (no step in between → jax host-copy caches hit):
    # isolates the container write + disk cost
    t0 = time.perf_counter()
    engine.save_checkpoint(d, tag="sync")
    rows["container_write_s"] = round(time.perf_counter() - t0, 3)
    rows["device_to_host_s"] = round(
        rows["sync_save_stall_s"] - rows["container_write_s"], 3)

    # COLD async save: a fresh step invalidates the caches, so this stall
    # is the honest steady-state one — the device→host snapshot; the
    # container write drains on the background thread under the next step
    float(engine.train_batch(batch))
    t0 = time.perf_counter()
    engine.save_checkpoint(d, tag="async", async_save=True)
    rows["async_save_stall_s"] = round(time.perf_counter() - t0, 3)
    t0 = time.perf_counter()
    loss_after = float(engine.train_batch(batch))
    rows["overlapped_step_s"] = round(time.perf_counter() - t0, 3)
    t0 = time.perf_counter()
    engine.checkpoint_wait()
    rows["async_drain_s"] = round(time.perf_counter() - t0, 3)

    t0 = time.perf_counter()
    e2, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 8, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True}},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(1)))
    e2.load_checkpoint(d, tag="async")
    rows["restore_s"] = round(time.perf_counter() - t0, 3)
    parity = abs(float(e2.train_batch(batch)) - loss_after)
    if not tmpdir:
        shutil.rmtree(d, ignore_errors=True)

    state_gb = n_params * (2 + 4 + 4 + 4) / 2 ** 30  # bf16 p + fp32 m,mo
    mbps = state_gb * 1024 / max(rows["device_to_host_s"], 1e-3)
    _emit({"metric": "checkpoint_save_stall",
           "value": rows["async_save_stall_s"], "unit": "s (async stall)",
           "n_params": n_params, "state_gb": round(state_gb, 2),
           "device_to_host_mb_per_s": round(mbps, 1),
           "note": ("async stall = device->host snapshot only (the "
                    "container write drains on the writer thread).  On "
                    "this rig the chip is reached through the axon "
                    "tunnel at ~%.0f MB/s, which dominates; a real "
                    "TPU-VM host does GB/s DMA, putting the same "
                    "snapshot in low seconds" % mbps),
           "resume_loss_delta": round(parity, 6), **rows})
    return 0


def run_resume_bench(tmpdir=None):
    """End-to-end time-to-first-step after a relaunch (BENCH_RESUME=1):
    the two halves of fast resume, measured separately and summed.

    Restore: one engine saves a checkpoint, then a fresh engine (different
    init seed — nothing to reuse) restores it twice, first through the
    serial fallback (``restore_threads=1``) and then through the parallel
    streaming pipeline (``restore_threads=0`` auto) — same files, bitwise
    the same state, different wall-clock.  Compile: the persistent
    compilation cache is pointed at a fresh directory, so the FIRST
    train_batch pays real XLA compilation (cold, counted as cache misses)
    and the restored engine's first train_batch — after
    ``jax.clear_caches()`` drops the in-memory executables, exactly like a
    relaunched process — deserializes from the cache instead (warm,
    counted as hits).  One JSON line → bench_resume.json."""
    import shutil
    import tempfile

    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import BertForPreTraining
    from deepspeed_tpu.resilience.counters import COUNTERS

    on_tpu = jax.default_backend() == "tpu"
    size = os.environ.get("BENCH_SIZE", "large" if on_tpu else "base")
    root = tmpdir or tempfile.mkdtemp(prefix="dstpu_resume_bench_")
    cache_dir = os.path.join(root, "compile_cache")
    ckpt_dir = os.path.join(root, "ckpt")

    def build(seed):
        model = BertForPreTraining.from_size(size, max_seq_len=128)
        engine, _, _, _ = deepspeed_tpu.initialize(
            config={"train_batch_size": 8, "steps_per_print": 10 ** 9,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "bf16": {"enabled": True},
                    "compile_cache": {"dir": cache_dir},
                    "checkpoint": {"restore_threads": 1}},
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(seed)))
        return model, engine

    model, engine = build(0)
    n_params = _count_params(engine.params)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, model.config.vocab_size, size=(8, 128))
    positions = np.stack([rng.choice(128, size=20, replace=False)
                          for _ in range(8)]).astype(np.int32)
    batch = (ids.astype(np.int32), np.ones((8, 128), np.int32),
             np.zeros((8, 128), np.int32), positions,
             np.take_along_axis(ids, positions, axis=1).astype(np.int32),
             np.ones((8, 20), np.float32))

    rows = {}
    h0, m0 = COUNTERS.compile_cache_hits, COUNTERS.compile_cache_misses
    t0 = time.perf_counter()
    float(engine.train_batch(batch))
    rows["compile_cold_s"] = round(time.perf_counter() - t0, 3)
    rows["cold_cache_misses"] = COUNTERS.compile_cache_misses - m0
    engine.save_checkpoint(ckpt_dir, tag="resume")

    # fresh engine, serial restore (the pre-PR-5 read path)
    _, e_serial = build(1)
    t0 = time.perf_counter()
    e_serial.load_checkpoint(ckpt_dir, tag="resume")
    rows["restore_serial_s"] = round(time.perf_counter() - t0, 3)

    # fresh engine, parallel streaming restore (reader pool, auto width)
    _, e_par = build(2)
    e_par.config.checkpoint_restore_threads = 0
    t0 = time.perf_counter()
    e_par.load_checkpoint(ckpt_dir, tag="resume")
    rows["restore_parallel_s"] = round(time.perf_counter() - t0, 3)

    # weights-only fast path (the serving cold start): same reader
    # pipeline, but optimizer/ZeRO partitions are never read —
    # docs/resilience.md "Time to resume" carries this row next to the
    # full restores
    from deepspeed_tpu import checkpoint as _ckpt
    t0 = time.perf_counter()
    _tag, _tree = _ckpt.load_params_only(ckpt_dir, tag="resume",
                                         dtype="bfloat16")
    rows["restore_params_only_s"] = round(time.perf_counter() - t0, 3)
    del _tree

    # a relaunched process has no in-memory executables — drop ours so the
    # restored engine's first step goes to the persistent cache
    jax.clear_caches()
    h1 = COUNTERS.compile_cache_hits
    t0 = time.perf_counter()
    loss = float(e_par.train_batch(batch))
    rows["compile_warm_s"] = round(time.perf_counter() - t0, 3)
    rows["warm_cache_hits"] = COUNTERS.compile_cache_hits - h1
    if rows["warm_cache_hits"] <= 0:
        raise RuntimeError(
            "BENCH_RESUME: the restored engine's first step did not hit "
            "the persistent compilation cache (hits stayed at "
            f"{COUNTERS.compile_cache_hits}) — the relaunch would pay a "
            "full recompile")
    if not np.isfinite(loss):
        # fail LOUDLY: a non-finite loss from a bitwise-restored state
        # means the cache-deserialized executable computed garbage, and a
        # garbage artifact must never be committed silently.  Known
        # trigger: some jax 0.4.x XLA-CPU builds lose donation aliasing
        # when deserializing donated-buffer executables.
        raise RuntimeError(
            f"BENCH_RESUME: resumed loss is {loss} on a bitwise-restored "
            "state — the persistent-cache deserialized executable is "
            "computing garbage (known on jax 0.4.x XLA-CPU with donated "
            "buffers).  Rerun with DSTPU_NO_DONATE=1 to measure on this "
            "rig; the artifact records the switch")
    if os.environ.get("DSTPU_NO_DONATE") == "1":
        rows["donation"] = "off (DSTPU_NO_DONATE=1)"
    else:
        # the engine auto-skips donation when the persistent cache is
        # enabled on a quirk-listed backend (the incident this leg's
        # NaN guard caught — docs/resilience.md); record the EFFECTIVE
        # donation so the measurement conditions stay explicit
        from deepspeed_tpu.analysis import profiles as _prof
        _p = _prof.default_profile()
        rows["donation"] = (
            "off (auto: persistent_cache_donation_unsafe)"
            if (_p is not None and _p.persistent_cache_donation_unsafe
                and os.environ.get("DSTPU_FORCE_DONATE") != "1")
            else "on")

    rows["time_to_first_step_cold_s"] = round(
        rows["restore_serial_s"] + rows["compile_cold_s"], 3)
    rows["time_to_first_step_warm_s"] = round(
        rows["restore_parallel_s"] + rows["compile_warm_s"], 3)
    if not tmpdir:
        shutil.rmtree(root, ignore_errors=True)

    _emit({"metric": "resume_time_to_first_step",
           "value": rows["time_to_first_step_warm_s"],
           "unit": "s (parallel restore + warm compile cache)",
           "n_params": n_params, "platform": jax.default_backend(),
           "loss_after_resume": round(loss, 6),
           "note": ("cold = serial restore + full XLA compile (a relaunch "
                    "before PR 5); warm = parallel streaming restore + "
                    "persistent-cache deserialize.  warm_cache_hits > 0 "
                    "is the proof the restarted step skipped recompilation"),
           **rows})
    return 0


def _bench_serve(jsonl_dir=None):
    """Serving throughput/latency under synthetic heavy traffic
    (BENCH_SERVE=1): continuous batching vs the static baseline on the
    SAME deterministic request trace, greedy sampling, identical outputs
    asserted — so the comparison is pure scheduling, not generation
    luck.  Reports tokens/s/chip and p50/p99 time-to-first-token /
    inter-token latency for both schedulers plus an int8-quantized
    continuous leg; one JSON line → bench_serve.json.

    Env knobs: BENCH_SIZE (gpt2 size, default tiny on CPU / small on
    TPU), BENCH_SERVE_SLOTS (8), BENCH_SERVE_REQUESTS (32),
    BENCH_SERVE_TOKENS (per-slot cache capacity, 128),
    BENCH_SERVE_DTYPE (float32 on CPU / bfloat16 on TPU),
    BENCH_SERVE_LEGS (comma subset of
    int8,fused,obs,prefix,spec,router,disagg — default all; the
    continuous/static base always runs: every other leg compares
    against it)."""
    import shutil
    import tempfile

    import jax

    from deepspeed_tpu.inference import (InferenceEngine, StaticScheduler,
                                         latency_summary, run_serve,
                                         synthetic_requests)
    from deepspeed_tpu.models.gpt2 import GPT2

    on_tpu = jax.default_backend() == "tpu"
    size = os.environ.get("BENCH_SIZE", "small" if on_tpu else "tiny")
    vocab = int(os.environ.get("BENCH_VOCAB", "512"))
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", "8"))
    n_req = int(os.environ.get("BENCH_SERVE_REQUESTS", "32"))
    max_tokens = int(os.environ.get("BENCH_SERVE_TOKENS", "128"))
    dtype = os.environ.get("BENCH_SERVE_DTYPE",
                           "bfloat16" if on_tpu else "float32")
    bucket = min(64, max_tokens)
    root = jsonl_dir or tempfile.mkdtemp(prefix="dstpu_serve_bench_")
    legs = {s.strip() for s in os.environ.get(
        "BENCH_SERVE_LEGS", "all").split(",") if s.strip()}

    def leg_on(name):
        return "all" in legs or name in legs

    def build(quantize=None, decode_iters=1, n_slots=None):
        model = GPT2.from_size(size, vocab_size=vocab,
                               max_seq_len=max_tokens)
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "inference": {"max_slots": n_slots or slots,
                             "max_tokens": max_tokens,
                             "prefill_bucket": bucket, "page_tokens": 32,
                             "dtype": dtype, "quantize": quantize,
                             "decode_iters_per_dispatch": decode_iters}}
        return InferenceEngine(model, config=cfg, seed=0)

    # decode-heavy mixed-length trace: generation-length VARIANCE is what
    # static batching pays for (every batch decodes to its longest member)
    trace = synthetic_requests(
        n_req, vocab=vocab, seed=0, prompt_min=2,
        prompt_max=max(8, bucket // 4), new_min=4,
        new_max=int(os.environ.get("BENCH_SERVE_NEW_MAX", "48")))

    engine = build()
    # per-chip accounting uses the ENGINE's mesh (one replica = mp chips;
    # other devices on the host would serve other replicas)
    n_chips = len(engine.mesh.devices.flat)
    n_params = _count_params(engine.params)
    # warm the executables out of the timed region (both schedulers use
    # the same two programs, so neither side pays compile)
    engine.generate([trace[0].prompt], max_new_tokens=2)
    engine.reset()

    cont = run_serve(engine, trace,
                     jsonl_path=os.path.join(root, "serve.jsonl"),
                     window_iters=16)
    cont_sum, cont_results = cont["summary"], cont["results"]

    engine.reset()
    static = StaticScheduler(engine)
    t0 = time.perf_counter()
    static_results = static.run(trace)
    static_sum = latency_summary(static_results,
                                 time.perf_counter() - t0, n_chips)
    static_sum["decode_iters"] = static.decode_iters

    # same trace, same greedy sampler => identical generations, or the
    # comparison is meaningless
    by_rid = {r.rid: r.tokens for r in cont_results}
    for r in static_results:
        if by_rid[r.rid] != r.tokens:
            raise RuntimeError(
                f"BENCH_SERVE: request {r.rid} generated differently "
                f"under continuous vs static scheduling — the batching "
                f"invariance contract is broken")

    int8 = None
    if leg_on("int8"):
        engq = build(quantize="int8")
        engq.generate([trace[0].prompt], max_new_tokens=2)
        engq.reset()
        int8 = run_serve(engq, trace, window_iters=16)["summary"]

    # fused-decode leg: D=4 iterations per dispatch (the serving analog
    # of the multi-step driver) on the SAME trace — the ITL/p99-TTFT
    # row the D-amortization claim rests on, greedy outputs asserted
    # identical to the per-iteration run
    fused_d = int(os.environ.get("BENCH_SERVE_FUSED_D", "4"))
    fused_sum = None
    if leg_on("fused"):
        engf = build(decode_iters=fused_d)
        engf.generate([trace[0].prompt], max_new_tokens=2)
        engf.reset()
        fused = run_serve(engf, trace, window_iters=16)
        fused_sum, fused_results = fused["summary"], fused["results"]
        fused_sum["decode_iters_per_dispatch"] = fused_d
        by_rid_f = {r.rid: r.tokens for r in fused_results}
        for r in cont_results:
            if by_rid_f[r.rid] != r.tokens:
                raise RuntimeError(
                    f"BENCH_SERVE: request {r.rid} generated differently "
                    f"with D={fused_d} fused decode — the greedy-output "
                    f"identity contract is broken")

    # ---- observability-on leg: the SAME continuous trace with the
    # replica observability stack live — per-request lifecycle events +
    # serve v3 windows on the JSONL, the serve watchdog armed around
    # every dispatch, anomaly detectors at each flush (docs/
    # observability.md "Serving view").  Identical greedy outputs
    # asserted; the row records tokens/s as a RATIO of the baseline
    # continuous leg — the documented overhead bound is <= 3%.
    def build_obs():
        model = GPT2.from_size(size, vocab_size=vocab,
                               max_seq_len=max_tokens)
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "inference": {"max_slots": slots, "max_tokens": max_tokens,
                             "prefill_bucket": bucket, "page_tokens": 32,
                             "dtype": dtype,
                             "observability": {
                                 "window_iters": 16,
                                 "request_events": True,
                                 "watchdog_timeout_s": 60.0}}}
        return InferenceEngine(model, config=cfg, seed=0)

    # adjacent-in-time baseline PAIRS on warm engines: the ratio must
    # compare runs seconds apart, not the cold first leg of the bench
    # against a page-cache-warm later one — and on a virtual-CPU rig
    # one pair is contention noise, so it is best-of-N pairs (the PR 7
    # BENCH_OBS_REPEAT precedent; noise only ever LOWERS a ratio)
    obs_sum = obs_base = obs_ratio = obs_ok = None
    if leg_on("obs"):
        engo = build_obs()
        engo.generate([trace[0].prompt], max_new_tokens=2)
        obs_repeat = max(1, int(os.environ.get("BENCH_SERVE_OBS_REPEAT",
                                               "3")))
        for rep in range(obs_repeat):
            engine.reset()
            base_rep = run_serve(engine, trace,
                                 window_iters=16)["summary"]
            engo.reset()
            obs_rep = run_serve(
                engo, trace,
                jsonl_path=os.path.join(root, f"serve_obs_{rep}.jsonl"),
                window_iters=16)
            if rep == 0:
                by_rid_o = {r.rid: r.tokens for r in obs_rep["results"]}
                for r in cont_results:
                    if by_rid_o[r.rid] != r.tokens:
                        raise RuntimeError(
                            f"BENCH_SERVE: request {r.rid} generated "
                            f"differently with replica observability ON "
                            f"— the trajectory-neutrality contract is "
                            f"broken")
                from deepspeed_tpu.observability import \
                    schema as _obs_schema
                _obs_problems = _obs_schema.validate_jsonl(
                    os.path.join(root, "serve_obs_0.jsonl"))
                if _obs_problems:
                    raise RuntimeError(
                        f"BENCH_SERVE: observability-leg JSONL fails "
                        f"validation: {_obs_problems[:3]}")
            if not (base_rep["tokens_per_sec"]
                    and obs_rep["summary"]["tokens_per_sec"]):
                continue
            ratio = round(obs_rep["summary"]["tokens_per_sec"]
                          / base_rep["tokens_per_sec"], 4)
            if obs_ratio is None or ratio > obs_ratio:
                obs_ratio = ratio
                obs_sum, obs_base = obs_rep["summary"], base_rep
        obs_ok = obs_ratio is not None and obs_ratio >= 0.97
        if not obs_ok:
            print(f"BENCH_SERVE: WARNING — observability-on throughput "
                  f"ratio {obs_ratio} < 0.97 (documented bound is <= 3% "
                  f"overhead; virtual-CPU wall clock is contention noise "
                  f"— rerun or use a chip)", file=sys.stderr)

    # ---- shared-prefix multi-tenant leg: N requests share a system
    # prompt; with prefix reuse ON the engine maps the shared pages and
    # prefills only each request's tail — the no-reuse run re-prefills
    # the whole prompt every admission.  Identical greedy outputs
    # asserted; the delta is pure prefill FLOPs/dispatch width.
    from deepspeed_tpu.inference import Request
    from deepspeed_tpu.models.gpt2 import GPT2 as _GPT2
    sys_len = int(os.environ.get("BENCH_SERVE_PREFIX_TOKENS", "64"))
    pfx_bucket = sys_len + 32
    pfx_tokens = max(max_tokens, sys_len + 64)

    def build_prefix(reuse=True):
        model = _GPT2.from_size(size, vocab_size=vocab,
                                max_seq_len=pfx_tokens)
        cfg = {"train_micro_batch_size_per_gpu": 1,
               "inference": {"max_slots": slots, "max_tokens": pfx_tokens,
                             "prefill_bucket": pfx_bucket,
                             "page_tokens": 32, "dtype": dtype,
                             "prefix_reuse": reuse}}
        return InferenceEngine(model, config=cfg, seed=0)

    rng = np.random.default_rng(7)
    sys_prompt = rng.integers(0, vocab, size=sys_len).astype(int).tolist()
    pfx_trace = []
    for i in range(n_req):
        tail = rng.integers(0, vocab, size=int(
            rng.integers(2, 17))).astype(int).tolist()
        pfx_trace.append(Request(
            rid=i, prompt=sys_prompt + tail,
            max_new_tokens=int(rng.integers(8, 25))))

    def clone(tr):
        return [Request(rid=r.rid, prompt=list(r.prompt),
                        max_new_tokens=r.max_new_tokens) for r in tr]

    pfx_sum = pfx_base = reuse_beats = None
    if leg_on("prefix"):
        engp = build_prefix(reuse=True)
        # warm BOTH admission executables out of the timed region: the
        # first generate publishes the prefix (full-bucket program), the
        # second hits it (tail-bucket program)
        engp.generate([pfx_trace[0].prompt], max_new_tokens=2)
        engp.generate([pfx_trace[1].prompt], max_new_tokens=2)
        engp.reset()
        pfx = run_serve(engp, clone(pfx_trace), window_iters=16)
        pfx_sum, pfx_results = pfx["summary"], pfx["results"]
        engb = build_prefix(reuse=False)
        engb.generate([pfx_trace[0].prompt], max_new_tokens=2)
        engb.reset()
        pfx_base = run_serve(engb, clone(pfx_trace), window_iters=16)
        by_rid_p = {r.rid: r.tokens for r in pfx_base["results"]}
        for r in pfx_results:
            if by_rid_p[r.rid] != r.tokens:
                raise RuntimeError(
                    f"BENCH_SERVE: request {r.rid} generated differently "
                    f"with prefix reuse ON — the byte-identity contract "
                    f"is broken")
        pfx_sum["prefix_tokens"] = sys_len
        if not (pfx_sum["prefix_hit_rate"] or 0) > 0:
            raise RuntimeError(
                "BENCH_SERVE: shared-prefix leg recorded no prefix hits "
                "— the reuse path did not engage")
        reuse_beats = (
            (pfx_sum["tokens_per_sec"] or 0)
            >= (pfx_base["summary"]["tokens_per_sec"] or 0)
            and (pfx_sum["ttft_p50_ms"] or 0)
            <= (pfx_base["summary"]["ttft_p50_ms"] or 0))
        if not reuse_beats:
            print("BENCH_SERVE: WARNING — prefix reuse did not beat the "
                  "no-reuse baseline on this rig (wall-clock contention "
                  "noise; rerun or use a chip)", file=sys.stderr)

    # ---- speculative leg: J draft proposals + target verify fused into
    # ONE dispatch per iteration, vs the target-only continuous row on
    # the SAME trace/config.  The draft is the target's LEADING LAYERS
    # (default half) sharing its embedding/head — a distillation
    # stand-in with honestly MEASURED acceptance (spec_accept_rate in
    # the row); BENCH_SERVE_DRAFT_LAYERS overrides the depth.
    import jax as _jax
    spec_j = int(os.environ.get("BENCH_SERVE_SPEC_J", "6"))
    spec_sum = spec_beats = None
    if leg_on("spec"):
        tgt_model = _GPT2.from_size(size, vocab_size=vocab,
                                    max_seq_len=max_tokens)
        tgt_layers = tgt_model.config.num_layers
        draft_layers = int(os.environ.get("BENCH_SERVE_DRAFT_LAYERS",
                                          str(max(1, tgt_layers // 2))))
        tgt_params = tgt_model.init_params(_jax.random.PRNGKey(0))
        draft_model = _GPT2.from_size(size, vocab_size=vocab,
                                      max_seq_len=max_tokens,
                                      num_layers=draft_layers)
        draft_params = dict(
            tgt_params,
            blocks=_jax.tree_util.tree_map(
                lambda l: np.asarray(l)[:draft_layers],
                tgt_params["blocks"]))
        draft_kind = (f"{size}[first {draft_layers}/{tgt_layers} layers, "
                      f"shared embeddings]")
        spec_cfg = {"train_micro_batch_size_per_gpu": 1,
                    "inference": {"max_slots": slots,
                                  "max_tokens": max_tokens,
                                  "prefill_bucket": bucket,
                                  "page_tokens": 32, "dtype": dtype,
                                  "speculative": {
                                      "draft_tokens": spec_j}}}
        engs = InferenceEngine(tgt_model, config=spec_cfg, seed=0,
                               draft_model=draft_model,
                               draft_params=draft_params)
        engs.generate([trace[0].prompt], max_new_tokens=2)
        engs.reset()
        specr = run_serve(engs, trace, window_iters=16)
        spec_sum, spec_results = specr["summary"], specr["results"]
        spec_sum["draft_tokens"] = spec_j
        spec_sum["draft_kind"] = draft_kind
        by_rid_s = {r.rid: r.tokens for r in spec_results}
        for r in cont_results:
            if by_rid_s[r.rid] != r.tokens:
                raise RuntimeError(
                    f"BENCH_SERVE: request {r.rid} generated differently "
                    f"under speculative decoding — the token-identity "
                    f"contract is broken")
        spec_beats = ((spec_sum["tokens_per_sec"] or 0)
                      >= (cont_sum["tokens_per_sec"] or 0))
        if not spec_beats:
            print("BENCH_SERVE: WARNING — the speculative leg did not "
                  "beat target-only decode on this rig (low accept rate "
                  "or contention noise)", file=sys.stderr)

    # ---- router leg: a 2-replica FLEET behind the least-loaded router
    # (deepspeed_tpu/inference/router.py) vs ONE replica on the SAME
    # trace.  Each replica runs on its own driver thread (XLA releases
    # the GIL during compute, so replicas genuinely overlap — the
    # in-process stand-in for replicas on separate chips); scaling =
    # fleet tokens/s over the single replica's, the near-linear-scaling
    # claim (>= 1.8x for 2 replicas).  Greedy outputs asserted identical
    # to the single-replica run — batching invariance is what makes the
    # router's placement decisions output-invisible.  A second fleet run
    # wedges one replica mid-trace (chaos stall → serve watchdog → 503 →
    # router evicts + resubmits) and re-asserts identity THROUGH the
    # eviction.
    router_sum = router_single = router_scaling = router_ok = None
    evict_sum = None
    if leg_on("router"):
        from deepspeed_tpu.inference import run_fleet
        from deepspeed_tpu.observability import schema as _r_schema
        from deepspeed_tpu.resilience import chaos as _chaos_mod
        n_rep = int(os.environ.get("BENCH_SERVE_REPLICAS", "2"))
        # the leg's replica config (BOTH sides: the single baseline IS
        # one fleet replica): D-fused decode + a wider slot count push
        # the per-iteration HOST share down — on a CPU rig every replica
        # thread shares one interpreter, so GIL-serialized scheduler
        # bookkeeping is the in-process stand-in's scaling ceiling
        # (real chips don't share an interpreter; D=1 measures that
        # ceiling honestly at ~1.6x, documented in the note)
        router_d = int(os.environ.get("BENCH_SERVE_ROUTER_D", "8"))
        router_slots = int(os.environ.get("BENCH_SERVE_ROUTER_SLOTS",
                                          str(2 * slots)))

        def build_router():
            return build(decode_iters=router_d, n_slots=router_slots)

        single_eng = build_router()
        single_eng.generate([trace[0].prompt], max_new_tokens=2)
        fleet_engines = [build_router() for _ in range(n_rep)]
        for e in fleet_engines:
            e.generate([trace[0].prompt], max_new_tokens=2)
        # adjacent-in-time single/fleet PAIRS, best-of-N (the obs-leg
        # precedent: virtual-CPU contention noise only ever LOWERS a
        # scaling ratio); identity + JSONL gates ride the first pair
        router_repeat = max(1, int(os.environ.get(
            "BENCH_SERVE_ROUTER_REPEAT", "3")))
        for rep in range(router_repeat):
            single_eng.reset()
            single_rep = run_serve(single_eng, trace,
                                   window_iters=16)["summary"]
            for e in fleet_engines:
                e.reset()
            fleet = run_fleet(
                fleet_engines, trace, poll_s=0.02,
                jsonl_path=(os.path.join(root, "router.jsonl")
                            if rep == 0 else None))
            if rep == 0:
                by_rid_fl = {r.rid: r.tokens for r in fleet["results"]}
                for r in cont_results:
                    if by_rid_fl[r.rid] != r.tokens:
                        raise RuntimeError(
                            f"BENCH_SERVE: request {r.rid} generated "
                            f"differently through the fleet router — "
                            f"placement must be output-invisible "
                            f"(batching invariance)")
                _r_problems = _r_schema.validate_jsonl(
                    os.path.join(root, "router.jsonl"))
                if _r_problems:
                    raise RuntimeError(
                        f"BENCH_SERVE: router-leg JSONL fails "
                        f"validation: {_r_problems[:3]}")
            if not (single_rep["tokens_per_sec"]
                    and fleet["summary"]["tokens_per_sec"]):
                continue
            scaling = round(fleet["summary"]["tokens_per_sec"]
                            / single_rep["tokens_per_sec"], 4)
            if router_scaling is None or scaling > router_scaling:
                router_scaling = scaling
                router_sum = fleet["summary"]
                router_single = single_rep
        if router_sum is not None:
            router_sum["decode_iters_per_dispatch"] = router_d
            router_sum["slots"] = router_slots
        router_ok = (router_scaling is not None
                     and router_scaling >= 1.8)
        if not router_ok:
            print(f"BENCH_SERVE: WARNING — {n_rep}-replica fleet scaled "
                  f"{router_scaling}x (< 1.8x): replica threads are "
                  f"contending for host cores (virtual-CPU rig); rerun "
                  f"on a multi-chip host", file=sys.stderr)

        # eviction sub-leg: same trace, one replica wedged mid-traffic
        def build_wd():
            model = GPT2.from_size(size, vocab_size=vocab,
                                   max_seq_len=max_tokens)
            cfg = {"train_micro_batch_size_per_gpu": 1,
                   "inference": {"max_slots": slots,
                                 "max_tokens": max_tokens,
                                 "prefill_bucket": bucket,
                                 "page_tokens": 32, "dtype": dtype,
                                 "observability": {
                                     "watchdog_timeout_s": 0.75}}}
            return InferenceEngine(model, config=cfg, seed=0)

        evict_engines = [build_wd() for _ in range(2)]
        for e in evict_engines:
            e.generate([trace[0].prompt], max_new_tokens=2)
            e.reset()
        stall_at = max(e.decode_dispatches for e in evict_engines) + 5
        _chaos_mod.configure(stall_step=stall_at, stall_s=30.0)
        try:
            evict = run_fleet(evict_engines, trace, poll_s=0.02)
        finally:
            _chaos_mod.reset()
        by_rid_e = {r.rid: r.tokens for r in evict["results"]}
        for r in cont_results:
            if by_rid_e[r.rid] != r.tokens:
                raise RuntimeError(
                    f"BENCH_SERVE: request {r.rid} generated differently "
                    f"through an eviction + resubmit — the greedy "
                    f"identity contract must survive replica death")
        if evict["summary"]["evictions"] < 1:
            raise RuntimeError(
                "BENCH_SERVE: the eviction sub-leg wedged no replica — "
                "the chaos stall did not reach the watchdog")
        evict_sum = {k: evict["summary"][k] for k in
                     ("requests", "tokens_per_sec", "evictions",
                      "resubmits", "ttft_p99_ms", "queue_wait_p99_ms")}

    # ---- disaggregation leg: prefill and decode pools with KV handoff
    # vs the same TWO replicas as a mixed pool, under concurrent LONG
    # prefills.  The decode cohort's inter-token tail is the number
    # disaggregation protects: in the mixed pool a long prefill dispatch
    # sits inside a serving replica's token loop (every active slot's
    # next token waits behind it); in the disaggregated fleet the decode
    # replica only ever imports finished pages (a small scatter).
    # Identical greedy outputs asserted across single/mixed/disagg —
    # the KV handoff's byte-identity proof rides every run.
    disagg_sum = mixed_sum = None
    disagg_itl = mixed_itl = disagg_ok = None
    if leg_on("disagg"):
        from deepspeed_tpu.inference import run_fleet
        from deepspeed_tpu.inference.scheduler import percentile
        long_bucket = int(os.environ.get("BENCH_SERVE_DISAGG_BUCKET",
                                         "192"))
        dtokens = max(max_tokens, long_bucket + 64)

        def build_disagg():
            model = GPT2.from_size(size, vocab_size=vocab,
                                   max_seq_len=dtokens)
            cfg = {"train_micro_batch_size_per_gpu": 1,
                   "inference": {"max_slots": slots,
                                 "max_tokens": dtokens,
                                 "prefill_bucket": long_bucket,
                                 "page_tokens": 32, "dtype": dtype,
                                 "fleet": {"disaggregate": True}}}
            return InferenceEngine(model, config=cfg, seed=0)

        rngd = np.random.default_rng(11)
        n_decode = int(os.environ.get("BENCH_SERVE_DISAGG_DECODE", "16"))
        n_long = int(os.environ.get("BENCH_SERVE_DISAGG_LONG", "6"))
        decode_rids = set(range(n_decode))
        dtrace = [Request(
            rid=i,
            prompt=rngd.integers(0, vocab, size=int(
                rngd.integers(2, 9))).astype(int).tolist(),
            max_new_tokens=int(rngd.integers(32, 49)))
            for i in range(n_decode)]
        # long prefills interleave INTO the decode traffic (every 3rd
        # position from the middle), almost pure prefill work
        for i in range(n_long):
            dtrace.insert(n_decode // 2 + 2 * i, Request(
                rid=1000 + i,
                prompt=rngd.integers(0, vocab, size=int(
                    long_bucket - 1 - rngd.integers(0, 8))).astype(
                        int).tolist(),
                max_new_tokens=3))

        def itl_cohort_ms(results, which):
            mean = [r.itl_mean_s * 1e3 for r in results
                    if r.rid in which and r.itl_mean_s is not None]
            gap = [max(r.itl_s) * 1e3 for r in results
                   if r.rid in which and r.itl_s]
            return (percentile(mean, 50), percentile(mean, 99),
                    percentile(gap, 99))

        # single-replica identity reference
        engd0 = build_disagg()
        engd0.generate([dtrace[0].prompt], max_new_tokens=2)
        engd0.reset()
        dref = {r.rid: r.tokens
                for r in run_serve(engd0, dtrace)["results"]}
        del engd0

        mixed_engines = [build_disagg(), build_disagg()]
        disagg_decode = build_disagg()
        disagg_prefill = build_disagg()
        # warm every program (incl. export/import) out of the timed
        # region with a tiny fleet pass, then reset the pools
        warm = [Request(rid=9000 + i, prompt=[1, 2, 3],
                        max_new_tokens=3) for i in range(2)]
        run_fleet(mixed_engines, warm)
        run_fleet([disagg_decode], warm,
                  prefill_engines=[disagg_prefill])
        for e in mixed_engines + [disagg_decode, disagg_prefill]:
            e.reset()

        mixed = run_fleet(mixed_engines, dtrace, poll_s=0.02)
        disagg = run_fleet([disagg_decode], dtrace,
                           prefill_engines=[disagg_prefill],
                           jsonl_path=os.path.join(root,
                                                   "disagg.jsonl"),
                           poll_s=0.02)
        for name, res in (("mixed", mixed), ("disaggregated", disagg)):
            got = {r.rid: r.tokens for r in res["results"]}
            if got != dref:
                bad = [k for k in dref if got.get(k) != dref[k]]
                raise RuntimeError(
                    f"BENCH_SERVE: requests {bad[:4]} generated "
                    f"differently under the {name} fleet — the KV "
                    f"handoff byte-identity contract is broken")
        if disagg["summary"]["handoffs"] < n_decode:
            raise RuntimeError(
                "BENCH_SERVE: disaggregation leg recorded "
                f"{disagg['summary']['handoffs']} handoffs — the "
                f"prefill→decode path did not engage")
        mixed_itl = itl_cohort_ms(mixed["results"], decode_rids)
        disagg_itl = itl_cohort_ms(disagg["results"], decode_rids)
        mixed_sum = dict(mixed["summary"],
                         decode_cohort_itl_mean_p50_ms=mixed_itl[0],
                         decode_cohort_itl_mean_p99_ms=mixed_itl[1],
                         decode_cohort_itl_gap_p99_ms=mixed_itl[2])
        disagg_sum = dict(disagg["summary"],
                          decode_cohort_itl_mean_p50_ms=disagg_itl[0],
                          decode_cohort_itl_mean_p99_ms=disagg_itl[1],
                          decode_cohort_itl_gap_p99_ms=disagg_itl[2],
                          long_prefills=n_long,
                          prefill_bucket=long_bucket)
        disagg_ok = (disagg_itl[1] is not None
                     and mixed_itl[1] is not None
                     and disagg_itl[1] <= mixed_itl[1])
        if not disagg_ok:
            print(f"BENCH_SERVE: WARNING — disaggregated decode-pool "
                  f"p99 ITL {disagg_itl[1]} did not beat the mixed "
                  f"pool's {mixed_itl[1]} under long prefills "
                  f"(virtual-CPU contention noise; rerun or use a "
                  f"chip)", file=sys.stderr)

    beats = (cont_sum["tokens_per_sec"] is not None
             and static_sum["tokens_per_sec"] is not None
             and cont_sum["tokens_per_sec"] >= static_sum["tokens_per_sec"]
             and (cont_sum["ttft_p99_ms"] or 0)
             <= (static_sum["ttft_p99_ms"] or 0))
    if not beats:
        print("BENCH_SERVE: WARNING — continuous batching did not beat "
              "static batching on this rig (wall-clock contention noise "
              "on virtual-CPU hosts; rerun or use a chip)",
              file=sys.stderr)

    if not jsonl_dir:
        shutil.rmtree(root, ignore_errors=True)
    row = {"metric": "serve_tokens_per_sec_per_chip",
           "value": cont_sum["tokens_per_sec_per_chip"],
           "unit": "tokens/s/chip (continuous batching, greedy)",
           "platform": jax.default_backend(),
           "device_kind": jax.devices()[0].device_kind,
           "n_chips": n_chips, "n_params": n_params,
           "model": size, "dtype": dtype, "slots": slots,
           "requests": n_req, "max_tokens": max_tokens,
           "prefill_bucket": bucket,
           "continuous": cont_sum, "static": static_sum,
           "continuous_beats_static": bool(beats)}
    if int8 is not None:
        row["int8"] = int8
    if fused_sum is not None:
        row["fused_decode"] = fused_sum
    if obs_ok is not None:
        row.update({"observability": obs_sum,
                    "observability_baseline": obs_base,
                    "observability_ratio": obs_ratio,
                    "observability_overhead_ok": bool(obs_ok)})
    if pfx_sum is not None:
        row.update({"shared_prefix": pfx_sum,
                    "shared_prefix_baseline": pfx_base["summary"],
                    "prefix_hit_rate": pfx_sum["prefix_hit_rate"],
                    "prefill_tokens_saved":
                        pfx_sum["prefill_tokens_saved"],
                    "prefix_reuse_beats_baseline": bool(reuse_beats)})
    if spec_sum is not None:
        row.update({"speculative": spec_sum,
                    "spec_accept_rate": spec_sum["spec_accept_rate"],
                    "draft_params": spec_sum["draft_params"],
                    "speculative_beats_target_only": bool(spec_beats)})
    if router_sum is not None:
        row.update({"router": router_sum,
                    "router_single_baseline": router_single,
                    "router_scaling": router_scaling,
                    "router_scaling_ok": bool(router_ok),
                    "router_eviction": evict_sum})
    if disagg_sum is not None:
        row.update({"disagg": disagg_sum,
                    "disagg_mixed_baseline": mixed_sum,
                    "disagg_decode_itl_p99_ok": bool(disagg_ok)})
    row["note"] = (
        "identical greedy outputs asserted across schedulers "
        "AND across D=1 vs D-fused decode; static decodes "
        "every batch until its last member finishes, "
        "continuous admits into freed slots each iteration — "
        "the delta is pure scheduling.  fused_decode runs "
        "the continuous scheduler with "
        "decode_iters_per_dispatch=D (one dispatch + one "
        "token read per D iterations) — compare its "
        "itl_MEAN_ms and tokens_per_sec against the "
        "continuous row; the itl p50 honestly collapses "
        "toward 0 at D>1 because tokens arrive in bursts "
        "of D (latency_summary docstring).  shared_prefix "
        "runs a multi-tenant trace (every request shares a "
        "system prompt) with prefix reuse ON vs the "
        "no-reuse baseline — identical outputs asserted, "
        "prefill_tokens_saved prompt tokens served from "
        "shared pages.  speculative fuses J drafts + "
        "verify into one dispatch on the continuous "
        "trace — token-identity vs the continuous row "
        "asserted; the default draft is the target's "
        "LEADING LAYERS with shared embeddings (draft_kind "
        "names the depth) — a distillation stand-in whose "
        "spec_accept_rate is honestly measured, not "
        "assumed; BENCH_SERVE_DRAFT_LAYERS picks the "
        "depth (= target depth reproduces the "
        "identical-twin accept≈1 ceiling).  observability "
        "re-runs the continuous trace with the replica "
        "observability stack live (request events, serve "
        "watchdog, detectors) — identical outputs asserted, "
        "observability_ratio = its tokens/s over the "
        "baseline's (documented bound: >= 0.97).  router runs the "
        "SAME trace through a 2-replica fleet behind the "
        "least-loaded router (one driver thread per replica — the "
        "in-process stand-in for per-chip replicas): "
        "router_scaling = fleet tokens/s over the adjacent "
        "single-replica run of the IDENTICAL replica config "
        "(target >= 1.8x for 2 replicas, best-of-N pairs); both "
        "sides serve D-fused with a widened slot count (recorded "
        "in the router row) because on a CPU rig every replica "
        "thread shares one interpreter and at D=1 GIL-serialized "
        "scheduler bookkeeping caps thread overlap near 1.6x — "
        "real chips don't share an interpreter.  Outputs identical "
        "incl. THROUGH the router_eviction "
        "sub-leg (chaos-wedged replica → watchdog → 503 → evict + "
        "resubmit with original timestamps).  disagg splits the "
        "same two replicas into a prefill pool + a decode pool "
        "with chunk-container KV handoff and drives decode "
        "traffic under concurrent LONG prefills — "
        "decode_cohort_itl_mean_p99_ms vs the mixed-pool "
        "baseline's is the protected number "
        "(disagg_decode_itl_p99_ok), byte-identical outputs "
        "asserted against a single replica on every run")
    _emit(row)
    return 0


def run_dispatch_bench():
    """Dispatch-path microbench (BENCH_DISPATCH=1) — the measurement side
    of the dispatch-cost pass (analysis/dispatchplan.py), modeled on
    SNIPPETS [3]'s launch/fence/transfer microbenchmarks: empty-program
    launch overhead (base + per-argument-leaf), per-step fence cost (the
    host's device round trip), and host→device transfer latency +
    bandwidth.  Emits measured columns NEXT TO the active BackendProfile's
    predicted constants so each rig calibrates the profile — the ruler
    ROADMAP item 4's multi-step driver will be judged against.

    Knobs: BENCH_DISPATCH_REPEATS (median-of, default 5),
    BENCH_DISPATCH_CALLS (launches per leg, default 200)."""
    import statistics

    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.analysis import profiles as prof_mod

    repeats = int(os.environ.get("BENCH_DISPATCH_REPEATS", "5"))
    calls = int(os.environ.get("BENCH_DISPATCH_CALLS", "200"))
    prof = prof_mod.default_profile()

    def med(fn):
        return statistics.median(fn() for _ in range(repeats))

    # ---- empty-program launch: dispatch-only time of a trivial jitted
    # program (async queuing returns before execution), then the same
    # with a 64-leaf argument tree to split out per-leaf marshalling
    x = jnp.zeros((8,), jnp.float32)
    f1 = jax.jit(lambda v: v + 1.0)
    f1(x).block_until_ready()

    def leg_dispatch():
        t0 = time.perf_counter()
        y = None
        for _ in range(calls):
            y = f1(x)
        t1 = time.perf_counter()
        y.block_until_ready()
        return (t1 - t0) / calls * 1e6

    dispatch_us = med(leg_dispatch)

    NLEAF = 64
    tree = {f"l{i}": jnp.zeros((8,), jnp.float32) for i in range(NLEAF)}
    ftree = jax.jit(lambda t: jax.tree_util.tree_map(lambda v: v + 1.0, t))
    jax.block_until_ready(ftree(tree))

    def leg_tree():
        t0 = time.perf_counter()
        y = None
        for _ in range(calls):
            y = ftree(tree)
        t1 = time.perf_counter()
        jax.block_until_ready(y)
        return (t1 - t0) / calls * 1e6

    tree_us = med(leg_tree)
    leaf_us = max(0.0, (tree_us - dispatch_us) / NLEAF)

    # ---- per-step fence cost: dispatch + block on the result (one
    # device round trip) minus the dispatch-only time
    def leg_fence():
        t0 = time.perf_counter()
        for _ in range(calls):
            f1(x).block_until_ready()
        t1 = time.perf_counter()
        return (t1 - t0) / calls * 1e6

    fence_us = max(0.0, med(leg_fence) - dispatch_us)

    # ---- host→device transfer: tiny buffer = latency, big buffer =
    # bandwidth (the batch-feeding cost class)
    small = np.zeros((256,), np.float32)
    big = np.zeros((16 << 20,), np.float32)        # 64 MiB
    jax.device_put(big).block_until_ready()

    def leg_small():
        t0 = time.perf_counter()
        for _ in range(calls):
            jax.device_put(small).block_until_ready()
        return (time.perf_counter() - t0) / calls * 1e6

    def leg_big():
        n = max(1, calls // 50)
        t0 = time.perf_counter()
        for _ in range(n):
            jax.device_put(big).block_until_ready()
        return (time.perf_counter() - t0) / n

    h2d_latency_us = med(leg_small)
    big_s = med(leg_big)
    h2d_gibps = big.nbytes / big_s / (1 << 30)

    # calibration drift gate: the dispatch-cost pass prices host time
    # with the profile's predicted constants — a >4× measured/predicted
    # ratio means the profile is pricing a DIFFERENT rig (the state the
    # cpu-8 recalibration fixed: 60 µs predicted vs 3.7 µs measured)
    drift = []
    if prof is not None:
        for name, measured, predicted in (
                ("dispatch_us", dispatch_us, prof.dispatch_us),
                ("dispatch_leaf_us", leaf_us, prof.dispatch_leaf_us),
                ("fence_us", fence_us, prof.fence_us),
                ("h2d_gibps", h2d_gibps, prof.h2d_gibps)):
            if measured > 0 and predicted > 0:
                ratio = max(measured / predicted, predicted / measured)
                if ratio > 4.0:
                    drift.append(f"{name}: measured {measured:.3g} vs "
                                 f"predicted {predicted:.3g} ({ratio:.1f}×)")
        if drift:
            print("BENCH_DISPATCH: WARNING — profile "
                  f"'{prof.name}' dispatch constants drift >4× from this "
                  "rig's measurements; recalibrate analysis/profiles.py: "
                  + "; ".join(drift), file=sys.stderr)

    _emit({
        "metric": "dispatch_microbench",
        "unit": "us (median of repeats; predicted = BackendProfile "
                "constants)",
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "hardware_true": jax.default_backend() == "tpu",
        "calls_per_leg": calls, "repeats": repeats,
        "profile": prof.name if prof else None,
        "dispatch_us_measured": round(dispatch_us, 3),
        "dispatch_us_predicted": prof.dispatch_us if prof else None,
        "dispatch_leaf_us_measured": round(leaf_us, 4),
        "dispatch_leaf_us_predicted": (prof.dispatch_leaf_us if prof
                                       else None),
        "fence_us_measured": round(fence_us, 3),
        "fence_us_predicted": prof.fence_us if prof else None,
        "h2d_latency_us_measured": round(h2d_latency_us, 3),
        "h2d_gibps_measured": round(h2d_gibps, 3),
        "h2d_gibps_predicted": prof.h2d_gibps if prof else None,
        "callback_us_predicted": prof.callback_us if prof else None,
        "drift_over_4x": drift,
        "note": ("the dispatch-cost pass prices the static host timeline "
                 "with the predicted columns; measured columns are this "
                 "rig's truth — the leg warns (drift_over_4x) when a "
                 "constant drifts past 4× so the profile gets "
                 "recalibrated, not quietly wrong. Re-measure: "
                 "BENCH_DISPATCH=1 "
                 "BENCH_OUT=bench_dispatch.json python bench.py")})
    return 0


def run_multistep_bench():
    """Multi-step driver leg (BENCH_MULTISTEP=1) — the on-device K-fused
    dispatch vs the per-step ``train_batch`` loop on the SAME model and
    batches: samples/s and per-step wall time at K ∈ {1, 2, 8}, plus a
    per-step fixed-cost column from the 1/K amortization model
    ``t(K) = t_compute + fixed/K`` fitted over the measured K points
    (fit residual reported — a bad fit means the model, not the data,
    is wrong).  One JSON line → bench_multistep.json.

    Env knobs: BENCH_MULTISTEP_KS ("1,2,8"), BENCH_MULTISTEP_STEPS (48,
    must be divisible by every K), BENCH_MULTISTEP_REPEAT (best-of, 3),
    BENCH_HIDDEN (64).  Chip re-measurement: BENCH_MULTISTEP=1
    BENCH_OUT=bench_multistep.json python bench.py (WALLCLOCK §7)."""
    import jax

    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "tests"))
    from simple_model import SimpleModel

    import deepspeed_tpu as dstpu

    hidden = int(os.environ.get("BENCH_HIDDEN", "64"))
    # sorted ascending: the speedup ratio and the 1/K fit both assume
    # ks[0] is the smallest and ks[-1] the largest
    ks = sorted({int(x) for x in os.environ.get(
        "BENCH_MULTISTEP_KS", "1,2,8").split(",")})
    steps = int(os.environ.get("BENCH_MULTISTEP_STEPS", "48"))
    repeat = int(os.environ.get("BENCH_MULTISTEP_REPEAT", "3"))
    for k in ks:
        if steps % k:
            raise SystemExit(
                f"BENCH_MULTISTEP_STEPS={steps} must be divisible by "
                f"every K in {ks}")
    batch_n = 16
    cfg = {"train_batch_size": batch_n,
           "gradient_accumulation_steps": 1,
           "steps_per_print": 10 ** 9,
           "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
           "bf16": {"enabled": True}}

    def make_batch(i):
        rng = np.random.default_rng(7000 + i)
        return (rng.normal(size=(batch_n, hidden)).astype(np.float32),
                rng.integers(0, hidden, size=(batch_n,)).astype(np.int32))

    batches = [make_batch(i) for i in range(steps)]
    rows = {}
    for k in ks:
        engine, _, _, _ = dstpu.initialize(
            model=SimpleModel(hidden_dim=hidden), config=dict(cfg))
        run_one = (
            (lambda s: engine.train_batch(batches[s])) if k == 1 else
            (lambda s: engine.train_many(batches[s:s + k])))
        # warm the executable out of the timed region
        run_one(0)
        best = None
        for _ in range(repeat):
            t0 = time.perf_counter()
            s = 0
            out = None
            while s < steps:
                out = run_one(s)
                s += k
            jax.block_until_ready(out)
            elapsed = time.perf_counter() - t0
            if best is None or elapsed < best:
                best = elapsed
        rows[k] = {
            "step_ms": round(best / steps * 1e3, 4),
            "samples_per_sec": round(steps * batch_n / best, 2),
            "dispatches": steps // k,
        }

    # fixed-cost fit: t(K) = t_compute + fixed/K  (least squares over
    # the measured K points; fixed = the per-step host boundary cost the
    # fusion amortizes).  Report the residual so a poorly-fitting rig is
    # visible, and the raw step_ms rows stay the ground truth.  A
    # single-K run cannot determine the 2-parameter model — the fit
    # columns go null instead of emitting a fabricated perfect fit.
    if len(ks) >= 2:
        xs = np.array([1.0 / k for k in ks])
        ys = np.array([rows[k]["step_ms"] for k in ks])
        A = np.stack([np.ones_like(xs), xs], axis=1)
        (t_compute, fixed), res, _, _ = np.linalg.lstsq(A, ys, rcond=None)
        fixed = max(0.0, float(fixed))
        t_compute = float(t_compute)
        residual = (float(np.sqrt(res[0] / len(ks))) if len(res) else 0.0)
        for k in ks:
            rows[k]["fixed_cost_ms_per_step"] = round(fixed / k, 4)
    else:
        fixed = t_compute = residual = None
    speedup = rows[ks[0]]["step_ms"] / rows[ks[-1]]["step_ms"]
    _emit({
        "metric": "multistep_driver",
        "unit": "ms/step (best-of-%d, %d optimizer steps, Adam bf16 "
                "hidden=%d)" % (repeat, steps, hidden),
        "platform": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "hardware_true": jax.default_backend() == "tpu",
        "ks": ks,
        "rows": {str(k): rows[k] for k in ks},
        "fixed_cost_ms_k1": (round(fixed, 4) if fixed is not None
                             else None),
        "compute_ms_fitted": (round(t_compute, 4)
                              if t_compute is not None else None),
        "fit_residual_ms": (round(residual, 4) if residual is not None
                            else None),
        "stepms_kmin_over_kmax": round(speedup, 3),
        "note": ("t(K) = compute + fixed/K fitted over the measured Ks; "
                 "rows carry the raw per-step wall time — the "
                 "amortization claim rests on step_ms falling with K, "
                 "the fit only prices it.  K-fused is bitwise with "
                 "serial (tests/test_multistep.py).  Re-measure on "
                 "chip: BENCH_MULTISTEP=1 BENCH_OUT=bench_multistep.json "
                 "python bench.py"),
    })
    return 0


def main():
    # artifact diff mode needs no backend at all — handle it before the
    # device watchdog so it runs anywhere (CI gates, laptops, artifact
    # review): bench.py --diff old.json new.json [--threshold 0.1]
    # [--strict]
    if "--diff" in sys.argv:
        argv = sys.argv[1:]
        argv.remove("--diff")
        strict = "--strict" in argv
        if strict:
            argv.remove("--strict")
        threshold = 0.10
        usage = ("usage: bench.py --diff old.json new.json "
                 "[--threshold 0.1] [--strict]")
        if "--threshold" in argv:
            i = argv.index("--threshold")
            try:
                threshold = float(argv[i + 1])
            except (IndexError, ValueError):
                raise SystemExit(usage)
            del argv[i:i + 2]
        if len(argv) != 2:
            raise SystemExit(usage)
        return run_bench_diff(argv[0], argv[1], threshold=threshold,
                              strict=strict)

    # A wedged device tunnel makes the first jax.devices() hang FOREVER
    # (observed failure mode: the axon relay listener disappears and every
    # client blocks in make_c_api_client).  Fail crisply instead: a
    # watchdog emits a diagnosable JSON line and exits nonzero when the
    # backend doesn't come up within BENCH_DEVICE_TIMEOUT seconds.
    import threading

    backend_up = threading.Event()
    try:
        budget = float(os.environ.get("BENCH_DEVICE_TIMEOUT", "600"))
    except ValueError:
        raise SystemExit(
            f"BENCH_DEVICE_TIMEOUT={os.environ['BENCH_DEVICE_TIMEOUT']!r} "
            "is not a number of seconds (<= 0 disables the watchdog)")

    def watchdog():
        if not backend_up.wait(timeout=budget):
            # stdout only — NEVER through _emit/BENCH_OUT, which would
            # overwrite a previously committed artifact with the error
            print(json.dumps(
                {"metric": "bench_error",
                 "error": f"jax backend init exceeded {budget:.0f}s "
                          "(device tunnel unreachable/wedged?)"}))
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(3)

    if budget > 0:
        threading.Thread(target=watchdog, daemon=True).start()

    import jax

    jax.devices()
    backend_up.set()

    if os.environ.get("BENCH_PP_SWEEP", "0") == "1":
        return run_pipeline_sweep(
            steps=int(os.environ.get("BENCH_STEPS", "4")))
    if os.environ.get("BENCH_CKPT", "0") == "1":
        return run_ckpt_bench()
    if os.environ.get("BENCH_RESUME", "0") == "1":
        return run_resume_bench()
    if os.environ.get("BENCH_SERVE", "0") == "1":
        return _bench_serve()
    if os.environ.get("BENCH_MFU_BREAKDOWN", "0") == "1":
        return run_mfu_breakdown()
    if os.environ.get("BENCH_OPT", "0") == "1":
        return run_opt_bench()
    if os.environ.get("BENCH_HEAD", "0") == "1":
        return run_head_bench()
    if os.environ.get("BENCH_OVERLAP", "0") == "1":
        return run_overlap_bench()
    if os.environ.get("BENCH_OBS", "0") == "1":
        return run_obs_bench()
    if os.environ.get("BENCH_DISPATCH", "0") == "1":
        return run_dispatch_bench()
    if os.environ.get("BENCH_MULTISTEP", "0") == "1":
        return run_multistep_bench()
    if os.environ.get("BENCH_DATA", "0") == "1":
        return run_data_bench()
    if os.environ.get("BENCH_ATTN_SWEEP", "0") == "1":
        return run_attention_sweep(
            steps=int(os.environ.get("BENCH_STEPS", "10")))

    on_tpu = jax.devices()[0].platform == "tpu"
    seq = int(os.environ.get("BENCH_SEQ", "128"))
    size = os.environ.get("BENCH_SIZE", "large" if on_tpu else "tiny")
    # r4 sweep (BENCH_SWEEP=1 + manual refinement, bench_headline.json):
    # micro-batch 24 x gas 48 beats the old 96 x 16 by 10% at seq128 —
    # 449.05 vs 409.5 samples/s/chip with selective remat.  The smaller
    # live micro-batch keeps the fused fwd+bwd working set closer to
    # VMEM and the longer accumulation scan amortises the LAMB step;
    # global batch stays in the published LAMB recipe range
    # (bert-pretraining.md 16K-64K: 24 x 48 x 32 chips = 36.9K).
    # remat=False fails to compile at any batch (score tensors exceed
    # HBM without the replay); full remat peaks lower end-to-end.
    # seq512 defaults (r5 sweep): micro-batch 6 x gas 48 with the streaming
    # kernel (auto at >= 512 non-causal now) = 84.8 samples/s/chip; larger
    # micro-batches spill (b=8 collapsed to 43.5).  The recipe-faithful
    # 256-samples/chip/step config (b=8 x gas=32, bert-pretraining.md
    # phase 2) measures within 1% of the optimum — WALLCLOCK.md uses it.
    seq512 = seq >= 512
    batch_per_chip = int(os.environ.get(
        "BENCH_BATCH", ("6" if seq512 else "24") if on_tpu else "8"))
    steps = int(os.environ.get("BENCH_STEPS", "8" if on_tpu else "4"))
    gas = int(os.environ.get("BENCH_GAS", "48" if on_tpu else "1"))
    remat_env = os.environ.get("BENCH_REMAT", "selective")
    remat = {"0": False, "1": True, "false": False, "true": True}.get(
        remat_env.lower(), remat_env)   # "selective"/"dots"/"full" pass

    if os.environ.get("BENCH_SWEEP", "0") == "1":
        best = None
        for r in (False, "selective", "full"):
            for b in (batch_per_chip // 2, batch_per_chip, batch_per_chip * 2):
                try:
                    res = run_config(size, seq, b, steps, r, gas=gas)
                except Exception as e:  # OOM etc: report and move on
                    print(f"sweep remat={r} batch={b}: FAILED {e}",
                          file=sys.stderr)
                    continue
                print(f"sweep remat={r} batch={b}: "
                      f"{res['per_chip']:.1f} samples/s/chip "
                      f"mfu={res['mfu']:.3f}", file=sys.stderr)
                if best is None or res["per_chip"] > best[0]["per_chip"]:
                    best = (res, r, b)
        if best is None:
            raise RuntimeError(
                "BENCH_SWEEP: every configuration failed (see stderr)")
        res, remat, batch_per_chip = best
    else:
        res = run_config(size, seq, batch_per_chip, steps, remat, gas=gas)

    _emit({
        "metric": "bert_%s_seq%d_pretrain_samples_per_sec_per_chip"
                  % (size, seq),
        "value": round(res["per_chip"], 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(res["per_chip"] / 200.0, 3),
        "mfu": round(res["mfu"], 4),
        "achieved_tflops": round(res["achieved_tflops"], 1),
        "predicted_peak_hbm_gb": res.get("predicted_peak_hbm_gb"),
        "predicted_boundary_ms": res.get("predicted_boundary_ms"),
        "predicted_profile": res.get("predicted_profile"),
        "measured_boundary_ms": res.get("measured_boundary_ms"),
        "predicted_drift": res.get("predicted_drift"),
        "batch_per_chip": batch_per_chip,
        "gas": gas,
        "remat": remat,
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
