"""Checkpoint round-trip tests.

Port of /root/reference/tests/unit/test_checkpointing.py: train N steps →
save → fresh engine → load → deep-compare compute-dtype weights, fp32
masters, inner optimizer state tensors, loss-scale + scheduler state; then
continue training both and compare losses (resume parity).  Run with and
without optimizer-state load, with and without ZeRO, and ZeRO across a
DIFFERENT dp world size (the re-partition path).
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import make_mesh
from simple_model import SimpleModel, random_dataset

HIDDEN = 16


def base_config(**over):
    cfg = {
        "train_batch_size": 32,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
        "fp16": {"enabled": True, "initial_scale_power": 8},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_min_lr": 0.0,
                                 "warmup_max_lr": 0.01,
                                 "warmup_num_steps": 20}},
    }
    cfg.update(over)
    return cfg


def make_engine(config, seed=0, mesh=None):
    model = SimpleModel(HIDDEN)
    engine, optim, _, _ = deepspeed_tpu.initialize(
        config=config, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(seed)),
        mesh=mesh)
    return engine, optim


def train(engine, steps, data_seed=0):
    ds = random_dataset(64, HIDDEN, seed=data_seed)
    dl = engine.deepspeed_io(ds)
    it = iter(dl)
    losses = []
    for _ in range(steps):
        try:
            batch = next(it)
        except StopIteration:
            it = iter(dl)
            batch = next(it)
        loss = engine(*batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def tree_equal(a, b, rtol=0.0, atol=0.0):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol)


@pytest.mark.parametrize("zero", [False, True])
def test_checkpoint_roundtrip_bit_exact(tmpdir, zero):
    cfg = base_config(zero_optimization=zero)
    e1, _ = make_engine(cfg)
    train(e1, 12)
    path = e1.save_checkpoint(str(tmpdir), client_state={"epoch": 3})
    assert path

    e2, _ = make_engine(cfg, seed=99)   # different init — must be overwritten
    load_path, client = e2.load_checkpoint(str(tmpdir))
    assert load_path is not None
    assert client["epoch"] == 3
    assert e2.global_steps == e1.global_steps
    assert e2.skipped_steps == e1.skipped_steps

    tree_equal(e1.params, e2.params)
    if zero:
        tree_equal(e1.master_flat, e2.master_flat)
    else:
        tree_equal(e1.master, e2.master)
    tree_equal(e1.opt_state, e2.opt_state)
    tree_equal(e1.loss_scale_state, e2.loss_scale_state)
    assert (e1.lr_scheduler.state_dict() == e2.lr_scheduler.state_dict())

    # resume parity: both engines continue identically
    l1 = train(e1, 5, data_seed=7)
    l2 = train(e2, 5, data_seed=7)
    np.testing.assert_allclose(l1, l2, rtol=0, atol=0)


def test_checkpoint_no_optimizer_states(tmpdir):
    cfg = base_config()
    e1, _ = make_engine(cfg)
    train(e1, 8)
    e1.save_checkpoint(str(tmpdir))

    e2, _ = make_engine(cfg, seed=99)
    _, _ = e2.load_checkpoint(str(tmpdir), load_optimizer_states=False)
    tree_equal(e1.params, e2.params)
    # fresh optimizer: moments zero, step zero
    assert int(e2.opt_state.step) == 0
    for leaf in jax.tree_util.tree_leaves(e2.opt_state.m):
        assert float(np.abs(np.asarray(leaf)).max()) == 0.0
    # masters re-derived from fp16 weights
    tree_equal(e2.master,
               jax.tree_util.tree_map(lambda p: np.asarray(p, np.float32),
                                      e1.params), rtol=0, atol=0)


def test_zero_load_without_optimizer_states(tmpdir):
    """ZeRO weights-only fine-tune: masters MUST be re-derived from the
    loaded weights, or step() silently reverts params to the stale
    init-time master (the silent-corruption path)."""
    cfg = base_config(zero_optimization=True)
    e1, _ = make_engine(cfg)
    train(e1, 8)
    e1.save_checkpoint(str(tmpdir))

    e2, _ = make_engine(cfg, seed=99)
    e2.load_checkpoint(str(tmpdir), load_optimizer_states=False)
    tree_equal(e1.params, e2.params)
    assert int(e2.opt_state.step) == 0
    # flat master rebuilt from loaded (fp16) weights, not the seed-99 init:
    # matches e1's fp32 master to fp16 round-trip precision
    n = e1.flat_meta.total
    np.testing.assert_allclose(np.asarray(e2.master_flat)[:n],
                               np.asarray(e1.master_flat)[:n],
                               rtol=1e-3, atol=1e-4)
    # one more step must move params, continuing from the checkpoint
    before = np.asarray(jax.tree_util.tree_leaves(e2.params)[0]).copy()
    train(e2, 1)
    after = np.asarray(jax.tree_util.tree_leaves(e2.params)[0])
    assert not np.array_equal(before, after)


def test_zero_checkpoint_across_dp_sizes(tmpdir):
    """Save under dp=8, restore under dp=4 (different partition layout) —
    the 'different restore topology' case (SURVEY.md §7.3)."""
    cfg = base_config(zero_optimization=True)
    e1, _ = make_engine(cfg)
    train(e1, 10)
    e1.save_checkpoint(str(tmpdir))

    mesh4 = make_mesh(model_parallel_size=1, devices=jax.devices()[:4])
    cfg4 = base_config(zero_optimization=True)
    e2, _ = make_engine(cfg4, seed=99, mesh=mesh4)
    load_path, _ = e2.load_checkpoint(str(tmpdir))
    assert load_path is not None

    # same unpadded master content
    n = e1.flat_meta.total
    np.testing.assert_array_equal(
        np.asarray(e1.master_flat)[:n], np.asarray(e2.master_flat)[:n])
    tree_equal(e1.params, e2.params)

    l1 = train(e1, 5, data_seed=11)
    l2 = train(e2, 5, data_seed=11)
    # dp=8 vs dp=4 sum gradients in different orders: bit-exact state, but
    # continued losses only match to reduction-order fp noise
    np.testing.assert_allclose(l1, l2, rtol=1e-4)


def test_zero_checkpoint_into_nonzero_engine_errors(tmpdir):
    """Loading a ZeRO-saved checkpoint into a non-ZeRO engine with
    load_optimizer_states=True must fail loudly, not silently reset the
    optimizer."""
    e1, _ = make_engine(base_config(zero_optimization=True))
    train(e1, 4)
    e1.save_checkpoint(str(tmpdir))

    e2, _ = make_engine(base_config(zero_optimization=False), seed=9)
    with pytest.raises(ValueError, match="zero_optimization"):
        e2.load_checkpoint(str(tmpdir))
    # weights-only load is the sanctioned escape hatch
    path, _ = e2.load_checkpoint(str(tmpdir), load_optimizer_states=False)
    assert path is not None
    tree_equal(e1.params, e2.params)


def test_load_missing_returns_none(tmpdir):
    e, _ = make_engine(base_config())
    path, client = e.load_checkpoint(str(tmpdir))
    assert path is None and client is None


def test_latest_tag_and_explicit_tag(tmpdir):
    e, _ = make_engine(base_config())
    train(e, 4)
    e.save_checkpoint(str(tmpdir), tag="step4")
    train(e, 4)
    e.save_checkpoint(str(tmpdir))   # default tag global_step8

    e2, _ = make_engine(base_config(), seed=5)
    path, _ = e2.load_checkpoint(str(tmpdir))           # latest
    assert path.endswith("global_step8")
    assert e2.global_steps == 8
    e3, _ = make_engine(base_config(), seed=5)
    path, _ = e3.load_checkpoint(str(tmpdir), tag="step4")
    assert e3.global_steps == 4


# --------------------------------------------- pretrain -> fine-tune transfer

def test_init_from_module_tree_transfers_backbone(tmpdir):
    """The BingBertSquad workflow: pretrain BERT, save, initialize the QA
    model's BACKBONE from the checkpoint (fresh task head stays), masters
    re-derived so the first step doesn't revert the transfer."""
    from deepspeed_tpu import checkpoint as ckpt_mod
    from deepspeed_tpu.models import (BertForPreTraining,
                                      BertForQuestionAnswering)

    kw = dict(vocab_size=64, max_seq_len=32, num_layers=2,
              hidden_size=32, num_heads=4)
    pre = BertForPreTraining.from_size("tiny", **kw)
    e1, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 4, "steps_per_print": 10 ** 6,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        model=pre, model_parameters=pre.init_params(jax.random.PRNGKey(0)),
        mesh=make_mesh(devices=jax.devices()[:2]))
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, size=(4, 32)).astype(np.int32)
    mlm = np.where(rng.random((4, 32)) < 0.15, ids, -1).astype(np.int32)
    for _ in range(2):
        e1.train_batch((ids, np.ones_like(ids), np.zeros_like(ids), mlm))
    e1.save_checkpoint(str(tmpdir), tag="pre")
    want = {jax.tree_util.keystr(k): np.asarray(v) for k, v in
            jax.tree_util.tree_leaves_with_path(e1.params)}

    module = ckpt_mod.load_module_tree(str(tmpdir), tag="pre")
    assert module is not None

    qa = BertForQuestionAnswering.from_size("tiny", **kw)
    e2, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 4, "steps_per_print": 10 ** 6,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        model=qa, model_parameters=qa.init_params(jax.random.PRNGKey(9)),
        mesh=make_mesh(devices=jax.devices()[:2]))
    fresh_qa_w = np.asarray(e2.params["qa_w"])
    loaded, skipped = ckpt_mod.init_from_module_tree(e2, module)
    assert any("wte" in k for k in loaded)
    assert any("blocks" in k for k in loaded)
    assert all("qa_" in k or "mlm_" in k or "pool" in k or "nsp" in k
               for k in skipped), skipped
    # backbone now equals the pretrained weights; the head kept its init
    for k, v in {jax.tree_util.keystr(kk): vv for kk, vv in
                 jax.tree_util.tree_leaves_with_path(e2.params)}.items():
        if k in want and k in loaded:
            np.testing.assert_array_equal(np.asarray(v), want[k])
    np.testing.assert_array_equal(np.asarray(e2.params["qa_w"]), fresh_qa_w)

    # masters were re-derived: a training step MOVES from the transferred
    # weights instead of reverting to the random init
    before = np.asarray(e2.params["wte"])
    starts = np.zeros((4,), np.int32)
    e2.train_batch((ids, np.ones_like(ids), np.zeros_like(ids),
                    starts, starts + 1))
    after = np.asarray(e2.params["wte"])
    assert not np.array_equal(after, before)
    assert np.abs(after - before).max() < 0.1   # moved FROM the transfer


def test_load_module_tree_mp_sharded_needs_specs(tmpdir):
    """mp>1 checkpoints reassemble through the saving model's specs; the
    helper refuses to guess."""
    from deepspeed_tpu import checkpoint as ckpt_mod
    from deepspeed_tpu.models import GPT2

    model = GPT2.from_size("tiny", vocab_size=64, max_seq_len=16,
                           num_layers=2, hidden_size=32, num_heads=4)
    e, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 4, "steps_per_print": 10 ** 6,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=make_mesh(model_parallel_size=2, devices=jax.devices()[:4]))
    toks = np.zeros((4, 16), np.int32)
    e.train_batch((toks, toks))
    e.save_checkpoint(str(tmpdir), tag="mp2")

    with pytest.raises(ValueError, match="partition_specs"):
        ckpt_mod.load_module_tree(str(tmpdir), tag="mp2")
    tree = ckpt_mod.load_module_tree(str(tmpdir), tag="mp2",
                                     specs=model.partition_specs(None))
    # reassembled to GLOBAL shapes
    got = {jax.tree_util.keystr(k): v for k, v in
           jax.tree_util.tree_leaves_with_path(tree)}
    want = {jax.tree_util.keystr(k): v.shape for k, v in
            jax.tree_util.tree_leaves_with_path(e.params)}
    for k, shape in want.items():
        assert tuple(np.shape(got[k])) == tuple(shape), k
