"""ZeRO stage 3: parameter partitioning / FSDP (beyond the reference's
v0.1.0, which ships stage 1 and teases the ZeRO roadmap in
docs/_posts/2020-03-17-zero-stage2.md).

Design under test (zero3.py + models/transformer.py zero3_enter):
params, fp32 masters and Adam moments persist per-leaf data-sharded; the
model gathers each layer's weights inside the block scan; the gather's
autodiff transpose reduce-scatters the grads; the update is elementwise on
local shards.  Pinned here: trajectory parity with stage 0/1, composition
with MP / SP / grad accumulation / fp16, checkpoint round trips (including
cross-stage and cross-topology restores), the memory envelope, and the
config guards.
"""

import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu import zero3
from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.models import GPT2, BertForPreTraining
from deepspeed_tpu.parallel.topology import make_mesh

pytestmark = pytest.mark.slow

VOCAB, SEQ = 64, 16


def tiny_gpt2():
    return GPT2.from_size("tiny", vocab_size=VOCAB, max_seq_len=SEQ,
                          num_layers=2, hidden_size=32, num_heads=4)


def lm_batch(batch, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, VOCAB, size=(batch, SEQ)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    labels[:, -1] = -1
    return toks, labels


def make_engine(stage, mp=1, sp=1, gas=1, fp16=False, seed=7, model=None,
                **cfg_over):
    prec = ({"fp16": {"enabled": True, "initial_scale_power": 8}}
            if fp16 else {"bf16": {"enabled": True}})
    cfg = {
        "train_batch_size": 8 * gas,
        "gradient_accumulation_steps": gas,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        **prec,
    }
    cfg.update(cfg_over)
    model = model or tiny_gpt2()
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(seed)),
        mesh=make_mesh(model_parallel_size=mp, context_parallel_size=sp))
    return engine


def run_steps(engine, n=3, seed=1, split=False):
    losses = []
    for i in range(n):
        toks, labels = lm_batch(8 * engine.gradient_accumulation_steps(),
                                seed=seed + i)
        if split:
            gas = engine.gradient_accumulation_steps()
            tm = toks.reshape(gas, -1, SEQ)
            lm = labels.reshape(gas, -1, SEQ)
            for g in range(gas):
                loss = engine(tm[g], lm[g])
                engine.backward(loss)
                engine.step()
            losses.append(float(loss))
        else:
            losses.append(float(engine.train_batch((toks, labels))))
    return losses


# ------------------------------------------------------------- choose_dims

def test_choose_dim_rules():
    sizes = {"data": 8, "model": 2}
    # largest divisible dim wins
    assert zero3.choose_dim((64, 128), P(None, None), sizes, 8) == 1
    # dims sharded by model divide before the dp check: local 128/2 = 64
    # ties with dim 0, and ties go to the LOWEST index
    assert zero3.choose_dim((64, 128), P(None, "model"), sizes, 8) == 0
    assert zero3.choose_dim((64, 256), P(None, "model"), sizes, 8) == 1
    # non-divisible dims are skipped
    assert zero3.choose_dim((13, 64), P(None, None), sizes, 8,
                            min_size=1) == 1
    # too small -> replicated
    assert zero3.choose_dim((4, 4), P(None, None), sizes, 8) == -1
    # nothing divisible -> replicated
    assert zero3.choose_dim((13, 17), P(None, None), sizes, 8,
                            min_size=1) == -1
    # min_dim pins the scan axis
    assert zero3.choose_dim((64, 32), P(None, None), sizes, 8,
                            min_dim=1) == 1
    # dp=1 -> nothing to partition
    assert zero3.choose_dim((64, 64), P(None, None), sizes, 1) == -1


def test_choose_dims_model_hook():
    model = tiny_gpt2()
    params = model.init_params(jax.random.PRNGKey(0))
    specs = model.partition_specs(params)
    dims = zero3.choose_dims(params, specs, {"data": 8, "model": 1}, 8,
                             min_dims=model.zero3_min_dims(params))
    # block leaves never partition their layer axis
    for leaf_dim in jax.tree_util.tree_leaves(dims["blocks"]):
        assert leaf_dim != 0
    # the big matmul weights must be partitioned
    assert dims["blocks"]["qkv_w"] >= 1
    assert dims["wte"] >= 0


def test_augment_specs_appends_data_axis():
    specs = {"w": P(None, "model"), "b": P()}
    dims = {"w": 1, "b": -1}
    out = zero3.augment_specs(specs, dims)
    assert out["w"] == P(None, ("model", "data"))
    assert out["b"] == P()


# ------------------------------------------------------ trajectory parity

def test_zero3_matches_stage0():
    l0 = run_steps(make_engine(0))
    l3 = run_steps(make_engine(3))
    np.testing.assert_allclose(l0, l3, rtol=5e-3, atol=5e-3)


def test_zero3_matches_stage1():
    l1 = run_steps(make_engine(1, fp16=True))
    l3 = run_steps(make_engine(3, fp16=True))
    np.testing.assert_allclose(l1, l3, rtol=5e-3, atol=5e-3)


def test_zero3_with_model_parallel():
    l0 = run_steps(make_engine(0, mp=2))
    l3 = run_steps(make_engine(3, mp=2))
    np.testing.assert_allclose(l0, l3, rtol=5e-3, atol=5e-3)


def test_zero3_with_context_parallel():
    l0 = run_steps(make_engine(0, sp=2))
    l3 = run_steps(make_engine(3, sp=2))
    np.testing.assert_allclose(l0, l3, rtol=5e-3, atol=5e-3)


def test_zero3_sp_grad_norm_not_deduped_over_seq():
    # grads are already identical across the sequence ring (the engine
    # psums + /sp them before the norm) and the stage-3 norm psums over
    # data + model/pipe ONLY — dividing replicated leaves by sp as well
    # would shrink the norm by sqrt(sp) and under-clip (review r4 finding)
    e0 = make_engine(0, sp=2, gradient_clipping=0.05)
    e3 = make_engine(3, sp=2, gradient_clipping=0.05)
    l0 = run_steps(e0, 2)
    l3 = run_steps(e3, 2)
    np.testing.assert_allclose(l0, l3, rtol=5e-3, atol=5e-3)
    np.testing.assert_allclose(float(e0._last_grad_norm),
                               float(e3._last_grad_norm), rtol=1e-2)


def test_zero3_grad_accumulation_split_vs_fused():
    ls = run_steps(make_engine(3, gas=2), split=True)
    lf = run_steps(make_engine(3, gas=2), split=False)
    # split slices micro-batches globally, fused scans per-shard rows —
    # same summed gradient, micro-order differs (engine.train_batch doc)
    np.testing.assert_allclose(ls, lf, rtol=3e-2, atol=3e-2)


def test_zero3_moe():
    from deepspeed_tpu.models import GPT2MoE

    def make(stage):
        model = GPT2MoE.from_size(
            "tiny", num_experts=4, capacity_factor=2.0, vocab_size=VOCAB,
            max_seq_len=SEQ, num_layers=2, hidden_size=32, num_heads=4)
        return make_engine(stage, mp=2, model=model)

    out = []
    for stage in (0, 3):
        eng = make(stage)
        out.append(run_steps(eng, 2))
    np.testing.assert_allclose(out[0], out[1], rtol=5e-3, atol=5e-3)


def test_zero3_bert():
    def make(stage):
        model = BertForPreTraining.from_size(
            "tiny", vocab_size=VOCAB, max_seq_len=SEQ, num_layers=2,
            hidden_size=32, num_heads=4)
        return make_engine(stage, model=model)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, VOCAB, (8, SEQ)).astype(np.int32)
    mask = np.ones((8, SEQ), np.float32)
    tt = np.zeros((8, SEQ), np.int32)
    labels = np.where(rng.random((8, SEQ)) < 0.15, ids, -1).astype(np.int32)

    out = []
    for stage in (0, 3):
        eng = make(stage)
        out.append([float(eng.train_batch((ids, mask, tt, labels)))
                    for _ in range(2)])
    np.testing.assert_allclose(out[0], out[1], rtol=5e-3, atol=5e-3)


# ------------------------------------------------------------- checkpoints

def test_zero3_checkpoint_resume_parity(tmp_path):
    eng = make_engine(3)
    run_steps(eng, 2)
    eng.save_checkpoint(str(tmp_path), tag="t")
    fresh = make_engine(3, seed=23)
    fresh.load_checkpoint(str(tmp_path), tag="t")
    np.testing.assert_allclose(run_steps(eng, 2, seed=9),
                               run_steps(fresh, 2, seed=9),
                               rtol=1e-4, atol=1e-4)


def test_zero3_checkpoint_cross_stage(tmp_path):
    eng = make_engine(3)
    run_steps(eng, 2)
    eng.save_checkpoint(str(tmp_path), tag="t")
    # stage-3 checkpoints restore into a ZeRO-off engine (optimizer state
    # is inline, per-leaf) ...
    eng0 = make_engine(0, seed=23)
    eng0.load_checkpoint(str(tmp_path), tag="t")
    np.testing.assert_allclose(run_steps(eng, 2, seed=9),
                               run_steps(eng0, 2, seed=9),
                               rtol=5e-3, atol=5e-3)
    # ... and stage-0 checkpoints restore into a stage-3 engine
    engA = make_engine(0, seed=3)
    run_steps(engA, 1)
    engA.save_checkpoint(str(tmp_path), tag="u")
    engB = make_engine(3, seed=29)
    engB.load_checkpoint(str(tmp_path), tag="u")
    np.testing.assert_allclose(run_steps(engA, 2, seed=9),
                               run_steps(engB, 2, seed=9),
                               rtol=5e-3, atol=5e-3)


def test_zero3_checkpoint_cross_topology(tmp_path):
    eng = make_engine(3)
    run_steps(eng, 2)
    eng.save_checkpoint(str(tmp_path), tag="t")
    other = make_engine(3, mp=2, seed=31)
    other.load_checkpoint(str(tmp_path), tag="t")
    np.testing.assert_allclose(run_steps(eng, 2, seed=9),
                               run_steps(other, 2, seed=9),
                               rtol=5e-3, atol=5e-3)


def test_zero3_stage12_checkpoint_rejected(tmp_path):
    eng = make_engine(1, fp16=True)
    run_steps(eng, 1)
    eng.save_checkpoint(str(tmp_path), tag="t")
    eng3 = make_engine(3, fp16=True, seed=23)
    with pytest.raises(ValueError, match="stage 1/2"):
        eng3.load_checkpoint(str(tmp_path), tag="t")
    # weights-only load still works
    path, _ = eng3.load_checkpoint(str(tmp_path), tag="t",
                                   load_optimizer_states=False)
    assert path is not None


# ------------------------------------------------------------ memory claim

def test_zero3_memory_envelope():
    dp = 8
    e0 = make_engine(0)
    e3 = make_engine(3)
    m0 = e0.memory_estimate()
    m3 = e3.memory_estimate()
    # persistent per-device state shrinks toward 1/dp (small replicated
    # leaves keep the ratio above the ideal)
    assert m3["total_persistent_bytes"] < m0["total_persistent_bytes"] / 4
    assert m3["zero_stage"] == 3

    # the estimate is exact: measure the live shard bytes of params +
    # masters + moments on device 0
    def live_bytes(tree):
        total = 0
        for leaf in jax.tree_util.tree_leaves(tree):
            sh = leaf.addressable_shards[0]
            total += int(np.prod(sh.data.shape)) * leaf.dtype.itemsize
        return total

    measured = (live_bytes(e3.params) + live_bytes(e3.master)
                + live_bytes(e3.opt_state.m) + live_bytes(e3.opt_state.v))
    est = m3["params_bytes"] + m3["optimizer_state_bytes"]
    assert measured == est

    # partitioned leaves really are 1/dp on device
    qkv = e3.master["blocks"]["qkv_w"]
    assert (qkv.addressable_shards[0].data.size * dp) == qkv.size


# ------------------------------------------------------------------ guards

def test_zero3_requires_model_support():
    class Opaque:
        def init_params(self, rng):
            return {"w": jnp.zeros((64, 64), jnp.float32)}

        def apply(self, params, x):
            return jnp.sum(params["w"]) * 0.0 + jnp.mean(x)

        __call__ = apply

    with pytest.raises(DeepSpeedConfigError, match="zero3_dims"):
        deepspeed_tpu.initialize(
            config={"train_batch_size": 8, "bf16": {"enabled": True},
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {"stage": 3}},
            model=Opaque())


def test_zero3_rejects_parameter_parallel_size():
    with pytest.raises(DeepSpeedConfigError, match="parameter_parallel"):
        make_engine(3, zero_optimization={"stage": 3,
                                          "parameter_parallel_size": 2})


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_zero3_with_pipeline(schedule):
    from deepspeed_tpu.models.pipeline_gpt2 import GPT2Pipelined

    def make(stage):
        model = GPT2Pipelined.from_size(
            "tiny", vocab_size=VOCAB, max_seq_len=SEQ, num_layers=2,
            hidden_size=32, num_heads=4, num_micro_batches=2,
            schedule=schedule)
        cfg = {"train_batch_size": 8, "bf16": {"enabled": True},
               "steps_per_print": 10 ** 6,
               "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
               "zero_optimization": {"stage": stage}}
        engine, _, _, _ = deepspeed_tpu.initialize(
            config=cfg, model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(7)),
            mesh=make_mesh(pipeline_parallel_size=2))
        return engine

    l0 = run_steps(make(0), 2)
    l3 = run_steps(make(3), 2)
    np.testing.assert_allclose(l0, l3, rtol=5e-3, atol=5e-3)
    # the stage-3 engine really partitioned the per-stage stacks
    e3 = make(3)
    qkv = e3.master["blocks"]["qkv_w"]
    assert qkv.addressable_shards[0].data.size * 8 == qkv.size  # pp*dp*...


def test_zero3_grad_norm_and_clipping_match_stage0():
    # the clip factor derives from the global grad norm — a wrong norm
    # (e.g. specs mis-zipped against grad leaves) silently diverges the
    # trajectory and misreports _last_grad_norm
    l0 = run_steps(make_engine(0, gradient_clipping=0.05))
    l3 = run_steps(make_engine(3, gradient_clipping=0.05))
    np.testing.assert_allclose(l0, l3, rtol=5e-3, atol=5e-3)
    e0 = make_engine(0, gradient_clipping=0.05)
    e3 = make_engine(3, gradient_clipping=0.05)
    run_steps(e0, 1)
    run_steps(e3, 1)
    np.testing.assert_allclose(float(e0._last_grad_norm),
                               float(e3._last_grad_norm),
                               rtol=1e-2)


def test_zero3_lion_matches_stage0():
    # stage 3's update is per-leaf elementwise on local shards, so Lion
    # (m-only state) is admitted there — the engine guard keeps the flat
    # stages 1-2 Adam-only (ADVICE r4).  Tolerance is looser than the
    # Adam parity tests: Lion's sign() is discontinuous, so bf16
    # summation-order noise between allreduce (stage 0) and the gather
    # transpose's psum_scatter (stage 3) can flip signs near zero.
    opt = {"optimizer": {"type": "Lion",
                         "params": {"lr": 3e-4, "weight_decay": 0.01}}}
    l0 = run_steps(make_engine(0, **opt))
    l3 = run_steps(make_engine(3, **opt))
    np.testing.assert_allclose(l0, l3, rtol=2e-2, atol=2e-2)


def test_zero3_lion_checkpoint_resume(tmp_path):
    # v=None state must round-trip through the stage-3 checkpoint path
    opt = {"optimizer": {"type": "Lion", "params": {"lr": 3e-4}}}
    e = make_engine(3, **opt)
    run_steps(e, 2)
    e.save_checkpoint(str(tmp_path), tag="lion")
    ref = run_steps(e, 2, seed=9)
    e2 = make_engine(3, **opt)
    e2.load_checkpoint(str(tmp_path), tag="lion")
    got = run_steps(e2, 2, seed=9)
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)


def test_zero3_shared_model_instance_safe():
    # one model object, two engines (stage 3 first): the stage-3 engine
    # must not poison the shared instance with zero3_dims
    model = tiny_gpt2()
    e3 = make_engine(3, model=model)
    e0 = make_engine(0, model=model)
    assert model.zero3_dims is None
    l3 = run_steps(e3)
    l0 = run_steps(e0)
    np.testing.assert_allclose(l0, l3, rtol=5e-3, atol=5e-3)


def test_zero3_fp16_dynamic_scale_runs():
    eng = make_engine(3, **{"fp16": {"enabled": True, "loss_scale": 0,
                                     "initial_scale_power": 4}})
    losses = run_steps(eng, 3)
    assert np.all(np.isfinite(losses))
    assert losses[-1] < losses[0]
