"""Tensor-parallel agreement on overflow flags and clipping norms.

The reference MAX-reduces the overflow flag and SUM-reduces grad norms over
the model-parallel group so every TP rank takes the same skip/clip decision
(/root/reference/deepspeed/pt/deepspeed_utils.py:62-75,100-158).  These tests
exercise the failure modes that agreement prevents:

* an inf appearing in ONE TP shard's slice of a model-sharded gradient must
  make ALL shards skip the update and take the same loss-scale transition
  (otherwise replicated parameters silently diverge across the model axis);
* gradient clipping under mp>1 must use the GLOBAL norm, giving the same
  trajectory as mp=1.
"""

import jax
import pytest
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import make_mesh

from tests.test_models import gpt2_config, lm_batch, tiny_gpt2

# composition tier: 30-85 s of shard_map compiles per test — runs in the
# full suite/CI, excluded from `-m fast` (VERDICT r2 weak #6)
pytestmark = pytest.mark.slow



def _make_engine(mp, **cfg_over):
    model = tiny_gpt2()
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=gpt2_config(mp, **cfg_over), model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(7)),
        mesh=make_mesh(model_parallel_size=mp))
    return engine


def _device_values(arr):
    """Per-device buffer values of a (nominally replicated) global array."""
    return [np.asarray(s.data) for s in arr.addressable_shards]


def test_tp_overflow_in_one_shard_skips_all_shards():
    """Inject inf into model-shard-1's slice of a TP-sharded gradient; every
    shard must skip and agree on cur_scale (reference test analog:
    tests/unit/test_dynamic_loss_scale.py inf injection, plus the MP
    agreement of deepspeed_utils.py:62-75)."""
    init_scale = 2.0 ** 8
    engine = _make_engine(2, fp16={"enabled": True, "initial_scale_power": 8})
    toks, labels = lm_batch(8)
    loss = engine(toks, labels)
    engine.backward(loss)

    # qkv_w is column-parallel: global [L, h, 3h], model shard 1 owns the
    # upper half of the last dim.  Poison one element of THAT slice only.
    leaf = engine._acc["blocks"]["qkv_w"]
    host = np.asarray(leaf).copy()
    host[..., -1] = np.inf
    engine._acc["blocks"]["qkv_w"] = jax.device_put(host, leaf.sharding)

    params_before = jax.tree_util.tree_map(np.asarray, engine.params)
    engine.step()

    assert engine.overflow
    assert engine.skipped_steps == 1
    # all devices agree on the halved scale
    for v in _device_values(engine.loss_scale_state.cur_scale):
        assert float(v) == init_scale / 2.0
    # the update was skipped everywhere: params identical to before on every
    # device buffer (a desync would leave shard 0 updated, shard 1 not)
    flat_before = jax.tree_util.tree_leaves(params_before)
    flat_after = jax.tree_util.tree_leaves(engine.params)
    for before, after in zip(flat_before, flat_after):
        np.testing.assert_array_equal(np.asarray(after), np.asarray(before))


def test_tp_replicated_state_identical_across_devices_after_overflow():
    """After an overflow step under mp=2, nominally replicated state must be
    bitwise identical on every device (catches the per-shard FSM desync)."""
    engine = _make_engine(2, fp16={"enabled": True, "initial_scale_power": 8})
    toks, labels = lm_batch(8)
    loss = engine(toks, labels)
    engine.backward(loss)
    leaf = engine._acc["blocks"]["fc_w"]      # column-parallel [L, h, 4h]
    host = np.asarray(leaf).copy()
    host[..., -1] = np.nan                    # lands in model shard 1 only
    engine._acc["blocks"]["fc_w"] = jax.device_put(host, leaf.sharding)
    engine.step()

    vals = _device_values(engine.loss_scale_state.cur_scale)
    assert len(set(float(v) for v in vals)) == 1
    # a replicated param (layer norm) must hold the same buffer everywhere
    ln = engine.params["lnf_s"]
    ln_vals = _device_values(ln)
    for v in ln_vals[1:]:
        np.testing.assert_array_equal(v, ln_vals[0])


def test_tp_clipping_parity_mp2_vs_mp1():
    """gradient_clipping under mp=2 must clip by the GLOBAL norm: same loss
    trajectory as mp=1 (reference run_func_test.py parity methodology)."""
    def run(mp):
        engine = _make_engine(mp, gradient_clipping=0.05)
        losses, norms = [], []
        for i in range(5):
            toks, labels = lm_batch(8, seed=i)
            loss = engine(toks, labels)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
            norms.append(float(engine._last_grad_norm))
        return losses, norms

    losses1, norms1 = run(1)
    losses2, norms2 = run(2)
    # the clip threshold is tiny, so clipping is active every step: any
    # per-shard norm bug would change the trajectory immediately
    np.testing.assert_allclose(norms2, norms1, rtol=2e-4)
    np.testing.assert_allclose(losses2, losses1, rtol=2e-4, atol=2e-5)


def test_tp_grad_norm_parity_mp4():
    """Reported grad norm is the global norm at any mp degree."""
    def one_step_norm(mp):
        engine = _make_engine(mp, gradient_clipping=1.0)
        toks, labels = lm_batch(8)
        loss = engine(toks, labels)
        engine.backward(loss)
        engine.step()
        return float(engine._last_grad_norm)

    ref = one_step_norm(1)
    for mp in (2, 4):
        assert abs(one_step_norm(mp) - ref) / ref < 2e-4
