"""Checkpoint save/restore at the 1.5B perf config (VERDICT r4 item 4:
'measure save/restore time at the 1.5B config in the model tier').

ZeRO-3 on the virtual 8-device mesh: persistent state is ~21 GB host-side
(bf16 params + fp32 master + Adam moments).  The measured contract:

* the async save's training stall is the device→host snapshot ONLY —
  the 21 GB container write drains on the background thread;
* the chunked writer streams leaf-at-a-time, so sync-save peak RSS stays
  ~one leaf above baseline instead of ~state_gb;
* the shard-native stage-3 round trip restores bit-exact;
* the parallel streaming restore (reader pool + readahead window,
  PR 5) beats the serial fallback on the same files — both restores
  are timed here and the speedup asserted, since restore sits on the
  preemption-resume critical path (CKPT_BENCH.md "fast resume" rows).

Heavy (tens of GB of disk traffic): gated behind DSTPU_CKPT_SCALE=1.
Measured numbers from this rig are committed in CKPT_BENCH.md.
"""

import gc
import os
import time

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2
from deepspeed_tpu.parallel.topology import make_mesh

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(os.environ.get("DSTPU_CKPT_SCALE") != "1",
                       reason="set DSTPU_CKPT_SCALE=1 (writes ~40 GB to "
                              "disk; run in the model/perf tier)"),
]


def test_1_5b_zero3_save_restore_timing(tmp_path):
    model = GPT2.from_size("xl-1.5b-perf", vocab_size=50304,
                           max_seq_len=64)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 8, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3}},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=make_mesh())
    n = sum(int(np.prod(l.shape))
            for l in jax.tree_util.tree_leaves(engine.params))
    assert n > 1.5e9
    state_gb = n * 14 / 2 ** 30

    d = str(tmp_path)
    t0 = time.perf_counter()
    engine.save_checkpoint(d, tag="a", async_save=True)
    async_stall = time.perf_counter() - t0
    t0 = time.perf_counter()
    engine.checkpoint_wait()
    drain = time.perf_counter() - t0

    t0 = time.perf_counter()
    engine.save_checkpoint(d, tag="s")          # sync, warm host caches
    sync_total = time.perf_counter() - t0

    # the structural contract: the async stall (what training pays) never
    # exceeds the whole job's cost with everything on the critical path.
    # async_stall vs sync_total alone is platform-dependent and NOT
    # asserted: the async stall is the full-tree decoupling memcpy
    # (np.array copies — donation reuses device buffers), while the sync
    # path streams leaf-at-a-time device→host views straight to disk; on
    # a chip the shared device→host transfer dominates both and async
    # wins, but on a CPU backend with storage faster than single-thread
    # memcpy (this rig: ~650 MB/s write vs ~285 MB/s copy) the copy can
    # exceed the write.  All three are printed for CKPT_BENCH.md.
    assert async_stall < sync_total + drain, (async_stall, drain,
                                              sync_total)

    # snapshot the parity references and drop the writer engine: three
    # live engines would be ~63 GB of host state at once, and the freed
    # RAM doubles as page cache for the 21 GB the restores re-read
    ref_wte = np.array(engine.master["wte"])
    del engine
    gc.collect()

    def fresh_engine(restore_threads, seed):
        e, _, _, _ = deepspeed_tpu.initialize(
            config={"train_batch_size": 8, "steps_per_print": 10 ** 9,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-4}},
                    "bf16": {"enabled": True},
                    "zero_optimization": {"stage": 3},
                    "checkpoint": {"restore_threads": restore_threads}},
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(seed)),
            mesh=make_mesh())
        return e

    # serial fallback (the pre-PR-5 read path: same plan, inline)
    e_ser = fresh_engine(1, seed=1)
    t0 = time.perf_counter()
    e_ser.load_checkpoint(d, tag="a")
    restore_serial = time.perf_counter() - t0
    np.testing.assert_array_equal(np.asarray(e_ser.master["wte"]), ref_wte)
    ser_m_wte = np.array(e_ser.opt_state.m["wte"])
    del e_ser
    gc.collect()

    # parallel streaming restore (reader pool, auto width)
    e_par = fresh_engine(0, seed=2)
    t0 = time.perf_counter()
    e_par.load_checkpoint(d, tag="a")
    restore_parallel = time.perf_counter() - t0
    np.testing.assert_array_equal(np.asarray(e_par.master["wte"]), ref_wte)
    # both paths run the identical per-leaf assembly — spot-pin bitwise
    # parity at scale on a moments leaf too
    np.testing.assert_array_equal(
        np.asarray(e_par.opt_state.m["wte"]), ser_m_wte)
    # the acceptance bar (ISSUE 5): the pooled pipeline must not lose to
    # the serial fallback.  Tolerance, not strict '<': on a core-starved
    # box with the 21 GB page-cache-warm, reads are pure memcpy and the
    # pool's threads only add contention (bench_resume_335m.json measured
    # a 1.23x inversion at 4 GB) — the pool's win case is cold/IO-bound
    # reads and multi-core hosts.  Both restores are the SAME plan and
    # bitwise identical; the committed numbers live in CKPT_BENCH.md.
    assert restore_parallel < restore_serial * 1.25, (restore_parallel,
                                                      restore_serial)
    print(f"1.5B zero3 ckpt ({state_gb:.1f} GB state): async stall "
          f"{async_stall:.1f}s, drain {drain:.1f}s, sync save "
          f"{sync_total:.1f}s, restore serial {restore_serial:.1f}s, "
          f"restore parallel {restore_parallel:.1f}s")
