"""SQuAD fine-tune-to-F1 harness on REAL text (BingBertSquad analog).

BASELINE.md's north star is wall-clock to *F1 parity*; the reference ships
a fine-tune suite asserting EM/F1 after a SQuAD run
(/root/reference/tests/model/BingBertSquad/BingBertSquad_run_func_test.py:14-30,
run_BingBertSquad.sh).  This tier runs the full real-text pipeline that
the reference's suite exercises — wordpiece tokenization (vocab trained
in-process, no downloads), [CLS] q [SEP] ctx windows with character
offsets, span prediction mapped back to context SUBSTRINGS, official
evaluate-v1.1 normalization — on the in-repo natural-language corpus
``data/squad_mini.json``.  The engine fine-tune must reach high text-F1
and land within 1 point of a plain-JAX fp32 baseline.

The earlier synthetic-marker task (answer flagged by in-band tokens) is
demoted to a training smoke test at the bottom of the file.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu import metrics, squad
from deepspeed_tpu.models import BertForQuestionAnswering
from deepspeed_tpu.ops import optim as optim_mod
from deepspeed_tpu.parallel.topology import make_mesh
from deepspeed_tpu.tokenization import BertTokenizer, train_wordpiece

DATA = os.path.join(os.path.dirname(__file__), "data", "squad_mini.json")
VOCAB_SIZE, SEQ, BATCH, STEPS = 768, 160, 16, 300


def model_fn(vocab_size):
    return BertForQuestionAnswering.from_size(
        "tiny", vocab_size=vocab_size, max_seq_len=SEQ, num_layers=2,
        hidden_size=64, num_heads=4)


@pytest.fixture(scope="module")
def pipeline():
    """(examples, tokenizer, features): the real-text data pipeline."""
    exs = squad.load_squad_json(DATA)
    corpus = list(dict.fromkeys(e.context for e in exs))  # dedupe paras
    vocab = train_wordpiece(corpus + [e.question for e in exs],
                            vocab_size=VOCAB_SIZE)
    tok = BertTokenizer(vocab)
    feats = squad.featurize(exs, tok, seq_len=SEQ, doc_stride=40)
    return exs, tok, feats


def train_batches(feats, steps=STEPS, batch=BATCH, seed=0):
    order = np.random.default_rng(seed)
    idx = np.arange(len(feats))
    for _ in range(steps):
        take = order.choice(idx, size=batch, replace=True)
        yield squad.batch_features([feats[i] for i in take])


def evaluate_text_f1(model, params, exs, feats):
    """Predict spans, map back to context text, official normalization."""
    predict = metrics.make_span_predictor(model, params)
    ids, attn, tt, _, _ = squad.batch_features(feats)
    sl, el = predict(ids, attn, tt)
    ps, pe = metrics.best_spans(sl, el, attn, max_answer_len=24)
    sl, el = np.asarray(sl), np.asarray(el)
    scores = (sl[np.arange(len(feats)), ps]
              + el[np.arange(len(feats)), pe])
    preds = squad.postprocess(exs, feats, ps, pe, scores)
    return squad.evaluate_predictions(exs, preds)


@pytest.fixture(scope="module")
def baseline_f1(pipeline):
    """Plain-JAX fp32 Adam fine-tune of the same model/data."""
    exs, tok, feats = pipeline
    model = model_fn(len(tok.vocab))
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, jnp.float32),
        model.init_params(jax.random.PRNGKey(1)))
    opt = optim_mod.Adam(lr=2e-3)
    state = opt.init(params)
    mesh = make_mesh(model_parallel_size=1, devices=jax.devices()[:1])

    def local(params, state, *batch):
        loss, grads = jax.value_and_grad(
            lambda p: model.apply(p, *batch))(params)
        new_p, new_s = opt.update(params, grads, state, lr=2e-3)
        return new_p, new_s, loss

    rep = lambda t: jax.tree_util.tree_map(lambda _: P(), t)
    step = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(rep(params), rep(state)) + (P(),) * 5,
        out_specs=(rep(params), rep(state), P()), check_vma=False))
    for batch in train_batches(feats):
        params, state, _ = step(params, state, *batch)
    return evaluate_text_f1(model, params, exs, feats)


def test_real_text_pipeline_oracle(pipeline):
    """Gold token spans must map back to answer text at F1 ~100 — pins
    the tokenizer offsets, window mapping, and normalization end to end
    before any model enters the picture."""
    exs, _, feats = pipeline
    starts = np.array([f.start_position for f in feats])
    ends = np.array([f.end_position for f in feats])
    scores = np.array([1.0 if f.has_answer else -1.0 for f in feats])
    preds = squad.postprocess(exs, feats, starts, ends, scores)
    r = squad.evaluate_predictions(exs, preds)
    assert r["f1"] > 99.0 and r["exact_match"] > 95.0, r


def test_engine_finetune_reaches_baseline_f1(pipeline, baseline_f1):
    """Engine fine-tune (bf16) text-F1 within 1 point of the fp32
    baseline — the reference suite's pass criterion shape, now on real
    text with the official normalization."""
    exs, tok, feats = pipeline
    model = model_fn(len(tok.vocab))
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": BATCH,
                "steps_per_print": 10 ** 6,
                "optimizer": {"type": "Adam", "params": {"lr": 2e-3}},
                "bf16": {"enabled": True}},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(1)),
        mesh=make_mesh(model_parallel_size=1))
    for batch in train_batches(feats):
        engine.train_batch(batch)
    got = evaluate_text_f1(model, engine.params, exs, feats)
    assert baseline_f1["f1"] > 85.0, baseline_f1
    assert got["f1"] > baseline_f1["f1"] - 1.0, (got, baseline_f1)
    assert got["exact_match"] > baseline_f1["exact_match"] - 2.0, (
        got, baseline_f1)


def test_metric_unit_semantics():
    """Metric math pinned: official text normalization + span overlap."""
    assert metrics.text_exact_match("The Cat!", "cat") == 1.0
    assert metrics.text_f1("the cat sat", "a cat") == pytest.approx(2 / 3)
    assert metrics.span_f1((3, 5), (3, 5)) == 1.0
    assert metrics.span_f1((3, 5), (5, 7)) == pytest.approx(1 / 3)
    assert metrics.span_f1((0, 1), (4, 5)) == 0.0
    sl = np.full((1, 8), -5.0)
    el = np.full((1, 8), -5.0)
    sl[0, 2] = 5.0
    el[0, 4] = 5.0
    ps, pe = metrics.best_spans(sl, el, max_answer_len=8)
    assert (ps[0], pe[0]) == (2, 4)
    # max_answer_len forbids the wide span; falls back to best short one
    ps, pe = metrics.best_spans(sl, el, max_answer_len=2)
    assert pe[0] - ps[0] < 2


# --------------------------------------------------- demoted synthetic smoke

def test_synthetic_marker_smoke():
    """The old in-band-marker task, kept as a fast smoke test of the QA
    head's training path only (the real-text harness above is the F1
    bar): loss must fall on a trivially learnable span corpus."""
    rng = np.random.default_rng(0)
    V, T = 128, 32

    def marker_batch():
        ids = rng.integers(4, V, size=(16, T)).astype(np.int32)
        start = rng.integers(1, T - 4, size=(16,)).astype(np.int32)
        end = (start + 2).astype(np.int32)
        for b in range(16):
            ids[b, start[b]] = 1
            ids[b, end[b]] = 2
        return ids, np.ones_like(ids), np.zeros_like(ids), start, end

    model = BertForQuestionAnswering.from_size(
        "tiny", vocab_size=V, max_seq_len=T, num_layers=2,
        hidden_size=64, num_heads=4)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 16, "steps_per_print": 10 ** 6,
                "optimizer": {"type": "Adam", "params": {"lr": 2e-3}},
                "bf16": {"enabled": True}},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(1)),
        mesh=make_mesh(model_parallel_size=1))
    losses = [float(engine.train_batch(marker_batch())) for _ in range(40)]
    assert np.mean(losses[-5:]) < 0.5 * np.mean(losses[:5]), losses
