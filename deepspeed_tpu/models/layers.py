"""Tensor-parallel layer primitives (Megatron-style) for the ('data','model')
mesh.

The reference consumes an external Megatron-LM for tensor parallelism through
the ``mpu`` protocol (/root/reference/docs/_pages/features.md §"Support for
Custom Model Parallelism"; engine hooks at
/root/reference/deepspeed/pt/deepspeed_light.py:420-430).  On TPU we own the
model layer, so the Megatron column/row-parallel linears, vocab-parallel
embedding and vocab-parallel cross-entropy are provided here as pure functions
meant to run INSIDE ``shard_map``: every function sees *local* shards of its
weights and issues explicit collectives (``psum``/``pmax``) over the ``model``
mesh axis.  With ``model`` axis size 1 every collective degenerates to a
no-op, so the same model code serves mp=1 and mp>1.

Conventions:
* column-parallel weight  [in, out/mp]  — output stays sharded, no collective
  in forward (Megatron's "f" operator: JAX autodiff inserts the backward
  all-reduce for the replicated input automatically through shard_map).
* row-parallel weight     [in/mp, out]  — forward ends with a psum over
  ``model`` (Megatron's "g" operator); bias is replicated and added after.
* QKV packing is head-major ``(n_heads, 3, head_dim)`` flattened on the output
  dim, so an even split over ``model`` hands each shard whole heads with their
  q, k and v together.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from deepspeed_tpu.parallel.topology import MODEL_AXIS, SEQ_AXIS

# Pallas attention dispatch (DSTPU_FUSED_ATTN = "auto" | "1" | "0").
# Measured on a v5e chip, END-TO-END training step (12-layer model,
# selective remat — the remat replay doubles attention's share, so these
# are the numbers that matter for users; bench_attn_sweep.json r4/r5):
#   GPT-2 causal:   kernel 1.127x @128 (whole-tile — streaming needs a
#                   256 tile), 1.18x @512, 1.87x @1024, 2.44x @2048,
#                   3.21x @4096
#   BERT-large 128: whole-tile kernel 0.92x (375.6 vs 409.2 samples/s,
#                   non-causal, 16 heads) -> XLA below the threshold
# "auto" (default) picks per DIRECTION and per KIND: the streaming
# online-softmax kernel from the calibrated threshold up, the whole-tile
# kernel for causal shapes from BLOCK_AUTO_MIN_CAUSAL (the seq-128 causal
# sweep row the old threshold left on the table — VERDICT r5 weak #3),
# XLA otherwise; "1" forces a kernel wherever one supports the shape; "0"
# disables both.  Causal thresholds are lower: both kernels skip (or never
# compute) fully-masked KV tiles, which the XLA einsum path cannot.
# Forward and backward resolve INDEPENDENTLY (ops/pallas_attention.py
# dispatch_attention): the backward runs ~2.5x the forward's matmul passes
# per tile pair, so its kernel crossover sits lower on DMA-bound shapes.
#
# The crossover is chip-generation dependent.  Resolution order per
# (kind, direction):
#   1. DSTPU_STREAM_ATTN_MIN_CAUSAL_FWD / _BWD (most specific)
#   2. DSTPU_STREAM_ATTN_MIN_CAUSAL (causal, both directions — what
#      calibrate() prints, since it measures the causal crossover)
#   3. DSTPU_STREAM_ATTN_MIN_FWD / _BWD (both kinds, one direction)
#   4. DSTPU_STREAM_ATTN_MIN (applies everywhere; a causal-measured value
#      here would force the kernel on non-causal shapes where XLA wins —
#      prefer the causal-scoped pin)
#   5. the per-device-kind table below
#   6. the v5e-measured defaults
# `ops.pallas_attention.calibrate_stream_threshold()` measures the
# crossover on the attached chip and prints the env pin to persist.
STREAM_AUTO_MIN = 1024            # non-causal default (conservative)
STREAM_AUTO_MIN_CAUSAL = 512      # causal default (v5e end-to-end sweep)
#: measured per device kind: {"causal": (fwd_min, bwd_min), "noncausal":
#: (fwd_min, bwd_min)}; extend as sweeps run on new generations
#: (BENCH_ATTN_SWEEP=1 BENCH_SEQ=<n> python bench.py)
#: v5e non-causal: XLA wins at 128 (0.92x r4 sweep) but the kernel wins
#: 1.17x at 512 (BERT-large seq512 84.8 vs 72.3 samples/s/chip, r5) —
#: threshold 512 is measured at both ends.  fwd == bwd until a
#: direction-split sweep lands; the mechanism is in place for it.
STREAM_AUTO_MIN_BY_KIND = {
    "TPU v5 lite": {"causal": (512, 512), "noncausal": (512, 512)},
    "TPU v5e": {"causal": (512, 512), "noncausal": (512, 512)},
}

#: whole-tile kernel auto-dispatch BELOW the streaming threshold, causal
#: only: the committed causal seq-128 sweep row (bench_attn_sweep.json,
#: 1.127x end-to-end — under force mode seq 128 selects the whole-tile
#: kernel since streaming needs a 256-token tile) was previously
#: unreachable in auto mode.  Non-causal short sequences keep XLA (0.92x
#: measured, BERT-large 128).  Env pin: DSTPU_BLOCK_ATTN_MIN_CAUSAL
#: (0 disables the whole-tile auto path).
BLOCK_AUTO_MIN_CAUSAL = 128


def _env_int(name):
    env = os.environ.get(name)
    if not env:
        return None
    try:
        v = int(env)
    except ValueError:
        raise ValueError(
            f"{name}={env!r} is not an integer token count") from None
    if v < 0:
        raise ValueError(f"{name}={env!r} must be a non-negative count")
    return v


def stream_auto_min(causal: bool = False, direction: str = "fwd") -> int:
    """The streaming auto-dispatch threshold for the CURRENT backend and
    the given pass direction ("fwd" | "bwd"); see the resolution order
    above."""
    if direction not in ("fwd", "bwd"):
        raise ValueError(f"direction must be 'fwd' or 'bwd', "
                         f"got {direction!r}")
    suff = direction.upper()
    names = ((f"DSTPU_STREAM_ATTN_MIN_CAUSAL_{suff}",
              "DSTPU_STREAM_ATTN_MIN_CAUSAL",
              f"DSTPU_STREAM_ATTN_MIN_{suff}",
              "DSTPU_STREAM_ATTN_MIN") if causal else
             (f"DSTPU_STREAM_ATTN_MIN_{suff}", "DSTPU_STREAM_ATTN_MIN"))
    for name in names:
        v = _env_int(name)
        if v is None:
            continue
        if v == 0:
            raise ValueError(
                f"{name}=0 is not a valid token count (use "
                f"DSTPU_FUSED_ATTN=0 to disable kernels)")
        return v
    default = STREAM_AUTO_MIN_CAUSAL if causal else STREAM_AUTO_MIN
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        return default
    entry = STREAM_AUTO_MIN_BY_KIND.get(kind)
    if entry is None:
        return default
    pair = entry["causal" if causal else "noncausal"]
    return pair[0] if direction == "fwd" else pair[1]


def block_auto_min_causal():
    """Whole-tile kernel auto threshold for causal shapes; None disables
    (env pin 0)."""
    v = _env_int("DSTPU_BLOCK_ATTN_MIN_CAUSAL")
    if v is None:
        v = BLOCK_AUTO_MIN_CAUSAL
    return None if v == 0 else v


def _attn_mode() -> str:
    mode = os.environ.get("DSTPU_FUSED_ATTN", "auto")
    if mode not in ("auto", "1", "0"):
        # fail loudly, not open: "off"/"false"/"" must not silently enable
        # the kernel the operator meant to disable
        raise ValueError(
            f"DSTPU_FUSED_ATTN={mode!r} is not a valid mode: use 'auto' "
            f"(streaming kernel from the calibrated threshold, "
            f"DSTPU_STREAM_ATTN_MIN), '1' (force a kernel), or '0' "
            f"(XLA only)")
    return mode


def axis_size_or_1(axis) -> int:
    """Static size of a mesh axis, or 1 when the axis isn't bound (allows
    the same layer code under 2-axis test meshes and the full
    ('data','seq','model') mesh)."""
    try:
        return jax.lax.axis_size(axis)
    except (NameError, KeyError, ValueError):
        return 1


def column_parallel_linear(x, w_local, b_local=None):
    """x: [..., in] replicated over model axis; w_local: [in, out/mp].
    Returns [..., out/mp] (sharded on the feature dim).  ``w_local`` may
    be an int8-quantized subtree (serving — see ``matmul_dequant``)."""
    if is_quantized(w_local):
        y = matmul_dequant(x, w_local)
    else:
        y = x @ w_local.astype(x.dtype)
    if b_local is not None:
        y = y + b_local.astype(y.dtype)
    return y


def row_parallel_linear(x_local, w_local, b=None, axis=MODEL_AXIS):
    """x_local: [..., in/mp]; w_local: [in/mp, out].  psum completes the
    contraction over the sharded input dim; result is replicated.
    Quantized weights dequantize per shard BEFORE the psum — per-output-
    channel scales are identical on every model rank, so the reduction
    is unchanged."""
    if is_quantized(w_local):
        y = jax.lax.psum(matmul_dequant(x_local, w_local), axis)
    else:
        y = jax.lax.psum(x_local @ w_local.astype(x_local.dtype), axis)
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


def vocab_parallel_embedding(tokens, wte_local, axis=MODEL_AXIS):
    """tokens: int [...]; wte_local: [vocab/mp, h] (vocab dim sharded).

    Masked local lookup + psum (Megatron VocabParallelEmbedding): each shard
    contributes rows it owns, zeros elsewhere.
    """
    if is_quantized(wte_local):
        # int8 rows dequantize AFTER the lookup (per-ROW scales: the
        # embedding's output channel is the vocab row)
        q, s = wte_local["q"], wte_local["s"]
        vocab_local = q.shape[0]
        start = jax.lax.axis_index(axis) * vocab_local
        idx = tokens - start
        valid = (idx >= 0) & (idx < vocab_local)
        idx = jnp.clip(idx, 0, vocab_local - 1)
        emb = (jnp.take(q, idx, axis=0).astype(s.dtype)
               * jnp.take(s.reshape(-1), idx)[..., None])
        emb = emb * valid[..., None].astype(emb.dtype)
        return jax.lax.psum(emb, axis)
    vocab_local = wte_local.shape[0]
    start = jax.lax.axis_index(axis) * vocab_local
    idx = tokens - start
    valid = (idx >= 0) & (idx < vocab_local)
    idx = jnp.clip(idx, 0, vocab_local - 1)
    emb = jnp.take(wte_local, idx, axis=0)
    emb = emb * valid[..., None].astype(emb.dtype)
    return jax.lax.psum(emb, axis)


def vocab_parallel_logits(h, wte_local):
    """Weight-tied LM head: h [..., hid] replicated; wte_local [vocab/mp, hid]
    → logits [..., vocab/mp] sharded on the vocab dim (feeds directly into
    ``vocab_parallel_cross_entropy`` with no gather).  An int8-quantized
    ``wte`` follows the matmul-dequant dispatch (per-row scales are the
    logits' per-output-channel scales)."""
    if is_quantized(wte_local):
        if quant_matmul_plan() == "dequant":
            return h @ dequantize(wte_local).astype(h.dtype).T
        y = h @ wte_local["q"].astype(h.dtype).T
        return y * wte_local["s"].reshape(-1).astype(y.dtype)
    return h @ wte_local.astype(h.dtype).T


def vocab_parallel_cross_entropy(logits_local, labels, axis=MODEL_AXIS):
    """Per-token CE over vocab-sharded logits (Megatron's vocab-parallel
    softmax-CE: pmax for the max, psum for the partition function and the
    target logit — never materialises the full-vocab softmax on one shard).

    logits_local: [..., vocab/mp] (any float dtype; math in fp32)
    labels:       int [...]
    returns       fp32 [...] per-token loss
    """
    logits_local = logits_local.astype(jnp.float32)
    vocab_local = logits_local.shape[-1]
    start = jax.lax.axis_index(axis) * vocab_local

    # the max shift is numerical stabilisation only — stop-grad before the
    # pmax (which has no differentiation rule); CE grads flow via shifted/tgt
    lmax = jax.lax.pmax(
        jnp.max(jax.lax.stop_gradient(logits_local), axis=-1), axis)
    shifted = logits_local - lmax[..., None]
    sumexp = jax.lax.psum(jnp.sum(jnp.exp(shifted), axis=-1), axis)

    idx = labels - start
    valid = (idx >= 0) & (idx < vocab_local)
    idxc = jnp.clip(idx, 0, vocab_local - 1)
    tgt_local = jnp.take_along_axis(shifted, idxc[..., None], axis=-1)[..., 0]
    tgt = jax.lax.psum(tgt_local * valid.astype(jnp.float32), axis)

    return jnp.log(sumexp) - tgt


def seq_shard_positions(wpe, t_local):
    """Position embeddings for THIS sequence shard: global offset
    ``seq_index * t_local`` under context parallelism, 0 otherwise."""
    pos0 = (jax.lax.axis_index(SEQ_AXIS) * t_local
            if axis_size_or_1(SEQ_AXIS) > 1 else 0)
    return jax.lax.dynamic_slice_in_dim(wpe, pos0, t_local)


def _gather_mode() -> str:
    mode = os.environ.get("DSTPU_MLM_GATHER", "auto")
    if mode not in ("auto", "onehot", "take"):
        raise ValueError(
            f"DSTPU_MLM_GATHER={mode!r} is not a valid mode: use 'auto' "
            f"(one-hot matmul on TPU, take_along_axis elsewhere), "
            f"'onehot', or 'take'")
    return mode


def gather_positions(x, positions):
    """Gather per-sequence positions: x [B, T, H], positions int [B, P] →
    [B, P, H] (the masked-LM head's input selection).

    On TPU the gather is expressed as a one-hot MATMUL: ``take_along_axis``
    lowers to an HBM gather whose VJP is a serialized scatter-add over the
    [B, T, H] activations — the dominant cost of the maxpred-80 head at
    seq 512 (bench_mfu_breakdown.json).  The one-hot form keeps both
    directions on the MXU (B·P·T·H MACs, ~0.5 ms at the phase-2 shape
    against tens of ms of scatter).  Off-TPU the plain gather wins; env
    DSTPU_MLM_GATHER pins either."""
    mode = _gather_mode()
    if mode == "onehot" or (mode == "auto"
                            and jax.default_backend() == "tpu"):
        T = x.shape[1]
        onehot = jax.nn.one_hot(positions.astype(jnp.int32), T,
                                dtype=x.dtype)              # [B, P, T]
        return jax.lax.dot_general(
            onehot, x, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=x.dtype)
    return jnp.take_along_axis(
        x, positions[..., None].astype(jnp.int32), axis=1)


def masked_mean_loss(loss, mask):
    """Global masked mean of a per-token loss under sequence sharding.

    Returns a value whose pmean over the seq axis equals the TRUE global
    masked mean (sum of masked losses / total valid count), and whose
    psum-of-grads/sp under the engine's aggregation yields the true global
    gradient — valid-token counts may differ per shard (trailing padding,
    sparse MLM labels).  With sp == 1 this is the plain masked mean.
    """
    mask = mask.astype(jnp.float32)
    local_sum = jnp.sum(loss * mask)
    local_cnt = jnp.sum(mask)
    sp = axis_size_or_1(SEQ_AXIS)
    if sp > 1:
        total_cnt = jax.lax.psum(local_cnt, SEQ_AXIS)
        return local_sum * sp / jnp.maximum(total_cnt, 1.0)
    return local_sum / jnp.maximum(local_cnt, 1.0)


def layer_norm(x, scale, bias, eps=1e-5):
    """LayerNorm in fp32 (bf16/fp16 inputs upcast for the moments)."""
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def gelu(x):
    """tanh-approx GELU (matches GPT-2/BERT)."""
    xf = x.astype(jnp.float32)
    y = 0.5 * xf * (1.0 + jnp.tanh(
        0.7978845608028654 * (xf + 0.044715 * xf ** 3)))
    return y.astype(x.dtype)


# --------------------------------------------------------------- serving
# int8 weight-only quantization (deepspeed_tpu/inference/): weights are
# stored as {"q": int8, "s": per-output-channel scale} subtrees, and the
# matmul-dequant strategy rides a per-backend dispatch table like the
# attention kernels above (docs/inference.md "Quantization").  Two impls:
#   "dequant" — materialise W = q*s in the compute dtype, then matmul
#               (the exactness anchor: one rounding per weight element)
#   "scaled"  — contract x @ q first, scale the [..., out] activation
#               (the serving default: per-output-channel scales commute
#               with the contraction, so this is the same math with the
#               scale applied once per OUTPUT element — it never
#               materialises the dequantized [in, out] weight, which is
#               the entire memory win of int8 at decode batch sizes)
# The two differ by float rounding only; the contract is pinned in
# tests/test_inference.py and documented in docs/inference.md.
QUANT_MATMUL_IMPLS = ("auto", "dequant", "scaled")


def quant_matmul_plan() -> str:
    """Resolved matmul-dequant impl ("dequant" | "scaled") for the current
    mode: env ``DSTPU_QUANT_MATMUL`` pins one; "auto" (default) picks
    "scaled" — at serving shapes the activation side is orders of
    magnitude smaller than the weight it would otherwise dequantize."""
    mode = os.environ.get("DSTPU_QUANT_MATMUL", "auto")
    if mode not in QUANT_MATMUL_IMPLS:
        raise ValueError(
            f"DSTPU_QUANT_MATMUL={mode!r} is not a valid impl: use 'auto', "
            f"'dequant' or 'scaled'")
    return "scaled" if mode == "auto" else mode


def is_quantized(w) -> bool:
    """True for an int8-quantized weight subtree ({"q", "s"})."""
    return isinstance(w, dict) and set(w) == {"q", "s"}


def dequantize(wq):
    """Materialise the full-precision weight of a quantized subtree: the
    scale's dtype IS the serving compute dtype (inference/quant.py)."""
    return wq["q"].astype(wq["s"].dtype) * wq["s"]


def matmul_dequant(x, wq):
    """``x @ W`` for an int8 per-OUTPUT-channel quantized ``W`` (scale
    keepdims-shaped ``[1, out]``), per the dispatch plan."""
    if quant_matmul_plan() == "dequant":
        return x @ dequantize(wq).astype(x.dtype)
    y = x @ wq["q"].astype(x.dtype)
    return y * wq["s"].reshape(-1).astype(y.dtype)


def gather_kv_rows(pool, rows):
    """Per-slot view of the flat KV page pool: ``pool`` [R, n, d],
    ``rows`` int32 [B, cap] (the host-resolved page-table row map) →
    [B, cap, n, d].  Shared pages appear in several slots' views at
    zero copy cost — the gather is the read attention does anyway."""
    return jnp.take(pool, rows, axis=0, mode="clip")


def scatter_kv_rows(pool, new, rows):
    """Write ``new`` token rows into the flat pool: ``pool`` [R, n, d],
    ``new`` [..., n, d] with ``rows`` int32 matching its leading dims.
    Rows ``>= R`` are DROPPED — the masked-write convention (padding /
    inactive slots aim at the out-of-range drop row).  In-bounds rows
    are exclusively owned by their writer (the page table's refcount
    discipline), so duplicates only ever occur among dropped writes."""
    n, d = new.shape[-2], new.shape[-1]
    flat = new.reshape(-1, n, d).astype(pool.dtype)
    return pool.at[rows.reshape(-1)].set(flat, mode="drop")


def cached_attention(q, k_cache, v_cache, pos, ring: bool = False):
    """Single-query attention against a per-slot KV cache.

    q: [B, n, d] (this step's query, already written to the cache at its
    own index); caches: [B, cap, n, d]; pos: int32 [B] — the query's own
    position, so cache entries ``<= pos`` attend.  ``ring=True`` admits
    every entry once a slot has wrapped (the sliding-window layout).
    Numerics mirror ``ops.pallas_attention.xla_attention`` (fp32 MXU
    accumulation for the scores and softmax, probabilities cast to the
    compute dtype before the value contraction) so incremental decode
    stays within dtype tolerance of a full-context re-forward."""
    d = q.shape[-1]
    scores = jnp.einsum("bnd,btnd->bnt", q, k_cache,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    cap = k_cache.shape[1]
    valid = jnp.arange(cap, dtype=jnp.int32)[None, :] <= pos[:, None]
    if ring:
        valid = valid | (pos[:, None] >= cap)
    scores = jnp.where(valid[:, None, :], scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bnt,btnd->bnd", probs, v_cache.astype(q.dtype))


def extend_attention(q, k_view, v_view, start):
    """Multi-query attention of a block of NEW tokens against a per-slot
    KV view that already contains their rows.

    q: [B, E, n, d] (queries for E new tokens, slot b's first at
    absolute position ``start[b]``); views: [B, cap, n, d] (gathered
    AFTER this block's K/V rows were scattered in).  Query e attends
    rows ``t <= start + e`` — earlier new tokens included, later ones
    masked out, exactly causal.  Numerics mirror :func:`cached_attention`
    (fp32 score accumulation and softmax, probs cast to compute dtype)
    so a tail prefill over reused pages stays within dtype tolerance of
    the full-prompt forward.  The caller guarantees no ring wrap inside
    the block (``start + E <= cap`` — admission starts slots fresh and
    the schedulers bound prompt length by the bucket)."""
    d = q.shape[-1]
    scores = jnp.einsum("bend,btnd->bent", q, k_view,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    cap = k_view.shape[1]
    e_pos = (start[:, None]
             + jnp.arange(q.shape[1], dtype=jnp.int32)[None, :])  # [B, E]
    valid = (jnp.arange(cap, dtype=jnp.int32)[None, None, :]
             <= e_pos[:, :, None])                               # [B, E, t]
    scores = jnp.where(valid[:, :, None, :], scores, -1e9)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bent,btnd->bend", probs, v_view.astype(q.dtype))


def decode_multihead_attention(x, qkv_w_local, qkv_b_local, proj_w_local,
                               proj_b, k_pool, v_pool, pos, rows,
                               write_rows, *, n_heads_global,
                               ring: bool = False, axis=MODEL_AXIS):
    """One-token attention step against the KV page pool.

    x: [B, 1, h]; pools: [R, n_local, d] flat rows; pos: int32 [B]
    (absolute position the new token occupies); rows: int32 [B, cap]
    (the slot's page-table row map); write_rows: int32 [B] (this
    step's flat target row, ``>= R`` = masked write).  Scatters this
    step's K/V, gathers the per-slot view, attends, and returns
    ``(out [B, 1, h], k_pool', v_pool')``."""
    B, _, h = x.shape
    d = h // n_heads_global
    qkv = column_parallel_linear(x, qkv_w_local, qkv_b_local)  # [B,1,3h/mp]
    n_local = qkv.shape[-1] // (3 * d)
    qkv = qkv.reshape(B, n_local, 3, d)
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    k_pool = scatter_kv_rows(k_pool, k[:, None], write_rows[:, None])
    v_pool = scatter_kv_rows(v_pool, v[:, None], write_rows[:, None])
    k_view = gather_kv_rows(k_pool, rows)
    v_view = gather_kv_rows(v_pool, rows)
    ctx = cached_attention(q, k_view, v_view, pos, ring=ring)
    ctx = ctx.reshape(B, 1, n_local * d)
    out = row_parallel_linear(ctx, proj_w_local, proj_b, axis=axis)
    return out, k_pool, v_pool


def extend_multihead_attention(x, qkv_w_local, qkv_b_local, proj_w_local,
                               proj_b, k_pool, v_pool, rows, start, n_new,
                               *, n_heads_global, axis=MODEL_AXIS):
    """Attention for a BLOCK of new tokens against the KV page pool —
    the prefill / tail-prefill / speculative-verify path (one program
    shape serves all three, docs/inference.md).

    x: [B, E, h] (E new tokens per slot, left-aligned, ``n_new[b]``
    real); pools: [R, n_local, d]; rows: int32 [B, cap]; start: int32
    [B] (absolute position of each slot's first new token).  Pad
    positions and positions past the slot's range write to the drop row;
    their outputs are garbage the caller masks.  Sequence parallelism is
    not a serving layout, so the seq axis must be unsharded here."""
    if axis_size_or_1(SEQ_AXIS) > 1:
        raise ValueError(
            "extend_multihead_attention: KV-cached serving does not "
            "compose with context parallelism (shard requests over "
            "engine replicas instead)")
    B, E, h = x.shape
    d = h // n_heads_global
    qkv = column_parallel_linear(x, qkv_w_local, qkv_b_local)
    n_local = qkv.shape[-1] // (3 * d)
    qkv = qkv.reshape(B, E, n_local, 3, d)
    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    cap = rows.shape[1]
    R = k_pool.shape[0]
    idx = (start[:, None]
           + jnp.arange(E, dtype=jnp.int32)[None, :])            # [B, E]
    wrows = jnp.take_along_axis(rows, jnp.clip(idx, 0, cap - 1), axis=1)
    real = ((jnp.arange(E, dtype=jnp.int32)[None, :] < n_new[:, None])
            & (idx < cap))
    wrows = jnp.where(real, wrows, R)            # pad/overflow → drop row
    k_pool = scatter_kv_rows(k_pool, k, wrows)
    v_pool = scatter_kv_rows(v_pool, v, wrows)
    k_view = gather_kv_rows(k_pool, rows)
    v_view = gather_kv_rows(v_pool, rows)
    ctx = extend_attention(q, k_view, v_view, start)
    ctx = ctx.reshape(B, E, n_local * d)
    return row_parallel_linear(ctx, proj_w_local, proj_b, axis=axis), \
        k_pool, v_pool


def attention_plan(T, n, d, causal):
    """(fwd_impl, bwd_impl), each in {"xla", "block", "stream"}, for the
    current backend/mode — the per-direction dispatch table.  Forward and
    backward resolve independently under "auto" (their crossovers differ);
    "1" forces one kernel for both, "0" / non-TPU yields ("xla", "xla")."""
    mode = _attn_mode()
    if mode == "0" or jax.default_backend() != "tpu":
        return "xla", "xla"
    from deepspeed_tpu.ops import pallas_attention as pattn
    stream_ok = pattn.stream_supported(T, d)
    block_ok = pattn.supported(T, n, d)
    if mode == "1":
        impl = "stream" if stream_ok else ("block" if block_ok else "xla")
        return impl, impl

    def pick(direction):
        if stream_ok and T >= stream_auto_min(causal, direction):
            return "stream"
        bmin = block_auto_min_causal()
        if block_ok and causal and bmin is not None and T >= bmin:
            return "block"
        return "xla"

    fwd, bwd = pick("fwd"), pick("bwd")
    if bwd == "stream" and fwd == "block":
        # a streaming backward needs the forward's logsumexp, which the
        # whole-tile kernel doesn't emit
        bwd = "block"
    return fwd, bwd


def core_attention(q, k, v, *, causal, attn_mask=None):
    """Single-device attention on [B, T, n, d] q/k/v with the per-direction
    kernel dispatch table (``attention_plan``): streaming Pallas kernel from
    the calibrated threshold, whole-tile kernel for short causal shapes (or
    under force mode), XLA einsum otherwise — forward and backward chosen
    independently.  ``attn_mask``: optional [B, T] float/int, 1 = attend.
    Shared by the plain path and Ulysses sequence parallelism (which
    calls it on the all-to-all'd full-sequence view — so long-context
    kernels and sequence sharding compose)."""
    B, T, n, d = q.shape
    fwd_impl, bwd_impl = attention_plan(T, n, d, causal)
    from deepspeed_tpu.ops import pallas_attention as pattn
    mvec = (jnp.ones((B, T), jnp.float32) if attn_mask is None
            else attn_mask.astype(jnp.float32))
    if fwd_impl == bwd_impl == "stream":
        return pattn.stream_attention(q, k, v, mvec, causal)
    if fwd_impl == bwd_impl == "block":
        return pattn.fused_attention(q, k, v, mvec, causal)
    if (fwd_impl, bwd_impl) == ("xla", "xla"):
        # single source of the reference einsum math (fp32 MXU
        # accumulation, masked softmax) — also the hybrid paths' "xla"
        # side, so the threshold branches can never drift numerically
        return pattn.xla_attention(q, k, v, mvec, causal)[0]
    return pattn.dispatch_attention(q, k, v, mvec, causal,
                                    fwd_impl, bwd_impl)


def multihead_attention(x, qkv_w_local, qkv_b_local, proj_w_local, proj_b,
                        *, n_heads_global, causal, attn_mask=None,
                        axis=MODEL_AXIS, sp_impl="ring"):
    """Tensor-parallel multi-head attention over local heads.

    x:            [B, T, h] replicated over ``model``
    qkv_w_local:  [h, 3h/mp]  packed head-major (n_local, 3, d)
    qkv_b_local:  [3h/mp]
    proj_w_local: [h/mp, h]   row-parallel output projection
    proj_b:       [h]         replicated
    attn_mask:    optional [B, T] with 1=attend, 0=pad (BERT)
    sp_impl:      sequence-parallel strategy when the ``seq`` axis is
                  sharded: "ring" (K/V rotation, nearest-neighbour ICI
                  only) or "ulysses" (head<->sequence all-to-all; each
                  shard sees the FULL sequence for n/sp heads, so the
                  streaming kernel dispatch applies — models/ulysses.py)
    """
    B, T, h = x.shape
    d = h // n_heads_global
    qkv = column_parallel_linear(x, qkv_w_local, qkv_b_local)  # [B,T,3h/mp]
    # named for the "selective" remat policy: saving qkv lets backward
    # recompute attention (cheap einsums) without replaying the qkv matmul
    qkv = checkpoint_name(qkv, "qkv")
    n_local = qkv.shape[-1] // (3 * d)
    qkv = qkv.reshape(B, T, n_local, 3, d)

    if axis_size_or_1(SEQ_AXIS) > 1 and sp_impl == "ulysses":
        # packed entry point: one all-to-all moves q, k and v together
        from deepspeed_tpu.models.ulysses import ulysses_attention_packed
        ctx = ulysses_attention_packed(qkv, causal=causal,
                                       attn_mask=attn_mask)
        ctx = ctx.reshape(B, T, n_local * d)
        return row_parallel_linear(ctx, proj_w_local, proj_b, axis=axis)

    q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]   # [B,T,n,d]

    if axis_size_or_1(SEQ_AXIS) > 1:
        if sp_impl == "ring":
            # sequence-sharded: exact blockwise attention over the ring
            from deepspeed_tpu.models.ring_attention import ring_attention
            ctx = ring_attention(q, k, v, causal=causal, kv_mask=attn_mask)
        else:
            raise ValueError(
                f"unknown sequence_parallel_impl {sp_impl!r} "
                "(expected 'ring' or 'ulysses')")
    else:
        ctx = core_attention(q, k, v, causal=causal, attn_mask=attn_mask)
    ctx = ctx.reshape(B, T, n_local * d)                        # [B,T,h/mp]
    return row_parallel_linear(ctx, proj_w_local, proj_b, axis=axis)
