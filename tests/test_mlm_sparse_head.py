"""Sparse masked-position MLM head: gather-vs-dense equivalence.

The maxpred-80 head at seq 512 is a top-three phase-2 cost
(bench_mfu_breakdown.json); the sparse path gathers the masked positions
BEFORE the vocab projection.  These tests pin:

* ``layers.gather_positions`` — the one-hot-matmul gather (scatter-free
  VJP, the TPU form) against ``take_along_axis``, forward and gradient;
* the dense-labels format with ``mlm_gather_budget`` against the plain
  dense head, including the all-positions-masked and zero-masked edge
  cases and the documented overflow contract.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import BertForPreTraining
from deepspeed_tpu.models import layers as L
from deepspeed_tpu.parallel.topology import make_mesh

VOCAB, SEQ = 64, 16
B = 8   # the test mesh has 8 fake devices on the data axis


def tiny_bert(**over):
    return BertForPreTraining.from_size(
        "tiny", vocab_size=VOCAB, max_seq_len=SEQ,
        num_layers=1, hidden_size=16, num_heads=2, **over)


# ------------------------------------------------------- gather_positions

def test_gather_positions_onehot_matches_take(monkeypatch):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(B, SEQ, 8)).astype(np.float32))
    pos = jnp.asarray(rng.integers(0, SEQ, size=(B, 5)).astype(np.int32))

    monkeypatch.setenv("DSTPU_MLM_GATHER", "take")
    want = L.gather_positions(x, pos)
    g_take = jax.grad(lambda x: jnp.sum(jnp.sin(
        L.gather_positions(x, pos))))(x)
    monkeypatch.setenv("DSTPU_MLM_GATHER", "onehot")
    got = L.gather_positions(x, pos)
    g_onehot = jax.grad(lambda x: jnp.sum(jnp.sin(
        L.gather_positions(x, pos))))(x)

    # one-hot selection is exact (one nonzero term per output element),
    # including repeated positions (the VJP scatter-adds either way)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=0, atol=0)
    np.testing.assert_allclose(np.asarray(g_onehot), np.asarray(g_take),
                               rtol=1e-6, atol=1e-6)


def test_gather_positions_mode_validation(monkeypatch):
    monkeypatch.setenv("DSTPU_MLM_GATHER", "scatter")
    with pytest.raises(ValueError, match="DSTPU_MLM_GATHER"):
        L.gather_positions(jnp.zeros((1, 4, 2)), jnp.zeros((1, 1), jnp.int32))


# --------------------------------------------- dense-labels sparse budget

def _loss_fn(model, params, batch, mesh):
    specs = model.partition_specs(params)
    fn = jax.jit(jax.shard_map(
        lambda p, *b: model.apply(p, *b), mesh=mesh,
        in_specs=(specs,) + tuple(P("data", None) for _ in batch),
        out_specs=P(), check_vma=False))
    return fn(params, *batch)


def _bert_inputs(mlm_dense):
    rng = np.random.default_rng(3)
    ids = rng.integers(0, VOCAB, size=(B, SEQ)).astype(np.int32)
    mask = np.ones((B, SEQ), np.int32)
    mask[:, SEQ - 3:] = 0
    tt = np.zeros((B, SEQ), np.int32)
    return (ids, mask, tt, mlm_dense)


@pytest.mark.parametrize("budget", [6, SEQ, SEQ + 50])
def test_sparse_budget_matches_dense(budget):
    """Within-budget masked counts: sparse gather == dense head, loss and
    parameter gradients (budget > T exercises the clamp)."""
    rng = np.random.default_rng(5)
    mlm = np.full((B, SEQ), -1, np.int32)
    for b in range(B):
        pos = rng.choice(SEQ, size=4, replace=False)
        mlm[b, pos] = rng.integers(0, VOCAB, size=4)
    batch = _bert_inputs(mlm)
    mesh = make_mesh(model_parallel_size=1)

    dense_m = tiny_bert()
    sparse_m = tiny_bert(mlm_gather_budget=budget)
    params = dense_m.init_params(jax.random.PRNGKey(0))

    want = float(_loss_fn(dense_m, params, batch, mesh))
    got = float(_loss_fn(sparse_m, params, batch, mesh))
    np.testing.assert_allclose(got, want, rtol=1e-5)

    g_dense = jax.grad(lambda p: _loss_fn(dense_m, p, batch, mesh))(params)
    g_sparse = jax.grad(lambda p: _loss_fn(sparse_m, p, batch, mesh))(params)
    flat_d = jax.tree_util.tree_leaves(g_dense)
    flat_s = jax.tree_util.tree_leaves(g_sparse)
    for a, b_ in zip(flat_s, flat_d):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=1e-6)


def test_sparse_budget_all_positions_masked():
    """Every position masked: budget >= T keeps the gather an exact
    permutation of the dense head."""
    rng = np.random.default_rng(6)
    mlm = rng.integers(0, VOCAB, size=(B, SEQ)).astype(np.int32)
    batch = _bert_inputs(mlm)
    mesh = make_mesh(model_parallel_size=1)
    params = tiny_bert().init_params(jax.random.PRNGKey(1))
    want = float(_loss_fn(tiny_bert(), params, batch, mesh))
    got = float(_loss_fn(tiny_bert(mlm_gather_budget=SEQ), params, batch,
                         mesh))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_sparse_budget_zero_masked():
    """No masked positions: both paths degrade to a zero loss (the
    max(count, 1) guard), not a NaN."""
    mlm = np.full((B, SEQ), -1, np.int32)
    batch = _bert_inputs(mlm)
    mesh = make_mesh(model_parallel_size=1)
    params = tiny_bert().init_params(jax.random.PRNGKey(2))
    want = float(_loss_fn(tiny_bert(), params, batch, mesh))
    got = float(_loss_fn(tiny_bert(mlm_gather_budget=4), params, batch,
                         mesh))
    assert np.isfinite(got)
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert got == 0.0


def test_engine_switches_mlm_batch_formats():
    """The fused train_batch program is keyed on batch STRUCTURE: a BERT
    engine fed masked-positions batches must accept a dense-labels batch
    next (different leaf count -> different shard_map in_specs) instead
    of failing on a spec/pytree mismatch."""
    import deepspeed_tpu

    model = tiny_bert(mlm_gather_budget=SEQ)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": B, "steps_per_print": 10 ** 9,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=make_mesh(model_parallel_size=1))

    rng = np.random.default_rng(11)
    ids = rng.integers(0, VOCAB, size=(B, SEQ)).astype(np.int32)
    mask = np.ones((B, SEQ), np.int32)
    tt = np.zeros((B, SEQ), np.int32)
    pos = np.stack([np.sort(rng.choice(SEQ, size=4, replace=False))
                    for _ in range(B)]).astype(np.int32)
    mids = np.take_along_axis(ids, pos, axis=1)
    w = np.ones((B, 4), np.float32)
    dense = np.full((B, SEQ), -1, np.int32)
    np.put_along_axis(dense, pos, mids, axis=1)

    l_pos = float(engine.train_batch((ids, mask, tt, pos, mids, w)))
    l_dense = float(engine.train_batch((ids, mask, tt, dense)))
    l_pos2 = float(engine.train_batch((ids, mask, tt, pos, mids, w)))
    assert np.isfinite(l_pos) and np.isfinite(l_dense) and np.isfinite(l_pos2)


def test_sparse_budget_overflow_contract():
    """Masked counts past the budget: the documented contract drops the
    LAST overflow positions (top_k is stable), i.e. the loss equals the
    dense loss over each row's first ``budget`` masked positions."""
    rng = np.random.default_rng(7)
    mlm = rng.integers(0, VOCAB, size=(B, SEQ)).astype(np.int32)  # all masked
    batch = _bert_inputs(mlm)
    budget = 5
    mesh = make_mesh(model_parallel_size=1)
    params = tiny_bert().init_params(jax.random.PRNGKey(3))

    got = float(_loss_fn(tiny_bert(mlm_gather_budget=budget), params,
                         batch, mesh))
    truncated = np.full((B, SEQ), -1, np.int32)
    truncated[:, :budget] = mlm[:, :budget]
    want = float(_loss_fn(tiny_bert(), params,
                          _bert_inputs(truncated), mesh))
    np.testing.assert_allclose(got, want, rtol=1e-5)
