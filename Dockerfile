# TPU-host image for deepspeed_tpu (reference Dockerfile analog: the
# reference ships a CUDA+apex image; the TPU equivalent is jax[tpu] + libtpu).
# For CPU-only development builds: --build-arg JAX_SPEC="jax".
FROM python:3.12-slim

ARG JAX_SPEC="jax[tpu] -f https://storage.googleapis.com/jax-releases/libtpu_releases.html"

RUN apt-get update && apt-get install -y --no-install-recommends \
        openssh-client pdsh git \
    && rm -rf /var/lib/apt/lists/*

RUN pip install --no-cache-dir ${JAX_SPEC} numpy psutil pytest

WORKDIR /opt/deepspeed_tpu
COPY pyproject.toml README.md ./
COPY deepspeed_tpu ./deepspeed_tpu
COPY bin ./bin
COPY tests ./tests
COPY docs ./docs
RUN pip install --no-cache-dir .

# sanity: the package imports and the CLI resolves
RUN python -c "import deepspeed_tpu" && dst --help >/dev/null

CMD ["/bin/bash"]
