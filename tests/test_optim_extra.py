"""RMSprop/Adagrad parity vs torch + the optimizer registry + profiler
config window.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.ops import optim as optim_mod

torch = pytest.importorskip("torch")


def _run_ours(opt, steps=5, lr=0.05):
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(4, 8)).astype(np.float32)
    grads = [rng.normal(size=(4, 8)).astype(np.float32) for _ in range(steps)]
    params = {"w": jnp.asarray(p0)}
    state = opt.init(params)
    for g in grads:
        params, state = opt.update(params, {"w": jnp.asarray(g)}, state,
                                   lr=lr)
    return np.asarray(params["w"]), p0, grads


@pytest.mark.parametrize("name", ["rmsprop", "adagrad"])
def test_matches_torch(name):
    lr = 0.05
    if name == "rmsprop":
        ours = optim_mod.RMSprop(lr=lr)
    else:
        ours = optim_mod.Adagrad(lr=lr)
    got, p0, grads = _run_ours(ours, lr=lr)

    tp = torch.nn.Parameter(torch.tensor(p0))
    if name == "rmsprop":
        topt = torch.optim.RMSprop([tp], lr=lr, alpha=0.99, eps=1e-8)
    else:
        topt = torch.optim.Adagrad([tp], lr=lr, eps=1e-10)
    for g in grads:
        tp.grad = torch.tensor(g)
        topt.step()
    want = tp.detach().numpy()
    # torch adagrad uses lr/(1+(t-1)*lr_decay) with lr_decay=0 → identical;
    # torch rmsprop adds eps outside sqrt like ours
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_from_config_names():
    assert optim_mod.from_config("RMSprop", {"lr": 0.1,
                                             "alpha": 0.9}).alpha == 0.9
    assert optim_mod.from_config("Adagrad", {"lr": 0.1}).name == "adagrad"
    lion = optim_mod.from_config("Lion", {"lr": 3e-4, "betas": [0.95, 0.98],
                                          "weight_decay": 0.1})
    assert (lion.name, lion.beta1, lion.beta2,
            lion.weight_decay) == ("lion", 0.95, 0.98, 0.1)


def test_lion_update_rule_closed_form():
    """One step from zero momentum: u = sign((1-b1)·g) = sign(g), so
    p1 = p0 - lr·(sign(g) + wd·p0) and m1 = (1-b2)·g — the paper's
    update, checked exactly."""
    lr, wd, b1, b2 = 0.01, 0.1, 0.9, 0.99
    opt = optim_mod.Lion(lr=lr, beta1=b1, beta2=b2, weight_decay=wd)
    rng = np.random.default_rng(1)
    p0 = rng.normal(size=(4, 8)).astype(np.float32)
    g = rng.normal(size=(4, 8)).astype(np.float32)
    params = {"w": jnp.asarray(p0)}
    state = opt.init(params)
    new_p, new_state = opt.update(params, {"w": jnp.asarray(g)}, state)
    np.testing.assert_allclose(
        np.asarray(new_p["w"]), p0 - lr * (np.sign(g) + wd * p0),
        rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(new_state.m["w"]),
                               (1 - b2) * g, rtol=1e-6, atol=1e-7)
    assert new_state.v is None

    # sign-update invariance: scaling the gradient leaves the step
    # unchanged (the momentum differs) — the documented Lion property
    new_p2, _ = opt.update(params, {"w": jnp.asarray(10.0 * g)}, state)
    np.testing.assert_allclose(np.asarray(new_p2["w"]),
                               np.asarray(new_p["w"]), rtol=1e-6)


def test_engine_trains_with_lion():
    from simple_model import SimpleModel, random_dataset
    model = SimpleModel(16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 16,
                "optimizer": {"type": "Lion",
                              "params": {"lr": 3e-4, "betas": [0.9, 0.99],
                                         "weight_decay": 0.01}},
                "steps_per_print": 10 ** 6},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    ds = random_dataset(64, 16)
    losses = []
    for batch in engine.deepspeed_io(ds):
        loss = engine(*batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_lion_rejected_under_flat_zero():
    # stages 1-2 keep the flat [S, padded] m+v layout -> Adam-family only;
    # stage 3 (per-leaf elementwise) admits Lion — parity pinned in
    # tests/test_zero3.py::test_zero3_lion_matches_stage0
    from simple_model import SimpleModel
    model = SimpleModel(16)
    with pytest.raises(DeepSpeedConfigError, match="Adam-family"):
        deepspeed_tpu.initialize(
            config={"train_batch_size": 16,
                    "optimizer": {"type": "Lion", "params": {"lr": 3e-4}},
                    "fp16": {"enabled": True},
                    "zero_optimization": True,
                    "steps_per_print": 10 ** 6},
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(0)))


def test_registry_extension():
    class MyOpt(optim_mod.Sgd):
        pass

    optim_mod.register_optimizer("myopt", lambda **kw: MyOpt(**kw))
    try:
        opt = optim_mod.from_config("MyOpt", {"lr": 0.5})
        assert isinstance(opt, MyOpt) and opt.lr == 0.5
    finally:
        optim_mod._REGISTRY.pop("myopt", None)


def test_engine_trains_with_rmsprop():
    from simple_model import SimpleModel, random_dataset
    model = SimpleModel(16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 16,
                "optimizer": {"type": "RMSprop", "params": {"lr": 1e-3}},
                "steps_per_print": 10 ** 6},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    ds = random_dataset(64, 16)
    losses = []
    for batch in engine.deepspeed_io(ds):
        loss = engine(*batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert all(np.isfinite(losses))


def test_profiler_window(tmpdir):
    from simple_model import SimpleModel, random_dataset
    model = SimpleModel(16)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 16,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "profile": {"enabled": True, "start_step": 1,
                            "end_step": 2, "output_path": str(tmpdir)},
                "steps_per_print": 10 ** 6},
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    ds = random_dataset(64, 16)
    for batch in engine.deepspeed_io(ds):
        loss = engine(*batch)
        engine.backward(loss)
        engine.step()
    assert not engine._profiling            # closed after the window
    # a trace landed under output_path/plugins/profile/...
    found = []
    for root, _, files in os.walk(str(tmpdir)):
        found.extend(files)
    assert found, "no profiler trace files written"


def test_profiler_bad_window_rejected():
    from simple_model import SimpleModel
    model = SimpleModel(16)
    with pytest.raises(DeepSpeedConfigError, match="end_step"):
        deepspeed_tpu.initialize(
            config={"train_batch_size": 16,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "profile": {"enabled": True, "start_step": 5,
                                "end_step": 5}},
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(0)))
