"""Machine-readable telemetry event schema (one JSONL line per window).

The JSONL event log is the machine half of the exporter fan-out
(TensorBoard is the human half): one line per drained report window,
schema-versioned so downstream tooling (bench diffing, fleet dashboards,
the CI smoke gate) can parse it without guessing.  Validation is
hand-rolled — no jsonschema dependency — and doubles as the documentation
of record for every field (docs/observability.md mirrors this table).

Schema evolution contract: additive fields bump ``SCHEMA_VERSION`` minor
semantics only (validators accept unknown EXTRA keys); removing or
retyping a field is a breaking change and bumps the major version.
"""

from __future__ import annotations

import json
import numbers
from typing import Optional

#: event-log schema identifier + version, stamped on every line
SCHEMA_ID = "dstpu.telemetry.window"
SCHEMA_VERSION = 1

_NUM = numbers.Real

#: field -> (type check, required).  Optional fields must still be PRESENT
#: (null when unknown) — a missing column and an unmeasured column are
#: different facts, and downstream diffing relies on a stable key set.
FIELDS = {
    "schema": (str, True),
    "version": (int, True),
    "ts": (_NUM, True),                 # unix seconds at drain
    "step": (int, True),                # engine global_steps at window end
    "window_steps": (int, True),        # boundaries in this window (>0)
    "loss": (_NUM, False),              # last boundary's loss (sum of leaves)
    "loss_mean": (_NUM, False),         # mean over the window
    "grad_norm": (_NUM, False),         # last boundary's global grad norm
    "loss_scale": (_NUM, False),        # loss scale in effect (fp16)
    "skipped": (int, True),             # skip-on-overflow boundaries
    "step_ms": (_NUM, False),           # measured mean step wall ms
    "samples_per_sec": (_NUM, False),
    "mfu": (_NUM, False),               # needs observability.flops_per_sample
    # predicted-vs-measured capacity (PR 6 planner handoff): drift =
    # measured / predicted, the number that makes prediction rot visible
    "predicted_peak_hbm_gb": (_NUM, False),
    "measured_peak_hbm_gb": (_NUM, False),
    "hbm_drift": (_NUM, False),
    "predicted_boundary_ms": (_NUM, False),
    "measured_boundary_ms": (_NUM, False),
    "boundary_drift": (_NUM, False),
    # which BackendProfile priced the predictions: the planner defaults to
    # the RUNNING backend (matching what `measured_*` sees), but a config
    # `analysis.profile` overrides it — drift is only meaningful knowing
    # which one applied
    "predicted_profile": (str, False),
    "counters": (dict, True),           # resilience/compile-cache counters
}


def validate_event(event: dict) -> Optional[str]:
    """Return None when ``event`` is a valid window event, else a message
    naming the first problem.  Unknown extra keys are allowed (additive
    schema evolution); known keys must carry the declared type or null
    (optional fields only)."""
    if not isinstance(event, dict):
        return f"event is {type(event).__name__}, expected object"
    if event.get("schema") != SCHEMA_ID:
        return (f"schema is {event.get('schema')!r}, expected "
                f"{SCHEMA_ID!r}")
    if event.get("version") != SCHEMA_VERSION:
        return (f"version is {event.get('version')!r}, expected "
                f"{SCHEMA_VERSION}")
    for name, (typ, required) in FIELDS.items():
        if name not in event:
            return f"missing field {name!r}"
        val = event[name]
        if val is None:
            if required:
                return f"required field {name!r} is null"
            continue
        if typ is int:
            # bool is an int subclass; a true/false here is a bug
            if not isinstance(val, int) or isinstance(val, bool):
                return f"field {name!r} must be an integer, got {val!r}"
        elif not isinstance(val, typ):
            return (f"field {name!r} must be "
                    f"{getattr(typ, '__name__', typ)}, got {val!r}")
    if event["window_steps"] <= 0:
        return f"window_steps must be > 0, got {event['window_steps']}"
    if not (0 <= event["skipped"] <= event["window_steps"]):
        return (f"skipped ({event['skipped']}) outside "
                f"[0, window_steps={event['window_steps']}]")
    for k, v in event["counters"].items():
        if not isinstance(k, str) or (v is not None
                                      and not isinstance(v, _NUM)):
            return f"counters[{k!r}] must map str -> number, got {v!r}"
    return None


def validate_jsonl(path: str) -> list:
    """Validate every line of a JSONL event log.  Returns a list of
    ``(line_number, message)`` problems (empty = valid); an unreadable or
    EMPTY file is a problem — the CI smoke gate treats "no telemetry" as
    a failure, not a pass."""
    problems = []
    n = 0
    try:
        with open(path, "r") as f:
            for i, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                n += 1
                try:
                    event = json.loads(line)
                except ValueError as e:
                    problems.append((i, f"not valid JSON: {e}"))
                    continue
                msg = validate_event(event)
                if msg is not None:
                    problems.append((i, msg))
    except OSError as e:
        return [(0, f"cannot read {path!r}: {e}")]
    if n == 0:
        problems.append((0, f"{path!r} contains no events"))
    return problems
