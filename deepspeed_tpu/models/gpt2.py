"""GPT-2 causal LM with Megatron-style tensor parallelism.

The integration model for the engine — the reference's equivalent role is
Megatron-LM GPT-2 driven through the mpu bridge
(/root/reference/tests/model/Megatron_GPT2/ds_gpt2_test.sh:63-97,
run_perf_test.py:18-62 for the 1.5B/4B/8B/20B configs).  Weight-tied
vocab-parallel LM head feeds the vocab-parallel cross-entropy directly, so the
full-vocab logits are never materialised on one shard.

Engine protocol: ``init_params(rng)`` → global param pytree;
``partition_specs(params)`` → PartitionSpec tree; ``apply(params, tokens,
labels)`` → scalar mean loss (runs inside shard_map; see models/layers.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import layers as L
from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.parallel.topology import MODEL_AXIS


# Published GPT-2 size ladder incl. the reference's perf-test configs
# (/root/reference/tests/model/Megatron_GPT2/run_perf_test.py:18-62).
GPT2_SIZES = {
    "tiny":   dict(num_layers=2,  hidden_size=128,  num_heads=4,
                   max_seq_len=128, vocab_size=512),
    "small":  dict(num_layers=12, hidden_size=768,  num_heads=12),
    "medium": dict(num_layers=24, hidden_size=1024, num_heads=16),
    "large":  dict(num_layers=24, hidden_size=1536, num_heads=16),
    "xl-1.5b": dict(num_layers=48, hidden_size=1600, num_heads=25),
    # the reference's perf-test 1.5B shape (run_perf_test.py:18-31 uses 16
    # heads, not the published 25, so tensor parallelism divides evenly)
    "xl-1.5b-perf": dict(num_layers=48, hidden_size=1600, num_heads=16),
    "4b":     dict(num_layers=64, hidden_size=2304, num_heads=24),
    "8b":     dict(num_layers=72, hidden_size=3072, num_heads=24),
    "20b":    dict(num_layers=111, hidden_size=3808, num_heads=32),
}


@dataclasses.dataclass
class GPT2:
    """Callable model object satisfying the engine protocol."""
    config: T.TransformerConfig
    #: ZeRO-3 partition dims (set by the engine at stage 3; zero3.py).
    #: The block subtree is gathered per layer inside the scan, the rest
    #: at apply entry (transformer.zero3_enter).
    zero3_dims: object = None
    #: ZeRO-3 gather prefetch (set by the engine from overlap_comm): the
    #: block scan runs over layer pairs issuing both gathers up front, so
    #: the second layer's all-gather hides under the first layer's
    #: compute (transformer.scan_layers; two-layer transient memory).
    zero3_prefetch: bool = False

    @classmethod
    def from_size(cls, size: str, **overrides) -> "GPT2":
        kw = dict(GPT2_SIZES[size])
        kw.update(overrides)
        kw.setdefault("pre_ln", True)
        kw.setdefault("causal", True)
        return cls(T.TransformerConfig(**kw))

    def validate(self, mp_size: int = 1):
        """Engine hook: shape checks against the actual mp degree."""
        self.config.validate(mp_size)

    # ------------------------------------------------------------------ init
    def _init_blocks(self, rng):
        """Block-stack init hook (GPT2MoE overrides with expert params)."""
        return T.init_block_params(self.config, rng)

    def _block_specs(self):
        """Block-stack sharding hook."""
        return T.block_partition_specs()

    def init_params(self, rng):
        cfg = self.config
        cfg.validate()
        k_wte, k_wpe, k_blocks = jax.random.split(rng, 3)
        return {
            "wte": jax.random.normal(
                k_wte, (cfg.vocab_size, cfg.hidden_size), jnp.float32)
            * cfg.init_std,
            "wpe": jax.random.normal(
                k_wpe, (cfg.max_seq_len, cfg.hidden_size), jnp.float32)
            * cfg.init_std * 0.5,
            "blocks": self._init_blocks(k_blocks),
            "lnf_s": jnp.ones((cfg.hidden_size,), jnp.float32),
            "lnf_b": jnp.zeros((cfg.hidden_size,), jnp.float32),
        }

    def partition_specs(self, params=None):
        return {
            "wte": P(MODEL_AXIS, None),   # vocab-parallel
            "wpe": P(),
            "blocks": self._block_specs(),
            "lnf_s": P(), "lnf_b": P(),
        }

    def batch_specs(self, batch):
        """Engine hook: (tokens, labels) are both [B, T] — dim 1 is the
        sequence, so it shards over the context-parallel ring."""
        return T.token_batch_specs(batch)

    def zero3_min_dims(self, params):
        """Engine hook (stage 3): lowest partitionable dim per leaf.  Block
        leaves pin dim >= 1 — their dim 0 is the layer stack the scan
        consumes, which must stay whole on every shard."""
        md = jax.tree_util.tree_map(lambda _: 0, params)
        md["blocks"] = jax.tree_util.tree_map(lambda _: 1, md["blocks"])
        return md

    # --------------------------------------------------------------- forward
    def _stack(self, x, blocks, z3_dims=None):
        """Block-stack hook: returns (x, auxiliary loss term).  GPT2MoE
        overrides this with the MoE stack + weighted load-balance loss."""
        return T.stack_apply(
            x, blocks, self.config, z3_dims=z3_dims,
            z3_prefetch=getattr(self, "zero3_prefetch", False)), 0.0

    # ------------------------------------------------- serving (inference/)
    def kv_cache_dims(self, mp_size: int = 1):
        """(num_layers, local kv heads, head_dim) — what the serving KV
        cache must hold per token on one model shard."""
        cfg = self.config
        return (cfg.num_layers, cfg.num_heads // mp_size,
                cfg.hidden_size // cfg.num_heads)

    def apply_extend(self, params, tokens, k, v, pos, n_new, rows):
        """A block of NEW tokens forwarded against the KV page pool —
        prefill (``pos=0``), tail prefill over a reused prefix
        (``pos=reused``), and the speculative VERIFY step are all this
        one program shape (runs inside shard_map, like ``apply``).

        tokens: int32 [B, E] left-aligned new tokens (``n_new[b]``
        real); k/v: [L, R, n_local, d] flat page pools; pos: int32 [B]
        absolute position of each slot's first new token; rows: int32
        [B, cap] page-table row map.  Returns ``(logits [B, E,
        vocab/mp], k', v')`` — logits for EVERY block position (the
        verify step consumes all of them; prefill takes row
        ``n_new-1``); pad positions' logits are garbage the caller
        masks.  Pad K/V writes are dropped, never visible."""
        cfg = self.config
        B, E = tokens.shape
        x = L.vocab_parallel_embedding(tokens, params["wte"])
        wpe = params["wpe"]
        positions = jnp.clip(
            pos[:, None] + jnp.arange(E, dtype=jnp.int32)[None, :],
            0, wpe.shape[0] - 1)
        x = x + jnp.take(wpe, positions, axis=0).astype(x.dtype)
        x, k, v = T.stack_extend(x, params["blocks"], cfg, k, v, rows,
                                 pos, n_new)
        x = L.layer_norm(x, params["lnf_s"], params["lnf_b"], cfg.ln_eps)
        logits = L.vocab_parallel_logits(x, params["wte"])
        return logits, k, v

    def apply_decode(self, params, tokens, k, v, pos, active, rows,
                     ring: bool = False):
        """One incremental decode step (runs inside shard_map).

        tokens: int32 [B] (this step's input token per slot); k/v:
        [L, R, n_local, d] flat page pools; pos: int32 [B] absolute
        position the new token occupies; active: bool [B] (inactive
        slots write nothing and keep their state — their logits are
        computed but meaningless); rows: int32 [B, cap] page-table row
        map.  Returns ``(logits [B, vocab/mp], k', v', pos')`` with
        ``pos' = pos + active``."""
        cfg = self.config
        cap = rows.shape[1]
        R = k.shape[1]
        write_idx = (pos % cap) if ring else jnp.clip(pos, 0, cap - 1)
        wrow = jnp.take_along_axis(rows, write_idx[:, None], axis=1)[:, 0]
        wrow = jnp.where(active, wrow, R)     # inactive → drop row
        x = L.vocab_parallel_embedding(tokens[:, None], params["wte"])
        wpe = params["wpe"]
        prow = jnp.take(wpe, jnp.clip(pos, 0, wpe.shape[0] - 1), axis=0)
        x = x + prow[:, None].astype(x.dtype)
        x, k, v = T.stack_decode(x, params["blocks"], cfg, k, v, pos,
                                 rows, wrow, ring=ring)
        x = L.layer_norm(x, params["lnf_s"], params["lnf_b"], cfg.ln_eps)
        logits = L.vocab_parallel_logits(x[:, 0], params["wte"])
        return logits, k, v, pos + active.astype(jnp.int32)

    def apply(self, params, tokens, labels):
        """tokens, labels: int32 [B, T]; labels < 0 are ignored.  Returns the
        mean per-token LM loss (fp32 scalar, local to the DP shard — the
        engine pmean's across data) plus any stack auxiliary loss."""
        cfg = self.config
        T_len = tokens.shape[1]
        params, z3_deferred = T.zero3_enter(params, self.zero3_dims)
        x = L.vocab_parallel_embedding(tokens, params["wte"])
        x = x + L.seq_shard_positions(params["wpe"], T_len).astype(
            x.dtype)[None]
        x, aux = self._stack(x, params["blocks"],
                             z3_dims=z3_deferred.get("blocks"))
        x = L.layer_norm(x, params["lnf_s"], params["lnf_b"], cfg.ln_eps)
        logits = L.vocab_parallel_logits(x, params["wte"])
        loss = L.vocab_parallel_cross_entropy(logits, labels)
        return L.masked_mean_loss(loss, labels >= 0) + aux

    __call__ = apply
