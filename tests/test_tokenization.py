"""Wordpiece tokenization: trainer, tokenizer, offsets, vocab IO.

Pins the contracts the SQuAD pipeline depends on (squad.py,
tests/model/test_squad_f1.py): greedy longest-match-first with ``##``
continuations (BERT semantics), character offsets that index the ORIGINAL
text, deterministic training, and vocab.txt round-trips.
"""

import pytest

from deepspeed_tpu.tokenization import (BasicTokenizer, BertTokenizer,
                                        UNK_TOKEN, Vocab, WordpieceTokenizer,
                                        SPECIAL_TOKENS, train_wordpiece)


def test_basic_tokenizer_offsets_index_original_text():
    text = "  The cat, named O'Malley — slept. "
    toks, spans = BasicTokenizer().tokenize_with_offsets(text)
    # every span slices the surface form whose normalization is the token
    from deepspeed_tpu.tokenization import normalize_word
    for tok, (lo, hi) in zip(toks, spans):
        assert normalize_word(text[lo:hi]) == tok, (tok, text[lo:hi])
    assert toks[:3] == ["the", "cat", ","]
    assert "'" in toks            # punctuation split inside O'Malley
    assert toks[-1] == "."


def test_basic_tokenizer_strips_accents():
    toks, _ = BasicTokenizer().tokenize_with_offsets("Café déjà vu")
    assert toks == ["cafe", "deja", "vu"]


def test_wordpiece_greedy_longest_match():
    vocab = {t: i for i, t in enumerate(
        ["un", "##aff", "##able", "##ffa", "##ble", "unaff", "[UNK]"])}
    wp = WordpieceTokenizer(vocab)
    # longest first: 'unaff' beats 'un'
    assert wp.tokenize("unaffable") == ["unaff", "##able"]
    assert wp.tokenize("zzz") == [UNK_TOKEN]
    assert wp.tokenize("") == [UNK_TOKEN]


def test_trainer_learns_frequent_units_and_is_deterministic():
    corpus = ["the cat sat on the mat", "the bat and the rat sat"] * 8
    v1 = train_wordpiece(corpus, vocab_size=64)
    v2 = train_wordpiece(list(reversed(corpus)), vocab_size=64)
    assert v1.id_to_token == v2.id_to_token     # order-independent
    assert list(v1.id_to_token[:5]) == list(SPECIAL_TOKENS)
    tok = BertTokenizer(v1)
    # 'the' is the most frequent word: must become a single piece
    assert tok.tokenize("the") == ["the"]
    # frequent '##at' family merges
    assert any(t.endswith("at") for t in v1.id_to_token[5:])


def test_full_tokenizer_offsets_roundtrip_substrings():
    corpus = ["The Amazon River discharges more water than any other "
              "river on the planet."] * 4
    vocab = train_wordpiece(corpus, vocab_size=128)
    tok = BertTokenizer(vocab)
    text = "The Amazon River discharges water."
    pieces, spans = tok.tokenize_with_offsets(text)
    assert len(pieces) == len(spans)
    # concatenating the span substrings of one word reconstructs it
    joined = "".join(text[lo:hi] for lo, hi in spans)
    assert joined.replace(" ", "") == text.replace(" ", "").replace(
        ".", "") + "."
    # piece surfaces match their spans (modulo ## and case)
    for p, (lo, hi) in zip(pieces, spans):
        if p == UNK_TOKEN:
            continue
        assert text[lo:hi].lower() == p.lstrip("#") or \
            text[lo:hi].lower() == p


def test_vocab_save_load_roundtrip(tmp_path):
    v = train_wordpiece(["hello world hello"], vocab_size=32)
    p = tmp_path / "vocab.txt"
    v.save(str(p))
    v2 = Vocab.load(str(p))
    assert v2.id_to_token == v.id_to_token
    assert v2.id("hello") == v.id("hello")
    assert v2.id("zzzz-not-there") == v2.token_to_id[UNK_TOKEN]


def test_wordpiece_memo_cache_is_transparent():
    """The word→pieces memo must never change results — cached and
    uncached calls agree on every input class (match, UNK, unicode,
    over-length), and results are fresh lists (caller mutation safe)."""
    corpus = ["the cat sat on the mat", "unaffable runners ran",
              "café déjà vu naïve", "растение растёт"] * 4
    vocab = train_wordpiece(corpus, vocab_size=160)
    cached = WordpieceTokenizer(vocab.token_to_id)
    cold = WordpieceTokenizer(vocab.token_to_id, cache_size=0)
    words = ([w for t in corpus for w in t.split()]
             + ["zzz", "q", "", "a" * 101, "caférastение"]) * 2
    for w in words:           # second sweep hits the memo
        a, b = cached.tokenize(w), cold.tokenize(w)
        assert a == b, (w, a, b)
        a.append("mutated")   # must not poison the cache
        assert cached.tokenize(w) == b, w


def test_encode_uses_unk_for_unknown():
    v = train_wordpiece(["aaa bbb aaa"], vocab_size=16)
    tok = BertTokenizer(v)
    ids = tok.encode("aaa qqq")
    assert ids[0] != v.token_to_id[UNK_TOKEN]
    # 'q' never appeared: whole word falls to [UNK]
    assert v.token_to_id[UNK_TOKEN] in ids
