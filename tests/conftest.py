"""Test rig: run everything on a virtual 8-device CPU mesh.

The reference tests "multi-node" semantics by forking N local processes
(/root/reference/tests/unit/common.py:14-100).  On TPU/XLA we get the same
coverage cheaper: ``--xla_force_host_platform_device_count=8`` gives 8 fake
devices in one process, so sharding, ZeRO partition math and collectives all
execute for real.

Environment wrinkle: this image's sitecustomize registers the experimental
``axon`` TPU PJRT plugin at interpreter start (PALLAS_AXON_POOL_IPS set), and
once registered, selecting the cpu platform hangs.  The registration guard is
the env var, so the only reliable way to get a CPU-only test interpreter is to
re-exec with the var cleared before python starts.  This makes a plain
``python -m pytest tests/`` work regardless of the caller's environment.
"""

import os
import sys

if os.environ.get("_DSTPU_TEST_ENV") != "1":
    env = dict(os.environ)
    env["_DSTPU_TEST_ENV"] = "1"
    env["PALLAS_AXON_POOL_IPS"] = ""      # skip axon PJRT registration
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
    env.setdefault("JAX_ENABLE_X64", "0")
    os.execve(sys.executable,
              [sys.executable, "-m", "pytest"] + sys.argv[1:], env)

import tempfile  # noqa: E402

# flight-recorder dumps from bare-watchdog tests (no engine-configured
# dump dir) must not litter the checkout: route the env-fallback dump
# directory to a throwaway location (observability/flightrec.py resolve
# order: configured dir > this env var > cwd)
os.environ.setdefault("DSTPU_FLIGHTREC_DIR",
                      tempfile.mkdtemp(prefix="dstpu_flightrec_test_"))

import pytest  # noqa: E402  (post-re-exec: safe to import)

import deepspeed_tpu  # noqa: E402,F401  (installs the jax compat shims —
# tests use jax.shard_map directly, which older jax only has under
# jax.experimental; deepspeed_tpu.compat bridges both spellings)


def pytest_collection_modifyitems(config, items):
    """Tier markers by location: tests/model/ is the 300-step convergence
    tier (slow); everything else is the fast tier.  `-m fast` gives <5 min
    signal; CI still runs the full suite (reference CI split:
    azure-pipelines.yml unit vs model stages)."""
    for item in items:
        path = str(item.fspath).replace(os.sep, "/")
        if "/tests/model/" in path:
            item.add_marker(pytest.mark.slow)
        elif (item.get_closest_marker("slow") is None
              and item.get_closest_marker("distributed") is None):
            item.add_marker(pytest.mark.fast)
