"""GPT-2 with pipeline-parallel layer stages (GPipe schedule, ``pipe`` axis).

Beyond-reference model variant (the reference has no pipeline engine): the
same parameters and math as ``models.gpt2.GPT2``, but the stacked block
parameters shard their layer dimension over ``pipe`` and the stack executes
through ``parallel.pipeline.pipeline_apply``.  Embeddings and the final
LayerNorm/head are replicated across stages; the loss is masked to the last
stage and psum'd, so stage-replicated parameter gradients arrive as
per-stage partial sums the engine completes over ``pipe``.

Composes with tensor parallelism (blocks sharded over BOTH pipe and model),
data parallelism, context parallelism (ring attention inside the stage
body), ZeRO-1 (per-stage [S, local] flat masters), and checkpointing
(per-stage model files).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import layers as L
from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.models.gpt2 import GPT2
from deepspeed_tpu.parallel import pipeline as pipe_mod
from deepspeed_tpu.parallel.topology import PIPE_AXIS


@dataclasses.dataclass
class GPT2Pipelined(GPT2):
    """``num_micro_batches`` micro-batches stream through the stage ring per
    forward; the per-shard batch must divide evenly.  ``schedule`` selects
    the pipeline schedule: ``"gpipe"`` (all forwards, then autodiff
    backward; head sharded over stages) or ``"1f1b"`` (interleaved
    one-forward-one-backward with activation recompute — in-flight
    activations bounded by ``2·pp-1`` instead of the micro-batch count;
    the engine's ``pipeline_schedule`` config key overrides this field)."""
    num_micro_batches: int = 2
    schedule: str = "gpipe"

    @classmethod
    def from_size(cls, size: str, num_micro_batches: int = 2,
                  schedule: str = "gpipe", **overrides):
        base = GPT2.from_size(size, **overrides)
        return cls(config=base.config, num_micro_batches=num_micro_batches,
                   schedule=schedule)

    def partition_specs(self, params=None):
        specs = super().partition_specs(params)
        # layer stacks: leading (layer) dim over the pipe axis, everything
        # else (incl. model-axis TP dims) unchanged
        specs["blocks"] = {
            k: P(PIPE_AXIS, *s[1:]) for k, s in specs["blocks"].items()
        }
        return specs

    def apply(self, params, tokens, labels):
        cfg = self.config
        B, T_len = tokens.shape
        m = self.num_micro_batches
        if B % m:
            raise ValueError(
                f"per-shard batch {B} not divisible by "
                f"num_micro_batches={m}")
        if self.schedule not in ("gpipe", "1f1b"):
            raise ValueError(
                f"unknown pipeline schedule {self.schedule!r} "
                "(expected 'gpipe' or '1f1b')")
        params, z3_deferred = T.zero3_enter(params, self.zero3_dims)
        z3_block_dims = z3_deferred.get("blocks")
        x = L.vocab_parallel_embedding(tokens, params["wte"])
        x = x + L.seq_shard_positions(params["wpe"], T_len).astype(
            x.dtype)[None]
        x_micro = x.reshape(m, B // m, T_len, x.shape[-1])

        if self.schedule == "1f1b":
            # interleaved schedule: the per-micro head runs inside the
            # pipeline scan, 1/pp-sharded over the micro-batch when
            # mb % pp == 0 (replicated fallback otherwise) — see
            # parallel.pipeline._run_1f1b
            labels_micro = labels.reshape(m, B // m, T_len)
            count = jnp.sum((labels >= 0).astype(jnp.float32))
            head_params = {"lnf_s": params["lnf_s"],
                           "lnf_b": params["lnf_b"],
                           "wte": params["wte"]}

            def stage_1f1b(blocks, u):
                return self._pipe_stack(u, blocks,
                                        z3_dims=z3_block_dims)   # (y, aux)

            def head_1f1b(hp, y, ys):
                h = L.layer_norm(y, hp["lnf_s"], hp["lnf_b"], cfg.ln_eps)
                logits = L.vocab_parallel_logits(h, hp["wte"])
                ce = L.vocab_parallel_cross_entropy(logits, ys)
                mask = (ys >= 0).astype(jnp.float32)
                return jnp.sum(ce * mask)

            return pipe_mod.pipeline_1f1b_loss(
                stage_1f1b, head_1f1b, params["blocks"], head_params,
                x_micro, labels_micro, count, with_aux=True)

        def stage_fn(u):
            # inside shard_map the blocks leaf is this stage's LOCAL
            # [L/pp, ...] slice; the stack hook scans exactly those layers
            # (with the configured remat policy; under ZeRO-3 each layer's
            # data-partitioned weights gather inside the scan body)
            return self._pipe_stack(u, params["blocks"],
                                    z3_dims=z3_block_dims)

        # head sharded over the pipe stages: each computes LN + vocab
        # logits + CE for its 1/pp batch slice instead of every stage
        # repeating the full O(B·T·V·H) head; the psum'd scalar stays
        # pipe-uniform, so replicated-leaf grads still arrive as
        # per-stage partials the engine completes over 'pipe'
        def head_fn(xs, ys):
            h = L.layer_norm(xs, params["lnf_s"], params["lnf_b"],
                             cfg.ln_eps)
            logits = L.vocab_parallel_logits(h, params["wte"])
            ce = L.vocab_parallel_cross_entropy(logits, ys)
            mask = (ys >= 0).astype(jnp.float32)
            return jnp.sum(ce * mask), jnp.sum(mask)

        mb = B // m
        pp_sz = L.axis_size_or_1(PIPE_AXIS)
        if pp_sz > 1 and mb % pp_sz == 0:
            # scatter-collect (r5, VERDICT r4 weak #6): the boundary moves
            # each stage's 1/pp batch slice ONCE (psum_scatter) instead of
            # psum-replicating the full [m, mb, T, H] output volume; the
            # already-sharded head then consumes the slices directly
            x_loc, aux = pipe_mod.pipeline_apply(
                x_micro, stage_fn, with_aux=True, collect="scatter")
            aux = aux / m
            sl = mb // pp_sz
            stage = jax.lax.axis_index(PIPE_AXIS)
            lab_loc = jax.lax.dynamic_slice_in_dim(
                labels.reshape(m, mb, T_len), stage * sl, sl, axis=1)
            x_loc = x_loc.reshape(m * sl, T_len, x_loc.shape[-1])
            lab_loc = lab_loc.reshape(m * sl, T_len)
            return pipe_mod.pipe_scattered_loss(x_loc, lab_loc,
                                                head_fn) + aux

        if pp_sz > 1:
            pipe_mod.warn_slow_path_once(
                "gpipe_full_collect",
                f"GPipe is using the full psum output collect (micro-batch "
                f"size {mb} not divisible by pp={pp_sz}): the boundary "
                f"moves the whole [m, mb, T, H] activation volume to every "
                f"stage instead of 1/pp scatter slices — pad or resize the "
                f"micro-batch to a multiple of pp for collect='scatter'")
        x, aux = pipe_mod.pipeline_apply(x_micro, stage_fn, with_aux=True)
        # per-micro aux terms are means over their own tokens: average over
        # micros so aux_weight's meaning is independent of m (the LM loss
        # is likewise a mean over all tokens)
        aux = aux / m
        x = x.reshape(B, T_len, x.shape[-1])
        return pipe_mod.pipe_sharded_loss(x, labels, head_fn) + aux

    def _pipe_stack(self, u, blocks, z3_dims=None):
        """Stage-stack hook: returns (y, aux scalar).  The MoE variant
        overrides this with the expert stack + load-balance aux."""
        return T.stack_apply(
            u, blocks, self.config, z3_dims=z3_dims,
            z3_prefetch=getattr(self, "zero3_prefetch", False)), 0.0

    __call__ = apply
