"""Resilience observability counters.

One process-wide :class:`Counters` instance (``COUNTERS``) accumulates the
degradation events the resilience subsystem absorbs — restarts, skipped
non-finite steps, storage retries, watchdog near-misses/fires, preemption
signals.  The engine exports them through the existing TensorBoard path
(``Train/Resilience/*`` scalars, engine._post_boundary_bookkeeping) and via
``engine.resilience_counters()``, so a job that is silently limping —
retrying every save, skipping every tenth step — is observable instead of
merely "still running" (docs/resilience.md "Observability").
"""

from __future__ import annotations

from dataclasses import dataclass, fields


@dataclass
class Counters:
    #: successful resume-from-checkpoint restores (driver.run_resumable)
    restarts: int = 0
    #: preemption signals / sentinel observations (preempt.PreemptionHandler)
    preemptions: int = 0
    #: optimizer boundaries skipped by the NaN/Inf sentinel
    #: (resilience.nan_sentinel; engine._post_boundary_bookkeeping)
    nan_skips: int = 0
    #: storage operations retried after a transient error (retry.io_retry)
    io_retries: int = 0
    #: armed operations that finished but consumed more than
    #: ``near_miss_frac`` of the watchdog deadline (watchdog.Watchdog)
    watchdog_near_misses: int = 0
    #: watchdog deadline expiries (stack dump emitted; process aborted when
    #: ``watchdog_abort`` is set)
    watchdog_fires: int = 0
    #: wall-clock seconds of the most recent checkpoint restore
    #: (engine.load_checkpoint) — the resume-latency half of fast resume
    restore_seconds: float = 0.0
    #: persistent-compilation-cache hits/misses (utils/compile_cache.py;
    #: hits > 0 on a relaunch means the restart skipped recompilation)
    compile_cache_hits: int = 0
    compile_cache_misses: int = 0

    def as_dict(self) -> dict:
        return {f.name: getattr(self, f.name) for f in fields(self)}

    def reset(self) -> None:
        for f in fields(self):
            setattr(self, f.name, f.default)


#: process-wide counter instance (tests reset it between scenarios)
COUNTERS = Counters()
