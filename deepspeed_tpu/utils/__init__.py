from deepspeed_tpu.utils.timer import (  # noqa: F401
    SynchronizedWallClockTimer,
    ThroughputTimer,
)
