"""Serving fleet: a least-loaded router over N InferenceEngine replicas.

The layer above the replica (ROADMAP item 1, docs/inference.md "Fleet
serving").  One :class:`FleetRouter` drives N replicas — each an
:class:`~deepspeed_tpu.inference.engine.InferenceEngine` with its own
:class:`~deepspeed_tpu.inference.scheduler.ContinuousScheduler` on its
own driver thread — and owns the fleet-level decisions:

* **Admission** — every request lands on the least-loaded HEALTHY
  replica, scored from the replica's live ``/metrics`` gauges (slots in
  use, queue depth, free pages — the PR 14 endpoints are the router's
  sensor, scraped over real HTTP when the replica serves a port) plus
  the router's own in-flight accounting.  With prefix **affinity** on,
  a request whose page-aligned prompt prefix was already served goes
  back to the replica whose page-hash index holds those pages — the
  PR 13 reuse keeps paying at fleet scale instead of being diluted
  1/N by round-robin.
* **Eviction** — the moment a replica's ``/healthz`` turns 503 (its
  serve watchdog fired: alive-but-wedged is replaceable) the router
  evicts it and RESUBMITS its in-flight requests to the survivors,
  each with its ORIGINAL arrival timestamp (the
  :meth:`~deepspeed_tpu.inference.scheduler.ContinuousScheduler.
  evacuate` contract) — queue-wait and TTFT percentiles keep measuring
  from the user's submit.  Greedy decode re-derives the identical
  token stream from the prompt, so eviction is invisible in the
  outputs (pinned end-to-end by the chaos tests and the bench).
* **Disaggregation** — with a prefill pool configured
  (``inference.fleet.prefill_replicas``), prefill and decode run on
  SEPARATE replicas: a prefill replica runs the extend program, its
  slot's written KV page rows ship as a chunk-container artifact
  (``checkpoint.write_kv_handoff`` — atomic seal, positioned reads,
  ``io_retry``, named corruption errors), and a decode replica imports
  them into its own page pool and continues BYTE-IDENTICALLY (the
  PR 13 bitwise-page contract: same weights + same tokens ⇒ same page
  bytes).  Long prefills then never sit inside the decode pool's
  token loop — the decode ITL tail stops paying for other tenants'
  prompts.

Telemetry: one ``dstpu.telemetry.router`` v1 line per router window
(fleet tokens/s, per-replica load map, evictions/resubmits/handoffs,
affinity hits) interleaved with each replica's serve/request events on
one validator-clean stream; ``inference.fleet.health_port`` serves the
router's own /healthz /status /metrics.

Scale model: this module is the IN-PROCESS fleet (replicas as threads
over one host's devices — the bench and CI shape, and the building
block for one-host-many-chips serving).  The decisions it encodes
(admission scoring off /metrics, 503-eviction, timestamp-preserving
resubmission, artifact-based KV handoff) are exactly the cross-host
protocol; a multi-host front-end speaks the same endpoints over the
network.
"""

from __future__ import annotations

import logging
import os
import queue as queue_mod
import tempfile
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import List, Optional

import numpy as np

from deepspeed_tpu.analysis import concurrency, lockwatch
from deepspeed_tpu.inference import kvcache
from deepspeed_tpu.inference.scheduler import (ContinuousScheduler,
                                               KVHandoff, Request,
                                               RequestResult,
                                               _check_request,
                                               greedy_sampler,
                                               latency_summary, percentile,
                                               request_latency_ms)

logger = logging.getLogger(__name__)

#: affinity index depth: page-chain hashes recorded per admission (a
#: deeper shared prefix than this still routes, just on its first pages)
_AFFINITY_MAX_PAGES = 16

#: max_age sentinel meaning "serve ANY cached probe result, never
#: scrape" — the only mode allowed under the router lock (the poll
#: loop owns refreshing; a missing cache still scrapes live, which
#: serve() prevents by priming every replica's caches up front)
_CACHE_ANY_AGE = float("inf")

#: affinity index size bound (insertion-ordered, oldest evicted): the
#: replicas' own prefix caches LRU pages out, so unbounded router-side
#: entries would grow forever while going stale — a bounded map keeps
#: the hot prefixes routable and the memory O(1) in requests served
_AFFINITY_MAX_ENTRIES = 4096


class _Flight:
    """Router-side record of one in-flight request — the ownership token
    the eviction path pivots on.  A completion is only accepted from the
    replica that CURRENTLY owns the flight: a wedged replica that
    un-sticks after eviction reports into the void instead of
    double-completing a resubmitted request."""

    __slots__ = ("req", "t_enqueue", "owner", "phase")

    def __init__(self, req, t_enqueue, owner, phase):
        self.req = req
        self.t_enqueue = t_enqueue
        self.owner = owner            # Replica currently serving it
        self.phase = phase            # "prefill" | "decode" | "mixed"


class Replica:
    """One serving replica under the router: engine + scheduler +
    observability endpoints + a driver thread.

    ``role`` is ``"mixed"`` (prefill AND decode — the plain fleet),
    ``"decode"`` (imports KV handoffs, never prefills) or ``"prefill"``
    (prefills + exports, never decodes).  The driver thread owns every
    engine dispatch; the router only touches the thread-safe inbox and
    the read-only load signals."""

    def __init__(self, rid: int, engine, router, role: str = "mixed",
                 health_port: int = 0, telemetry=None):
        from deepspeed_tpu.inference import observability as serve_obs
        self.rid = int(rid)
        self.engine = engine
        self.router = router
        self.role = role
        self.inbox = queue_mod.Queue()
        self.stop = threading.Event()
        self.dead = False             # set by the router at eviction
        self.error = None
        self._health = None           # (monotonic ts, bool) probe cache
        self._load = None             # (monotonic ts, dict) probe cache
        self.telemetry = telemetry    # ServeTelemetry (decode/mixed)
        self.obs = None
        if health_port or serve_obs.configured(engine.config):
            self.obs = serve_obs.ServeObservability(
                engine, telemetry=telemetry, port=health_port or None)
            if telemetry is not None and telemetry.observability is None:
                telemetry.observability = self.obs
        self.sched = None
        if role != "prefill":
            self.sched = ContinuousScheduler(
                engine, sampler=router.sampler,
                on_complete=self._on_complete)
            if self.obs is not None:
                self.obs.note_scheduler(self.sched)
        self.thread = threading.Thread(
            target=self._drive_prefill if role == "prefill" else self._drive,
            daemon=True, name=f"dstpu-replica-{rid}-{role}")

    # ------------------------------------------------------------ signals
    @property
    def port(self) -> Optional[int]:
        return self.obs.port if self.obs is not None else None

    def healthy(self, max_age: Optional[float] = None) -> bool:
        """The router's eviction signal: scraped over real HTTP when the
        replica serves ``/healthz`` (the protocol a cross-host router
        speaks), read in-process otherwise.  An errored driver thread is
        unhealthy regardless.  ``max_age`` serves a cached verdict (the
        admission path must never block on a probe under the router
        lock); the poll loop passes None to force a fresh scrape."""
        if self.dead or self.error is not None:
            return False
        now = time.monotonic()
        if max_age is not None and self._health is not None \
                and now - self._health[0] <= max_age:
            return self._health[1]
        ok = self._healthy_now()
        self._health = (now, ok)
        return ok

    def _healthy_now(self) -> bool:
        if self.port is not None:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{self.port}/healthz")
                with urllib.request.urlopen(req, timeout=2) as r:
                    return r.getcode() == 200
            except urllib.error.HTTPError as e:
                return e.code == 200
            except Exception:
                return False          # unreachable endpoint = not healthy
        if self.obs is not None:
            return self.obs.healthy()
        wd = self.engine.watchdog
        return not (wd is not None and wd.fired)

    def load(self, max_age: Optional[float] = None) -> dict:
        """Normalized load gauges — the admission score's inputs.  Over
        HTTP (``/metrics`` parsed as Prometheus text) when the replica
        serves a port, else the same ``health_metrics()`` dict the
        endpoint would render — one source either way.  Cached like
        :meth:`healthy` (same reason)."""
        now = time.monotonic()
        if max_age is not None and self._load is not None \
                and now - self._load[0] <= max_age:
            return self._load[1]
        out = {"slots_total": self.engine.num_slots, "slots_in_use": 0,
               "queue_depth": 0, "free_pages":
                   self.engine.pool.gauges()["free_pages"]}
        metrics = None
        if self.port is not None:
            try:
                from deepspeed_tpu.observability.health import \
                    parse_prometheus_text
                req = urllib.request.Request(
                    f"http://127.0.0.1:{self.port}/metrics")
                with urllib.request.urlopen(req, timeout=2) as r:
                    parsed = parse_prometheus_text(r.read().decode())
                metrics = {k[len("dstpu_"):] if k.startswith("dstpu_")
                           else k: v for k, v in parsed.items()}
            except Exception as e:
                logger.debug("replica %d /metrics scrape failed: %s",
                             self.rid, e)
        if metrics is None and self.obs is not None:
            metrics = self.obs.health_metrics()
        if metrics:
            for name, key in (("slots_in_use", "slots_in_use"),
                              ("queue_depth", "queue_depth"),
                              ("free_pages", "pool_free_pages"),
                              ("slots_total", "slots_total")):
                val = metrics.get(key)
                if isinstance(val, (int, float)):
                    out[name] = int(val)
        elif self.sched is not None:
            out["slots_in_use"] = self.sched.active
            out["queue_depth"] = self.sched.pending
        self._load = (now, out)
        return out

    # ------------------------------------------------------------ driving
    def _on_complete(self, result: RequestResult) -> None:
        if self.telemetry is not None:
            self.telemetry.on_complete(result)
        self.router._complete(self, result)

    def _drain_inbox(self) -> int:
        moved = 0
        while True:
            try:
                item = self.inbox.get_nowait()
            except queue_mod.Empty:
                return moved
            moved += 1
            if isinstance(item, KVHandoff):
                self.sched.submit_handoff(item)
            elif item[0] == "kvh":
                # a sealed handoff artifact: positioned reads + named
                # corruption errors (checkpoint.read_kv_handoff); the
                # file is consumed — deleted once the rows are in memory.
                # A corrupt/torn artifact fails THIS request loudly
                # (back to the router for a fresh prefill) — it must
                # never kill the replica, and never import garbage.
                from deepspeed_tpu import checkpoint
                _, path, rid = item
                try:
                    meta, k, v = checkpoint.read_kv_handoff(path)
                except checkpoint.CheckpointReadError as e:
                    logger.error(
                        "replica %d: KV handoff for request %d is "
                        "corrupt (%s) — returning it to the router for "
                        "a fresh prefill", self.rid, rid, e)
                    self.router._handoff_read_failed(self, rid)
                    try:
                        os.remove(path)
                    except OSError:
                        pass
                    continue
                self.sched.submit_handoff(KVHandoff(
                    req=Request(rid=int(meta["rid"]),
                                prompt=list(meta["prompt"]),
                                max_new_tokens=int(meta["max_new_tokens"]),
                                eos_id=meta.get("eos_id")),
                    prompt=list(meta["prompt"]),
                    first_token=int(meta["first_token"]),
                    k=k, v=v, n_tokens=int(meta["n_tokens"]),
                    t_enqueue=float(meta["t_enqueue"]),
                    t_admit=float(meta["t_admit"]),
                    t_first_token=float(meta["t_first_token"]),
                    path=path))
                try:
                    os.remove(path)
                except OSError:
                    pass
            else:
                req, t_enq = item
                self.sched.submit(req, now=t_enq)

    def _drive(self) -> None:
        """Decode/mixed driver: drain the inbox into the scheduler, step
        while there is work, park briefly when idle."""
        try:
            while not self.stop.is_set():
                moved = self._drain_inbox()
                if self.sched.pending or self.sched.active:
                    stats = self.sched.step()
                    if self.telemetry is not None:
                        self.telemetry.on_iteration(self.sched, stats)
                elif not moved:
                    time.sleep(self.router.idle_s)
        except BaseException as e:  # noqa: BLE001 - reported via health
            self.error = e
            logger.error("replica %d driver died: %s", self.rid, e)

    def _drive_prefill(self) -> None:
        """Prefill driver: admit → first token → export the slot's KV
        rows → seal the handoff artifact → hand back to the router.
        One request at a time through slot 0 — prefill is a single
        full-width dispatch, so slots buy nothing here."""
        eng = self.engine
        try:
            while not self.stop.is_set():
                try:
                    item = self.inbox.get(timeout=self.router.idle_s)
                except queue_mod.Empty:
                    continue
                req, t_enq = item
                t_admit = time.perf_counter()
                res = eng.admit(0, req.prompt, req.max_new_tokens)
                if res is None:
                    # transient pool refusal (overcommitted pool): back
                    # off and retry — nothing was allocated
                    self.inbox.put(item)
                    time.sleep(self.router.idle_s)
                    continue
                logits, reused = res
                tok0 = self.router.sampler(logits)
                t_first = time.perf_counter()
                pages = len(eng.pool.slot_pages(0))
                if (req.eos_id is not None and tok0 == req.eos_id) \
                        or req.max_new_tokens <= 1:
                    # one-token request: nothing to hand off — complete
                    # directly (the decode pool would only evict it)
                    eng.release(0)
                    self.router._complete(self, RequestResult(
                        rid=req.rid, tokens=[tok0],
                        finish_reason=("eos" if req.eos_id is not None
                                       and tok0 == req.eos_id
                                       else "length"),
                        ttft_s=t_first - t_enq, itl_s=[],
                        prompt_len=len(req.prompt),
                        queue_wait_s=t_admit - t_enq,
                        prefill_s=t_first - t_admit,
                        finished_ts=time.time(), slot=0,
                        prefix_hit=reused > 0, reused_tokens=reused,
                        pages_mapped=pages))
                    continue
                k, v, n_tokens = eng.export_kv(0)
                eng.release(0)
                path = os.path.join(
                    self.router.handoff_dir,
                    f"handoff_rid{req.rid}_{self.rid}.kvh")
                from deepspeed_tpu import checkpoint
                checkpoint.write_kv_handoff(
                    path, k=k, v=v,
                    meta={"rid": req.rid, "prompt": list(req.prompt),
                          "max_new_tokens": req.max_new_tokens,
                          "eos_id": req.eos_id, "first_token": int(tok0),
                          "n_tokens": n_tokens, "t_enqueue": t_enq,
                          "t_admit": t_admit, "t_first_token": t_first,
                          "reused_tokens": int(reused)})
                self.router._handoff(self, req, t_enq, path)
        except BaseException as e:  # noqa: BLE001 - reported via health
            self.error = e
            logger.error("prefill replica %d driver died: %s",
                         self.rid, e)

    def close(self) -> None:
        """Stop the driver thread and ONLY THEN tear down the
        endpoints: the drive loop reads ``self.obs``/``self.sched``
        mid-tick, so closing the observability server under a live
        driver races a completion against a dead scheduler.  Bounded
        join — a wedged thread is daemonic and dies with the process.
        Never called FROM the driver thread (joining yourself
        deadlocks), which the current-thread guard enforces."""
        self.stop.set()
        if self.thread.is_alive() \
                and self.thread is not threading.current_thread():
            self.thread.join(timeout=10)
        if self.obs is not None:
            self.obs.close()


class RouterTelemetry:
    """Windowed ``dstpu.telemetry.router`` emitter over one (possibly
    shared) JSONL sink — the fleet record next to each replica's serve
    events."""

    def __init__(self, router, sink=None):
        from deepspeed_tpu.observability import schema
        self.router = router
        self.sink = sink
        self.schema = schema
        self.window = 0
        self.last_event = None
        self._tokens_prev = 0
        self._t_prev = time.perf_counter()

    def emit(self) -> dict:
        r = self.router
        now = time.perf_counter()
        with r._lock:  # dstpu-lock: FleetRouter._lock
            tokens = r.tokens_out
            completed = len(r.results)
            ttft, _, queue_wait = request_latency_ms(r.results)
            snap = {
                "submitted": r.submitted, "inflight": len(r._inflight),
                "queued": len(r._queue), "evictions": r.evictions,
                "resubmits": r.resubmits, "handoffs": r.handoffs,
                "affinity_hits": r.affinity_hits,
            }
        elapsed = now - self._t_prev
        delta = tokens - self._tokens_prev
        self.window += 1
        per_replica = {}
        healthy = 0
        for rep in r.all_replicas:
            ok = rep.healthy(max_age=_CACHE_ANY_AGE)
            healthy += ok
            per_replica[str(rep.rid)] = dict(
                rep.load(max_age=_CACHE_ANY_AGE), healthy=bool(ok),
                role=rep.role, port=rep.port)
        event = {
            "schema": self.schema.ROUTER_SCHEMA_ID,
            "version": self.schema.ROUTER_SCHEMA_VERSION,
            "ts": time.time(),
            "window": self.window,
            "n_replicas": len(r.all_replicas),
            "healthy_replicas": int(healthy),
            "prefill_replicas": len(r.prefill_pool),
            "requests_submitted": snap["submitted"],
            "requests_completed": completed,
            "requests_inflight": snap["inflight"],
            "queue_depth": snap["queued"],
            "tokens_out": tokens,
            "tokens_per_sec": (round(delta / elapsed, 3)
                               if elapsed > 0 else None),
            "evictions": snap["evictions"],
            "resubmits": snap["resubmits"],
            "handoffs": snap["handoffs"],
            "affinity_hits": snap["affinity_hits"],
            "ttft_p50_ms": percentile(ttft, 50),
            "ttft_p99_ms": percentile(ttft, 99),
            "queue_wait_p50_ms": percentile(queue_wait, 50),
            "queue_wait_p99_ms": percentile(queue_wait, 99),
            "per_replica": per_replica,
        }
        self.last_event = event
        self._tokens_prev = tokens
        self._t_prev = now
        if self.sink is not None:
            self.sink.emit(event)
        return event


class RouterObservability:
    """The router's own live endpoints (``inference.fleet.health_port``)
    — the HealthServer telemetry contract over fleet-level state, so
    one curl answers "is the FLEET serving" next to each replica's
    per-process endpoints."""

    def __init__(self, router, port: int):
        from deepspeed_tpu.observability import health as health_mod
        self.router = router
        self.health = None
        try:
            self.health = health_mod.HealthServer(port, self, rank=0)
        except OSError as e:
            logger.warning("fleet router: health endpoints DISABLED — "
                           "could not bind port %d: %s", port, e)

    @property
    def port(self) -> Optional[int]:
        return self.health.port if self.health is not None else None

    def healthy(self) -> bool:
        """The fleet serves as long as ONE replica is healthy."""
        return any(rep.healthy(max_age=_CACHE_ANY_AGE)
                   for rep in self.router.all_replicas)

    def health_snapshot(self) -> dict:
        r = self.router
        ok = self.healthy()
        states = {str(rep.rid): {"role": rep.role, "port": rep.port,
                                 "healthy": rep.healthy(
                                     max_age=_CACHE_ANY_AGE)}
                  for rep in r.all_replicas}
        with r._lock:  # dstpu-lock: FleetRouter._lock
            out = {
                "healthy": ok,
                "n_replicas": len(r.all_replicas),
                "prefill_replicas": len(r.prefill_pool),
                "requests_submitted": r.submitted,
                "requests_completed": len(r.results),
                "requests_inflight": len(r._inflight),
                "queue_depth": len(r._queue),
                "evictions": r.evictions,
                "resubmits": r.resubmits,
                "handoffs": r.handoffs,
                "affinity_hits": r.affinity_hits,
            }
        out["replicas"] = states
        if r.telemetry is not None:
            out["last_window"] = r.telemetry.last_event
        return out

    def health_metrics(self) -> dict:
        from deepspeed_tpu.observability import health as health_mod
        r = self.router
        ok = self.healthy()
        n_healthy = sum(rep.healthy(max_age=_CACHE_ANY_AGE)
                        for rep in r.all_replicas)
        with r._lock:  # dstpu-lock: FleetRouter._lock
            out = {
                "healthy": 1 if ok else 0,
                "n_replicas": len(r.all_replicas),
                "healthy_replicas": int(n_healthy),
                "prefill_replicas": len(r.prefill_pool),
                "requests_submitted": r.submitted,
                "requests_completed": len(r.results),
                "requests_inflight": len(r._inflight),
                "queue_depth": len(r._queue),
                "tokens_out": r.tokens_out,
                "evictions": r.evictions,
                "resubmits": r.resubmits,
                "handoffs": r.handoffs,
                "affinity_hits": r.affinity_hits,
                "process_uptime_s": round(health_mod.process_uptime_s(),
                                          3),
                "replica_generation": health_mod.replica_generation(),
            }
        last = r.telemetry.last_event if r.telemetry is not None else None
        if last:
            for name in ("tokens_per_sec", "ttft_p50_ms", "ttft_p99_ms",
                         "queue_wait_p50_ms", "queue_wait_p99_ms"):
                val = last.get(name)
                if isinstance(val, (int, float)):
                    out[f"window_{name}"] = val
        return out

    def close(self) -> None:
        if self.health is not None:
            self.health.close()


class FleetRouter:
    """Least-loaded router over N serving replicas (module docstring).

    ``engines`` become the decode/mixed pool; ``prefill_engines`` (each
    built with ``inference.fleet.disaggregate: true``, like the decode
    engines) form the prefill pool — non-empty means disaggregated
    serving with KV handoff.  All engines must hold IDENTICAL weights
    (same checkpoint): greedy identity across replicas — the property
    eviction/resubmission and handoff both lean on — is only as true as
    the weights are.

    Knobs resolve config-first (the FIRST engine's ``inference.fleet``
    section) with constructor overrides; ``replica_ports`` assigns each
    replica's /healthz endpoint explicitly (base+index when the config
    sets ``inference.observability.health_port``)."""

    def __init__(self, engines: List, prefill_engines: List = (),
                 *, sampler=greedy_sampler, jsonl_path: Optional[str] = None,
                 health_port: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 window_s: Optional[float] = None,
                 affinity: Optional[bool] = None,
                 handoff_dir: Optional[str] = None,
                 replica_ports: Optional[List[int]] = None,
                 window_iters: Optional[int] = None):
        if not engines:
            raise ValueError("FleetRouter needs at least one decode/"
                             "mixed replica engine")
        cfg = engines[0].config
        # build-time gate (memoized per process): with config
        # analysis.concurrency on, lint the control-plane sources BEFORE
        # standing up the thread fleet they describe — error mode
        # refuses to build on an error-severity finding
        concurrency.check_control_plane(
            cfg.analysis_concurrency_mode,
            cfg.analysis_concurrency_suppress, where="FleetRouter")
        self.sampler = sampler
        if prefill_engines and sampler is not greedy_sampler:
            raise ValueError(
                "disaggregated serving is greedy-only: the prefill "
                "pool samples the first token and the decode pool "
                "continues it — a custom sampler would have to run on "
                "both sides (docs/inference.md)")
        if prefill_engines:
            for eng in list(engines) + list(prefill_engines):
                if not eng.fleet_disaggregate:
                    raise ValueError(
                        "every engine in a disaggregated fleet needs "
                        "inference.fleet.disaggregate: true (the KV "
                        "export/import programs)")
            # handoff compatibility is a BUILD error, not a replica
            # death: an import_kv shape/dtype mismatch fires inside the
            # decode replica's driver thread, where it reads as a wedge
            # — the router would evict the replica and resubmit its
            # neighbours, and a minimal 1+1 topology deadlocks into the
            # stall timeout instead of naming the misconfiguration
            def _kv_sig(e):
                s = e.cache_spec
                return (s.layers, s.kv_heads_local * s.mp_size,
                        s.head_dim, np.dtype(s.dtype))
            want = _kv_sig(engines[0])
            for eng in list(engines) + list(prefill_engines):
                if _kv_sig(eng) != want:
                    raise ValueError(
                        f"disaggregated fleet KV specs diverge: replica "
                        f"(layers, kv_heads, head_dim, dtype) = "
                        f"{_kv_sig(eng)} vs {want} — prefill and decode "
                        f"pools must share the cache geometry and dtype "
                        f"or the handoff rows cannot import "
                        f"byte-identically")
        self.poll_s = float(poll_s if poll_s is not None
                            else cfg.inference_fleet_poll_s)
        self.window_s = float(window_s if window_s is not None
                              else max(0.25, self.poll_s * 4))
        self.idle_s = min(0.002, self.poll_s)
        self.affinity = bool(affinity if affinity is not None
                             else cfg.inference_fleet_affinity)
        self.handoff_dir = (handoff_dir
                            or cfg.inference_fleet_handoff_dir)
        # a dir the router created is the router's to remove at close
        # (artifacts are unlinked as they are consumed, but the mkdtemp
        # itself would otherwise accumulate one /tmp dir per fleet)
        self._own_handoff_dir = self.handoff_dir is None
        if self.handoff_dir is None:
            self.handoff_dir = tempfile.mkdtemp(prefix="dstpu_handoff_")
        os.makedirs(self.handoff_dir, exist_ok=True)
        jsonl_path = jsonl_path or cfg.inference_fleet_jsonl_path

        # one shared sink: router windows + every replica's serve and
        # request events interleave on ONE validator-clean stream
        self._sink = None
        if jsonl_path:
            from deepspeed_tpu.observability.registry import JsonlSink
            self._sink = JsonlSink(jsonl_path)

        # created through the lockwatch factory: a plain Lock unless
        # the sanitizer is armed (DSTPU_LOCKWATCH=1 / instrument()),
        # then an InstrumentedLock recording order edges and wait/held
        # durations under this canonical name
        self._lock = lockwatch.named_lock("FleetRouter._lock")
        self._queue = deque()          # (request, t_enqueue) unassigned
        self._inflight = {}            # rid -> _Flight
        self.results: List[RequestResult] = []
        self.submitted = 0
        self.tokens_out = 0
        self.evictions = 0
        self.resubmits = 0
        self.handoffs = 0
        self.affinity_hits = 0
        self._affinity_map = {}        # page-chain hash -> replica

        base_port = int(cfg.inference_obs_health_port or 0)
        if not base_port:
            from deepspeed_tpu.observability import health as health_mod
            env_port = health_mod.resolve_health_port(0)
            base_port = int(env_port or 0)

        def _port(i):
            if replica_ports is not None:
                return int(replica_ports[i]) if i < len(replica_ports) \
                    else 0
            return base_port + i if base_port else 0

        self.replicas: List[Replica] = []
        self.prefill_pool: List[Replica] = []
        idx = 0
        from deepspeed_tpu.inference.driver import ServeTelemetry
        for eng in engines:
            tel = None
            if self._sink is not None:
                # jsonl_path="" (not None) suppresses the constructor's
                # config-path fallback — None would open the replica's
                # own configured sink only to leak it when the fleet's
                # shared sink is swapped in below
                tel = ServeTelemetry(eng, jsonl_path="",
                                     window_iters=window_iters,
                                     request_events=True)
                tel.sink = self._sink
            elif eng.config.inference_obs_jsonl_path:
                # no fleet-level sink: the replica's own configured
                # stream must still be honored (the observability knob
                # cannot be silently ignored in fleet mode)
                tel = ServeTelemetry(eng, window_iters=window_iters)
            self.replicas.append(Replica(
                idx, eng, self,
                role="decode" if prefill_engines else "mixed",
                health_port=_port(idx), telemetry=tel))
            idx += 1
        for eng in prefill_engines:
            self.prefill_pool.append(Replica(
                idx, eng, self, role="prefill",
                health_port=_port(idx)))
            idx += 1
        self.all_replicas = self.replicas + self.prefill_pool

        self.telemetry = RouterTelemetry(self, sink=self._sink)
        self.obs = None
        fleet_port = (health_port if health_port is not None
                      else cfg.inference_fleet_health_port)
        if fleet_port:
            self.obs = RouterObservability(self, int(fleet_port))
        self._started = False
        # affinity hashing uses the decode pool's page size (all engines
        # share one cache spec in a coherent fleet)
        self._page_tokens = engines[0].cache_spec.page_tokens

    # ------------------------------------------------------------ intake
    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for rep in self.all_replicas:
            rep.thread.start()

    def submit(self, request: Request, now: Optional[float] = None):
        """Accept a request into the fleet (timestamped NOW unless the
        caller preserves an earlier arrival).  Budget-checked HERE,
        before any replica owns it: an over-budget request must be the
        submitter's loud error — handed to a driver thread it would
        kill the replica, be resubmitted by the eviction path, and
        serially wedge the whole fleet."""
        _check_request(self.replicas[0].engine, request)
        with self._lock:
            self._queue.append((request, time.perf_counter()
                                if now is None else now))
            self.submitted += 1

    # --------------------------------------------------------- callbacks
    # dstpu-thread: driver-callback owner-check=owner
    def _complete(self, replica: Replica, result: RequestResult) -> None:
        """Driver-thread completion: accepted only from the CURRENT
        owner — a zombie replica un-sticking after eviction must not
        double-complete a request the fleet already re-served."""
        with self._lock:
            flight = self._inflight.get(result.rid)
            if flight is None or flight.owner is not replica:
                logger.info(
                    "dropping completion of request %d from evicted "
                    "replica %d (re-owned elsewhere)", result.rid,
                    replica.rid)
                return
            del self._inflight[result.rid]
            self.results.append(result)
            self.tokens_out += len(result.tokens)

    # dstpu-thread: prefill-callback owner-check=owner
    def _handoff(self, prefill_rep: Replica, req, t_enq,
                 path: str) -> None:
        """Prefill-thread handoff: route the sealed artifact to the
        least-loaded healthy DECODE replica (ownership moves with it).
        The critical section is bookkeeping ONLY — the artifact unlink
        (file IO) happens after the lock is released, or every
        completion callback in the fleet stalls behind the filesystem."""
        target = None
        with self._lock:
            flight = self._inflight.get(req.rid)
            if flight is None or flight.owner is not prefill_rep:
                pass                  # ownership moved: drop the artifact
            elif (target := self._pick(self.replicas, req,
                                       record_affinity=False)) is None:
                # no healthy decode replica RIGHT NOW: requeue at the
                # router with the original timestamp; the tick loop
                # re-dispatches (possibly re-prefilling elsewhere)
                del self._inflight[req.rid]
                self._queue.appendleft((req, t_enq))
            else:
                flight.owner = target
                flight.phase = "decode"
                self.handoffs += 1
        if target is None:
            try:
                os.remove(path)
            except OSError:
                pass
            return
        target.inbox.put(("kvh", path, req.rid))
        if target.dead:
            # raced an eviction: _evict's inbox drain may have run
            # before the put landed, so nothing would ever consume the
            # artifact (the request itself was already resubmitted from
            # _inflight) — unlink it here; a double-remove is harmless
            try:
                os.remove(path)
            except OSError:
                pass

    # dstpu-thread: decode-callback owner-check=owner
    def _handoff_read_failed(self, replica: Replica, rid: int) -> None:
        """Decode-thread report of a corrupt handoff artifact: the ONE
        affected request re-enters the fleet queue with its original
        timestamp (a fresh prefill re-derives the identical stream);
        the replica stays healthy — a torn file on the handoff path is
        the request's problem, not the replica's."""
        with self._lock:
            flight = self._inflight.get(rid)
            if flight is None or flight.owner is not replica:
                return
            del self._inflight[rid]
            self._queue.appendleft((flight.req, flight.t_enqueue))

    # --------------------------------------------------------- admission
    def _prefix_hashes(self, prompt) -> list:
        return kvcache.prefix_page_hashes(
            prompt, self._page_tokens, max_pages=_AFFINITY_MAX_PAGES)

    # dstpu-thread: admission holds=FleetRouter._lock
    def _pick(self, pool: List[Replica], req,
              record_affinity: bool = True) -> Optional[Replica]:
        """Admission policy (call with the lock held): prefix affinity
        first — the replica whose page-hash index already holds the
        prompt's page-aligned prefix serves it again (the deepest
        recorded chain wins) — then least-loaded by (in-flight share of
        slots, queue depth, -free pages)."""
        candidates = [r for r in pool if not r.dead and r.error is None]
        if not candidates:
            return None
        # cached verdicts only — ANY age: this runs under the router
        # lock (the prefill thread's _handoff too), and a live HTTP
        # probe here would stall every completion callback behind a 2 s
        # socket timeout.  serve() primes both caches before the loop
        # and its poll cadence refreshes them, so "stale" here means at
        # most one poll interval old.
        max_age = _CACHE_ANY_AGE
        healthy = [r for r in candidates if r.healthy(max_age=max_age)]
        if not healthy:
            return None
        counts = {}
        for flight in self._inflight.values():
            counts[flight.owner.rid] = counts.get(flight.owner.rid, 0) + 1
        hashes = self._prefix_hashes(req.prompt) if self.affinity else []
        chosen, via_affinity = None, False
        for h in reversed(hashes):          # deepest chain first
            rep = self._affinity_map.get(h)
            if rep is not None and rep in healthy:
                # affinity yields to overload: a full replica with the
                # prefix is still slower than a re-prefill elsewhere
                if counts.get(rep.rid, 0) < 2 * rep.engine.num_slots:
                    chosen, via_affinity = rep, True
                break
        if chosen is None:
            def score(rep):
                load = rep.load(max_age=max_age)
                inflight = counts.get(rep.rid, 0)
                return (inflight / max(1, load["slots_total"]),
                        load["queue_depth"], -load["free_pages"],
                        rep.rid)
            chosen = min(healthy, key=score)
            if counts.get(chosen.rid, 0) >= 2 * chosen.engine.num_slots:
                return None               # backlogged fleet: stay queued
        if via_affinity:
            self.affinity_hits += 1
        if record_affinity and self.affinity:
            for h in hashes:
                # re-inserting keeps the entry fresh in insertion order
                self._affinity_map.pop(h, None)
                self._affinity_map[h] = chosen
            while len(self._affinity_map) > _AFFINITY_MAX_ENTRIES:
                self._affinity_map.pop(
                    next(iter(self._affinity_map)))
        return chosen

    def _dispatch(self) -> None:
        # a fully-evicted prefill pool falls back to the decode/mixed
        # replicas — they are full engines and can prefill; a dead
        # prefill pool must degrade the fleet to mixed serving, not
        # stall intake until the stall timeout fires
        alive_prefill = [r for r in self.prefill_pool
                         if not r.dead and r.error is None]
        intake = alive_prefill or self.replicas
        phase = "prefill" if alive_prefill else "mixed"
        while True:
            with self._lock:
                if not self._queue:
                    return
                req, t_enq = self._queue[0]
                target = self._pick(intake, req)
                if target is None:
                    return
                self._queue.popleft()
                self._inflight[req.rid] = _Flight(req, t_enq, target,
                                                  phase)
            target.inbox.put((req, t_enq))

    # ----------------------------------------------------------- eviction
    def _evict(self, replica: Replica) -> None:
        """503/wedge: stop routing to the replica and resubmit
        everything it owned — each request re-enters the fleet queue
        with its ORIGINAL arrival timestamp (front of the queue: they
        are the oldest work in the system)."""
        replica.dead = True
        replica.stop.set()
        # drain the inbox for CLEANUP only (unlink sealed handoff
        # artifacts): every inbox item already has an _inflight record —
        # _dispatch/_handoff record ownership BEFORE the put — so the
        # authoritative displaced set comes from _inflight alone, or a
        # request still in the inbox would resubmit twice
        while True:
            try:
                item = replica.inbox.get_nowait()
            except queue_mod.Empty:
                break
            if not isinstance(item, KVHandoff) and item[0] == "kvh":
                try:
                    os.remove(item[1])
                except OSError:
                    pass
        with self._lock:
            self.evictions += 1
            owned = {rid: f for rid, f in self._inflight.items()
                     if f.owner is replica}
            displaced = []
            for rid, flight in owned.items():
                del self._inflight[rid]
                displaced.append((flight.req, flight.t_enqueue))
            # oldest-first back at the FRONT, original timestamps intact
            for req, t_enq in sorted(displaced, key=lambda p: -p[1]):
                self._queue.appendleft((req, t_enq))
            self.resubmits += len(displaced)
            # a dead replica's prefix index is gone with it
            self._affinity_map = {h: r for h, r
                                  in self._affinity_map.items()
                                  if r is not replica}
        logger.warning(
            "router: evicted replica %d (unhealthy); resubmitted %d "
            "in-flight request(s) with original timestamps",
            replica.rid, len(displaced))

    # ------------------------------------------------------------- serving
    def serve(self, requests, timeout_s: float = 600.0,
              stall_timeout_s: float = 120.0) -> dict:
        """Drive ``requests`` through the fleet to completion; returns
        ``{"results", "summary"}`` shaped like
        :func:`~deepspeed_tpu.inference.driver.run_serve` plus the
        router counters.  ``stall_timeout_s`` bounds zero-progress time
        (every replica wedged is an error, not a hang)."""
        self.start()
        # prime every replica's health/load caches BEFORE any dispatch:
        # _pick (under the router lock) reads caches only, so the first
        # admission must never be the first probe
        for rep in self.all_replicas:
            rep.healthy()
            rep.load()
        for r in requests:
            self.submit(r)
        n_total = self.submitted
        t0 = time.perf_counter()
        last_poll = last_window = t0
        last_progress = (t0, 0)
        while True:
            with self._lock:
                done = len(self.results)
            if done >= n_total and not self._inflight:
                break
            now = time.perf_counter()
            if now - t0 > timeout_s:
                raise RuntimeError(
                    f"fleet serve timed out after {timeout_s}s "
                    f"({done}/{n_total} complete)")
            if done > last_progress[1]:
                last_progress = (now, done)
            elif now - last_progress[0] > stall_timeout_s:
                raise RuntimeError(
                    f"fleet made no progress for {stall_timeout_s}s "
                    f"({done}/{n_total} complete, "
                    f"{sum(r.healthy() for r in self.all_replicas)} "
                    f"healthy replicas)")
            if now - last_poll >= self.poll_s:
                last_poll = now
                # the poll loop is the ONE place live probes happen (no
                # router lock held here): health for eviction, load for
                # the admission scores _pick reads from cache
                for rep in self.all_replicas:
                    if rep.dead:
                        continue
                    if not rep.healthy():
                        self._evict(rep)
                    else:
                        rep.load()
            self._dispatch()
            if now - last_window >= self.window_s:
                last_window = now
                self.telemetry.emit()
            time.sleep(self.idle_s)
        elapsed = time.perf_counter() - t0
        self.telemetry.emit()                 # final (partial) window
        for rep in self.replicas:
            if rep.telemetry is not None and rep.sched is not None:
                rep.telemetry.flush(rep.sched)
        with self._lock:
            results = list(self.results)
        n_chips = sum(len(rep.engine.mesh.devices.flat)
                      for rep in self.all_replicas)
        summary = latency_summary(results, elapsed, n_chips=n_chips)
        summary.update({
            "n_replicas": len(self.all_replicas),
            "prefill_replicas": len(self.prefill_pool),
            "evictions": self.evictions,
            "resubmits": self.resubmits,
            "handoffs": self.handoffs,
            "affinity_hits": self.affinity_hits,
            "router_windows": self.telemetry.window,
        })
        return {"results": results, "summary": summary}

    def close(self) -> None:
        """Stop every driver thread and release the endpoints/sink.
        Wedged threads get a bounded join — a chaos stall ends when its
        watchdog reacted, so they unstick; a truly stuck thread is
        daemonic and dies with the process."""
        for rep in self.all_replicas:
            rep.stop.set()
        for rep in self.all_replicas:
            if rep.thread.is_alive():
                rep.thread.join(timeout=10)
        for rep in self.all_replicas:
            rep.close()
        if self.obs is not None:
            self.obs.close()
        if self._sink is not None:
            self._sink.close()
        if self._own_handoff_dir:
            import shutil
            shutil.rmtree(self.handoff_dir, ignore_errors=True)


def run_fleet(engines, requests, prefill_engines=(), **kwargs) -> dict:
    """Convenience mirror of :func:`~deepspeed_tpu.inference.driver.
    run_serve` for a fleet: build a :class:`FleetRouter`, serve the
    trace, close everything — crash or not."""
    serve_kwargs = {k: kwargs.pop(k) for k in ("timeout_s",
                                               "stall_timeout_s")
                    if k in kwargs}
    router = FleetRouter(engines, prefill_engines, **kwargs)
    try:
        return router.serve(requests, **serve_kwargs)
    finally:
        router.close()
