"""Model family: tensor-parallel transformer building blocks + GPT-2 / BERT.

The reference delegates models to Megatron-LM / BingBert examples; on TPU the
framework owns a sharded model zoo (SURVEY.md §7.1 "mpu protocol" row).
"""

from deepspeed_tpu.models.transformer import (TransformerConfig,
                                              init_block_params,
                                              block_partition_specs,
                                              block_apply, stack_apply,
                                              token_batch_specs)
from deepspeed_tpu.models.gpt2 import GPT2, GPT2_SIZES
from deepspeed_tpu.models.pipeline_gpt2 import GPT2Pipelined
from deepspeed_tpu.models.gpt2_moe import GPT2MoE, GPT2MoEPipelined
from deepspeed_tpu.models.moe import MoEConfig
from deepspeed_tpu.models.bert import (BertForPreTraining,
                                       BertForQuestionAnswering, BERT_SIZES)

__all__ = [
    "TransformerConfig", "init_block_params", "block_partition_specs",
    "block_apply", "stack_apply", "token_batch_specs",
    "GPT2", "GPT2_SIZES",
    "GPT2Pipelined", "GPT2MoE", "GPT2MoEPipelined", "MoEConfig",
    "BertForPreTraining", "BertForQuestionAnswering", "BERT_SIZES",
]
