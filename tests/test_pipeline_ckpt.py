"""Pipeline parallelism × checkpointing × ZeRO-1.

VERDICT r2 #4: per-stage checkpoint files (the mp_rank layout generalized to
pp_stage, reference layout rule deepspeed_light.py:949-967), and the ZeRO
flat master generalized to a per-(stage, model-rank) [S, local] layout so
pp>1 composes with optimizer-state partitioning.

Pinned semantics:
  * ZeRO × pp=2 reproduces the non-ZeRO pp=2 trajectory;
  * pp=2 train → save → fresh engine load → resume matches the unbroken
    run (with and without ZeRO, and composed with mp=2);
  * restoring ZeRO shards across a different pp degree fails loudly.
"""

import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2Pipelined
from deepspeed_tpu.parallel.topology import make_mesh

pytestmark = pytest.mark.slow

VOCAB, SEQ = 64, 16


def lm_batch(batch, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, VOCAB, size=(batch, SEQ)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    labels[:, -1] = -1
    return toks, labels


def make_engine(pp=2, mp=1, zero=False, **cfg_over):
    cfg = {
        "train_batch_size": 8,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "fp16": {"enabled": True, "initial_scale_power": 8},
    }
    if zero:
        cfg["zero_optimization"] = {"stage": 1}
    cfg.update(cfg_over)
    # pp=1 runs on a data-only mesh where the per-shard batch is 1
    model = GPT2Pipelined.from_size(
        "tiny", num_micro_batches=(2 if pp > 1 else 1), vocab_size=VOCAB,
        max_seq_len=SEQ, num_layers=4, hidden_size=32, num_heads=4)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(7)),
        mesh=make_mesh(pipeline_parallel_size=pp, model_parallel_size=mp))
    return engine


def train(engine, steps, seed0=0):
    out = []
    for i in range(steps):
        toks, labels = lm_batch(8, seed=seed0 + i)
        loss = engine(toks, labels)
        engine.backward(loss)
        engine.step()
        out.append(float(loss))
    return out


def test_zero_pp_matches_plain_pp():
    """ZeRO × pp=2: same losses as pp=2 without ZeRO (the partitioned
    update must not change the math)."""
    ref = train(make_engine(pp=2, zero=False), 4)
    got = train(make_engine(pp=2, zero=True), 4)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("zero,mp", [(False, 1), (True, 1), (True, 2)])
def test_pp_checkpoint_resume(tmp_path, zero, mp):
    """pp=2 train → save → fresh-engine load → resume == unbroken run."""
    ref_engine = make_engine(pp=2, mp=mp, zero=zero)
    ref = train(ref_engine, 6)

    e1 = make_engine(pp=2, mp=mp, zero=zero)
    train(e1, 3)
    e1.save_checkpoint(str(tmp_path), tag="mid")
    # per-stage files exist
    files = os.listdir(os.path.join(str(tmp_path), "mid"))
    assert any("pp_stage_00" in f for f in files), files
    assert any("pp_stage_01" in f for f in files), files

    e2 = make_engine(pp=2, mp=mp, zero=zero)
    path, _ = e2.load_checkpoint(str(tmp_path), tag="mid")
    assert path is not None
    resumed = train(e2, 3, seed0=3)
    np.testing.assert_allclose(resumed, ref[3:], rtol=2e-4, atol=2e-5)


def test_resave_same_tag_under_different_pp(tmp_path):
    """Re-saving a tag under a different pp degree must not leave stale
    model-state files from the old naming scheme for the loader to pick."""
    e_pp1 = make_engine(pp=1, zero=False)
    train(e_pp1, 1)
    e_pp1.save_checkpoint(str(tmp_path), tag="best")
    e_pp2 = make_engine(pp=2, zero=False)
    train(e_pp2, 2)
    e_pp2.save_checkpoint(str(tmp_path), tag="best")
    files = os.listdir(os.path.join(str(tmp_path), "best"))
    assert not any(f.startswith("mp_rank_") for f in files), files
    e_load = make_engine(pp=2, zero=False)
    e_load.load_checkpoint(str(tmp_path), tag="best")
    assert e_load.global_steps == e_pp2.global_steps == 2


def test_zero_pp_shards_reject_cross_pp_restore(tmp_path):
    """ZeRO flat partitions are per-stage; restoring them under a different
    pp degree must fail loudly (weights-only restore stays possible)."""
    e1 = make_engine(pp=2, zero=True)
    train(e1, 2)
    e1.save_checkpoint(str(tmp_path), tag="t")
    e2 = make_engine(pp=4, zero=True)
    with pytest.raises(ValueError, match="pipeline_parallel_size"):
        e2.load_checkpoint(str(tmp_path), tag="t")
