"""Argparse surface (behavioral equivalent of
/root/reference/tests/unit/test_ds_arguments.py:12-100)."""

import argparse

import pytest

import deepspeed_tpu


def basic_parser():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_epochs", type=int)
    return parser


def test_no_ds_arguments_no_ds_parser():
    args = basic_parser().parse_args(["--num_epochs", "2"])
    assert args.num_epochs == 2
    assert not hasattr(args, "deepspeed")
    assert not hasattr(args, "deepspeed_config")


def test_no_ds_arguments():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--num_epochs", "2"])
    assert args.num_epochs == 2
    assert args.deepspeed is False
    assert args.deepspeed_config is None
    assert args.deepspeed_mpi is False


def test_config_argument_only():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--num_epochs", "2", "--deepspeed_config", "foo.json"])
    assert args.deepspeed is False
    assert isinstance(args.deepspeed_config, str)
    assert args.deepspeed_config == "foo.json"


def test_enable_argument_only():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--num_epochs", "2", "--deepspeed"])
    assert args.deepspeed is True
    assert args.deepspeed_config is None


def test_no_ds_parser_rejects_flags():
    with pytest.raises(SystemExit):
        basic_parser().parse_args(["--num_epochs", "2", "--deepspeed"])


def test_core_arguments_together():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(
        ["--num_epochs", "2", "--deepspeed", "--deepspeed_config", "foo.json"])
    assert args.num_epochs == 2
    assert args.deepspeed is True
    assert args.deepspeed_config == "foo.json"


def test_deprecated_deepscale_spellings():
    parser = deepspeed_tpu.add_config_arguments(basic_parser())
    args = parser.parse_args(["--deepscale", "--deepscale_config", "bar.json"])
    assert args.deepscale is True
    assert args.deepscale_config == "bar.json"
