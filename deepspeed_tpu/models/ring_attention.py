"""Ring attention: exact attention over a sequence-sharded ring.

Long-context support the reference lacks entirely (SURVEY.md §2.3 row 22 —
no sequence/context parallelism anywhere in the reference); built TPU-first:
the sequence axis is sharded over the ``seq`` mesh axis, K/V blocks rotate
around the ring via ``ppermute`` (nearest-neighbour ICI traffic only), and
each shard folds incoming blocks into a running flash-style softmax
(running max ``m``, partition sum ``l``, weighted accumulator ``o``) so the
full [T, T] score matrix never materialises.  Compute of step i overlaps the
DMA of step i+1 under XLA's latency-hiding scheduler.

Memory per shard: O(T/sp · d) activations instead of O(T²) scores; exact
(not approximate) — results match full attention to fp tolerance, verified
in tests/test_ring_attention.py.

Causality across ring steps: shard ``s`` holds query block ``s``; at step
``i`` it sees the K/V block of shard ``(s - i) mod sp``.  Blocks with
src < s attend fully, src == s applies the local causal triangle,
src > s is skipped (mask −1e30 → zero weight).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.topology import SEQ_AXIS

_NEG = -1e30


def ring_attention(q, k, v, *, causal=True, kv_mask=None, axis=SEQ_AXIS,
                   scale=None):
    """q, k, v: [B, Tl, n, d] — the LOCAL sequence shard (inside shard_map).
    kv_mask: optional [B, Tl] with 1 = attend (padding mask; rotates with
    K/V).  Returns [B, Tl, n, d].
    """
    sp = jax.lax.axis_size(axis)
    B, Tl, n, d = q.shape
    if scale is None:
        scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    my = jax.lax.axis_index(axis)
    perm = [(j, (j + 1) % sp) for j in range(sp)]

    qf = q.astype(jnp.float32)
    m = jnp.full((B, n, Tl), _NEG, jnp.float32)       # running max
    l = jnp.zeros((B, n, Tl), jnp.float32)            # partition sum
    o = jnp.zeros((B, Tl, n, d), jnp.float32)         # weighted accumulator

    k_cur, v_cur = k, v
    mask_cur = kv_mask
    local_tri = jnp.tril(jnp.ones((Tl, Tl), jnp.bool_))

    for i in range(sp):
        src = (my - i) % sp                            # owner of k_cur block
        scores = jnp.einsum(
            "btnd,bsnd->bnts", qf, k_cur.astype(jnp.float32)) * scale

        if causal:
            # src < my: full attend; src == my: triangle; src > my: none
            allow_full = src < my
            allow_tri = src == my
            block_mask = (allow_full
                          | (allow_tri & local_tri[None, None]))
            scores = jnp.where(block_mask, scores, _NEG)
        if mask_cur is not None:
            scores = jnp.where(
                mask_cur[:, None, None, :].astype(jnp.bool_), scores, _NEG)

        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        p = jnp.exp(scores - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1)
        o = (o * jnp.transpose(corr, (0, 2, 1))[..., None]
             + jnp.einsum("bnts,bsnd->btnd", p,
                          v_cur.astype(jnp.float32)))
        m = m_new

        if i + 1 < sp:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
            if mask_cur is not None:
                mask_cur = jax.lax.ppermute(mask_cur, axis, perm)

    denom = jnp.maximum(jnp.transpose(l, (0, 2, 1)), 1e-30)[..., None]
    return (o / denom).astype(q.dtype)
