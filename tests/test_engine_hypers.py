"""Boundary hyperparameter staging: one cached [4, G] device array.

The old boundary staged FOUR small host→device transfers per optimizer
step (lr/beta1/beta2/weight_decay vectors) — part of the fixed per-step
dispatch cost that gas=8 cannot amortize (bench_mfu_breakdown.json
``per_step_fixed_lamb_dispatch``).  These tests pin the new contract:
no restaging while the facade values are unchanged, restage (one array)
when a scheduler moves them, and identical training math either way.
"""

import jax
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.parallel.topology import make_mesh

from simple_model import SimpleModel  # noqa: E402  (tests dir helper)


def make_engine(**cfg_over):
    cfg = {
        "train_batch_size": 8,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Lamb",
                      "params": {"lr": 1e-3, "weight_decay": 0.01}},
    }
    cfg.update(cfg_over)
    model = SimpleModel(hidden_dim=8)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        mesh=make_mesh())
    return engine


def batch(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(8, 8)).astype(np.float32)
    y = rng.integers(0, 8, size=(8,)).astype(np.int32)
    return x, y


def test_hypers_cached_until_values_move():
    engine = make_engine()
    h1 = engine._current_hypers()
    assert h1.shape == (4, 1) and h1.dtype == np.float32
    np.testing.assert_allclose(np.asarray(h1)[:, 0],
                               [1e-3, 0.9, 0.999, 0.01], rtol=1e-6)
    # unchanged facade values -> the SAME staged array, no new transfer
    assert engine._current_hypers() is h1
    engine.train_batch(batch())
    assert engine._current_hypers() is h1
    # a scheduler-style mutation restages exactly once
    engine.optimizer.param_groups[0]["lr"] = 5e-4
    h2 = engine._current_hypers()
    assert h2 is not h1
    np.testing.assert_allclose(float(np.asarray(h2)[0, 0]), 5e-4)
    assert engine._current_hypers() is h2


def test_lr_mutation_changes_update():
    """The staged hypers must FOLLOW param-group mutations (the LR
    scheduler contract) — caching must never freeze a stale lr."""
    e1 = make_engine()
    e2 = make_engine()
    b = batch()
    float(e1.train_batch(b))
    float(e2.train_batch(b))
    e2.optimizer.param_groups[0]["lr"] = 0.0     # freeze e2
    # train_batch returns the loss at the step's ENTRY params: the second
    # call's losses still agree (first update used the same lr)...
    np.testing.assert_allclose(float(e1.train_batch(b)),
                               float(e2.train_batch(b)), rtol=1e-6)
    # ...the third call sees e1 moved by its second update while e2's
    # lr=0 update was a no-op — the staged hypers followed the mutation
    l1 = float(e1.train_batch(b))
    l2 = float(e2.train_batch(b))
    assert l1 != l2
    l2b = float(e2.train_batch(b))
    np.testing.assert_allclose(l2b, l2, rtol=1e-6)
