"""Graph Lint — jaxpr-level static analysis of the engine's step programs.

JAX exposes the whole train step as a traceable jaxpr before any chip
executes it, so the distributed-training mistakes that cost a multi-hour
hang on a pod slice are decidable at engine-build time.  Four passes
(``analysis/passes.py``):

1. collective consistency (rank-divergent collective order = deadlock)
2. precision flow (fp32 compute reachable from bf16/fp16 via upcasts)
3. transfer/recompile lint (host callbacks, weak types, donation)
4. shard-spec validation (specs vs mesh axes and value shapes, pre-compile)

Three entry points:

* engine config ``graph_lint: {"mode": "off"|"warn"|"error"}`` — the engine
  lints each step program once per batch format at build time.
* CLI ``python -m deepspeed_tpu.analysis <ds_config.json> ...`` — builds a
  representative model for the config, traces, prints a findings report.
* library: :func:`analyze_jaxpr` for any jaxpr, :func:`analyze_engine` for
  a constructed engine + batch.

See docs/analysis.md for the rule catalogue and suppression story.
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

import jax

from deepspeed_tpu.analysis import graph  # noqa: F401  (re-export for users)
from deepspeed_tpu.analysis import commplan  # noqa: F401
from deepspeed_tpu.analysis import concurrency  # noqa: F401
from deepspeed_tpu.analysis import dispatchplan  # noqa: F401
from deepspeed_tpu.analysis import lockwatch  # noqa: F401
from deepspeed_tpu.analysis import memplan  # noqa: F401
from deepspeed_tpu.analysis import passes
from deepspeed_tpu.analysis import profiles  # noqa: F401
from deepspeed_tpu.analysis import stability  # noqa: F401
from deepspeed_tpu.analysis.concurrency import ConcurrencyLintError
from deepspeed_tpu.analysis.dispatchplan import (DispatchPlan,
                                                 plan_engine_dispatch,
                                                 plan_serve_dispatch)
from deepspeed_tpu.analysis.memplan import (CapacityPlan, ProgramPlan,
                                            analyze_program, plan_engine)
from deepspeed_tpu.analysis.report import (ERROR, INFO, WARNING, Finding,
                                           GraphLintError, MemoryPlanError,
                                           Report, ShardSpecError)
from deepspeed_tpu.analysis.stability import (ExecutablePrediction,
                                              ProgramSignature,
                                              predict_executables,
                                              signature_of)

logger = logging.getLogger(__name__)

MODES = ("off", "warn", "error")

__all__ = [
    "ERROR", "WARNING", "INFO", "Finding", "Report", "GraphLintError",
    "MemoryPlanError", "ShardSpecError", "ConcurrencyLintError", "MODES",
    "concurrency", "lockwatch", "analyze_jaxpr",
    "analyze_step", "analyze_engine", "analyze_engine_train_batch",
    "analyze_engine_train_many", "trace_train_batch", "train_batch_args",
    "train_many_args", "step_args",
    "check_shard_specs",
    "validate_specs_or_raise", "dispatch_report",
    "CapacityPlan", "ProgramPlan", "analyze_program", "plan_engine",
    "DispatchPlan", "plan_engine_dispatch", "plan_serve_dispatch",
    "ExecutablePrediction", "ProgramSignature", "predict_executables",
    "signature_of",
    "commplan", "dispatchplan", "memplan", "profiles", "stability",
]


def analyze_jaxpr(jaxpr, mesh_axes: Optional[Sequence[str]] = None,
                  subject: str = "") -> Report:
    """Run the three jaxpr passes over one (closed or open) jaxpr."""
    rep = Report(subject=subject)
    passes.check_collectives(jaxpr, rep, mesh_axes=mesh_axes)
    passes.check_precision(jaxpr, rep)
    passes.check_transfers(jaxpr, rep)
    return rep


def analyze_step(fn, args, mesh=None, subject: str = "") -> Report:
    """Trace ``fn(*args)`` to a jaxpr (jitted fns included — the pjit level
    is walked through, and its ``donated_invars`` feed the donation lint)
    and run the jaxpr passes."""
    mesh_axes = list(mesh.shape.keys()) if mesh is not None else None
    closed = jax.make_jaxpr(fn)(*args)
    return analyze_jaxpr(closed, mesh_axes=mesh_axes, subject=subject)


def check_shard_specs(mesh, specs, tree, subject: str = "",
                      where: str = "") -> Report:
    """Pass 4 standalone: PartitionSpecs vs mesh axes and value shapes."""
    rep = Report(subject=subject)
    passes.check_shard_specs(dict(mesh.shape), specs, tree, rep, where=where)
    return rep


def validate_specs_or_raise(mesh, specs, tree, where: str = "") -> None:
    """The engine's first-class pre-compile shard-spec gate: raises
    :class:`ShardSpecError` naming the offending leaf, spec and axis
    instead of letting shard_map fail with a raw spec-mismatch error.
    Always on (independent of ``graph_lint.mode``) — it replaces a crash,
    it does not add a new failure mode."""
    rep = check_shard_specs(mesh, specs, tree, where=where)
    errs = rep.errors
    if errs:
        raise ShardSpecError(
            f"invalid sharding for {where or 'shard_map operands'} "
            f"({len(errs)} problem(s)):\n"
            + "\n".join("  - " + f.message for f in errs))


def analyze_engine(engine, batch, train: bool = True,
                   include_step: bool = True) -> Report:
    """Full engine analysis for one batch format: shard-spec pass over the
    param and batch specs, then the jaxpr passes over the traced
    forward+backward (or eval) program and the boundary step program."""
    batch = tuple(batch) if isinstance(batch, (tuple, list)) else (batch,)
    rep = Report(subject="engine")

    # pass 4 first: a spec problem would make tracing fail anyway
    passes.check_shard_specs(dict(engine.mesh.shape), engine._param_specs,
                             engine.params, rep, where="params")
    passes.check_shard_specs(dict(engine.mesh.shape),
                             engine._batch_specs(batch), batch, rep,
                             where="batch")
    if rep.errors:
        return rep

    mesh_axes = list(engine.mesh.shape.keys())
    if train:
        fwdbwd = engine._ensure_fwdbwd(batch)
        traced = jax.make_jaxpr(fwdbwd)(
            engine.params, engine.loss_scale_state.cur_scale, batch)
        rep.extend(analyze_jaxpr(traced, mesh_axes=mesh_axes,
                                 subject="fwdbwd"))
        if include_step:
            # shape of the accumulated grads == shape of one micro-step's
            # grads (fp32 stacks / ZeRO partitions)
            _, grad_shapes = jax.eval_shape(
                fwdbwd, engine.params, engine.loss_scale_state.cur_scale,
                batch)
            if engine._step_fn is None:
                engine._step_fn = engine._build_step()
            master = (engine.master_flat if engine.zero_flat
                      else engine.master)
            step_tr = jax.make_jaxpr(engine._step_fn)(
                master, engine.opt_state, grad_shapes,
                engine.loss_scale_state, engine._current_hypers(),
                engine._zero_norm_w, engine._zero_gid_flat)
            rep.extend(analyze_jaxpr(step_tr, mesh_axes=mesh_axes,
                                     subject="step"))
            # master-weight precision contract (precision.MASTER_DTYPE):
            # the fp32 master is what makes bf16/fp16 training converge
            from deepspeed_tpu import precision as prec
            bad = [str(jax.tree_util.keystr(p))
                   for p, l in jax.tree_util.tree_flatten_with_path(
                       master)[0]
                   if hasattr(l, "dtype") and l.dtype != prec.MASTER_DTYPE]
            if bad:
                rep.add(
                    "precision.master-dtype", ERROR,
                    f"master weights are expected in fp32 but "
                    f"{bad[:3]}{'...' if len(bad) > 3 else ''} are not — "
                    f"low-precision masters silently stall convergence",
                    pass_name="precision")
    else:
        ev = engine._ensure_eval(batch)
        traced = jax.make_jaxpr(ev)(engine.params, batch)
        rep.extend(analyze_jaxpr(traced, mesh_axes=mesh_axes,
                                 subject="eval"))
    return rep


def train_batch_args(engine, batch):
    """The fused train_batch call tuple with the engine's CURRENT state —
    THE single owner of the step-function call protocol.  Every caller
    that needs the tuple (the tracer below, the capacity planner, the
    XLA-parity tests, the engine itself) marshals through here;
    hand-rolled copies drift silently when the signature changes.  With
    the metric spool on (``observability.report_window``) the tuple grows
    a trailing spool-state argument — the device ring buffer the compiled
    step appends this boundary's metrics into."""
    batch = tuple(batch) if isinstance(batch, (tuple, list)) else (batch,)
    master = engine.master_flat if engine.zero_flat else engine.master
    args = (engine.params, master, engine.opt_state,
            engine.loss_scale_state, engine._current_hypers(),
            engine._zero_norm_w, engine._zero_gid_flat, batch)
    spool = getattr(engine, "_spool", None)
    if spool is not None:
        args = args + (spool.state,)
    return args


def train_many_args(engine, batches):
    """The K-fused ``train_many`` call tuple with the engine's CURRENT
    state — single owner like :func:`train_batch_args`.  ``batches`` is
    the sequence of K per-step batch tuples (separate program arguments,
    NOT a stacked tree — see ``engine._build_train_many`` for why); the
    hyper slot carries the staged ``[K, 4, G]`` block, and with the
    metric spool on the tuple grows the trailing ring state."""
    batches = tuple(tuple(b) if isinstance(b, (tuple, list)) else (b,)
                    for b in batches)
    k = len(batches)
    master = engine.master_flat if engine.zero_flat else engine.master
    args = (engine.params, master, engine.opt_state,
            engine.loss_scale_state, engine._stage_hypers_many(k),
            engine._zero_norm_w, engine._zero_gid_flat,
            engine._live_flag, batches)
    spool = getattr(engine, "_spool", None)
    if spool is not None:
        args = args + (spool.state,)
    return args


def analyze_engine_train_many(engine, batches) -> Report:
    """Jaxpr passes over the K-fused ``train_many`` program (K unrolled
    fused steps feeding each other inside one shard_map) — one trace
    covers every step's model, collectives and optimizer, so a
    rank-divergent collective introduced by the unrolling is caught
    exactly like in the single-step program."""
    batches = tuple(tuple(b) if isinstance(b, (tuple, list)) else (b,)
                    for b in batches)
    rep = Report(subject="train_many")
    passes.check_shard_specs(dict(engine.mesh.shape),
                             engine._batch_specs(batches[0]), batches[0],
                             rep, where="batch")
    if rep.errors:
        return rep
    # the CURRENT cached program only fits if it was built for this
    # (K, format) pair — otherwise build a matching one (a K=8 program
    # traced with 2 batches would die on the shard_map arg count)
    key = (len(batches), engine._batch_cache_key(batches[0]))
    fn = (engine._train_many_fn if engine._train_many_key == key
          else engine._cached_batch_fn(
              engine._train_many_fns, key,
              lambda: engine._build_train_many(batches[0], len(batches))))
    rep.extend(analyze_jaxpr(
        jax.make_jaxpr(fn)(*train_many_args(engine, batches)),
        mesh_axes=list(engine.mesh.shape.keys()), subject="train_many"))
    return rep


def step_args(engine, grads):
    """The split-API boundary step call tuple (engine._step_fn's 7-arg
    protocol) with the engine's CURRENT state — single owner, like
    :func:`train_batch_args`: the engine's ``step()``, the capacity
    planner's split branch, and the bench boundary microbench all marshal
    through here.  ``grads`` is the accumulated-grad slot (real arrays or
    ShapeDtypeStructs)."""
    master = engine.master_flat if engine.zero_flat else engine.master
    return (master, engine.opt_state, grads, engine.loss_scale_state,
            engine._current_hypers(), engine._zero_norm_w,
            engine._zero_gid_flat)


def trace_train_batch(engine, batch, fn=None):
    """Jaxpr of the fused train_batch program (args via
    :func:`train_batch_args`; the overlap microbench counts collectives
    through this too).  ``fn`` defaults to the engine's built
    ``_train_batch_fn``."""
    return jax.make_jaxpr(fn or engine._train_batch_fn)(
        *train_batch_args(engine, batch))


def analyze_engine_train_batch(engine, batch) -> Report:
    """Jaxpr passes over the fused train_batch program (scan over gas
    micro-steps feeding the boundary update) — one trace covers the model,
    the collectives AND the optimizer."""
    batch = tuple(batch) if isinstance(batch, (tuple, list)) else (batch,)
    rep = Report(subject="train_batch")
    passes.check_shard_specs(dict(engine.mesh.shape),
                             engine._batch_specs(batch), batch, rep,
                             where="batch")
    if rep.errors:
        return rep
    rep.extend(analyze_jaxpr(trace_train_batch(engine, batch),
                             mesh_axes=list(engine.mesh.shape.keys()),
                             subject="train_batch"))
    return rep


def dispatch_report(rep: Report, mode: str, where: str = "",
                    log: Optional[logging.Logger] = None,
                    label: str = "graph lint",
                    info_hint: Optional[str] = None,
                    error_cls=None) -> Report:
    """Apply a ``graph_lint.mode``-style gate: log warnings+errors in
    ``warn`` mode, raise ``error_cls`` (default :class:`GraphLintError`)
    on error findings in ``error`` mode.  The capacity planner rides the
    same dispatcher with ``label="capacity plan"`` and
    ``error_cls=MemoryPlanError`` — one gate implementation, two pass
    families."""
    log = log or logger
    if mode == "off" or not len(rep):
        return rep
    worst = rep.errors or rep.warnings
    if worst or rep.infos:
        hint = (info_hint or "engine.run_graph_lint(batch).format() "
                             "shows them")
        body = (rep.format(min_severity=WARNING) if worst else
                f"{len(rep.infos)} info-severity finding(s); {hint}")
        log.log(logging.WARNING if worst else logging.INFO,
                "%s%s: %s\n%s", label,
                f" [{where}]" if where else "", rep.summary(), body)
    if mode == "error":
        rep.raise_on_error(where=where, error_cls=error_cls)
    return rep
