"""Pipeline parallelism (GPipe over the 'pipe' mesh axis).

Beyond-reference component: parity is pinned against the NON-pipelined
model — same params, same data, the pipelined forward/backward must
reproduce losses and updates exactly (the schedule changes execution order,
not math).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.models import GPT2, GPT2Pipelined
from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.parallel import pipeline as pipe_mod
from deepspeed_tpu.parallel.topology import make_mesh

# composition tier: 30-85 s of shard_map compiles per test — runs in the
# full suite/CI, excluded from `-m fast` (VERDICT r2 weak #6)
pytestmark = pytest.mark.slow


VOCAB, SEQ = 64, 16


def lm_batch(batch, seed=0):
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, VOCAB, size=(batch, SEQ)).astype(np.int32)
    labels = np.roll(toks, -1, axis=1)
    labels[:, -1] = -1
    return toks, labels


def test_pipeline_apply_matches_sequential():
    """The raw schedule: pp=4 stages of 1 layer each == a 4-layer scan."""
    cfg = T.TransformerConfig(vocab_size=VOCAB, max_seq_len=SEQ,
                              hidden_size=32, num_layers=4, num_heads=4,
                              causal=True, remat=False)
    params = T.init_block_params(cfg, jax.random.PRNGKey(0))
    x = np.random.default_rng(1).normal(size=(8, SEQ, 32)).astype(np.float32)

    # sequential reference on a pipe-less mesh
    mesh1 = make_mesh(devices=jax.devices()[:1])
    seq_fn = jax.jit(jax.shard_map(
        lambda p, x: T.stack_apply(x, p, cfg), mesh=mesh1,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params), P()),
        out_specs=P(), check_vma=False))
    want = np.asarray(seq_fn(params, x))

    mesh = make_mesh(pipeline_parallel_size=4,
                     devices=jax.devices()[:4])
    block_specs = {k: P("pipe", *s[1:])
                   for k, s in T.block_partition_specs().items()}

    def local(p, x):
        xm = x.reshape(2, 4, SEQ, 32)          # 2 micro-batches
        out = pipe_mod.pipeline_apply(
            xm, lambda u: T.stack_apply(u, p, cfg))
        return out.reshape(8, SEQ, 32)

    fn = jax.jit(jax.shard_map(
        local, mesh=mesh, in_specs=(block_specs, P()),
        out_specs=P(), check_vma=False))
    got = np.asarray(fn(params, x))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def run_engine(model, mesh, steps=4, batch=8, **cfg_over):
    cfg = {
        "train_batch_size": batch,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    cfg.update(cfg_over)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(7)),
        mesh=mesh)
    losses = []
    for i in range(steps):
        toks, labels = lm_batch(batch, seed=i)
        loss = engine(toks, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses, engine


def make_models():
    kw = dict(vocab_size=VOCAB, max_seq_len=SEQ, num_layers=4,
              hidden_size=32, num_heads=4)
    return (GPT2.from_size("tiny", **kw),
            GPT2Pipelined.from_size("tiny", num_micro_batches=2, **kw))


@pytest.mark.parametrize("pp,mp", [(2, 1), (4, 1), (2, 2)])
def test_pipelined_training_matches_plain(pp, mp):
    """Same init + data: pipelined engine trajectory == plain GPT-2 (fp32),
    including composed with tensor parallelism."""
    plain, pipelined = make_models()
    ref, _ = run_engine(plain, make_mesh(model_parallel_size=mp,
                                         devices=jax.devices()[:4]))
    got, engine = run_engine(
        pipelined, make_mesh(pipeline_parallel_size=pp,
                             model_parallel_size=mp))
    assert engine.pp_world_size == pp
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_pipelined_with_context_parallel():
    """pp=2 x sp=2 x dp=2 (VERDICT r3 item 4 — the engine guard is lifted):
    the pipeline schedule streams sequence-sharded activations, ring
    attention runs inside the stage body, and the composed trajectory
    matches plain GPT-2."""
    plain, pipelined = make_models()
    ref, _ = run_engine(plain, make_mesh(devices=jax.devices()[:4]))
    got, engine = run_engine(
        pipelined, make_mesh(pipeline_parallel_size=2,
                             context_parallel_size=2))
    assert engine.pp_world_size == 2 and engine.sp_world_size == 2
    assert engine.dp_world_size == 2
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_pipelined_fp16_and_clipping_match():
    """The fp16 loss-scale FSM and grad clipping see pipe-partial grads —
    the norm dedup and overflow agreement must keep parity with plain."""
    plain, pipelined = make_models()
    over = dict(fp16={"enabled": True, "initial_scale_power": 8},
                gradient_clipping=0.1)
    ref, _ = run_engine(plain, make_mesh(devices=jax.devices()[:4]), **over)
    got, _ = run_engine(pipelined,
                        make_mesh(pipeline_parallel_size=2), **over)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)


def test_pipelined_train_batch_fused():
    """Fused train_batch parity vs the split API under pp=2."""
    _, pipelined = make_models()
    split, _ = run_engine(pipelined, make_mesh(pipeline_parallel_size=2),
                          steps=3)
    engine, _, _, _ = deepspeed_tpu.initialize(
        config={"train_batch_size": 8, "steps_per_print": 10 ** 6,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
        model=pipelined,
        model_parameters=pipelined.init_params(jax.random.PRNGKey(7)),
        mesh=make_mesh(pipeline_parallel_size=2))
    fused = [float(engine.train_batch(lm_batch(8, seed=i)))
             for i in range(3)]
    np.testing.assert_allclose(fused, split, rtol=2e-5, atol=2e-6)


def test_pipelined_sgd_scale_parity():
    """SGD is NOT gradient-scale invariant: this pins the absolute gradient
    scale (a uniform pp-factor — the psum-transpose of the stage-replicated
    loss — would shift the whole trajectory)."""
    plain, pipelined = make_models()
    over = dict(optimizer={"type": "SGD", "params": {"lr": 0.5}})
    ref, eref = run_engine(plain, make_mesh(devices=jax.devices()[:4]),
                           steps=2, **over)
    got, egot = run_engine(pipelined, make_mesh(pipeline_parallel_size=2),
                           steps=2, **over)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)
    for a, b in zip(jax.tree_util.tree_leaves(eref.master),
                    jax.tree_util.tree_leaves(egot.master)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_1f1b_training_matches_plain():
    """1F1B schedule (VERDICT r3 item 5), selected through the config key:
    trajectory == plain GPT-2, and the eval (primal, forward-only) path
    agrees with the differentiated schedule's loss."""
    plain, pipelined = make_models()
    ref, _ = run_engine(plain, make_mesh(devices=jax.devices()[:4]))
    got, engine = run_engine(
        pipelined, make_mesh(pipeline_parallel_size=2),
        pipeline_schedule="1f1b")
    # the config override reaches an ENGINE-OWNED copy; the caller's model
    # object keeps its own schedule (overrides must not leak into other
    # engines sharing the instance — see engine._own_model)
    assert engine.module.schedule == "1f1b"
    assert pipelined.schedule == "gpipe"
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
    engine.eval()
    toks, labels = lm_batch(8, seed=99)
    ev = float(engine(toks, labels))
    engine.train()
    tr = float(engine(toks, labels))
    assert ev == pytest.approx(tr, rel=1e-6)


def test_1f1b_sgd_scale_and_masters_parity():
    """SGD pins the absolute gradient scale: the custom_vjp must emit the
    same uniform pp-factor convention as GPipe autodiff or the whole
    trajectory shifts."""
    plain, _ = make_models()
    kw = dict(vocab_size=VOCAB, max_seq_len=SEQ, num_layers=4,
              hidden_size=32, num_heads=4)
    p1 = GPT2Pipelined.from_size("tiny", num_micro_batches=2,
                                 schedule="1f1b", **kw)
    over = dict(optimizer={"type": "SGD", "params": {"lr": 0.5}})
    ref, eref = run_engine(plain, make_mesh(devices=jax.devices()[:4]),
                           steps=2, **over)
    got, egot = run_engine(p1, make_mesh(pipeline_parallel_size=2),
                           steps=2, **over)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)
    for a, b in zip(jax.tree_util.tree_leaves(eref.master),
                    jax.tree_util.tree_leaves(egot.master)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)


def test_1f1b_peak_memory_below_gpipe():
    """The point of 1F1B: at m=8 micro-batches the compiled program's temp
    (activation) footprint is measurably below GPipe's — in-flight stage
    inputs are a 2·pp-1 ring, not m+pp-1 saved carries."""
    kw = dict(vocab_size=VOCAB, max_seq_len=32, num_layers=4,
              hidden_size=64, num_heads=4)

    def compiled_temp(schedule):
        model = GPT2Pipelined.from_size("tiny", num_micro_batches=8,
                                        schedule=schedule, **kw)
        params = model.init_params(jax.random.PRNGKey(0))
        mesh = make_mesh(pipeline_parallel_size=2,
                         devices=jax.devices()[:2])
        specs = model.partition_specs(params)
        fn = jax.jit(jax.shard_map(
            lambda p, t, l: jax.value_and_grad(
                lambda q: model.apply(q, t, l))(p),
            mesh=mesh, in_specs=(specs, P(), P()),
            out_specs=(P(), specs), check_vma=False))
        toks = np.zeros((16, 32), np.int32)
        labels = np.zeros((16, 32), np.int32)
        return fn.lower(params, toks, labels).compile() \
                 .memory_analysis().temp_size_in_bytes

    gpipe, f1b = compiled_temp("gpipe"), compiled_temp("1f1b")
    assert f1b < 0.95 * gpipe, (f1b, gpipe)


def test_1f1b_rejects_unknown_schedule():
    _, pipelined = make_models()
    pipelined.schedule = "zigzag"
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        run_engine(pipelined, make_mesh(pipeline_parallel_size=2), steps=1)


def test_sharded_head_fallback_indivisible_batch(caplog):
    """Per-shard batch 1 under pp=2 cannot split across stages; the head
    falls back to the replicated mask_to_last_stage path and the trajectory
    still matches plain GPT-2 — and the degraded path WARNS (one-time), so
    users know they left the scatter-collect fast path."""
    import logging

    kw = dict(vocab_size=VOCAB, max_seq_len=SEQ, num_layers=4,
              hidden_size=32, num_heads=4)
    plain = GPT2.from_size("tiny", **kw)
    pipelined = GPT2Pipelined.from_size("tiny", num_micro_batches=1, **kw)
    ref, _ = run_engine(plain, make_mesh(devices=jax.devices()[:4]),
                        batch=4)
    pipe_mod._warned_slow_paths.clear()
    with caplog.at_level(logging.WARNING):
        got, _ = run_engine(pipelined, make_mesh(pipeline_parallel_size=2),
                            batch=4)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
    assert any("full psum output collect" in r.message
               for r in caplog.records), caplog.records


def test_1f1b_replicated_head_fallback_warns(caplog):
    """1F1B with mb % pp != 0 runs the full-head masked VJP on every stage;
    the one-time warning must fire and the run stays finite."""
    import logging

    kw = dict(vocab_size=VOCAB, max_seq_len=SEQ, num_layers=4,
              hidden_size=32, num_heads=4)
    pipelined = GPT2Pipelined.from_size("tiny", num_micro_batches=1,
                                        schedule="1f1b", **kw)
    pipe_mod._warned_slow_paths.clear()
    with caplog.at_level(logging.WARNING):
        losses, _ = run_engine(pipelined,
                               make_mesh(pipeline_parallel_size=2),
                               steps=2, batch=4)
    assert all(np.isfinite(losses))
    assert any("REPLICATED" in r.message for r in caplog.records), \
        caplog.records
    # one-time: a second trace does not re-warn
    n = sum("REPLICATED" in r.message for r in caplog.records)
    assert n == 1


def test_warn_slow_path_once_is_one_time(caplog):
    import logging

    pipe_mod._warned_slow_paths.discard("unit_test_key")
    with caplog.at_level(logging.WARNING):
        pipe_mod.warn_slow_path_once("unit_test_key", "slow path taken")
        pipe_mod.warn_slow_path_once("unit_test_key", "slow path taken")
    assert sum("slow path taken" in r.message
               for r in caplog.records) == 1


def test_zero_and_checkpoint_compose_with_pipeline(tmpdir):
    """ZeRO-1 and checkpointing now compose with pp>1 (trajectory/resume
    parity pinned in tests/test_pipeline_ckpt.py); this pins the API accepts
    them and the save produces per-stage files."""
    _, pipelined = make_models()
    _, engine = run_engine(pipelined, make_mesh(pipeline_parallel_size=2),
                           steps=1, zero_optimization=True,
                           fp16={"enabled": True, "initial_scale_power": 8})
    assert engine.zero_enabled and engine.pp_world_size == 2
    engine.save_checkpoint(str(tmpdir), tag="t")
    import os
    files = os.listdir(os.path.join(str(tmpdir), "t"))
    assert any("pp_stage_01" in f for f in files), files


def test_1f1b_sharded_head_matches_plain():
    """The SHARDED in-schedule head branch (r5: mb % pp == 0 broadcasts
    the last stage's output and splits the head VJP 1/pp per stage) must
    be trajectory-identical to the plain model.  Every other 1F1B test
    here runs mb=1 and exercises only the replicated fallback — this
    config (pp=4, m=4, per-shard batch 16 -> mb=4) pins the sharded
    gradient path numerically: a wrong slice offset or psum-reassembly
    would shift every loss."""
    kw = dict(vocab_size=VOCAB, max_seq_len=SEQ, num_layers=4,
              hidden_size=32, num_heads=4)
    plain = GPT2.from_size("tiny", **kw)
    pipelined = GPT2Pipelined.from_size("tiny", num_micro_batches=4, **kw)
    ref, _ = run_engine(plain, make_mesh(), batch=32)
    got, engine = run_engine(
        pipelined, make_mesh(pipeline_parallel_size=4), batch=32,
        pipeline_schedule="1f1b")
    # per-shard micro-batch = 32*4/8/4 = 4, divisible by pp=4 -> sharded
    assert engine.module.schedule == "1f1b"
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_gpipe_scatter_collect_matches_plain():
    """The scatter-collect boundary (r5: psum_scatter delivers each stage
    its 1/pp output slice instead of psum-replicating the full volume)
    must be trajectory-identical to the plain model.  Most pipeline tests
    run mb < pp and take the full-collect fallback; this config (pp=2,
    per-shard batch 4, m=2 -> mb=2) exercises the scattered path."""
    kw = dict(vocab_size=VOCAB, max_seq_len=SEQ, num_layers=4,
              hidden_size=32, num_heads=4)
    plain = GPT2.from_size("tiny", **kw)
    pipelined = GPT2Pipelined.from_size("tiny", num_micro_batches=2, **kw)
    ref, _ = run_engine(plain, make_mesh(), batch=16)
    got, _ = run_engine(pipelined, make_mesh(pipeline_parallel_size=2),
                        batch=16)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
