"""deepspeed_tpu.resilience — preemption-safe training, fault injection,
hang detection.

Four cooperating pieces (docs/resilience.md):

* **preemption** (:mod:`.preempt`): SIGTERM/SIGINT (+ ``DSTPU_PREEMPT_FILE``
  sentinel) set a flag the step loop polls at optimizer boundaries; a psum
  agreement collective makes every host drain at the SAME step, take one
  emergency checkpoint (``emergency/`` tags), and exit
  ``RESUME_EXIT_CODE``.
* **auto-resume** (:mod:`.driver`): :func:`run_resumable` discovers the
  newest VALID checkpoint, restores engine + lr-scheduler + data-iterator
  state, and continues step-accurately; the launcher's ``--max_restarts``
  relaunch loop closes the circle.
* **hang watchdog** (:mod:`.watchdog`): a heartbeat thread armed around
  each blocking step/collective/checkpoint call; past the deadline it dumps
  all-thread stacks + recent step timings and (configurably) aborts with
  ``WATCHDOG_EXIT_CODE``.  Storage IO is additionally retry-wrapped
  (:func:`.retry.io_retry`).
* **fault injection** (:mod:`.chaos`): deterministic env/config-keyed
  injection points (IO error on Nth write, SIGTERM at step K, stall,
  non-finite loss) driving the ``chaos`` test tier.

Config: the ``resilience`` JSON block (``preempt_save``, ``max_restarts``,
``watchdog_timeout_s``, ``watchdog_abort``, ``io_retries``,
``nan_sentinel``) — docs/config.md.

This module (and everything it imports eagerly) stays importable without
jax: the launcher parent process imports the exit-code contract.
``run_resumable`` and friends load lazily.
"""

from deepspeed_tpu.resilience import chaos  # noqa: F401
from deepspeed_tpu.resilience.counters import COUNTERS, Counters  # noqa: F401
from deepspeed_tpu.resilience.preempt import (  # noqa: F401
    PREEMPT_FILE_ENV, PreemptionHandler, RESUME_EXIT_CODE, agree_any)
from deepspeed_tpu.resilience.retry import io_retry  # noqa: F401
from deepspeed_tpu.resilience.watchdog import (  # noqa: F401
    WATCHDOG_EXIT_CODE, Watchdog)

#: exit codes after which the launcher's --max_restarts loop relaunches
RESTARTABLE_EXIT_CODES = (RESUME_EXIT_CODE, WATCHDOG_EXIT_CODE)

_DRIVER_API = ("run_resumable", "restore_latest", "save_with_retry",
               "load_with_retry", "DATA_ITER_KEY", "EMERGENCY_PREFIX")


def __getattr__(name):
    # driver imports checkpoint (which imports jax and, for the chaos IO
    # hook, this package) — load it lazily to keep this module light and
    # cycle-free
    if name in _DRIVER_API or name == "driver":
        # importlib, not a from-import: ``from pkg import mod`` re-enters
        # this __getattr__ via _handle_fromlist before the submodule is
        # bound, recursing forever
        import importlib
        _driver = importlib.import_module("deepspeed_tpu.resilience.driver")
        return _driver if name == "driver" else getattr(_driver, name)
    raise AttributeError(
        f"module 'deepspeed_tpu.resilience' has no attribute {name!r}")
