"""Inference & serving engine (deepspeed_tpu/inference/, docs/inference.md).

The load-bearing pins:

* **Decode-path correctness oracle** — N-step incremental decode with the
  KV cache is EXACT vs a full-context re-forward on the same prompt
  (argmax-identical, logits within dtype tolerance), at mp=1 and mp=2.
* **Batching invariance** — a slot's output stream is identical whether
  it shares decode iterations with neighbours or runs alone (continuous
  batching must be a scheduling optimization, never a numerics change).
* **int8 exactness contract** — quantized serving within the documented
  relative-logit tolerance of the unquantized engine; the "scaled" and
  "dequant" matmul-dequant impls agree.
* **Weights-only restore** — ``checkpoint.load_params_only`` never opens
  a ``zero_pp_rank_*`` optimizer shard record.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import deepspeed_tpu
from deepspeed_tpu import checkpoint
from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.inference import (ContinuousScheduler, InferenceEngine,
                                     Request, StaticScheduler, kvcache,
                                     run_serve, synthetic_requests)
from deepspeed_tpu.models.gpt2 import GPT2

TINY = dict(vocab_size=128, max_seq_len=64, num_layers=2, hidden_size=64,
            num_heads=4)


def tiny_model():
    return GPT2.from_size("tiny", **TINY)


def serve_config(**inf):
    base = {"max_slots": 3, "max_tokens": 32, "prefill_bucket": 16,
            "page_tokens": 32, "dtype": "float32"}
    base.update(inf)
    return {"train_micro_batch_size_per_gpu": 1, "inference": base,
            "graph_lint": "error",
            "analysis": {"mode": "error", "profile": "v4-8"}}


@pytest.fixture(scope="module")
def eng_fp32():
    return InferenceEngine(tiny_model(), config=serve_config(), seed=0)


@pytest.fixture(scope="module")
def eng_mp2():
    cfg = serve_config()
    cfg["model_parallel_size"] = 2
    return InferenceEngine(tiny_model(), config=cfg, seed=0)


def _oracle(eng, prompt, steps, atol):
    """Incremental decode vs full-context re-forward, step by step."""
    eng.reset()
    logits = eng.prefill(0, prompt)
    seq = list(prompt)
    cur = int(np.argmax(logits))
    for _ in range(steps):
        seq.append(cur)
        ref = eng.prefill(1, seq)            # full re-forward, other slot
        feed = np.zeros(eng.num_slots, np.int32)
        feed[0] = cur
        act = np.zeros(eng.num_slots, bool)
        act[0] = True
        dec = eng.decode(feed, act)[0]
        assert int(np.argmax(dec)) == int(np.argmax(ref)), (
            "incremental decode argmax diverged from full re-forward")
        np.testing.assert_allclose(dec, ref, atol=atol)
        cur = int(np.argmax(dec))
    eng.reset()


def test_decode_oracle_exact_mp1(eng_fp32):
    _oracle(eng_fp32, [1, 2, 3, 4, 5], steps=5, atol=1e-4)


def test_decode_oracle_exact_mp2(eng_mp2, eng_fp32):
    _oracle(eng_mp2, [7, 8, 9], steps=5, atol=1e-4)
    # and mp=2 matches mp=1 on the same prompt (tensor parallelism is a
    # layout, not a model change)
    l1 = eng_fp32.prefill(0, [1, 2, 3, 4])
    l2 = eng_mp2.prefill(0, [1, 2, 3, 4])
    np.testing.assert_allclose(l1, l2, atol=1e-4)
    eng_fp32.reset()
    eng_mp2.reset()


def test_decode_oracle_bf16_within_dtype_tolerance():
    eng = InferenceEngine(tiny_model(),
                          config=serve_config(dtype="bfloat16"), seed=3)
    eng.reset()
    prompt = [5, 6, 7, 8]
    logits = eng.prefill(0, prompt)
    cur = int(np.argmax(logits))
    seq = list(prompt)
    for _ in range(3):
        seq.append(cur)
        ref = eng.prefill(1, seq)
        feed = np.zeros(eng.num_slots, np.int32)
        feed[0] = cur
        act = np.zeros(eng.num_slots, bool)
        act[0] = True
        dec = eng.decode(feed, act)[0]
        # bf16: same math, different reduction orders — dtype tolerance
        scale = np.max(np.abs(ref)) + 1e-9
        assert np.max(np.abs(dec - ref)) / scale < 0.05
        assert int(np.argmax(dec)) == int(np.argmax(ref))
        cur = int(np.argmax(dec))


# ------------------------------------------------------------ quantization

def test_int8_within_documented_tolerance(eng_fp32):
    engq = InferenceEngine(tiny_model(),
                           config=serve_config(quantize="int8"), seed=0)
    prompt = [1, 2, 3, 4, 5]
    lq = engq.prefill(0, prompt)
    lf = eng_fp32.prefill(0, prompt)
    eng_fp32.reset()
    # the exactness contract of docs/inference.md: relative logit error
    # under 5% (measured ~0.6% at this shape)
    rel = np.max(np.abs(lq - lf)) / (np.max(np.abs(lf)) + 1e-9)
    assert rel < 0.05, rel
    # int8 payloads actually live as int8 (the memory win is real)
    q = engq.params["blocks"]["qkv_w"]
    assert set(q) == {"q", "s"}
    assert np.asarray(q["q"]).dtype == np.int8
    assert engq.weight_bytes < eng_fp32.weight_bytes / 2

    # dispatch table: "scaled" (default) vs "dequant" agree within float
    # rounding; an invalid impl is rejected loudly
    os.environ["DSTPU_QUANT_MATMUL"] = "dequant"
    try:
        ld = engq.prefill(0, prompt)
    finally:
        del os.environ["DSTPU_QUANT_MATMUL"]
    np.testing.assert_allclose(lq, ld, atol=1e-4)
    os.environ["DSTPU_QUANT_MATMUL"] = "fast"
    try:
        from deepspeed_tpu.models import layers as L
        with pytest.raises(ValueError, match="DSTPU_QUANT_MATMUL"):
            L.quant_matmul_plan()
    finally:
        del os.environ["DSTPU_QUANT_MATMUL"]


def test_int8_generates_and_config_guard():
    engq = InferenceEngine(tiny_model(),
                           config=serve_config(quantize="int8"), seed=1)
    outs = engq.generate([[1, 2, 3]], max_new_tokens=4)
    assert len(outs[0]) == 4
    with pytest.raises(DeepSpeedConfigError, match="quantize"):
        InferenceEngine(tiny_model(), config=serve_config(quantize="int4"))


# ------------------------------------------------- continuous batching

def test_batching_invariance(eng_fp32):
    """A request's stream is identical solo vs sharing slots — the KV
    cache masks strictly per slot."""
    prompts = [[1, 2, 3], [9, 8, 7, 6], [4, 4]]
    eng_fp32.reset()
    together = eng_fp32.generate(prompts, max_new_tokens=6)
    solo = []
    for p in prompts:
        eng_fp32.reset()
        solo.append(eng_fp32.generate([p], max_new_tokens=6)[0])
    assert together == solo
    eng_fp32.reset()


def test_scheduler_admission_eviction_bookkeeping(eng_fp32):
    eng_fp32.reset()
    sched = ContinuousScheduler(eng_fp32)
    max_active = 0
    reqs = [Request(rid=i, prompt=[i + 1, i + 2],
                    max_new_tokens=3 + (i % 4)) for i in range(7)]
    for r in reqs:
        sched.submit(r)
    while sched.queue or sched.active:
        stats = sched.step()
        max_active = max(max_active, stats["active"])
        assert stats["active"] <= eng_fp32.num_slots
    assert max_active == eng_fp32.num_slots       # slots actually fill
    assert sched.admitted == 7 and sched.evicted == 7
    assert len(sched.results) == 7
    for r in sched.results:
        req = reqs[r.rid]
        assert len(r.tokens) == req.max_new_tokens
        assert r.finish_reason == "length"
        assert r.ttft_s is not None
        assert len(r.itl_s) == len(r.tokens) - 1
    eng_fp32.reset()


def test_eos_eviction(eng_fp32):
    """A sampler that emits EOS on the second token frees the slot early."""
    eng_fp32.reset()
    calls = {"n": 0}

    def eos_on_second(logits_row):
        calls["n"] += 1
        return 42 if calls["n"] >= 2 else 7

    sched = ContinuousScheduler(eng_fp32, sampler=eos_on_second)
    sched.submit(Request(rid=0, prompt=[1, 2, 3], max_new_tokens=20,
                         eos_id=42))
    results = sched.run()
    assert results[0].finish_reason == "eos"
    assert results[0].tokens == [7, 42]
    eng_fp32.reset()


def test_static_matches_continuous_with_more_iters(eng_fp32):
    reqs = synthetic_requests(6, vocab=TINY["vocab_size"], seed=5,
                              prompt_min=2, prompt_max=8, new_min=2,
                              new_max=9)
    eng_fp32.reset()
    cont = ContinuousScheduler(eng_fp32)
    cont_results = cont.run(list(reqs))
    eng_fp32.reset()
    static = StaticScheduler(eng_fp32)
    static_results = static.run(list(reqs))
    by_rid = {r.rid: r.tokens for r in cont_results}
    for r in static_results:
        assert by_rid[r.rid] == r.tokens
    # static decodes every batch to its longest member — it can never
    # need FEWER iterations than continuous on the same trace
    assert static.decode_iters >= cont.decode_iters
    eng_fp32.reset()


def test_prompt_guards(eng_fp32):
    with pytest.raises(ValueError, match="prefill bucket"):
        eng_fp32.prefill(0, list(range(17)))      # bucket is 16
    with pytest.raises(ValueError, match="empty"):
        eng_fp32.prefill(0, [])
    with pytest.raises(ValueError, match="slot"):
        eng_fp32.prefill(99, [1, 2])


def test_request_budget_rejected_at_submit(eng_fp32):
    """Over-budget requests fail at submit(), not mid-drain: past the
    paged capacity (or max_seq_len) decode would silently clamp the
    write row / position embedding and break the exactness contract."""
    assert eng_fp32.max_total_tokens() == 32      # min(capacity, max_seq)
    sched = ContinuousScheduler(eng_fp32)
    with pytest.raises(ValueError, match="token budget"):
        sched.submit(Request(rid=0, prompt=[1] * 10, max_new_tokens=30))
    with pytest.raises(ValueError, match="prefill bucket"):
        sched.submit(Request(rid=1, prompt=[1] * 17, max_new_tokens=1))
    assert sched.pending == 0                     # nothing half-admitted
    # the static baseline enforces the same contract up front
    with pytest.raises(ValueError, match="token budget"):
        StaticScheduler(eng_fp32).run(
            [Request(rid=2, prompt=[1] * 10, max_new_tokens=30)])
    # within budget still admits
    sched.submit(Request(rid=3, prompt=[1, 2], max_new_tokens=4))
    assert sched.pending == 1
    sched.queue.clear()


# ------------------------------------------------------------ KV cache

def test_ring_layout_wraps_and_paged_matches_below_capacity():
    cfgp = serve_config(max_tokens=8, prefill_bucket=8, page_tokens=8,
                        max_slots=2)
    cfgr = serve_config(max_tokens=8, prefill_bucket=8, page_tokens=8,
                        max_slots=2, kv_layout="ring")
    ep = InferenceEngine(tiny_model(), config=cfgp, seed=2)
    er = InferenceEngine(tiny_model(), config=cfgr, seed=2)
    assert er.cache_spec.ring and not ep.cache_spec.ring
    # below capacity the layouts are the same math
    outs_p = ep.generate([[1, 2, 3]], max_new_tokens=4)
    outs_r = er.generate([[1, 2, 3]], max_new_tokens=4)
    assert outs_p == outs_r
    # beyond capacity the ring wraps instead of clamping: positions keep
    # advancing and generation continues (windowed attention, documented
    # approximation)
    er.reset()
    out = er.generate([[1, 2, 3]], max_new_tokens=12)[0]
    assert len(out) == 12


def test_kvcache_arithmetic():
    assert kvcache.round_to_pages(100, 64) == 128
    spec = kvcache.KVCacheSpec(layers=2, slots=4, capacity=128,
                               kv_heads_local=4, head_dim=16,
                               dtype=np.float32)
    # 2 (k+v) * L * slots * cap * heads * dim * 4B
    assert kvcache.cache_bytes(spec) == 2 * 2 * 4 * 128 * 4 * 16 * 4
    n = kvcache.plan_slots(2, 4, 16, 128, np.float32,
                           hbm_bytes=10 * (1 << 20), weight_bytes=1 << 20,
                           headroom_frac=0.1)
    per_slot = 2 * 2 * 128 * 4 * 16 * 4
    assert n == (int(10 * (1 << 20) * 0.9) - (1 << 20)) // per_slot
    assert kvcache.plan_slots(2, 4, 16, 128, np.float32,
                              hbm_bytes=1 << 40, weight_bytes=0) == 256
    with pytest.raises(ValueError, match="does not fit"):
        kvcache.plan_slots(2, 4, 16, 128, np.float32,
                           hbm_bytes=1 << 20, weight_bytes=1 << 20)


def test_auto_slots_need_profile_and_size_against_it():
    cfg = serve_config(max_slots=0)
    cfg["analysis"] = {"mode": "off"}     # no profile configured
    with pytest.raises(ValueError, match="profile"):
        InferenceEngine(tiny_model(), config=cfg, seed=0)
    cfg2 = serve_config(max_slots=0)      # v4-8 profile: plenty of slots
    cfg2["analysis"]["mode"] = "off"      # auto-sized 256-slot cache is
    # bigger than the tiny gate fixtures need — sizing is what's under
    # test here, not the budget gate
    eng = InferenceEngine(tiny_model(), config=cfg2, seed=0)
    assert eng.num_slots == 256           # the auto cap, with this much HBM


# -------------------------------------------- lint + capacity plan gates

def test_serve_programs_lint_clean_and_planned(eng_fp32):
    rep = eng_fp32.run_graph_lint()
    assert not rep.errors, rep.format()
    plan = eng_fp32.plan_capacity()
    assert sorted(p.subject for p in plan.programs) == ["decode", "prefill"]
    assert plan.persistent["kv_cache_bytes"] == kvcache.cache_bytes(
        eng_fp32.cache_spec)
    assert plan.peak_bytes > 0
    assert "kv cache" in plan.format_table()


def test_memplan_gate_fails_closed_on_tiny_budget():
    from deepspeed_tpu.analysis import MemoryPlanError
    cfg = serve_config()
    cfg["analysis"] = {"mode": "error", "memory_budget_gb": 1e-6}
    with pytest.raises(MemoryPlanError):
        InferenceEngine(tiny_model(), config=cfg, seed=0)


def test_inference_config_validation():
    with pytest.raises(DeepSpeedConfigError, match="unknown inference"):
        InferenceEngine(tiny_model(),
                        config={"inference": {"slots": 4}})
    with pytest.raises(DeepSpeedConfigError, match="kv_layout"):
        InferenceEngine(tiny_model(),
                        config=serve_config(kv_layout="circular"))
    with pytest.raises(DeepSpeedConfigError, match="prefill_bucket"):
        InferenceEngine(tiny_model(),
                        config=serve_config(prefill_bucket=999))
    with pytest.raises(DeepSpeedConfigError, match="dtype"):
        InferenceEngine(tiny_model(), config=serve_config(dtype="int7"))


# ------------------------------------------------- weights-only restore

def _train_and_save(tmp_path, stage, fmt_kw=None):
    model = tiny_model()
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "bf16": {"enabled": True}}
    if stage:
        cfg["zero_optimization"] = {"stage": stage}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    toks = np.random.default_rng(0).integers(
        0, TINY["vocab_size"], (8, 16)).astype(np.int32)
    engine.train_batch((toks, toks.copy()))
    engine.save_checkpoint(str(tmp_path), **(fmt_kw or {}))
    return engine


@pytest.mark.parametrize("stage", [1, 3])
def test_load_params_only_skips_zero_shards(tmp_path, stage):
    engine = _train_and_save(tmp_path, stage)
    opened = []
    orig = checkpoint._load_obj

    def spy(path):
        opened.append(os.path.basename(path))
        return orig(path)

    checkpoint._load_obj = spy
    try:
        tag, tree = checkpoint.load_params_only(str(tmp_path))
    finally:
        checkpoint._load_obj = orig
    assert tag == "global_step1"
    # the regression pin: optimizer flat-partition shard records are
    # NEVER opened by the weights-only path
    assert not any(p.startswith("zero_pp_rank") for p in opened), opened
    for got, want in zip(jax.tree_util.tree_leaves(tree),
                         jax.tree_util.tree_leaves(engine.params)):
        np.testing.assert_array_equal(np.asarray(got, np.float32),
                                      np.asarray(want, np.float32))


def test_load_params_only_dtype_cast_and_parallel_parity(tmp_path):
    engine = _train_and_save(tmp_path, 1)
    _, t32 = checkpoint.load_params_only(str(tmp_path), dtype=np.float32)
    _, tbf = checkpoint.load_params_only(str(tmp_path), dtype="bfloat16")
    _, tser = checkpoint.load_params_only(str(tmp_path), threads=1)
    for a in jax.tree_util.tree_leaves(t32):
        assert a.dtype == np.float32
    for a in jax.tree_util.tree_leaves(tbf):
        assert str(a.dtype) == "bfloat16"
    # serial fallback executes the identical read plan — bitwise
    for a, b in zip(jax.tree_util.tree_leaves(tser),
                    jax.tree_util.tree_leaves(
                        checkpoint.load_params_only(str(tmp_path))[1])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    del engine


def test_serve_from_checkpoint_cold_start_numbers(tmp_path):
    """Checkpoint → tokens, with the cold-start facts recorded: the serve
    startup event carries restore_seconds + compile-cache counters
    exactly like the PR 9 training startup event."""
    _train_and_save(tmp_path, 1)
    eng = InferenceEngine(tiny_model(), config=serve_config(),
                          checkpoint_dir=str(tmp_path))
    assert eng.loaded_tag == "global_step1"
    assert eng.restore_seconds is not None and eng.restore_seconds > 0
    outs = eng.generate([[1, 2, 3]], max_new_tokens=3)
    assert len(outs[0]) == 3
    ev = eng.startup_event()
    from deepspeed_tpu.observability import schema
    assert schema.validate_any(ev) is None
    assert ev["restore_seconds"] is not None
    assert ev["time_to_first_step_s"] is not None
    assert ev["compile_cache_hits"] is not None


# ----------------------------------------------------- serve telemetry

def test_serve_jsonl_validator_clean(tmp_path, eng_fp32):
    eng_fp32.reset()
    path = str(tmp_path / "serve.jsonl")
    out = run_serve(eng_fp32,
                    synthetic_requests(5, vocab=TINY["vocab_size"],
                                       seed=2, prompt_min=2, prompt_max=8,
                                       new_min=2, new_max=6),
                    jsonl_path=path, window_iters=3)
    assert out["summary"]["tokens_out"] > 0
    assert out["summary"]["ttft_p99_ms"] is not None
    from deepspeed_tpu.observability import schema
    assert schema.validate_jsonl(path) == []
    events = [json.loads(l) for l in open(path)]
    serve = [e for e in events if e["schema"] == schema.SERVE_SCHEMA_ID]
    start = [e for e in events if e["schema"] == schema.STARTUP_SCHEMA_ID]
    assert serve and start
    assert serve[-1]["itl_p99_ms"] is not None
    # the validator CLI accepts the mixed serve/startup stream
    rc = subprocess.call([sys.executable, "-m",
                          "deepspeed_tpu.observability", path])
    assert rc == 0
    eng_fp32.reset()


def test_serve_event_schema_rejects_bad_events():
    from deepspeed_tpu.observability import schema
    good = {"schema": schema.SERVE_SCHEMA_ID, "version": 1, "ts": 1.0,
            "window": 1, "decode_iters": 4, "tokens_out": 9,
            "admitted": 2, "evicted": 1, "active_slots_mean": 1.5,
            "queue_depth": 0, "slots": 4, "kv_cache_gb": 0.1,
            "tokens_per_sec": 10.0, "ttft_p50_ms": 1.0,
            "ttft_p99_ms": 2.0, "itl_p50_ms": 0.5, "itl_p99_ms": 0.9,
            "counters": {}}
    assert schema.validate_any(good) is None
    bad_version = dict(good, version=9)
    assert "version" in schema.validate_any(bad_version)
    missing = dict(good)
    del missing["decode_iters"]
    assert "decode_iters" in schema.validate_any(missing)
    zero_iters = dict(good, decode_iters=0)
    assert "decode_iters" in schema.validate_any(zero_iters)
    assert "unknown schema" in schema.validate_any(
        {"schema": "dstpu.telemetry.nonsense", "version": 1})
