"""The full BingBert workflow as an executable test: pretrain on real
text (wordpiece vocab trained in-process) → export checkpoint + vocab →
fine-tune SQuAD from the transferred encoder → evaluate-v1.1 F1.

Drives the actual example scripts in subprocesses (the user-facing
surface), small step counts: this pins the MECHANICS of the hand-off —
vocab reuse, module-tree transfer, F1 reporting — not model quality
(tests/model/test_squad_f1.py owns the quality bar).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DATA = os.path.join(REPO, "tests", "model", "data", "squad_mini.json")


def _env():
    env = dict(os.environ)
    env.update({"PALLAS_AXON_POOL_IPS": "", "JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    env.pop("_DSTPU_TEST_ENV", None)
    return env


def _cfg(tmp_path, body):
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(body))
    return str(p)


def test_pretrain_then_finetune_end_to_end(tmp_path):
    corpus = tmp_path / "corpus.txt"
    with open(DATA) as f:
        data = json.load(f)["data"]
    lines = []
    for art in data:
        for para in art["paragraphs"]:
            lines.append(para["context"])
            lines += [q["question"] for q in para["qas"]]
    corpus.write_text("\n".join(lines))

    vocab = tmp_path / "vocab.txt"
    ckdir = tmp_path / "ck"
    pre_cfg = _cfg(tmp_path, {
        "train_batch_size": 8,
        "optimizer": {"type": "Lamb", "params": {"lr": 2e-3}},
        "fp16": {"enabled": True, "initial_scale_power": 8},
        "steps_per_print": 10 ** 6})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "bert",
                                      "pretrain_bert.py"),
         "--steps", "8", "--seq-len", "160", "--corpus", str(corpus),
         "--vocab-size", "768", "--save-vocab", str(vocab),
         "--save-checkpoint", str(ckdir),
         "--deepspeed_config", pre_cfg],
        env=_env(), cwd=REPO, capture_output=True, text=True, timeout=420)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    assert "checkpoint saved:" in out, out
    assert vocab.exists()

    ft_cfg = _cfg(tmp_path, {
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 2e-3}},
        "bf16": {"enabled": True},
        "steps_per_print": 10 ** 6})
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "bert",
                                      "squad_finetune.py"),
         "--steps", "10", "--seq-len", "160", "--doc-stride", "40",
         "--train-file", DATA, "--predict-file", DATA,
         "--vocab-file", str(vocab),
         "--init-checkpoint", str(ckdir),
         "--deepspeed_config", ft_cfg],
        env=_env(), cwd=REPO, capture_output=True, text=True, timeout=420)
    out = r.stdout + r.stderr
    assert r.returncode == 0, out
    # the transfer actually moved weights in (and skipped the QA head)
    assert "init-checkpoint: transferred" in out, out
    n_transferred = int(out.split("init-checkpoint: transferred ")[1]
                        .split(" ")[0])
    assert n_transferred >= 8, out
    # evaluate-v1.1 JSON line with the full example count
    result = json.loads([l for l in out.splitlines()
                         if l.startswith("{")][-1])
    assert result["total"] == 32 and 0.0 <= result["f1"] <= 100.0, result
