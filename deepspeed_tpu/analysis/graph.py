"""Jaxpr plumbing shared by the lint passes.

The passes never import jax internals beyond what this module wraps:

* :func:`subjaxprs` — version-tolerant discovery of nested jaxprs inside an
  equation (``scan``/``cond``/``pjit``/``shard_map``/``remat``/custom-vjp all
  carry them under different param names; we scan every param value for
  jaxpr-shaped objects instead of hard-coding the names).
* :func:`walk` — flat recursive iteration over every equation with its
  jaxpr path (``"shard_map/scan"``).
* :func:`source_of` — "file:line (function)" of the Python frame an equation
  was traced from, so findings point at model/engine code.
* :func:`Taint` — forward dataflow marking: seed some vars (or the outputs
  of seed primitives), propagate through equations in order, with a hook to
  stop propagation (the precision pass stops at down-casts).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax

try:  # the stable-ish internal home across 0.4.x
    from jax._src import source_info_util as _srcinfo
except Exception:  # pragma: no cover - future jax moved it
    _srcinfo = None

try:
    from jax._src import core as _core
except Exception:  # pragma: no cover
    _core = jax.core

Var = getattr(_core, "Var", None)
Literal = getattr(_core, "Literal", None)


def is_var(x) -> bool:
    return Var is not None and isinstance(x, Var)


def _as_open_jaxpr(obj):
    """Jaxpr from a Jaxpr | ClosedJaxpr, else None."""
    if obj is None:
        return None
    if hasattr(obj, "eqns") and hasattr(obj, "invars"):
        return obj                       # already an open Jaxpr
    inner = getattr(obj, "jaxpr", None)  # ClosedJaxpr
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return None


def subjaxprs(eqn) -> List[Tuple[str, object]]:
    """All nested jaxprs of one equation as ``(label, open_jaxpr)`` pairs.

    Labels are ``"<prim>"`` for a single sub-jaxpr and ``"<prim>.branchN"``
    when a param holds several (``cond`` branches).  Param values are probed
    structurally so new primitives keep working.
    """
    out: List[Tuple[str, object]] = []
    name = eqn.primitive.name
    for key, val in eqn.params.items():
        j = _as_open_jaxpr(val)
        if j is not None:
            out.append((name, j))
            continue
        if isinstance(val, (tuple, list)):
            js = [_as_open_jaxpr(v) for v in val]
            if js and all(x is not None for x in js):
                if len(js) == 1:
                    out.append((name, js[0]))
                else:
                    out.extend((f"{name}.branch{i}", x)
                               for i, x in enumerate(js))
    return out


def walk(jaxpr, path: str = "") -> Iterator[Tuple[object, str]]:
    """Yield ``(eqn, path)`` for every equation, depth-first, including all
    nested sub-jaxprs.  ``jaxpr`` may be open or closed."""
    j = _as_open_jaxpr(jaxpr)
    if j is None:
        return
    for eqn in j.eqns:
        yield eqn, path
        for label, sub in subjaxprs(eqn):
            sub_path = f"{path}/{label}" if path else label
            yield from walk(sub, sub_path)


def source_of(eqn) -> str:
    """Best-effort "file:line (function)" for an equation."""
    si = getattr(eqn, "source_info", None)
    if si is None or _srcinfo is None:
        return ""
    try:
        return _srcinfo.summarize(si)
    except Exception:  # pragma: no cover - defensive across jax versions
        return ""


def aval_of(atom):
    """The abstract value of a Var or Literal."""
    return getattr(atom, "aval", None)


def dtype_of(atom):
    aval = aval_of(atom)
    return getattr(aval, "dtype", None)


def size_of(atom) -> int:
    aval = aval_of(atom)
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        try:
            n *= int(d)
        except Exception:   # symbolic dims: treat as big
            return 1 << 62
    return n


class Taint:
    """Forward dataflow taint over one jaxpr level.

    Marked vars are tracked by identity.  Use :meth:`step` on each equation
    in program order; it marks the outputs when any input is marked (unless
    ``stop(eqn)`` says the equation launders the taint) and returns whether
    any input was marked.  Sub-jaxpr seeding: :meth:`seed_sub` maps the
    marking of an equation's invars onto a nested jaxpr's invars
    (tail-aligned, which matches scan/cond/pjit/shard_map operand layout
    closely enough for lint purposes).
    """

    def __init__(self, marked: Optional[set] = None):
        self.marked = set(marked or ())

    def mark(self, var) -> None:
        if is_var(var):
            self.marked.add(var)

    def is_marked(self, atom) -> bool:
        return is_var(atom) and atom in self.marked

    def any_marked(self, atoms: Sequence) -> bool:
        return any(self.is_marked(a) for a in atoms)

    def step(self, eqn, stop=None) -> bool:
        hit = self.any_marked(eqn.invars)
        if hit and not (stop is not None and stop(eqn)):
            for v in eqn.outvars:
                self.mark(v)
        return hit

    def seed_sub(self, eqn, sub_jaxpr) -> "Taint":
        sub = _as_open_jaxpr(sub_jaxpr)
        sub_in = list(sub.invars)
        outer_in = list(eqn.invars)
        t = Taint()
        # tail-align: scan prepends consts, cond prepends the predicate —
        # in both cases the trailing operands line up positionally
        k = min(len(sub_in), len(outer_in))
        for sv, ov in zip(sub_in[len(sub_in) - k:],
                          outer_in[len(outer_in) - k:]):
            if self.is_marked(ov):
                t.mark(sv)
        return t

    def propagate_out(self, eqn, sub_jaxpr, sub_taint: "Taint") -> None:
        """Carry a sub-jaxpr's output marking back onto the equation's
        outvars (tail-aligned, like :meth:`seed_sub`), so taint computed
        inside cond/scan/pjit bodies survives into the enclosing level."""
        sub = _as_open_jaxpr(sub_jaxpr)
        sub_out = list(sub.outvars)
        outer_out = list(eqn.outvars)
        k = min(len(sub_out), len(outer_out))
        for sv, ov in zip(sub_out[len(sub_out) - k:],
                          outer_out[len(outer_out) - k:]):
            if sub_taint.is_marked(sv):
                self.mark(ov)


class AxisTaint:
    """Per-axis rank-dependence tracking for the collective pass.

    Each var maps to the set of mesh axes whose *rank identity* its value
    depends on: ``axis_index(a)`` seeds ``{a}``, ordinary equations union
    their inputs' sets, and a full-axis reduction (``psum``/``pmax``/... with
    ``axis_index_groups=None``) REMOVES the reduced axes — its result is
    replicated over them, so a predicate built from it cannot diverge
    (the global-vote pattern: ``cond(psum(flag) > 0, ...)`` is uniform).
    """

    def __init__(self):
        self.axes = {}            # Var -> frozenset of axis names

    def mark(self, var, axes) -> None:
        if is_var(var) and axes:
            self.axes[var] = frozenset(self.axes.get(var, frozenset())
                                       | frozenset(axes))

    def axes_of(self, atom) -> frozenset:
        if is_var(atom):
            return self.axes.get(atom, frozenset())
        return frozenset()

    def union_in(self, eqn) -> frozenset:
        out = frozenset()
        for a in eqn.invars:
            out |= self.axes_of(a)
        return out

    def step(self, eqn, removed=()) -> None:
        axes = self.union_in(eqn) - frozenset(removed)
        for v in eqn.outvars:
            self.mark(v, axes)

    def seed_sub(self, eqn, sub_jaxpr) -> "AxisTaint":
        sub = _as_open_jaxpr(sub_jaxpr)
        sub_in = list(sub.invars)
        outer_in = list(eqn.invars)
        t = AxisTaint()
        k = min(len(sub_in), len(outer_in))
        for sv, ov in zip(sub_in[len(sub_in) - k:],
                          outer_in[len(outer_in) - k:]):
            t.mark(sv, self.axes_of(ov))
        return t

    def propagate_out(self, eqn, sub_jaxpr, sub_taint: "AxisTaint") -> None:
        sub = _as_open_jaxpr(sub_jaxpr)
        sub_out = list(sub.outvars)
        outer_out = list(eqn.outvars)
        k = min(len(sub_out), len(outer_out))
        for sv, ov in zip(sub_out[len(sub_out) - k:],
                          outer_out[len(outer_out) - k:]):
            self.mark(ov, sub_taint.axes_of(sv))
