"""Optimizer numerics vs closed-form references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import ops


def np_adam_reference(p, g, m, v, t, lr=1e-3, b1=0.9, b2=0.999, eps=1e-8,
                      scale=1.0, wd=0.0):
    """Mirror of the fused kernel math (apex-style step-size bias
    correction)."""
    sg = g / scale
    m = b1 * m + (1 - b1) * sg
    v = b2 * v + (1 - b2) * sg * sg
    step_size = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
    upd = m / (np.sqrt(v) + eps) + wd * p
    return p - step_size * upd, m, v


def test_adam_matches_closed_form():
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(5, 3)).astype(np.float32)
    params = {"w": jnp.asarray(p0)}
    opt = ops.Adam(lr=1e-2, weight_decay=0.01)
    state = opt.init(params)

    p_np, m_np, v_np = p0.copy(), np.zeros_like(p0), np.zeros_like(p0)
    for t in range(1, 5):
        g = rng.normal(size=p0.shape).astype(np.float32)
        params, state = opt.update(params, {"w": jnp.asarray(g)}, state)
        p_np, m_np, v_np = np_adam_reference(p_np, g, m_np, v_np, t,
                                             lr=1e-2, wd=0.01)
        np.testing.assert_allclose(np.asarray(params["w"]), p_np, rtol=2e-5,
                                   atol=1e-7)
        assert int(state.step) == t


def test_adam_combined_scale_divides_grads():
    params = {"w": jnp.ones((4,))}
    opt = ops.Adam(lr=1e-2)
    s = opt.init(params)
    p1, _ = opt.update(params, {"w": jnp.full((4,), 8.0)}, s, combined_scale=8.0)
    p2, _ = opt.update(params, {"w": jnp.full((4,), 1.0)}, s, combined_scale=1.0)
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(p2["w"]), rtol=1e-6)


def test_adamw_decoupled_decay():
    params = {"w": jnp.full((4,), 2.0)}
    g = {"w": jnp.zeros((4,))}
    aw = ops.AdamW(lr=0.1, weight_decay=0.1)
    s = aw.init(params)
    p, _ = aw.update(params, g, s)
    # zero grads: update term 0, only decoupled decay applies: p - lr*wd*p
    np.testing.assert_allclose(np.asarray(p["w"]), 2.0 - 0.1 * 0.1 * 2.0,
                               rtol=1e-6)


def test_lamb_trust_ratio_clamped():
    # ||w|| huge, ||update|| tiny -> ratio clamps at max_coeff
    params = {"w": jnp.full((16,), 100.0)}
    g = {"w": jnp.full((16,), 1e-6)}
    lamb = ops.Lamb(lr=1.0, max_coeff=10.0, min_coeff=0.01,
                    bias_correction=False)
    s = lamb.init(params)
    p, _ = lamb.update(params, g, s)
    # m = 0.1*g_scaled tiny; denom ~ sqrt(v)+eps; update magnitude bounded;
    # delta = lr * coeff * upd with coeff == 10
    delta = 100.0 - np.asarray(p["w"])[0]
    # compute expected update leafwise
    sg = 1e-6
    m = 0.1 * sg
    v = 0.001 * sg * sg
    upd = m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(delta, 10.0 * upd, rtol=1e-4)


def test_lamb_zero_norm_coeff_is_one():
    # zero params -> ||w||=0 -> coeff 1.0 (kernel part3: only scale when both
    # norms nonzero)
    params = {"w": jnp.zeros((8,))}
    g = {"w": jnp.ones((8,))}
    lamb = ops.Lamb(lr=0.1, bias_correction=False)
    s = lamb.init(params)
    p, _ = lamb.update(params, g, s)
    sg = 1.0
    m = 0.1 * sg
    v = 0.001
    upd = m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(np.asarray(p["w"]), -0.1 * upd, rtol=1e-4)


def test_lamb_per_tensor_ratio_differs():
    # two leaves with very different scales get different trust ratios
    params = {"a": jnp.full((4,), 100.0), "b": jnp.full((4,), 0.1)}
    g = {"a": jnp.ones((4,)), "b": jnp.ones((4,))}
    lamb = ops.Lamb(lr=0.01, bias_correction=False)
    s = lamb.init(params)
    p, _ = lamb.update(params, g, s)
    da = 100.0 - float(p["a"][0])
    db = 0.1 - float(p["b"][0])
    assert da / db > 10  # big-norm tensor took a much larger step


def test_sgd_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(1)
    p0 = rng.normal(size=(6,)).astype(np.float32)
    tp = torch.nn.Parameter(torch.tensor(p0))
    topt = torch.optim.SGD([tp], lr=0.1, momentum=0.9)

    params = {"w": jnp.asarray(p0)}
    opt = ops.Sgd(lr=0.1, momentum=0.9)
    s = opt.init(params)
    for _ in range(3):
        g = rng.normal(size=p0.shape).astype(np.float32)
        tp.grad = torch.tensor(g)
        topt.step()
        params, s = opt.update(params, {"w": jnp.asarray(g)}, s)
    np.testing.assert_allclose(np.asarray(params["w"]), tp.detach().numpy(),
                               rtol=1e-5, atol=1e-7)


def test_none_grads_leave_params_untouched():
    # reference: p.grad None params are skipped (deepspeed_fused_lamb.py:151)
    params = {"w": jnp.ones((4,)), "frozen": jnp.full((2,), 5.0)}
    g = {"w": jnp.ones((4,)), "frozen": None}
    for opt in (ops.Adam(lr=0.1), ops.Lamb(lr=0.1), ops.Sgd(lr=0.1)):
        s = opt.init(params)
        p, _ = opt.update(params, g, s)
        np.testing.assert_array_equal(np.asarray(p["frozen"]),
                                      np.full((2,), 5.0))
        assert not np.array_equal(np.asarray(p["w"]), np.ones((4,)))


def test_from_config():
    o = ops.from_config("adam", {"lr": 0.1, "betas": [0.8, 0.88], "eps": 1e-6,
                                 "weight_decay": 0.01, "max_grad_norm": 0.0})
    assert isinstance(o, ops.Adam)
    assert o.lr == 0.1 and o.beta1 == 0.8 and o.beta2 == 0.88
    o = ops.from_config("lamb", {"lr": 0.004, "max_coeff": 0.5,
                                 "min_coeff": 0.08})
    assert isinstance(o, ops.Lamb)
    assert o.max_coeff == 0.5 and o.min_coeff == 0.08
    o = ops.from_config("sgd", {"lr": 0.1, "momentum": 0.9})
    assert isinstance(o, ops.Sgd) and o.momentum == 0.9
    with pytest.raises(ValueError):
        ops.from_config("nonexistent_optimizer", {})


def test_update_is_jittable():
    opt = ops.Adam(lr=1e-3)
    params = {"w": jnp.ones((8, 8))}
    s = opt.init(params)
    f = jax.jit(lambda p, g, s, lr: opt.update(p, g, s, lr=lr))
    p, s2 = f(params, {"w": jnp.ones((8, 8))}, s, 1e-3)
    assert int(s2.step) == 1
