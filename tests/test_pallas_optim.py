"""Pallas fused optimizer kernels vs the pure-XLA reference path.

The reference validates its CUDA LAMB against convergence suites; here the
fused kernels are validated directly against ops/optim.py's leaf math
(same numerics contract as csrc/fused_lamb_cuda_kernel.cu) in interpreter
mode on CPU — sizes chosen to exercise padding (non-multiples of 128/tile)
and multi-block grids."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops import optim as optim_mod
from deepspeed_tpu.ops.pallas_optim import (fused_adam_update,
                                            fused_lamb_update)


def rand(shape, seed):
    return jnp.asarray(
        np.random.default_rng(seed).normal(size=shape).astype(np.float32))


def reference_leaf(opt, p, g, m, v, *, lr, combined_scale=1.0, step=1):
    """Drive the pure-XLA path via a single-leaf pytree."""
    state = optim_mod.OptimizerState(
        step=jnp.asarray(step - 1, jnp.int32), m={"x": m}, v={"x": v})
    newp, newstate = dataclasses_replace_update(
        opt, {"x": p}, {"x": g}, state, lr=lr, combined_scale=combined_scale)
    return newp["x"], newstate.m["x"], newstate.v["x"]


def dataclasses_replace_update(opt, params, grads, state, **kw):
    import dataclasses
    xla_opt = dataclasses.replace(opt, use_pallas=False)
    return xla_opt.update(params, grads, state, **kw)


@pytest.mark.parametrize("n", [100, 128 * 8, 1000, 128 * 512 + 77])
@pytest.mark.parametrize("scale", [1.0, 64.0])
def test_fused_lamb_matches_xla(n, scale):
    opt = optim_mod.Lamb(lr=0.002, weight_decay=0.01,
                         max_coeff=10.0, min_coeff=0.01)
    p, g, m, v = (rand((n,), s) for s in range(4))
    v = jnp.abs(v)
    step_size = opt._step_size(0.002, jnp.asarray(3.0), 0.9, 0.999)

    want = reference_leaf(opt, p, g * scale, m, v, lr=0.002,
                          combined_scale=scale, step=3)
    got = fused_lamb_update(
        p, g * scale, m, v, beta1=0.9, beta2=0.999, eps=opt.eps,
        weight_decay=0.01, combined_scale=scale, step_size=step_size,
        min_coeff=0.01, max_coeff=10.0, block_rows=128, interpret=True)

    for w, h in zip(want, got):
        np.testing.assert_allclose(np.asarray(w), np.asarray(h),
                                   rtol=1e-5, atol=1e-7)


def test_fused_lamb_zero_param_norm_gives_unit_coeff():
    """coeff falls back to 1.0 when ‖w‖==0 (kernel.cu:319-329)."""
    n = 256
    p = jnp.zeros((n,), jnp.float32)
    g, m, v = rand((n,), 1), rand((n,), 2), jnp.abs(rand((n,), 3))
    opt = optim_mod.Lamb(lr=0.01, weight_decay=0.0)
    step_size = opt._step_size(0.01, jnp.asarray(1.0), 0.9, 0.999)
    want = reference_leaf(opt, p, g, m, v, lr=0.01, step=1)
    got = fused_lamb_update(
        p, g, m, v, beta1=0.9, beta2=0.999, eps=opt.eps, weight_decay=0.0,
        combined_scale=1.0, step_size=step_size, min_coeff=0.01,
        max_coeff=10.0, block_rows=8, interpret=True)
    np.testing.assert_allclose(np.asarray(want[0]), np.asarray(got[0]),
                               rtol=1e-5, atol=1e-8)


@pytest.mark.parametrize("decoupled", [False, True])
@pytest.mark.parametrize("n", [100, 128 * 64 + 3])
def test_fused_adam_matches_xla(n, decoupled):
    opt = (optim_mod.AdamW if decoupled else optim_mod.Adam)(
        lr=0.001, weight_decay=0.05)
    p, g, m, v = (rand((n,), 10 + s) for s in range(4))
    v = jnp.abs(v)
    step_size = opt._step_size(0.001, jnp.asarray(5.0), 0.9, 0.999)

    want = reference_leaf(opt, p, g, m, v, lr=0.001, step=5)
    got = fused_adam_update(
        p, g, m, v, beta1=0.9, beta2=0.999, eps=opt.eps, weight_decay=0.05,
        combined_scale=1.0, step_size=step_size, lr=0.001,
        decoupled_decay=decoupled, block_rows=64, interpret=True)
    for w, h in zip(want, got):
        np.testing.assert_allclose(np.asarray(w), np.asarray(h),
                                   rtol=1e-5, atol=1e-7)


def test_fused_2d_shapes_roundtrip():
    """Non-flat tensors tile and untile losslessly."""
    p = rand((37, 19), 0)
    g, m, v = rand((37, 19), 1), rand((37, 19), 2), jnp.abs(rand((37, 19), 3))
    opt = optim_mod.Adam(lr=0.001)
    step_size = opt._step_size(0.001, jnp.asarray(1.0), 0.9, 0.999)
    got = fused_adam_update(
        p, g, m, v, beta1=0.9, beta2=0.999, eps=1e-8, weight_decay=0.0,
        combined_scale=1.0, step_size=step_size, lr=0.001,
        block_rows=8, interpret=True)
    assert got[0].shape == (37, 19)
    want = reference_leaf(opt, p, g, m, v, lr=0.001, step=1)
    np.testing.assert_allclose(np.asarray(want[0]), np.asarray(got[0]),
                               rtol=1e-5, atol=1e-7)
