"""On-device multi-step driver (engine.train_many) + D-fused decode.

The contracts this file pins (ISSUE 12):

* **Bitwise trajectory parity**: K fused steps in ONE dispatch produce
  the IDENTICAL master/loss-scale/LR/skip trajectory as K serial
  ``train_batch`` dispatches — across ZeRO stages 0/1/2/3, gas>1,
  fp16-with-skips (mid-block!), and with an LR scheduler (whose hypers
  ride the scanned [K, 4, G] stage, h_idx-gated by the in-program skip
  flags).
* **Host-boundary accounting**: predicted executables ==
  ``compile_cache_misses`` and predicted fences == ``FENCE_COUNT`` over
  real K-fused runs (PR 11 style), with the skip-contract fence
  amortized to once per K-block.
* **Serving analog**: D fused decode iterations per dispatch keep the
  greedy-output-identity and batching-invariance contracts, with one
  counted fence per D-block.
* **Resilience × K**: a preemption request lands mid-block and drains at
  the NEXT K boundary with a bitwise resume; the watchdog deadline
  scales by K so a healthy K-block never fires a 1-step deadline.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu as ds
from deepspeed_tpu import analysis, resilience
from deepspeed_tpu.analysis import dispatchplan, stability
from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.data import BlockPrefetcher
from deepspeed_tpu.observability import fences as obs_fences
from deepspeed_tpu.resilience import (COUNTERS, PreemptionHandler,
                                      RESUME_EXIT_CODE, Watchdog, chaos)
from deepspeed_tpu.utils import compile_cache

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from simple_model import SimpleModel, master_bytes  # noqa: E402

HIDDEN = 8
TINY_GPT2 = dict(vocab_size=64, max_seq_len=16, num_layers=2,
                 hidden_size=32, num_heads=2)


def base_config(**over):
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 2,
        "steps_per_print": 10 ** 9,
        "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
    }
    cfg.update(over)
    return cfg


def make_engine(cfg):
    engine, _, _, _ = ds.initialize(model=SimpleModel(hidden_dim=HIDDEN),
                                    config=dict(cfg))
    return engine


def batch(i, n=16, dtype=np.float32, poison=False):
    rng = np.random.default_rng(1000 + i)
    x = rng.normal(size=(n, HIDDEN)).astype(dtype)
    if poison:
        x[0, 0] = np.inf
    y = rng.integers(0, HIDDEN, size=(n,)).astype(np.int32)
    return (x, y)


def gpt2_engine(cfg):
    from deepspeed_tpu.models.gpt2 import GPT2
    engine, _, _, _ = ds.initialize(
        model=GPT2.from_size("tiny", **TINY_GPT2), config=dict(cfg))
    return engine


def gpt2_batch(i, n=8):
    rng = np.random.default_rng(2000 + i)
    ids = rng.integers(0, 64, size=(n, 16)).astype(np.int32)
    return (ids, ids)


def trajectory_state(engine):
    """Everything the parity contract compares: master bytes + the host
    bookkeeping the block form must keep in lockstep."""
    return (master_bytes(engine), engine.global_steps,
            engine.skipped_steps, engine.optimizer.cur_scale,
            tuple(g["lr"] for g in engine.optimizer.param_groups))


@pytest.fixture(autouse=True)
def _clean():
    chaos.reset()
    yield
    chaos.reset()


@pytest.fixture
def cold_cache(tmp_path):
    d = str(tmp_path / "cc")
    compile_cache.enable(d)
    jax.clear_caches()
    yield d
    compile_cache.disable()


# =====================================================================
# bitwise trajectory parity: K fused vs K serial train_batch
# =====================================================================

PARITY_CASES = [
    ("stage0_fp32_gas2", base_config(), np.float32),
    ("stage0_bf16_gas2", base_config(bf16={"enabled": True}), np.float32),
    ("stage1_fp16", base_config(zero_optimization={"stage": 1},
                                fp16={"enabled": True,
                                      "loss_scale": 128.0}),
     np.float16),
    ("stage2_bf16", base_config(zero_optimization={"stage": 2},
                                bf16={"enabled": True}), np.float32),
    ("fp16_dynamic_sched", base_config(
        fp16={"enabled": True, "loss_scale": 0},
        scheduler={"type": "WarmupLR",
                   "params": {"warmup_num_steps": 10,
                              "warmup_max_lr": 0.01}}), np.float16),
    ("bf16_sentinel", base_config(bf16={"enabled": True},
                                  resilience={"nan_sentinel": True}),
     np.float32),
]


@pytest.mark.parametrize("name,cfg,dtype",
                         PARITY_CASES, ids=[c[0] for c in PARITY_CASES])
def test_parity_bitwise(name, cfg, dtype):
    K = 4
    e1 = make_engine(cfg)
    e2 = make_engine(cfg)
    bs = [batch(i, dtype=dtype) for i in range(K)]
    serial_losses = [e1.train_batch(b) for b in bs]
    loss_many = e2.train_many(bs)
    assert trajectory_state(e1) == trajectory_state(e2)
    # the driver returns the LAST step's loss, equal to serial's
    assert float(jax.tree_util.tree_leaves(serial_losses[-1])[0]) \
        == float(jax.tree_util.tree_leaves(loss_many)[0])


def test_parity_bitwise_zero3_gpt2():
    """Stage 3 with really-partitioned GPT-2 leaves (dp=8 virtual
    devices), lint + capacity gates in error mode riding along: the
    cond-isolated K-step program must be gate-clean AND bitwise."""
    cfg = {"train_batch_size": 16, "gradient_accumulation_steps": 2,
           "steps_per_print": 10 ** 9,
           "optimizer": {"type": "Adam", "params": {"lr": 0.01}},
           "bf16": {"enabled": True}, "zero_optimization": {"stage": 3},
           "graph_lint": "error",
           "analysis": {"mode": "error", "profile": "v4-8"}}
    K = 3
    e1 = gpt2_engine(cfg)
    e2 = gpt2_engine(cfg)
    bs = [gpt2_batch(i, n=16) for i in range(K)]
    for b in bs:
        e1.train_batch(b)
    e2.train_many(bs)
    assert master_bytes(e1) == master_bytes(e2)
    assert e1.global_steps == e2.global_steps == K


def test_parity_fp16_skip_mid_block_with_scheduler():
    """A REAL overflow in the middle of a K-block under fp16 + LR
    scheduler: the in-program h_idx gating must hold the prospective
    hyper row back on the skipped boundary, and the host replay must
    leave the scheduler at exactly the serial position — bitwise master,
    identical skip count, identical LR."""
    cfg = base_config(
        fp16={"enabled": True, "loss_scale": 128.0},
        scheduler={"type": "WarmupLR",
                   "params": {"warmup_num_steps": 10,
                              "warmup_max_lr": 0.01}})
    K = 4
    e1 = make_engine(cfg)
    e2 = make_engine(cfg)
    bs = [batch(0, dtype=np.float16),
          batch(1, dtype=np.float16, poison=True),   # skips mid-block
          batch(2, dtype=np.float16),
          batch(3, dtype=np.float16)]
    for b in bs:
        e1.train_batch(b)
    e2.train_many(bs)
    assert e1.skipped_steps == e2.skipped_steps == 1
    assert trajectory_state(e1) == trajectory_state(e2)


def test_parity_spool_on_off_and_deferred_skip(tmp_path):
    """Trajectory neutrality of the K in-program spool appends (spool
    on == spool off bitwise), and the deferred skip bookkeeping settling
    at the window drain: a poisoned mid-block step under the nan
    sentinel never takes a host read, yet skipped_steps catches up."""
    K = 2
    plain = base_config(bf16={"enabled": True},
                        resilience={"nan_sentinel": True})
    spooled = dict(plain)
    spooled["train_steps_per_dispatch"] = K
    spooled["observability"] = {
        "report_window": 4, "jsonl_path": str(tmp_path / "t.jsonl")}
    e1 = make_engine(plain)
    e2 = make_engine(spooled)
    blocks = [[batch(0), batch(1, poison=True)], [batch(2), batch(3)]]
    f0 = obs_fences.FENCE_COUNT
    for blk in blocks:
        e1.train_many(blk)
        e2.train_many(blk)
    assert master_bytes(e1) == master_bytes(e2)
    # spooled run: the [K] skip read DEFERS to the drain — zero fences
    # beyond the plain engine's one per block
    assert obs_fences.FENCE_COUNT - f0 == len(blocks)   # plain engine only
    e2.flush_telemetry()
    assert e2.skipped_steps == e1.skipped_steps == 1
    events = [json.loads(l) for l in open(tmp_path / "t.jsonl")]
    wins = [e for e in events if e["schema"].endswith(".window")]
    assert [w["window_steps"] for w in wins] == [4]
    assert wins[0]["skipped"] == 1


def test_mixed_train_batch_then_block_flushes_straddle(tmp_path):
    """A stray train_batch on a K>1 spooled engine leaves the ring
    mid-window; the next K-block would wrap over the undrained row
    IN-PROGRAM — train_many must deliver the partial window first
    (would_straddle → flush), so every window row stays correctly
    attributed."""
    K = 4
    engine = make_engine(base_config(
        train_steps_per_dispatch=K, bf16={"enabled": True},
        observability={"report_window": K,
                       "jsonl_path": str(tmp_path / "t.jsonl")}))
    serial = make_engine(base_config(bf16={"enabled": True}))
    engine.train_batch(batch(0))                  # ring row 0, undrained
    serial.train_batch(batch(0))
    engine.train_many([batch(i) for i in range(1, K + 1)])
    for i in range(1, K + 1):
        serial.train_batch(batch(i))
    engine.flush_telemetry()
    assert master_bytes(engine) == master_bytes(serial)
    evs = [json.loads(l) for l in open(tmp_path / "t.jsonl")]
    wins = [e for e in evs if e["schema"].endswith(".window")]
    # the straddle flush delivered the 1-row partial, then the block's
    # crossing drain the 4-row window — 5 boundaries, none dropped or
    # misattributed
    assert [w["window_steps"] for w in wins] == [1, K]
    assert [w["step"] for w in wins] == [1, 1 + K]


def test_train_many_k1_matches_train_batch():
    """K=1 through the multi-step builder is the degenerate case — still
    bitwise with train_batch (same per-step body, cond-isolated)."""
    e1 = make_engine(base_config(bf16={"enabled": True}))
    e2 = make_engine(base_config(bf16={"enabled": True}))
    e1.train_batch(batch(0))
    e2.train_many([batch(0)])
    assert trajectory_state(e1) == trajectory_state(e2)


# =====================================================================
# validation + config surface
# =====================================================================

def test_train_many_rejects_mixed_formats_and_bad_leads():
    engine = make_engine(base_config(bf16={"enabled": True}))
    with pytest.raises(ValueError, match="share one"):
        engine.train_many([batch(0), batch(1, n=8)])
    with pytest.raises(ValueError, match="non-empty"):
        engine.train_many([])
    with pytest.raises(ValueError, match="not divisible"):
        engine.train_many([batch(0, n=15)])


def test_config_window_must_be_multiple_of_k():
    with pytest.raises(DeepSpeedConfigError, match="multiple"):
        DeepSpeedConfig(base_config(
            train_steps_per_dispatch=3,
            observability={"report_window": 4}), dp_world_size=1)
    # aligned is fine
    cfg = DeepSpeedConfig(base_config(
        train_steps_per_dispatch=3,
        observability={"report_window": 6}), dp_world_size=1)
    assert cfg.train_steps_per_dispatch == 3


def test_config_env_escape_hatches(monkeypatch):
    monkeypatch.setenv("DSTPU_MULTISTEP", "off")
    cfg = DeepSpeedConfig(base_config(train_steps_per_dispatch=8),
                          dp_world_size=1)
    assert cfg.train_steps_per_dispatch == 1
    monkeypatch.setenv("DSTPU_MULTISTEP", "4")
    cfg = DeepSpeedConfig(base_config(), dp_world_size=1)
    assert cfg.train_steps_per_dispatch == 4
    monkeypatch.setenv("DSTPU_MULTISTEP", "soon")
    with pytest.raises(DeepSpeedConfigError, match="DSTPU_MULTISTEP"):
        DeepSpeedConfig(base_config(), dp_world_size=1)
    monkeypatch.delenv("DSTPU_MULTISTEP")
    with pytest.raises(DeepSpeedConfigError, match="must be >= 1"):
        DeepSpeedConfig(base_config(train_steps_per_dispatch=0),
                        dp_world_size=1)
    monkeypatch.setenv("DSTPU_DECODE_ITERS", "off")
    cfg = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 1,
                           "inference": {"decode_iters_per_dispatch": 4}},
                          dp_world_size=1)
    assert cfg.inference_decode_iters_per_dispatch == 1


def test_spool_multi_append_overrun_is_loud():
    from deepspeed_tpu.observability.spool import MetricSpool
    spool = MetricSpool(2, lambda rows, pos: None)
    with pytest.raises(ValueError, match="exceed the report window"):
        spool.note_appends(spool.state, 3)


def test_block_prefetcher_groups_and_propagates():
    blocks = list(BlockPrefetcher(iter(range(7)), k=3))
    assert blocks == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(BlockPrefetcher(iter(range(7)), k=3, drop_last=True)) \
        == [[0, 1, 2], [3, 4, 5]]
    placed = list(BlockPrefetcher(iter(range(4)), k=2,
                                  place=lambda b: b * 10))
    assert placed == [[0, 10], [20, 30]]

    def boom():
        yield 1
        raise RuntimeError("collate exploded")
    with pytest.raises(RuntimeError, match="collate exploded"):
        list(BlockPrefetcher(boom(), k=1))
    with pytest.raises(ValueError, match="k must be"):
        BlockPrefetcher(iter([]), k=0)


# =====================================================================
# host-boundary contract: predicted executables + fences == runtime
# counters (the PR 11 verification discipline)
# =====================================================================

def _counters():
    return (COUNTERS.compile_cache_misses, obs_fences.FENCE_COUNT)


def test_contract_multistep_fp16(cold_cache):
    """fp16 K=4, spool off: ONE train_many executable for the whole run,
    ONE skip-vector fence per K-block (the per-step overflow fence
    amortized K×) — both exactly matching the static prediction."""
    K, BLOCKS = 4, 3
    engine = make_engine(base_config(
        train_steps_per_dispatch=K,
        fp16={"enabled": True, "loss_scale": 128.0}))
    b = batch(0, dtype=np.float16)
    m0, f0 = _counters()
    for blk in range(BLOCKS):
        engine.train_many([batch(blk * K + j, dtype=np.float16)
                           for j in range(K)])

    pred = stability.predict_executables(engine, [b], train=True,
                                         fused=True)
    assert [(k, n) for k, _, n in pred.programs] == [("train_many", 1)]
    assert COUNTERS.compile_cache_misses - m0 == pred.total == 1

    plan = engine.plan_dispatch(b, fused=True)
    assert plan.subject == "train_many"
    assert plan.fence_model.block_steps == K
    assert plan.fence_model.per_boundary == 1
    assert plan.fences_per_step() == 1.0 / K
    n_steps = K * BLOCKS
    assert obs_fences.FENCE_COUNT - f0 \
        == plan.predict_fences(n_steps) == BLOCKS
    # no per-step fence event survives at warning severity — the block
    # read amortizes below the fence-per-step threshold
    rep = plan.to_report()
    assert not any(f.code == "dispatch.fence-per-step"
                   for f in rep.warnings)


def test_contract_multistep_spooled(cold_cache, tmp_path):
    """bf16 + sentinel + spool at K=2: executables = train_many + the
    drain program; ZERO per-block fences (deferred to the drain), one
    counted flush."""
    K, BLOCKS = 2, 4
    engine = make_engine(base_config(
        train_steps_per_dispatch=K,
        bf16={"enabled": True}, resilience={"nan_sentinel": True},
        observability={"report_window": 4,
                       "jsonl_path": str(tmp_path / "t.jsonl")}))
    b = batch(0)
    m0, f0 = _counters()
    for blk in range(BLOCKS):
        engine.train_many([batch(blk * K + j) for j in range(K)])
    engine.flush_telemetry()

    pred = stability.predict_executables(engine, [b], train=True,
                                         fused=True)
    assert sorted(k for k, _, _ in pred.programs) == [
        "spool_drain", "train_many"]
    assert COUNTERS.compile_cache_misses - m0 == pred.total == 2

    plan = engine.plan_dispatch(b, fused=True)
    assert plan.fence_model.per_boundary == 0
    assert plan.fence_model.flush_fences == 1
    assert obs_fences.FENCE_COUNT - f0 \
        == plan.predict_fences(K * BLOCKS, flushes=1) == 1


def test_contract_decode_many(cold_cache):
    """D-fused serving: still exactly TWO executables (prefill +
    decode_many), one counted fence per admission and per D-block —
    runtime counters matching the static serve plan."""
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2
    D = 4
    cfg = {"train_micro_batch_size_per_gpu": 1,
           "inference": {"max_slots": 3, "max_tokens": 16,
                         "prefill_bucket": 8, "page_tokens": 16,
                         "dtype": "float32",
                         "decode_iters_per_dispatch": D},
           "graph_lint": "error"}
    engine = InferenceEngine(GPT2.from_size("tiny", **TINY_GPT2),
                             config=cfg, seed=0)
    m0, f0 = _counters()
    prompts = [[1, 2, 3], [4, 5], [6]]
    for slot, p in enumerate(prompts):
        engine.prefill(slot, p)
    blocks = 3
    toks = np.zeros((engine.num_slots,), np.int32)
    active = np.array([True, True, False])
    eos = np.full((engine.num_slots,), -1, np.int32)
    remaining = np.full((engine.num_slots,), 100, np.int32)
    for _ in range(blocks):
        toks_out, emitted = engine.decode_many(toks, active, eos,
                                               remaining)
        assert toks_out.shape == (D, engine.num_slots)
        assert emitted[:, 2].sum() == 0          # inactive slot silent

    pred = engine.predict_executables()
    assert sorted(k for k, _, _ in pred.programs) == [
        "decode_many", "prefill"]
    assert pred.total == 2
    assert COUNTERS.compile_cache_misses - m0 == 2

    plans = engine.plan_dispatch()
    assert plans["decode"].fence_model.block_steps == D
    predicted = dispatchplan.serve_predict_fences(
        plans, prefills=len(prompts), decode_iters=blocks * D)
    assert obs_fences.FENCE_COUNT - f0 == predicted \
        == len(prompts) + blocks


# =====================================================================
# D-fused decode: output contracts
# =====================================================================

def _serve_engine(d, **inf_over):
    from deepspeed_tpu.inference import InferenceEngine
    from deepspeed_tpu.models.gpt2 import GPT2
    inf = {"max_slots": 3, "max_tokens": 16, "prefill_bucket": 8,
           "page_tokens": 16, "dtype": "float32",
           "decode_iters_per_dispatch": d}
    inf.update(inf_over)
    return InferenceEngine(
        GPT2.from_size("tiny", **TINY_GPT2),
        config={"train_micro_batch_size_per_gpu": 1, "inference": inf},
        seed=0)


def test_decode_many_greedy_identity_and_invariance():
    from deepspeed_tpu.inference.driver import synthetic_requests
    reqs = synthetic_requests(6, vocab=64, seed=3, prompt_min=2,
                              prompt_max=6, new_min=3, new_max=9,
                              eos_id=5)
    prompts = [r.prompt for r in reqs]
    serial = _serve_engine(1).generate(prompts, max_new_tokens=9,
                                       eos_id=5)
    fused_engine = _serve_engine(4)
    fused = fused_engine.generate(prompts, max_new_tokens=9, eos_id=5)
    assert serial == fused
    # batching invariance: solo streams == batched streams at D=4
    solo = []
    for p in prompts[:3]:
        fused_engine.reset()
        solo.append(fused_engine.generate([p], max_new_tokens=9,
                                          eos_id=5)[0])
    fused_engine.reset()
    assert solo == fused_engine.generate(prompts[:3], max_new_tokens=9,
                                         eos_id=5)


def test_decode_many_non_greedy_falls_back_loudly(caplog):
    """A custom sampler cannot ride the fused program (the token loop
    closed on device with argmax) — the scheduler warns once and serves
    correctly through the per-iteration path."""
    import logging
    from deepspeed_tpu.inference.scheduler import (ContinuousScheduler,
                                                   Request)
    engine = _serve_engine(4)
    my_sampler = lambda row: int(np.argmax(row))   # greedy by value,
    # but not THE greedy_sampler object the fused path keys on
    sched = ContinuousScheduler(engine, sampler=my_sampler)
    with caplog.at_level(logging.WARNING):
        results = sched.run([Request(rid=0, prompt=[1, 2, 3],
                                     max_new_tokens=5)])
    assert any("falling back" in r.message for r in caplog.records)
    assert len(results) == 1 and len(results[0].tokens) == 5
    ref = _serve_engine(1).generate([[1, 2, 3]], max_new_tokens=5)
    assert results[0].tokens == ref[0]


def test_decode_many_requires_config():
    engine = _serve_engine(1)
    with pytest.raises(RuntimeError, match="decode_iters_per_dispatch"):
        engine.decode_many(np.zeros(3, np.int32), np.zeros(3, bool),
                           np.full(3, -1, np.int32),
                           np.full(3, 4, np.int32))


def test_serve_stability_clean_with_decode_many():
    engine = _serve_engine(4)
    rep = engine.run_stability(prompt_lengths=[1, 4, 8])
    assert not rep.errors, rep.format()
    rep = engine.run_graph_lint()
    assert not rep.errors, rep.format()


# =====================================================================
# resilience × K-block
# =====================================================================

@pytest.mark.chaos
def test_preempt_mid_block_drains_at_k_boundary_bitwise(tmpdir):
    """A preemption request raised MID-BLOCK (while the fused dispatch
    runs) is honoured at the NEXT K boundary — the documented ≤ K-step
    drain granularity — with an emergency checkpoint and a BITWISE
    resume."""
    K, STEPS = 3, 9
    cfg = base_config(zero_optimization={"stage": 1},
                      fp16={"enabled": True, "loss_scale": 128.0},
                      train_steps_per_dispatch=K)

    def factory():
        return make_engine(cfg)

    def k_block(engine, _batch):
        start = engine.global_steps
        engine.train_many([batch(start + j, dtype=np.float16)
                           for j in range(K)])

    unbroken = resilience.run_resumable(
        factory, k_block, steps=STEPS,
        save_dir=str(tmpdir.join("unbroken")))
    ref = master_bytes(unbroken)

    sentinel = str(tmpdir.join("preempt"))
    handler = PreemptionHandler(sentinel_file=sentinel)
    save_dir = str(tmpdir.join("interrupted"))
    fired = []

    def k_block_interrupting(engine, _batch):
        start = engine.global_steps
        if start == K and not fired:
            # the request lands while THIS block is about to run — the
            # drain must wait for the block to complete (global step 2K)
            fired.append(True)
            open(sentinel, "w").close()
        engine.train_many([batch(start + j, dtype=np.float16)
                           for j in range(K)])

    try:
        with pytest.raises(SystemExit) as ei:
            resilience.run_resumable(factory, k_block_interrupting,
                                     steps=STEPS, save_dir=save_dir,
                                     handler=handler)
        assert ei.value.code == RESUME_EXIT_CODE
        from deepspeed_tpu.checkpoint import find_latest_valid_tag
        tag = find_latest_valid_tag(save_dir)
        # drained at the K boundary AFTER the request: step 2K, not K
        assert tag == f"emergency/global_step{2 * K}"
        os.remove(sentinel)
        handler.clear()
        resumed = resilience.run_resumable(factory, k_block, steps=STEPS,
                                           save_dir=save_dir,
                                           handler=handler)
    finally:
        handler.uninstall()
    assert resumed.global_steps == STEPS
    assert master_bytes(resumed) == ref


@pytest.mark.chaos
def test_watchdog_deadline_scales_with_k():
    """A healthy K-block runs K× longer than one step: armed with
    ``deadline_scale=K`` the 1-step deadline must NOT fire, and the
    near-miss threshold scales with it."""
    wd = Watchdog(timeout_s=0.3, poll_s=0.02)
    with wd.armed("k-block", deadline_scale=5):
        time.sleep(0.9)                  # 3× the base deadline
    assert not wd.fired
    assert COUNTERS.watchdog_near_misses == 0   # 0.9 < 0.8 * 1.5
    with wd.armed("single"):
        time.sleep(0.6)                  # past the UNSCALED deadline
        wd.fire_event.wait(timeout=2.0)
    assert wd.fired
    with pytest.raises(ValueError, match="deadline_scale"):
        wd._arm("bad", 0)


@pytest.mark.chaos
def test_train_many_arms_watchdog_scaled():
    engine = make_engine(base_config(
        bf16={"enabled": True}, resilience={"watchdog_timeout_s": 60.0}))
    seen = []
    real_armed = engine._watchdog.armed
    engine._watchdog.armed = (
        lambda label, deadline_scale=1.0:
        seen.append((label, deadline_scale)) or
        real_armed(label, deadline_scale=deadline_scale))
    engine.train_many([batch(0), batch(1), batch(2)])
    assert ("train_many", 3) in seen


# =====================================================================
# lint/analysis wiring
# =====================================================================

def test_train_many_rides_graph_lint_gate():
    """A seeded per-step host callback inside the model is caught by the
    lint over the K-fused program in error mode — the gate covers the
    composed program, not just the single step."""
    engine = make_engine(base_config(graph_lint="error"))
    engine.train_many([batch(0), batch(1)])      # clean program passes

    class CallbackModel(SimpleModel):
        def apply(self, params, x, y):
            import jax.experimental
            jax.experimental.io_callback(lambda v: None, None,
                                         x[0, 0], ordered=True)
            return super().apply(params, x, y)

    bad, _, _, _ = ds.initialize(model=CallbackModel(hidden_dim=HIDDEN),
                                 config=base_config(graph_lint="error"))
    with pytest.raises(analysis.GraphLintError):
        bad.train_many([batch(0), batch(1)])


def test_capacity_plan_prices_k_batches():
    """The K>1 fused capacity plan must price the ACTUAL train_many
    program — K staged effective batches of residency, not one (the
    under-pricing would let an over-HBM K config through the memplan
    error gate)."""
    K = 8
    engine = make_engine(base_config(train_steps_per_dispatch=K,
                                     bf16={"enabled": True}))
    b = batch(0)
    plan_k = engine.plan_capacity(b, train=True, fused=True)
    plan_1 = engine.plan_capacity(b, train=True, fused=True,
                                  steps_per_dispatch=1)
    assert plan_k.programs[0].subject == "train_many"
    assert plan_1.programs[0].subject == "train_batch"
    # the plan prices PER-DEVICE bytes: the batch shards over dp
    local_batch = sum(x.nbytes for x in b) // engine.dp_world_size
    # at least the K-1 extra staged batches show up in the peak
    assert plan_k.peak_bytes >= plan_1.peak_bytes \
        + (K - 1) * local_batch - local_batch


def test_dispatch_plan_json_carries_block_model():
    engine = make_engine(base_config(
        train_steps_per_dispatch=8,
        fp16={"enabled": True, "loss_scale": 128.0}))
    plan = engine.plan_dispatch(batch(0, dtype=np.float16), fused=True)
    doc = plan.to_json()
    assert doc["subject"] == "train_many"
    assert doc["fence_model"]["block_steps"] == 8
    assert doc["fences_per_step"] == pytest.approx(1 / 8)
    assert doc["executables"]["programs"][0]["kind"] == "train_many"
    # the amortized dispatch event prices at 1/K per step
    ev = {e["label"]: e for e in doc["events"]}
    assert ev["train_many"]["per_step"] == pytest.approx(1 / 8)
