"""ZeRO stage 1: optimizer-state partitioning over the data-parallel axis.

TPU-native analog of /root/reference/deepspeed/pt/deepspeed_zero_optimizer.py
(class FP16_DeepSpeedZeroOptimizer).  The reference manually flattens each
param group aligned to the DP world size (:20-41), splits the flat buffer into
per-rank partitions (:196-212), keeps an fp32 master clone of only this rank's
partition (:158-165), and after the local update all-gathers the fp16
partitions (:397-432).

Here the same layout is expressed through GSPMD sharding instead of offset
bookkeeping: the fp32 master (and Adam moments) live in ONE flat padded global
array with ``NamedSharding(mesh, P('data'))`` — XLA materialises exactly the
reference's "each DP rank owns 1/N of the flat buffer".  Gradients are
``psum_scatter`` (reduce-scatter) onto the owned partition — the upgrade the
reference itself teased (docs/_posts/2020-03-17-reduce-scatter.md) — the
update runs shard-locally, and the updated weights return to every rank via a
tiled ``all_gather`` over ICI.

The "empty partition" edge case the reference tests (DP=3 over 2 params,
tests/unit/test_fp16.py:320-347) is handled by the padding: ranks beyond the
real parameter count own pure padding and the gather discards it.

``parameter_parallel_size`` sub-groups (reference deepspeed_light.py:63-77)
partition over a SUBSET of DP: the flat buffer is tiled ``dp/pps`` times into
``[repl * padded]`` P('data') so each consecutive block of pps devices holds
the full partitioned state, with ``axis_index_groups`` collectives
(engine._make_step_local / parallel.comm).  The ``allgather_size`` chunking
knob (:399-425) is accepted in config; under XLA the gather schedule is the
compiler's, so chunking is a no-op — kept as a documented escape hatch.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class FlatMeta(NamedTuple):
    """Static metadata to flatten/unflatten a pytree through one padded flat
    buffer (the reference's partition bookkeeping, zero_optimizer.py:214-262,
    reduced to shapes)."""
    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    total: int            # unpadded element count
    padded: int           # total padded to a multiple of (dp * align)
    partition: int        # padded // dp


def make_flat_meta(params, dp_size: int, align: int = 128) -> FlatMeta:
    """Compute the flatten layout.  ``align=128`` keeps every partition
    lane-aligned for the MXU/VPU (the reference aligns to the DP world size
    only, zero_optimizer.py:20-41; 128 additionally keeps XLA tiling clean)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = tuple(tuple(l.shape) for l in leaves)
    return _meta_from_shapes(treedef, shapes, dp_size, align)


def _meta_from_shapes(treedef, shapes, dp_size: int, align: int) -> FlatMeta:
    sizes = tuple(int(np.prod(s)) if len(s) else 1 for s in shapes)
    total = int(sum(sizes))
    chunk = dp_size * align
    padded = ((total + chunk - 1) // chunk) * chunk
    return FlatMeta(treedef=treedef, shapes=shapes, sizes=sizes, total=total,
                    padded=padded, partition=padded // dp_size)


def _spec_axes(entry):
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


class LazyParts:
    """Deferred host leaf for the streaming checkpoint restore.

    ``parts`` are the raw array sources (np.memmap chunk views into the
    checkpoint container) and ``assemble(arrays)`` — arrays in ``parts``
    order — builds the materialized leaf.  Threading these through the
    host-side tree reassembly instead of eager ``np.concatenate`` lets the
    restore path hand every chunk read to a reader pool and assemble each
    leaf as its chunks land (checkpoint._stream_leaves); ``materialize()``
    is the inline (serial) equivalent and produces bitwise the same value.
    """

    __slots__ = ("parts", "assemble")

    def __init__(self, parts, assemble):
        self.parts = list(parts)
        self.assemble = assemble

    def materialize(self):
        return self.assemble([np.asarray(p) for p in self.parts])

    @property
    def nbytes(self) -> int:
        return sum(int(getattr(p, "nbytes", 0)) for p in self.parts)

    @classmethod
    def wrap(cls, value) -> "LazyParts":
        """Lift a plain array source into a single-part LazyParts."""
        if isinstance(value, cls):
            return value
        return cls([value], lambda arrs: arrs[0])

    @classmethod
    def concat(cls, values, axis: int) -> "LazyParts":
        """Compose: concatenate ``values`` (LazyParts or raw sources) along
        ``axis``, keeping every underlying chunk an independent part."""
        lazies = [cls.wrap(v) for v in values]
        counts = [len(lz.parts) for lz in lazies]
        subs = [lz.assemble for lz in lazies]

        def assemble(arrs):
            out, i = [], 0
            for n, sub in zip(counts, subs):
                out.append(sub(arrs[i:i + n]))
                i += n
            return np.concatenate(out, axis=axis)

        return cls([p for lz in lazies for p in lz.parts], assemble)


def _local_shape(shape, spec, axis_sizes) -> Tuple[int, ...]:
    """Per-device-group shape of a leaf under a PartitionSpec: each dim is
    divided by the product of the mesh-axis sizes sharding it."""
    out = list(shape)
    for i, entry in enumerate(spec):
        if i >= len(out):
            break
        for ax in _spec_axes(entry):
            size = axis_sizes.get(ax, 1)
            if out[i] % size != 0:
                raise ValueError(
                    f"dim {i} of shape {shape} not divisible by mesh axis "
                    f"{ax!r} (size {size})")
            out[i] //= size
    return tuple(out)


def make_local_flat_meta(params, specs, axis_sizes, dp_size: int,
                         align: int = 128) -> FlatMeta:
    """Flatten layout of the LOCAL (per-model-shard) parameter slices.

    Under ZeRO x tensor parallelism the reference partitions optimizer state
    within each MP rank's data-parallel group (deepspeed_light.py:63-77,
    _configure_zero_optimizer :520-531): every model shard keeps a flat fp32
    master of only ITS slice of the parameters, split over DP.  The local
    meta describes exactly those slices — model-sharded leaves shrink by the
    model-axis degree, model-replicated leaves keep their global shape."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    spec_leaves = treedef.flatten_up_to(specs)
    shapes = tuple(_local_shape(tuple(l.shape), s, axis_sizes)
                   for l, s in zip(leaves, spec_leaves))
    return _meta_from_shapes(treedef, shapes, dp_size, align)


def norm_dedup_weights(meta: FlatMeta, specs, state_axes) -> np.ndarray:
    """Per-element weights so a state-axes psum of weighted squared norms
    counts every parameter exactly once (the reference's replicated-parameter
    dedup, deepspeed_utils.py:100-158).  ``state_axes`` is a sequence of
    ``(axis_name, size)`` — the model/pipe axes parameters may shard over:
    leaves sharded over an axis contribute distinct slices on every shard
    (weight factor 1), leaves replicated over it are identical on every
    shard (factor 1/size); factors multiply across axes."""
    spec_leaves = meta.treedef.flatten_up_to(specs)
    pieces = []
    for spec, size in zip(spec_leaves, meta.sizes):
        axes = set()
        for entry in spec:
            axes.update(_spec_axes(entry))
        w = 1.0
        for name, n in state_axes:
            if name not in axes:
                w /= n
        pieces.append(np.full((size,), w, np.float32))
    pad = meta.padded - meta.total
    if pad:
        pieces.append(np.zeros((pad,), np.float32))
    return np.concatenate(pieces)


def combine_composite_trees(local_trees, specs, axes, lazy=False):
    """Reassemble a global pytree from per-composite-rank local trees (host
    side).  ``axes`` is ``[(axis_name, size), ...]`` row-major (first axis
    slowest-varying — pipe before model); the innermost axis combines
    first.  Single owner of the composite-rank ordering invariant shared by
    checkpoint reassembly and engine._params_from_master_flat.

    ``lazy=True`` defers every model-sharded concatenation to
    :class:`LazyParts` (streaming-restore callers only — the leaves reach
    ``checkpoint._place_trees``, which schedules the underlying chunks on
    the reader pool and assembles as they land)."""
    if len(local_trees) == 1:
        return local_trees[0]
    if len(axes) == 1:
        return combine_local_trees(local_trees, specs, axes[0][0],
                                   lazy=lazy)
    inner = 1
    for _, n in axes[1:]:
        inner *= n
    outer = [combine_composite_trees(local_trees[o * inner:(o + 1) * inner],
                                     specs, axes[1:], lazy=lazy)
             for o in range(axes[0][1])]
    return combine_local_trees(outer, specs, axes[0][0], lazy=lazy)


def combine_local_trees(local_trees, specs, model_axis: str, lazy=False):
    """Reassemble a global pytree from per-model-shard local trees (host
    side): model-sharded leaves concatenate along their sharded dim,
    replicated leaves are taken from shard 0.  ``lazy=True`` (and any
    already-deferred input leaf) keeps the concatenation deferred — see
    :func:`combine_composite_trees`."""
    treedef = jax.tree_util.tree_structure(local_trees[0])
    spec_leaves = treedef.flatten_up_to(specs)
    all_leaves = [jax.tree_util.tree_leaves(t) for t in local_trees]
    out = []
    for i, spec in enumerate(spec_leaves):
        dim = None
        for d, entry in enumerate(spec):
            if model_axis in _spec_axes(entry):
                dim = d
                break
        if dim is None:
            out.append(all_leaves[0][i])
        elif lazy or any(isinstance(lv[i], LazyParts) for lv in all_leaves):
            # streaming restore: keep the per-shard chunks independent so
            # the reader pool schedules them (raw memmap sources would
            # otherwise page-fault serially, GIL held, on the consumer);
            # assembly is the SAME np.concatenate, just deferred
            # (bitwise-identical)
            out.append(LazyParts.concat([lv[i] for lv in all_leaves], dim))
        else:
            out.append(np.concatenate(
                [np.asarray(lv[i]) for lv in all_leaves], axis=dim))
    return treedef.unflatten(out)


def flatten_tree(tree, meta: FlatMeta, dtype=jnp.float32) -> jnp.ndarray:
    """Concat + pad all leaves into one flat [padded] vector (jit-safe).
    Equivalent of ``flatten_dense_tensors_aligned``
    (zero_optimizer.py:20-41)."""
    leaves = meta.treedef.flatten_up_to(tree)
    flat = jnp.concatenate(
        [jnp.reshape(l, (-1,)).astype(dtype) for l in leaves])
    pad = meta.padded - meta.total
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    return flat


def unflatten_tree(flat: jnp.ndarray, meta: FlatMeta, dtype=None):
    """Split a flat [padded] vector back into the original pytree (jit-safe).
    Equivalent of re-viewing model params into the flat buffer
    (zero_optimizer.py:146-149)."""
    out = []
    offset = 0
    for shape, size in zip(meta.shapes, meta.sizes):
        piece = jax.lax.dynamic_slice_in_dim(flat, offset, size)
        piece = jnp.reshape(piece, shape)
        if dtype is not None:
            piece = piece.astype(dtype)
        out.append(piece)
        offset += size
    return meta.treedef.unflatten(out)
