"""ZeRO stage 3: parameter partitioning (FSDP) over the data-parallel axis.

The reference's v0.1.0 ships stage 1 only and *teases* stages 2-3
(/root/reference/docs/_posts/2020-03-17-zero-stage2.md — the ZeRO roadmap:
optimizer states, then gradients, then parameters partitioned across
data-parallel ranks).  Stage 2 (gradient partitioning) is in
``zero.py``/``engine.py``; this module is the stage-3 parameter
partitioning, designed TPU-first rather than as a port of the later CUDA
implementation:

* **Persistent layout**: every large parameter leaf gets the ``data`` mesh
  axis appended to one of its dims (``choose_dims``) on top of its
  tensor/pipeline-parallel sharding, so params, fp32 masters AND Adam
  moments all persist at ``1/dp`` per device.  No flat buffer, no offset
  bookkeeping: GSPMD materialises the partitioning from the PartitionSpec.
* **Gather-on-use**: the model gathers each LAYER's weights right before
  using them (``gather_tree`` inside the ``lax.scan`` block body,
  models/transformer.py).  Under rematerialisation the gather replays in
  the backward, so the full parameter set is never resident — peak weight
  memory is one layer, not the model.
* **Reduce-scatter for free**: ``jax.lax.all_gather(tiled=True)`` transposes
  to ``psum_scatter(tiled=True)`` under autodiff, so gradients for
  partitioned leaves arrive ALREADY summed over DP and scattered onto the
  owning shard — stage-2 gradient partitioning falls out of the stage-3
  program with zero extra code in the backward.
* **Elementwise update**: Adam-family updates are elementwise, so the
  optimizer step runs directly on the local shards of (master, moments,
  grad) with no knowledge of the partitioning; global grad norms are one
  ``psum`` of local squared sums (with replicated-leaf dedup).

Engine protocol: the engine computes ``choose_dims`` over the model's own
partition specs, re-places parameters/masters/moments with
``augment_specs``, and hands the dims tree to the model
(``model.zero3_dims``); family models thread it into their block scan.

Dims trees use ``-1`` for "stays replicated" (never ``None`` — a ``None``
pytree node is an empty subtree and silently breaks tree_map pairing).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.topology import DATA_AXIS

#: leaves smaller than this stay replicated: gathering a tiny LayerNorm
#: vector costs more in latency than its shard saves in HBM (the later
#: reference implementations keep the same escape hatch as
#: ``stage3_param_persistence_threshold``)
DEFAULT_MIN_PARTITION_SIZE = 2 ** 10

REPLICATED = -1


def _spec_axes(entry):
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


def choose_dim(shape, spec, axis_sizes, dp: int,
               min_size: int = DEFAULT_MIN_PARTITION_SIZE,
               min_dim: int = 0) -> int:
    """Pick the dim of one leaf to partition over ``data`` (-1 = keep
    replicated).

    Rule: consider every dim >= ``min_dim`` whose LOCAL size (global
    divided by the mesh axes already sharding it) is divisible by ``dp``;
    pick the one with the largest local size (ties -> lowest index, so the
    choice is stable across runs).  Leaves with fewer than ``min_size``
    elements stay replicated.  ``min_dim`` lets models pin scan-consumed
    axes (the [L, ...] layer stack) as never-partitioned."""
    if dp <= 1:
        return REPLICATED
    total = 1
    for s in shape:
        total *= int(s)
    if total < min_size:
        return REPLICATED
    best, best_local = REPLICATED, 0
    for d, size in enumerate(shape):
        if d < min_dim:
            continue
        local = int(size)
        for ax in _spec_axes(spec[d] if d < len(spec) else None):
            local //= int(axis_sizes.get(ax, 1))
        if local % dp == 0 and local > best_local:
            best, best_local = d, local
    return best


def choose_dims(params, specs, axis_sizes, dp: int,
                min_size: int = DEFAULT_MIN_PARTITION_SIZE,
                min_dims=None):
    """Dims tree (same structure as ``params``) of int: which dim of each
    leaf partitions over ``data`` (-1 = replicated).  ``min_dims`` (same
    structure, int) pins the lowest partitionable dim per leaf (the
    model's ``zero3_min_dims`` hook).  Sparse-gradient embeddings never
    reach this: ``sparse_gradients`` is disabled under every ZeRO stage
    (engine._resolve_sparse_flags), so no leaf needs a CSR escape here."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    spec_leaves = treedef.flatten_up_to(specs)
    mins = ([0] * len(leaves) if min_dims is None
            else treedef.flatten_up_to(min_dims))
    dims = [choose_dim(tuple(l.shape), s, axis_sizes, dp, min_size,
                       min_dim=int(md))
            for l, s, md in zip(leaves, spec_leaves, mins)]
    return jax.tree_util.tree_unflatten(treedef, dims)


def augment_specs(specs, dims):
    """Append ``DATA_AXIS`` to the chosen dim of each partitioned leaf's
    PartitionSpec (replicated leaves pass through)."""
    from jax.sharding import PartitionSpec as P

    def one(spec, dim):
        if dim < 0:
            return spec
        entries = list(spec) + [None] * (dim + 1 - len(spec))
        entries[dim] = _spec_axes(entries[dim]) + (DATA_AXIS,)
        return P(*entries)

    return jax.tree_util.tree_map(
        one, specs, dims,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))


def gather_tree(tree, dims, axis: str = DATA_AXIS):
    """All-gather the partitioned leaves back to their (model-local) shapes.
    Must run inside ``shard_map``; the autodiff transpose is a tiled
    ``psum_scatter`` — the grads come back summed over DP and scattered."""
    def one(x, dim):
        if dim < 0:
            return x
        return jax.lax.all_gather(x, axis, axis=dim, tiled=True)

    return jax.tree_util.tree_map(one, tree, dims)


def shift_dims(dims, by: int = -1):
    """Re-index a dims tree after an axis is consumed (scan slices the
    leading layer axis off every block leaf, so dim k becomes k+by).  The
    layer axis itself is never partitioned (the engine pins block-stack
    leaves' dim 0; ``partition_specs`` of the family models put only
    model/pipe axes there)."""
    return jax.tree_util.tree_map(
        lambda d: d if d < 0 else d + by, dims)


def partitioned_any(dims) -> bool:
    return any(d >= 0 for d in jax.tree_util.tree_leaves(dims))


def local_sqnorm_and_finite(grads, dims, specs, dp, state_axes):
    """(sum of squares, all-finite) over this device's UNIQUE grad elements.

    Partitioned leaves are disjoint across DP (weight 1); replicated leaves
    are identical on every DP shard, so they carry weight ``1/dp`` under
    the later DP psum.  On top of that, leaves replicated over one of the
    ``state_axes`` (the model/pipe axes the CALLER will psum the result
    over — and ONLY those; grads are already identical across e.g. the
    sequence ring, which the caller never psums) get ``1/size`` per such
    axis — the same dedup as stage 1/2's ``norm_dedup_weights`` (zero.py)
    and the reference's MP-aware norms (deepspeed_utils.py:100-158).
    ``state_axes`` is ``[(axis_name, size), ...]``.  Returns fp32 scalars;
    callers psum over data + exactly ``state_axes``."""
    dp = int(dp)
    leaves, treedef = jax.tree_util.tree_flatten(
        grads, is_leaf=lambda x: x is None)
    dim_leaves = treedef.flatten_up_to(dims)
    spec_leaves = treedef.flatten_up_to(specs)
    sq = jnp.zeros((), jnp.float32)
    finite = jnp.asarray(True)
    for g, dim, spec in zip(leaves, dim_leaves, spec_leaves):
        if g is None:
            continue
        w = 1.0 if dim >= 0 else 1.0 / dp
        sharded_axes = set()
        for entry in spec:
            sharded_axes.update(_spec_axes(entry))
        for name, size in state_axes:
            if int(size) <= 1 or name == DATA_AXIS:
                continue
            if name not in sharded_axes:
                w /= int(size)
        g32 = g.astype(jnp.float32)
        sq = sq + w * jnp.sum(g32 * g32)
        finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
    return sq, finite
