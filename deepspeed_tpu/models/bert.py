"""BERT pretraining model (MLM + optional NSP) with tensor parallelism.

Counterpart of the reference's BingBert pretraining + BingBertSquad fine-tune
suites (/root/reference/tests/model/BingBertSquad/,
docs/_tutorials/bert-pretraining.md — the 14h/64-GPU headline workload).
Post-LN encoder per the original BERT; vocab-parallel MLM head tied to the
embedding.  The SQuAD-style span head is provided for fine-tuning parity.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models import layers as L
from deepspeed_tpu.models import transformer as T
from deepspeed_tpu.parallel.topology import (DATA_AXIS, MODEL_AXIS,
                                             SEQ_AXIS)


BERT_SIZES = {
    "tiny":  dict(num_layers=2,  hidden_size=128, num_heads=4,
                  max_seq_len=128, vocab_size=512),
    "base":  dict(num_layers=12, hidden_size=768, num_heads=12,
                  vocab_size=30528, max_seq_len=512),
    "large": dict(num_layers=24, hidden_size=1024, num_heads=16,
                  vocab_size=30528, max_seq_len=512),
}


def _init_backbone_params(cfg: T.TransformerConfig, rng) -> dict:
    """Embeddings (word/position/token-type) + encoder stack."""
    cfg.validate()
    h = cfg.hidden_size
    ks = jax.random.split(rng, 4)
    std = cfg.init_std
    return {
        "wte": jax.random.normal(ks[0], (cfg.vocab_size, h),
                                 jnp.float32) * std,
        "wpe": jax.random.normal(ks[1], (cfg.max_seq_len, h),
                                 jnp.float32) * std,
        "wtt": jax.random.normal(ks[2], (2, h), jnp.float32) * std,
        "ln_emb_s": jnp.ones((h,), jnp.float32),
        "ln_emb_b": jnp.zeros((h,), jnp.float32),
        "blocks": T.init_block_params(cfg, ks[3]),
    }


def _backbone_partition_specs() -> dict:
    return {
        "wte": P(MODEL_AXIS, None),
        "wpe": P(), "wtt": P(),
        "ln_emb_s": P(), "ln_emb_b": P(),
        "blocks": T.block_partition_specs(),
    }


def _encode(cfg, params, input_ids, attention_mask, token_type_ids,
            z3_block_dims=None, z3_prefetch=False):
    """Embed + encoder stack (runs inside shard_map on local shards).
    Callers must already have run ``T.zero3_enter`` on ``params`` under
    ZeRO-3 (``z3_block_dims`` = its deferred block dims; ``z3_prefetch``
    pairs the per-layer gathers — transformer.scan_layers)."""
    T_len = input_ids.shape[1]
    x = L.vocab_parallel_embedding(input_ids, params["wte"])
    x = x + L.seq_shard_positions(params["wpe"], T_len).astype(
        x.dtype)[None]
    x = x + jnp.take(params["wtt"].astype(x.dtype), token_type_ids, axis=0)
    x = L.layer_norm(x, params["ln_emb_s"], params["ln_emb_b"], cfg.ln_eps)
    return T.stack_apply(x, params["blocks"], cfg, attn_mask=attention_mask,
                         z3_dims=z3_block_dims, z3_prefetch=z3_prefetch)


def _zero3_min_dims(params):
    """Stage-3 hook body shared by both BERT heads (see GPT2)."""
    md = jax.tree_util.tree_map(lambda _: 0, params)
    md["blocks"] = jax.tree_util.tree_map(lambda _: 1, md["blocks"])
    return md


@dataclasses.dataclass
class BertForPreTraining:
    """MLM (+NSP when ``use_nsp``) pretraining loss.

    apply(params, input_ids, attention_mask, token_type_ids, mlm_labels
          [, nsp_labels]) → scalar loss.  mlm_labels < 0 are ignored.
    """
    config: T.TransformerConfig
    use_nsp: bool = False
    #: dense-labels MLM only: when set, gather up to this many masked
    #: positions per sequence BEFORE the vocab projection (the sparse head
    #: the masked-positions format gets for free), instead of the
    #: [B, T, vocab] dense logits.  EXACTNESS CONTRACT: per-sequence masked
    #: counts must not exceed the budget — overflow positions are silently
    #: dropped from the loss (standard BERT data caps masking at
    #: max_predictions_per_seq, so the pipeline's cap is the right value).
    #: Clamped to the sequence length (budget >= T is always exact).  The
    #: dense path remains the fallback: budget None, or sequence
    #: parallelism > 1 (the gather indexes global positions).
    mlm_gather_budget: object = None
    #: ZeRO-3 partition dims (set by the engine at stage 3; zero3.py)
    zero3_dims: object = None
    #: ZeRO-3 gather prefetch (engine overlap_comm): paired-layer scan
    #: hiding the second gather under the first block's compute
    #: (transformer.scan_layers)
    zero3_prefetch: bool = False

    @classmethod
    def from_size(cls, size: str, use_nsp: bool = False,
                  mlm_gather_budget=None, **overrides):
        kw = dict(BERT_SIZES[size])
        kw.update(overrides)
        kw.setdefault("pre_ln", False)   # BERT is post-LN
        kw.setdefault("causal", False)
        return cls(T.TransformerConfig(**kw), use_nsp=use_nsp,
                   mlm_gather_budget=mlm_gather_budget)

    def validate(self, mp_size: int = 1):
        """Engine hook: shape checks against the actual mp degree."""
        self.config.validate(mp_size)

    def init_params(self, rng):
        cfg = self.config
        h = cfg.hidden_size
        k_bb, k4, k5 = jax.random.split(rng, 3)
        std = cfg.init_std
        params = _init_backbone_params(cfg, k_bb)
        params.update({
            # MLM head: dense + LN + tied decoder with its own output bias
            "mlm_dense_w": jax.random.normal(k4, (h, h), jnp.float32) * std,
            "mlm_dense_b": jnp.zeros((h,), jnp.float32),
            "mlm_ln_s": jnp.ones((h,), jnp.float32),
            "mlm_ln_b": jnp.zeros((h,), jnp.float32),
            "mlm_bias": jnp.zeros((cfg.vocab_size,), jnp.float32),
        })
        if self.use_nsp:
            params["pool_w"] = jax.random.normal(k5, (h, h),
                                                 jnp.float32) * std
            params["pool_b"] = jnp.zeros((h,), jnp.float32)
            params["nsp_w"] = jnp.zeros((h, 2), jnp.float32)
            params["nsp_b"] = jnp.zeros((2,), jnp.float32)
        return params

    def partition_specs(self, params=None):
        specs = _backbone_partition_specs()
        specs.update({
            "mlm_dense_w": P(), "mlm_dense_b": P(),
            "mlm_ln_s": P(), "mlm_ln_b": P(),
            "mlm_bias": P(MODEL_AXIS),     # rides with the vocab shard
        })
        if self.use_nsp:
            specs.update({"pool_w": P(), "pool_b": P(),
                          "nsp_w": P(), "nsp_b": P()})
        return specs

    def batch_specs(self, batch):
        """Engine hook, format-aware (mirrors ``apply``): the first three
        leaves and dense ``mlm_labels`` are [B, T] sequence-aligned; the
        masked-positions leaves are [B, P] (P = max predictions, NOT the
        sequence) and shard over ``data`` only; nsp_labels is [B]."""
        batch = tuple(batch)
        rest = len(batch) - 3
        seq = P(DATA_AXIS, SEQ_AXIS)
        specs = [seq, seq, seq]
        if rest in (1, 2):
            specs.append(seq)                      # dense mlm_labels [B, T]
        elif rest in (3, 4):
            specs += [P(DATA_AXIS, None)] * 3      # positions/ids/weights
        else:
            raise TypeError(
                f"BertForPreTraining batch: expected 4-7 leaves, "
                f"got {len(batch)}")
        if rest in (2, 4):
            specs.append(P(DATA_AXIS))             # nsp_labels [B]
        return tuple(specs)

    def zero3_min_dims(self, params):
        """Engine hook (stage 3): block leaves pin dim >= 1 (layer stack)."""
        return _zero3_min_dims(params)

    def _mlm_head(self, params, h):
        """Dense + LN + tied vocab decoder on [.., H] hidden states."""
        cfg = self.config
        g = L.gelu(h @ params["mlm_dense_w"].astype(h.dtype)
                   + params["mlm_dense_b"].astype(h.dtype))
        g = L.layer_norm(g, params["mlm_ln_s"], params["mlm_ln_b"], cfg.ln_eps)
        logits = L.vocab_parallel_logits(g, params["wte"])
        return logits + params["mlm_bias"].astype(logits.dtype)

    def apply(self, params, input_ids, attention_mask, token_type_ids, *rest):
        """Two MLM input formats (both are scalar-loss):

        * dense labels — ``apply(.., mlm_labels[, nsp_labels])`` with
          ``mlm_labels`` int [B, T], positions < 0 ignored.  Simple, but
          materialises [B, T, vocab] logits.
        * masked positions — ``apply(.., mlm_positions, mlm_ids,
          mlm_weights[, nsp_labels])`` with [B, P] leaves (P = static
          max_predictions_per_seq): the standard BERT pretraining data
          format (the reference's BingBert recipe trains this way,
          docs/_tutorials/bert-pretraining.md).  Gathers the P masked
          positions BEFORE the vocab projection, so the head costs
          P/T of the dense variant in both FLOPs and memory.
        """
        cfg = self.config
        if len(rest) in (1, 2):
            mlm_labels, nsp_labels = rest[0], (rest[1] if len(rest) == 2
                                               else None)
            mlm_positions = None
        elif len(rest) in (3, 4):
            mlm_positions, mlm_ids, mlm_weights = rest[:3]
            nsp_labels = rest[3] if len(rest) == 4 else None
            if L.axis_size_or_1(SEQ_AXIS) > 1:
                raise NotImplementedError(
                    "masked-positions MLM gathers global sequence positions "
                    "— use dense mlm_labels under context_parallel_size > 1")
        else:
            raise TypeError(
                f"BertForPreTraining.apply: expected mlm_labels[, nsp] or "
                f"mlm_positions, mlm_ids, mlm_weights[, nsp], got "
                f"{len(rest)} trailing args")

        params, z3_deferred = T.zero3_enter(params, self.zero3_dims)
        x = _encode(cfg, params, input_ids, attention_mask, token_type_ids,
                    z3_block_dims=z3_deferred.get("blocks"),
                    z3_prefetch=getattr(self, "zero3_prefetch", False))

        if mlm_positions is None:
            budget = self.mlm_gather_budget
            if budget and L.axis_size_or_1(SEQ_AXIS) == 1:
                # sparse head for the dense-labels format: select <= budget
                # masked positions per sequence (top_k of the 0/1 mask is
                # stable, so masked positions come first, in order), gather
                # them, and run the vocab projection on [B, P, H] instead
                # of [B, T, H].  Matches the dense loss exactly while every
                # sequence's masked count fits the budget (see the field
                # docstring for the overflow contract).
                P_ = min(int(budget), mlm_labels.shape[1])
                maskf = (mlm_labels >= 0).astype(jnp.float32)
                w, pos = jax.lax.top_k(maskf, P_)           # [B, P] each
                ids = jnp.clip(jnp.take_along_axis(mlm_labels, pos, axis=1),
                               0, None)                     # w=0 rows: any id
                h_m = L.gather_positions(x, pos)
                logits = self._mlm_head(params, h_m)        # [B, P, vocab/mp]
                tok_loss = L.vocab_parallel_cross_entropy(logits, ids)
                loss = (jnp.sum(tok_loss * w)
                        / jnp.maximum(jnp.sum(w), 1.0))
            else:
                logits = self._mlm_head(params, x)
                tok_loss = L.vocab_parallel_cross_entropy(logits, mlm_labels)
                loss = L.masked_mean_loss(tok_loss, mlm_labels >= 0)
        else:
            h_m = L.gather_positions(x, mlm_positions)
            logits = self._mlm_head(params, h_m)          # [B, P, vocab/mp]
            tok_loss = L.vocab_parallel_cross_entropy(logits, mlm_ids)
            w = mlm_weights.astype(jnp.float32)
            loss = jnp.sum(tok_loss * w) / jnp.maximum(jnp.sum(w), 1.0)

        if self.use_nsp and nsp_labels is not None:
            if L.axis_size_or_1(SEQ_AXIS) > 1:
                raise NotImplementedError(
                    "NSP pools the global [CLS] token, which lives only on "
                    "sequence shard 0 — NSP is not supported under "
                    "context_parallel_size > 1")
            pooled = jnp.tanh(x[:, 0] @ params["pool_w"].astype(x.dtype)
                              + params["pool_b"].astype(x.dtype))
            nsp_logits = (pooled @ params["nsp_w"].astype(pooled.dtype)
                          + params["nsp_b"].astype(pooled.dtype))
            logp = jax.nn.log_softmax(nsp_logits.astype(jnp.float32), -1)
            nsp = -jnp.mean(jnp.take_along_axis(
                logp, nsp_labels[:, None], axis=-1)[:, 0])
            loss = loss + nsp
        return loss

    __call__ = apply


@dataclasses.dataclass
class BertForQuestionAnswering:
    """SQuAD span-extraction fine-tune head (BingBertSquad parity,
    /root/reference/tests/model/BingBertSquad/BingBertSquad_run_func_test.py).

    apply(params, input_ids, attention_mask, token_type_ids, start_positions,
    end_positions) → scalar loss.
    """
    config: T.TransformerConfig
    #: ZeRO-3 partition dims (set by the engine at stage 3; zero3.py)
    zero3_dims: object = None
    #: ZeRO-3 gather prefetch (engine overlap_comm): paired-layer scan
    #: hiding the second gather under the first block's compute
    #: (transformer.scan_layers)
    zero3_prefetch: bool = False

    @classmethod
    def from_size(cls, size: str, **overrides):
        kw = dict(BERT_SIZES[size])
        kw.update(overrides)
        kw.setdefault("pre_ln", False)
        kw.setdefault("causal", False)
        return cls(T.TransformerConfig(**kw))

    def validate(self, mp_size: int = 1):
        """Engine hook: shape checks against the actual mp degree."""
        self.config.validate(mp_size)

    def init_params(self, rng):
        cfg = self.config
        h = cfg.hidden_size
        k_bb, k_qa = jax.random.split(rng, 2)
        params = _init_backbone_params(cfg, k_bb)
        params["qa_w"] = jax.random.normal(k_qa, (h, 2),
                                           jnp.float32) * cfg.init_std
        params["qa_b"] = jnp.zeros((2,), jnp.float32)
        return params

    def partition_specs(self, params=None):
        specs = _backbone_partition_specs()
        specs.update({"qa_w": P(), "qa_b": P()})
        return specs

    def batch_specs(self, batch):
        """Engine hook: (ids, mask, type_ids) are [B, T]; start/end
        positions are [B] per-example scalars."""
        seq = P(DATA_AXIS, SEQ_AXIS)
        return (seq, seq, seq, P(DATA_AXIS), P(DATA_AXIS))

    def zero3_min_dims(self, params):
        """Engine hook (stage 3): block leaves pin dim >= 1 (layer stack)."""
        return _zero3_min_dims(params)

    def span_logits(self, params, input_ids, attention_mask, token_type_ids):
        """(start_logits, end_logits), each [B, T] fp32 — the prediction
        path for EM/F1 evaluation (metrics.best_spans)."""
        if L.axis_size_or_1(SEQ_AXIS) > 1:
            raise NotImplementedError(
                "span extraction softmaxes over the FULL sequence and "
                "indexes global positions — not supported under "
                "context_parallel_size > 1 (fine-tune lengths don't need it)")
        cfg = self.config
        params, z3_deferred = T.zero3_enter(params, self.zero3_dims)
        x = _encode(cfg, params, input_ids, attention_mask, token_type_ids,
                    z3_block_dims=z3_deferred.get("blocks"),
                    z3_prefetch=getattr(self, "zero3_prefetch", False))
        logits = (x @ params["qa_w"].astype(x.dtype)
                  + params["qa_b"].astype(x.dtype)).astype(jnp.float32)
        return logits[..., 0], logits[..., 1]

    def apply(self, params, input_ids, attention_mask, token_type_ids,
              start_positions, end_positions):
        start_logits, end_logits = self.span_logits(
            params, input_ids, attention_mask, token_type_ids)

        def span_loss(lg, pos):
            lg = jnp.where(attention_mask.astype(jnp.bool_), lg, -1e9)
            logp = jax.nn.log_softmax(lg, axis=-1)
            return -jnp.mean(jnp.take_along_axis(
                logp, pos[:, None], axis=-1)[:, 0])

        return 0.5 * (span_loss(start_logits, start_positions)
                      + span_loss(end_logits, end_positions))

    __call__ = apply
