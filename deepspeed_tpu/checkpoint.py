"""Checkpoint save/load with the reference's layout and role split.

TPU-native analog of /root/reference/deepspeed/pt/deepspeed_light.py:949-1127:

* layout   ``<dir>/<tag>/mp_rank_{MP:02d}_model_states.pt`` — ONE file per
           model shard (reference writes per-MP-rank files, :961-967) +
           ``<dir>/<tag>/zero_pp_rank_{DP}_mp_rank_{MP:02d}optim_states.pt``
           (path builders reference :949-967)
* roles    each model shard's states are written by the process holding its
           replica-0 device shards; every ZeRO partition owner saves its
           optimizer shard (reference _configure_checkpointing :329-343).
           All writes go through ``addressable_shards`` — a model-axis-sharded
           global array is NEVER gathered across hosts.
* content  model (compute-dtype) weights + fp32 masters, optimizer state,
           loss-scale state, lr-scheduler state, engine counters
           (global_steps/skipped_steps/micro_steps) and arbitrary
           ``client_state`` returned to the caller on load (reference
           :1019-1032)
* resume   fp32 master partitions round-trip bit-exactly (the reference saves
           them for the same reason, zero_optimizer.py:510-513); ZeRO
           checkpoints are saved UNPADDED, so a restore onto a different DP
           world size re-pads and re-partitions cleanly; non-ZeRO model
           states reassemble from per-MP-rank files and re-shard, so a
           restore onto a different MP degree also works (both beyond the
           reference, SURVEY.md §7.3)

Serialization is a pickled dict of numpy arrays per file, loaded through a
restricted unpickler that only resolves numpy array/dtype reconstructors and
builtin containers — unlike ``torch.load``, a checkpoint cannot smuggle
arbitrary code.
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu import zero as zero_mod
from deepspeed_tpu.parallel.topology import (DATA_AXIS, MODEL_AXIS,
                                             PIPE_AXIS)
from deepspeed_tpu.resilience import chaos as _chaos

MODEL_FILE = "mp_rank_{mp:02d}_model_states.pt"
# pipeline stages get their own model-state files (generalizing the
# reference's per-MP-rank layout rule, deepspeed_light.py:949-967)
MODEL_FILE_PP = "pp_stage_{pp:02d}_mp_rank_{mp:02d}_model_states.pt"
ZERO_FILE = "zero_pp_rank_{dp}_mp_rank_{mp:02d}optim_states.pt"
LATEST_FILE = "latest"


def _to_np(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


# ------------------------------------------------- chunked container format
#
# Layout: MAGIC (8 bytes) | header offset (8 bytes LE) | raw array payloads
# | pickled header.  In the header every ndarray above _INLINE_MAX bytes is
# replaced by a plain tuple ("__dstpu_chunk__", offset, dtype_name, shape)
# pointing into the payload region.  Writers stream one leaf at a time
# (peak host RAM = one leaf, not the whole state dict — VERDICT r4 weak #3:
# the old single-pickle format serialized ~14 bytes/param in RAM with
# training stalled); readers hand back np.memmap views, so restores stream
# from disk too.  Legacy files (plain pickle, no magic) still load.

_MAGIC = b"DSTPUCK1"
_CHUNK_TAG = "__dstpu_chunk__"
#: wrapper for USER tuples that collide with the ref namespace (a tuple in
#: ``client_state`` whose first element is the chunk/escape tag string):
#: the writer wraps them ``(_ESCAPE_TAG, t)`` at seal time, the reader
#: unwraps — so a chunk ref is ALWAYS the writer's own, never user data
_ESCAPE_TAG = "__dstpu_escape__"
_INLINE_MAX = 512          # small arrays stay pickled in the header
_HEADER_PREFIX = len(_MAGIC) + 8   # magic + header-offset word
_ML_DTYPES = {"bfloat16", "float8_e3m4", "float8_e4m3",
              "float8_e4m3b11fnuz", "float8_e4m3fn", "float8_e4m3fnuz",
              "float8_e5m2", "float8_e5m2fnuz", "float8_e8m0fnu",
              "float4_e2m1fn", "float6_e2m3fn", "float6_e3m2fn",
              "int2", "int4", "uint2", "uint4"}


def _np_dtype(name: str):
    if name in _ML_DTYPES:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))
    return np.dtype(name)


class _ChunkedWriter:
    """Streams arrays into the payload region; ``finish(header)`` seals the
    file.  ``put(obj)`` walks dict/list/tuple containers, converting each
    ndarray (or jax.Array) leaf to a chunk ref AS IT IS WRITTEN, so only one
    leaf's host copy is live at a time."""

    def __init__(self, path: str):
        self._path = path
        self._tmp = path + ".tmp"
        self._f = open(self._tmp, "wb")
        self._f.write(_MAGIC)
        self._f.write((0).to_bytes(8, "little"))
        self._refs = set()     # id()s of the ref tuples THIS writer issued

    def put_array(self, arr) -> tuple:
        a = np.ascontiguousarray(np.asarray(arr))
        off = self._f.tell()
        a.tofile(self._f)
        ref = (_CHUNK_TAG, off, a.dtype.name, tuple(a.shape))
        self._refs.add(id(ref))
        return ref

    def put(self, obj):
        if isinstance(obj, dict):
            return {k: self.put(v) for k, v in obj.items()}
        if isinstance(obj, tuple) and hasattr(obj, "_fields"):
            # the restricted unpickler cannot reconstruct user namedtuple
            # classes on load, and silently flattening them to plain
            # tuples (what this writer once did) corrupts round trips —
            # refuse loudly (docs/features.md "client_state restrictions")
            raise TypeError(
                f"checkpoint state contains a namedtuple "
                f"({type(obj).__name__}): convert it to a dict or plain "
                f"tuple before save_checkpoint — namedtuple classes "
                f"cannot be reconstructed by the restricted checkpoint "
                f"loader")
        if isinstance(obj, (list, tuple)):
            t = [self.put(v) for v in obj]
            return t if isinstance(obj, list) else tuple(t)
        if isinstance(obj, jax.Array) or (
                isinstance(obj, np.ndarray) and obj.nbytes > _INLINE_MAX):
            return self.put_array(obj)
        return obj

    def _escape(self, obj):
        """Namespace the ref tags: any tuple in the header that LOOKS like
        a chunk ref / escape wrapper but was not issued by this writer is
        user data — wrap it ``(_ESCAPE_TAG, t)`` so the reader never
        misinterprets it (``_resolve_chunks`` unwraps)."""
        if id(obj) in self._refs:
            return obj
        if isinstance(obj, dict):
            return {k: self._escape(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [self._escape(v) for v in obj]
        if isinstance(obj, tuple):
            t = tuple(self._escape(v) for v in obj)
            if t and t[0] in (_CHUNK_TAG, _ESCAPE_TAG):
                return (_ESCAPE_TAG, t)
            return t
        return obj

    def finish(self, header: Any) -> None:
        _chaos.io_point("ckpt_write")   # chaos tier: Nth-write IO failure
        header = self._escape(header)
        off = self._f.tell()
        pickle.dump(header, self._f, protocol=pickle.HIGHEST_PROTOCOL)
        self._f.seek(len(_MAGIC))
        self._f.write(off.to_bytes(8, "little"))
        self._f.close()
        os.replace(self._tmp, self._path)   # readers never see a torn file

    def abort(self) -> None:
        self._f.close()
        if os.path.exists(self._tmp):
            os.remove(self._tmp)


def _resolve_chunks(obj, path: str, payload_end: Optional[int] = None):
    """Replace chunk refs with read-only np.memmap views into ``path``.

    ``payload_end`` is the header offset — the payload region is
    ``[_HEADER_PREFIX, payload_end)`` and every ref is validated against
    it (offset/dtype/shape) BEFORE the memmap is constructed: a corrupt or
    truncated ref raises a ValueError naming the problem instead of
    handing back a garbage view.  User tuples that collide with the tag
    namespace arrive wrapped ``(_ESCAPE_TAG, t)`` and unwrap here."""
    if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == _ESCAPE_TAG:
        return tuple(_resolve_chunks(v, path, payload_end) for v in obj[1])
    if isinstance(obj, tuple) and len(obj) == 4 and obj[0] == _CHUNK_TAG:
        _, off, dtype_name, shape = obj
        if not (isinstance(off, int) and isinstance(dtype_name, str)
                and isinstance(shape, (tuple, list))
                and all(isinstance(s, int) and s >= 0 for s in shape)):
            raise ValueError(
                f"corrupt checkpoint {path!r}: malformed chunk ref "
                f"{obj!r}")
        try:
            dtype = _np_dtype(dtype_name)
        except Exception:
            raise ValueError(
                f"corrupt checkpoint {path!r}: chunk ref names unknown "
                f"dtype {dtype_name!r}")
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if off < _HEADER_PREFIX or (
                payload_end is not None and off + nbytes > payload_end):
            raise ValueError(
                f"corrupt checkpoint {path!r}: chunk ref offset={off} "
                f"size={nbytes} falls outside the payload region "
                f"[{_HEADER_PREFIX}, {payload_end})")
        return np.memmap(path, dtype=dtype, mode="r",
                         offset=off, shape=tuple(shape))
    if isinstance(obj, dict):
        return {k: _resolve_chunks(v, path, payload_end)
                for k, v in obj.items()}
    if isinstance(obj, list):
        return [_resolve_chunks(v, path, payload_end) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_resolve_chunks(v, path, payload_end) for v in obj)
    return obj


def _save_obj(path: str, obj: Any) -> None:
    """One-shot save through the chunked container (the streaming writers
    below are preferred for large states; this keeps small single-dict
    call sites simple)."""
    w = _ChunkedWriter(path)
    try:
        w.finish(w.put(obj))
    except BaseException:
        w.abort()
        raise


class _RestrictedUnpickler(pickle.Unpickler):
    """Only numpy array machinery and builtin containers resolve; anything
    else (os.system, subprocess, __reduce__ payloads) raises.  The format
    stays torch.save-like on disk without torch.load's arbitrary-code risk
    (ADVICE.md round 1)."""

    _SAFE = {
        "builtins": {"dict", "list", "tuple", "set", "frozenset", "complex",
                     "slice", "bytearray", "range"},
        "numpy": {"ndarray", "dtype", "bool_", "number", "generic"},
        "numpy.core.multiarray": {"_reconstruct", "scalar"},
        "numpy._core.multiarray": {"_reconstruct", "scalar"},
        "numpy.core.numeric": {"_frombuffer"},
        "numpy._core.numeric": {"_frombuffer"},
        "collections": {"OrderedDict"},
    }

    # the ml_dtypes scalar types a checkpoint can legitimately reference
    # (dtype classes only — finfo/iinfo and any future public callables
    # stay forbidden)
    _SAFE_ML_DTYPES = {
        "bfloat16", "float8_e3m4", "float8_e4m3", "float8_e4m3b11fnuz",
        "float8_e4m3fn", "float8_e4m3fnuz", "float8_e5m2",
        "float8_e5m2fnuz", "float8_e8m0fnu", "float4_e2m1fn",
        "float6_e2m3fn", "float6_e3m2fn", "int2", "int4", "uint2", "uint4",
    }

    def find_class(self, module, name):
        if module == "numpy.dtypes" or module == "numpy.core.numerictypes" \
                or module == "numpy._core.numerictypes":
            return super().find_class(module, name)   # dtype classes only
        if module == "ml_dtypes" and name in self._SAFE_ML_DTYPES:
            # bf16/fp8/intN numpy scalar types: a bf16 params array pickles
            # a reference to ml_dtypes.bfloat16.  Explicit allowlist (like
            # _SAFE) so new ml_dtypes public callables never widen this
            return super().find_class(module, name)
        if name in self._SAFE.get(module, ()):
            return super().find_class(module, name)
        if module == "numpy" and not name.startswith("_"):
            attr = getattr(np, name, None)
            if isinstance(attr, type) and issubclass(attr, np.generic):
                return attr                            # numpy scalar types
        raise pickle.UnpicklingError(
            f"checkpoint contains forbidden global {module}.{name}")


def _load_obj(path: str) -> Any:
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if head == _MAGIC:
            off = int.from_bytes(f.read(8), "little")
            f.seek(off)
            header = _RestrictedUnpickler(f).load()
            return _resolve_chunks(header, path, payload_end=off)
        f.seek(0)            # legacy single-pickle file (round <= 4)
        return _RestrictedUnpickler(f).load()


def model_file(ckpt_dir: str, tag: str, mp_rank: int = 0,
               pp_stage: int = 0, pp_size: int = 1) -> str:
    if pp_size > 1:
        return os.path.join(ckpt_dir, tag,
                            MODEL_FILE_PP.format(pp=pp_stage, mp=mp_rank))
    return os.path.join(ckpt_dir, tag, MODEL_FILE.format(mp=mp_rank))


def zero_file(ckpt_dir: str, tag: str, dp_rank: int, mp_rank: int = 0) -> str:
    return os.path.join(ckpt_dir, tag,
                        ZERO_FILE.format(dp=dp_rank, mp=mp_rank))


# ------------------------------------------- per-(pp stage, mp rank) split

def _axis_dim(spec, axis: str) -> Optional[int]:
    for d, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        if axis in axes:
            return d
    return None


def _rank_owners(mesh, axes):
    """Writer process for each composite rank: the process holding the mesh
    device at (rank's axis coordinates, every other axis 0).  Deterministic
    and communication-free — unlike replica-id probing, it cannot leave a
    rank ownerless when its sharded leaves' replica-0 copies straddle hosts
    (pipe-sharded blocks on one host, pipe-replicated embeddings on
    another)."""
    names = list(mesh.axis_names)
    sizes = [n for _, n in axes]
    S = 1
    for n in sizes:
        S *= n
    owners = []
    for r in range(S):
        rem, comps = r, []
        for n in reversed(sizes):
            rem, c = divmod(rem, n)
            comps.insert(0, c)
        idx = [0] * len(names)
        for (name, _), c in zip(axes, comps):
            if name in names:
                idx[names.index(name)] = c
        owners.append(int(mesh.devices[tuple(idx)].process_index))
    return owners


def _collect_shard_states(tree, specs, axes, mesh=None, replace=None,
                          materialize=True):
    """Split a sharded pytree into per-composite-rank local trees using ONLY
    this process's addressable shards (multi-host safe: nothing is gathered).

    ``axes`` is ``[(axis_name, size), ...]`` (row-major: first axis is the
    slowest-varying component of the composite rank — pipe before model).
    Returns ``(local_trees, owned)``: ``local_trees[r]`` is composite rank
    r's local slice tree (leaves this process cannot see are None) and
    ``owned[r]`` says whether this process is rank r's writer — the
    write-role rule (the reference's "dp rank 0 of each MP group saves",
    deepspeed_light.py:329-343).  With ``mesh`` the role comes from
    ``_rank_owners`` (multi-host safe for composite ranks); without it,
    from holding the replica-0 copy of every sharded leaf.

    ``replace`` (flat list aligned with the tree's leaves) substitutes
    non-None entries verbatim for every rank WITHOUT touching the leaf —
    the stage-3 save uses it to stamp partitioned-leaf markers into model
    files while the actual data goes to per-dp shard files.
    ``materialize=False`` returns the live ``Shard`` objects instead of
    host np copies (callers then stream ``np.asarray(shard.data)`` one
    leaf at a time — the chunked-writer path)."""
    sizes = [n for _, n in axes]
    axis_size = {name: n for name, n in axes}
    if mesh is not None:
        axis_size.update({str(k): int(v) for k, v in mesh.shape.items()})
    S = 1
    for n in sizes:
        S *= n
    strides = []
    acc = 1
    for n in reversed(sizes):
        strides.insert(0, acc)
        acc *= n
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    spec_leaves = treedef.flatten_up_to(specs)
    per_rank = [[None] * len(leaves) for _ in range(S)]
    owned = [True] * S
    any_sharded = False

    def ranks_for(comps):
        """Composite ranks a shard with per-axis components ``comps``
        (None = replicated over that axis → all positions) belongs to."""
        ranks = [0]
        for k, c in enumerate(comps):
            if c is None:
                ranks = [r + j * strides[k] for r in ranks
                         for j in range(sizes[k])]
            else:
                ranks = [r + c * strides[k] for r in ranks]
        return ranks

    def dim_comps(leaf, spec, s):
        """Per-state-axis component of shard ``s``, decoding dims that
        carry SEVERAL mesh axes (e.g. the stage-3 ``('model','data')``
        weight dim) by mixed radix in the spec entry's (major → minor)
        order."""
        comps = [None] * len(axes)
        for d, entry in enumerate(spec):
            if entry is None:
                continue
            names = list(entry) if isinstance(entry, tuple) else [entry]
            if not any(nm == name for nm in names for name, _ in axes):
                continue
            if any(nm not in axis_size for nm in names):
                raise ValueError(
                    f"cannot decode dim {d} sharded over {names}: axis "
                    f"size unknown (pass mesh)")
            total = 1
            for nm in names:
                total *= axis_size[nm]
            block = leaf.shape[d] // total
            linear = (s.index[d].start or 0) // block
            minor = 1
            for nm in reversed(names):
                comp = (linear // minor) % axis_size[nm]
                minor *= axis_size[nm]
                for k, (name, _) in enumerate(axes):
                    if name == nm:
                        comps[k] = comp
        return comps

    for i, (leaf, spec) in enumerate(zip(leaves, spec_leaves)):
        if replace is not None and replace[i] is not None:
            for r in range(S):
                per_rank[r][i] = replace[i]
            continue
        dims = [_axis_dim(spec, name) for name, _ in axes]
        if all(d is None for d in dims) or S == 1:
            # replicated over every state axis: addressable everywhere
            val = (leaf.addressable_shards[0] if not materialize
                   else np.asarray(leaf.addressable_shards[0].data))
            for r in range(S):
                per_rank[r][i] = val
            continue
        any_sharded = True
        seen = {}
        for s in leaf.addressable_shards:
            for r in ranks_for(dim_comps(leaf, spec, s)):
                if r not in seen or s.replica_id == 0:
                    seen[r] = (s, s.replica_id == 0)
        for r in range(S):
            if r in seen:
                per_rank[r][i] = (seen[r][0] if not materialize
                                  else np.asarray(seen[r][0].data))
                owned[r] = owned[r] and seen[r][1]
            else:
                owned[r] = False
    if mesh is not None:
        me = jax.process_index()
        owners = _rank_owners(mesh, axes)
        owned = [owners[r] == me for r in range(S)]
        for r in range(S):
            if owned[r] and any(v is None for v in per_rank[r]):
                raise RuntimeError(
                    f"checkpoint write role for composite rank {r} assigned "
                    f"to process {me} but some leaves are not addressable "
                    f"here — mesh/process layout mismatch")
    elif not any_sharded:
        owned = [jax.process_index() == 0] * S
    trees = [treedef.unflatten(per_rank[r]) for r in range(S)]
    return trees, owned


def _combine_shard_states(local_trees, specs, axes, lazy=False):
    """Inverse of ``_collect_shard_states`` on the host: one global np tree
    (``lazy=True``: deferred :class:`LazyParts` leaves for the streaming
    restore — only callers that feed ``_place_trees`` may ask for it).
    Combines the innermost axis first (rank = outer * inner_size + inner)."""
    return zero_mod.combine_composite_trees(local_trees, specs, axes,
                                            lazy=lazy)


def _state_axes(pp_size: int, mp_size: int):
    """The composite split used for model-state files: pipe major, model
    minor; at least one axis so the rank-0 path is uniform."""
    axes = []
    if pp_size > 1:
        axes.append((PIPE_AXIS, pp_size))
    axes.append((MODEL_AXIS, mp_size))
    return axes


def _collect_mp_states(tree, specs, mp_size: int):
    """Model-axis-only split (multi-process write-role tests exercise this
    directly; the engine paths use the composite _collect_shard_states)."""
    return _collect_shard_states(tree, specs, [(MODEL_AXIS, mp_size)])


# ------------------------------------------------- stage-3 native sharding
#
# ADVICE r4 (medium): the old stage-3 save materialised EVERY leaf's full
# global value on EVERY host (~14 bytes/param held simultaneously) — the
# exact anti-pattern ZeRO-3 exists to avoid.  The native format instead has
# each process write only its addressable data-axis shards: partitioned
# leaves live in per-(row, dp-rank) shard files, the per-row model-state
# files carry replicated leaves plus ("__dstpu_zero3_part__", dim, dp)
# markers, and loads reassemble by concatenating shard chunks along the
# recorded dim — so cross-topology and cross-stage restores still work.

_Z3_TAG = "__dstpu_zero3_part__"
_Z3_SKIP = ("__dstpu_zero3_skip__",)
ZERO3_FILE = "zero3_dp_rank_{dp}_row_{row:02d}_states.pt"


def zero3_file(ckpt_dir: str, tag: str, dp_rank: int, row: int) -> str:
    return os.path.join(ckpt_dir, tag,
                        ZERO3_FILE.format(dp=dp_rank, row=row))


def _z3_marker(obj):
    return (isinstance(obj, tuple) and len(obj) == 3 and obj[0] == _Z3_TAG)


def _flat_with_paths(tree):
    """(keystr, leaf) pairs in tree_flatten order."""
    return [(jax.tree_util.keystr(p), l)
            for p, l in jax.tree_util.tree_leaves_with_path(tree)]


def _shard_np(x):
    """Host value of a collected entry (a live Shard when collection ran
    with materialize=False, else an ndarray/marker already)."""
    return np.asarray(x.data) if hasattr(x, "data") and hasattr(
        x, "replica_id") else x


def _snapshot_put(x):
    """Async-save leaf transform: host np copy now, chunk-write later.

    The copy must be EXPLICIT (``np.array(..., copy=True)``):
    ``np.asarray`` on a jax array may return a zero-copy view of the
    device/host buffer on backends that allow it (CPU, and donated-buffer
    aliasing), and the async writer's "copy before donate" contract says
    the snapshot must survive the next train step overwriting that buffer
    — relying on backend-specific copy behavior is a silent-corruption
    bug waiting for a backend change (ADVICE round 5)."""
    if _z3_marker(x) or x is None:
        return x
    return np.array(_shard_np(x), copy=True)


def _stream_put(writer):
    """Sync-save leaf transform: host copy AND chunk write per leaf, so
    only one leaf's host copy is ever live."""
    def put(x):
        if _z3_marker(x) or x is None:
            return x
        a = np.asarray(_shard_np(x))
        if a.nbytes <= _INLINE_MAX:
            return a
        return writer.put_array(a)
    return put


# ------------------------------------------------------------------- saving

class _AsyncSaver:
    """One background writer thread; saves queue in submission order.  The
    synchronous caller hands over HOST data only (np copies made before the
    next step can donate the device buffers), so the training stall is the
    device→host snapshot, not the disk write (VERDICT r4 weak #3)."""

    def __init__(self):
        self._queue = None
        self._thread = None
        self._errors = []

    def _ensure(self):
        import atexit
        import queue
        import threading
        if self._thread is None or not self._thread.is_alive():
            self._queue = queue.Queue()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="dstpu-ckpt-writer")
            self._thread.start()
            atexit.register(self.wait)

    def _run(self):
        while True:
            fn = self._queue.get()
            try:
                fn()
            except BaseException as e:        # surfaced at wait()
                self._errors.append(e)
            finally:
                self._queue.task_done()

    def submit(self, fn):
        self._ensure()
        self._queue.put(fn)

    def wait(self):
        """Block until every queued save is on disk; re-raise the first
        background failure (a silent half-written checkpoint is worse
        than a late exception)."""
        if self._queue is not None:
            self._queue.join()
        if self._errors:
            e, self._errors = self._errors[0], []
            raise e


ASYNC_SAVER = _AsyncSaver()


def _reject_namedtuples(obj, where: str) -> None:
    """Raise on namedtuples anywhere in a user state tree (see
    _ChunkedWriter.put; checked eagerly so async saves fail at submit
    time on the calling thread, not inside the background writer)."""
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        raise TypeError(
            f"save_checkpoint: {where} contains a namedtuple "
            f"({type(obj).__name__}): convert it to a dict or plain tuple "
            f"— namedtuple classes cannot be reconstructed by the "
            f"restricted checkpoint loader (docs/features.md)")
    if isinstance(obj, dict):
        for k, v in obj.items():
            _reject_namedtuples(v, f"{where}[{k!r}]")
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _reject_namedtuples(v, f"{where}[{i}]")


def save_checkpoint(engine, save_dir: str, tag: Optional[str] = None,
                    client_state: Optional[dict] = None,
                    async_save: Optional[bool] = None) -> str:
    """Engine-level save (reference save_checkpoint :1048-1114).

    ``async_save=True`` snapshots device state to host synchronously (the
    only part that must stall training — after it returns, the next step
    may donate every device buffer) and performs the container writes on a
    background thread; ``engine.checkpoint_wait()`` blocks until durable.
    Defaults to the ``checkpoint.async_save`` config key.  Multi-process
    runs fall back to synchronous saves: the publish barriers are device
    collectives and must run on the main thread."""
    if async_save is None:
        async_save = bool(getattr(engine.config, "checkpoint_async_save",
                                  False))
    if async_save and jax.process_count() > 1:
        import logging
        logging.getLogger("deepspeed_tpu").warning(
            "async_save requested in a multi-process run: falling back to "
            "synchronous saves (the publish barrier is a device collective "
            "and cannot run on the writer thread)")
        async_save = False
    ASYNC_SAVER.wait()     # serialize with any still-pending earlier save
    # client_state restriction (docs/features.md): namedtuples cannot be
    # reconstructed by the restricted loader, and the async writer once
    # silently flattened them to plain tuples — reject at CALL time so the
    # failure is synchronous in both save modes
    _reject_namedtuples(client_state, "client_state")

    tag = tag or f"global_step{engine.global_steps}"
    path = os.path.join(save_dir, tag)
    os.makedirs(path, exist_ok=True)

    mp = engine.mp_world_size
    pp = getattr(engine, "pp_world_size", 1)
    axes = _state_axes(pp, mp)
    zero_flat = getattr(engine, "zero_flat", engine.zero_enabled)
    zero3 = getattr(engine, "zero3", False)
    scalar_state = {
        "loss_scale_state": _to_np(engine.loss_scale_state._asdict()),
        "loss_scale_variant": engine._ls_variant,
        "lr_scheduler": (engine.lr_scheduler.state_dict()
                         if engine.lr_scheduler is not None
                         and hasattr(engine.lr_scheduler, "state_dict")
                         else None),
        # the live hyperparameters the scheduler wrote into the facade
        # (torch persists these inside optimizer.state_dict param_groups)
        "param_groups": [dict(g) for g in engine.optimizer.param_groups],
        "global_steps": engine.global_steps,
        "skipped_steps": engine.skipped_steps,
        "micro_steps": engine.micro_steps,
        "zero_enabled": engine.zero_enabled,
        "zero_stage": getattr(engine, "zero_stage",
                              1 if engine.zero_enabled else 0),
        "mp_world_size": mp,
        "pp_world_size": pp,
        "client_state": dict(client_state or {}),
    }
    # the scheduler's state_dict is user-shaped too: catch namedtuples
    # there at CALL time as well, or an async save would only fail later
    # on the writer thread (surfacing at the NEXT wait/save, far from the
    # offending call)
    _reject_namedtuples(scalar_state["lr_scheduler"],
                        "lr_scheduler.state_dict()")

    S = pp * mp
    specs = engine._param_specs
    markers = None
    if zero3:
        # partitioned leaves go to per-(row, dp) shard files; model files
        # get markers (the stage-3-native format — ADVICE r4 medium)
        leaves, treedef = jax.tree_util.tree_flatten(engine.params)
        dflat = treedef.flatten_up_to(engine._zero3_dims)
        markers = [(_Z3_TAG, int(d), engine.dp_world_size) if d >= 0
                   else None for d in dflat]
        scalar_state["zero3_native"] = True
    collect = lambda t: _collect_shard_states(
        t, specs, axes, mesh=engine.mesh, replace=markers,
        materialize=False)
    params_s, owned = collect(engine.params)
    if zero_flat:
        # three SEPARATE lists: masters live in ZeRO files, and sharing one
        # list object would make any future in-place write corrupt all three
        master_s, m_s, v_s = ([None] * S for _ in range(3))
        step_np = None
    else:
        # replicated masters — or, at stage 3, markers pointing at the
        # per-dp shard files (no zero_pp_rank_* flat partitions)
        master_s, _ = collect(engine.master)
        m_s = ([None] * S if engine.opt_state.m is None else
               collect(engine.opt_state.m)[0])
        v_s = ([None] * S if engine.opt_state.v is None else
               collect(engine.opt_state.v)[0])
        step_np = np.asarray(engine.opt_state.step)

    writes = []      # (path, header_builder(writer)) thunks

    def model_state_write(rank):
        stage, mp_rank = divmod(rank, mp)

        def build(put):
            state = dict(scalar_state)
            state["mp_rank"] = mp_rank
            state["pp_stage"] = stage
            state["module"] = jax.tree_util.tree_map(
                put, params_s[rank], is_leaf=_z3_marker)
            if zero_flat:
                state["optimizer"] = None
            else:
                state["optimizer"] = {
                    "master": jax.tree_util.tree_map(
                        put, master_s[rank], is_leaf=_z3_marker),
                    "opt_state": {
                        "step": step_np,
                        "m": (None if m_s[rank] is None else
                              jax.tree_util.tree_map(
                                  put, m_s[rank], is_leaf=_z3_marker)),
                        "v": (None if v_s[rank] is None else
                              jax.tree_util.tree_map(
                                  put, v_s[rank], is_leaf=_z3_marker))},
                }
            return state
        return model_file(save_dir, tag, mp_rank, stage, pp), build

    for rank in range(S):
        if owned[rank]:
            writes.append(model_state_write(rank))

    if zero3:
        writes.extend(_zero3_shard_writes(engine, save_dir, tag, axes))
    if engine.save_zero_checkpoint:
        writes.extend(_zero_checkpoint_writes(engine, save_dir, tag))

    if async_save:
        # snapshot NOW (device→host copies — the training stall); write in
        # the background thread.
        snapped = [(p, build(_snapshot_put)) for p, build in writes]

        def flush():
            for p, header in snapped:
                w = _ChunkedWriter(p)
                try:
                    w.finish(w.put(header))
                except BaseException:
                    w.abort()
                    raise
            _publish(engine, save_dir, tag, path, S, mp, pp)
        ASYNC_SAVER.submit(flush)
        return path

    for p, build in writes:
        w = _ChunkedWriter(p)
        try:
            # leaves stream through the writer one at a time: ``put``
            # materialises one Shard's host copy and writes it immediately
            w.finish(build(_stream_put(w)))
        except BaseException:
            w.abort()
            raise

    _publish(engine, save_dir, tag, path, S, mp, pp)
    return path


def _publish(engine, save_dir, tag, path, S, mp, pp):
    """Barrier + stale-file cleanup + `latest` pointer.  In async mode this
    runs on the writer thread — safe because async saves are single-process
    (the barriers are device collectives and are skipped at
    process_count == 1)."""
    # all hosts finish their shard writes BEFORE the dp-leader publishes the
    # pointer (reference uses dist.barrier around checkpoint dirs,
    # deepspeed_light.py:1089); otherwise a reader following `latest` could
    # see a tag whose zero_pp_rank_* shards are still being written
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"dstpu_ckpt_{tag}_written")
    if jax.process_index() == 0:
        # drop model-state / zero3 shard files left by an earlier save of
        # the SAME tag under a different topology or stage (pp=1's
        # mp_rank_* vs pp>1's pp_stage_* names; stage-3's zero3_dp_rank_*
        # vs none) — a reader following `latest` must never pick up a
        # stale file (the flat zero shards handle the same hazard via
        # partition_count)
        expected = {os.path.basename(model_file(save_dir, tag,
                                                r % mp, r // mp, pp))
                    for r in range(S)}
        if getattr(engine, "zero3", False):
            dp = engine.dp_world_size
            expected |= {ZERO3_FILE.format(dp=d, row=row)
                         for d in range(dp) for row in range(S)}
        for f in os.listdir(path):
            stale = ((f.endswith("_model_states.pt")
                      or f.startswith("zero3_dp_rank_"))
                     and f not in expected)
            if stale:
                os.remove(os.path.join(path, f))
        # atomic pointer publish: a crash mid-write must never leave a
        # truncated/empty `latest` that breaks every future resume (the
        # same temp + os.replace contract as the state files themselves)
        latest = os.path.join(save_dir, LATEST_FILE)
        tmp = latest + ".tmp"
        with open(tmp, "w") as f:
            f.write(tag)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, latest)
    # second barrier: by the time ANY process returns, the pointer is
    # visible — tests/distributed/workers.py pins this contract
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(f"dstpu_ckpt_{tag}_published")


def _zero3_shard_writes(engine, save_dir, tag, axes):
    """Write thunks for the stage-3 per-(row, dp-rank) shard files: each
    process emits ONLY its addressable replica-0 data-axis slices of the
    partitioned leaves (param + fp32 master + moments) — nothing is
    gathered, so per-process host RAM during save is 1/dp of the
    partitioned state (the ADVICE r4 fix)."""
    dp = engine.dp_world_size
    mp = engine.mp_world_size
    pp = getattr(engine, "pp_world_size", 1)
    axes3 = axes + [(DATA_AXIS, dp)]
    specs = engine._param_specs
    leaves, treedef = jax.tree_util.tree_flatten(engine.params)
    dflat = treedef.flatten_up_to(engine._zero3_dims)
    skip = [None if d >= 0 else _Z3_SKIP for d in dflat]
    keys = [jax.tree_util.keystr(p) for p, _ in
            jax.tree_util.tree_leaves_with_path(engine.params)]
    collect3 = lambda t: _collect_shard_states(
        t, specs, axes3, mesh=engine.mesh, replace=skip, materialize=False)
    p3, owned3 = collect3(engine.params)
    mast3, _ = collect3(engine.master)
    m3 = (None if engine.opt_state.m is None
          else collect3(engine.opt_state.m)[0])
    v3 = (None if engine.opt_state.v is None
          else collect3(engine.opt_state.v)[0])
    step_np = np.asarray(engine.opt_state.step)

    writes = []
    for r in range(pp * mp * dp):
        if not owned3[r]:
            continue
        row, dpi = divmod(r, dp)

        def build(put, r=r, row=row, dpi=dpi):
            pl = treedef.flatten_up_to(p3[r])
            ml = treedef.flatten_up_to(mast3[r])
            mm = None if m3 is None else treedef.flatten_up_to(m3[r])
            vv = None if v3 is None else treedef.flatten_up_to(v3[r])
            # records key by FLATTEN-ORDER LEAF INDEX: the index is the
            # one identifier save and load share exactly (both walk the
            # same treedef), whereas a formatted keystr depends on the key
            # type's repr — an int-keyed dict in the state tree broke the
            # old string reconstruction.  keystr stays as a debug label.
            recs = {}
            for i, key in enumerate(keys):
                if skip[i] is not None:
                    continue
                recs[i] = {
                    "keystr": key,
                    "dim": int(dflat[i]),
                    "param": put(pl[i]),
                    "master": put(ml[i]),
                    "m": None if mm is None else put(mm[i]),
                    "v": None if vv is None else put(vv[i]),
                }
            return {"row": row, "dp_rank": dpi, "dp_world_size": dp,
                    "mp_world_size": mp, "pp_world_size": pp,
                    "step": step_np, "leaves": recs}
        writes.append((zero3_file(save_dir, tag, dpi, row), build))
    return writes


def _flat_partitions(arr, part: int) -> dict:
    """(mp_rank, dp_rank) → np partition for the flat-buffer shards THIS
    process holds (replica 0 only).  Handles both the 1-D P('data') layout
    and the ZeRO x MP [mp, local_padded] P('model','data') layout.
    Multi-host safe: never materialises the non-addressable global array."""
    out = {}
    for s in arr.addressable_shards:
        if s.replica_id != 0:
            continue
        if arr.ndim == 2:
            m = s.index[0].start or 0
            start = s.index[1].start or 0
            data = np.asarray(s.data)[0]
        else:
            m = 0
            start = (s.index[0].start or 0) if s.index else 0
            data = np.asarray(s.data)
        # a device shard may span several logical partitions (e.g. after a
        # mesh with fewer data shards than dp ranks); split it
        for off in range(0, data.shape[0], part):
            out[(m, (start + off) // part)] = data[off:off + part]
    return out


def _zero_checkpoint_writes(engine, save_dir: str, tag: str):
    """Write thunks for the per-partition flat optimizer shards (reference
    _save_zero_checkpoint :1116-1127).  Each process writes ONLY the
    partitions it owns (the reference's every-partition-owner-saves role,
    :338-343); the trailing padding is dropped so restores re-pad for
    their own topology."""
    meta = engine.flat_meta
    dp = engine.dp_world_size
    # parameter-parallel sub-groups (parameter_parallel_size < dp) tile the
    # flat buffer: only the first sub-group's partitions are distinct
    parts = engine.zero_pps
    part = meta.partition
    masters = _flat_partitions(engine.master_flat, part)
    ms = _flat_partitions(engine.opt_state.m["flat"], part)
    vs = _flat_partitions(engine.opt_state.v["flat"], part)
    step = np.asarray(engine.opt_state.step)
    writes = []
    for (m, r), master in masters.items():
        if r >= parts:
            continue  # replica of partition r % parts
        lo = r * part
        count = int(np.clip(meta.total - lo, 0, part))

        def build(put, m=m, r=r, master=master, count=count):
            return {
                "partition_id": r,
                "mp_rank": m,  # composite row id: pp_stage * mp + mp_rank
                "dp_world_size": dp,
                "partition_count": parts,
                "mp_world_size": engine.mp_world_size,
                "pp_world_size": getattr(engine, "pp_world_size", 1),
                "unpadded_total": meta.total,
                "step": step,
                "master": put(master[:count]),
                "m": put(ms[(m, r)][:count]),
                "v": put(vs[(m, r)][:count]),
            }
        writes.append((zero_file(save_dir, tag, r, m), build))
    return writes


# ------------------------------------------------------- tag discovery

def _model_probe(load_dir: str, tag: str) -> Optional[str]:
    """Path of the tag's canonical model-state file (mp rank 0 / stage 0),
    or None when neither layout's file exists."""
    mfile = model_file(load_dir, tag, 0)
    if os.path.exists(mfile):
        return mfile
    mfile = os.path.join(load_dir, tag, MODEL_FILE_PP.format(pp=0, mp=0))
    return mfile if os.path.exists(mfile) else None


def validate_tag(load_dir: str, tag: str) -> bool:
    """True when ``tag`` looks restorable: its canonical model-state file
    exists and its container header parses.  Cheap (header-only; chunk
    payloads resolve to lazy memmaps) — the auto-resume discovery runs it
    over every candidate, so a half-written or corrupt tag is skipped
    instead of crashing the restart (docs/resilience.md)."""
    probe = _model_probe(load_dir, tag)
    if probe is None:
        return False
    try:
        _load_obj(probe)
    except Exception:
        return False
    return True


def list_tags(load_dir: str) -> list:
    """Candidate tag names under ``load_dir``: every direct tag directory
    plus ``emergency/<tag>`` preemption-drain tags."""
    out = []
    try:
        entries = sorted(os.listdir(load_dir))
    except OSError:
        return out
    for e in entries:
        p = os.path.join(load_dir, e)
        if not os.path.isdir(p):
            continue
        if e == "emergency":
            try:
                subs = sorted(os.listdir(p))
            except OSError:
                continue
            out.extend(f"emergency/{s}" for s in subs
                       if os.path.isdir(os.path.join(p, s)))
        else:
            out.append(e)
    return out


def _tag_step(tag: str) -> int:
    """Trailing step number of a tag (``global_step12`` → 12; -1 when the
    tag carries none) — NUMERIC, so the mtime tie-break cannot misorder
    ``global_step9`` above ``global_step10`` lexicographically."""
    m = re.search(r"(\d+)$", tag)
    return int(m.group(1)) if m else -1


def find_latest_valid_tag(load_dir: str, exclude=()) -> Optional[str]:
    """Newest VALID checkpoint tag under ``load_dir`` — the auto-resume
    discovery (resilience.run_resumable).  "Newest" is by model-state-file
    mtime (trailing step number, then tag name, as deterministic
    tie-breaks for coarse-mtime or copy-flattened filesystems), over
    regular AND ``emergency/`` tags; tags whose model-state header does
    not parse are skipped, as are ``exclude``d tags (the resume driver
    passes tags that already failed a full load — e.g. a mid-save SIGKILL
    left the model header durable but the ZeRO shard files missing, which
    a header-only probe cannot see — so discovery falls back to the
    next-newest candidate instead of bricking every restart on the same
    half-written tag).  The ``latest`` pointer is NOT trusted blindly: a
    stale or corrupt pointer must not hide a newer (or the only) valid
    checkpoint."""
    best = None
    excluded = set(exclude)
    for tag in list_tags(load_dir):
        if tag in excluded:
            continue
        probe = _model_probe(load_dir, tag)
        if probe is None:
            continue
        try:
            _load_obj(probe)        # validate_tag's check, probe reused
        except Exception:
            continue
        key = (os.path.getmtime(probe), _tag_step(tag), tag)
        if best is None or key > best[0]:
            best = (key, tag)
    return None if best is None else best[1]


# ------------------------------------------- parallel streaming restore
#
# PR 4 made auto-resume the normal operating mode, which put RESTORE on the
# critical path of every restart — and the serial read path (leaf-at-a-time
# np.concatenate over memmap views, then per-leaf device placement) was the
# slow side: CKPT_BENCH.md measured 621 s restore vs 45 s async-save stall
# at 1.5B.  The pipeline below mirrors the async writer in the other
# direction: a reader pool streams chunk records from the container (ZeRO-3
# shard records read concurrently per shard file), each leaf is assembled
# as its chunks land, and device placement (`_put_global`) of leaf i
# overlaps the reads of every later leaf.  Readers use positioned file
# reads (`readinto`, which releases the GIL during the syscall) instead of
# memmap page faults (which hold it), each read is composed with
# ``io_retry``, and in-flight read results are bounded by
# ``restore_readahead_mb`` — peak host RAM is one readahead window plus the
# leaf being placed, NOT the whole state tree.  ``restore_threads <= 1``
# executes the same plan inline (the serial fallback); both paths run the
# identical per-leaf assembly, so they are bitwise-interchangeable
# (pinned by tests/test_checkpoint_restore.py).

LazyParts = zero_mod.LazyParts


class CheckpointReadError(RuntimeError):
    """A restore reader failed (corrupt/truncated chunk, or storage errors
    that exhausted the per-reader ``io_retries`` budget).  Named — a dead
    reader must surface as a prompt exception on the restoring thread, not
    as a hang of the consumer."""


class _RestorePlan:
    """Resolved restore-path knobs for one load: reader-pool width,
    readahead window, per-reader retry budget."""

    def __init__(self, threads: int = 1, readahead_mb: float = 256.0,
                 io_retries: int = 3):
        self.threads = int(threads)
        self.readahead_bytes = max(1, int(float(readahead_mb) * 2 ** 20))
        self.io_retries = int(io_retries)

    @classmethod
    def auto_threads(cls) -> int:
        # reads are memcpy-bound once the page cache is warm and IO-bound
        # when cold; a couple of readers per core covers both without
        # oversubscribing small hosts
        return max(2, min(8, 2 * (os.cpu_count() or 1)))

    @classmethod
    def from_engine(cls, engine) -> "_RestorePlan":
        cfg = getattr(engine, "config", None)
        threads = int(getattr(cfg, "checkpoint_restore_threads", 0))
        if threads == 0:
            threads = cls.auto_threads()
        return cls(
            threads=threads,
            readahead_mb=float(getattr(cfg, "checkpoint_restore_readahead_mb",
                                       256.0)),
            io_retries=int(getattr(cfg, "resilience_io_retries", 3)))


def _read_part(part):
    """Materialize one chunk source as a host array.

    np.memmap chunks are fetched with a positioned ``readinto`` — unlike
    ``np.asarray(memmap)``, whose page faults hold the GIL for the whole
    IO wait, ``readinto`` releases it, so pool readers actually overlap.
    A short read names the truncation instead of handing back garbage."""
    _chaos.read_point("ckpt_read")
    if isinstance(part, np.memmap) and getattr(part, "filename", None):
        out = np.empty(part.shape, part.dtype)
        if out.nbytes:
            with open(part.filename, "rb") as f:
                f.seek(int(part.offset))
                got = f.readinto(memoryview(
                    out.reshape(-1).view(np.uint8)))
            if got != out.nbytes:
                raise CheckpointReadError(
                    f"truncated checkpoint chunk in {part.filename!r}: "
                    f"wanted {out.nbytes} bytes at offset {part.offset}, "
                    f"file ended after {got}")
        return out
    if isinstance(part, np.ndarray):
        return np.asarray(part)
    return part


def _leaf_plan(leaf):
    """(parts, assemble) of one restore leaf — LazyParts pass through,
    anything else is a single already-resolved source."""
    if isinstance(leaf, LazyParts):
        return leaf.parts, leaf.assemble
    return [leaf], (lambda arrs: arrs[0])


def _part_desc(part) -> str:
    fn = getattr(part, "filename", None)
    if fn:
        return f"{fn}@{getattr(part, 'offset', '?')}"
    return type(part).__name__


def _stream_leaves(leaves, plan: _RestorePlan):
    """Yield host arrays for ``leaves`` in order, reads pipelined.

    Every leaf expands into its chunk parts; with ``plan.threads > 1`` a
    reader pool fetches parts concurrently (submission runs ahead of
    consumption until ``readahead_bytes`` of results are in flight, so
    the window — not the pool — bounds host RAM), and the consumer
    assembles each leaf as its chunks land.  The serial fallback
    (``threads <= 1``) executes the same plan inline: identical reads,
    identical assembly, bitwise-identical leaves."""
    from deepspeed_tpu.resilience.retry import io_retry

    def read(part):
        # exhausted-retry storage errors surface as the SAME named error on
        # both the serial and pooled paths (tests pin the contract)
        try:
            return io_retry(lambda: _read_part(part),
                            retries=plan.io_retries,
                            what=f"checkpoint chunk read ({_part_desc(part)})")
        except CheckpointReadError:
            raise
        except Exception as e:
            raise CheckpointReadError(
                f"checkpoint restore reader failed on "
                f"{_part_desc(part)}: {e}") from e

    plans = [_leaf_plan(x) for x in leaves]
    if plan.threads <= 1:
        for parts, assemble in plans:
            yield assemble([read(p) for p in parts])
        return

    import collections
    from concurrent.futures import ThreadPoolExecutor
    flat = [(p, int(getattr(p, "nbytes", 0) or 0))
            for parts, _ in plans for p in parts]
    ex = ThreadPoolExecutor(max_workers=plan.threads,
                            thread_name_prefix="dstpu-ckpt-reader")
    pending = collections.deque()   # (future, nbytes, part) in flat order
    state = {"si": 0, "inflight": 0}

    def pump():
        # keep at least one read in flight and the window full; consuming
        # a result frees window bytes, so the pool always drains forward
        # (no reader ever waits on the consumer — deadlock-free)
        while state["si"] < len(flat) and (
                not pending or state["inflight"] < plan.readahead_bytes):
            part, nb = flat[state["si"]]
            pending.append((ex.submit(read, part), nb, part))
            state["si"] += 1
            state["inflight"] += nb

    try:
        for parts, assemble in plans:
            arrs = []
            for _ in parts:
                pump()
                fut, nb, part = pending.popleft()
                try:
                    arrs.append(fut.result())
                except CheckpointReadError:
                    raise
                except Exception as e:
                    raise CheckpointReadError(
                        f"checkpoint restore reader failed on "
                        f"{_part_desc(part)}: {e}") from e
                state["inflight"] -= nb
                pump()
            yield assemble(arrs)
    finally:
        ex.shutdown(wait=False, cancel_futures=True)


def _place_trees(pairs, plan: _RestorePlan):
    """Restore ``pairs`` of (engine tree, loaded host/lazy tree): streams
    every leaf through ONE pipelined read plan (so placing the module
    overlaps reading the masters) and places each with ``_put_global``.
    Returns the placed trees in ``pairs`` order; ``None`` new-trees map to
    ``None`` (absent moment trees)."""
    olds, news, treedefs, counts = [], [], [], []
    for old, new in pairs:
        if old is None or new is None:
            treedefs.append(None)
            counts.append(0)
            continue
        o, td = jax.tree_util.tree_flatten(old)
        olds.extend(o)
        news.extend(td.flatten_up_to(new))
        treedefs.append(td)
        counts.append(len(o))
    stream = _stream_leaves(news, plan)
    try:
        placed = [_put_global(o, h) for o, h in zip(olds, stream)]
    finally:
        stream.close()      # releases the reader pool on error paths too
    out, i = [], 0
    for td, n in zip(treedefs, counts):
        if td is None:
            out.append(None)
        else:
            out.append(td.unflatten(placed[i:i + n]))
            i += n
    return out


# ------------------------------------------------------------------ loading

def load_module_tree(load_dir: str, tag: Optional[str] = None, specs=None):
    """Host-side module pytree reassembled from a checkpoint's model-state
    files, WITHOUT an engine — the raw-weights read behind
    pretrain→fine-tune transfer (reference BingBertSquad initializes from
    a pretrained BERT checkpoint this way).

    ``specs`` (a PartitionSpec tree matching the SAVED module structure)
    is required only when the checkpoint was written at mp>1 or pp>1 —
    reassembly must know which dims concatenate.  Returns None when no
    checkpoint exists under ``load_dir``.
    """
    ASYNC_SAVER.wait()
    read = _read_model_states(load_dir, tag)
    if read is None:
        return None
    _, states, saved_mp, saved_pp = read
    if saved_mp * saved_pp == 1:
        return states[0]["module"]
    if specs is None:
        raise ValueError(
            f"checkpoint was saved at mp={saved_mp}, pp={saved_pp}: pass "
            "specs (the saving model's partition_specs) so sharded leaves "
            "can be reassembled")
    return _combine_shard_states([s["module"] for s in states], specs,
                                 _state_axes(saved_pp, saved_mp))


def load_params_only(load_dir: str, tag: Optional[str] = None, specs=None,
                     dtype=None, threads: int = 0,
                     readahead_mb: float = 256.0, io_retries: int = 3):
    """Weights-only restore fast path: just the module tree, streamed
    through the PR 5 parallel reader — the serving cold-start read
    (deepspeed_tpu/inference/, docs/inference.md).  Re-entrant by
    design: a speculative-decoding engine calls it TWICE per cold start
    (target weights, then the draft model's checkpoint as a second
    stream with the draft's own ``specs`` — docs/inference.md
    "Speculative decoding").

    Skips every optimizer/ZeRO partition: the stage-1/2 flat-state
    ``zero_pp_rank_*`` shard records are NEVER opened (regression-pinned
    in tests/test_inference.py), and a stage-3 shard-native checkpoint
    reads only the ``param`` chunks of its per-dp shard files (masters
    and moments stay untouched on disk — the container format memmaps
    per chunk, so unread fields cost nothing).

    ``specs`` (the saving model's ``partition_specs()``) is required when
    the checkpoint was written at mp>1 or pp>1, like
    :func:`load_module_tree`.  ``dtype`` casts every floating leaf on
    the host as it lands (the serving engine loads fp32 masters' module
    copies straight into bf16).  ``threads=0`` auto-sizes the reader
    pool; 1 is the serial fallback running the identical plan.

    Returns ``(tag, host_tree)``; ``None`` when no valid checkpoint
    exists under ``load_dir``.
    """
    ASYNC_SAVER.wait()
    plan = _RestorePlan(
        threads=(threads if threads > 0 else _RestorePlan.auto_threads()),
        readahead_mb=readahead_mb, io_retries=io_retries)
    read = _read_model_states(load_dir, tag, lazy=True)
    if read is None:
        return None
    tag, states, saved_mp, saved_pp = read
    if saved_mp * saved_pp == 1:
        module = states[0]["module"]
    else:
        if specs is None:
            raise ValueError(
                f"checkpoint was saved at mp={saved_mp}, pp={saved_pp}: "
                "pass specs (the saving model's partition_specs) so "
                "sharded leaves can be reassembled")
        module = _combine_shard_states([s["module"] for s in states],
                                       specs, _state_axes(saved_pp, saved_mp),
                                       lazy=True)
    np_dtype = None if dtype is None else np.dtype(dtype)

    def _cast(arr):
        arr = np.asarray(arr)
        if np_dtype is None or not (
                np.issubdtype(arr.dtype, np.floating)
                or arr.dtype == jnp.bfloat16):
            return arr
        return arr.astype(np_dtype)

    leaves, treedef = jax.tree_util.tree_flatten(module)
    stream = _stream_leaves(leaves, plan)
    try:
        out = [_cast(h) for h in stream]
    finally:
        stream.close()
    return tag, treedef.unflatten(out)


# --------------------------------------------------------- KV handoff
# Prefill/decode disaggregation ships a slot's written KV page rows from
# a prefill replica to a decode replica as ONE chunk-container file —
# the same on-disk machinery as checkpoints (atomic tmp+rename seal,
# positioned memmap reads, validated chunk refs), so the handoff
# inherits every corruption/torn-file guarantee for free
# (deepspeed_tpu/inference/router.py, docs/inference.md "Fleet serving").

KV_HANDOFF_SCHEMA = "dstpu.kv_handoff"
KV_HANDOFF_VERSION = 1


def write_kv_handoff(path: str, *, k, v, meta: dict,
                     io_retries: int = 3) -> str:
    """Seal one slot's KV handoff artifact at ``path``: the written
    ``k``/``v`` rows (``[layers, tokens, kv_heads, head_dim]``, the
    GLOBAL heads dim) as payload chunks plus a ``meta`` bookkeeping dict
    (prompt tokens, first token, dims — the importer validates these
    against its own cache spec).  Atomic (tmp + rename) and retried
    through ``io_retry`` like every checkpoint write; the target
    directory is created if missing."""
    from deepspeed_tpu.resilience.retry import io_retry
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    header = {"schema": KV_HANDOFF_SCHEMA, "version": KV_HANDOFF_VERSION,
              "meta": dict(meta)}

    def _write():
        w = _ChunkedWriter(path)
        try:
            payload = dict(header)
            payload["k"] = w.put_array(k)
            payload["v"] = w.put_array(v)
            w.finish(payload)
        except BaseException:
            w.abort()
            raise
    io_retry(_write, retries=io_retries,
             what=f"kv handoff write {path!r}")
    return path


def read_kv_handoff(path: str, io_retries: int = 3):
    """Load a KV handoff artifact: ``(meta, k, v)`` with the arrays
    materialized from positioned memmap reads (the PR 5 reader's chunk
    resolution — offsets/dtypes/shapes validated against the payload
    region BEFORE any view is built).  Transient storage errors retry
    through ``io_retry``; a corrupt, truncated or wrong-schema file
    raises :class:`CheckpointReadError` naming the problem — a decode
    replica must fail the one handoff loudly, never import garbage
    pages."""
    from deepspeed_tpu.resilience.retry import io_retry

    def _read():
        _chaos.read_point("ckpt_read")   # chaos tier: Nth-read IO failure
        return _load_obj(path)

    try:
        obj = io_retry(_read, retries=io_retries,
                       what=f"kv handoff read {path!r}")
    except (ValueError, pickle.UnpicklingError, EOFError) as e:
        raise CheckpointReadError(
            f"corrupt KV handoff {path!r}: {e}") from e
    if not isinstance(obj, dict) \
            or obj.get("schema") != KV_HANDOFF_SCHEMA:
        raise CheckpointReadError(
            f"{path!r} is not a KV handoff artifact (schema "
            f"{obj.get('schema') if isinstance(obj, dict) else None!r})")
    if obj.get("version") != KV_HANDOFF_VERSION:
        raise CheckpointReadError(
            f"KV handoff {path!r} has version {obj.get('version')!r}, "
            f"this reader understands {KV_HANDOFF_VERSION}")
    try:
        # np.asarray faults the memmap pages in NOW, so a payload
        # truncated past the validated header surfaces here, named
        k = np.asarray(obj["k"])
        v = np.asarray(obj["v"])
    except (KeyError, ValueError, OSError) as e:
        raise CheckpointReadError(
            f"corrupt KV handoff {path!r}: {e}") from e
    return obj.get("meta", {}), k, v


def _zero3_rehydrate(load_dir: str, tag: str, states, lazy: bool = False):
    """Replace stage-3 partition markers in freshly read model states with
    full-along-data leaves reassembled from the per-(row, dp) shard files
    (concat along the recorded dim).  After this the states look exactly
    like stage-<=2 files, so every downstream path (cross-row combine,
    cross-topology/-stage restore, raw-weights reads) works unchanged.
    With ``lazy=False`` reassembly materialises one full leaf at a time on
    the host (the shard chunks themselves are memmap views); ``lazy=True``
    returns :class:`LazyParts` leaves instead — same chunks, same concat,
    deferred so the restore reader pool can fetch the per-dp shard records
    of one leaf concurrently (``_stream_leaves``)."""
    if not states or not states[0].get("zero3_native"):
        return states
    for row, state in enumerate(states):
        cache = {}

        def shard_leaves(dpi):
            if dpi not in cache:
                f = zero3_file(load_dir, tag, dpi, row)
                if not os.path.exists(f):
                    raise FileNotFoundError(
                        f"stage-3 checkpoint is missing shard file {f} "
                        f"(saved at dp={states[0].get('dp_world_size')})")
                cache[dpi] = _load_obj(f)["leaves"]
            return cache[dpi]

        def fix(tree, field):
            """Replace markers by walking the state tree in FLATTEN ORDER:
            leaf i here is leaf i of the saving engine's params tree, so
            the shard record is ``leaves[i]`` — no path-string formatting
            (the old hand-built keystrs broke on int-keyed dicts; ADVICE
            r5).  ``keystr``-keyed records from legacy shard files still
            resolve as a fallback."""
            idx = [-1]

            def one(path, leaf):
                idx[0] += 1
                if not _z3_marker(leaf):
                    return leaf
                _, dim, dp = leaf

                def rec(d):
                    leaves = shard_leaves(d)
                    r = leaves.get(idx[0])
                    if r is None:   # legacy keystr-keyed shard files
                        r = leaves[jax.tree_util.keystr(path)]
                    return r

                chunks = [rec(d)[field] for d in range(dp)]
                if lazy:
                    return LazyParts.concat(chunks, dim)
                return np.concatenate(
                    [np.asarray(c) for c in chunks], axis=dim)

            return jax.tree_util.tree_map_with_path(
                one, tree, is_leaf=_z3_marker)

        state["module"] = fix(state["module"], "param")
        opt = state.get("optimizer")
        if opt is not None:
            opt["master"] = fix(opt["master"], "master")
            if opt["opt_state"]["m"] is not None:
                opt["opt_state"]["m"] = fix(opt["opt_state"]["m"], "m")
            if opt["opt_state"]["v"] is not None:
                opt["opt_state"]["v"] = fix(opt["opt_state"]["v"], "v")
    return states


def _read_model_states(load_dir: str, tag: Optional[str], lazy: bool = False):
    """Shared tag-resolution + model-state file reads (load_checkpoint and
    load_module_tree).  Returns ``(tag, states, saved_mp, saved_pp)`` or
    None when no checkpoint exists.  ``lazy`` defers the stage-3 shard
    reassembly to :class:`LazyParts` leaves (the streaming restore)."""
    if tag is None:
        latest = os.path.join(load_dir, LATEST_FILE)
        tag = None
        if os.path.exists(latest):
            with open(latest) as f:
                tag = f.read().strip() or None
        if tag is None or not validate_tag(load_dir, tag):
            # a corrupt/empty/stale `latest` (crash mid-publish, deleted
            # tag dir) must not break resume: fall back to the newest
            # valid tag directory on disk (regression-pinned in
            # tests/test_resilience.py)
            fallback = find_latest_valid_tag(load_dir)
            if tag is not None and fallback is not None:
                import logging
                logging.getLogger(__name__).warning(
                    "checkpoint `latest` pointer (%r) is corrupt or names "
                    "an invalid tag; falling back to newest valid tag %r",
                    tag, fallback)
            tag = fallback
            if tag is None:
                return None
    mfile = _model_probe(load_dir, tag)
    if mfile is None:
        # (explicit-tag path; the canonical probe covers both the mp_rank
        # and the pp>1 per-stage file layouts)
        return None
    state = _load_obj(mfile)
    saved_mp = int(state.get("mp_world_size", 1))
    saved_pp = int(state.get("pp_world_size", 1))
    states = [state] + [
        _load_obj(model_file(load_dir, tag, r % saved_mp, r // saved_mp,
                             saved_pp))
        for r in range(1, saved_pp * saved_mp)]
    states = _zero3_rehydrate(load_dir, tag, states, lazy=lazy)
    return tag, states, saved_mp, saved_pp


def _put_global(old, new):
    """Place the host-global value ``new`` on devices with ``old``'s
    sharding and dtype, WITHOUT collectives.

    ``jax.device_put`` of a host value whose target sharding spans
    processes first runs ``multihost_utils.assert_equal`` — a full-array
    cross-host broadcast per LEAF.  Across a restore that is O(model
    bytes) of gloo/ICI traffic for values every host just read from the
    same files, and the per-leaf broadcast stream was the desync surface
    the chaos tier kept tripping (a lagging rank pairs broadcast k with
    k+1 and the transport aborts).  ``make_array_from_callback`` builds
    the array from locally-addressable shards with no cross-process
    traffic at all."""
    arr = np.asarray(new, old.dtype)
    if arr.shape != tuple(old.shape):
        raise ValueError(
            f"checkpoint restore: loaded value has shape {arr.shape}, "
            f"engine expects {tuple(old.shape)}")
    sharding = old.sharding
    if sharding.is_fully_addressable:
        # device_put straight from the host buffer: routing through
        # jnp.asarray first would stage an extra full-leaf copy on the
        # restore critical path
        return jax.device_put(arr, sharding)
    return jax.make_array_from_callback(arr.shape, sharding,
                                        lambda idx: arr[idx])


def init_from_module_tree(engine, module) -> tuple:
    """Transfer same-named, same-shaped leaves of ``module`` into
    ``engine.params`` — the pretrain→fine-tune initialization (a fresh
    task head keeps its random init).  fp32 masters re-derive from the
    merged params so the first ``step()`` cannot revert the transfer.
    Returns ``(loaded, skipped)`` key-path lists.
    """
    src = {jax.tree_util.keystr(k): v
           for k, v in jax.tree_util.tree_leaves_with_path(module)}
    loaded, skipped = [], []

    def merge(path, old):
        key = jax.tree_util.keystr(path)
        new = src.get(key)
        if new is not None and tuple(np.shape(new)) == tuple(old.shape):
            loaded.append(key)
            return _put_global(old, new)
        skipped.append(key)
        return old

    engine.params = jax.tree_util.tree_map_with_path(merge, engine.params)
    _rederive_masters(engine)
    return loaded, skipped


def load_checkpoint(engine, load_dir: str, tag: Optional[str] = None,
                    load_optimizer_states: bool = True,
                    load_lr_scheduler_states: bool = True):
    """Engine-level load (reference load_checkpoint :974-1046).  Returns
    ``(path, client_state)``; (None, None) when nothing is found.

    The heavy reads run through the streaming restore pipeline (see the
    "parallel streaming restore" section above): every state tree's leaves
    enter ONE read plan, so the reader pool fetches the masters' chunks
    while the module weights are already being placed on devices."""
    ASYNC_SAVER.wait()   # never read a tag whose writes are still queued
    plan = _RestorePlan.from_engine(engine)
    read = _read_model_states(load_dir, tag, lazy=True)
    if read is None:
        return None, None
    tag, states, saved_mp, saved_pp = read
    state = states[0]

    # module weights (compute dtype), reassembled from the per-stage/MP-rank
    # local slices and re-sharded for the CURRENT mesh — reference :995-1004
    # (which requires the same MP degree; the reassembly lifts that)
    saved_axes = _state_axes(saved_pp, saved_mp)
    # lazy: cross-MP/PP-shard concatenations stay deferred so the reader
    # pool fetches each shard's chunks concurrently (_place_trees streams
    # every leaf below)
    module = _combine_shard_states([s["module"] for s in states],
                                   engine._param_specs, saved_axes,
                                   lazy=True)

    # counters — reference :1014-1017
    engine.global_steps = int(state["global_steps"])
    engine.skipped_steps = int(state["skipped_steps"])
    engine.micro_steps = int(state["micro_steps"])

    # loss scale — through _put_global, NOT a bare jnp.asarray: the
    # engine pins these leaves committed+replicated at build, and an
    # unpinned restore would hash a DIFFERENT executable key than the
    # cached step program, so every resume would pay a recompile the
    # persistent cache can never serve (the same stability.unpinned-
    # sharding class as the opt_state.step incident; pinned by
    # test_compile_cache_hits_after_restore)
    old_ls = engine.loss_scale_state._asdict()
    engine.loss_scale_state = type(engine.loss_scale_state)(
        **{k: _put_global(old_ls[k], np.asarray(v))
           for k, v in state["loss_scale_state"].items()})

    for live, saved in zip(engine.optimizer.param_groups,
                           state.get("param_groups", [])):
        live.update(saved)

    if (load_lr_scheduler_states and engine.lr_scheduler is not None
            and state.get("lr_scheduler") is not None
            and hasattr(engine.lr_scheduler, "load_state_dict")):
        engine.lr_scheduler.load_state_dict(state["lr_scheduler"])

    restored_masters = False
    saved_stage = state.get("zero_stage",
                            1 if state.get("zero_enabled") else 0)
    zero_flat = getattr(engine, "zero_flat", engine.zero_enabled)
    opt_pairs = []
    if load_optimizer_states:
        if zero_flat:
            if saved_stage == 3:
                raise ValueError(
                    "checkpoint was saved at ZeRO stage 3 (optimizer state "
                    "inline, per-leaf) but this engine runs the stage-1/2 "
                    "flat layout — set zero_optimization.stage=3 (or 0) to "
                    "restore it, or pass load_optimizer_states=False")
        elif saved_stage in (1, 2):
            raise ValueError(
                "checkpoint was saved with zero_optimization stage 1/2 "
                "(its optimizer state lives in zero_pp_rank_* shards) but "
                "this engine runs no flat ZeRO layout — match the stage, "
                "or pass load_optimizer_states=False for a weights-only "
                "load")
        elif state.get("optimizer") is not None:
            master = _combine_shard_states(
                [s["optimizer"]["master"] for s in states],
                engine._param_specs, saved_axes, lazy=True)
            m_trees = [s["optimizer"]["opt_state"]["m"] for s in states]
            m_tree = (None if m_trees[0] is None
                      else _combine_shard_states(m_trees,
                                                 engine._param_specs,
                                                 saved_axes, lazy=True))
            v_trees = [s["optimizer"]["opt_state"]["v"] for s in states]
            v_tree = (None if v_trees[0] is None
                      else _combine_shard_states(v_trees,
                                                 engine._param_specs,
                                                 saved_axes, lazy=True))
            opt_pairs = [(engine.master, master),
                         (engine.opt_state.m, m_tree),
                         (engine.opt_state.v, v_tree)]

    placed = _place_trees([(engine.params, module)] + opt_pairs, plan)
    engine.params = placed[0]
    if opt_pairs:
        engine.master = placed[1]
        engine.opt_state = type(engine.opt_state)(
            # through _put_global, NOT a bare jnp.asarray: the step counter
            # must come back with the engine's replicated sharding or the
            # boundary program re-lowers with an unpinned scalar input —
            # a different executable, so the persistent compile cache
            # misses on every resume (the exact recompile fast resume
            # exists to avoid)
            step=_put_global(engine.opt_state.step,
                             state["optimizer"]["opt_state"]["step"]),
            m=placed[2], v=placed[3])
        restored_masters = True
    if load_optimizer_states and zero_flat:
        _load_zero_checkpoint(engine, load_dir, tag, plan)
        restored_masters = True
    if not restored_masters:
        # weights-only fine-tune (load_optimizer_states=False), or a
        # checkpoint whose optimizer states live elsewhere: the fp32 masters
        # MUST be re-derived from the loaded weights or the first step()
        # would silently revert params to the pre-load masters
        _rederive_masters(engine)

    return os.path.join(load_dir, tag), state.get("client_state", {})


def _rederive_masters(engine) -> None:
    """Rebuild fp32 masters (flat or per-leaf) from engine.params."""
    masters = jax.tree_util.tree_map(
        lambda p: jnp.asarray(p, jnp.float32), engine.params)
    zero_flat = getattr(engine, "zero_flat", engine.zero_enabled)
    if zero_flat and engine._zero_state_axes:
        engine.master_flat = engine._flatten_masters_2d(masters)
    elif zero_flat:
        flat = engine._tile_flat(
            zero_mod.flatten_tree(masters, engine.flat_meta))
        engine.master_flat = jax.device_put(flat,
                                            engine.master_flat.sharding)
    else:
        engine.master = jax.tree_util.tree_map(
            lambda old, m: jax.device_put(m, old.sharding),
            engine.master, masters)


def _load_zero_checkpoint(engine, load_dir: str, tag: str,
                          plan: Optional[_RestorePlan] = None) -> None:
    """Reassemble the flat fp32 master + moments from per-partition shards
    saved under ANY dp world size, re-pad for the current topology
    (reference _load_zero_checkpoint :1034-1046 requires matching topology;
    we lift the DP restriction — MP and PP must match, like the
    reference).  The shard-chunk reads stream through the restore plan:
    master / m / v enter one pipelined plan, so the moments' partitions
    read while the master is being placed and the params re-derived."""
    mp = engine.mp_world_size
    pp = getattr(engine, "pp_world_size", 1)
    meta = engine.flat_meta
    first = zero_file(load_dir, tag, 0, 0)
    if not os.path.exists(first):
        raise FileNotFoundError(
            f"no zero checkpoint shards under {load_dir}/{tag}")
    shard0 = _load_obj(first)
    saved_mp = int(shard0.get("mp_world_size", 1))
    saved_pp = int(shard0.get("pp_world_size", 1))
    if saved_mp != mp or saved_pp != pp:
        raise ValueError(
            f"zero checkpoint was saved with model_parallel_size="
            f"{saved_mp}, pipeline_parallel_size={saved_pp}; engine has "
            f"mp={mp}, pp={pp}: ZeRO flat partitions are per-stage/shard "
            f"and cannot be re-split (load with "
            f"load_optimizer_states=False for a weights-only restore)")
    # trust the recorded partition count, not directory probing — stale
    # shards from an earlier save of the same tag under a larger dp must be
    # ignored (partition_count < dp_world_size when the save side used
    # parameter_parallel_size sub-groups)
    saved_dp = int(shard0.get("partition_count", shard0["dp_world_size"]))
    total = int(shard0["unpadded_total"])
    if total != meta.total:
        raise ValueError(
            f"zero checkpoint has {total} elements, engine expects "
            f"{meta.total} (different model?)")

    rows = pp * mp  # composite stage/rank rows of the [S, local] layout
    table = [[_load_obj(zero_file(load_dir, tag, r, m))
              for r in range(saved_dp)] for m in range(rows)]

    def lazy_stack(key):
        """Deferred [rows?, padded·repl] buffer for ``key``: the per-(row,
        partition) shard chunks are the parts a reader pool fetches;
        assembly concatenates each row, re-pads, and re-tiles for the
        engine's sub-group layout (no-op at pps == dp)."""
        parts = [table[m][r][key]
                 for m in range(rows) for r in range(saved_dp)]

        def assemble(arrs):
            mats = []
            for m in range(rows):
                flat = np.concatenate(
                    [np.asarray(a)
                     for a in arrs[m * saved_dp:(m + 1) * saved_dp]])
                assert flat.shape[0] == total, (key, flat.shape, total)
                pad = meta.padded - total
                if pad:
                    flat = np.concatenate(
                        [flat, np.zeros((pad,), flat.dtype)])
                mats.append(engine._tile_flat(flat))
            return mats[0] if rows == 1 else np.stack(mats)

        return LazyParts(parts, assemble)

    stream = _stream_leaves(
        [lazy_stack("master"), lazy_stack("m"), lazy_stack("v")],
        plan or _RestorePlan())
    try:
        host_master = next(stream)
        engine.master_flat = _put_global(engine.master_flat, host_master)
        host_m = next(stream)
        host_v = next(stream)
    finally:
        stream.close()
    engine.opt_state = type(engine.opt_state)(
        # _put_global keeps the step counter's replicated sharding so the
        # restored boundary step re-lowers to the SAME executable and the
        # persistent compile cache can serve it (see the stage-3 site)
        step=_put_global(engine.opt_state.step, table[0][0]["step"]),
        m={"flat": _put_global(engine.opt_state.m["flat"], host_m)},
        v={"flat": _put_global(engine.opt_state.v["flat"], host_v)})
    # params re-derived from the HOST copy of the restored master (bit-exact
    # resume; never device_gets the sharded global array — multi-host safe)
    engine.params = engine._params_from_master_flat(host_master)
