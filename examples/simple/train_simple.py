"""Minimal deepspeed_tpu example: a 2-layer MLP on synthetic regression data.

Shows the full user surface in ~80 lines: CLI flags, config file, the
dataloader route, the forward/backward/step loop, fp16 loss-scale
observables, and checkpoint save/resume.

    python examples/simple/train_simple.py \
        --deepspeed_config examples/simple/ds_config.json
"""

import os as _os
import sys as _sys

# run from a checkout without installing (docs/install.md covers
# pip install; this keeps `python examples/...` working in-place)
_REPO_ROOT = _os.path.abspath(
    _os.path.join(_os.path.dirname(__file__), "..", ".."))
if _REPO_ROOT not in _sys.path:
    _sys.path.insert(0, _REPO_ROOT)

import argparse
import os

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu

HIDDEN = 64


class MLP:
    def init_params(self, rng):
        k1, k2 = jax.random.split(rng)
        s = 1.0 / np.sqrt(HIDDEN)
        return {
            "w1": jax.random.normal(k1, (HIDDEN, HIDDEN)) * s,
            "b1": jnp.zeros((HIDDEN,)),
            "w2": jax.random.normal(k2, (HIDDEN, 1)) * s,
        }

    def apply(self, params, x, y):
        # cast inputs to the parameter dtype: under fp16/bf16 the engine
        # keeps params low-precision, and an fp32 batch would silently
        # promote every matmul back to fp32 (graph-lint
        # precision.upcast-dot); the loss math stays fp32
        x = x.astype(params["w1"].dtype)
        h = jax.nn.relu(x @ params["w1"] + params["b1"])
        pred = (h @ params["w2"])[:, 0].astype(jnp.float32)
        return jnp.mean((pred - y) ** 2)


class RegressionDataset:
    """numpy dataset: y = a quadratic of a random projection + noise."""

    def __init__(self, n=4096, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.normal(size=(n, HIDDEN)).astype(np.float32)
        w = rng.normal(size=(HIDDEN,)) / np.sqrt(HIDDEN)
        z = self.x @ w
        self.y = (z + 0.1 * z ** 2 + 0.01 * rng.normal(size=n)).astype(
            np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--ckpt_dir", type=str, default="/tmp/dst_simple")
    parser.add_argument("--local_rank", type=int, default=-1)
    deepspeed_tpu.add_config_arguments(parser)
    args = parser.parse_args()

    # rendezvous when launched by `dst` (no-op single-process): the CI
    # observability smoke runs this script 2-process with fleet
    # aggregation + live health endpoints (docs/observability.md)
    deepspeed_tpu.init_distributed()

    model = MLP()
    engine, optimizer, dataloader, _ = deepspeed_tpu.initialize(
        args, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)),
        training_data=RegressionDataset())

    # resume if a checkpoint exists
    path, client = engine.load_checkpoint(args.ckpt_dir)
    start = client.get("step", 0) if client else 0
    if path:
        print(f"resumed from {path} at step {start}")

    it = iter(dataloader)

    def next_batch(step):
        nonlocal it
        try:
            return next(it)
        except StopIteration:
            dataloader.set_epoch(step)   # reshuffle
            it = iter(dataloader)
            return next(it)

    k = engine.steps_per_dispatch
    if k > 1:
        # multi-step driver (config train_steps_per_dispatch): K fused
        # optimizer steps per dispatch, blocks staged ahead by the
        # double-buffered prefetcher (docs/features.md "Multi-step
        # driver").  Bitwise-identical trajectory to the K=1 loop.
        from deepspeed_tpu.data import BlockPrefetcher

        def batches():
            step = start
            while True:
                yield next_batch(step)
                step += 1

        for block in BlockPrefetcher(batches(), k=k):
            need = args.steps - engine.global_steps
            if need <= 0:
                break
            # clamp the trailing block so --steps is exact (a short
            # final block compiles one extra K'-step program)
            loss = engine.train_many(block[:need] if need < k else block)
            step = engine.global_steps
            if step % 20 < k:
                print(f"step {step:4d}  loss {float(loss):.5f}  "
                      f"scale {optimizer.cur_scale:.0f}")
            if step >= args.steps:
                break
    else:
        for step in range(start, args.steps):
            batch = next_batch(step)
            loss = engine(*batch)
            engine.backward(loss)
            engine.step()
            if step % 20 == 0:
                print(f"step {step:4d}  loss {float(loss):.5f}  "
                      f"scale {optimizer.cur_scale:.0f}")

    # drain the final (possibly partial) telemetry window before exit —
    # a no-op unless the config enables the observability metric spool
    # (ds_config_telemetry.json; docs/observability.md)
    engine.flush_telemetry()
    engine.save_checkpoint(args.ckpt_dir, client_state={"step": args.steps})
    print("final loss:", float(loss))


if __name__ == "__main__":
    main()
