"""Host-concurrency lint — the fourth ``analysis/`` pass family.

graph-lint/memplan/dispatchplan pin the DEVICE programs; this pass pins
the HOST threads that drive them.  PRs 9–15 grew a concurrent serving
control plane (FleetRouter, ContinuousScheduler, PagePool, the
observability/resilience drivers — 15 modules use ``threading``), and
every concurrency bug so far was caught by manual review.  The control
plane is plain Python, so its locking discipline is decidable from the
AST:

* **Lock-order graph** (``concurrency.lock-order``) — every ``with
  <lock>:`` nested inside another (directly or through a resolved call)
  is an order edge; a cycle in the edge set is a potential deadlock and
  errors.  Re-acquiring a non-reentrant lock already held is the
  degenerate one-lock deadlock and errors under the same code.
* **Blocking-under-lock** (``concurrency.blocking-under-lock``) — HTTP
  probes, file IO (the ``io_retry``'d checkpoint paths included),
  ``queue.get``/``Thread.join``/``Event.wait``/``time.sleep``, and JAX
  dispatch/fence helpers made while a lock is held stall every thread
  behind that lock (the PR 15 ``_pick`` bug: a 2 s socket timeout under
  the router lock froze all completion callbacks).  Deliberate cases
  carry a ``# dstpu-lock: allow-blocking(reason)`` line annotation and
  downgrade to info.
* **Thread-role contracts** (``concurrency.thread-role``,
  ``concurrency.lock-contract``) — lightweight ``# dstpu-thread:``
  annotations on known entry points declare what the pass then checks:
  ``enqueue-only`` (a runtime-callback must not block or take locks —
  the FleetAggregator drain contract), ``owner-check=<attr>`` (a
  completion path must compare ownership before mutating — the router's
  zombie-replica rule), ``holds=<Lock>`` (a helper documented "call with
  the lock held" is analyzed under that lock — and every resolved caller
  is checked to actually hold it).
* **Guarded-attribute writes** (``concurrency.unlocked-guarded-write``)
  — in a class that owns a lock, an attribute ever written under that
  lock is a shared field; writing it elsewhere without the lock is a
  cross-thread unlocked mutation.  ``__init__`` (and functions flagged
  ``init`` — construction-time, single-threaded by contract) are exempt.

Annotation syntax (full table in docs/analysis.md "Host concurrency"):

* ``# dstpu-thread: <role> [enqueue-only] [owner-check=<attr>]
  [holds=<Class._lock>] [init]`` — on (or directly above) a ``def``.
* ``# dstpu-lock: <Class._attr>`` — on a ``with``/``acquire`` line whose
  lock the resolver cannot type (a foreign object's lock).
* ``# dstpu-lock: allow-blocking(<reason>)`` — on a blocking call line
  that is deliberate.

The pass is pure ``ast`` over source files — no import, no trace, no
accelerator; it runs in milliseconds at FleetRouter build (config
``analysis.concurrency``), from the CLI (``python -m
deepspeed_tpu.analysis --concurrency``) and as the ``concurrency-lint``
CI job.  The runtime half (``analysis/lockwatch.py``) feeds observed
order edges back through :func:`merge_observed`, so an order the AST
could not resolve still fails the cycle check when it happens.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from deepspeed_tpu.analysis import report as R

#: the serving control plane + the observability/resilience drivers it
#: leans on — every module here uses threading (or is mutated across
#: threads, like kvcache's PagePool)
CONTROL_PLANE = (
    "inference/router.py",
    "inference/scheduler.py",
    "inference/kvcache.py",
    "inference/observability.py",
    "observability/__init__.py",
    "observability/registry.py",
    "observability/fleet.py",
    "observability/flightrec.py",
    "observability/health.py",
    "observability/spool.py",
    "observability/tracing.py",
    "observability/detectors.py",
    "resilience/watchdog.py",
    "resilience/preempt.py",
    "resilience/chaos.py",
)

#: dotted call names (matched on the full name or any ``.``-suffix)
#: that block the calling thread — never legal under a control-plane
#: lock without an allow-blocking annotation
BLOCKING_CALLS = {
    "time.sleep": "sleeps",
    "urllib.request.urlopen": "makes an HTTP request (2 s socket "
                              "timeouts under a lock wedge every waiter "
                              "— the PR 15 _pick bug)",
    "socket.create_connection": "opens a socket",
    "io_retry": "runs io_retry'd IO (retries with backoff sleeps)",
    "os.remove": "does file IO",
    "os.rename": "does file IO",
    "os.replace": "does file IO",
    "os.makedirs": "does file IO",
    "shutil.rmtree": "does file IO",
    "open": "does file IO",
    "write_kv_handoff": "writes a KV handoff artifact (io_retry'd IO)",
    "read_kv_handoff": "reads a KV handoff artifact (io_retry'd IO)",
    "jax.block_until_ready": "fences device work",
    "block_until_ready": "fences device work",
    "jax.effects_barrier": "fences device work",
    "jax.device_get": "blocks on a device transfer",
    "subprocess.run": "runs a subprocess",
}

#: method names that block depending on the RECEIVER's inferred type
#: (``self.X = queue.Queue()`` / ``threading.Event()`` /
#: ``threading.Thread(...)`` assignments type the attribute)
_TYPED_BLOCKING = {
    "queue": {"get": "blocks on a queue"},
    "event": {"wait": "waits on an event"},
    "thread": {"join": "joins a thread"},
}

#: names too generic to resolve a method call by uniqueness alone
_COMMON_METHODS = frozenset({
    "get", "put", "join", "wait", "set", "clear", "close", "append",
    "appendleft", "pop", "popleft", "popitem", "items", "values", "keys",
    "acquire", "release", "start", "run", "emit", "format", "read",
    "write", "flush", "send", "recv", "info", "debug", "warning",
    "error", "exception", "submit", "add", "remove", "update", "copy",
    "healthy", "load", "record", "step", "reset", "collect", "gauges",
})

_ANN_THREAD = re.compile(r"#\s*dstpu-thread:\s*(.+?)\s*$")
_ANN_LOCK = re.compile(r"#\s*dstpu-lock:\s*(.+?)\s*$")


class ConcurrencyLintError(R.GraphLintError):
    """Raised in ``analysis.concurrency.mode == "error"`` when
    error-severity ``concurrency.*`` findings survive suppression.
    Subclasses :class:`GraphLintError` like :class:`MemoryPlanError`, so
    one renderer and one except-clause contract cover every pass
    family."""


# ===================================================================== model

@dataclasses.dataclass
class LockDef:
    name: str                    # canonical: "Class._attr" | "mod._name"
    file: str
    line: int
    reentrant: bool = False


@dataclasses.dataclass
class ThreadAnnotation:
    role: str
    enqueue_only: bool = False
    owner_check: Optional[str] = None
    holds: Tuple[str, ...] = ()
    init: bool = False


@dataclasses.dataclass
class FuncInfo:
    qual: str                    # "mod.Class.meth" | "mod.func"
    cls: Optional[str]
    file: str
    line: int
    annotation: Optional[ThreadAnnotation] = None
    # (lock, line) pairs acquired anywhere in the body
    acquires: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)
    # (held, acquired, line) direct order edges
    edges: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list)
    # (call name, why, line, held locks) direct blocking calls under lock
    blocking_under: List[Tuple[str, str, int, Tuple[str, ...]]] = \
        dataclasses.field(default_factory=list)
    # (call name, why, line) blocking calls anywhere in the body
    blocking: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list)
    # (callee qual, line, held locks at the call)
    calls: List[Tuple[str, int, Tuple[str, ...]]] = dataclasses.field(
        default_factory=list)
    # attr -> [(line, held locks)] direct self-attribute writes
    writes: Dict[str, List[Tuple[int, Tuple[str, ...]]]] = \
        dataclasses.field(default_factory=dict)
    has_owner_compare: Dict[str, bool] = dataclasses.field(
        default_factory=dict)


@dataclasses.dataclass
class ConcurrencyModel:
    """Everything the pass extracted: the lock set, the static order
    graph (with one representative site per edge), the per-function
    summaries and the declared thread roles — the docs' thread-ownership
    map and lockwatch's merge target both read from here."""
    locks: Dict[str, LockDef] = dataclasses.field(default_factory=dict)
    edges: Dict[Tuple[str, str], str] = dataclasses.field(
        default_factory=dict)            # (a, b) -> "file:line (func)"
    functions: Dict[str, FuncInfo] = dataclasses.field(
        default_factory=dict)
    roles: Dict[str, str] = dataclasses.field(default_factory=dict)

    def lock_order_edges(self) -> Set[Tuple[str, str]]:
        return set(self.edges)


# ===================================================================== parse

def _dotted(expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = _dotted(expr.value)
        return f"{base}.{expr.attr}" if base else None
    if isinstance(expr, ast.Call):
        return _dotted(expr.func)
    return None


def _is_lockish_name(name: str) -> bool:
    last = name.rsplit(".", 1)[-1].lower()
    return "lock" in last or "mutex" in last


class _ModuleSource:
    """One parsed file: tree, lines, per-line annotations."""

    def __init__(self, path: str, modname: str):
        self.path = path
        self.modname = modname
        with open(path) as f:
            self.text = f.read()
        self.tree = ast.parse(self.text, filename=path)
        self.lines = self.text.splitlines()
        self.thread_ann: Dict[int, str] = {}
        self.lock_ann: Dict[int, str] = {}
        for i, line in enumerate(self.lines, 1):
            m = _ANN_THREAD.search(line)
            if m:
                self.thread_ann[i] = m.group(1)
            m = _ANN_LOCK.search(line)
            if m:
                self.lock_ann[i] = m.group(1)
        self.consumed_thread_ann: Set[int] = set()

    def rel(self) -> str:
        return os.path.relpath(self.path, os.getcwd()) \
            if self.path.startswith(os.getcwd()) else self.path

    def annotation_for_def(self, node) -> Optional[str]:
        """The dstpu-thread annotation attached to a def: on the def
        line, or on a comment line directly above the def/decorators."""
        first = min([node.lineno]
                    + [d.lineno for d in node.decorator_list])
        for ln in (node.lineno, first - 1, first - 2):
            if ln in self.thread_ann and ln not in self.consumed_thread_ann:
                # a line above only counts if it is a pure comment
                if ln != node.lineno:
                    stripped = self.lines[ln - 1].strip() \
                        if 0 < ln <= len(self.lines) else ""
                    if not stripped.startswith("#"):
                        continue
                self.consumed_thread_ann.add(ln)
                return self.thread_ann[ln]
        return None


def _parse_thread_annotation(text: str, where: str,
                             rep: R.Report) -> ThreadAnnotation:
    toks = text.replace(",", " ").split()
    ann = ThreadAnnotation(role=toks[0] if toks else "")
    for tok in toks[1:]:
        if tok == "enqueue-only":
            ann.enqueue_only = True
        elif tok == "init":
            ann.init = True
        elif tok.startswith("owner-check="):
            ann.owner_check = tok.split("=", 1)[1]
        elif tok.startswith("holds="):
            ann.holds = tuple(tok.split("=", 1)[1].split("+"))
        else:
            rep.add("concurrency.annotation", R.WARNING,
                    f"unknown dstpu-thread clause {tok!r} (known: "
                    f"enqueue-only, init, owner-check=<attr>, "
                    f"holds=<Lock>)", source=where,
                    pass_name="concurrency")
    return ann


def _lock_ctor(value) -> Optional[Tuple[Optional[str], bool]]:
    """``(explicit name, reentrant)`` if ``value`` constructs a lock:
    ``threading.Lock()``, ``threading.RLock()``, or
    ``lockwatch.named_lock("Name", rlock=...)`` (whose string argument
    is the canonical name)."""
    if not isinstance(value, ast.Call):
        return None
    name = _dotted(value.func) or ""
    last = name.rsplit(".", 1)[-1]
    if last == "Lock":
        return (None, False)
    if last == "RLock":
        return (None, True)
    if last == "named_lock":
        explicit = None
        if value.args and isinstance(value.args[0], ast.Constant) \
                and isinstance(value.args[0].value, str):
            explicit = value.args[0].value
        rl = any(kw.arg == "rlock" and isinstance(kw.value, ast.Constant)
                 and bool(kw.value.value) for kw in value.keywords)
        return (explicit, rl)
    return None


def _attr_type(value) -> Optional[str]:
    """queue/event/thread type of an attribute from its constructor."""
    if not isinstance(value, ast.Call):
        return None
    name = _dotted(value.func) or ""
    last = name.rsplit(".", 1)[-1]
    return {"Queue": "queue", "Event": "event",
            "Thread": "thread"}.get(last)


# ================================================================= extraction

class _Extractor:
    """Walks every module twice: pass 1 collects lock definitions,
    attribute types and class/method inventories; pass 2 walks each
    function body with an explicit held-lock stack."""

    def __init__(self, sources: List[_ModuleSource], rep: R.Report):
        self.sources = sources
        self.rep = rep
        self.model = ConcurrencyModel()
        # class -> {attr -> lock canonical name}
        self.class_locks: Dict[str, Dict[str, str]] = {}
        # class -> {attr -> "queue"|"event"|"thread"|class name}
        self.class_attr_types: Dict[str, Dict[str, str]] = {}
        # lock attr name -> [canonical names] (fallback resolution)
        self.lock_attr_index: Dict[str, List[str]] = {}
        # method name -> [qual] across all analyzed classes
        self.method_index: Dict[str, List[str]] = {}
        self.known_classes: Set[str] = set()
        # module -> {func name -> qual}
        self.module_funcs: Dict[str, Dict[str, str]] = {}

    # ------------------------------------------------------------- pass 1
    def collect(self) -> None:
        for src in self.sources:
            mod = src.modname
            self.module_funcs.setdefault(mod, {})
            for node in src.tree.body:
                if isinstance(node, ast.Assign):
                    self._module_assign(src, node)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self.module_funcs[mod][node.name] = \
                        f"{mod}.{node.name}"
                elif isinstance(node, ast.ClassDef):
                    self._collect_class(src, node)
        for cls, locks in self.class_locks.items():
            for attr, canon in locks.items():
                self.lock_attr_index.setdefault(attr, []).append(canon)

    def _module_assign(self, src, node) -> None:
        ctor = _lock_ctor(node.value)
        if ctor is None:
            return
        explicit, reentrant = ctor
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                canon = explicit or f"{src.modname}.{tgt.id}"
                self.model.locks[canon] = LockDef(
                    canon, src.rel(), node.lineno, reentrant)
                self.lock_attr_index.setdefault(tgt.id, []).append(canon)

    def _collect_class(self, src, cnode) -> None:
        cls = cnode.name
        self.known_classes.add(cls)
        locks = self.class_locks.setdefault(cls, {})
        types = self.class_attr_types.setdefault(cls, {})
        for meth in cnode.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            self.method_index.setdefault(meth.name, []).append(
                f"{src.modname}.{cls}.{meth.name}")
            for sub in ast.walk(meth):
                if not isinstance(sub, ast.Assign):
                    continue
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self":
                        ctor = _lock_ctor(sub.value)
                        if ctor is not None:
                            explicit, reentrant = ctor
                            canon = explicit or f"{cls}.{tgt.attr}"
                            locks[tgt.attr] = canon
                            self.model.locks[canon] = LockDef(
                                canon, src.rel(), sub.lineno, reentrant)
                            continue
                        t = _attr_type(sub.value)
                        if t is not None:
                            types[tgt.attr] = t
                        elif isinstance(sub.value, ast.Call):
                            nm = _dotted(sub.value.func) or ""
                            last = nm.rsplit(".", 1)[-1]
                            if last in self.known_classes \
                                    or last[:1].isupper():
                                types.setdefault(tgt.attr, last)

    # ------------------------------------------------------------- pass 2
    def analyze(self) -> None:
        # known_classes must be complete before method-call resolution,
        # so class collection ran fully in collect(); a second sweep
        # catches classes referenced before their definition
        for src in self.sources:
            for node in src.tree.body:
                if isinstance(node, ast.ClassDef):
                    for meth in node.body:
                        if isinstance(meth, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            self._analyze_function(src, meth, node.name)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self._analyze_function(src, node, None)
        # dangling annotations: a dstpu-thread comment nobody consumed
        # is a contract the pass is NOT checking — say so
        for src in self.sources:
            for ln in sorted(set(src.thread_ann)
                             - src.consumed_thread_ann):
                self.rep.add(
                    "concurrency.annotation", R.WARNING,
                    f"dstpu-thread annotation not attached to any "
                    f"function def — the declared contract is not being "
                    f"checked", source=f"{src.rel()}:{ln}",
                    pass_name="concurrency")

    # ------------------------------------------------------- lock resolve
    def _resolve_lock(self, src, cls, expr, line) -> Optional[str]:
        """Canonical lock name of a with/acquire target, or None."""
        ann = src.lock_ann.get(line)
        if ann and not ann.startswith("allow-"):
            return ann.strip()
        name = _dotted(expr)
        if name is None:
            return None
        if name.startswith("self."):
            attr = name.split(".", 1)[1]
            if "." not in attr and cls is not None:
                canon = self.class_locks.get(cls, {}).get(attr)
                if canon:
                    return canon
        parts = name.rsplit(".", 1)
        attr = parts[-1]
        if len(parts) == 1:
            # module-level lock of this module
            canon = f"{src.modname}.{attr}"
            if canon in self.model.locks:
                return canon
        cands = self.lock_attr_index.get(attr, [])
        if len(set(cands)) == 1:
            return cands[0]
        if _is_lockish_name(name):
            self.rep.add(
                "concurrency.unresolved-lock", R.WARNING,
                f"cannot resolve which lock {name!r} is "
                f"({len(set(cands))} candidates) — annotate the line "
                f"with `# dstpu-lock: <Class._attr>` so the order graph "
                f"stays sound", source=f"{src.rel()}:{line}",
                pass_name="concurrency")
        return None

    def _lock_reentrant(self, canon: str) -> bool:
        d = self.model.locks.get(canon)
        return d.reentrant if d is not None else False

    # ------------------------------------------------------ call resolve
    def _resolve_call(self, src, cls, node) -> Optional[str]:
        name = _dotted(node.func)
        if name is None:
            return None
        parts = name.split(".")
        meth = parts[-1]
        if parts[0] == "self" and len(parts) == 2 and cls is not None:
            quals = [q for q in self.method_index.get(meth, ())
                     if q.split(".")[-2] == cls]
            if len(quals) == 1:
                return quals[0]
        if parts[0] == "self" and len(parts) == 3 and cls is not None:
            # self.attr.meth(): type the attr if we can
            t = self.class_attr_types.get(cls, {}).get(parts[1])
            if t in self.known_classes:
                quals = [q for q in self.method_index.get(meth, ())
                         if q.split(".")[-2] == t]
                if len(quals) == 1:
                    return quals[0]
        if len(parts) == 1:
            qual = self.module_funcs.get(src.modname, {}).get(meth)
            if qual:
                return qual
        # last resort: a method name unique across the analyzed classes
        # (and not a generic stdlib name)
        if meth not in _COMMON_METHODS:
            quals = self.method_index.get(meth, ())
            if len(quals) == 1:
                return quals[0]
        return None

    # -------------------------------------------------- blocking catalog
    def _blocking_reason(self, src, cls, node) -> Optional[Tuple[str, str]]:
        name = _dotted(node.func)
        if name is None:
            return None
        for cat, why in BLOCKING_CALLS.items():
            if name == cat or name.endswith("." + cat):
                return (name, why)
        parts = name.split(".")
        meth = parts[-1]
        if len(parts) >= 2:
            recv_attr = parts[-2]
            # typed receiver: self.X.get() with X a Queue, etc.
            if parts[0] == "self" and len(parts) == 3 and cls is not None:
                t = self.class_attr_types.get(cls, {}).get(parts[1])
                why = _TYPED_BLOCKING.get(t or "", {}).get(meth)
                if why is not None:
                    if meth == "get" and _kw_false(node, "block"):
                        return None
                    return (name, why)
            # name-based fallback: *.thread.join(), *queue.get(),
            # *.stop.wait() are unambiguous enough to flag
            if meth == "join" and recv_attr.endswith("thread"):
                return (name, _TYPED_BLOCKING["thread"]["join"])
            if meth == "get" and ("queue" in recv_attr
                                  or recv_attr == "inbox") \
                    and not _kw_false(node, "block"):
                return (name, _TYPED_BLOCKING["queue"]["get"])
            # device dispatch under a control-plane lock: any engine/
            # scheduler dispatch entry point stalls every waiter for a
            # full device program
            if recv_attr in ("engine", "eng") and meth in (
                    "prefill", "decode", "decode_many", "extend",
                    "spec_step", "admit", "export_kv", "import_kv"):
                return (name, "dispatches a device program")
            if recv_attr == "sched" and meth == "step":
                return (name, "dispatches a device program")
        return None

    # --------------------------------------------------------- the walker
    def _analyze_function(self, src, fnode, cls: Optional[str]) -> None:
        qual = (f"{src.modname}.{cls}.{fnode.name}" if cls
                else f"{src.modname}.{fnode.name}")
        info = FuncInfo(qual=qual, cls=cls, file=src.rel(),
                        line=fnode.lineno)
        ann_text = src.annotation_for_def(fnode)
        if ann_text:
            info.annotation = _parse_thread_annotation(
                ann_text, f"{src.rel()}:{fnode.lineno}", self.rep)
            self.model.roles[qual] = info.annotation.role
        held: List[str] = list(info.annotation.holds) \
            if info.annotation else []

        def loc(line) -> str:
            return f"{src.rel()}:{line} ({qual.split('.', 1)[1]})"

        def note_acquire(canon: str, line: int) -> None:
            if canon in held and not self._lock_reentrant(canon):
                self.rep.add(
                    "concurrency.lock-order", R.ERROR,
                    f"re-acquiring non-reentrant lock {canon} already "
                    f"held on this path — self-deadlock",
                    path=canon, source=loc(line),
                    pass_name="concurrency")
            for h in held:
                if h != canon:
                    info.edges.append((h, canon, line))
            info.acquires.append((canon, line))

        def visit(node, held_now: List[str]) -> None:
            if isinstance(node, ast.With):
                extra = []
                for item in node.items:
                    canon = self._resolve_lock(
                        src, cls, item.context_expr, node.lineno)
                    if canon is not None:
                        held.extend([])  # no-op; clarity
                        for h in held_now + extra:
                            if h != canon:
                                info.edges.append(
                                    (h, canon, node.lineno))
                        if canon in held_now + extra \
                                and not self._lock_reentrant(canon):
                            self.rep.add(
                                "concurrency.lock-order", R.ERROR,
                                f"re-acquiring non-reentrant lock "
                                f"{canon} already held on this path — "
                                f"self-deadlock", path=canon,
                                source=loc(node.lineno),
                                pass_name="concurrency")
                        info.acquires.append((canon, node.lineno))
                        extra.append(canon)
                    else:
                        visit(item.context_expr, held_now)
                inner = held_now + extra
                for child in node.body:
                    visit(child, inner)
                return
            if isinstance(node, ast.Call):
                name = _dotted(node.func) or ""
                if name.endswith(".acquire"):
                    canon = self._resolve_lock(
                        src, cls, node.func.value, node.lineno)
                    if canon is not None:
                        note_acquire(canon, node.lineno)
                blk = self._blocking_reason(src, cls, node)
                if blk is not None:
                    cname, why = blk
                    info.blocking.append((cname, why, node.lineno))
                    if held_now:
                        info.blocking_under.append(
                            (cname, why, node.lineno, tuple(held_now)))
                callee = self._resolve_call(src, cls, node)
                if callee is not None:
                    info.calls.append(
                        (callee, node.lineno, tuple(held_now)))
                for child in ast.iter_child_nodes(node):
                    visit(child, held_now)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    attr = _self_attr_of_target(tgt)
                    if attr is not None:
                        info.writes.setdefault(attr, []).append(
                            (node.lineno, tuple(held_now)))
                visit(node.value, held_now)
                return
            if isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    if isinstance(side, ast.Attribute):
                        info.has_owner_compare[side.attr] = True
                for child in ast.iter_child_nodes(node):
                    visit(child, held_now)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # nested defs/lambdas run where they are CALLED; the
                # common pattern here is an inline helper invoked under
                # the same locks, so analyze under the current stack
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                for child in body:
                    visit(child, held_now)
                return
            for child in ast.iter_child_nodes(node):
                visit(child, held_now)

        for stmt in fnode.body:
            visit(stmt, held)
        self.model.functions[qual] = info


def _kw_false(node: ast.Call, kwname: str) -> bool:
    for kw in node.keywords:
        if kw.arg == kwname and isinstance(kw.value, ast.Constant) \
                and kw.value.value is False:
            return True
    return False


def _self_attr_of_target(tgt) -> Optional[str]:
    """``self.X = ...`` / ``self.X[i] = ...`` / ``self.X += ...`` →
    ``X``."""
    if isinstance(tgt, ast.Subscript):
        tgt = tgt.value
    if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
            and tgt.value.id == "self":
        return tgt.attr
    if isinstance(tgt, ast.Tuple):
        for el in tgt.elts:
            a = _self_attr_of_target(el)
            if a is not None:
                return a
    return None


# ================================================================== analysis

def _propagate(model: ConcurrencyModel):
    """Transitive (acquires, blocking) summaries per function, memoized
    and cycle-safe — so a call made under a lock inherits everything its
    callee does."""
    acq_memo: Dict[str, Set[str]] = {}
    blk_memo: Dict[str, List[Tuple[str, str, str]]] = {}

    def acquires(qual: str, seen: Set[str]) -> Set[str]:
        if qual in acq_memo:
            return acq_memo[qual]
        if qual in seen:
            return set()
        seen = seen | {qual}
        info = model.functions.get(qual)
        if info is None:
            return set()
        out = {lock for lock, _ in info.acquires}
        for callee, _, _ in info.calls:
            out |= acquires(callee, seen)
        acq_memo[qual] = out
        return out

    def blocking(qual: str, seen: Set[str]) \
            -> List[Tuple[str, str, str]]:
        """[(call name, why, "file:line")] anywhere under ``qual``."""
        if qual in blk_memo:
            return blk_memo[qual]
        if qual in seen:
            return []
        seen = seen | {qual}
        info = model.functions.get(qual)
        if info is None:
            return []
        out = [(n, w, f"{info.file}:{ln}")
               for n, w, ln in info.blocking]
        for callee, _, _ in info.calls:
            for n, w, site in blocking(callee, seen):
                out.append((n, w, site))
        blk_memo[qual] = out[:8]      # summaries, not transcripts
        return blk_memo[qual]

    return acquires, blocking


def _find_cycles(edges: Dict[Tuple[str, str], str]) \
        -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles, state = [], {}

    def dfs(n, path):
        state[n] = 1
        path.append(n)
        for m in sorted(graph.get(n, ())):
            if state.get(m, 0) == 1:
                cycles.append(path[path.index(m):] + [m])
            elif state.get(m, 0) == 0:
                dfs(m, path)
        path.pop()
        state[n] = 2

    for n in sorted(graph):
        if state.get(n, 0) == 0:
            dfs(n, [])
    # dedupe rotations
    seen, out = set(), []
    for cyc in cycles:
        key = frozenset(cyc)
        if key not in seen:
            seen.add(key)
            out.append(cyc)
    return out


def analyze_paths(paths: Sequence[str]) \
        -> Tuple[ConcurrencyModel, R.Report]:
    """Run the full pass over ``paths``; returns the model (lock set,
    order graph, roles) and the findings report."""
    rep = R.Report(subject="concurrency")
    sources = []
    for p in paths:
        modname = os.path.basename(p)[:-3] \
            if p.endswith(".py") else os.path.basename(p)
        if modname == "__init__":
            modname = os.path.basename(os.path.dirname(p))
        try:
            sources.append(_ModuleSource(p, modname))
        except (OSError, SyntaxError) as e:
            rep.add("concurrency.parse", R.ERROR,
                    f"cannot analyze {p}: {e}", source=p,
                    pass_name="concurrency")
    ex = _Extractor(sources, rep)
    ex.collect()
    ex.analyze()
    model = ex.model
    acquires, blocking = _propagate(model)

    src_by_rel = {s.rel(): s for s in sources}

    def allowed(file: str, line: int) -> bool:
        s = src_by_rel.get(file)
        ann = s.lock_ann.get(line) if s is not None else None
        return bool(ann and ann.startswith("allow-blocking"))

    # ---- blocking under lock (direct + through resolved calls)
    for qual, info in model.functions.items():
        for cname, why, line, locks in info.blocking_under:
            sev = R.INFO if allowed(info.file, line) else R.ERROR
            code = ("concurrency.allowed-blocking" if sev == R.INFO
                    else "concurrency.blocking-under-lock")
            rep.add(code, sev,
                    f"{cname}() {why} while holding "
                    f"{' + '.join(locks)} — every thread waiting on "
                    f"the lock stalls behind it",
                    path=" + ".join(locks),
                    source=f"{info.file}:{line} "
                           f"({qual.split('.', 1)[1]})",
                    pass_name="concurrency")
        for callee, line, locks in info.calls:
            if not locks:
                continue
            for cname, why, site in blocking(callee, set()):
                if allowed(info.file, line) or allowed(
                        *_split_site(site)):
                    continue
                rep.add(
                    "concurrency.blocking-under-lock", R.ERROR,
                    f"call to {callee.split('.', 1)[1]}() while "
                    f"holding {' + '.join(locks)} — it {why} via "
                    f"{cname}() at {site}",
                    path=" + ".join(locks),
                    source=f"{info.file}:{line} "
                           f"({qual.split('.', 1)[1]})",
                    pass_name="concurrency")

    # ---- order edges (direct + through resolved calls) + cycles
    for qual, info in model.functions.items():
        site = f"{info.file} ({qual.split('.', 1)[1]})"
        for a, b, line in info.edges:
            model.edges.setdefault((a, b), f"{info.file}:{line} "
                                           f"({qual.split('.', 1)[1]})")
        for callee, line, locks in info.calls:
            for acquired in acquires(callee, set()):
                for h in locks:
                    if h != acquired:
                        model.edges.setdefault(
                            (h, acquired),
                            f"{info.file}:{line} "
                            f"({qual.split('.', 1)[1]} -> "
                            f"{callee.split('.', 1)[1]})")
                    elif not model.locks.get(acquired, LockDef(
                            acquired, "", 0)).reentrant:
                        rep.add(
                            "concurrency.lock-order", R.ERROR,
                            f"call to {callee.split('.', 1)[1]}() "
                            f"re-acquires non-reentrant {acquired} "
                            f"already held — self-deadlock",
                            path=acquired,
                            source=f"{info.file}:{line} "
                                   f"({qual.split('.', 1)[1]})",
                            pass_name="concurrency")
    for cyc in _find_cycles(model.edges):
        sites = []
        for a, b in zip(cyc, cyc[1:]):
            sites.append(f"{a} -> {b} at "
                         f"{model.edges.get((a, b), '?')}")
        rep.add("concurrency.lock-order", R.ERROR,
                f"lock-order cycle {' -> '.join(cyc)} — two threads "
                f"taking the ends in opposite order deadlock:\n          "
                + "\n          ".join(sites),
                path=" -> ".join(cyc), pass_name="concurrency")

    # ---- contracts: holds= callers, enqueue-only, owner-check
    for qual, info in model.functions.items():
        for callee, line, locks in info.calls:
            cinfo = model.functions.get(callee)
            if cinfo is None or cinfo.annotation is None:
                continue
            for need in cinfo.annotation.holds:
                if need not in locks:
                    rep.add(
                        "concurrency.lock-contract", R.ERROR,
                        f"{callee.split('.', 1)[1]}() declares "
                        f"holds={need} but this call site does not "
                        f"hold it (held: "
                        f"{' + '.join(locks) or 'nothing'})",
                        path=need,
                        source=f"{info.file}:{line} "
                               f"({qual.split('.', 1)[1]})",
                        pass_name="concurrency")
        ann = info.annotation
        if ann is None:
            continue
        where = f"{info.file}:{info.line} ({qual.split('.', 1)[1]})"
        if ann.enqueue_only:
            for cname, why, line in info.blocking:
                rep.add("concurrency.thread-role", R.ERROR,
                        f"declared enqueue-only ({ann.role}) but "
                        f"{cname}() {why}",
                        source=f"{info.file}:{line} "
                               f"({qual.split('.', 1)[1]})",
                        pass_name="concurrency")
            for lock, line in info.acquires:
                rep.add("concurrency.thread-role", R.ERROR,
                        f"declared enqueue-only ({ann.role}) but "
                        f"acquires {lock} — a callback thread stuck "
                        f"on a lock stalls the runtime",
                        path=lock,
                        source=f"{info.file}:{line} "
                               f"({qual.split('.', 1)[1]})",
                        pass_name="concurrency")
            for callee, line, _ in info.calls:
                deep = blocking(callee, set())
                if deep:
                    cname, why, site = deep[0]
                    rep.add("concurrency.thread-role", R.ERROR,
                            f"declared enqueue-only ({ann.role}) but "
                            f"calls {callee.split('.', 1)[1]}() which "
                            f"{why} via {cname}() at {site}",
                            source=f"{info.file}:{line} "
                                   f"({qual.split('.', 1)[1]})",
                            pass_name="concurrency")
        if ann.owner_check and not info.has_owner_compare.get(
                ann.owner_check):
            rep.add("concurrency.thread-role", R.ERROR,
                    f"declared owner-check={ann.owner_check} but never "
                    f"compares .{ann.owner_check} — a completion from "
                    f"an evicted owner would be accepted",
                    source=where, pass_name="concurrency")

    # ---- guarded-attribute writes
    _check_guarded_writes(model, rep)
    return model, rep


def _split_site(site: str) -> Tuple[str, int]:
    file, _, line = site.rpartition(":")
    try:
        return file, int(line)
    except ValueError:
        return site, 0


def _check_guarded_writes(model: ConcurrencyModel,
                          rep: R.Report) -> None:
    # class -> lock canonical names owned by it
    class_locks: Dict[str, Set[str]] = {}
    for canon in model.locks:
        cls = canon.split(".", 1)[0]
        class_locks.setdefault(cls, set()).add(canon)
    # guarded attrs per class: written at least once under a class lock
    guarded: Dict[str, Set[str]] = {}
    for qual, info in model.functions.items():
        if info.cls is None:
            continue
        own = class_locks.get(info.cls, set())
        if not own:
            continue
        for attr, writes in info.writes.items():
            for _, locks in writes:
                if own & set(locks):
                    guarded.setdefault(info.cls, set()).add(attr)
    lock_attrs = {canon.split(".", 1)[1] for canon in model.locks
                  if "." in canon}
    for qual, info in model.functions.items():
        if info.cls is None or info.cls not in guarded:
            continue
        meth = qual.rsplit(".", 1)[-1]
        if meth == "__init__":
            continue
        if info.annotation is not None and info.annotation.init:
            continue
        own = class_locks.get(info.cls, set())
        for attr, writes in info.writes.items():
            if attr not in guarded[info.cls] or attr in lock_attrs:
                continue
            for line, locks in writes:
                if own & set(locks):
                    continue
                rep.add(
                    "concurrency.unlocked-guarded-write", R.ERROR,
                    f"self.{attr} is written under "
                    f"{'/'.join(sorted(own))} elsewhere in "
                    f"{info.cls} but written here with no lock held — "
                    f"a cross-thread unlocked mutation",
                    path=f"{info.cls}.{attr}",
                    source=f"{info.file}:{line} "
                           f"({qual.split('.', 1)[1]})",
                    pass_name="concurrency")


# ================================================================ entrypoints

def control_plane_paths() -> List[str]:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return [os.path.join(root, p) for p in CONTROL_PLANE]


def check_paths(paths: Optional[Sequence[str]] = None,
                suppress: Sequence[str] = ()) -> R.Report:
    """The pass over ``paths`` (default: the shipped control plane),
    suppression applied."""
    _, rep = analyze_paths(paths or control_plane_paths())
    return rep.filtered(suppress)


_gate_memo: Dict[tuple, bool] = {}


def check_control_plane(mode: str = "warn",
                        suppress: Sequence[str] = (),
                        where: str = "control plane") -> None:
    """The build-time gate (FleetRouter rides it via config
    ``analysis.concurrency``): run once per process per (mode,
    suppress) — the source files do not change under a running process,
    so re-linting per router build would be pure overhead."""
    if mode == "off":
        return
    key = (mode, tuple(suppress))
    if key in _gate_memo:
        return
    from deepspeed_tpu import analysis
    rep = check_paths(suppress=suppress)
    analysis.dispatch_report(
        rep, mode, where=where, label="concurrency lint",
        info_hint="analysis.concurrency.check_paths().format() shows "
                  "them", error_cls=ConcurrencyLintError)
    _gate_memo[key] = True


def merge_observed(model: ConcurrencyModel,
                   observed: Set[Tuple[str, str]]) -> R.Report:
    """Merge lockwatch's observed order edges into the static graph and
    re-run the cycle check: an inversion the AST could not see (an
    unresolved foreign lock, an order through unanalyzed code) still
    fails once it actually happens.  Clean runtime edges are also the
    consistency proof the CI legs assert: observed ⊆ acyclic(static ∪
    observed)."""
    rep = R.Report(subject="concurrency+observed")
    edges = dict(model.edges)
    for a, b in observed:
        edges.setdefault((a, b), "observed at runtime (lockwatch)")
    for cyc in _find_cycles(edges):
        sites = [f"{a} -> {b} at {edges.get((a, b), '?')}"
                 for a, b in zip(cyc, cyc[1:])]
        rep.add("concurrency.lock-order", R.ERROR,
                f"lock-order cycle {' -> '.join(cyc)} (static + "
                f"observed edges) — two threads taking the ends in "
                f"opposite order deadlock:\n          "
                + "\n          ".join(sites),
                path=" -> ".join(cyc), pass_name="lockwatch-merge")
    return rep
