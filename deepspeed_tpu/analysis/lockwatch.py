"""Runtime lock sanitizer for the host-side serving control plane.

The static pass (``analysis/concurrency.py``) proves properties of the
lock-acquisition ORDER it can see in the source; this module watches the
orders that actually happen.  Control-plane classes create their locks
through :func:`named_lock` — a plain ``threading.Lock``/``RLock`` when
the watcher is disarmed (the default: zero overhead, zero behavior
change), an :class:`InstrumentedLock` when armed.  Armed locks record,
per acquisition:

* the **order edge** from every lock the acquiring thread already holds
  to the new lock — the observed lock-order graph, merged into the
  static graph by ``concurrency.merge_observed`` so a runtime-only
  inversion (an order the AST pass could not resolve) still fails the
  cycle check;
* **wait and held durations** — exported as ``lockwatch/…`` counters
  through the PR 7 :class:`~deepspeed_tpu.observability.registry.
  MetricRegistry` (``register_metrics``) so ``/metrics`` answers "which
  lock is hot";
* **flight-recorder breadcrumbs** on long waits and long holds
  (``lock_wait`` / ``lock_held`` rows naming the lock, the waiter and
  the holder thread) — a watchdog hang dump names the contended lock,
  not just the stuck frame.

Arming: call :func:`instrument` before the locks are CREATED, or set
``DSTPU_LOCKWATCH=1`` in the environment (the chaos and fleet CI legs
do).  Arming is a creation-time decision — locks built while disarmed
stay plain.

Everything here is stdlib-only and import-cycle-free: the flight
recorder is imported lazily on the first over-threshold event, and the
module never imports jax — it is safe from any module in the tree.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Set, Tuple

ENV_ARMED = "DSTPU_LOCKWATCH"

#: breadcrumb thresholds (ms): a wait/hold longer than this leaves a
#: flight-recorder row.  Deliberately above anything a healthy control
#: plane does (its critical sections are bookkeeping-only — the
#: blocking-under-lock lint is what keeps them that way).
DEFAULT_WAIT_WARN_MS = 50.0
DEFAULT_HOLD_WARN_MS = 100.0

_armed = False
_wait_warn_ms = DEFAULT_WAIT_WARN_MS
_hold_warn_ms = DEFAULT_HOLD_WARN_MS

#: module-global observation state, guarded by a PLAIN lock (the watcher
#: cannot watch itself)
_state_lock = threading.Lock()
_stats: Dict[str, "_LockStats"] = {}
_edges: Dict[Tuple[str, str], int] = {}
_tls = threading.local()


class _LockStats:
    __slots__ = ("acquisitions", "contentions", "wait_ms", "held_ms",
                 "max_wait_ms", "max_held_ms")

    def __init__(self):
        self.acquisitions = 0
        self.contentions = 0
        self.wait_ms = 0.0
        self.held_ms = 0.0
        self.max_wait_ms = 0.0
        self.max_held_ms = 0.0


def instrument(enable: bool = True) -> None:
    """Arm (or disarm) the watcher for locks created FROM NOW ON."""
    global _armed
    _armed = bool(enable)


def armed() -> bool:
    return _armed or os.environ.get(ENV_ARMED, "") not in ("", "0")


def configure(wait_warn_ms: Optional[float] = None,
              hold_warn_ms: Optional[float] = None) -> None:
    """Adjust the breadcrumb thresholds (tests lower them to force
    rows without real contention)."""
    global _wait_warn_ms, _hold_warn_ms
    if wait_warn_ms is not None:
        _wait_warn_ms = float(wait_warn_ms)
    if hold_warn_ms is not None:
        _hold_warn_ms = float(hold_warn_ms)


def reset() -> None:
    """Drop every recorded edge and counter (test isolation).  Locks
    already created stay instrumented and keep recording."""
    with _state_lock:
        _stats.clear()
        _edges.clear()


def named_lock(name: str, rlock: bool = False):
    """The control-plane lock factory: a plain ``threading.Lock`` /
    ``RLock`` when disarmed, an :class:`InstrumentedLock` when armed.
    ``name`` is the lock's identity in the order graph and the counters
    — by convention ``ClassName._attr``, matching the name the static
    pass derives, so observed and static edges merge by equality."""
    if not armed():
        return threading.RLock() if rlock else threading.Lock()
    return InstrumentedLock(name, rlock=rlock)


def _held_stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _breadcrumb(kind: str, **fields) -> None:
    try:
        from deepspeed_tpu.observability.flightrec import RECORDER
        RECORDER.record(kind, **fields)
    except Exception:  # pragma: no cover - diagnostics must not throw
        pass


class InstrumentedLock:
    """A wrapped ``threading.Lock``/``RLock`` recording acquisition
    order, wait time and held duration.  Context-manager and
    ``acquire``/``release`` compatible; reentrant acquisitions of an
    RLock count once (no self-edges, no double timing)."""

    __slots__ = ("name", "_inner", "_rlock", "_holder", "_owner_ident",
                 "_depth", "_t_acquired")

    def __init__(self, name: str, rlock: bool = False):
        self.name = str(name)
        self._rlock = bool(rlock)
        self._inner = threading.RLock() if rlock else threading.Lock()
        self._holder = None          # holder thread NAME (diagnostics)
        self._owner_ident = None     # holder thread ident (reentrancy)
        self._depth = 0
        self._t_acquired = 0.0
        with _state_lock:
            _stats.setdefault(self.name, _LockStats())

    # ------------------------------------------------------------ acquire
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.current_thread()
        if self._rlock and self._owner_ident == me.ident:
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._depth += 1
            return got
        holder_before = self._holder    # best-effort: who we waited on
        contended = self._owner_ident is not None
        t0 = time.monotonic()
        got = (self._inner.acquire(blocking, timeout) if timeout != -1
               or not blocking else self._inner.acquire())
        wait_ms = (time.monotonic() - t0) * 1e3
        if not got:
            return False
        self._holder = me.name
        self._owner_ident = me.ident
        self._depth = 1
        self._t_acquired = time.monotonic()
        stack = _held_stack()
        with _state_lock:
            st = _stats.setdefault(self.name, _LockStats())
            st.acquisitions += 1
            st.wait_ms += wait_ms
            st.max_wait_ms = max(st.max_wait_ms, wait_ms)
            if contended:
                st.contentions += 1
            for held in stack:
                edge = (held.name, self.name)
                _edges[edge] = _edges.get(edge, 0) + 1
        stack.append(self)
        if wait_ms >= _wait_warn_ms:
            _breadcrumb("lock_wait", lock=self.name, waiter=me.name,
                        holder=holder_before, wait_ms=round(wait_ms, 3))
        return True

    def release(self) -> None:
        me = threading.current_thread()
        if self._rlock and self._owner_ident == me.ident \
                and self._depth > 1:
            self._depth -= 1
            self._inner.release()
            return
        held_ms = (time.monotonic() - self._t_acquired) * 1e3
        holder = self._holder
        self._holder = None
        self._owner_ident = None
        self._depth = 0
        stack = getattr(_tls, "stack", None)
        if stack and self in stack:
            stack.remove(self)
        with _state_lock:
            st = _stats.setdefault(self.name, _LockStats())
            st.held_ms += held_ms
            st.max_held_ms = max(st.max_held_ms, held_ms)
        self._inner.release()
        if held_ms >= _hold_warn_ms:
            _breadcrumb("lock_held", lock=self.name, holder=holder,
                        held_ms=round(held_ms, 3))

    def locked(self) -> bool:
        return self._owner_ident is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):  # pragma: no cover - debugging nicety
        return (f"<InstrumentedLock {self.name!r} "
                f"holder={self._holder!r}>")


# ------------------------------------------------------------- exports

def observed_edges() -> Set[Tuple[str, str]]:
    """Distinct (held → acquired) lock-name pairs observed so far."""
    with _state_lock:
        return set(_edges)


def snapshot() -> Dict[str, dict]:
    """Per-lock stats: ``{name: {acquisitions, contentions, wait_ms,
    held_ms, max_wait_ms, max_held_ms}}``."""
    with _state_lock:
        return {name: {
            "acquisitions": st.acquisitions,
            "contentions": st.contentions,
            "wait_ms": round(st.wait_ms, 3),
            "held_ms": round(st.held_ms, 3),
            "max_wait_ms": round(st.max_wait_ms, 3),
            "max_held_ms": round(st.max_held_ms, 3),
        } for name, st in _stats.items()}


def counters() -> Dict[str, float]:
    """Flat ``{metric: number}`` dict — the MetricRegistry source shape.
    Lock names keep their dots (``lock_wait_ms.FleetRouter._lock``); the
    registry namespaces the group."""
    out: Dict[str, float] = {}
    for name, st in snapshot().items():
        out[f"lock_wait_ms.{name}"] = st["wait_ms"]
        out[f"lock_held_ms.{name}"] = st["held_ms"]
        out[f"lock_acquisitions.{name}"] = st["acquisitions"]
        out[f"lock_contentions.{name}"] = st["contentions"]
    return out


def register_metrics(registry) -> None:
    """Export the counters through a PR 7 MetricRegistry: they appear as
    ``lockwatch/lock_wait_ms.<name>`` in every snapshot."""
    registry.register("lockwatch", counters)
