"""Prefix KV reuse + speculative decoding (PR 13, docs/inference.md
"Prefix reuse" / "Speculative decoding").

The load-bearing pins:

* **Page-table bookkeeping** — refcount on evict, published pages
  surviving on the LRU, copy-on-write when a ring wrap would overwrite a
  SHARED page, page-aligned prompts, sub-page prefixes (no reuse), and
  capacity-exhausted admission refusal (queued, never half-allocated).
* **Bitwise page identity** — a reused page is byte-identical to the
  page a fresh prefill of the same prefix produces (same weights + same
  tokens ⇒ same bytes), and the decode-exactness oracle stays pinned at
  mp=1 AND mp=2 with prefix reuse ON.
* **Greedy-output identity** — prefix reuse and speculative decoding are
  FLOP optimizations, never generation changes: token streams equal the
  no-reuse / target-only baselines, mixed hit/miss batches included.
* **Exactly-N executables** — the new program set (tail bucket, draft
  prefill, fused spec step) still matches the static prediction against
  the runtime compile-cache and fence counters (the PR 11 contract).
"""

import numpy as np
import pytest

import jax

from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.inference import (ContinuousScheduler, InferenceEngine,
                                     PagePool, Request, kvcache, run_serve)
from deepspeed_tpu.models.gpt2 import GPT2

TINY = dict(vocab_size=128, max_seq_len=64, num_layers=2, hidden_size=64,
            num_heads=4)
DRAFT = dict(vocab_size=128, max_seq_len=64, num_layers=1, hidden_size=32,
             num_heads=2)


def tiny_model():
    return GPT2.from_size("tiny", **TINY)


def serve_config(**inf):
    base = {"max_slots": 3, "max_tokens": 32, "prefill_bucket": 24,
            "page_tokens": 8, "dtype": "float32"}
    base.update(inf)
    return {"train_micro_batch_size_per_gpu": 1, "inference": base,
            "graph_lint": "error",
            "analysis": {"mode": "error", "profile": "v4-8"}}


def spec_of(slots=3, capacity=32, pt=8, pool_pages=0, layout="paged"):
    return kvcache.KVCacheSpec(layers=2, slots=slots, capacity=capacity,
                               kv_heads_local=4, head_dim=16,
                               dtype=np.float32, layout=layout,
                               page_tokens=pt, pool_pages=pool_pages)


# =====================================================================
# PagePool bookkeeping (pure host, no programs)
# =====================================================================

def test_pool_refcount_on_evict():
    pool = PagePool(spec_of())
    prompt = list(range(17))                       # 2 full pages + tail
    g0 = pool.admit(0, prompt, 4)
    pool.publish(g0)
    shared = pool.slot_pages(0)[:2]
    g1 = pool.admit(1, prompt, 4)                  # hits both full pages
    assert g1.reused_pages == 2 and g1.reused_tokens == 16
    assert [pool.refcount(p) for p in shared] == [2, 2]
    pool.release(0)                                # evict the publisher
    assert [pool.refcount(p) for p in shared] == [1, 1]
    pool.release(1)
    # published pages at refcount 0 park on the LRU, still hittable
    assert [pool.refcount(p) for p in shared] == [0, 0]
    g2 = pool.admit(2, prompt, 4)
    assert g2.reused_pages == 2                    # revived from the LRU
    assert pool.slot_pages(2)[:2] == shared
    assert [pool.refcount(p) for p in shared] == [1, 1]


def test_pool_sub_page_prefix_never_hits():
    pool = PagePool(spec_of())
    g0 = pool.admit(0, list(range(17)), 4)
    pool.publish(g0)
    # same leading tokens, but shorter than one page — no reuse
    g1 = pool.admit(1, list(range(7)), 4)
    assert g1.reused_pages == 0 and g1.reused_tokens == 0
    # exactly one page long: the last token must still be forwarded, so
    # a single-page prompt cannot reuse its only page
    pool.release(1)
    g2 = pool.admit(1, list(range(8)), 4)
    assert g2.reused_pages == 0


def test_pool_page_aligned_prompt_reuses_all_but_last_page():
    pool = PagePool(spec_of())
    prompt = list(range(24))                       # exactly 3 pages
    g0 = pool.admit(0, prompt, 4)
    pool.publish(g0)                               # publishes all 3
    g1 = pool.admit(1, prompt, 4)
    # >= 1 token must be forwarded for the first generated token's
    # logits, so the aligned prompt reuses pages 0..1, re-prefills page 2
    assert g1.reused_pages == 2 and g1.reused_tokens == 16


def test_pool_chained_hash_stops_at_first_divergence():
    pool = PagePool(spec_of())
    g0 = pool.admit(0, list(range(24)), 4)
    pool.publish(g0)
    diverged = list(range(8)) + [99] * 8 + list(range(16, 24))
    g1 = pool.admit(1, diverged, 4)
    assert g1.reused_pages == 1                    # page 0 only: the
    # chain breaks at page 1 and page 2 CANNOT hit without it


def test_pool_admission_refusal_and_lru_reclaim():
    # pool of 6 pages, slots need ceil((prompt+budget)/8) pages each
    pool = PagePool(spec_of(slots=3, pool_pages=6))
    assert pool.admit(0, list(range(20)), 12) is not None   # 4 pages
    g1 = pool.admit(1, list(range(30, 40)), 6)              # 2 pages
    assert g1 is not None
    assert pool.admit(2, list(range(50, 60)), 6) is None    # exhausted
    assert pool.refusals == 1
    assert pool.slot_pages(2) == []                # nothing half-allocated
    pool.publish(g1)
    pool.release(1)                                # 2 pages → LRU
    # the allocator reclaims LRU pages (un-publishing them) when free
    # pages run out
    assert pool.admit(2, list(range(50, 60)), 6) is not None
    assert pool.free_pages == 0


def test_pool_pricing_is_pool_based():
    spec = spec_of(slots=4, capacity=100, pt=64)   # rounds to 2 pages
    assert spec.pages_per_slot == 2
    assert spec.num_pages == 8
    assert spec.pool_rows == 8 * 64
    per_tok = 4 * 16 * 4                           # heads * dim * fp32
    assert kvcache.cache_bytes(spec) == 2 * 2 * 8 * 64 * per_tok
    # overcommitted pool prices FEWER bytes than slots × capacity
    over = spec_of(slots=4, capacity=100, pt=64, pool_pages=5)
    assert kvcache.cache_bytes(over) < kvcache.cache_bytes(spec)
    with pytest.raises(ValueError, match="pool_pages"):
        spec_of(slots=4, capacity=100, pt=64, pool_pages=1)


# =====================================================================
# engine: bitwise page identity + the oracle with reuse ON
# =====================================================================

def _pool_rows(eng, slot, n_rows):
    """Host copy of the slot's first n_rows K rows: [L, n_rows, n, d]."""
    rows = eng.pool.slot_rows(slot)[:n_rows]
    k = np.asarray(eng._cache["k"])
    return k[:, rows]


def test_reused_pages_bitwise_equal_and_outputs_identical():
    m = tiny_model()
    eng = InferenceEngine(m, config=serve_config(), seed=0)
    assert eng.prefix_reuse and eng.tail_bucket == 8
    prefix = list(range(1, 17))                    # 2 full pages
    sched = ContinuousScheduler(eng)
    res = sched.run([Request(rid=i, prompt=prefix + [30 + i],
                             max_new_tokens=4) for i in range(3)])
    assert sched.prefix_hits == 2
    assert sched.prefix_tokens_reused == 32
    # a new admission's leading pages ARE the published ones (shared,
    # not copied)
    _, reused = eng.admit(0, prefix + [77], 2)
    assert reused == 16 and eng.pool.shared_pages(0) == 2
    shared_rows = _pool_rows(eng, 0, 16)

    # a FRESH engine prefilling the same prefix produces byte-identical
    # page content (same weights + same tokens ⇒ same bytes)
    eng2 = InferenceEngine(m, config=serve_config(prefix_reuse=False),
                           seed=0)
    eng2.prefill(0, prefix + [77])
    fresh_rows = _pool_rows(eng2, 0, 16)
    np.testing.assert_array_equal(shared_rows, fresh_rows)

    # and the token streams equal the no-reuse baseline exactly
    base = ContinuousScheduler(eng2)
    res2 = base.run([Request(rid=i, prompt=prefix + [30 + i],
                             max_new_tokens=4) for i in range(3)])
    assert ({r.rid: r.tokens for r in res}
            == {r.rid: r.tokens for r in res2})


@pytest.mark.parametrize("mp", [1, 2])
def test_decode_oracle_with_prefix_reuse(mp):
    """The decode-exactness oracle with reuse ON: a slot admitted over
    SHARED prefix pages decodes argmax-identically to a full-context
    re-forward, at mp=1 and mp=2."""
    cfg = serve_config()
    if mp > 1:
        cfg["model_parallel_size"] = mp
    eng = InferenceEngine(tiny_model(), config=cfg, seed=0)
    prefix = list(range(1, 17))
    # slot 0 publishes the prefix; slot 1 is admitted over the shared
    # pages (reuse ON) and then decodes incrementally
    assert eng.admit(0, prefix + [50], 2) is not None
    logits, reused = eng.admit(1, prefix + [60], 8)
    assert reused == 16
    seq = prefix + [60]
    cur = int(np.argmax(logits))
    for _ in range(4):
        seq.append(cur)
        ref = eng.prefill(2, seq)           # full re-forward, other slot
        feed = np.zeros(eng.num_slots, np.int32)
        feed[1] = cur
        act = np.zeros(eng.num_slots, bool)
        act[1] = True
        dec = eng.decode(feed, act)[1]
        assert int(np.argmax(dec)) == int(np.argmax(ref))
        np.testing.assert_allclose(dec, ref, atol=1e-4)
        cur = int(np.argmax(dec))


def test_mixed_hit_miss_batching_invariance():
    """Hitting and missing requests sharing decode iterations generate
    exactly what they generate solo — reuse must stay invisible."""
    eng = InferenceEngine(tiny_model(), config=serve_config(), seed=0)
    prefix = list(range(1, 17))
    eng.prefill(0, prefix)                  # publish the prefix
    eng.reset()                             # …but reset clears the index
    prompts = [prefix + [40], [9, 8, 7], prefix + [41], [5, 5]]
    eng.prefill(0, prefix + [99])           # re-publish on the live pool
    eng.release(0)
    together = eng.generate(prompts, max_new_tokens=5)
    solo = []
    for p in prompts:
        eng.reset()
        eng.prefill(0, prefix + [99])       # same index state per run
        eng.release(0)
        solo.append(eng.generate([p], max_new_tokens=5)[0])
    assert together == solo


def test_reset_clears_the_prefix_index():
    eng = InferenceEngine(tiny_model(), config=serve_config(), seed=0)
    prefix = list(range(1, 17))
    eng.prefill(0, prefix + [50])
    eng.reset()
    sched = ContinuousScheduler(eng)
    sched.run([Request(rid=0, prompt=prefix + [51], max_new_tokens=2)])
    assert sched.prefix_hits == 0           # nothing survives reset


# =====================================================================
# ring layout: copy-on-write on wrap of a shared page
# =====================================================================

def test_cow_on_ring_wrap_of_shared_page():
    """Two CONCURRENT ring slots share a prefix page; one wraps past
    capacity and would overwrite it — the engine copies the page out
    first (refcount > 1 ⇒ COW) and the neighbour's stream is
    untouched."""
    cfg = serve_config(max_slots=2, max_tokens=16, prefill_bucket=16,
                       kv_layout="ring")
    m = tiny_model()
    eng = InferenceEngine(m, config=cfg, seed=0)
    assert eng._copy_page_fn is not None
    prefix = list(range(1, 13))             # page 0 full, page 1 partial
    wrapper = Request(rid=0, prompt=prefix + [50], max_new_tokens=10)
    neighbour = Request(rid=1, prompt=prefix + [60], max_new_tokens=10)
    sched = ContinuousScheduler(eng)
    res = sched.run([wrapper, neighbour])
    assert sched.prefix_hits == 1           # they shared page 0
    assert eng.pool.cow_copies >= 1         # the wrap copied it out
    # both streams equal their no-reuse solo runs
    for req in (wrapper, neighbour):
        solo = InferenceEngine(m, config=dict(
            cfg, inference=dict(cfg["inference"], prefix_reuse=False)),
            seed=0)
        ref = ContinuousScheduler(solo).run(
            [Request(rid=req.rid, prompt=list(req.prompt),
                     max_new_tokens=req.max_new_tokens)])
        got = next(r for r in res if r.rid == req.rid)
        assert got.tokens == ref[0].tokens


# =====================================================================
# capacity-exhausted admission refusal (engine + scheduler)
# =====================================================================

def test_admission_refusal_queues_until_pages_free():
    """An overcommitted pool refuses admissions instead of OOMing; the
    refused request stays queued and completes once eviction releases
    pages — with the same tokens as an uncontended run."""
    m = tiny_model()
    # 2 slots × 2 pages each need 4 pages at capacity 16/pt 8;
    # pool_pages=3 cannot hold two full-budget requests at once
    cfg = serve_config(max_slots=2, max_tokens=16, prefill_bucket=16,
                       pool_pages=3, prefix_reuse=False)
    eng = InferenceEngine(m, config=cfg, seed=0)
    reqs = [Request(rid=i, prompt=[1 + i, 2 + i, 3 + i],
                    max_new_tokens=10) for i in range(3)]
    sched = ContinuousScheduler(eng)
    res = sched.run([Request(rid=r.rid, prompt=list(r.prompt),
                             max_new_tokens=r.max_new_tokens)
                     for r in reqs])
    assert sched.admission_refusals > 0
    assert len(res) == 3
    free = InferenceEngine(m, config=serve_config(
        max_slots=2, max_tokens=16, prefill_bucket=16,
        prefix_reuse=False), seed=0)
    ref = ContinuousScheduler(free).run(reqs)
    assert ({r.rid: r.tokens for r in res}
            == {r.rid: r.tokens for r in ref})


# =====================================================================
# speculative decoding
# =====================================================================

def spec_config(j=3, **inf):
    base = {"prefill_bucket": 16, "page_tokens": 16}
    base.update(inf)
    cfg = serve_config(**base)
    cfg["inference"]["speculative"] = {"draft_tokens": j}
    return cfg


def test_spec_outputs_identical_to_target_only():
    """The exactness-by-construction contract: with ANY draft — a
    different (smaller) model or an identical twin — the emitted stream
    equals target-only greedy decode, token for token."""
    m = tiny_model()
    reqs = lambda: [Request(rid=i, prompt=[1 + i, 2 + i, 3],
                            max_new_tokens=7) for i in range(5)]
    base = InferenceEngine(m, config=serve_config(
        prefill_bucket=16, page_tokens=16), seed=0)
    sb = ContinuousScheduler(base)
    want = {r.rid: r.tokens for r in sb.run(reqs())}

    small = InferenceEngine(m, config=spec_config(), seed=0,
                            draft_model=GPT2.from_size("tiny", **DRAFT))
    ss = ContinuousScheduler(small)
    got = {r.rid: r.tokens for r in ss.run(reqs())}
    assert got == want
    assert ss.spec_proposed > 0
    assert 0 <= ss.spec_accepted <= ss.spec_proposed
    # one dispatch per up-to-(J+1) tokens: never more iterations than
    # the per-token baseline
    assert ss.decode_iters <= sb.decode_iters

    twin = InferenceEngine(
        m, config=spec_config(), seed=0,
        draft_model=tiny_model(),
        draft_params=tiny_model().init_params(jax.random.PRNGKey(0)))
    st = ContinuousScheduler(twin)
    assert {r.rid: r.tokens for r in st.run(reqs())} == want
    # the identical twin agrees (near-)always → fewer target dispatches
    assert st.decode_iters < sb.decode_iters
    assert st.spec_accepted >= ss.spec_accepted


def test_spec_eos_mid_block_and_budget():
    """EOS landing inside a speculative block stops the slot exactly
    like target-only decode (finish reason, token list, budgets)."""
    m = tiny_model()
    base = InferenceEngine(m, config=serve_config(
        prefill_bucket=16, page_tokens=16), seed=0)
    ref = ContinuousScheduler(base).run(
        [Request(rid=0, prompt=[3, 1], max_new_tokens=9, eos_id=None)])
    eos = ref[0].tokens[2]                  # force an eos mid-stream
    r_ref = ContinuousScheduler(base).run(
        [Request(rid=0, prompt=[3, 1], max_new_tokens=9, eos_id=eos)])
    spec = InferenceEngine(m, config=spec_config(), seed=0,
                           draft_model=GPT2.from_size("tiny", **DRAFT))
    r_spec = ContinuousScheduler(spec).run(
        [Request(rid=0, prompt=[3, 1], max_new_tokens=9, eos_id=eos)])
    assert r_spec[0].tokens == r_ref[0].tokens
    assert r_spec[0].finish_reason == r_ref[0].finish_reason == "eos"


def test_spec_draft_cache_has_no_holes_after_full_acceptance():
    """A fully-accepted block advances pos by J+1, so draft row pos+J
    becomes draft HISTORY — the chain runs J+1 draft steps precisely so
    that row is written (review regression: it stayed zero forever,
    silently decaying the accept rate of every later block)."""
    m = tiny_model()
    twin = InferenceEngine(
        m, config=spec_config(j=3), seed=0,
        draft_model=tiny_model(),
        draft_params=tiny_model().init_params(jax.random.PRNGKey(0)))
    sched = ContinuousScheduler(twin)
    res = sched.run([Request(rid=0, prompt=[1, 2, 3],
                             max_new_tokens=12)])
    assert len(res[0].tokens) == 12
    # the twin accepts (nearly) everything, so blocks advance J+1 —
    # every draft-history row up to the last written position must be
    # populated (norm > 0; a zero row is the hole)
    written = 3 + 12 - 1                  # prompt + generated - feed
    kd = np.asarray(twin._draft_cache["k"])      # [L, R, n, d]
    rows = twin._draft_rows[0][:written]
    norms = np.abs(kd[:, rows]).sum(axis=(0, 2, 3))
    assert np.all(norms > 0), f"zero draft rows at {np.where(norms == 0)}"


def test_spec_custom_sampler_falls_back_loudly():
    eng = InferenceEngine(tiny_model(), config=spec_config(), seed=0,
                          draft_model=GPT2.from_size("tiny", **DRAFT))
    sched = ContinuousScheduler(eng, sampler=lambda row: 7)
    sched.run([Request(rid=0, prompt=[1, 2], max_new_tokens=3)])
    assert eng._warned_fused_fallback
    assert sched.spec_proposed == 0         # the fused path never ran


def test_spec_config_guards():
    with pytest.raises(DeepSpeedConfigError, match="speculative"):
        InferenceEngine(tiny_model(), config=spec_config(
            kv_layout="ring"))
    with pytest.raises(DeepSpeedConfigError, match="speculative"):
        InferenceEngine(tiny_model(), config=spec_config(
            decode_iters_per_dispatch=4))
    bad = spec_config()
    bad["inference"]["speculative"]["drafty"] = 1
    with pytest.raises(DeepSpeedConfigError, match="drafty"):
        InferenceEngine(tiny_model(), config=bad)
    # draft_tokens > 0 with neither draft_model nor draft_size is loud
    with pytest.raises(DeepSpeedConfigError, match="draft"):
        InferenceEngine(tiny_model(), config=spec_config())
    # vocab mismatch is loud (acceptance compares token ids)
    with pytest.raises(DeepSpeedConfigError, match="vocab"):
        InferenceEngine(tiny_model(), config=spec_config(), seed=0,
                        draft_model=GPT2.from_size(
                            "tiny", **dict(DRAFT, vocab_size=64)))


def test_spec_verify_never_writes_past_allocation():
    """A speculative verify block WIDER than the slot's remaining
    budget aims writes past the slot's allocated pages — those must be
    DROPPED, never land in pages the slot does not own.  (Review
    regression: unallocated page-table entries used to resolve to
    page 0, silently corrupting whichever request — or published shared
    prefix — held it.)"""
    m = tiny_model()
    # capacity 32 = 4 pages/slot, but the request allocates only 2
    # (prompt 7 + budget 9 = 16 rows); its final spec block (pos 14,
    # remaining 1) writes verify rows 14..20 — rows 16..20 aim at the
    # 3rd, UNALLOCATED table entry
    cfg = spec_config(j=6, max_slots=1, max_tokens=32, prefill_bucket=16,
                      page_tokens=8, pool_pages=4)
    eng = InferenceEngine(m, config=cfg, seed=0,
                          draft_model=GPT2.from_size("tiny", **DRAFT))
    # the drop-row convention, checked at the map level
    rows = eng.pool.rows()
    assert rows.shape == (1, 32)
    sched = ContinuousScheduler(eng)
    owned = set()
    sched.submit(Request(rid=0, prompt=list(range(1, 8)),
                         max_new_tokens=9))
    while sched.queue or sched.active:
        sched.step()
        for page in eng.pool.slot_pages(0):       # before eviction
            owned.update(range(page * 8, page * 8 + 8))
    assert len(sched.results[0].tokens) == 9
    unowned = sorted(set(range(eng.cache_spec.pool_rows)) - owned)
    assert len(unowned) == 16                     # 2 pages never owned
    k = np.asarray(eng._cache["k"])
    v = np.asarray(eng._cache["v"])
    # never-allocated pool pages are bitwise untouched (still zeros)
    assert not np.any(k[:, unowned])
    assert not np.any(v[:, unowned])
    # and unallocated table entries resolve to the drop row
    assert np.all(eng.pool.rows()[0, 16:] == eng.cache_spec.pool_rows)


def test_pool_refusal_counts_revived_lru_hits():
    """The refusal check must not count LRU pages the admission itself
    is about to revive as hits — that passed the check and then ran the
    allocator dry mid-admission (review regression: refcounts were
    corrupted and the table write crashed instead of refusing)."""
    pool = PagePool(spec_of(slots=3, capacity=24, pool_pages=4))
    a = pool.admit(0, list(range(16)), 0)          # 2 pages
    pool.publish(a)                                # both pages indexed
    pool.release(0)                                # -> LRU (published)
    assert pool.admit(1, list(range(30, 46)), 0) is not None  # drains free
    # hits BOTH LRU pages and needs 1 fresh page — nothing allocatable
    refused = pool.admit(2, list(range(16)) + [99], 7)
    assert refused is None and pool.refusals == 1
    assert pool.slot_pages(2) == []                # nothing half-applied
    assert int(pool._ref.max()) <= 1               # refcounts untouched
    # once the neighbour releases, the same admission succeeds
    pool.release(1)
    g = pool.admit(2, list(range(16)) + [99], 7)
    assert g is not None and g.reused_pages == 2


def test_prefill_raises_loudly_on_exhausted_overcommitted_pool():
    """engine.prefill (the no-reuse oracle/baseline path) allocates the
    full slot range and has no queue to fall back to — on an
    overcommitted pool it must raise an actionable error, not corrupt
    state."""
    cfg = serve_config(max_slots=2, max_tokens=16, prefill_bucket=16,
                       pool_pages=3, prefix_reuse=False)
    eng = InferenceEngine(tiny_model(), config=cfg, seed=0)
    eng.prefill(0, [1, 2, 3])                      # holds 2 of 3 pages
    with pytest.raises(RuntimeError, match="page pool exhausted"):
        eng.prefill(1, [4, 5, 6])
    eng.release(0)
    assert eng.prefill(1, [4, 5, 6]) is not None   # recovers cleanly


# =====================================================================
# telemetry: serve schema v2 + summary columns
# =====================================================================

def test_serve_summary_and_v2_events(tmp_path):
    from deepspeed_tpu.observability import schema
    m = tiny_model()
    eng = InferenceEngine(m, config=spec_config(prefill_bucket=24,
                                                page_tokens=8), seed=0,
                          draft_model=GPT2.from_size("tiny", **DRAFT))
    prefix = list(range(1, 17))
    path = str(tmp_path / "serve.jsonl")
    out = run_serve(eng, [Request(rid=i, prompt=prefix + [40 + i],
                                  max_new_tokens=5) for i in range(4)],
                    jsonl_path=path, window_iters=2)
    s = out["summary"]
    assert s["prefix_hit_rate"] == 0.75             # 3 of 4 admissions
    assert s["prefill_tokens_saved"] == 48
    assert s["spec_accept_rate"] is not None
    assert s["draft_params"] and s["draft_params"] > 0
    assert schema.validate_jsonl(path) == []
    import json
    serve = [json.loads(l) for l in open(path)
             if json.loads(l).get("schema") == schema.SERVE_SCHEMA_ID]
    assert serve and serve[-1]["version"] == schema.SERVE_SCHEMA_VERSION
    assert serve[-1]["prefix_hits"] == 3
    assert serve[-1]["prefix_tokens_reused"] == 48
    assert serve[-1]["spec_proposed"] > 0


def test_serve_schema_version_awareness():
    """v1 logs (PR 10, no reuse/spec columns) still validate; a v2
    event missing them does not."""
    from deepspeed_tpu.observability import schema
    v1 = {"schema": schema.SERVE_SCHEMA_ID, "version": 1, "ts": 1.0,
          "window": 1, "decode_iters": 4, "tokens_out": 9,
          "admitted": 2, "evicted": 1, "active_slots_mean": 1.5,
          "queue_depth": 0, "slots": 4, "kv_cache_gb": 0.1,
          "tokens_per_sec": 10.0, "ttft_p50_ms": 1.0,
          "ttft_p99_ms": 2.0, "itl_p50_ms": 0.5, "itl_p99_ms": 0.9,
          "counters": {}}
    assert schema.validate_any(v1) is None
    v2 = dict(v1, version=2)
    msg = schema.validate_any(v2)
    assert msg is not None and "prefix_hits" in msg
    v2.update({"prefix_hits": 0, "prefix_tokens_reused": 0,
               "spec_proposed": 0, "spec_accepted": 0})
    assert schema.validate_any(v2) is None


# =====================================================================
# exactly-N executables + counted fences (the PR 11 contract, new N)
# =====================================================================

def test_contract_executables_with_tail_and_spec(tmp_path):
    from deepspeed_tpu.observability import fences as obs_fences
    from deepspeed_tpu.resilience import COUNTERS
    from deepspeed_tpu.utils import compile_cache

    d = str(tmp_path / "cc")
    compile_cache.enable(d)
    jax.clear_caches()
    try:
        m = tiny_model()
        # ---- reuse engine: prefill + prefill_tail + decode = 3
        eng = InferenceEngine(m, config=serve_config(), seed=0)
        assert eng.tail_bucket == 8
        m0, f0 = COUNTERS.compile_cache_misses, obs_fences.FENCE_COUNT
        prefix = list(range(1, 17))
        eng.admit(0, prefix + [50], 2)          # miss → full bucket
        eng.admit(1, prefix + [60], 2)          # hit, tail 1 → tail bucket
        eng.admit(2, prefix + [61], 2)          # hit again (cached prog)
        toks = np.zeros((eng.num_slots,), np.int32)
        act = np.ones((eng.num_slots,), bool)
        for _ in range(3):
            eng.decode(toks, act)
        pred = eng.predict_executables()
        assert pred.total == 3
        assert COUNTERS.compile_cache_misses - m0 == 3
        from deepspeed_tpu.analysis import dispatchplan
        plans = eng.plan_dispatch()
        predicted = dispatchplan.serve_predict_fences(plans, prefills=3,
                                                      decode_iters=3)
        assert obs_fences.FENCE_COUNT - f0 == predicted == 6
        assert not eng.run_stability().errors

        # ---- spec engine: prefill + draft_prefill + spec_step = 3
        # (tail bucket off: page_tokens == bucket)
        jax.clear_caches()
        eng2 = InferenceEngine(m, config=spec_config(), seed=0,
                               draft_model=GPT2.from_size("tiny", **DRAFT))
        assert eng2.tail_bucket == 0
        m1, f1 = COUNTERS.compile_cache_misses, obs_fences.FENCE_COUNT
        sched = ContinuousScheduler(eng2)
        sched.run([Request(rid=i, prompt=[1 + i, 2], max_new_tokens=6)
                   for i in range(3)])
        pred2 = eng2.predict_executables()
        assert pred2.total == 3
        assert sorted(p[0] for p in pred2.programs) == [
            "draft_prefill", "prefill", "spec_step"]
        assert COUNTERS.compile_cache_misses - m1 == 3
        plans2 = eng2.plan_dispatch()
        predicted2 = dispatchplan.serve_predict_fences(
            plans2, prefills=sched.admitted,
            decode_iters=sched.decode_iters)
        assert obs_fences.FENCE_COUNT - f1 == predicted2
        assert not eng2.run_stability().errors
    finally:
        compile_cache.disable()
