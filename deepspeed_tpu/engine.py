"""DeepSpeedTpuEngine — the central runtime.

TPU-native analog of ``DeepSpeedLight``
(/root/reference/deepspeed/pt/deepspeed_light.py:87-1127).  The outward API is
preserved — ``loss = engine(batch); engine.backward(loss); engine.step()`` —
but the execution model is JAX-native:

* ``forward`` runs ONE jitted shard_mapped function that computes the loss
  *and* the local (per-DP-shard, unreduced) gradients via ``value_and_grad``.
  XLA fuses forward+backward+loss-scaling into a single TPU program; the
  reference's separate autograd pass doesn't exist as a separate execution.
* ``backward`` accumulates those cached local grads into an fp32 buffer
  (reference accumulates into ``param.grad``); no collective happens before
  the gradient-accumulation boundary — the reference's "smart gradient
  accumulation" (deepspeed_light.py:625-627).
* ``step`` at a boundary runs the jitted update: DP gradient reduction
  (``psum`` with the fp32_allreduce / prescale knobs, reference :819-849),
  overflow check + dynamic loss scale FSM, optional ZeRO-1 partitioned update
  (reduce-scatter → shard-local Adam → all-gather, see ``zero.py``), and the
  skip-on-overflow semantics expressed as ``jnp.where`` instead of a host
  branch.
* ``train_batch`` drives a full effective batch (gas micro-steps + update)
  through the split API in one call.

Gradient accumulation state is represented as global arrays with a leading
``[dp]`` axis sharded over the data axis: each DP shard owns exactly its local
unreduced gradient — the same per-rank state the reference keeps in
``param.grad``, with the same per-device memory.
"""

from __future__ import annotations

import logging
import os
import time
import weakref
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu import analysis as graph_lint
from deepspeed_tpu import constants as C
from deepspeed_tpu.observability import fences as obs_fences
from deepspeed_tpu.observability.flightrec import RECORDER as _flightrec
from deepspeed_tpu.observability.tracing import annotate as _annotate
from deepspeed_tpu import lr_schedules as schedules_mod
from deepspeed_tpu import precision as prec
from deepspeed_tpu import zero as zero_mod
from deepspeed_tpu import zero3 as zero3_mod
from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.data import DeepSpeedDataLoader
from deepspeed_tpu.ops import optim as optim_mod
from deepspeed_tpu.parallel import comm
from deepspeed_tpu.parallel.topology import (DATA_AXIS, MODEL_AXIS,
                                             PIPE_AXIS, SEQ_AXIS,
                                             MeshConfig, make_mesh,
                                             init_distributed)
from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer, ThroughputTimer

logger = logging.getLogger(__name__)

MEMORY_OPT_ALLREDUCE_SIZE = 500000000  # reference deepspeed_light.py:30

FORWARD_TIMER = "forward"
BACKWARD_TIMER = "backward"
BACKWARD_INNER_TIMER = "backward_inner"
BACKWARD_REDUCE_TIMER = "backward_allreduce"
STEP_TIMER = "step"


def _as_tuple(batch):
    if isinstance(batch, (tuple, list)):
        return tuple(batch)
    return (batch,)


class _PendingStep:
    """A train-mode forward whose fused fwd+bwd program has not run yet.

    The reference's ``forward`` is forward-only and ``backward`` is
    backward-only (deepspeed_light.py:603-696); this engine fuses both into
    one XLA program for dispatch efficiency, so the grad computation is
    *deferred* here until ``backward()`` (or until the caller materializes a
    loss value).  A pending step whose loss object becomes unreachable
    without ever being observed or backward-ed is dropped unexecuted (see
    ``_force_live_pendings``) — it costs nothing.
    """

    def __init__(self, engine, batch):
        self.engine = engine
        self.batch = batch
        # bind the program at CREATION: a later forward with a different
        # batch format swaps engine._fwdbwd_fn, and forcing this pending
        # must run the program its own batch was traced for
        self.fn = engine._fwdbwd_fn
        self.loss = None  # filled by force()

    @property
    def forced(self):
        return self.loss is not None

    def force(self):
        if self.loss is None:
            e = self.engine
            loss, grads = self.fn(
                e.params, e.loss_scale_state.cur_scale, self.batch)
            # only the engine's CURRENT pending may feed a later backward();
            # a superseded one must not poison the cached grads / last loss
            if e._pending is self:
                e._cached_grads = grads
                e._last_loss = loss
            self.loss = loss
            # the loss values are all a _DeferredLoss can still need; don't
            # pin the micro-batch, the engine, or the compiled executable
            # (format-cache eviction must be able to free it)
            self.batch = None
            self.engine = None
            self.fn = None
        return self.loss


class _DeferredLoss:
    """Lazy scalar returned by train-mode ``forward()``.

    Materializing it (``float``, ``np.asarray``, ``jnp`` ops, arithmetic,
    attribute access) runs the engine's fused fwd+bwd program once; the
    subsequent ``backward()`` reuses the cached gradients so the step still
    costs exactly one program.  Probing losses without training should use
    ``engine.eval()``, whose forward program carries no backward.
    """

    def __init__(self, pending, index):
        self._pending = pending
        self._index = index

    def force(self):
        loss = self._pending.force()
        return jax.tree_util.tree_leaves(loss)[self._index]

    # --- materialization protocols
    def __jax_array__(self):
        return jnp.asarray(self.force())

    def __array__(self, dtype=None):
        import numpy as _np
        return _np.asarray(self.force(), dtype=dtype)

    def __float__(self):
        return float(self.force())

    def __int__(self):
        return int(self.force())

    def __bool__(self):
        return bool(self.force())

    def __repr__(self):
        return repr(self.force())

    def __format__(self, spec):
        return format(self.force(), spec)

    # --- arithmetic (loss scaling / summing before backward)
    def __add__(self, o):
        return self.force() + _resolve_loss(o)

    __radd__ = __add__

    def __sub__(self, o):
        return self.force() - _resolve_loss(o)

    def __rsub__(self, o):
        return _resolve_loss(o) - self.force()

    def __mul__(self, o):
        return self.force() * _resolve_loss(o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self.force() / _resolve_loss(o)

    def __rtruediv__(self, o):
        return _resolve_loss(o) / self.force()

    def __neg__(self):
        return -self.force()

    # --- comparisons (early stopping / logging on the train loss)
    def __eq__(self, o):
        return self.force() == _resolve_loss(o)

    def __ne__(self, o):
        return self.force() != _resolve_loss(o)

    def __lt__(self, o):
        return self.force() < _resolve_loss(o)

    def __le__(self, o):
        return self.force() <= _resolve_loss(o)

    def __gt__(self, o):
        return self.force() > _resolve_loss(o)

    def __ge__(self, o):
        return self.force() >= _resolve_loss(o)

    # value-based __eq__ makes identity hashing inconsistent; match jax.Array
    # (unhashable) so deferred losses can't silently mis-key dicts/sets
    __hash__ = None

    #: array attributes a _DeferredLoss forwards (forcing the fused program).
    #: Anything else — dunder protocol probes, hasattr() sweeps, debugger
    #: introspection — raises AttributeError WITHOUT forcing, preserving the
    #: "unobserved forward costs nothing" contract.
    _ARRAY_ATTRS = frozenset({
        "item", "tolist", "shape", "dtype", "ndim", "size", "nbytes",
        "astype", "block_until_ready", "device", "devices", "sharding",
        "sum", "mean", "min", "max", "copy",
    })

    def __getattr__(self, name):
        if name in self._ARRAY_ATTRS:
            return getattr(self.force(), name)
        raise AttributeError(
            f"_DeferredLoss has no attribute {name!r}; materialize it first "
            "(float(loss), jnp.asarray(loss)) to access the full jax.Array")


def _resolve_loss(x):
    """Replace any _DeferredLoss leaves in a loss pytree with real arrays."""
    return jax.tree_util.tree_map(
        lambda l: l.force() if isinstance(l, _DeferredLoss) else l, x)


class OptimizerFacade:
    """The object returned as ``optimizer`` from ``initialize()``.

    Duck-types the reference wrapper optimizers
    (FP16_Optimizer/FP16_DeepSpeedZeroOptimizer): exposes ``param_groups`` for
    the LR schedulers, the dynamic-loss-scale observables asserted by the
    reference tests (cur_scale/cur_iter/scale_window/min_loss_scale,
    tests/unit/test_dynamic_loss_scale.py), and ``overflow``.
    """

    def __init__(self, engine: "DeepSpeedTpuEngine"):
        self._engine = engine
        base = engine.base_optimizer
        # group 0 is the default (base-optimizer hyperparameters, unmatched
        # leaves); groups 1..n are the user's param_groups patterns — the
        # reference's torch param-group list, addressable by LR schedules
        # with list-valued params (_format_param)
        self.param_groups = []
        for d in engine._group_defs:
            g = {
                "lr": d.get("lr", base.lr),
                "betas": tuple(d.get("betas", (base.beta1, base.beta2))),
                "weight_decay": d.get("weight_decay", base.weight_decay),
                "name": base.name,
            }
            if "params" in d:
                g["params"] = d["params"]    # the defining pattern
            self.param_groups.append(g)

    # loss-scale observables -------------------------------------------------
    @property
    def dynamic_loss_scale(self):
        return bool(self._engine._dynamic_loss_scale)

    @property
    def cur_scale(self):
        return float(self._engine.loss_scale_state.cur_scale)

    @property
    def loss_scale(self):
        return self.cur_scale

    @property
    def cur_iter(self):
        return int(self._engine.loss_scale_state.cur_iter)

    @property
    def scale_window(self):
        return int(self._engine.loss_scale_state.scale_window)

    @property
    def min_loss_scale(self):
        return float(self._engine.loss_scale_state.min_scale)

    @property
    def overflow(self):
        return bool(self._engine.overflow)

    # passthroughs -----------------------------------------------------------
    def state_dict(self):
        return self._engine._optimizer_state_dict()

    def load_state_dict(self, sd):
        self._engine._optimizer_load_state_dict(sd)


class DeepSpeedTpuEngine:
    """See module docstring.  Constructor stages mirror the reference ctor
    (deepspeed_light.py:90-185)."""

    def __init__(self,
                 args=None,
                 model=None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mesh: Optional[Mesh] = None,
                 dist_init_required: Optional[bool] = None,
                 collate_fn: Optional[Callable] = None,
                 config=None,
                 config_params=None,
                 param_groups=None,
                 seed: int = 0):
        if model is None:
            raise ValueError("deepspeed_tpu.initialize: model is required")
        self.module = model
        self.client_optimizer = optimizer
        self.client_lr_scheduler = lr_scheduler
        self.collate_fn = collate_fn
        self.training = True
        self.seed = seed

        # -- distributed bootstrap (reference _init_distributed / _mpi_check)
        use_mpi = bool(getattr(args, "deepspeed_mpi", False))
        if dist_init_required or use_mpi or (
                dist_init_required is None and "DSTPU_COORDINATOR" in os.environ):
            init_distributed(use_mpi=use_mpi)

        # -- config resolution (reference _do_args_sanity_check :381-397:
        #    args.deepspeed_config, deprecated deepscale_config)
        cfg_src = config if config is not None else config_params
        if cfg_src is None and args is not None:
            ds_cfg = getattr(args, "deepspeed_config", None)
            if ds_cfg is None:
                ds_cfg = getattr(args, "deepscale_config", None)
                if ds_cfg is not None:
                    logger.warning(
                        "DeepSpeedConfig: 'deepscale_config' is deprecated,"
                        " use 'deepspeed_config'")
            cfg_src = ds_cfg
        if cfg_src is None:
            raise DeepSpeedConfigError(
                "DeepSpeed requires --deepspeed_config to specify "
                "configuration file or a config dict")
        if isinstance(cfg_src, str):
            import json as _json
            try:
                with open(cfg_src, "r") as f:
                    cfg_src = _json.load(f)
            except Exception as e:
                raise DeepSpeedConfigError(
                    f"Could not read DeepSpeed config file {cfg_src!r}: {e}")

        # -- mesh (the mpu): explicit Mesh beats config parallel sizes
        if isinstance(mesh, MeshConfig):
            mesh = make_mesh(model_parallel_size=mesh.model_parallel_size,
                             context_parallel_size=mesh.context_parallel_size,
                             pipeline_parallel_size=mesh.pipeline_parallel_size,
                             devices=mesh.devices)
        if mesh is None:
            mesh = make_mesh(
                model_parallel_size=cfg_src.get(C.MODEL_PARALLEL_SIZE, 1),
                context_parallel_size=cfg_src.get(
                    C.CONTEXT_PARALLEL_SIZE, 1),
                pipeline_parallel_size=cfg_src.get(
                    C.PIPELINE_PARALLEL_SIZE, 1))
        self.mesh = mesh
        self.dp_world_size = mesh.shape[DATA_AXIS]
        self.mp_world_size = mesh.shape[MODEL_AXIS]
        self.sp_world_size = mesh.shape.get(SEQ_AXIS, 1)
        self.pp_world_size = mesh.shape.get(PIPE_AXIS, 1)

        self.config = DeepSpeedConfig(cfg_src, dp_world_size=self.dp_world_size)

        # -- persistent compilation cache (fast resume: a relaunched worker
        #    reuses the prior attempt's compiled step programs).  Enabled
        #    HERE, before any step function traces — the engine compiles
        #    lazily, so every program this build produces goes through the
        #    cache (utils/compile_cache.py; docs/resilience.md)
        from deepspeed_tpu.utils import compile_cache as _compile_cache
        self.compile_cache_dir = _compile_cache.enable_from_config(
            self.config)

        # knobs the reference uses to schedule NCCL that XLA owns here —
        # accepted for config compatibility, but warn instead of silently
        # doing nothing (VERDICT r1 weak #6)
        if self.config.disable_allgather:
            logger.warning(
                "disable_allgather=true is a no-op on TPU: the ZeRO weight "
                "all-gather is a single XLA collective, not a schedulable "
                "torch op")
        if self.config.allgather_size != C.ALLGATHER_SIZE_DEFAULT:
            logger.warning(
                "allgather_size is a no-op on TPU: XLA owns the collective "
                "chunking schedule")

        # model-side shape checks against the real mp degree (heads/vocab
        # divisibility — the errors would otherwise surface as opaque reshape
        # failures inside shard_map)
        validate_fn = getattr(model, "validate", None)
        if validate_fn is not None:
            validate_fn(self.mp_world_size)

        # fail fast: context parallelism needs declared batch shardings
        # (the same error _batch_specs raises, but before the expensive
        # parameter placement instead of at the first forward)
        if (self.sp_world_size > 1
                and getattr(model, "batch_specs", None) is None):
            raise DeepSpeedConfigError(
                "context_parallel_size > 1 requires the model to declare "
                "batch_specs(batch) -> pytree[PartitionSpec]: the engine "
                "will not guess which batch dims are sequences. The "
                "built-in model family declares this; see "
                "models.transformer.token_batch_specs for the standard "
                "[B, T] token-batch layout.")

        # Config-beats-model overrides below MUTATE the model object.  Users
        # and the repo's own tests reuse one model instance across several
        # engines, and every engine traces its step functions lazily — a
        # shared mutation would silently retrace ANOTHER engine's step with
        # THIS engine's settings.  First override takes a shallow copy
        # (same rationale as the ZeRO-3 zero3_dims hand-off below).
        self._model_owned = False

        def _own_model():
            nonlocal model
            if not self._model_owned:
                import copy
                model = self.module = copy.copy(model)
                self._model_owned = True
            return model

        # -- activation checkpointing override (config beats the model's own
        #    remat flag; the reference's analog is Megatron's
        #    --checkpoint-activations, ds_gpt2_test.sh gpt_options)
        ac = self.config.activation_checkpointing
        if ac is not None:
            mcfg = getattr(model, "config", None)
            if mcfg is not None and hasattr(mcfg, "remat"):
                import dataclasses as _dc
                repl = {"remat": bool(ac)}
                pol = self.config.activation_checkpointing_policy
                if pol is not None and hasattr(mcfg, "remat_policy"):
                    repl["remat_policy"] = pol
                _own_model().config = _dc.replace(mcfg, **repl)
            else:
                logger.warning(
                    "activation_checkpointing set but the model exposes no "
                    "remat toggle; ignored")

        # -- pipeline schedule override (config beats the model field, like
        #    activation_checkpointing above)
        ps = self.config.pipeline_schedule
        if ps is not None:
            if hasattr(model, "schedule"):
                _own_model().schedule = ps
            else:
                logger.warning(
                    "pipeline_schedule set but the model exposes no "
                    "schedule field; ignored")

        # -- sequence-parallel strategy override (ring | ulysses)
        spi = self.config.sequence_parallel_impl
        if spi is not None:
            mcfg = getattr(model, "config", None)
            if mcfg is not None and hasattr(mcfg, "sp_impl"):
                import dataclasses as _dc
                _own_model().config = _dc.replace(mcfg, sp_impl=spi)
            else:
                logger.warning(
                    "sequence_parallel_impl set but the model exposes no "
                    "sp_impl config field; ignored")
        if self.sp_world_size > 1:
            mcfg = getattr(model, "config", None)
            if (mcfg is not None and getattr(mcfg, "sp_impl", None)
                    == "ulysses"):
                n_local = mcfg.num_heads // max(self.mp_world_size, 1)
                if n_local % self.sp_world_size:
                    raise DeepSpeedConfigError(
                        f"sequence_parallel_impl='ulysses' needs local "
                        f"heads ({mcfg.num_heads}/{self.mp_world_size} = "
                        f"{n_local}) divisible by context_parallel_size "
                        f"({self.sp_world_size}); use 'ring' for "
                        f"head-limited models")

        # -- precision policy
        self.policy = prec.policy_from_config(self.config.fp16_enabled,
                                              self.config.bf16_enabled)
        self._dynamic_loss_scale = (self.config.fp16_enabled
                                    and self.config.dynamic_loss_scale)

        # -- optimizer (client object beats JSON, reference :438-443)
        self._configure_optimizer()

        # -- ZeRO guard (reference restricts ZeRO to (fused) Adam,
        #    deepspeed_light.py:450-457 + _configure_zero_optimizer :520)
        self.zero_enabled = self.config.zero_enabled
        # axes model STATE shards over beyond data: each (pipe stage, model
        # rank) pair keeps a flat fp32 master of only ITS parameter slices,
        # partitioned over its DP group (the [S, local_padded] layout)
        self._zero_state_axes = []
        if self.pp_world_size > 1:
            self._zero_state_axes.append((PIPE_AXIS, self.pp_world_size))
        if self.mp_world_size > 1:
            self._zero_state_axes.append((MODEL_AXIS, self.mp_world_size))
        if self.zero_enabled:
            # stages 1-2 keep the reference's Adam-family guard (the flat
            # [S, padded] master/moment layout is built for m+v state);
            # stage 3 updates per-leaf on partitioned shards, so any
            # elementwise optimizer works — Lion (m-only state) is admitted
            # there (ADVICE r4; parity pinned in
            # tests/test_zero3.py::test_zero3_lion_matches_stage0)
            stage3_ok = ("lion",) if self.config.zero_stage == 3 else ()
            if self.base_optimizer.name not in ("adam", "adamw") + stage3_ok:
                raise DeepSpeedConfigError(
                    f"zero_optimization stage {self.config.zero_stage} is "
                    f"only supported for Adam-family optimizers (Lion is "
                    f"admitted at stage 3, where the update is per-leaf "
                    f"elementwise), got {self.base_optimizer.name!r} "
                    f"(reference guard: deepspeed_light.py:450-457)")
            # parameter-parallel sub-groups (reference deepspeed_light.py:
            # 63-77): optimizer state partitions over a SUBSET of size pps
            # within the DP group, replicated across the dp/pps sub-groups.
            # Layout: the flat master is [repl * padded] sharded P('data') —
            # consecutive blocks of pps devices each hold the full
            # partitioned state, exactly the reference's sub-group
            # arrangement; collectives use axis_index_groups (reduce-scatter
            # within the sub-group, psum across sub-groups, weight gather
            # within the sub-group)
            pps = self.config.zero_parameter_parallel_size
            if pps in (None, 0):
                pps = self.dp_world_size
            pps = int(pps)
            if pps <= 0 or self.dp_world_size % pps != 0:
                raise DeepSpeedConfigError(
                    f"zero_optimization.parameter_parallel_size={pps} must "
                    f"divide the DP world size ({self.dp_world_size})")
            self.zero_pps = pps
            self.zero_repl = self.dp_world_size // pps
        else:
            self.zero_pps = self.dp_world_size
            self.zero_repl = 1
        # stage 2 = gradient partitioning (beyond the reference's v0.1.0
        # stage 1): each micro-step's gradients reduce-scatter into the
        # owned flat partition INSIDE the accumulation loop, so the
        # grad-accumulation buffer shrinks from full-size to 1/pps
        self.zero_stage = self.config.zero_stage if self.zero_enabled else 0
        # stage 3 = parameter partitioning (zero3.py): params/masters/
        # moments persist per-leaf data-sharded, the model gathers each
        # layer's weights on use, and the gather's autodiff transpose
        # reduce-scatters the grads.  Stages 1-2 keep the flat-buffer
        # layout; ``zero_flat`` gates every flat-layout code path.
        self.zero3 = self.zero_stage == 3
        self.zero_flat = self.zero_enabled and not self.zero3
        # -- comm/compute overlap (zero_optimization.overlap_comm): the
        # boundary collectives split into lane-aligned buckets so XLA's
        # async collectives overlap the shard-local update (and, at ZeRO-3,
        # the block scan prefetches the next layer's gather).  Bucketing
        # only re-tiles the same elementwise math — bit-exact with serial.
        # DSTPU_OVERLAP=off is the escape hatch restoring today's exact
        # monolithic programs (DSTPU_OVERLAP=on forces it over the config).
        self.overlap_comm = bool(self.config.zero_overlap_comm)
        _ov = os.environ.get("DSTPU_OVERLAP", "").strip().lower()
        if _ov in ("off", "0", "false"):
            self.overlap_comm = False
        elif _ov in ("on", "1", "true"):
            self.overlap_comm = True
        elif _ov:
            raise DeepSpeedConfigError(
                f"DSTPU_OVERLAP={_ov!r} is not a valid mode: use 'on' or "
                f"'off'")
        # bucket size in fp32 elements, floored to the 128-lane tile (the
        # flat partition is 128-padded, so aligned buckets never split a
        # lane); comm_bucket_mb may be fractional for tiny test meshes
        self.comm_bucket_elems = max(
            128, (int(self.config.zero_comm_bucket_mb * (1 << 20)) // 4
                  // 128) * 128)
        if self.zero3:
            if not hasattr(model, "zero3_dims"):
                raise DeepSpeedConfigError(
                    "zero_optimization.stage=3 requires a model that "
                    "cooperates with parameter partitioning (a zero3_dims "
                    "attribute the engine fills and a per-layer gather in "
                    "the block scan — the built-in GPT-2/BERT/MoE family "
                    "does; see models/transformer.py zero3_enter)")
            if self.zero_pps != self.dp_world_size:
                raise DeepSpeedConfigError(
                    "zero_optimization.parameter_parallel_size is a "
                    "stage-1/2 flat-layout knob; stage 3 partitions over "
                    "the full DP group")
            # (pipeline composes: the stage-local [L/pp] stack gathers per
            # layer exactly like the full stack — dim 0 is pipe-sharded
            # and zero3_min_dims pins it, so the data axis lands on a
            # weight dim; tests/test_zero3.py::test_zero3_with_pipeline)
            # Partitioned leaves reduce inside the gather's autodiff
            # transpose (a compute-dtype psum_scatter BEFORE the /world
            # division), so the stage-0 reduction envelope knobs cannot
            # apply to them (ADVICE r4; docs/features.md "ZeRO-3
            # reduction dtype").  Warn loudly rather than silently
            # ignoring the config.
            inert = [k for k, dflt, v in (
                ("fp32_allreduce", C.FP32_ALLREDUCE_DEFAULT,
                 self.config.fp32_allreduce),
                ("prescale_gradients", C.PRESCALE_GRADIENTS_DEFAULT,
                 self.config.prescale_gradients),
                ("gradient_predivide_factor",
                 C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT,
                 self.config.gradient_predivide_factor)) if v != dflt]
            if inert:
                logger.warning(
                    "zero_optimization.stage=3: %s only affect(s) "
                    "REPLICATED leaves; partitioned leaves reduce via the "
                    "gather transpose's compute-dtype (bf16/fp16) "
                    "psum_scatter before the 1/world division, so fp16 "
                    "partial sums there can overflow where the prescaled "
                    "stage-0 path would not (dynamic loss scaling "
                    "recovers but trajectories can diverge)",
                    ", ".join(inert))

        # -- loss scale state
        if self.config.fp16_enabled:
            if self.config.dynamic_loss_scale:
                variant = (prec.MEGATRON if self.zero_enabled else prec.INLINE)
                self._ls_variant = variant
                self.loss_scale_state = prec.from_dynamic_args(
                    self.config.dynamic_loss_scale_args, variant=variant)
            else:
                self._ls_variant = prec.INLINE
                self.loss_scale_state = prec.static_loss_scale_state(
                    float(self.config.loss_scale) or 1.0)
        else:
            self._ls_variant = prec.INLINE
            self.loss_scale_state = prec.static_loss_scale_state(1.0)
        # pin the loss-scale leaves to the mesh NOW (committed, replicated):
        # as fresh jnp scalars they are UNCOMMITTED single-device arrays,
        # which hash a DIFFERENT executable key than the committed
        # NamedSharding the step program's outputs carry — so the second
        # boundary used to re-lower (and re-compile) the whole step
        # program once per run (stability.unpinned-sharding; pinned by
        # tests/test_dispatch_stability.py)
        self.loss_scale_state = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._named(P())),
            self.loss_scale_state)

        # -- resilience (docs/resilience.md): NaN/Inf sentinel extends the
        #    fp16 skip-on-overflow contract to bf16/fp32 boundaries; the
        #    hang watchdog arms around every blocking engine call
        self._nan_sentinel = bool(self.config.resilience_nan_sentinel)
        self._watchdog = None
        if self.config.resilience_watchdog_timeout_s > 0:
            from deepspeed_tpu.resilience import Watchdog
            self._watchdog = Watchdog(
                self.config.resilience_watchdog_timeout_s,
                abort=self.config.resilience_watchdog_abort)

        # -- sanity (reference _do_sanity_check :404-413: LAMB needs dynamic
        #    loss scaling under fp16)
        if (self.config.fp16_enabled and not self.config.dynamic_loss_scale
                and self.base_optimizer.name == "lamb"):
            raise DeepSpeedConfigError(
                "LAMB optimizer requires dynamic loss scaling under fp16")

        # -- parameters: fp32 masters (+ flat ZeRO layout), compute-dtype copy
        if model_parameters is None:
            init_fn = getattr(model, "init_params", None)
            if init_fn is None:
                raise ValueError(
                    "model_parameters is required (or model.init_params(rng))")
            model_parameters = init_fn(jax.random.PRNGKey(seed))
        self._param_specs = self._resolve_param_specs(model, model_parameters)
        self._sparse_flags = self._resolve_sparse_flags(model,
                                                        model_parameters)
        self._zero3_dims = None
        if self.zero3:
            min_fn = getattr(model, "zero3_min_dims", None)
            self._zero3_dims = zero3_mod.choose_dims(
                model_parameters, self._param_specs, dict(self.mesh.shape),
                self.dp_world_size,
                min_dims=min_fn(model_parameters) if min_fn else None)
            if not zero3_mod.partitioned_any(self._zero3_dims):
                logger.warning(
                    "zero_optimization.stage=3: no parameter leaf is "
                    "partitionable at dp=%d (divisibility/min-size); "
                    "training proceeds with replicated parameters "
                    "(stage-1-like memory)", self.dp_world_size)
            self._param_specs = zero3_mod.augment_specs(self._param_specs,
                                                        self._zero3_dims)
            # hand the dims to an engine-OWNED copy: a stage-0 engine
            # tracing a shared instance with zero3_dims set would gather
            # unpartitioned leaves dp-fold (same ownership rule as the
            # config-override block in __init__)
            if not self._model_owned:
                import copy
                model = self.module = copy.copy(self.module)
                self._model_owned = True
            else:
                model = self.module
            model.zero3_dims = self._zero3_dims
            # overlap_comm at stage 3: the block scan runs over layer
            # pairs and issues both gathers up front, so the second
            # layer's all-gather hides under the first layer's compute
            # (forward AND the remat-replayed backward) — transient
            # weight memory is two gathered layers instead of one
            # (transformer.scan_layers; docs/scaling.md)
            model.zero3_prefetch = self.overlap_comm
        if param_groups is None and self.client_optimizer is None:
            # pure-JSON spelling (optimizer.param_groups); the explicit
            # initialize(param_groups=...) argument beats it, and a
            # client optimizer object disables the whole JSON optimizer
            # section (docs/config.md) — groups included
            param_groups = self.config.optimizer_param_groups
        self._group_defs, self._group_ids = self._resolve_param_groups(
            param_groups, model_parameters)
        self._init_parameters(model_parameters)

        # -- optimizer state
        self._init_optimizer_state()

        # -- counters (reference :144-149)
        self.micro_steps = 0
        self.global_steps = 0
        self.skipped_steps = 0
        self.overflow = False

        # -- timers / throughput (reference :150-156)
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.train_micro_batch_size_per_gpu(),
            num_workers=self.dp_world_size,
            steps_per_output=self.steps_per_print())

        # -- dataloader
        self.training_dataloader = (self.deepspeed_io(training_data)
                                    if training_data is not None else None)

        # -- facade + LR scheduler (JSON beats client object, reference
        #    :317-327)
        self.optimizer = OptimizerFacade(self)
        self._configure_lr_scheduler()

        # -- checkpoint roles (reference _configure_checkpointing :329-343).
        # Stage 3 saves masters/moments in the per-leaf (non-flat) format —
        # no zero_pp_rank_* partition files (checkpoint.py routes on
        # zero_flat).
        self.save_non_zero_checkpoint = jax.process_index() == 0
        self.save_zero_checkpoint = self.zero_flat

        # -- tensorboard (reference :106-120)
        self.summary_writer = (self._get_summary_writer()
                               if self.tensorboard_enabled()
                               and jax.process_index() == 0 else None)

        # -- compiled-function caches.  The batch-consuming programs are
        #    keyed on the batch FORMAT (pytree structure + leaf
        #    shapes/dtypes): the shard_map in_specs are baked per format
        #    (engine._batch_specs picks P(data) vs P() by leaf rank; BERT
        #    accepts dense-labels AND masked-positions batches), so a
        #    format switch must select another executable — never fail on
        #    a spec mismatch, never recompile a format already built.
        #    `_fwdbwd_fn`/`_eval_fn`/`_train_batch_fn` hold the CURRENT
        #    key's entry (only swapped on a key change, so tests may wrap
        #    them); the dicts keep the rest, evicting oldest past
        #    _BATCH_FN_CACHE_SIZE.
        self._fwdbwd_fn = None
        self._fwdbwd_key = None
        self._fwdbwd_fns = {}
        self._eval_fn = None
        self._eval_key = None
        self._eval_fns = {}
        self._step_fn = None
        self._train_batch_fn = None
        self._train_batch_key = None
        self._train_batch_fns = {}
        # multi-step driver (train_many): K fused optimizer steps per
        # dispatch.  Programs key on (K, batch format); the staged
        # [K, 4, G] hyper block caches on its host rows like
        # _current_hypers.
        self.steps_per_dispatch = int(self.config.train_steps_per_dispatch)
        self._train_many_fn = None
        self._train_many_key = None
        self._train_many_fns = {}
        self._hyper_many_key = None
        self._hyper_many_dev = None
        # runtime-true predicate input of the per-step cond isolation in
        # train_many (see _build_train_many) — pinned committed+replicated
        # at build like the loss-scale leaves (stability.unpinned-sharding)
        self._live_flag = jax.device_put(jnp.ones((), jnp.int32),
                                         self._named(P()))
        self._loss_treedefs = {}    # loss pytree structure per batch key
        self._acc = None            # accumulated local grads ([dp, ...] tree)
        self._cached_grads = None   # grads from the last forward
        self._pending = None        # latest train-mode forward not yet run
        self._pending_refs = []     # weakrefs to every unforced _PendingStep
        self._loss_treedef = None   # model loss pytree structure (cached)
        self._last_loss = None
        self._profiling = False
        self._hyper_key = None      # host values behind the staged hypers
        self._hyper_dev = None      # cached [4, G] device array

        # -- graph lint (docs/analysis.md): jaxpr static analysis at
        #    step-build time.  Each (program kind, batch format) pair is
        #    analyzed once; "error" mode turns error-severity findings
        #    into a build-time GraphLintError instead of a pod-slice hang.
        self._graph_lint_mode = self.config.graph_lint_mode
        self._graph_lint_suppress = list(self.config.graph_lint_suppress)
        self._linted_keys = set()

        # -- capacity planner (docs/analysis.md "Capacity planner"):
        #    static per-device peak-HBM + wire-cost prediction of each
        #    step program, once per (program kind, batch format).
        #    "error" mode turns a predicted over-budget peak into a
        #    build-time MemoryPlanError naming the top live-set
        #    contributors — instead of an OOM after minutes of compile.
        self._analysis_mode = self.config.analysis_mode
        self._analysis_suppress = list(self.config.analysis_suppress)
        self._planned_keys = set()

        # -- telemetry (docs/observability.md): spooled on-device metrics
        #    (zero per-step host fences), programmatic step tracing, and the
        #    unified exporter fan-out every scalar producer emits through.
        #    Built LAST — it reads the summary writer, scheduler and
        #    resilience wiring above.
        from deepspeed_tpu.observability import Telemetry
        self._telemetry = Telemetry.from_engine(self)
        if self._watchdog is not None:
            # a tripped hang deadline records a short trace before the
            # optional abort (resilience/watchdog.py on_fire)
            hook = self._telemetry.hang_capture_hook()
            if hook is not None:
                self._watchdog.on_fire = hook

        if self.config.dump_state:
            self.dump_state()

    # ------------------------------------------------------------------ setup

    def _resolve_param_specs(self, model, params):
        spec_fn = getattr(model, "partition_specs", None)
        if spec_fn is not None:
            return spec_fn(params)
        return jax.tree_util.tree_map(lambda _: P(), params)

    def _resolve_param_groups(self, defs, params):
        """Partition param leaves into optimizer groups by path regex.

        ``defs`` is a list of dicts: ``{"params": <regex over the leaf's
        pytree path>, "lr": ..., "betas": ...}`` — the TPU spelling of
        torch's param-group list (the reference takes pre-partitioned
        tensor lists; functional pytrees address leaves by path instead).
        A leaf joins the FIRST matching group (1-based); unmatched leaves
        form group 0 with the base optimizer's hyperparameters.  Returns
        ``(group_defs, group_ids)`` where group_ids is a pytree[int]."""
        if not defs:
            return [{}], jax.tree_util.tree_map(lambda _: 0, params)
        import re
        for d in defs:
            if "params" not in d:
                raise DeepSpeedConfigError(
                    "each param_groups entry needs a 'params' path regex")
            extra = set(d) - {"params", "lr", "betas", "weight_decay"}
            if extra:
                # anything beyond the four plumbed hypers would silently
                # train with other hyperparameters than the facade displays
                raise DeepSpeedConfigError(
                    f"param_groups entry has unsupported keys {sorted(extra)}:"
                    f" supported per-group hyperparameters are 'lr', 'betas' "
                    f"and 'weight_decay' (reference torch groups, "
                    f"deepspeed_fused_lamb.py:77-100)")
            if "betas" in d and not self.base_optimizer.uses_betas:
                # same contract: the group would display betas the update
                # rule never reads
                raise DeepSpeedConfigError(
                    f"per-group 'betas' given but optimizer "
                    f"'{self.base_optimizer.name}' does not consume betas")
        pats = [re.compile(d["params"]) for d in defs]
        flat, treedef = jax.tree_util.tree_flatten_with_path(params)

        def gid(path):
            s = jax.tree_util.keystr(path)
            for i, pat in enumerate(pats):
                if pat.search(s):
                    return i + 1
            return 0

        paths = [jax.tree_util.keystr(p) for p, _ in flat]
        for d, pat in zip(defs, pats):
            # a pattern that matches NOTHING is a typo, not a choice
            # (a pattern fully shadowed by an earlier group is allowed —
            # first match wins, like torch group order)
            if not any(pat.search(s) for s in paths):
                raise DeepSpeedConfigError(
                    f"param_groups pattern {d['params']!r} matches no "
                    f"parameter leaf (patterns are searched against pytree "
                    f"paths like {paths[0]!r})")
        ids = treedef.unflatten([gid(p) for p, _ in flat])
        return [{}] + [dict(d) for d in defs], ids

    def _resolve_sparse_flags(self, model, params):
        """Which leaves take the row-sparse gradient reduction.  The
        reference auto-marks ``nn.Embedding`` weights when
        ``sparse_gradients`` is on (deepspeed_light.py:170-176); functional
        pytrees carry no module types, so models declare them via a
        ``sparse_grad_specs(params) -> pytree[bool]`` hook.  Returns None
        (all-dense) unless the path is actually usable — with a warning, so
        the flag is never a silent no-op."""
        if not self.config.sparse_gradients_enabled:
            return None
        if self.zero_enabled:
            logger.warning(
                "sparse_gradients is ignored under ZeRO: gradients reduce "
                "through the flat partition buffer (reference likewise "
                "routes ZeRO grads densely)")
            return None
        fn = getattr(model, "sparse_grad_specs", None)
        if fn is None:
            logger.warning(
                "sparse_gradients=true but the model defines no "
                "sparse_grad_specs(params) hook (the nn.Embedding "
                "auto-marking analog); gradients stay dense")
            return None
        flags = fn(params)
        if not any(jax.tree_util.tree_leaves(flags)):
            logger.warning(
                "sparse_gradients=true but sparse_grad_specs marked no "
                "leaves; gradients stay dense")
            return None
        return flags

    def _named(self, spec):
        return NamedSharding(self.mesh, spec)

    def _init_parameters(self, model_parameters):
        """Place fp32 masters + compute-dtype params on the mesh (the
        reference's device placement + param broadcast, deepspeed_light.py:
        415-430, and the fp32 master clone, zero_optimizer.py:158-165).
        Master dtype contract: prec.MASTER_DTYPE (graph-lint-enforced)."""
        to_f32 = lambda x: jnp.asarray(x, prec.MASTER_DTYPE)
        masters = jax.tree_util.tree_map(to_f32, model_parameters)

        if self.zero_flat and self._zero_state_axes:
            # ZeRO x MP/PP: each (pipe stage, model rank) keeps a flat fp32
            # master of only ITS parameter slices, partitioned over its DP
            # group (reference parameter-parallel groups,
            # deepspeed_light.py:63-77 + _configure_zero_optimizer
            # :520-531).  Layout: [S, local_padded] sharded
            # P((pipe, model), data) — row is the composite stage/rank id.
            # With parameter_parallel_size < dp each row is additionally
            # block-tiled: consecutive blocks of pps devices within the
            # row's DP group hold the full partitioned state.
            self.flat_meta = zero_mod.make_local_flat_meta(
                masters, self._param_specs, dict(self.mesh.shape),
                self.zero_pps)
            self.master_flat = self._flatten_masters_2d(masters)
            self.master = None
            self._zero_norm_w = jax.device_put(
                self._tile_flat(jnp.asarray(zero_mod.norm_dedup_weights(
                    self.flat_meta, self._param_specs,
                    self._zero_state_axes))),
                self._named(P(DATA_AXIS)))
        elif self.zero_flat:
            # partitions align to zero_pps (== dp unless
            # parameter_parallel_size shrinks the partition group); with
            # sub-groups the flat buffer is tiled repl× so each consecutive
            # block of pps devices holds the full partitioned state
            self.flat_meta = zero_mod.make_flat_meta(masters, self.zero_pps)
            flat = self._tile_flat(zero_mod.flatten_tree(masters,
                                                         self.flat_meta))
            self.master_flat = jax.device_put(flat, self._named(P(DATA_AXIS)))
            self.master = None
            self._zero_norm_w = None
        else:
            # replicated masters — or, at ZeRO-3, per-leaf DATA-sharded
            # masters: self._param_specs is already augmented with the
            # partition dims, so the same placement code shards them
            self.flat_meta = None
            self.master_flat = None
            self.master = jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, self._named(s)),
                masters, self._param_specs)
            self._zero_norm_w = None
        if self._zero_norm_w is None:
            # dummy threaded through the step signature so its arity is
            # static; dead in every non-(ZeRO x MP) branch, DCE'd by XLA
            self._zero_norm_w = jax.device_put(
                jnp.zeros((self.dp_world_size,), jnp.float32),
                self._named(P(DATA_AXIS)))
        if self.zero_flat and len(self._group_defs) > 1:
            # per-element group ids over the flat layout: hypers expand as
            # vec[gid] inside the partitioned update.  meta.sizes are the
            # LOCAL slice sizes under MP/PP (identical for every
            # (stage, shard) row — uniform sharding), so ONE data-sharded
            # vector serves the 1-D and the [S, local] layouts alike.
            gids = np.concatenate(
                [np.full(size, g, np.int32) for g, size in
                 zip(jax.tree_util.tree_leaves(self._group_ids),
                     self.flat_meta.sizes)]
                + [np.zeros(self.flat_meta.padded - self.flat_meta.total,
                            np.int32)])
            self._zero_gid_flat = jax.device_put(
                self._tile_flat(gids), self._named(P(DATA_AXIS)))
        else:
            # dummy with static arity, dead in every other branch
            self._zero_gid_flat = jax.device_put(
                jnp.zeros((self.dp_world_size,), jnp.int32),
                self._named(P(DATA_AXIS)))

        cdt = self.policy.compute_dtype
        self.params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(jnp.asarray(x, cdt), self._named(s)),
            model_parameters, self._param_specs)

    def _tile_flat(self, flat):
        """Replicate a [padded] flat buffer into the parameter-parallel
        block-tiled [repl * padded] layout (no-op at full-DP partitioning).
        Single owner of the sub-group layout invariant; inverse:
        ``_untile_flat``."""
        if self.zero_repl <= 1:
            return flat
        xp = np if isinstance(flat, np.ndarray) else jnp
        return xp.tile(flat, self.zero_repl)

    def _untile_flat(self, flat):
        """First replica block of the block-tiled flat buffer (no-op at
        full-DP partitioning)."""
        return flat[:self.flat_meta.padded]

    def _flatten_masters_2d(self, masters):
        """Build the [S, local_padded] P((pipe, model), data) flat master
        (S = pp * mp): each stage/model shard flattens its local fp32
        slices and keeps only its DP partition (runs as one shard_mapped
        program, no host gather).  Under parameter-parallel sub-groups
        (pps < dp) partitions repeat every pps ranks, realising the
        per-row block-tiled layout."""
        meta = self.flat_meta
        part = meta.partition
        pps = self.zero_pps

        def local(m):
            flat = zero_mod.flatten_tree(m, meta)
            d = jax.lax.axis_index(DATA_AXIS)
            seg = jax.lax.dynamic_slice_in_dim(flat, (d % pps) * part, part)
            return seg[None]

        fn = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(self._param_specs,),
            out_specs=self._zero_flat_spec(),
            check_vma=False)
        placed = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(jnp.asarray(x, jnp.float32),
                                        self._named(s)),
            masters, self._param_specs)
        return jax.jit(fn)(placed)

    def _configure_optimizer(self):
        """Client optimizer beats JSON (reference _configure_optimizer
        :438-443); JSON names resolve via ops.from_config (reference
        _configure_basic_optimizer :466-481)."""
        if self.client_optimizer is not None:
            if not isinstance(self.client_optimizer, optim_mod.Optimizer):
                raise TypeError(
                    "optimizer must be a deepspeed_tpu.ops.Optimizer (pass "
                    "hyperparameters via config for JSON-defined optimizers)")
            self.base_optimizer = self.client_optimizer
        elif self.config.optimizer_name is not None:
            self.base_optimizer = optim_mod.from_config(
                self.config.optimizer_name, self.config.optimizer_params)
        else:
            raise DeepSpeedConfigError(
                "No optimizer: pass one to initialize() or define "
                "'optimizer' in the config json")
        # fp16 + max_grad_norm passthrough becomes the clip threshold
        # (reference deepspeed_config.py:411-415 + FP16 wrapper clip_grad)
        self.clip_grad = float(self.config.gradient_clipping or 0.0)
        op = self.config.optimizer_params or {}
        if self.clip_grad == 0.0 and op.get(C.MAX_GRAD_NORM, 0) > 0:
            self.clip_grad = float(op[C.MAX_GRAD_NORM])

    def _init_optimizer_state(self):
        opt = self.base_optimizer
        if self.zero_flat:
            # moments over the flat partition-sharded master
            flat_spec = self._zero_flat_spec()
            st = opt.init({"flat": self.master_flat})
            put = lambda t: jax.tree_util.tree_map(
                lambda x: jax.device_put(x, self._named(flat_spec)), t)
            self.opt_state = optim_mod.OptimizerState(
                step=jax.device_put(st.step, self._named(P())),
                m=put(st.m), v=put(st.v))
        else:
            st = opt.init(self.master)
            put_tree = lambda t: (jax.tree_util.tree_map(
                lambda x, s: jax.device_put(x, self._named(s)),
                t, self._param_specs) if t is not None else None)
            self.opt_state = optim_mod.OptimizerState(
                step=jax.device_put(st.step, self._named(P())),
                m=put_tree(st.m), v=put_tree(st.v))

    def _configure_lr_scheduler(self):
        if self.config.scheduler_name is not None:
            cls = schedules_mod.SCHEDULES.get(self.config.scheduler_name)
            if cls is None:
                raise DeepSpeedConfigError(
                    f"Unknown scheduler {self.config.scheduler_name!r}")
            self.lr_scheduler = cls(self.optimizer,
                                    **(self.config.scheduler_params or {}))
            if self.client_lr_scheduler is not None:
                logger.warning(
                    "JSON scheduler overrides the client lr_scheduler "
                    "(reference deepspeed_light.py:317-327)")
        else:
            self.lr_scheduler = self.client_lr_scheduler

    def _get_summary_writer(self):
        base = (self.config.tensorboard_output_path
                or os.path.join(os.path.expanduser("~"), "tensorboard"))
        name = self.config.tensorboard_job_name or "DeepSpeedJobName"
        path = os.path.join(base, name)
        try:
            from torch.utils.tensorboard import SummaryWriter
            return SummaryWriter(log_dir=path)
        except Exception:
            logger.warning("tensorboard requested but no writer available")
            return None

    # -------------------------------------------------------- config getters
    # (reference facade deepspeed_light.py:225-315)

    def train_batch_size(self):
        return self.config.train_batch_size

    def train_micro_batch_size_per_gpu(self):
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self):
        return self.config.gradient_accumulation_steps

    def steps_per_print(self):
        return self.config.steps_per_print

    def zero_optimization(self):
        return self.config.zero_enabled

    def fp16_enabled(self):
        return self.config.fp16_enabled

    def bfloat16_enabled(self):
        return self.config.bf16_enabled

    def gradient_clipping(self):
        return self.clip_grad

    def dynamic_loss_scale(self):
        return self._dynamic_loss_scale

    def wall_clock_breakdown(self):
        return self.config.wall_clock_breakdown

    def tensorboard_enabled(self):
        return self.config.tensorboard_enabled

    def sparse_gradients_enabled(self):
        return self.config.sparse_gradients_enabled

    def postscale_gradients(self):
        return not self.config.prescale_gradients

    def gradient_predivide_factor(self):
        return self.config.gradient_predivide_factor

    # ----------------------------------------------------------------- modes

    def train(self):
        """reference deepspeed_light.py:569-574"""
        self.training = True
        return self

    def eval(self):
        """reference deepspeed_light.py:576-581"""
        self.training = False
        return self

    def is_gradient_accumulation_boundary(self):
        """reference deepspeed_light.py:698-706"""
        return (self.micro_steps + 1) % self.gradient_accumulation_steps() == 0

    def _armed(self, label, deadline_scale: float = 1.0):
        """Watchdog-armed context for a blocking call (nullcontext when the
        resilience watchdog is off — docs/resilience.md).
        ``deadline_scale`` stretches the deadline for regions that cover
        several optimizer steps (the K-fused ``train_many`` dispatch)."""
        if self._watchdog is None:
            from contextlib import nullcontext
            return nullcontext()
        return self._watchdog.armed(label, deadline_scale=deadline_scale)

    def resilience_counters(self) -> dict:
        """Process-wide resilience counters (restarts, skipped-NaN steps,
        IO retries, watchdog near-misses/fires) — also exported through
        the telemetry registry as Train/Resilience/* TensorBoard scalars
        (per window when the metric spool is on, per boundary otherwise)."""
        from deepspeed_tpu.resilience import COUNTERS
        return COUNTERS.as_dict()

    @property
    def telemetry(self):
        """The engine's :class:`~deepspeed_tpu.observability.Telemetry`
        (always present; spool/tracer active only when configured —
        docs/observability.md)."""
        return self._telemetry

    @property
    def _spool(self):
        """The active MetricSpool, or None (observability.report_window
        unset) — the gate every spooled code path checks."""
        return self._telemetry.spool

    def flush_telemetry(self, local_only=False, fleet_timeout=None):
        """Synchronously drain the final (possibly partial) metric window
        — THE one deliberate telemetry fence.  Called by the resilience
        driver on a preemption drain, at run completion, and before a
        checkpoint restore, so no window is ever dropped or mixed across
        a restore; safe to call any time (idempotent).  ``local_only``
        skips the bounded cross-host fleet wait (the preemption drain
        uses it before the emergency save — see Telemetry.flush)."""
        self._telemetry.flush(local_only=local_only,
                              fleet_timeout=fleet_timeout)

    # ------------------------------------------------------------- data layer

    def deepspeed_io(self, dataset, batch_size=None, route=C.ROUTE_TRAIN,
                     collate_fn=None, num_local_io_workers=None,
                     data_sampler=None):
        """DataLoader factory (reference deepspeed_light.py:535-567).
        ``num_local_io_workers`` > 0 enables background batch prefetch
        (default: on for the train route, matching the reference's
        2 x device_count worker default)."""
        if batch_size is None:
            batch_size = (self.train_micro_batch_size_per_gpu()
                          * self.dp_world_size)
        if num_local_io_workers is None:
            num_local_io_workers = 1 if route == C.ROUTE_TRAIN else 0
        return DeepSpeedDataLoader(
            dataset,
            batch_size=batch_size,
            mesh=self.mesh,
            route=route,
            collate_fn=collate_fn or self.collate_fn,
            tput_timer=self.tput_timer if route == C.ROUTE_TRAIN else None,
            seed=self.seed,
            num_workers=int(num_local_io_workers),
            # engine-created loaders double-buffer the host->device copy
            # on the producer thread; direct DeepSpeedDataLoader users
            # keep host-numpy batches unless they opt in
            device_prefetch=True)

    # --------------------------------------------------------------- forward

    def _apply_fn(self):
        fn = getattr(self.module, "apply", None)
        return fn if fn is not None else self.module

    def _batch_specs(self, batch):
        # models may declare their own batch shardings (the batch analog of
        # partition_specs) — REQUIRED under context parallelism, where the
        # engine must know which batch dims are sequences (ADVICE r1/r2,
        # VERDICT r3 weak #2: guessing from shapes can silently shard a
        # non-sequence dim over the seq ring)
        spec_fn = getattr(self.module, "batch_specs", None)
        if spec_fn is not None:
            return spec_fn(batch)

        if self.sp_world_size > 1:
            raise DeepSpeedConfigError(
                "context_parallel_size > 1 requires the model to declare "
                "batch_specs(batch) -> pytree[PartitionSpec]: the engine "
                "will not guess which batch dims are sequences (a non-"
                "sequence dim sharded over the seq ring silently corrupts "
                "training). The built-in model family declares this; see "
                "models.transformer.token_batch_specs for the standard "
                "[B, T] token-batch layout.")

        def spec(leaf):
            arr = np.asarray(leaf) if not hasattr(leaf, "ndim") else leaf
            return P(DATA_AXIS) if arr.ndim >= 1 else P()
        return jax.tree_util.tree_map(spec, batch)

    def _loss_axes(self):
        return ((DATA_AXIS, SEQ_AXIS) if self.sp_world_size > 1
                else DATA_AXIS)

    def _grad_stack_specs(self):
        return jax.tree_util.tree_map(lambda s: P(DATA_AXIS, *s),
                                      self._param_specs)

    # ------------------------------------------------- ZeRO-3 grad plumbing
    # Split-API grads cross the shard_map boundary between micro-steps.  A
    # partitioned leaf's grad is already a true global slice (reduced +
    # scattered by the gather transpose) — its out-spec IS the param spec.
    # A replicated leaf's grad is a per-shard partial, represented as a
    # [dp, ...] stack exactly like the non-ZeRO path.

    def _z3_pack(self, grads):
        return jax.tree_util.tree_map(
            lambda g, d: (None if g is None else (g if d >= 0 else g[None])),
            grads, self._zero3_dims, is_leaf=lambda x: x is None)

    def _z3_unpack(self, acc):
        return jax.tree_util.tree_map(
            lambda g, d: (None if g is None else (g if d >= 0 else g[0])),
            acc, self._zero3_dims, is_leaf=lambda x: x is None)

    def _z3_grad_specs(self):
        return jax.tree_util.tree_map(
            lambda s, d: s if d >= 0 else P(DATA_AXIS, *s),
            self._param_specs, self._zero3_dims,
            is_leaf=lambda x: isinstance(x, P))

    @staticmethod
    def _spec_axes(spec) -> set:
        """Mesh axes a PartitionSpec shards any dim over."""
        flat_axes = set()
        for entry in spec:
            if entry is None:
                continue
            if isinstance(entry, tuple):
                flat_axes.update(entry)
            else:
                flat_axes.add(entry)
        return flat_axes

    def _spec_mentions_model(self, spec) -> bool:
        return MODEL_AXIS in self._spec_axes(spec)

    def _psum_model_replicated(self, grads):
        """Megatron rule, generalised to every sharding axis a param can be
        replicated over: grads of leaves NOT sharded over the model (resp.
        pipe) axis need a sum over that axis — each shard's autograd only
        sees its local path (for pipeline: exactly one stage contributes
        each partial, see parallel/pipeline.py).  Sharded leaves are already
        complete.  Identity when the axis size is 1."""
        axes = []
        if self.mp_world_size > 1:
            axes.append(MODEL_AXIS)
        if self.pp_world_size > 1:
            axes.append(PIPE_AXIS)
        if not axes:
            return grads

        def fix(g, s):
            if g is None:
                return None
            sharded = self._spec_axes(s)
            for ax in axes:
                if ax not in sharded:
                    g = jax.lax.psum(g, ax)
            return g

        return jax.tree_util.tree_map(fix, grads, self._param_specs)

    def _global_overflow_and_sqnorm(self, grads):
        """Overflow flag + squared grad norm with sharding-axis agreement.

        The reference MAX-reduces the overflow flag over the model-parallel
        group (deepspeed_utils.py:62-75) and SUM-reduces squared norms with
        replicated-parameter dedup (:100-158) so every TP rank takes the same
        skip/clip decision.  Generalised to the pipe axis: each leaf's
        squared-norm contribution is psum'd over exactly the sharding axes it
        is split over, and replicated leaves (identical grads everywhere
        after ``_psum_model_replicated``) are counted once.  Must be called
        inside shard_map, after the DP reduction.
        """
        axes = []
        if self.mp_world_size > 1:
            axes.append(MODEL_AXIS)
        if self.pp_world_size > 1:
            axes.append(PIPE_AXIS)
        # one accumulator per sharded-axes combination (frozenset key)
        sums: dict = {}
        finite = jnp.asarray(True)

        def visit(g, s):
            nonlocal finite
            if g is None:
                return
            key = frozenset(self._spec_axes(s) & set(axes))
            contrib = jnp.sum(g.astype(jnp.float32) ** 2)
            sums[key] = sums.get(key, jnp.zeros((), jnp.float32)) + contrib
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))

        # pair by tree structure (None-leaf-safe), like _psum_model_replicated
        jax.tree_util.tree_map(visit, grads, self._param_specs,
                               is_leaf=lambda x: x is None)
        sq_total = jnp.zeros((), jnp.float32)
        for key, val in sums.items():
            for ax in key:
                val = jax.lax.psum(val, ax)
            sq_total = sq_total + val
        overflow = jnp.logical_not(finite)
        for ax in axes:
            overflow = comm.overflow_any(overflow, ax)
        return overflow, sq_total

    def _make_loss_and_grads(self):
        """Local (per-shard) loss + fp32 gradient computation shared by the
        split-API ``forward`` and the fused ``train_batch`` program.  Returns
        ``f(params, ls_scale, batch_args) -> (loss_out, grads)`` with grads
        UNSTACKED; must run inside shard_map over the mesh."""
        apply_fn = self._apply_fn()
        gas = float(self.gradient_accumulation_steps())

        def loss_and_grads(params, ls_scale, batch_args):
            def loss_fn(p):
                out = apply_fn(p, *batch_args)
                # multi-output models return a tuple of losses; grads are of
                # the sum (the reference user sums before backward —
                # tests/unit/test_multi_output_model.py), each loss is
                # reported separately
                if isinstance(out, (tuple, list)):
                    total = sum(jnp.asarray(l, jnp.float32) for l in out)
                else:
                    total = jnp.asarray(out, jnp.float32)
                # loss scaling + grad-accum prescale in one multiply
                # (reference _scale_loss :583 + loss_scaler backward :176-178)
                return total * (ls_scale / gas), out
            (_, raw_out), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            loss_out = jax.tree_util.tree_map(
                lambda l: jax.lax.pmean(jnp.asarray(l, jnp.float32),
                                        self._loss_axes()), raw_out)
            grads = self._psum_model_replicated(grads)
            if self.sp_world_size > 1:
                # every param is replicated over the sequence ring; the loss
                # is the pmean of per-shard means, so grads = psum / sp
                sp = float(self.sp_world_size)
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, SEQ_AXIS) / sp, grads)
            if self.mp_world_size > 1:
                # differentiating the per-shard replicated loss is
                # differentiating the SUM of mp identical loss copies: the
                # collective transposes + the replicated-leaf psum above give
                # every leaf exactly mp× the true gradient (uniform across
                # sharded and replicated leaves — verified empirically at
                # mp=2 and mp=4).  Adam/LAMB are scale-invariant so training
                # was unaffected, but norms, clipping, and fp16 overflow
                # thresholds need the true scale (reference grads carry no
                # MP factor, deepspeed_utils.py:100-158).
                mp = float(self.mp_world_size)
                grads = jax.tree_util.tree_map(lambda g: g / mp, grads)
            if self.pp_world_size > 1:
                # same psum-transpose mechanism over the pipe axis: the loss
                # is pipe-uniform (a psum of per-stage partials —
                # pipe_sharded_loss, or its mask_to_last_stage fallback), so
                # every leaf's grad carries a uniform pp factor — verified
                # empirically at pp=2 (a one-step SGD update was exactly
                # 2x the pp=1 reference before this correction)
                pp = float(self.pp_world_size)
                grads = jax.tree_util.tree_map(lambda g: g / pp, grads)
            grads = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            return loss_out, grads

        return loss_and_grads

    def _scatter_grads_local(self, grads, rows: bool = None,
                             across_subgroups: bool = True):
        """Flatten this shard's grad tree and reduce-scatter onto the
        owned flat partition — the ZeRO boundary reduction, also run
        per micro-step under stage 2 (linearity makes per-micro
        scatter-then-accumulate equal accumulate-then-scatter; the
        stage-2 path defers the cross-sub-group psum to the boundary).
        ``rows=True`` wraps the result in the [1, part] per-row layout
        (default: when MP/PP state axes exist)."""
        cfg = self.config
        flat = zero_mod.flatten_tree(grads, self.flat_meta)
        knobs = dict(
            fp32_allreduce=cfg.fp32_allreduce,
            prescale_gradients=cfg.prescale_gradients,
            gradient_predivide_factor=cfg.gradient_predivide_factor,
            partition_group_size=self.zero_pps,
            across_subgroups=across_subgroups)
        bounds = self._comm_buckets()
        if bounds is not None:
            gpart = comm.reduce_scatter_grads_bucketed(
                flat, DATA_AXIS, self.dp_world_size, bounds, **knobs)
        else:
            gpart = comm.reduce_scatter_grads(
                flat, DATA_AXIS, self.dp_world_size, **knobs)
        if rows is None:
            rows = bool(self._zero_state_axes)
        return gpart[None] if rows else gpart

    def _comm_buckets(self):
        """Bucket bounds over the owned flat partition under overlap_comm
        (None = the serial monolithic path, DSTPU_OVERLAP=off)."""
        if not self.overlap_comm or self.flat_meta is None:
            return None
        return comm.bucket_bounds(self.flat_meta.partition,
                                  self.comm_bucket_elems)

    #: built batch-format executables kept per engine (a training run
    #: alternating two MLM formats needs exactly two)
    _BATCH_FN_CACHE_SIZE = 8

    @staticmethod
    def _batch_cache_key(batch):
        """Cache key of a batch's FORMAT: pytree structure + per-leaf
        shape/dtype.  Shapes are included because the shard_map in_specs
        depend on leaf rank (``_batch_specs``: P(data) for arrays, P() for
        scalars) and a model's ``batch_specs`` hook may inspect shapes —
        structure alone would silently reuse wrong specs."""
        flat, treedef = jax.tree_util.tree_flatten(batch)
        return (treedef,
                tuple((tuple(getattr(leaf, "shape", ())),
                       str(getattr(leaf, "dtype", type(leaf).__name__)))
                      for leaf in flat))

    def _cached_batch_fn(self, cache, key, build):
        fn = cache.get(key)
        if fn is None:
            if len(cache) >= self._BATCH_FN_CACHE_SIZE:
                cache.pop(next(iter(cache)))    # FIFO evict the oldest
            fn = build()
            cache[key] = fn
        return fn

    def _checked_batch_specs(self, batch):
        """Batch specs validated against the mesh and the actual leaf
        shapes BEFORE shard_map construction: a mismatch (unknown axis,
        non-divisible batch/sequence dim) raises a ShardSpecError naming
        the offending leaf, spec and axis instead of surfacing later as a
        raw shard_map spec-mismatch crash (the PR-1 failure class)."""
        specs = self._batch_specs(batch)
        graph_lint.validate_specs_or_raise(self.mesh, specs, batch,
                                           where="batch")
        return specs

    def _maybe_graph_lint(self, kind, key, run):
        """Run one lint analysis (once per (program kind, batch format))
        and dispatch it per ``graph_lint.mode``.  Analysis failures warn
        and move on — lint must never take down a healthy build; findings
        in 'error' mode raise GraphLintError."""
        mode = self._graph_lint_mode
        if mode == "off" or (kind, key) in self._linted_keys:
            return
        self._linted_keys.add((kind, key))
        try:
            rep = run()
        except Exception as e:  # pragma: no cover - defensive
            logger.warning("graph lint could not analyze %s: %s", kind, e)
            return
        rep = rep.filtered(self._graph_lint_suppress)
        try:
            graph_lint.dispatch_report(rep, mode, where=kind, log=logger)
        except graph_lint.GraphLintError:
            # stay sticky: a retried build of the same format must lint
            # (and fail) again, not silently proceed to train
            self._linted_keys.discard((kind, key))
            raise

    def run_graph_lint(self, batch, train: bool = True):
        """Analyze the step programs for ``batch``'s format and return the
        :class:`deepspeed_tpu.analysis.Report` (the CLI and test surface;
        ignores ``graph_lint.mode``)."""
        batch = _as_tuple(batch)
        rep = graph_lint.analyze_engine(self, batch, train=train)
        return rep.filtered(self._graph_lint_suppress)

    def plan_capacity(self, batch, train: bool = True, fused: bool = True,
                      profile=None, budget_gb=None,
                      steps_per_dispatch=None):
        """Static capacity plan (per-device peak HBM + bytes on wire) for
        ``batch``'s format — :class:`deepspeed_tpu.analysis.CapacityPlan`.
        No compile, no execution: the programs are traced abstractly.
        ``profile``/``budget_gb`` default to the config ``analysis``
        section; an unset budget falls back to the explicitly chosen
        profile's HBM, and with neither set the plan is report-only (the
        running backend's profile still shapes the memory model).
        ``steps_per_dispatch`` defaults to the configured K: a K>1
        engine's fused plan prices the ACTUAL K-fused ``train_many``
        program (K staged batches of residency, not one)."""
        from deepspeed_tpu.analysis import memplan, profiles
        batch = _as_tuple(batch)
        if profile is None and self.config.analysis_profile:
            profile = profiles.resolve(self.config.analysis_profile)
        if budget_gb is None:
            budget_gb = self.config.analysis_memory_budget_gb
        budget_bytes = (int(float(budget_gb) * (1 << 30))
                        if budget_gb is not None else None)
        if budget_bytes is None and profile is not None:
            # budget falls back to an EXPLICITLY chosen profile's HBM
            # (caller arg or config key).  With neither set, the plan is
            # report-only — plan_engine's own quirk-profile default must
            # never turn into a surprise budget (cpu-8's 4 GiB would gate
            # every real config built on a dev box).
            budget_bytes = profile.hbm_bytes
        return memplan.plan_engine(self, batch, train=train, fused=fused,
                                   profile=profile,
                                   budget_bytes=budget_bytes,
                                   steps_per_dispatch=steps_per_dispatch)

    def run_stability(self, batch, train: bool = True, fused: bool = True):
        """Compile-stability report for ``batch``'s format
        (:mod:`deepspeed_tpu.analysis.stability` — the PR 5/PR 10 hazard
        classes as build-time findings; the CLI and test surface, ignores
        ``analysis.mode``)."""
        from deepspeed_tpu.analysis import stability as stab
        rep = stab.check_engine(self, _as_tuple(batch), fused=fused,
                                train=train)
        return rep.filtered(self._analysis_suppress)

    def plan_dispatch(self, batch, fused: bool = True, profile=None):
        """Static host timeline of one optimizer step for ``batch``'s
        format — :class:`deepspeed_tpu.analysis.DispatchPlan` (program
        dispatches, deliberate fences cross-checked against the
        ``fences.py`` counter, host→device stagings, callback crossings),
        priced via the backend profile's dispatch-overhead constants."""
        from deepspeed_tpu.analysis import dispatchplan, profiles
        if profile is None and self.config.analysis_profile:
            profile = profiles.resolve(self.config.analysis_profile)
        return dispatchplan.plan_engine_dispatch(
            self, _as_tuple(batch), fused=fused, profile=profile)

    def _donate_argnums(self, fused):
        """jit donation of the step programs — the single source both the
        builders (_build_train_batch/_build_step) and the capacity
        planner read, so the planner's output-aliasing model can never
        drift from the compiled donation.  fp32 compute skips donating
        params/master (fused) or master (split): their output buffers may
        alias through the identity cast (see the builder comments).

        ``DSTPU_NO_DONATE=1`` disables donation everywhere — a debugging
        escape hatch (costs one extra copy of the donated state in HBM).
        The concrete case that needed it: some jax 0.4.x XLA-CPU builds
        deserialize donated-buffer executables from the persistent
        compile cache with broken aliasing, so a cache-HIT step silently
        computes garbage — bench.py's resume leg detects the garbage and
        names this switch.  That combination is now auto-avoided: on a
        backend whose profile declares
        ``persistent_cache_donation_unsafe`` (analysis/profiles.py) the
        engine skips donation whenever the persistent compile cache is
        enabled, and the compile-stability pass flags any forced
        re-combination (``stability.donation-cache-quirk``;
        ``DSTPU_FORCE_DONATE=1`` overrides the skip to reproduce)."""
        if os.environ.get("DSTPU_NO_DONATE", "") == "1":
            return ()
        if os.environ.get("DSTPU_FORCE_DONATE", "") != "1":
            from deepspeed_tpu.analysis import profiles as prof_mod
            from deepspeed_tpu.utils import compile_cache
            prof = prof_mod.default_profile()
            if (compile_cache.enabled_dir() is not None and prof is not None
                    and prof.persistent_cache_donation_unsafe):
                if not getattr(self, "_warned_donate_quirk", False):
                    self._warned_donate_quirk = True
                    logger.warning(
                        "donation DISABLED: the persistent compile cache "
                        "is enabled and backend profile '%s' declares "
                        "deserialized donated-buffer executables unsafe "
                        "(the PR 10 garbage-compute incident; "
                        "docs/resilience.md).  DSTPU_FORCE_DONATE=1 "
                        "overrides", prof.name)
                return ()
        if fused:
            return ((2, 3) if self.policy.compute_dtype == jnp.float32
                    else (0, 1, 2, 3))
        return ((1, 2, 3) if self.policy.compute_dtype == jnp.float32
                else (0, 1, 2, 3))

    def _maybe_capacity_plan(self, kind, key, run, batch=None,
                             steps_per_dispatch=1):
        """Run the capacity planner once per (program kind, batch format)
        and dispatch per ``analysis.mode`` through the same
        :func:`~deepspeed_tpu.analysis.dispatch_report` gate as graph
        lint — 'error' mode raises
        :class:`~deepspeed_tpu.analysis.MemoryPlanError` at build time.
        Planner failures warn and move on — the planner must never take
        down a healthy build.  When ``batch`` is given the
        compile-stability and dispatch-cost passes ride the same gate:
        their ``stability.*`` / ``dispatch.*`` findings join the report
        tree (same mode/suppress machinery, docs/analysis.md "Dispatch &
        compile-stability").  ``steps_per_dispatch`` is the GATED
        program's actual K (1 for the ``train_batch`` path even on a
        K-configured engine, the real block size for ``train_many``) —
        the ride-along dispatch plan must price the program being built,
        not the config's intent."""
        mode = self._analysis_mode
        if mode == "off" or (kind, key) in self._planned_keys:
            return
        self._planned_keys.add((kind, key))
        try:
            plan = run()
            if kind in ("train_batch", "train_many"):
                # planner handoff: the telemetry drift columns reuse THIS
                # plan instead of re-tracing the fused program
                self._telemetry.note_fused_plan(plan)
            rep = plan.to_report(subject=kind)
        except Exception as e:  # pragma: no cover - defensive
            logger.warning("capacity plan could not analyze %s: %s",
                           kind, e)
            return
        if batch is not None:
            try:
                from deepspeed_tpu.analysis import dispatchplan
                from deepspeed_tpu.analysis import stability as stab
                train = kind != "eval"
                fused = kind in ("train_batch", "train_many")
                rep.extend(stab.check_engine(self, batch, fused=fused,
                                             train=train))
                if train:
                    dplan = dispatchplan.plan_engine_dispatch(
                        self, batch, fused=fused, profile=plan.profile,
                        steps_per_dispatch=steps_per_dispatch)
                    rep.extend(dplan.to_report())
            except Exception as e:  # pragma: no cover - defensive
                logger.warning("stability/dispatch analysis could not "
                               "run for %s: %s", kind, e)
        rep = rep.filtered(self._analysis_suppress)
        try:
            graph_lint.dispatch_report(
                rep, mode, where=kind, log=logger, label="capacity plan",
                info_hint="engine.plan_capacity(batch).format_table() "
                          "shows the plan",
                error_cls=graph_lint.MemoryPlanError)
        except graph_lint.GraphLintError:
            # sticky like graph lint: a retried build must plan (and
            # fail) again, not silently proceed to an OOM
            self._planned_keys.discard((kind, key))
            raise

    def _ensure_fwdbwd(self, batch, key=None):
        """Build-or-fetch the fused fwd+bwd program for this batch format
        (shared by forward() and the graph-lint tracer)."""
        if key is None:
            key = self._batch_cache_key(batch)
        if self._fwdbwd_fn is None or self._fwdbwd_key != key:
            self._fwdbwd_fn = self._cached_batch_fn(
                self._fwdbwd_fns, key,
                lambda: self._build_fwdbwd(batch))
            self._fwdbwd_key = key
            self._loss_treedef = self._loss_treedefs.get(key)
        return self._fwdbwd_fn

    def _ensure_eval(self, batch, key=None):
        if key is None:
            key = self._batch_cache_key(batch)
        if self._eval_fn is None or self._eval_key != key:
            self._eval_fn = self._cached_batch_fn(
                self._eval_fns, key, lambda: self._build_eval(batch))
            self._eval_key = key
        return self._eval_fn

    def _build_fwdbwd(self, batch):
        loss_and_grads = self._make_loss_and_grads()
        stage2 = self.zero_stage == 2
        zero3 = self.zero3

        def local(params, ls_scale, batch_args):
            loss_out, grads = loss_and_grads(params, ls_scale, batch_args)
            if stage2:
                return loss_out, self._scatter_grads_local(
                    grads, across_subgroups=False)
            if zero3:
                return loss_out, self._z3_pack(grads)
            return loss_out, jax.tree_util.tree_map(
                lambda g: g[None], grads)

        fn = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(self._param_specs, P(), self._checked_batch_specs(batch)),
            out_specs=(P(), self._zero_flat_spec() if stage2
                       else self._z3_grad_specs() if zero3
                       else self._grad_stack_specs()),
            check_vma=False)
        return jax.jit(fn)

    def _build_eval(self, batch):
        apply_fn = self._apply_fn()

        def local(params, batch_args):
            out = apply_fn(params, *batch_args)
            return jax.tree_util.tree_map(
                lambda l: jax.lax.pmean(jnp.asarray(l, jnp.float32),
                                        self._loss_axes()), out)

        fn = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(self._param_specs, self._checked_batch_specs(batch)),
            out_specs=P(),
            check_vma=False)
        return jax.jit(fn)

    def _force_live_pendings(self):
        """Execute every deferred forward whose loss object is still
        reachable, before engine state (params / loss scale) mutates under
        it — so its values come out as if it had run eagerly at issue time.
        Pendings whose loss objects are already unreachable are dropped
        without ever running."""
        for ref in self._pending_refs:
            p = ref()
            if p is not None and not p.forced:
                p.force()
        self._pending_refs = []
        self._pending = None

    def forward(self, *inputs):
        """Compute loss (and, in train mode, record the micro-batch for the
        deferred fused fwd+bwd program — see _PendingStep).
        Reference deepspeed_light.py:603-623."""
        wcb = self.wall_clock_breakdown()
        if wcb:
            self.timers(FORWARD_TIMER).start()
        batch = inputs
        if self.training:
            # the superseded pending stays executable through the
            # _DeferredLoss the caller may hold; it is forced lazily or at
            # the next param mutation (an eval-mode forward leaves the live
            # train pending in place — backward() may still consume it)
            self._pending = None
            key = self._batch_cache_key(batch)
            self._ensure_fwdbwd(batch, key=key)
            self._maybe_graph_lint(
                "train", key,
                lambda: graph_lint.analyze_engine(self, batch, train=True))
            self._maybe_capacity_plan(
                "train", key,
                lambda: self.plan_capacity(batch, train=True, fused=False),
                batch=batch)
            if self._loss_treedef is None:
                loss_shape, _ = jax.eval_shape(
                    self._fwdbwd_fn, self.params,
                    self.loss_scale_state.cur_scale, batch)
                self._loss_treedef = jax.tree_util.tree_structure(loss_shape)
                self._loss_treedefs[key] = self._loss_treedef
            self._pending = _PendingStep(self, batch)
            self._pending_refs = [r for r in self._pending_refs
                                  if r() is not None]
            self._pending_refs.append(weakref.ref(self._pending))
            n = self._loss_treedef.num_leaves
            loss = jax.tree_util.tree_unflatten(
                self._loss_treedef,
                [_DeferredLoss(self._pending, i) for i in range(n)])
            if wcb:
                # dispatch-only under the fused design; the model compute is
                # timed by backward_inner (docs/features.md "wall-clock
                # breakdown")
                self.timers(FORWARD_TIMER).stop()
        else:
            # eval time must not be billed to the next training-throughput
            # report window (timer.py window accounting)
            self.tput_timer.discard_window()
            key = self._batch_cache_key(batch)
            self._ensure_eval(batch, key=key)
            self._maybe_graph_lint(
                "eval", key,
                lambda: graph_lint.analyze_engine(self, batch, train=False))
            self._maybe_capacity_plan(
                "eval", key,
                lambda: self.plan_capacity(batch, train=False),
                batch=batch)
            with _annotate("eval"):
                loss = self._eval_fn(self.params, batch)
            self._last_loss = loss
            if wcb:
                self.timers(FORWARD_TIMER).stop(sync_on=loss)
        return loss

    __call__ = forward

    # --------------------------------------------------------------- backward

    def backward(self, loss=None, allreduce_gradients=True):
        """Accumulate the cached local gradients (reference
        deepspeed_light.py:629-696; the collective is deferred to the
        boundary step — same bytes on the wire as the reference's
        boundary-only allreduce)."""
        assert self.training, "backward() requires train mode"
        if not allreduce_gradients:
            # Reference uses this to let an external MP framework own the
            # reduction; under single-controller SPMD there is no per-rank
            # user code to hand the grads to, so be loud instead of silently
            # reducing twice.
            raise NotImplementedError(
                "allreduce_gradients=False is not supported under SPMD: the "
                "boundary step owns the gradient reduction")
        assert self._pending is not None or self._cached_grads is not None, \
            "backward() must follow a forward() in train mode"
        wcb = self.wall_clock_breakdown()
        if wcb:
            self.timers(BACKWARD_TIMER).start()

        if self._pending is not None:
            # run the deferred fused fwd+bwd program (one program per micro
            # step; reference's backward_inner span = the model bwd compute)
            if wcb:
                self.timers(BACKWARD_INNER_TIMER).start()
            with self._armed("backward (fused fwd+bwd)"), _annotate("fwdbwd"):
                self._pending.force()
            if wcb:
                self.timers(BACKWARD_INNER_TIMER).stop(
                    sync_on=self._pending.loss)
            self._pending = None

        if self.summary_writer is not None and self.is_gradient_accumulation_boundary():
            self.sample_count = (self.train_micro_batch_size_per_gpu()
                                 * self.dp_world_size * (self.micro_steps + 1))
            if self._last_loss is not None and self._spool is None:
                # float(l) is a host fence; with the metric spool on the
                # loss rides the device ring buffer and reaches
                # TensorBoard at the window drain instead
                scalar = sum(float(l) for l in
                             jax.tree_util.tree_leaves(self._last_loss))
                obs_fences.count_fence()
                self.summary_writer.add_scalar("Train/Samples/train_loss",
                                               scalar, self.sample_count)

        if wcb:
            # the cross-DP reduction itself is deferred to the boundary step
            # program (same bytes on the wire as the reference's
            # boundary-only allreduce); this span covers the on-device
            # micro-step accumulate — see docs/features.md
            self.timers(BACKWARD_REDUCE_TIMER).start()
        if self._acc is None:
            self._acc = self._cached_grads
        else:
            self._acc = jax.tree_util.tree_map(jnp.add, self._acc,
                                               self._cached_grads)
        self._cached_grads = None
        if wcb:
            self.timers(BACKWARD_REDUCE_TIMER).stop(sync_on=self._acc)
            self.timers(BACKWARD_TIMER).stop()
        # the reference returns the grad-accum-scaled loss from backward
        # (asserted by tests/unit/test_multi_output_model.py)
        if loss is None:
            return None
        gas = float(self.gradient_accumulation_steps())
        return jax.tree_util.tree_map(lambda l: l / gas, _resolve_loss(loss))

    # ------------------------------------------------------------------- step

    def _make_step_local(self):
        """The boundary update on local shards: DP reduction → overflow/norm
        agreement → (ZeRO-partitioned or replicated) optimizer update →
        loss-scale FSM.  Shared by the split-API ``step`` and the fused
        ``train_batch`` program; must run inside shard_map over the mesh.
        Takes the UNSTACKED local grad tree."""
        opt = self.base_optimizer
        cfg = self.config
        world = self.dp_world_size
        fp16 = cfg.fp16_enabled
        # skip-on-non-finite guard: always under fp16 (the loss-scale FSM
        # needs the skip), and under ANY precision when the resilience NaN
        # sentinel is on — a non-finite gradient then leaves master/moments
        # untouched instead of poisoning the run (docs/resilience.md)
        skip_bad = fp16 or self._nan_sentinel
        clip = self.clip_grad
        variant = self._ls_variant
        zero = self.zero_flat
        zero3 = self.zero3
        z3_dims = self._zero3_dims
        param_specs = self._param_specs
        stage2 = self.zero_stage == 2
        mp = self.mp_world_size
        state_axes = list(self._zero_state_axes)
        zero_2d = zero and bool(state_axes)
        pps = self.zero_pps
        cdt = self.policy.compute_dtype
        meta = self.flat_meta
        sparse_flags = self._sparse_flags
        group_ids = self._group_ids
        multi_group = len(self._group_defs) > 1
        bounds = self._comm_buckets()      # None = serial boundary
        bucket_elems = (self.comm_bucket_elems if self.overlap_comm
                        else None)

        def step_local(master, opt_state, grads, ls_state, hypers,
                       normw, gids):
            # hypers arrive as ONE stacked [4, G] array (lr/b1/b2/wd rows,
            # one column per param group) — a single host→device staging
            # per boundary instead of four (and zero when the scheduler
            # didn't move, engine._current_hypers caches); expand to
            # per-leaf trees when groups exist (per-ELEMENT vectors over
            # the flat partition under ZeRO), else the plain scalars
            lr, b1, b2, wd = hypers[0], hypers[1], hypers[2], hypers[3]
            if not multi_group:
                lr, b1, b2, wd = lr[0], b1[0], b2[0], wd[0]
            elif zero:
                expand = lambda vec: {"flat": vec[gids]}
                lr, b1, b2, wd = expand(lr), expand(b1), expand(b2), expand(wd)
            else:
                expand = lambda vec: jax.tree_util.tree_map(
                    lambda gid: vec[gid], group_ids)
                lr, b1, b2, wd = expand(lr), expand(b1), expand(b2), expand(wd)
            if zero:
                if zero_2d:
                    # [1, part] local blocks of the [mp, local_padded] layout
                    master_1d = master[0]
                    opt_in = optim_mod.OptimizerState(
                        step=opt_state.step,
                        m=jax.tree_util.tree_map(lambda x: x[0], opt_state.m),
                        v=(jax.tree_util.tree_map(lambda x: x[0], opt_state.v)
                           if opt_state.v is not None else None))
                else:
                    master_1d, opt_in = master, opt_state
                if stage2:
                    # grads arrive reduced+scattered within each sub-group
                    # (per-micro, inside the accumulation loop); finish
                    # the single deferred cross-sub-group psum here
                    gpart = grads[0] if zero_2d else grads
                    gpart = comm.finish_subgroup_reduce(
                        gpart, DATA_AXIS, world, pps)
                else:
                    gpart = self._scatter_grads_local(grads, rows=False)
                overflow = comm.overflow_any(
                    jnp.logical_not(jnp.all(jnp.isfinite(gpart))), DATA_AXIS)
                if zero_2d:
                    # every stage/model shard must take the same skip
                    # decision (reference MP-group MAX-reduce,
                    # deepspeed_utils.py:62-75, generalized to the pipe axis)
                    for ax, _ in state_axes:
                        overflow = comm.overflow_any(overflow, ax)
                    # norm with replicated-leaf dedup: normw weights each
                    # element 1 (sharded) or 1/size per replicating axis, so
                    # the state-axes psum counts every parameter exactly
                    # once (reference deepspeed_utils.py:100-158).  With
                    # sub-groups (pps < dp) partitions replicate across the
                    # dp/pps blocks — sum within ONE sub-group only.
                    sq = jnp.sum(normw * gpart.astype(jnp.float32) ** 2)
                    if pps == world:
                        sq = jax.lax.psum(sq, DATA_AXIS)
                    else:
                        within, _ = comm.subgroup_index_groups(world, pps)
                        sq = jax.lax.psum(sq, DATA_AXIS,
                                          axis_index_groups=within)
                    for ax, _ in state_axes:
                        sq = jax.lax.psum(sq, ax)
                elif pps == world:
                    sq = jax.lax.psum(
                        jnp.sum(gpart.astype(jnp.float32) ** 2), DATA_AXIS)
                else:
                    # sub-partitions replicate across the dp/pps sub-groups;
                    # sum within ONE sub-group to count each element once
                    within, _ = comm.subgroup_index_groups(world, pps)
                    sq = jax.lax.psum(
                        jnp.sum(gpart.astype(jnp.float32) ** 2), DATA_AXIS,
                        axis_index_groups=within)
                total_norm = jnp.sqrt(sq)
                combined = prec.combined_unscale_and_clip_factor(
                    total_norm, ls_state, clip) if fp16 else (
                    prec.combined_unscale_and_clip_factor(
                        total_norm, prec.static_loss_scale_state(1.0), clip)
                    if clip > 0 else 1.0)
                def upd_seg(mseg, gseg, oin, lr_, b1_, b2_, wd_):
                    """Shard-local update + skip-on-overflow on one flat
                    segment (the whole partition, or one overlap bucket —
                    elementwise, so the tiling cannot change the values).
                    skip-on-overflow: reference zero_optimizer.py:349-359;
                    bf16/fp32 have no loss-scale recovery loop — a NaN
                    propagates visibly, like the reference fp32 path."""
                    new_p, new_o = opt.update(
                        {"flat": mseg}, {"flat": gseg}, oin,
                        lr=lr_, beta1=b1_, beta2=b2_, weight_decay=wd_,
                        combined_scale=combined)
                    nm = new_p["flat"]
                    if skip_bad:
                        nm = jnp.where(overflow, mseg, nm)
                        new_o = jax.tree_util.tree_map(
                            lambda new, old: jnp.where(overflow, old, new),
                            new_o, oin)
                    return nm, new_o

                hy_seg = (lambda h, s, e:
                          {"flat": h["flat"][s:e]} if isinstance(h, dict)
                          else h)
                if bounds is not None and len(bounds) > 1:
                    # software-pipelined boundary (overlap_comm): each
                    # bucket's update → all-gather chain is data-independent
                    # of every other bucket's, so XLA's async collectives
                    # run gather(i-1) ∥ update(i) instead of one monolithic
                    # update followed by one monolithic gather
                    segs, blocks = [], []
                    new_step = opt_in.step
                    for s, e in bounds:
                        oin = optim_mod.OptimizerState(
                            step=opt_in.step,
                            m=(None if opt_in.m is None
                               else {"flat": opt_in.m["flat"][s:e]}),
                            v=(None if opt_in.v is None
                               else {"flat": opt_in.v["flat"][s:e]}))
                        nm, new_o = upd_seg(
                            master_1d[s:e], gpart[s:e], oin,
                            hy_seg(lr, s, e), hy_seg(b1, s, e),
                            hy_seg(b2, s, e), hy_seg(wd, s, e))
                        segs.append((nm, new_o))
                        # weight all-gather, per bucket (reference
                        # zero_optimizer.py:397-432)
                        blocks.append(comm.allgather_partition_bucket(
                            nm.astype(jnp.float32), DATA_AXIS,
                            world_size=world, partition_group_size=pps))
                        new_step = new_o.step
                    new_master = jnp.concatenate([nm for nm, _ in segs])
                    cat = lambda pick: {"flat": jnp.concatenate(
                        [pick(o) for _, o in segs])}
                    new_opt = optim_mod.OptimizerState(
                        step=new_step,
                        m=(None if opt_in.m is None
                           else cat(lambda o: o.m["flat"])),
                        v=(None if opt_in.v is None
                           else cat(lambda o: o.v["flat"])))
                    flat_full = jnp.reshape(
                        jnp.concatenate(blocks, axis=1), (-1,))
                else:
                    new_master, new_opt = upd_seg(master_1d, gpart, opt_in,
                                                  lr, b1, b2, wd)
                    # weight all-gather (reference zero_optimizer.py:397-432)
                    flat_full = comm.allgather_params(
                        new_master.astype(jnp.float32), DATA_AXIS,
                        world_size=world, partition_group_size=pps)
                params = zero_mod.unflatten_tree(flat_full, meta, dtype=cdt)
                if zero_2d:
                    new_master = new_master[None]
                    new_opt = optim_mod.OptimizerState(
                        step=new_opt.step,
                        m=jax.tree_util.tree_map(lambda x: x[None], new_opt.m),
                        v=(jax.tree_util.tree_map(lambda x: x[None], new_opt.v)
                           if new_opt.v is not None else None))
            elif zero3:
                # ZeRO-3 (zero3.py): partitioned leaves arrive REDUCED and
                # SCATTERED (the layer gather's autodiff transpose is a
                # tiled psum_scatter over 'data') — finish their averaging
                # with 1/world; replicated leaves are plain local grads and
                # psum with the full knob semantics
                knobs = dict(
                    fp32_allreduce=cfg.fp32_allreduce,
                    prescale_gradients=cfg.prescale_gradients,
                    gradient_predivide_factor=cfg.gradient_predivide_factor)

                def reduce_leaf(g, d):
                    if g is None:
                        return None
                    if d >= 0:
                        return g / world
                    return comm.allreduce_grads(g, DATA_AXIS, world,
                                                bucket_elems=bucket_elems,
                                                **knobs)

                grads = jax.tree_util.tree_map(
                    reduce_leaf, grads, z3_dims,
                    is_leaf=lambda x: x is None)
                # norm/overflow: partitioned shards are disjoint over DP
                # (weight 1, psum over data); replicated leaves identical
                # over DP (1/dp); model/pipe dedup per the leaf spec —
                # every shard takes the same skip/clip decision (reference
                # deepspeed_utils.py:62-75, 100-158)
                sq, finite = zero3_mod.local_sqnorm_and_finite(
                    grads, z3_dims, param_specs, world, state_axes)
                overflow = comm.overflow_any(jnp.logical_not(finite),
                                             DATA_AXIS)
                sq = jax.lax.psum(sq, DATA_AXIS)
                for ax, _ in state_axes:
                    overflow = comm.overflow_any(overflow, ax)
                    sq = jax.lax.psum(sq, ax)
                total_norm = jnp.sqrt(sq)
                combined = prec.combined_unscale_and_clip_factor(
                    total_norm, ls_state, clip) if fp16 else (
                    prec.combined_unscale_and_clip_factor(
                        total_norm, prec.static_loss_scale_state(1.0), clip)
                    if clip > 0 else 1.0)
                # elementwise Adam-family update directly on the local
                # (master, moment, grad) shards — the partitioning is
                # invisible to the optimizer
                new_master, new_opt = opt.update(
                    master, grads, opt_state,
                    lr=lr, beta1=b1, beta2=b2, weight_decay=wd,
                    combined_scale=combined)
                if skip_bad:
                    new_master = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(overflow, old, new),
                        new_master, master)
                    new_opt = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(overflow, old, new),
                        new_opt, opt_state)
                # NO weight all-gather: params persist partitioned; the
                # next step's layer gathers re-materialise them on use
                params = jax.tree_util.tree_map(
                    lambda m: m.astype(cdt), new_master)
            else:
                knobs = dict(
                    fp32_allreduce=cfg.fp32_allreduce,
                    prescale_gradients=cfg.prescale_gradients,
                    gradient_predivide_factor=cfg.gradient_predivide_factor)
                if sparse_flags is None:
                    grads = comm.allreduce_grads(grads, DATA_AXIS, world,
                                                 bucket_elems=bucket_elems,
                                                 **knobs)
                else:
                    # marked leaves (embeddings) reduce as gathered
                    # (indices, values) with a dense-psum fallback
                    # (reference sparse_allreduce,
                    # deepspeed_light.py:884-940)
                    from deepspeed_tpu import sparse as sparse_mod

                    def reduce_one(g, flag):
                        if g is None:
                            return None
                        if flag:
                            return sparse_mod.sparse_psum(
                                g, DATA_AXIS, world,
                                cfg.sparse_gradients_max_rows, **knobs)
                        return comm.allreduce_grads(g, DATA_AXIS, world,
                                                    bucket_elems=bucket_elems,
                                                    **knobs)

                    grads = jax.tree_util.tree_map(
                        reduce_one, grads, sparse_flags,
                        is_leaf=lambda x: x is None)
                overflow, sq = self._global_overflow_and_sqnorm(grads)
                total_norm = jnp.sqrt(sq)
                combined = prec.combined_unscale_and_clip_factor(
                    total_norm, ls_state, clip) if fp16 else (
                    prec.combined_unscale_and_clip_factor(
                        total_norm, prec.static_loss_scale_state(1.0), clip)
                    if clip > 0 else 1.0)
                new_master, new_opt = opt.update(
                    master, grads, opt_state,
                    lr=lr, beta1=b1, beta2=b2, weight_decay=wd,
                    combined_scale=combined)
                if skip_bad:
                    new_master = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(overflow, old, new),
                        new_master, master)
                    new_opt = jax.tree_util.tree_map(
                        lambda new, old: jnp.where(overflow, old, new),
                        new_opt, opt_state)
                params = jax.tree_util.tree_map(
                    lambda m: m.astype(cdt), new_master)

            new_ls = (prec.update_loss_scale(ls_state, overflow,
                                             variant=variant)
                      if fp16 else ls_state)
            return (params, new_master, new_opt, new_ls,
                    jnp.asarray(overflow, jnp.bool_),
                    total_norm)

        return step_local

    def _zero_flat_spec(self):
        """Sharding of the ZeRO flat master/moment buffers: [S, local_padded]
        over ((pipe, model), data) when pipeline/tensor parallel, 1-D over
        data otherwise."""
        if self._zero_state_axes:
            return P(tuple(name for name, _ in self._zero_state_axes),
                     DATA_AXIS)
        return P(DATA_AXIS)

    def _step_specs(self):
        """(master_spec, opt_spec, ls_spec) partition specs for the update.
        At ZeRO-3 the per-leaf ``_param_specs`` (data-augmented) serve as
        the master/moment specs — the non-flat ``else`` arms below."""
        zero = self.zero_flat
        if zero:
            flat_spec = self._zero_flat_spec()
        master_spec = (flat_spec if zero else self._param_specs)
        opt_spec = optim_mod.OptimizerState(
            step=P(),
            m=(flat_spec if zero else self._param_specs)
            if self.opt_state.m is not None else None,
            v=(flat_spec if zero else self._param_specs)
            if self.opt_state.v is not None else None)
        ls_spec = jax.tree_util.tree_map(lambda _: P(), self.loss_scale_state)
        return master_spec, opt_spec, ls_spec

    def _build_step(self):
        step_local = self._make_step_local()
        stage2 = self.zero_stage == 2
        zero3 = self.zero3

        def local(master, opt_state, acc, ls_state, hypers, normw,
                  gids):
            if stage2:
                # acc IS the accumulated flat partition (ZeRO-2)
                grads = acc
            elif zero3:
                # partitioned leaves arrive as true local slices,
                # replicated leaves as [1, ...] per-shard stacks
                grads = self._z3_unpack(acc)
            else:
                # acc leaves arrive as [1, ...] local slices
                grads = jax.tree_util.tree_map(lambda g: g[0], acc)
            return step_local(master, opt_state, grads, ls_state, hypers,
                              normw, gids)

        master_spec, opt_spec, ls_spec = self._step_specs()
        fn = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(master_spec, opt_spec,
                      self._zero_flat_spec() if stage2
                      else self._z3_grad_specs() if zero3
                      else self._grad_stack_specs(),
                      ls_spec, P(), P(DATA_AXIS),
                      P(DATA_AXIS)),
            out_specs=(self._param_specs, master_spec, opt_spec, ls_spec,
                       P(), P()),
            check_vma=False)
        # donate master/opt-state/grad-acc/loss-scale: without donation XLA
        # double-buffers every optimizer buffer each step.  In fp32 mode the
        # output params is an identity cast of the output master, which XLA
        # may alias — donating master would then invalidate the buffer
        # self.params still references; skip it there (same guard as
        # _build_train_batch).
        return jax.jit(fn, donate_argnums=self._donate_argnums(fused=False))

    def dump_state(self):
        """Config + engine-state + memory dump (reference dump_state,
        deepspeed_light.py:183-185 + deepspeed_config.py:373-385)."""
        self.config.print("DeepSpeedTpuEngine config")
        logger.info(
            "engine state: mesh=%s (dp=%d mp=%d sp=%d) zero=%s "
            "compute_dtype=%s optimizer=%s groups=%d",
            dict(self.mesh.shape), self.dp_world_size, self.mp_world_size,
            self.sp_world_size, self.zero_enabled,
            jnp.dtype(self.policy.compute_dtype).name,
            self.base_optimizer.name, len(self._group_defs))
        logger.info("steps: global=%d micro=%d skipped=%d",
                    self.global_steps, self.micro_steps, self.skipped_steps)
        mem = SynchronizedWallClockTimer.memory_usage()
        if mem:
            logger.info("memory: %s", mem)

    def memory_estimate(self) -> dict:
        """Per-device BYTE estimate of persistent engine state — the
        programmatic twin of the measured envelope
        (tests/test_zero_memory.py; docs/features.md table).  Modern
        DeepSpeed's ZeRO memory-estimator analog, exact for this engine:

          params           compute-dtype copy, replicated over data
          optimizer_state  fp32 master + moments; /min(dp, pps) under
                           ZeRO, full-size otherwise
          grad_accumulator fp32; the ZeRO-2 partition, or a full tree
                           (only held between backward() and step() on
                           the split API / inside the fused scan)
        """
        cdt_bytes = jnp.dtype(self.policy.compute_dtype).itemsize
        n_params = sum(int(l.size)
                       for l in jax.tree_util.tree_leaves(self.params))
        # per-device parameter elements: every sharded dim divides — under
        # ZeRO-3 self._param_specs include the data axis, so this IS the
        # 1/dp partitioned count (total is padding-independent, so the dp
        # argument is moot)
        local_params = zero_mod.make_local_flat_meta(
            self.params, self._param_specs, dict(self.mesh.shape), 1).total
        moments = ((self.opt_state.m is not None)
                   + (self.opt_state.v is not None))
        if self.zero_flat:
            opt_state = 4 * (1 + moments) * self.flat_meta.padded \
                // self.zero_pps
            acc = (4 * self.flat_meta.padded // self.zero_pps
                   if self.zero_stage >= 2 else 4 * local_params)
        else:
            # replicated — or ZeRO-3, where local_params already carries
            # the data-axis division for params, masters, moments AND the
            # grad accumulator alike
            opt_state = 4 * (1 + moments) * local_params
            acc = 4 * local_params
        return {
            "params_bytes": cdt_bytes * local_params,
            "optimizer_state_bytes": opt_state,
            "grad_accumulator_bytes": acc,
            "total_persistent_bytes": cdt_bytes * local_params + opt_state,
            "n_params": n_params,
            "zero_stage": self.zero_stage,
        }

    # ------------------------------------------------------------- profiling

    def start_profile(self, output_path: Optional[str] = None):
        """Start a jax.profiler trace (TensorBoard/Perfetto-viewable) — the
        TPU tracing analog of the reference's wall_clock_breakdown spans
        (SURVEY §5).  Also driven automatically by the ``profile`` config
        section over a [start_step, end_step) window."""
        if self._profiling:
            return
        path = output_path or self.config.profile_output_path
        jax.profiler.start_trace(path)
        self._profiling = True
        from deepspeed_tpu.observability import tracing as obs_tracing
        obs_tracing.note_capture_active(True)
        # flush the trace even if training ends inside the window; register
        # exactly once (a bound-method atexit handler pins the engine — one
        # is tolerable, one per start/stop cycle is a leak)
        if not getattr(self, "_profile_atexit", False):
            import atexit
            atexit.register(self.stop_profile)
            self._profile_atexit = True
        logger.info("jax.profiler trace started -> %s", path)

    def stop_profile(self):
        if not self._profiling:
            return
        from deepspeed_tpu.observability import tracing as obs_tracing
        obs_tracing.note_capture_active(False)
        jax.profiler.stop_trace()
        self._profiling = False
        logger.info("jax.profiler trace stopped")

    def _profile_window(self):
        cfg = self.config
        if not cfg.profile_enabled:
            return
        # range (not equality) checks: a checkpoint resume can land past
        # start_step and must still trace the remainder of the window
        if (not self._profiling
                and cfg.profile_start_step <= self.global_steps
                < cfg.profile_end_step):
            self.start_profile()
        elif self._profiling and self.global_steps >= cfg.profile_end_step:
            self.stop_profile()

    def _post_boundary_bookkeeping(self, overflow):
        """Counters, overflow-aware LR step, progress + TB reporting after a
        boundary update (reference deepspeed_light.py:723-788)."""
        self.global_steps += 1
        # post-mortem breadcrumb: which boundary this process last
        # completed (flight recorder — who was at which step when the
        # fleet diverged; docs/observability.md "Flight recorder")
        _flightrec.record("boundary", step=self.global_steps)
        self._profile_window()
        self._telemetry.maybe_trace(self.global_steps)
        skip_contract = self.config.fp16_enabled or self._nan_sentinel
        defer = (skip_contract
                 and self._telemetry.defers_overflow(self))
        if skip_contract and not defer:
            # host sync, boundary-only.  With the resilience NaN sentinel
            # the bf16/fp32 paths honour the same skip contract as fp16:
            # overflow => untouched master/moments, no scheduler step.
            # With the metric spool on this read is DEFERRED to the window
            # drain (the flag rides the ring buffer) — except under the
            # scheduler exception defers_overflow documents.
            self.overflow = bool(obs_fences.read_scalar(overflow))
        else:
            # statically finite, or deferred: the drain settles
            # skipped_steps/overflow retroactively (Telemetry._on_window)
            self.overflow = False
        if self.overflow:
            self.skipped_steps += 1
            if self._nan_sentinel and not self.config.fp16_enabled:
                # under fp16 an overflow is routine loss-scale FSM
                # calibration (already counted in skipped_steps and logged
                # by the scaler) — nan_skips tracks only skips the
                # SENTINEL caused, or the observability signal drowns in
                # scale-search noise
                from deepspeed_tpu.resilience import COUNTERS
                COUNTERS.nan_skips += 1
                logger.warning(
                    "resilience: non-finite gradients at global step %d — "
                    "optimizer boundary skipped (nan_sentinel)",
                    self.global_steps)
        elif self.lr_scheduler is not None:
            # under deferral a skip contract never coexists with a
            # scheduler (defers_overflow retains the read in that case),
            # so stepping here is exactly the legacy semantics
            self.lr_scheduler.step()

        if self.global_steps % self.steps_per_print() == 0:
            self._report_progress(self.global_steps)

        if self.summary_writer is not None:
            if not self._telemetry.spool_active:
                # legacy cadence: per-boundary scalars through the ONE
                # registry (lr + resilience/compile-cache counters — the
                # dedup of the three historical write loops).  With the
                # spool on, export rides the window drain instead.
                self._telemetry.emit_boundary_scalars(
                    getattr(self, "sample_count", self.global_steps))

    def _current_hypers(self):
        """Live hyperparameters from the facade groups as ONE stacked
        [4, G] fp32 device array (rows lr/beta1/beta2/weight_decay, one
        column per param group): LR schedules may have written different
        LRs into each group, OneCycle cycles per-group betas
        (lr_schedules.py), and decay-excluded groups carry weight_decay=0
        (the published BERT recipe, reference
        docs/_tutorials/bert-pretraining.md:289-305).

        Staging is CACHED on the host values: the four per-step
        ``jnp.asarray`` transfers the old tuple form paid at EVERY
        boundary (part of the fixed per-step dispatch cost gas=8 cannot
        amortize, bench_mfu_breakdown.json
        ``per_step_fixed_lamb_dispatch``) collapse to one transfer when a
        scheduler moved a value and ZERO when none did (constant-LR runs,
        and every run's beta/wd rows)."""
        key = self._hyper_rows_host()
        if key != self._hyper_key:
            rows = np.asarray(
                [[k[0] for k in key], [k[1] for k in key],
                 [k[2] for k in key], [k[3] for k in key]], np.float32)
            self._hyper_dev = jnp.asarray(rows)
            self._hyper_key = key
        return self._hyper_dev

    def step(self):
        """Optimizer boundary step (reference deepspeed_light.py:709-807)."""
        assert self.training, "step() requires train mode"
        wcb = self.wall_clock_breakdown()
        if wcb:
            self.timers(STEP_TIMER).start()

        if self.is_gradient_accumulation_boundary():
            assert self._acc is not None, "step() with no accumulated grads"
            self._force_live_pendings()  # about to mutate params
            if self._step_fn is None:
                self._step_fn = self._build_step()
            # armed through the boundary's host sync (the overflow read in
            # bookkeeping): a hung boundary collective surfaces there, not
            # at the async dispatch
            with self._armed("optimizer boundary step"), \
                    _annotate("boundary"):
                from deepspeed_tpu.resilience import chaos as _chaos
                # same host-side pre-dispatch clock as train_batch (the
                # fleet straggler signal; see docs/observability.md)
                _t0 = time.monotonic()
                _flightrec.record("arm", label="boundary",
                                  step=self.global_steps)
                _chaos.maybe_stall(self.global_steps)
                spool = self._spool
                if spool is not None:
                    # the step program DONATES loss_scale_state; copy the
                    # scale in effect for this boundary before dispatch so
                    # the spool can record it (device copy — no fence)
                    ls_scale_used = jnp.array(
                        self.loss_scale_state.cur_scale, copy=True)
                _t1 = time.monotonic()
                (self.params, new_master, self.opt_state,
                 self.loss_scale_state, overflow,
                 self._last_grad_norm) = self._step_fn(
                    *graph_lint.step_args(self, self._acc))
                if self.zero_flat:
                    self.master_flat = new_master
                else:
                    self.master = new_master
                self._acc = None
                if spool is not None:
                    # split-API spool append: one tiny jitted program per
                    # boundary (the fused path folds this into
                    # train_batch itself) — still zero fences
                    self._telemetry.note_spool_base_step(self.global_steps)
                    spool.append_split(
                        self._last_loss if self._last_loss is not None
                        else jnp.zeros((), jnp.float32),
                        self._last_grad_norm, ls_scale_used, overflow)
                self._post_boundary_bookkeeping(overflow)
                self._telemetry.note_boundary_host_seconds(
                    _t1 - _t0, time.monotonic() - _t0)
                if spool is not None:
                    self.tput_timer.stop(report_speed=False, sync_on=None)
                else:
                    self.tput_timer.stop(sync_on=self.params)

        self.micro_steps += 1
        if wcb:
            self.timers(STEP_TIMER).stop()
            # per-span TB events (reference deepspeed_light.py:770-781 writes
            # Train/Samples/elapsed_time_ms_* alongside the console log)
            if self.summary_writer is not None:
                for name in (FORWARD_TIMER, BACKWARD_TIMER,
                             BACKWARD_INNER_TIMER, BACKWARD_REDUCE_TIMER,
                             STEP_TIMER):
                    self.summary_writer.add_scalar(
                        f"Train/Samples/elapsed_time_ms_{name}",
                        self.timers(name).elapsed(reset=False) * 1000.0,
                        getattr(self, "sample_count", self.global_steps))
            self.timers.log([FORWARD_TIMER, BACKWARD_TIMER,
                            BACKWARD_INNER_TIMER, BACKWARD_REDUCE_TIMER,
                            STEP_TIMER],
                            memory_breakdown=self.config.memory_breakdown)

    # --------------------------------------------------------- fused hot path

    def _make_fused_local(self):
        """The per-optimizer-step fused body (gas micro-steps scanned into
        the boundary update) that runs INSIDE shard_map — shared by
        ``_build_train_batch`` (one step per dispatch) and
        ``_build_train_many`` (K steps unrolled per dispatch).  Returns
        ``f(params, master, opt_state, ls_state, hypers, normw, gids,
        batch_args) -> (params, master, opt_state, ls_state, overflow,
        total_norm, last_loss)``."""
        gas = self.gradient_accumulation_steps()
        loss_and_grads = self._make_loss_and_grads()
        step_local = self._make_step_local()
        stage2 = self.zero_stage == 2
        # (ZeRO-3 needs no special casing here: grads/acc live on local
        # shard shapes — partitioned leaves are already scattered by the
        # gather transpose — and step_local consumes them in place)

        def local(params, master, opt_state, ls_state, hypers,
                  normw, gids, batch_args):
            if gas == 1:
                # no accumulator buffer, no scan machinery
                last_loss, acc = loss_and_grads(
                    params, ls_state.cur_scale, batch_args)
                if stage2:
                    acc = self._scatter_grads_local(
                        acc, across_subgroups=False)
            else:
                # fold the grad-accum axis out front for the scan; batch
                # leaves arrive as local [gas * micro_local, ...] slices
                mb = jax.tree_util.tree_map(
                    lambda x: x.reshape(
                        (gas, x.shape[0] // gas) + x.shape[1:]),
                    batch_args)

                def body(acc, micro):
                    loss_out, grads = loss_and_grads(
                        params, ls_state.cur_scale, micro)
                    if stage2:
                        # ZeRO-2: scatter per micro — the accumulator is
                        # the 1/pps flat partition, not a full grad tree
                        # (cross-sub-group psum deferred to the boundary)
                        grads = self._scatter_grads_local(
                            grads, across_subgroups=False)
                    acc = jax.tree_util.tree_map(jnp.add, acc, grads)
                    return acc, loss_out

                if stage2:
                    part = self.flat_meta.partition
                    shape = ((1, part) if self._zero_state_axes
                             else (part,))
                    zeros = jnp.zeros(shape, jnp.float32)
                else:
                    zeros = jax.tree_util.tree_map(
                        lambda p: jnp.zeros(p.shape, jnp.float32), params)
                acc, losses = jax.lax.scan(body, zeros, mb)
                last_loss = jax.tree_util.tree_map(lambda l: l[-1], losses)
            (params_new, master_new, opt_new, ls_new, overflow,
             total_norm) = step_local(master, opt_state, acc, ls_state,
                                      hypers, normw, gids)
            return (params_new, master_new, opt_new, ls_new, overflow,
                    total_norm, last_loss)

        return local

    def _build_train_batch(self, batch):
        """ONE jitted XLA program for the full effective batch: ``lax.scan``
        over gas micro-steps (fwd+bwd, grads accumulated on device) feeding
        straight into the boundary update — grads never leave the device and
        there is a single dispatch per optimizer step (the reference needs
        gas+1 host round-trips, deepspeed_light.py:603-807; the split API
        here needed gas fwd dispatches + an accumulate + a step dispatch)."""
        local = self._make_fused_local()
        master_spec, opt_spec, ls_spec = self._step_specs()
        fn = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(self._param_specs, master_spec, opt_spec, ls_spec,
                      P(), P(DATA_AXIS), P(DATA_AXIS),
                      self._checked_batch_specs(batch)),
            out_specs=(self._param_specs, master_spec, opt_spec, ls_spec,
                       P(), P(), P()),
            check_vma=False)
        if self._spool is not None:
            # MetricSpool: append this boundary's (loss, grad norm, loss
            # scale, skip flag) into the device ring buffer INSIDE the
            # compiled step — pure consumers of values the program already
            # computes, so the optimizer math is bitwise identical with
            # the spool off (docs/observability.md; pinned by
            # tests/test_observability.py).  The buffer stays on device;
            # one batched callback per report window drains it.
            from deepspeed_tpu.observability import spool as spool_mod
            shard_fn = fn

            def fn(params, master, opt_state, ls_state, hypers, normw,
                   gids, batch_args, spool_state):
                outs = shard_fn(params, master, opt_state, ls_state,
                                hypers, normw, gids, batch_args)
                (_, _, _, _, overflow, total_norm, last_loss) = outs
                new_spool = spool_mod.append(
                    spool_state, last_loss, total_norm,
                    ls_state.cur_scale, overflow)
                return outs + (new_spool,)

        # donate params/master/opt-state/loss-scale (all replaced by outputs).
        # In fp32 mode params.astype(fp32) is an identity, so XLA aliases the
        # output params and master buffers — donating either on the next call
        # would donate a buffer that is also passed as the other argument;
        # donate only the optimizer/loss-scale state there.  (The spool
        # state is NOT donated: the ring is tiny and an in-flight drain
        # callback still reads the previous buffer.)
        return jax.jit(fn, donate_argnums=self._donate_argnums(fused=True))

    def train_batch(self, batch):
        """Forward+backward+step over a full effective batch whose leaves
        carry a leading [gas * micro * dp] axis, as one fused XLA program.

        Semantics match gas iterations of the split API followed by the
        boundary step, except sample→(micro-step, DP-shard) assignment: the
        fused path scans each shard's contiguous rows, the split API slices
        micro-batches globally.  The summed gradient over the effective batch
        is identical either way.  Returns the last micro-step's loss."""
        assert self.training, "train_batch() requires train mode"
        self._force_live_pendings()  # train_batch mutates params
        batch = _as_tuple(batch)
        gas = self.gradient_accumulation_steps()
        leads = {x.shape[0] for x in jax.tree_util.tree_leaves(batch)}
        if len(leads) != 1:
            raise ValueError(
                f"train_batch: batch leaves disagree on the leading dim "
                f"({sorted(leads)}); every leaf must carry the same "
                f"[gas * micro * dp] axis")
        lead = leads.pop()
        if lead % gas != 0:
            raise ValueError(
                f"train_batch: leading batch dim {lead} is not divisible by "
                f"gradient_accumulation_steps={gas}")
        key = self._batch_cache_key(batch)
        if self._train_batch_fn is None or self._train_batch_key != key:
            self._train_batch_fn = self._cached_batch_fn(
                self._train_batch_fns, key,
                lambda: self._build_train_batch(batch))
            self._train_batch_key = key
        self._maybe_graph_lint(
            "train_batch", key,
            lambda: graph_lint.analyze_engine_train_batch(self, batch))
        # explicitly K=1: THIS path dispatches the single-step program,
        # whatever train_steps_per_dispatch says (train_many has its own
        # gate pricing the real block size)
        self._maybe_capacity_plan(
            "train_batch", key,
            lambda: self.plan_capacity(batch, train=True, fused=True,
                                       steps_per_dispatch=1),
            batch=batch, steps_per_dispatch=1)
        spool = self._spool
        if spool is not None:
            self._telemetry.note_spool_base_step(self.global_steps)
            self._telemetry.note_predictions(self, batch)
            self._maybe_graph_lint(
                "spool_drain", "spool",
                lambda: graph_lint.analyze_jaxpr(
                    jax.make_jaxpr(spool.drain_program())(spool.state),
                    subject="spool_drain"))
        # call tuple via the single protocol owner (analysis.train_batch
        # _args appends the spool state when the spool is on)
        args = graph_lint.train_batch_args(self, batch)
        # armed through the boundary's host sync (see step()): a hung
        # collective inside the fused program surfaces at the overflow
        # read / loss sync, not at the async dispatch
        with self._armed("train_batch"), _annotate("train_batch"):
            from deepspeed_tpu.resilience import chaos as _chaos
            # host-side pre-dispatch clock: [region entry, program call)
            # is time only THIS host pays (GC, data prep, an injected
            # stall) — the fleet straggler signal; the collective wait
            # rides the device queue and is excluded (two clock reads,
            # same cost class as watchdog arming)
            _t0 = time.monotonic()
            _flightrec.record("arm", label="train_batch",
                              step=self.global_steps)
            _chaos.maybe_stall(self.global_steps)
            _t1 = time.monotonic()
            outs = self._train_batch_fn(*args)
            if spool is not None:
                outs, new_spool = outs[:-1], outs[-1]
            (self.params, new_master, self.opt_state, self.loss_scale_state,
             overflow, self._last_grad_norm, loss) = outs
            if self.zero_flat:
                self.master_flat = new_master
            else:
                self.master = new_master
            self.micro_steps += gas
            if spool is not None:
                # adopt the ring state (auto-drains on window edges — one
                # async batched callback, the host never waits)
                spool.note_append(new_spool)
            self._post_boundary_bookkeeping(overflow)
            self._telemetry.note_boundary_host_seconds(
                _t1 - _t0, time.monotonic() - _t0)
            if spool is not None:
                # throughput/goodput ride the window drain timestamps;
                # fencing (and printing dispatch-rate numbers) here would
                # reintroduce the per-report-step stall the spool removes
                self.tput_timer.stop(report_speed=False, sync_on=None)
            else:
                self.tput_timer.stop(sync_on=loss)
        return loss

    # --------------------------------------------- multi-step fused driver

    def _build_train_many(self, batch, k):
        """ONE jitted program fusing K optimizer steps — K invocations of
        the fused per-step body chained inside one shard_map, one host
        dispatch per K steps (WALLCLOCK §7's per-step fixed cost
        amortized K×; ROADMAP item 4).

        Bitwise-parity architecture (the contract: identical trajectory
        to K serial ``train_batch`` dispatches, pinned by
        tests/test_multistep.py across ZeRO stages, gas>1 and
        fp16-with-skips).  Two measured XLA-CPU hazards shape the form:

        * a dot whose operand is a bitcast/slice of a leading-[K]-stacked
          parameter compiles to a kLoop fusion with a different
          accumulation order than the runtime-dot call the per-step
          program makes (``optimization_barrier`` does not stop the
          fold) — so each step's batch is a SEPARATE program argument
          and the K iterations unroll at trace time instead of scanning
          a stacked tree;
        * fusion heuristics are graph-global: the same per-step subgraph
          embedded K× re-fuses its elementwise/reduction clusters
          (~1-ulp re-association in the Adam moment chain) — so each
          step body runs inside a ``lax.cond`` whose predicate is
          runtime-true: cond branches compile as their OWN XLA
          computations, giving every fused step exactly the standalone
          program's compilation.  The predicate reads a dedicated
          replicated ``live`` input (``_live_flag``) rather than any
          carried state: a carried value passes through earlier branch
          outputs, which the collective-consistency lint conservatively
          rank-taints (at ZeRO-3 the step body uses ``axis_index``), and
          a tainted cond predicate with collectives in one branch is the
          lint's deadlock signature.  A fresh input is never tainted —
          and never constant-folded.

        Per-step semantics inside the program:

        * the fp16/nan-sentinel skip contract holds PER STEP — overflow
          gates the update through the existing ``jnp.where`` path in
          ``_make_step_local``, never a host read;
        * the loss-scale FSM advances per step through the chained
          ``ls_state``;
        * hypers arrive as ONE staged ``[K, 4, G]`` block
          (``_stage_hypers_many``): step i reads row ``h_idx``, and under
          a skip contract WITH an LR scheduler ``h_idx`` only advances on
          non-skipped steps — exactly the serial "no scheduler step on a
          skipped boundary" semantics, resolved on device.
        """
        single = self._make_fused_local()
        skip_bad = self.config.fp16_enabled or self._nan_sentinel
        # row selection is dynamic only when rows can differ AND a skip
        # can hold a row back; otherwise the static row i is the same
        # value and the gather is dead weight
        dynamic_hypers = skip_bad and self.lr_scheduler is not None

        def local(params, master, opt_state, ls_state, hypers_k,
                  normw, gids, live, *batch_ks):
            h_idx = jnp.int32(0)
            overflows, norms, losses, scales = [], [], [], []

            def stepped(operands):
                p, m, o, ls, hy, ba = operands
                return single(p, m, o, ls, hy, normw, gids, ba)

            def untaken(operands):
                # never executed (the predicate is runtime-true); exists
                # only so each real step body is a cond BRANCH — its own
                # XLA computation — instead of open graph
                p, m, o, ls, hy, ba = operands
                shapes = jax.eval_shape(stepped, operands)
                zeros = jax.tree_util.tree_map(
                    lambda s: jnp.zeros(s.shape, s.dtype), shapes[4:])
                return (p, m, o, ls) + tuple(zeros)

            for i in range(k):
                if dynamic_hypers:
                    hypers = jax.lax.dynamic_index_in_dim(
                        hypers_k, h_idx, 0, keepdims=False)
                else:
                    hypers = hypers_k[i]
                # the scale in effect FOR this step (pre-FSM-update) —
                # what the spool records, captured in-program instead of
                # the fused path's pre-dispatch host copy
                scales.append(jnp.asarray(ls_state.cur_scale, jnp.float32))
                (params, master, opt_state, ls_state, overflow,
                 total_norm, last_loss) = jax.lax.cond(
                    live > 0, stepped, untaken,
                    (params, master, opt_state, ls_state, hypers,
                     batch_ks[i]))
                overflows.append(jnp.asarray(overflow, jnp.bool_))
                norms.append(total_norm)
                losses.append(last_loss)
                if dynamic_hypers:
                    h_idx = h_idx + jnp.where(overflow, jnp.int32(0),
                                              jnp.int32(1))
            losses_k = jax.tree_util.tree_map(
                lambda *ls: jnp.stack(ls), *losses)
            return (params, master, opt_state, ls_state,
                    jnp.stack(overflows), norms[-1], losses[-1],
                    jnp.stack(norms), losses_k, jnp.stack(scales))

        master_spec, opt_spec, ls_spec = self._step_specs()
        batch_spec = self._checked_batch_specs(batch)
        shard_fn = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(self._param_specs, master_spec, opt_spec, ls_spec,
                      P(), P(DATA_AXIS), P(DATA_AXIS), P())
                     + tuple(batch_spec for _ in range(k)),
            out_specs=(self._param_specs, master_spec, opt_spec, ls_spec,
                       P(), P(), P(), P(), P(), P()),
            check_vma=False)
        if self._spool is not None:
            # K ring appends per dispatch — pure consumers of the per-step
            # outputs, exactly the fused path's trajectory-neutrality
            # argument; the drain still runs once per report window
            # (config guarantees window % K == 0)
            from deepspeed_tpu.observability import spool as spool_mod

            def fn(params, master, opt_state, ls_state, hypers_k, normw,
                   gids, live, batches, spool_state):
                outs = shard_fn(params, master, opt_state, ls_state,
                                hypers_k, normw, gids, live, *batches)
                (_, _, _, _, overflows, _, _, norms_k, losses_k,
                 scales_k) = outs
                for i in range(k):
                    loss_i = jax.tree_util.tree_map(lambda l: l[i],
                                                    losses_k)
                    spool_state = spool_mod.append(
                        spool_state, loss_i, norms_k[i], scales_k[i],
                        overflows[i])
                return outs + (spool_state,)
        else:
            def fn(params, master, opt_state, ls_state, hypers_k, normw,
                   gids, live, batches):
                return shard_fn(params, master, opt_state, ls_state,
                                hypers_k, normw, gids, live, *batches)

        # donation: the same (params, master, opt_state, ls_state)
        # positions as the fused single-step program, same fp32 guard
        return jax.jit(fn, donate_argnums=self._donate_argnums(fused=True))

    def _hyper_rows_host(self):
        """Host tuple of the CURRENT facade hyperparameters, one
        (lr, beta1, beta2, weight_decay) entry per param group — the
        cache key AND value behind both hyper stagings."""
        base = self.base_optimizer
        groups = self.optimizer.param_groups
        betas = [g.get("betas", (base.beta1, base.beta2)) for g in groups]
        return tuple((float(g["lr"]), float(b[0]), float(b[1]),
                      float(g.get("weight_decay", base.weight_decay)))
                     for g, b in zip(groups, betas))

    def _stage_hypers_many(self, k):
        """The ``[K, 4, G]`` hyper block for one K-fused dispatch: row j
        holds the hypers in effect after j non-skipped boundaries.  With
        an LR scheduler the prospective rows come from stepping the
        scheduler on a SNAPSHOT (state + facade groups restored after),
        so the host scheduler state only advances when the block's real
        skip outcome is known (``_post_block_bookkeeping`` replays one
        ``step()`` per non-skipped boundary).  Cached on the host row
        values — zero transfers when nothing moved."""
        sched = self.lr_scheduler
        if sched is None:
            rows_k = [self._hyper_rows_host()] * k
        else:
            if not (hasattr(sched, "state_dict")
                    and hasattr(sched, "load_state_dict")):
                raise DeepSpeedConfigError(
                    f"train_steps_per_dispatch > 1 with an LR scheduler "
                    f"needs state_dict/load_state_dict on the scheduler "
                    f"(to stage the K prospective hyper rows); "
                    f"{type(sched).__name__} has neither")
            sd = sched.state_dict()
            saved_groups = [dict(g) for g in self.optimizer.param_groups]
            saved_last_lr = getattr(sched, "_last_lr", None)
            rows_k = []
            for j in range(k):
                rows_k.append(self._hyper_rows_host())
                if j < k - 1:
                    sched.step()
            sched.load_state_dict(sd)
            for g, s in zip(self.optimizer.param_groups, saved_groups):
                g.clear()
                g.update(s)
            if saved_last_lr is not None:
                sched._last_lr = saved_last_lr
        key = (tuple(rows_k), k)
        if key != self._hyper_many_key:
            block = np.asarray(
                [[[r[c] for r in row] for c in range(4)]
                 for row in rows_k], np.float32)      # [K, 4, G]
            self._hyper_many_dev = jnp.asarray(block)
            self._hyper_many_key = key
        return self._hyper_many_dev

    def train_many(self, batches):
        """K optimizer steps — K full effective batches — in ONE compiled
        dispatch (the on-device multi-step driver, ROADMAP item 4;
        docs/features.md "Multi-step driver").

        ``batches`` is a sequence of K ``train_batch``-format batches
        (identical format; K is its length — typically
        ``config.train_steps_per_dispatch``, grouped by
        ``data.BlockPrefetcher``).  Trajectory contract: bitwise
        identical to K serial ``train_batch`` calls on the same batches
        (tests/test_multistep.py pins it across ZeRO stages 0/1/3,
        gas>1 and fp16-with-skips).  Returns the LAST step's loss.

        Host-boundary accounting per K steps: one program dispatch, one
        batch staging, at most ONE deliberate fence (the skip-contract
        overflow vector read — deferred entirely to the window drain
        when the metric spool is on and no scheduler retains it), and
        the watchdog armed once with a K-scaled deadline.  Preemption
        (``resilience.run_resumable``) polls between dispatches, so the
        documented drain granularity becomes ≤ K steps."""
        assert self.training, "train_many() requires train mode"
        if not isinstance(batches, (list, tuple)) or len(batches) == 0:
            raise ValueError(
                "train_many: pass a non-empty sequence of train_batch-"
                "format batches (one per fused optimizer step)")
        self._force_live_pendings()  # train_many mutates params
        batches = tuple(_as_tuple(b) for b in batches)
        k = len(batches)
        gas = self.gradient_accumulation_steps()
        fmt_keys = [self._batch_cache_key(b) for b in batches]
        if any(fk != fmt_keys[0] for fk in fmt_keys[1:]):
            raise ValueError(
                "train_many: every batch in a K-block must share one "
                "format (pytree structure + leaf shapes/dtypes); mixed "
                "formats must go through separate blocks")
        leads = {x.shape[0] for x in jax.tree_util.tree_leaves(batches[0])}
        if len(leads) != 1:
            raise ValueError(
                f"train_many: batch leaves disagree on the leading dim "
                f"({sorted(leads)}); every leaf must carry the same "
                f"[gas * micro * dp] axis")
        lead = leads.pop()
        if lead % gas != 0:
            raise ValueError(
                f"train_many: leading batch dim {lead} is not divisible "
                f"by gradient_accumulation_steps={gas}")
        key = (k, fmt_keys[0])
        if self._train_many_fn is None or self._train_many_key != key:
            self._train_many_fn = self._cached_batch_fn(
                self._train_many_fns, key,
                lambda: self._build_train_many(batches[0], k))
            self._train_many_key = key
        self._maybe_graph_lint(
            "train_many", key,
            lambda: graph_lint.analyze_engine_train_many(self, batches))
        self._maybe_capacity_plan(
            "train_many", key,
            lambda: self.plan_capacity(batches[0], train=True, fused=True,
                                       steps_per_dispatch=k),
            batch=batches[0], steps_per_dispatch=k)
        spool = self._spool
        if spool is not None:
            self._telemetry.note_spool_base_step(self.global_steps)
            self._telemetry.note_predictions(self, batches[0])
            self._maybe_graph_lint(
                "spool_drain", "spool",
                lambda: graph_lint.analyze_jaxpr(
                    jax.make_jaxpr(spool.drain_program())(spool.state),
                    subject="spool_drain"))
            if spool.would_straddle(k):
                # a stray train_batch on this K>1 engine left the ring
                # mid-window: this block's K in-program appends would
                # wrap over undrained rows BEFORE any drain could read
                # them, silently misattributing a whole window.  Deliver
                # the partial window first — one counted fence, paid
                # only by mixed train_batch/train_many usage
                spool.flush()
        args = graph_lint.train_many_args(self, batches)
        # armed ONCE around the K-step region, deadline scaled by K: a
        # healthy K-block must not fire a deadline tuned for one step
        # (docs/resilience.md "Watchdog tuning")
        with self._armed("train_many", deadline_scale=k), \
                _annotate("train_many"):
            from deepspeed_tpu.resilience import chaos as _chaos
            _t0 = time.monotonic()
            _flightrec.record("arm", label="train_many",
                              step=self.global_steps, block=k)
            _chaos.maybe_stall(self.global_steps)
            _t1 = time.monotonic()
            outs = self._train_many_fn(*args)
            if spool is not None:
                outs, new_spool = outs[:-1], outs[-1]
            (self.params, new_master, self.opt_state, self.loss_scale_state,
             overflows, self._last_grad_norm, loss, _norms_k, _losses_k,
             _scales_k) = outs
            if self.zero_flat:
                self.master_flat = new_master
            else:
                self.master = new_master
            self.micro_steps += gas * k
            self._last_loss = loss
            if spool is not None:
                # adopt the ring carrying K in-program appends; the drain
                # still fires once per report window (window % K == 0)
                spool.note_appends(new_spool, k)
            self._post_block_bookkeeping(overflows, k)
            self._telemetry.note_boundary_host_seconds(
                _t1 - _t0, time.monotonic() - _t0)
            # goodput rides the telemetry window drains at K > 1; the
            # PR 1 window-fence reporter would reintroduce a per-block
            # stall for a number the spool already measures
            self.tput_timer.stop(report_speed=False, sync_on=None)
        return loss

    def _post_block_bookkeeping(self, overflows, k):
        """Counters, skip accounting, scheduler replay and reporting
        after a K-fused dispatch — ``_post_boundary_bookkeeping``'s block
        form.  The per-boundary overflow host read becomes ONE read of
        the ``[K]`` skip vector per block (amortized K×), or no read at
        all when the spool defers it to the window drain."""
        prev = self.global_steps
        self.global_steps += k
        _flightrec.record("boundary", step=self.global_steps, block=k)
        self._profile_window()
        self._telemetry.maybe_trace(self.global_steps)
        skip_contract = self.config.fp16_enabled or self._nan_sentinel
        defer = (skip_contract
                 and self._telemetry.defers_overflow(self))
        sched = self.lr_scheduler
        if skip_contract and not defer:
            # ONE fence per K steps: the whole skip vector in one read
            # (observability/fences.py counts it; the dispatch plan
            # prices it at 1/K per step)
            flags = np.asarray(
                obs_fences.read_arrays(overflows)[0]).astype(bool)
            n_skip = int(flags.sum())
            self.overflow = bool(flags[-1])
            self.skipped_steps += n_skip
            if n_skip and self._nan_sentinel \
                    and not self.config.fp16_enabled:
                from deepspeed_tpu.resilience import COUNTERS
                COUNTERS.nan_skips += n_skip
                logger.warning(
                    "resilience: %d non-finite-gradient boundar%s skipped "
                    "in the K-block ending at global step %d "
                    "(nan_sentinel, fused)", n_skip,
                    "y" if n_skip == 1 else "ies", self.global_steps)
            if sched is not None:
                # replay exactly the non-skipped boundaries: the device
                # side already consumed the matching prospective hyper
                # rows (h_idx gating), this re-syncs the host scheduler
                for skipped in flags:
                    if not skipped:
                        sched.step()
        else:
            # statically finite, or deferred: the window drain settles
            # skipped_steps/overflow retroactively (Telemetry._on_window)
            self.overflow = False
            if sched is not None:
                for _ in range(k):
                    sched.step()
        spp = self.steps_per_print()
        if spp and self.global_steps // spp != prev // spp:
            self._report_progress(self.global_steps)
        if self.summary_writer is not None \
                and not self._telemetry.spool_active:
            self._telemetry.emit_boundary_scalars(
                getattr(self, "sample_count", self.global_steps))

    # ------------------------------------------------------------- reporting

    def _report_progress(self, step):
        """reference deepspeed_light.py:809-817"""
        lr = (self.lr_scheduler.get_last_lr()
              if self.lr_scheduler is not None
              and hasattr(self.lr_scheduler, "get_last_lr")
              else [self.optimizer.param_groups[0]["lr"]])
        mom = self.optimizer.param_groups[0].get("betas", None)
        if jax.process_index() == 0:
            logger.info("step=%d, skipped=%d, lr=%s, mom=%s",
                        step, self.skipped_steps, lr, mom)

    # ---------------------------------------------------------- checkpointing

    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        async_save=None):
        """reference deepspeed_light.py:1048-1114.  ``async_save=True``
        (or the ``checkpoint.async_save`` config key) returns after the
        device→host snapshot; the file writes happen on a background
        thread — call :meth:`checkpoint_wait` to block until durable."""
        from deepspeed_tpu import checkpoint as ckpt_mod
        # the save stall is not training throughput: keep it out of the
        # next report window (timer.py window accounting)
        self.tput_timer.discard_window()
        _flightrec.record("checkpoint.save", step=self.global_steps,
                          tag=tag)
        with self._armed("save_checkpoint"), _annotate("checkpoint.save"):
            return ckpt_mod.save_checkpoint(self, save_dir, tag=tag,
                                            client_state=client_state,
                                            async_save=async_save)

    def checkpoint_wait(self):
        """Block until every queued async checkpoint write is on disk;
        re-raises the first background write failure."""
        from deepspeed_tpu import checkpoint as ckpt_mod
        ckpt_mod.ASYNC_SAVER.wait()

    def load_checkpoint(self, load_dir, tag=None, load_optimizer_states=True,
                        load_lr_scheduler_states=True):
        """reference deepspeed_light.py:974-1046; returns (path,
        client_state)."""
        self._force_live_pendings()  # deferred forwards saw the old params
        # drain the undelivered metric window NOW, labeled with the
        # PRE-restore step numbers: stale ring rows must never mix into a
        # post-restore window (and deferred skip bookkeeping must not
        # land on the restored trajectory)
        self.flush_telemetry()
        import time as _time

        from deepspeed_tpu import checkpoint as ckpt_mod
        from deepspeed_tpu.resilience import COUNTERS
        t0 = _time.perf_counter()
        _flightrec.record("checkpoint.load", step=self.global_steps,
                          tag=tag)
        with self._armed("load_checkpoint"), _annotate("checkpoint.load"):
            path, client = ckpt_mod.load_checkpoint(
                self, load_dir, tag=tag,
                load_optimizer_states=load_optimizer_states,
                load_lr_scheduler_states=load_lr_scheduler_states)
        if path is not None:
            # restore sits on the preemption-resume critical path: keep its
            # latency observable (Train/Resilience/restore_seconds)
            COUNTERS.restore_seconds = _time.perf_counter() - t0
            # window step numbering follows the restored step count (the
            # pre-restore partial window was flushed above)
            self._telemetry.rebase_steps(self.global_steps)
        return path, client

    # ------------------------------------------------- optimizer state (ckpt)

    def _optimizer_state_dict(self):
        sd = {
            "opt_state": self.opt_state,
            "loss_scale_state": self.loss_scale_state,
            "zero_enabled": self.zero_enabled,
            "zero_stage": self.zero_stage,
        }
        if self.zero_flat:
            sd["master_flat"] = self.master_flat
        else:
            sd["master"] = self.master
        return sd

    def _optimizer_load_state_dict(self, sd):
        self._force_live_pendings()  # deferred forwards saw the old state
        self.opt_state = jax.tree_util.tree_map(
            lambda old, new: jax.device_put(jnp.asarray(new), old.sharding),
            self.opt_state, sd["opt_state"])
        self.loss_scale_state = jax.tree_util.tree_map(
            lambda old, new: jax.device_put(jnp.asarray(new), old.sharding),
            self.loss_scale_state, sd["loss_scale_state"])
        if self.zero_flat:
            self.master_flat = jax.device_put(
                jnp.asarray(sd["master_flat"]), self.master_flat.sharding)
            self.params = self._params_from_master_flat()
        else:
            self.master = jax.tree_util.tree_map(
                lambda old, new: jax.device_put(jnp.asarray(new), old.sharding),
                self.master, sd["master"])
            self.params = jax.tree_util.tree_map(
                lambda m, s: jax.device_put(
                    jnp.asarray(m, self.policy.compute_dtype), self._named(s)),
                self.master, self._param_specs)


    def _params_from_master_flat(self, host_flat=None):
        """Re-derive compute-dtype params from the flat fp32 master (host
        side, outside jit): 1-D buffers unflatten directly; the [mp, ...]
        ZeRO x MP layout reassembles global leaves from per-model-shard
        rows.  Pass ``host_flat`` (a host np copy, e.g. reassembled from
        checkpoint shards) to avoid fetching the sharded device array —
        ``device_get`` of a multi-host global array is not possible."""
        flat = (np.asarray(host_flat) if host_flat is not None
                else np.asarray(jax.device_get(self.master_flat)))
        if flat.ndim == 2:
            rows = []
            for r in range(flat.shape[0]):
                # each row may be block-tiled repl× (pps sub-groups);
                # the first block holds the full partitioned state
                t = zero_mod.unflatten_tree(
                    jnp.asarray(self._untile_flat(flat[r])), self.flat_meta)
                rows.append(jax.tree_util.tree_map(np.asarray, t))

            # rows are pipe-major, model-minor — the [S, local] composite
            # layout
            tree = zero_mod.combine_composite_trees(
                rows, self._param_specs, self._zero_state_axes)
        else:
            # parameter-parallel sub-groups tile the buffer repl×; every
            # block holds the same values — unflatten the first
            tree = zero_mod.unflatten_tree(
                jnp.asarray(self._untile_flat(flat)), self.flat_meta)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(
                jnp.asarray(x, self.policy.compute_dtype), self._named(s)),
            tree, self._param_specs)
