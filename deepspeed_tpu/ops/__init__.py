from deepspeed_tpu.ops.optim import (  # noqa: F401
    Adam,
    AdamW,
    Lamb,
    Lion,
    Sgd,
    Optimizer,
    OptimizerState,
    from_config,
)
