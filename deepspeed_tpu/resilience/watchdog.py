"""Hang watchdog: a heartbeat thread armed around blocking calls.

A hung collective on a pod slice is worse than a crash: the job burns its
reservation doing nothing and nobody is told.  The watchdog is armed around
each blocking engine call (step / train_batch / backward / checkpoint IO —
engine._armed) and, past the configured deadline:

1. dumps EVERY thread's stack (``sys._current_frames``) plus the last N
   armed-operation timings to the log (the dump names the stuck frame —
   pinned by the chaos suite), and
2. optionally aborts the process with ``WATCHDOG_EXIT_CODE`` so the
   launcher's ``--max_restarts`` path can take over
   (``resilience.watchdog_abort``).

Operations that complete but consume more than ``near_miss_frac`` of the
deadline increment ``COUNTERS.watchdog_near_misses`` — the observable
early-warning that a deadline is about to start firing.

NOTE: importable without jax (the launcher parent imports the exit-code
contract).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
import traceback
from collections import deque
from contextlib import contextmanager

from deepspeed_tpu.resilience.counters import COUNTERS

logger = logging.getLogger(__name__)

#: process aborted by the hang watchdog after dumping stacks: the launcher
#: should relaunch (docs/resilience.md "Exit codes")
WATCHDOG_EXIT_CODE = 44


def format_all_stacks() -> str:
    """Every live thread's current stack, rendered with frame names."""
    names = {t.ident: t.name for t in threading.enumerate()}
    parts = []
    for ident, frame in sorted(sys._current_frames().items()):
        parts.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        parts.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(parts)


class Watchdog:
    """Deadline monitor for armed operations.

    One background monitor thread (daemon, started on first arm) polls the
    armed deadline; arming is two clock reads and a field write, cheap
    enough for the per-step hot path.  Armed regions do not nest — the
    engine's blocking calls are sequential.
    """

    def __init__(self, timeout_s: float, abort: bool = False,
                 near_miss_frac: float = 0.8, history: int = 32,
                 poll_s: float = None, on_fire=None):
        self.timeout_s = float(timeout_s)
        self.abort = bool(abort)
        #: optional callable invoked (on the monitor thread) after the
        #: stack dump and BEFORE any abort — the telemetry layer hooks a
        #: short jax.profiler hang capture here so a wedged run leaves a
        #: trace artifact, not just stacks (observability/tracing.py)
        self.on_fire = on_fire
        self.near_miss_frac = float(near_miss_frac)
        self.poll_s = (poll_s if poll_s is not None
                       else max(0.02, min(1.0, self.timeout_s / 10.0)))
        self.timings = deque(maxlen=int(history))   # (label, seconds)
        self.fired = False          # any fire over the watchdog's lifetime
        self.last_dump = None
        self.fire_event = threading.Event()
        self._lock = threading.Lock()
        self._armed_label = None
        self._armed_at = None
        self._armed_deadline_s = self.timeout_s
        self._fired_this_arm = False
        self._thread = None

    # ------------------------------------------------------------- arming
    @contextmanager
    def armed(self, label: str, deadline_scale: float = 1.0):
        """``deadline_scale`` stretches THIS region's deadline (and its
        near-miss threshold): the multi-step driver arms once around a
        K-step fused dispatch, so a deadline tuned for one boundary must
        scale by K or every healthy K-block fires it
        (docs/resilience.md "Watchdog tuning")."""
        self._arm(label, deadline_scale)
        try:
            yield self
        finally:
            self._disarm()

    def _arm(self, label: str, deadline_scale: float = 1.0) -> None:
        if deadline_scale <= 0:
            raise ValueError(
                f"watchdog deadline_scale must be > 0, got {deadline_scale}")
        self._ensure_thread()
        with self._lock:
            if self._armed_label is not None:
                raise RuntimeError(
                    f"watchdog already armed for {self._armed_label!r}; "
                    f"armed regions do not nest (attempted {label!r})")
            self._armed_label = label
            self._armed_at = time.monotonic()
            self._armed_deadline_s = self.timeout_s * float(deadline_scale)
            self._fired_this_arm = False

    def _disarm(self) -> None:
        with self._lock:
            label, at = self._armed_label, self._armed_at
            deadline = self._armed_deadline_s
            fired = self._fired_this_arm
            self._armed_label = None
            self._armed_at = None
            self._armed_deadline_s = self.timeout_s
            self._fired_this_arm = False
        if at is None:
            return
        dur = time.monotonic() - at
        self.timings.append((label, dur))
        if not fired and dur > self.near_miss_frac * deadline:
            COUNTERS.watchdog_near_misses += 1
            logger.warning(
                "watchdog near-miss: %r took %.2fs of a %.2fs deadline",
                label, dur, deadline)

    # ------------------------------------------------------------ monitor
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._monitor, daemon=True, name="dstpu-watchdog")
            self._thread.start()

    def _monitor(self) -> None:
        while True:
            time.sleep(self.poll_s)
            with self._lock:
                label, at = self._armed_label, self._armed_at
                deadline = self._armed_deadline_s
                already = self._fired_this_arm
                if (label is None or already
                        or time.monotonic() - at <= deadline):
                    continue
                self._fired_this_arm = True
            self._fire(label, time.monotonic() - at, deadline)

    def _fire(self, label: str, elapsed: float,
              deadline_s: float = None) -> None:
        recent = "\n".join(f"  {lbl}: {dur * 1000.0:.1f} ms"
                           for lbl, dur in self.timings) or "  (none)"
        # flight-recorder enrichment (observability/flightrec.py): the
        # stack dump says where this thread is stuck NOW; the recorder
        # tail says which step/window the process reached before it hung
        # — together a post-mortem names the divergence point without
        # reconstructing it.  jax-free import; best-effort.
        flight = "  (unavailable)"
        try:
            from deepspeed_tpu.observability import flightrec
            flight = flightrec.RECORDER.format_tail()
        except Exception:  # pragma: no cover - defensive
            pass
        deadline_s = self.timeout_s if deadline_s is None else deadline_s
        dump = (f"WATCHDOG: {label!r} exceeded {deadline_s:.2f}s "
                f"deadline ({elapsed:.2f}s elapsed)\n"
                f"last {len(self.timings)} armed-operation timings:\n"
                f"{recent}\n"
                f"recent flight-recorder entries:\n{flight}\n"
                f"all-thread stacks:\n{format_all_stacks()}")
        self.last_dump = dump
        self.fired = True
        COUNTERS.watchdog_fires += 1
        logger.error("%s", dump)
        try:
            # persist the ring next to the stack dump: the launcher may
            # relaunch (or the abort below ends the process) — the file,
            # not the log buffer, is what the post-mortem collects
            from deepspeed_tpu.observability import flightrec
            flightrec.RECORDER.dump("watchdog")
        except Exception:  # pragma: no cover - defensive
            pass
        if self.on_fire is not None:
            # best-effort diagnostics (hang trace capture): a hook failure
            # must never mask the dump or block the abort path
            try:
                self.on_fire()
            except Exception as e:  # pragma: no cover - defensive
                logger.warning("watchdog on_fire hook failed: %s", e)
        self.fire_event.set()
        if self.abort:
            # the restart path takes over: flush the dump to stderr and
            # exit with the contract code.  os._exit, not sys.exit — the
            # main thread is by definition stuck and cannot unwind.
            sys.stderr.write(dump + "\n")
            sys.stderr.flush()
            os._exit(WATCHDOG_EXIT_CODE)
