"""SQuAD-style evaluation metrics: exact match + token F1.

The reference ships a full SQuAD fine-tune-to-F1 suite
(/root/reference/tests/model/BingBertSquad/BingBertSquad_run_func_test.py,
run_BingBertSquad.sh drives evaluate-v1.1-style EM/F1); this module is the
TPU-native analog used by ``examples/bert/squad_finetune.py`` and
``tests/model/test_squad_f1.py``:

* text metrics — the official SQuAD v1.1 normalization (lowercase, strip
  punctuation/articles/extra whitespace) with whitespace-token F1, for real
  SQuAD predictions;
* span metrics — position-level EM / overlap-F1 over (start, end) token
  spans, the tokenizer-free equivalent used with synthetic corpora;
* ``best_spans`` — the standard argmax over valid (start <= end,
  length <= max_answer_len) pairs, vectorized over the batch (jit-safe).
"""

from __future__ import annotations

import collections
import re
import string
from typing import Sequence, Tuple

import jax.numpy as jnp
import numpy as np

# ------------------------------------------------------------- text metrics


def normalize_answer(s: str) -> str:
    """Official SQuAD v1.1 normalization: lower, strip punctuation,
    articles, and extra whitespace."""
    s = s.lower()
    s = "".join(ch for ch in s if ch not in set(string.punctuation))
    s = re.sub(r"\b(a|an|the)\b", " ", s)
    return " ".join(s.split())


def text_exact_match(prediction: str, ground_truth: str) -> float:
    return float(normalize_answer(prediction) == normalize_answer(ground_truth))


def text_f1(prediction: str, ground_truth: str) -> float:
    pred_toks = normalize_answer(prediction).split()
    gold_toks = normalize_answer(ground_truth).split()
    if not pred_toks or not gold_toks:
        return float(pred_toks == gold_toks)
    common = collections.Counter(pred_toks) & collections.Counter(gold_toks)
    overlap = sum(common.values())
    if overlap == 0:
        return 0.0
    precision = overlap / len(pred_toks)
    recall = overlap / len(gold_toks)
    return 2 * precision * recall / (precision + recall)


def metric_max_over_ground_truths(metric_fn, prediction: str,
                                  ground_truths: Sequence[str]) -> float:
    """SQuAD rule: score against every annotated answer, keep the best."""
    return max(metric_fn(prediction, gt) for gt in ground_truths)


# ------------------------------------------------------------- span metrics


def best_spans(start_logits, end_logits, attention_mask=None,
               max_answer_len: int = 30) -> Tuple[np.ndarray, np.ndarray]:
    """Batch argmax over valid (start, end) pairs.

    start_logits/end_logits: [B, T]; attention_mask: optional [B, T] (0 =
    padding, excluded).  Valid pairs satisfy start <= end and
    end - start < max_answer_len.  Returns (starts, ends) int arrays [B].
    """
    sl = jnp.asarray(start_logits, jnp.float32)
    el = jnp.asarray(end_logits, jnp.float32)
    if attention_mask is not None:
        valid = jnp.asarray(attention_mask) > 0
        sl = jnp.where(valid, sl, -1e9)
        el = jnp.where(valid, el, -1e9)
    T = sl.shape[-1]
    scores = sl[:, :, None] + el[:, None, :]          # [B, S, E]
    s_idx = jnp.arange(T)[:, None]
    e_idx = jnp.arange(T)[None, :]
    band = (e_idx >= s_idx) & (e_idx - s_idx < max_answer_len)
    scores = jnp.where(band[None], scores, -jnp.inf)
    flat = jnp.argmax(scores.reshape(scores.shape[0], -1), axis=-1)
    return np.asarray(flat // T), np.asarray(flat % T)


def make_span_predictor(model, params):
    """Single-device replicated predictor for EM/F1 evaluation.

    The vocab-parallel embedding inside the encoder needs a bound model
    axis, so the prediction runs under ``shard_map`` over a one-device
    mesh with everything replicated.  ``params`` may be engine-sharded;
    a host copy is taken.  Returns ``predict(ids, attn, tt) ->
    (start_logits, end_logits)``.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel.topology import make_mesh

    host = jax.tree_util.tree_map(np.asarray, params)
    rep = jax.tree_util.tree_map(lambda _: P(), host)
    mesh = make_mesh(model_parallel_size=1, devices=jax.devices()[:1])
    fn = jax.jit(jax.shard_map(
        lambda p, i, a, t: model.span_logits(p, i, a, t), mesh=mesh,
        in_specs=(rep, P(), P(), P()), out_specs=(P(), P()),
        check_vma=False))
    return lambda i, a, t: fn(host, i, a, t)


def span_exact_match(pred_span: Tuple[int, int],
                     gold_span: Tuple[int, int]) -> float:
    return float(tuple(pred_span) == tuple(gold_span))


def span_f1(pred_span: Tuple[int, int], gold_span: Tuple[int, int]) -> float:
    """Token-overlap F1 between two inclusive [start, end] position spans."""
    ps, pe = int(pred_span[0]), int(pred_span[1])
    gs, ge = int(gold_span[0]), int(gold_span[1])
    overlap = max(0, min(pe, ge) - max(ps, gs) + 1)
    if overlap == 0:
        return 0.0
    precision = overlap / (pe - ps + 1)
    recall = overlap / (ge - gs + 1)
    return 2 * precision * recall / (precision + recall)


def evaluate_spans(pred_starts, pred_ends, gold_starts, gold_ends) -> dict:
    """Aggregate position-span EM/F1 as percentages (SQuAD convention)."""
    em, f1, n = 0.0, 0.0, 0
    for ps, pe, gs, ge in zip(np.asarray(pred_starts), np.asarray(pred_ends),
                              np.asarray(gold_starts), np.asarray(gold_ends)):
        em += span_exact_match((ps, pe), (gs, ge))
        f1 += span_f1((ps, pe), (gs, ge))
        n += 1
    return {"exact_match": 100.0 * em / max(n, 1),
            "f1": 100.0 * f1 / max(n, 1), "total": n}
