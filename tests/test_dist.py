"""Mesh + collectives smoke tests.

Equivalent of /root/reference/tests/unit/test_dist.py (init, allreduce
correctness vs closed form) on the 8-fake-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.parallel import comm, topology


def test_eight_fake_devices():
    assert len(jax.devices()) == 8
    assert jax.devices()[0].platform == "cpu"


def test_make_mesh_shapes():
    mesh = topology.make_mesh(model_parallel_size=2)
    assert topology.data_parallel_size(mesh) == 4
    assert topology.model_parallel_size(mesh) == 2
    mesh = topology.make_mesh()
    assert topology.data_parallel_size(mesh) == 8
    with pytest.raises(ValueError):
        topology.make_mesh(model_parallel_size=3)


def test_allreduce_matches_closed_form():
    # world of 8, each rank contributes rank+1; sum = 36, mean = 4.5
    mesh = topology.make_mesh()
    x = jnp.arange(1.0, 9.0)  # global array, one value per rank

    def body(xs):
        g = {"w": xs}
        out = comm.allreduce_grads(g, topology.DATA_AXIS, world_size=8)
        return out["w"]

    y = jax.jit(jax.shard_map(body, mesh=mesh,
                              in_specs=P(topology.DATA_AXIS),
                              out_specs=P(topology.DATA_AXIS)))(x)
    np.testing.assert_allclose(np.asarray(y), np.full((8,), 4.5))


def test_allreduce_prescale_matches_postscale():
    mesh = topology.make_mesh()
    x = jnp.arange(8.0).reshape(8, 1)

    def run(**kw):
        def body(xs):
            return comm.allreduce_grads({"w": xs}, topology.DATA_AXIS,
                                        world_size=8, **kw)["w"]
        return np.asarray(jax.jit(jax.shard_map(
            body, mesh=mesh, in_specs=P(topology.DATA_AXIS),
            out_specs=P(topology.DATA_AXIS)))(x))

    post = run()
    pre = run(prescale_gradients=True, gradient_predivide_factor=1.0)
    half = run(prescale_gradients=True, gradient_predivide_factor=2.0)
    np.testing.assert_allclose(post, pre, rtol=1e-6)
    np.testing.assert_allclose(post, half, rtol=1e-6)


def test_fp32_allreduce_upcasts():
    mesh = topology.make_mesh()
    # bf16 inputs whose exact sum needs more than bf16 mantissa
    x = jnp.full((8, 4), 1.001, jnp.bfloat16)

    def body(xs):
        out = comm.allreduce_grads({"w": xs}, topology.DATA_AXIS, world_size=8,
                                   fp32_allreduce=True)
        return out["w"]

    y = jax.jit(jax.shard_map(body, mesh=mesh,
                              in_specs=P(topology.DATA_AXIS),
                              out_specs=P(topology.DATA_AXIS)))(x)
    assert y.dtype == jnp.bfloat16  # cast back after fp32 reduce


def test_reduce_scatter_then_allgather_roundtrip():
    mesh = topology.make_mesh()
    world = 8
    n = 64
    # every rank holds the same flat grad; reduce-scatter then allgather must
    # equal the allreduced mean
    flat = jnp.arange(float(n))
    stacked = jnp.tile(flat, (world, 1))  # [world, n] sharded over data

    def body(local):
        # local: [1, n] this rank's copy
        part = comm.reduce_scatter_grads(local[0], topology.DATA_AXIS, world)
        full = comm.allgather_params(part, topology.DATA_AXIS)
        return full[None]

    y = jax.jit(jax.shard_map(body, mesh=mesh,
                              in_specs=P(topology.DATA_AXIS, None),
                              out_specs=P(topology.DATA_AXIS, None)))(stacked)
    np.testing.assert_allclose(np.asarray(y[0]), np.arange(float(n)), rtol=1e-6)


def test_overflow_any_agrees_across_ranks():
    mesh = topology.make_mesh()
    # rank 3 sees an overflow; everyone must agree
    flags = jnp.zeros((8,)).at[3].set(1.0)

    def body(f):
        return jnp.asarray(
            comm.overflow_any(f[0] > 0, topology.DATA_AXIS), jnp.float32)[None]

    y = jax.jit(jax.shard_map(body, mesh=mesh,
                              in_specs=P(topology.DATA_AXIS),
                              out_specs=P(topology.DATA_AXIS)))(flags)
    np.testing.assert_array_equal(np.asarray(y), np.ones((8,)))


def test_mpi_discovery(monkeypatch):
    monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "3")
    monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "16")
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.5")
    monkeypatch.setenv("MASTER_PORT", "12345")
    info = topology.mpi_discovery()
    assert info == {"rank": 3, "world_size": 16,
                    "coordinator_address": "10.0.0.5:12345"}


def test_mpi_discovery_missing(monkeypatch):
    for v in ("OMPI_COMM_WORLD_RANK", "PMI_RANK", "SLURM_PROCID"):
        monkeypatch.delenv(v, raising=False)
    with pytest.raises(RuntimeError):
        topology.mpi_discovery()
