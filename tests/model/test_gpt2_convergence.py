"""Model-scale convergence/parity tier.

The reference's model tier trains Megatron-GPT2 for 1000 steps and asserts
LM-loss parity against a non-DeepSpeed baseline at rtol 1e-2 over an
mp x gpus matrix, plus checkpoint resume-parity mid-run
(/root/reference/tests/model/Megatron_GPT2/run_func_test.py:14-30,169-215,
run_checkpoint_test.py:46-80).  The TPU analog below:

* a plain-JAX baseline loop (no engine, no sharding, fp32 Adam) trains the
  SAME GPT-2 config on the SAME synthetic Markov-Zipf corpus;
* the engine trains it across {mp=1,2} x {zero on/off} x {bf16,fp16} and the
  final smoothed loss must match the baseline within 1%;
* a checkpoint saved at the midpoint and resumed in a fresh engine must
  reproduce the unbroken run's trajectory.

Scaled to CI: hidden 64 x 2 layers x seq 32, 300 steps — big enough that a
wrong collective, loss-scale FSM, or ZeRO partition visibly diverges, small
enough for the 8-fake-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2
from deepspeed_tpu.ops import optim as optim_mod
from deepspeed_tpu.parallel.topology import make_mesh

VOCAB, SEQ = 128, 32
BATCH = 16
STEPS = 300
RESUME_AT = 150
LR = 3e-3


def model_fn():
    return GPT2.from_size("tiny", vocab_size=VOCAB, max_seq_len=SEQ,
                          num_layers=2, hidden_size=64, num_heads=4)


def corpus(steps=STEPS, batch=BATCH, seed=0):
    """Markov chain with Zipf-ish marginals: next token is a deterministic
    affine map of the current one 80% of the time, resampled from a Zipf
    otherwise — learnable bigram structure, so the loss drops well below the
    unigram entropy and a diverging run is unmistakable."""
    rng = np.random.default_rng(seed)
    zipf = 1.0 / np.arange(1, VOCAB + 1)
    zipf /= zipf.sum()
    out = []
    for _ in range(steps):
        toks = np.empty((batch, SEQ), np.int32)
        toks[:, 0] = rng.choice(VOCAB, size=batch, p=zipf)
        for t in range(1, SEQ):
            det = (toks[:, t - 1] * 31 + 7) % VOCAB
            noise = rng.choice(VOCAB, size=batch, p=zipf)
            keep = rng.random(batch) < 0.8
            toks[:, t] = np.where(keep, det, noise)
        labels = np.roll(toks, -1, axis=1)
        labels[:, -1] = -1
        out.append((toks, labels))
    return out


@pytest.fixture(scope="module")
def data():
    return corpus()


@pytest.fixture(scope="module")
def baseline_losses(data):
    """Plain-JAX training loop: fp32, single device semantics, the engine's
    own Adam math but none of its machinery — the reference's 'run Megatron
    without deepspeed' baseline (run_func_test.py:169-215)."""
    from jax.sharding import PartitionSpec as P
    model = model_fn()
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, jnp.float32),
        model.init_params(jax.random.PRNGKey(11)))
    opt = optim_mod.Adam(lr=LR)
    state = opt.init(params)
    # the TP layers use axis_index, so even the single-device baseline runs
    # under shard_map — over a trivial 1-device mesh, no actual sharding
    mesh = make_mesh(model_parallel_size=1, devices=jax.devices()[:1])

    def local(params, state, toks, labels):
        def loss_fn(p):
            return model.apply(p, toks, labels)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_state = opt.update(params, grads, state, lr=LR)
        return new_params, new_state, loss

    step = jax.jit(jax.shard_map(
        local, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                  jax.tree_util.tree_map(lambda _: P(), state),
                  P(), P()),
        out_specs=(jax.tree_util.tree_map(lambda _: P(), params),
                   jax.tree_util.tree_map(lambda _: P(), state),
                   P()),
        check_vma=False))

    losses = []
    for toks, labels in data:
        params, state, loss = step(params, state, toks, labels)
        losses.append(float(loss))
    return losses


def run_engine(data, mp=1, zero=False, precision="bf16", steps=STEPS,
               engine=None, start=0):
    if engine is None:
        engine = make_engine(mp=mp, zero=zero, precision=precision)
    losses = []
    for toks, labels in data[start:start + steps]:
        losses.append(float(engine.train_batch((toks, labels))))
    return losses, engine


def make_engine(mp=1, zero=False, precision="bf16", seed=11):
    cfg = {
        "train_batch_size": BATCH,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "Adam", "params": {"lr": LR}},
    }
    if precision == "fp16":
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    elif precision == "bf16":
        cfg["bf16"] = {"enabled": True}
    if zero:
        cfg["zero_optimization"] = True
    model = model_fn()
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(seed)),
        mesh=make_mesh(model_parallel_size=mp))
    return engine


def tail_mean(losses, k=20):
    return float(np.mean(losses[-k:]))


@pytest.mark.parametrize("mp,zero,precision", [
    (1, False, "fp32"),
    (1, False, "bf16"),
    (1, False, "fp16"),
    (2, False, "bf16"),
    (2, False, "fp16"),
    (1, True, "fp16"),
    (2, True, "fp16"),
    (2, True, "bf16"),
])
def test_convergence_matches_baseline(data, baseline_losses, mp, zero,
                                      precision):
    """Final smoothed LM loss within 1% of the plain-JAX fp32 baseline
    (reference asserts rtol 1e-2 on the LM loss curve,
    run_func_test.py:214)."""
    losses, engine = run_engine(data, mp=mp, zero=zero, precision=precision)
    assert all(np.isfinite(losses))
    base = tail_mean(baseline_losses)
    got = tail_mean(losses)
    # sanity: the model actually learned the bigram structure
    assert got < 0.8 * losses[0]
    assert abs(got - base) / base < 0.01, (got, base)
    if precision == "fp16":
        assert engine.optimizer.cur_scale > 0


def test_resume_parity_midrun(data):
    """Save at step RESUME_AT, restore in a fresh engine, continue: the
    resumed trajectory must match the unbroken run (reference
    run_checkpoint_test.py:46-80)."""
    full, _ = run_engine(data, mp=2, zero=True, precision="fp16")

    first, e1 = run_engine(data, mp=2, zero=True, precision="fp16",
                           steps=RESUME_AT)
    import tempfile
    d = tempfile.mkdtemp()
    e1.save_checkpoint(d)

    e2 = make_engine(mp=2, zero=True, precision="fp16", seed=77)
    path, _ = e2.load_checkpoint(d)
    assert path is not None
    rest, _ = run_engine(data, engine=e2, steps=STEPS - RESUME_AT,
                         start=RESUME_AT)
    np.testing.assert_allclose(first + rest, full, rtol=0, atol=0)
