"""Loss-scale FSM trajectories.

FSM-level equivalent of /root/reference/tests/unit/test_dynamic_loss_scale.py:
the reference injects inf/nan/uniform grads into a live engine and asserts the
exact scale trajectory; here the FSM is a pure function so the same
trajectories are asserted directly (the engine-level version is covered again
in test_fp16.py once an engine is in the loop).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import precision as P


def steps(state, overflows, variant):
    """Run the FSM over a list of overflow booleans, returning the state after
    each transition."""
    out = []
    fsm = jax.jit(lambda s, o: P.update_loss_scale(s, o, variant=variant),
                  static_argnames=())
    for o in overflows:
        state = P.update_loss_scale(state, o, variant=variant)
        out.append(state)
    return out


@pytest.mark.parametrize("variant", [P.INLINE, P.MEGATRON])
def test_no_overflow_doubling(variant):
    # initial_scale_power 8, window 2 (reference test_fused_no_overflow)
    state = P.make_loss_scale_state(init_scale=2 ** 8, scale_window=2)
    expected = 2.0 ** 8
    for i, st in enumerate(steps(state, [False] * 10, variant)):
        if variant == P.INLINE:
            assert float(st.cur_scale) == expected
            assert int(st.cur_iter) == i + 1
            if int(st.cur_iter) % 2 == 0:
                expected *= 2
        else:
            # MEGATRON doubles when (cur_iter - (-1)) % window == 0: iters 1,3,5...
            pass
    if variant == P.MEGATRON:
        st = steps(P.make_loss_scale_state(init_scale=2 ** 8, scale_window=2),
                   [False] * 4, variant)
        # transition at cur_iter=1 -> (1-(-1))%2==0 -> double; cur_iter=3 -> double
        assert [float(s.cur_scale) for s in st] == [256.0, 512.0, 512.0, 1024.0]


def test_inline_all_overflow_floor():
    # initial 2**4, every step overflows: halve to floor 1
    # (reference test_fused_all_overflow)
    state = P.make_loss_scale_state(init_scale=2 ** 4, scale_window=2)
    expected = 2.0 ** 4
    for i, st in enumerate(steps(state, [True] * 8, P.INLINE)):
        expected = max(expected / 2, 1.0)
        assert float(st.cur_scale) == expected
        assert int(st.cur_iter) == i + 1


def test_inline_all_overflow_custom_min():
    # min_loss_scale 0.25 honored (reference test_unfused_all_overflow)
    state = P.make_loss_scale_state(init_scale=2 ** 4, scale_window=2,
                                    min_scale=0.25)
    expected = 2.0 ** 4
    for st in steps(state, [True] * 8, P.INLINE):
        expected = max(expected / 2, 0.25)
        assert float(st.cur_scale) == expected


def test_inline_some_overflow():
    # reference test_fused_some_overflow: 2 overflows, window+1 clean, 1 overflow
    state = P.make_loss_scale_state(init_scale=2 ** 8, scale_window=2)
    scale = 2.0 ** 8
    hist = steps(state, [True, True] + [False] * 3 + [True], P.INLINE)
    # two overflows: /4
    assert float(hist[1].cur_scale) == scale / 4
    # window+1 clean steps: one doubling
    assert float(hist[4].cur_scale) == scale / 2
    # final overflow: halve again
    assert float(hist[5].cur_scale) == scale / 4
    assert int(hist[5].cur_iter) == 6


def test_megatron_hysteresis():
    # delayed_shift=2: first overflow only burns hysteresis, second halves
    # (reference loss_scaler.py:153-159)
    state = P.make_loss_scale_state(init_scale=2 ** 8, scale_window=1000,
                                    delayed_shift=2)
    hist = steps(state, [True, True, True], P.MEGATRON)
    assert float(hist[0].cur_scale) == 2.0 ** 8      # hysteresis absorbed
    assert int(hist[0].cur_hysteresis) == 1
    assert float(hist[1].cur_scale) == 2.0 ** 7      # now halves
    assert float(hist[2].cur_scale) == 2.0 ** 6      # keeps halving


def test_static_scale_never_moves():
    state = P.static_loss_scale_state(128.0)
    for st in steps(state, [True, False, True, False], P.INLINE):
        assert float(st.cur_scale) == 128.0
    assert int(st.cur_iter) == 4


def test_fsm_is_jittable():
    state = P.make_loss_scale_state(init_scale=2 ** 8, scale_window=2)
    step = jax.jit(lambda s, o: P.update_loss_scale(s, o, variant=P.INLINE))
    st = step(state, jnp.asarray(True))
    assert float(st.cur_scale) == 2.0 ** 7


def test_has_overflow():
    good = {"a": jnp.ones((4, 4)), "b": jnp.zeros((3,))}
    assert not bool(P.has_overflow(good))
    bad = {"a": jnp.ones((4, 4)), "b": jnp.array([1.0, jnp.inf, 0.0])}
    assert bool(P.has_overflow(bad))
    nan = {"a": jnp.array([jnp.nan]), "b": None}
    assert bool(P.has_overflow(nan))
    assert not bool(P.has_overflow({}))


def test_scale_and_unscale_roundtrip():
    state = P.make_loss_scale_state(init_scale=1024.0)
    loss = jnp.asarray(2.5, jnp.float16)
    scaled = P.scale_loss(loss, state)
    assert scaled.dtype == jnp.float32
    assert float(scaled) == 2.5 * 1024.0
    grads = {"w": jnp.full((8,), 512.0, jnp.float16)}
    un = P.unscale(grads, state)
    np.testing.assert_allclose(np.asarray(un["w"]), 0.5)


def test_combined_unscale_and_clip():
    state = P.make_loss_scale_state(init_scale=4.0)
    # unscaled norm 10, clip 1.0 -> combined ≈ 10*4
    c = P.combined_unscale_and_clip_factor(jnp.asarray(40.0), state, 1.0)
    np.testing.assert_allclose(float(c), (10.0 + 1e-6 / 4 * 4) * 4.0, rtol=1e-5)
    # norm below clip threshold -> plain scale
    c = P.combined_unscale_and_clip_factor(jnp.asarray(2.0), state, 1.0)
    assert float(c) == 4.0
    # clipping disabled
    c = P.combined_unscale_and_clip_factor(jnp.asarray(1e9), state, 0.0)
    assert float(c) == 4.0


def test_policy_selection():
    assert P.policy_from_config(True, False).compute_dtype == jnp.float16
    assert P.policy_from_config(True, False).needs_loss_scale
    assert P.policy_from_config(False, True).compute_dtype == jnp.bfloat16
    assert not P.policy_from_config(False, True).needs_loss_scale
    assert P.policy_from_config(False, False).compute_dtype == jnp.float32
