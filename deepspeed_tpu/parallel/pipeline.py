"""Pipeline parallelism: a GPipe schedule over the ``pipe`` mesh axis.

Beyond-reference component (the reference v0.1.0 has no pipeline engine —
SURVEY.md §0 lists it as explicitly absent; this is the TPU-native shape of
one).  Layer-stacked parameters shard their leading (layer) dimension over
``pipe`` so each stage owns ``L / pp`` consecutive blocks.  Execution is SPMD:
every stage runs the same program; micro-batches stream through a
``lax.scan`` over ``m + pp - 1`` ticks, each tick applying the stage's local
blocks and handing the activation to the next stage with a ``ppermute``.
Autodiff through ``ppermute`` (its transpose is the reverse permute) yields
the exact pipelined backward.  ``pipeline_1f1b_loss`` is the alternative
1F1B schedule: forward and backward micro-steps interleave in one scan
(custom_vjp), bounding in-flight stage inputs to a ``2·pp-1`` ring — select
it with ``"pipeline_schedule": "1f1b"`` in the engine config.

The finished micro-batches exist on the LAST stage; ``collect`` masks other
stages to zero and ``psum``s over ``pipe``, so downstream (head/loss) math is
replicated and uniform across stages — gradients of stage-replicated
parameters then arrive as per-stage partial contributions that the engine
sums over ``pipe`` (same rule as model-axis-replicated leaves).
"""

from __future__ import annotations

import logging
from typing import Callable

import jax
import jax.numpy as jnp

from deepspeed_tpu.parallel.topology import PIPE_AXIS

logger = logging.getLogger(__name__)

_warned_slow_paths: set = set()


def warn_slow_path_once(key: str, message: str) -> None:
    """One-time logger warning for a degraded schedule fallback.  These
    branches are taken at TRACE time (python-level shape checks), so the
    warning fires once per process when a config lands on the slow path —
    correct-but-wasteful fallbacks used to be silent (VERDICT r5 weak #5)."""
    if key in _warned_slow_paths:
        return
    _warned_slow_paths.add(key)
    logger.warning(message)


def pipeline_apply(x_micro: jnp.ndarray,
                   stage_fn: Callable[[jnp.ndarray], jnp.ndarray],
                   axis: str = PIPE_AXIS, with_aux: bool = False,
                   collect: str = "full"):
    """Run the GPipe schedule.

    x_micro:  [m, mb, ...] micro-batched activations, replicated over
              ``axis`` (every stage holds them; only stage 0 injects).
    stage_fn: applies THIS stage's local blocks to one [mb, ...]
              activation.  With ``with_aux`` it returns ``(y, aux)`` where
              aux is a scalar per-stage loss term (e.g. MoE load
              balancing); aux from bubble ticks (garbage activations) is
              masked out, and per-stage totals psum over ``axis``.
    collect:  ``"full"`` — [m, mb, ...] outputs replicated over ``axis``
              (masked psum from the last stage).  ``"scatter"`` — each
              stage receives only ITS [m, mb/pp, ...] batch slice via one
              ``psum_scatter`` over the micro-batch dim (requires
              mb % pp == 0): half the wire bytes of the full collect and
              1/pp the delivered activation memory (VERDICT r4 weak #6) —
              feed it to ``pipe_scattered_loss``.

    Returns the collected outputs plus the pipe-uniform aux sum when
    ``with_aux``.  Must run inside shard_map over a mesh with ``axis``.
    """
    pp = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    m = x_micro.shape[0]
    perm = [(i, (i + 1) % pp) for i in range(pp)]
    is_first = (stage == 0)
    is_last = (stage == pp - 1)

    def tick(carry, t):
        buf, outputs, aux_acc = carry
        # stage 0 ingests micro-batch t (clipped re-injections past the end
        # never reach the last stage within the scan — wasted, not wrong)
        inject = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        cur = jnp.where(is_first, inject, buf)
        if with_aux:
            y, aux = stage_fn(cur)
            # this stage's tick t computes micro f = t - stage; other
            # ticks are bubbles whose aux is garbage
            f = t - stage
            aux_acc = aux_acc + jnp.where(
                (f >= 0) & (f < m), jnp.asarray(aux, jnp.float32), 0.0)
        else:
            y = stage_fn(cur)
        # the last stage's y at tick t is finished micro t - (pp - 1)
        out_t = t - (pp - 1)
        updated = jax.lax.dynamic_update_index_in_dim(
            outputs, y.astype(outputs.dtype), jnp.clip(out_t, 0, m - 1),
            axis=0)
        outputs = jnp.where(out_t >= 0, updated, outputs)
        # hand off to the next stage (the wrap edge pp-1 -> 0 carries only
        # garbage that stage 0 immediately overwrites with its injection)
        buf = jax.lax.ppermute(y, axis, perm)
        return (buf, outputs, aux_acc), None

    buf0 = jnp.zeros_like(x_micro[0])
    out0 = jnp.zeros_like(x_micro)
    (_, outputs, aux_acc), _ = jax.lax.scan(
        tick, (buf0, out0, jnp.zeros((), jnp.float32)),
        jnp.arange(m + pp - 1))
    # only the last stage holds real outputs
    outputs = jnp.where(is_last, outputs, jnp.zeros_like(outputs))
    if collect == "scatter":
        if x_micro.shape[1] % pp:
            raise ValueError(
                f"collect='scatter' needs the micro-batch size "
                f"({x_micro.shape[1]}) divisible by pp ({pp})")
        outputs = jax.lax.psum_scatter(outputs, axis,
                                       scatter_dimension=1, tiled=True)
    else:
        outputs = jax.lax.psum(outputs, axis)
    if with_aux:
        # stages own disjoint layers: the global aux is the psum of the
        # per-stage micro-masked totals (pipe-uniform, like the loss)
        return outputs, jax.lax.psum(aux_acc, axis)
    return outputs


def pipeline_1f1b_loss(stage_fn, head_fn, blocks, head_params, x_micro,
                       labels_micro, count_total, axis: str = PIPE_AXIS,
                       with_aux: bool = False):
    """Pipeline forward+loss with a 1F1B (one-forward-one-backward)
    gradient schedule.

    Beyond-reference (the reference v0.1.0 has no pipeline; this is the
    memory-optimal schedule GPipe's ``pipeline_apply`` docstring deferred
    to rematerialisation).  Primal value: the masked-mean loss over all
    micro-batches, pipe-uniform.  Differentiating it runs the interleaved
    schedule in ``_run_1f1b``: each of the ``m + 2(pp-1)`` ticks performs
    one forward micro-step AND one backward micro-step per stage (either
    may be a bubble), the backward recomputing the stage body from its
    saved INPUT (activation recompute — the same trade ``remat='full'``
    makes).  In-flight stage inputs are bounded by a ``min(m, 2·pp-1)``
    ring instead of the ``m + pp - 1`` per-tick carries GPipe autodiff
    saves — the 1F1B memory win at large micro-batch counts.

    Args:
      stage_fn: ``(blocks_local, x[mb, ...]) -> y`` — this stage's blocks
                (``(y, aux_scalar)`` when ``with_aux``: the per-stage aux
                terms — e.g. MoE load balancing — are averaged over
                micro-batches, psum'd over stages, and added to the
                loss, matching the GPipe path's convention).
      head_fn:  ``(head_params, y, labels[mb, ...]) -> loss SUM`` (masked
                sum, fp32 scalar; labels arrive with their original
                integer dtype) — runs per micro on the last stage.
      blocks:   pipe-sharded stacked block params (this stage's slice).
      head_params: pipe-replicated head/embedding params (pytree).
      x_micro:  [m, mb, ...] micro-batched activations.
      labels_micro: [m, mb, ...] integer labels (no gradient).
      count_total: fp32 scalar — the global valid-token count the loss
                normalises by (computable from labels up front).

    Gradient convention: emitted cotangents carry the SAME uniform
    pp-factor as GPipe autodiff (engine._make_loss_and_grads divides by
    pp and psums pipe-replicated leaves), so the engine composes
    unchanged: head/input cotangents are per-stage partials (nonzero on
    one stage only), block cotangents are exact per-stage grads — all
    scaled by pp here.
    """
    lab_dtype = jnp.asarray(labels_micro).dtype
    # labels ride through custom_vjp as fp32 (exact for token ids) so their
    # cotangent is an ordinary zeros array instead of a float0
    labf = jnp.asarray(labels_micro).astype(jnp.float32)
    lab_shape = tuple(labf.shape)
    hfn = lambda hp, y, lf: head_fn(hp, y, lf.astype(lab_dtype))

    # normalize to the (y, aux) stage signature internally
    sfn = (stage_fn if with_aux
           else (lambda bl, u: (stage_fn(bl, u), 0.0)))

    @jax.custom_vjp
    def run(blocks, head_params, x_micro, labf, count_total):
        return _forward_1f1b(sfn, hfn, axis, blocks, head_params,
                             x_micro, labf, count_total)

    def fwd(blocks, head_params, x_micro, labf, count_total):
        return _run_1f1b(sfn, hfn, axis, blocks, head_params,
                         x_micro, labf, count_total)

    def bwd(res, g):
        gblocks, ghead, dx_out = res
        scale = jnp.asarray(g, jnp.float32) * jax.lax.axis_size(axis)
        sc = lambda tree: jax.tree_util.tree_map(
            lambda x: (x * scale).astype(x.dtype), tree)
        return (sc(gblocks), sc(ghead), sc(dx_out),
                jnp.zeros(lab_shape, jnp.float32),
                jnp.zeros((), jnp.float32))

    run.defvjp(fwd, bwd)
    return run(blocks, head_params, x_micro, labf, count_total)


def _forward_1f1b(stage_fn, head_fn, axis, blocks, head_params, x_micro,
                  labf, count_total):
    """Forward-only sweep + per-micro head on the last stage — the cheap
    primal for eval / non-differentiated calls."""
    pp = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    m = x_micro.shape[0]
    is_last = stage == pp - 1

    def tick(buf, t):
        inject = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(t, 0, m - 1), axis=0, keepdims=False)
        cur = jnp.where(stage == 0, inject, buf)
        y, aux = stage_fn(blocks, cur)
        f = t - stage
        aux = jnp.where((f >= 0) & (f < m),
                        jnp.asarray(aux, jnp.float32), 0.0)
        out_t = t - (pp - 1)
        lab = jax.lax.dynamic_index_in_dim(
            labf, jnp.clip(out_t, 0, m - 1), axis=0, keepdims=False)
        lsum = head_fn(head_params, y, lab)
        lsum = jnp.where(is_last & (out_t >= 0),
                         jnp.asarray(lsum, jnp.float32), 0.0)
        return jax.lax.ppermute(y, axis, [(i, (i + 1) % pp)
                                          for i in range(pp)]), (lsum, aux)

    _, (lsums, auxes) = jax.lax.scan(tick, jnp.zeros_like(x_micro[0]),
                                     jnp.arange(m + pp - 1))
    loss_sum = jax.lax.psum(jnp.sum(lsums), axis)
    aux_mean = jax.lax.psum(jnp.sum(auxes), axis) / m
    return loss_sum / jnp.maximum(count_total, 1.0) + aux_mean


def _run_1f1b(stage_fn, head_fn, axis, blocks, head_params, x_micro,
              labf, count_total):
    """The interleaved schedule; returns (loss, (dblocks, dhead,
    dx_micro)) with UNSCALED (true, per-stage partial) loss cotangents."""
    pp = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    m = x_micro.shape[0]
    R = min(m, 2 * pp - 1)              # in-flight stage-input ring
    is_first = stage == 0
    is_last = stage == pp - 1
    fwd_perm = [(i, (i + 1) % pp) for i in range(pp)]
    bwd_perm = [(i, (i - 1) % pp) for i in range(pp)]
    count = jnp.maximum(count_total, 1.0)
    seed = 1.0 / count                   # d(loss)/d(per-micro loss sum)

    def tick(carry, t):
        (fwd_buf, bwd_buf, ring, dx_out, gblocks, ghead, loss_sum,
         aux_sum) = carry

        # ---- forward sub-step: micro f enters this stage
        f = t - stage
        active_f = (f >= 0) & (f < m)
        inject = jax.lax.dynamic_index_in_dim(
            x_micro, jnp.clip(f, 0, m - 1), axis=0, keepdims=False)
        fin = jnp.where(is_first, inject, fwd_buf)
        ring = jnp.where(
            active_f,
            jax.lax.dynamic_update_index_in_dim(
                ring, fin, jnp.mod(f, R), axis=0),
            ring)
        fwd_send, _ = stage_fn(blocks, fin)

        # ---- backward sub-step: micro b leaves this stage (recompute
        # from the saved input; on the last stage b == f, so the head's
        # fwd+bwd run in the tick the micro finishes its forward)
        b = t - (2 * (pp - 1) - stage)
        active_b = (b >= 0) & (b < m)
        xb = jax.lax.dynamic_index_in_dim(
            ring, jnp.mod(b, R), axis=0, keepdims=False)
        (yb, aux_b), pull = jax.vjp(stage_fn, blocks, xb)

        mb = x_micro.shape[1]
        if mb % pp == 0 and pp > 1:
            # SHARDED in-schedule head (r5, the cost model's biggest
            # finding): under SPMD the head VJP used to run on EVERY
            # stage every tick with all but the last stage's masked —
            # 3 head units/tick of pure waste.  Instead: broadcast the
            # LAST stage's recompute output, each stage computes the head
            # fwd+VJP on ITS 1/pp batch slice (micro b_last = t-(pp-1),
            # the micro the last stage is backwarding), and the dy slices
            # psum-reassemble.  Head cost per tick drops to 3/pp units
            # + two [mb,...] collectives; head grads become per-stage
            # partials the engine's pipe-psum already sums.
            sl = mb // pp
            b_last = t - (pp - 1)
            active_h = (b_last >= 0) & (b_last < m)
            yb_last = jax.lax.psum(
                jnp.where(is_last, yb, jnp.zeros_like(yb)), axis)
            ys = jax.lax.dynamic_slice_in_dim(yb_last, stage * sl, sl,
                                              axis=0)
            lab_h = jax.lax.dynamic_index_in_dim(
                labf, jnp.clip(b_last, 0, m - 1), axis=0, keepdims=False)
            lab_s = jax.lax.dynamic_slice_in_dim(lab_h, stage * sl, sl,
                                                 axis=0)
            lsum_s, hpull = jax.vjp(
                lambda hp, yy: jnp.asarray(head_fn(hp, yy, lab_s),
                                           jnp.float32),
                head_params, ys)
            dhead_b, dy_s = hpull(jnp.asarray(seed, jnp.float32))
            dy_full = jax.lax.psum(
                jax.lax.dynamic_update_slice_in_dim(
                    jnp.zeros_like(yb_last), dy_s.astype(yb_last.dtype),
                    stage * sl, axis=0), axis)
            dy = jnp.where(is_last, dy_full.astype(yb.dtype), bwd_buf)
            # accumulate the LOCAL slice partial; the end-of-scan
            # psum(loss_sum) totals it — no per-tick scalar collective
            lsum = lsum_s
            acc_h = jnp.where(active_h, 1.0, 0.0)   # partials, ALL stages
            loss_active = active_h
        else:
            # replicated fallback (mb not divisible by pp): every stage
            # runs the full head on its own yb; only the last stage's is
            # real
            if pp > 1:
                warn_slow_path_once(
                    "1f1b_replicated_head",
                    f"1F1B head VJP is running REPLICATED on all {pp} "
                    f"stages (micro-batch size {mb} not divisible by "
                    f"pp={pp}): every stage pays the full head fwd+bwd "
                    f"each tick with all but one masked — pad or resize "
                    f"the micro-batch to a multiple of pp for the "
                    f"1/pp-sharded head")
            lab = jax.lax.dynamic_index_in_dim(
                labf, jnp.clip(b, 0, m - 1), axis=0, keepdims=False)
            lsum, hpull = jax.vjp(
                lambda hp, yy: jnp.asarray(head_fn(hp, yy, lab),
                                           jnp.float32),
                head_params, yb)
            dhead_b, dy_head = hpull(jnp.asarray(seed, jnp.float32))
            dy = jnp.where(is_last, dy_head.astype(yb.dtype), bwd_buf)
            acc_h = jnp.where(active_b & is_last, 1.0, 0.0)
            loss_active = active_b & is_last
        # aux averages over micros: d(loss)/d(aux_b) = 1/m (bubble ticks
        # are zeroed by the acc_b accumulation mask below)
        daux = jnp.asarray(1.0 / m, jnp.result_type(aux_b))
        dblocks_b, dxin = pull((dy, daux))

        acc_b = jnp.where(active_b, 1.0, 0.0)
        gblocks = jax.tree_util.tree_map(
            lambda a, g: a + acc_b * g, gblocks, dblocks_b)
        ghead = jax.tree_util.tree_map(
            lambda a, g: a + acc_h * g, ghead, dhead_b)
        dx_out = jnp.where(
            active_b & is_first,
            jax.lax.dynamic_update_index_in_dim(
                dx_out, dxin, jnp.clip(b, 0, m - 1), axis=0),
            dx_out)
        loss_sum = loss_sum + jnp.where(loss_active,
                                        lsum.astype(jnp.float32), 0.0)
        aux_sum = aux_sum + jnp.where(
            active_b, jnp.asarray(aux_b, jnp.float32), 0.0)

        fwd_buf = jax.lax.ppermute(fwd_send, axis, fwd_perm)
        bwd_buf = jax.lax.ppermute(dxin, axis, bwd_perm)
        return (fwd_buf, bwd_buf, ring, dx_out, gblocks, ghead,
                loss_sum, aux_sum), None

    zeros_like_tree = lambda tree: jax.tree_util.tree_map(
        lambda x: jnp.zeros(x.shape, x.dtype), tree)
    carry0 = (
        jnp.zeros_like(x_micro[0]),
        jnp.zeros_like(x_micro[0]),
        jnp.zeros((R,) + x_micro.shape[1:], x_micro.dtype),
        jnp.zeros_like(x_micro),
        zeros_like_tree(blocks),
        zeros_like_tree(head_params),
        jnp.zeros((), jnp.float32),
        jnp.zeros((), jnp.float32),
    )
    (_, _, _, dx_out, gblocks, ghead, loss_sum, aux_sum), _ = jax.lax.scan(
        tick, carry0, jnp.arange(m + 2 * (pp - 1)))
    loss = (jax.lax.psum(loss_sum, axis) / count
            + jax.lax.psum(aux_sum, axis) / m)
    return loss, (gblocks, ghead, dx_out)


def mask_to_last_stage(value: jnp.ndarray, axis: str = PIPE_AXIS):
    """Zero ``value`` except on the last stage, then psum — the loss-side
    collection rule: keeps the loss (and therefore every replicated-leaf
    gradient) a SUM of per-stage contributions, exactly one of which is
    nonzero."""
    pp = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    masked = jnp.where(stage == pp - 1, value, jnp.zeros_like(value))
    return jax.lax.psum(masked, axis)


def pipe_scattered_loss(x_local: jnp.ndarray, labels_local: jnp.ndarray,
                        head_fn, axis: str = PIPE_AXIS) -> jnp.ndarray:
    """Head + loss over PRE-SCATTERED per-stage slices (the
    ``collect="scatter"`` companion): ``head_fn`` returns the masked
    ``(loss_sum, valid_count)`` pair for this stage's rows, and the
    partial sums psum into the pipe-uniform masked mean — identical math
    to ``pipe_sharded_loss`` without ever materialising the full batch
    on every stage."""
    loss_sum, count = head_fn(x_local, labels_local)
    loss_sum = jax.lax.psum(jnp.asarray(loss_sum, jnp.float32), axis)
    count = jax.lax.psum(jnp.asarray(count, jnp.float32), axis)
    return loss_sum / jnp.maximum(count, 1.0)


def pipe_sharded_loss(x: jnp.ndarray, labels: jnp.ndarray, head_fn,
                      axis: str = PIPE_AXIS) -> jnp.ndarray:
    """Head + loss with the O(V·H) work SHARDED over the pipe stages.

    Each stage runs ``head_fn`` (LN → logits → per-token CE, returning the
    masked ``(loss_sum, valid_count)`` pair) on ITS 1/pp slice of the batch
    and the partial sums psum over ``axis`` — the per-stage head cost drops
    from O(B·T·V·H) replicated (VERDICT r2 weak #1) to O(B·T·V·H / pp),
    and the returned scalar equals the full-batch masked mean bit-for-bit
    up to reduction order.

    Gradient shape: the loss stays pipe-uniform (a psum of per-stage
    partials), so the engine's uniform-pp-factor correction and
    replicated-leaf pipe-psum rules apply unchanged.
    """
    pp = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    B = x.shape[0]
    if B % pp:
        # per-shard batch doesn't split across the stages: fall back to the
        # replicated head masked to the last stage — same gradients, head
        # cost replicated pp x (correct for any B, just not sharded)
        loss_sum, count = head_fn(x, labels)
        val = (jnp.asarray(loss_sum, jnp.float32)
               / jnp.maximum(jnp.asarray(count, jnp.float32), 1.0))
        return mask_to_last_stage(val, axis)
    sl = B // pp
    xs = jax.lax.dynamic_slice_in_dim(x, stage * sl, sl, axis=0)
    ys = jax.lax.dynamic_slice_in_dim(labels, stage * sl, sl, axis=0)
    return pipe_scattered_loss(xs, ys, head_fn, axis)
