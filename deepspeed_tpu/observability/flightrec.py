"""Flight recorder — a bounded host-side ring of recent engine events.

When a fleet diverges — one rank wedged in a collective, the rest blocked
behind it — the question a post-mortem must answer is *who was at which
step when*.  Stack dumps (resilience/watchdog.py) answer "where is this
thread NOW"; the flight recorder answers "what was this process doing for
the last N events": optimizer boundaries, program dispatches (the host-side
collective-sequence order), window drains, checkpoint IO, preemption
agreement, chaos injections.

Recording is deliberately cheap — a dict build and a deque append under a
lock, no device interaction, no fences — so it is always on.  The ring is
dumped to a named JSON file on:

* watchdog fire (``resilience/watchdog.py`` enriches its stack dump with
  the recorder tail AND writes a dump file),
* preemption drain and crash exit (``resilience/driver.py``),
* process exit when :data:`ENV_DUMP_AT_EXIT` is set (CI uses this so a
  healthy run still uploads artifacts).

One recorder per process (:data:`RECORDER`): the ring is a process-level
post-mortem artifact, not an engine-level one — the watchdog monitor
thread and the resilience driver reach it without an engine reference.
Importable without jax (the watchdog imports it; the launcher parent
imports the watchdog).
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from collections import deque
from typing import Optional

logger = logging.getLogger(__name__)

#: dump-file schema stamp (the dump is itself a machine-readable artifact)
DUMP_SCHEMA_ID = "dstpu.flightrec"
DUMP_SCHEMA_VERSION = 1

#: set to "1" to dump the ring at interpreter exit (reason ``exit``) —
#: the CI observability job sets it so flight-recorder artifacts exist
#: even on green runs
ENV_DUMP_AT_EXIT = "DSTPU_FLIGHTREC_DUMP_AT_EXIT"

#: env fallback for the dump directory (config
#: ``observability.flight_recorder_dir`` beats it)
ENV_DUMP_DIR = "DSTPU_FLIGHTREC_DIR"

DEFAULT_CAPACITY = 256

_UNSET = object()


class FlightRecorder:
    """Thread-safe bounded event ring with named dump files."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, rank: int = 0):
        self._lock = threading.Lock()
        self._ring = deque(maxlen=int(capacity) or 1)
        self.capacity = int(capacity)
        self.rank = int(rank)
        self.enabled = capacity > 0
        self.dump_dir: Optional[str] = None
        self._seq = 0
        self._dumped = {}       # reason -> path (idempotence per reason)

    def configure(self, capacity: int = None, rank: int = None,
                  dump_dir=_UNSET) -> None:
        """Re-point the process recorder (engine build: capacity/dir from
        config, rank from the initialized distributed runtime).  Existing
        entries are kept up to the new capacity; the per-reason dump
        idempotence resets — a fresh engine is a fresh post-mortem epoch.
        ``dump_dir`` is SET whenever passed, ``None`` included (falling
        back to :data:`ENV_DUMP_DIR`/cwd): a fresh engine must not keep
        dumping into the previous engine's directory."""
        with self._lock:
            self._dumped = {}
            if capacity is not None:
                self.capacity = int(capacity)
                self.enabled = capacity > 0
                self._ring = deque(self._ring if self.enabled else (),
                                   maxlen=int(capacity) or 1)
            if rank is not None:
                self.rank = int(rank)
            if dump_dir is not _UNSET:
                self.dump_dir = dump_dir

    # ------------------------------------------------------------ recording
    def record(self, kind: str, **fields) -> None:
        """Append one event (ts/seq stamped); drops silently when disabled.
        Called from the training thread (boundaries, dispatches), the
        runtime callback thread (window drains) and the watchdog monitor
        thread — hence the lock."""
        if not self.enabled:
            return
        entry = {"seq": None, "ts": time.time(), "kind": str(kind)}
        entry.update(fields)
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)

    def tail(self, n: int = None) -> list:
        with self._lock:
            entries = list(self._ring)
        return entries if n is None else entries[-int(n):]

    def format_tail(self, n: int = 16) -> str:
        """The last ``n`` entries as indented text — what the watchdog
        splices into its stack dump so the post-mortem names the stalled
        step/window without opening the dump file."""
        entries = self.tail(n)
        if not entries:
            return "  (empty)"
        now = time.time()
        lines = []
        for e in entries:
            extra = " ".join(f"{k}={e[k]}" for k in e
                             if k not in ("seq", "ts", "kind"))
            lines.append(f"  [-{now - e['ts']:8.3f}s] #{e['seq']} "
                         f"{e['kind']}" + (f" {extra}" if extra else ""))
        return "\n".join(lines)

    # --------------------------------------------------------------- dumping
    def resolve_dump_dir(self) -> str:
        return (self.dump_dir or os.environ.get(ENV_DUMP_DIR) or ".")

    def dump(self, reason: str, path: str = None) -> Optional[str]:
        """Write the ring to ``flightrec_rank<r>_<reason>.json`` (or an
        explicit ``path``) and return the path.  Idempotent per reason
        (a watchdog that fires twice must not truncate the first dump's
        evidence mid-read); best-effort — a dump failure must never mask
        the failure being dumped."""
        if not self.enabled:
            return None
        with self._lock:
            done = self._dumped.get(reason)
        if done is not None:
            return done
        if path is None:
            d = self.resolve_dump_dir()
            path = os.path.join(
                d, f"flightrec_rank{self.rank}_{reason}.json")
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            payload = {
                "schema": DUMP_SCHEMA_ID,
                "version": DUMP_SCHEMA_VERSION,
                "reason": reason,
                "rank": self.rank,
                "host": socket.gethostname(),
                "pid": os.getpid(),
                "ts": time.time(),
                "entries": self.tail(),
            }
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)       # atomic: never a half-written dump
        except OSError as e:  # pragma: no cover - defensive
            logger.warning("flight recorder dump (%s) failed: %s",
                           reason, e)
            return None
        with self._lock:
            self._dumped[reason] = path
        logger.warning("flight recorder: dumped %d entries -> %s "
                       "(reason: %s)", len(self.tail()), path, reason)
        return path


#: the process flight recorder (engine build re-configures capacity/rank/
#: dump dir; tests re-configure freely)
RECORDER = FlightRecorder()


def load_dump(path: str) -> dict:
    """Load + sanity-check a dump file (the post-mortem/test entry point);
    raises ValueError naming the problem on a foreign or damaged file."""
    with open(path) as f:
        payload = json.load(f)
    if payload.get("schema") != DUMP_SCHEMA_ID:
        raise ValueError(
            f"{path!r} is not a flight-recorder dump "
            f"(schema {payload.get('schema')!r})")
    if not isinstance(payload.get("entries"), list):
        raise ValueError(f"{path!r}: entries is not a list")
    return payload


_atexit_registered = False


def maybe_register_exit_dump() -> None:
    """Arm the at-exit dump when :data:`ENV_DUMP_AT_EXIT` is set (called
    at telemetry build; idempotent)."""
    global _atexit_registered
    if _atexit_registered or os.environ.get(ENV_DUMP_AT_EXIT) != "1":
        return
    _atexit_registered = True
    import atexit
    atexit.register(lambda: RECORDER.dump("exit"))
