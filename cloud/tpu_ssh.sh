#!/usr/bin/env bash
# Run a command on one worker (or interactively ssh in)
# (reference analog: azure/azure_ssh.sh).
#   ./tpu_ssh.sh <worker-id> [command...]
source "$(dirname "$0")/common.sh"

WORKER=${1:-0}
shift || true

if [ $# -eq 0 ]; then
    exec ${GC} ssh "${TPU_NAME}" "${GFLAGS[@]}" --worker="${WORKER}"
fi
exec ${GC} ssh "${TPU_NAME}" "${GFLAGS[@]}" --worker="${WORKER}" \
    --command "$*"
