"""Data loader: sharding, shuffling, routes, collation."""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.constants import ROUTE_EVAL, ROUTE_TRAIN
from deepspeed_tpu.data import ArrayDataset, DeepSpeedDataLoader
from deepspeed_tpu.parallel import topology


def make_ds(n=64, d=4):
    x = np.arange(n * d, dtype=np.float32).reshape(n, d)
    y = np.arange(n, dtype=np.int32)
    return ArrayDataset(x, y), x, y


def test_len_and_batch_shapes():
    ds, _, _ = make_ds()
    dl = DeepSpeedDataLoader(ds, batch_size=16)
    assert len(dl) == 4
    xb, yb = next(iter(dl))
    assert xb.shape == (16, 4) and yb.shape == (16,)


def test_drop_last():
    ds, _, _ = make_ds(n=30)
    assert len(DeepSpeedDataLoader(ds, batch_size=16)) == 1
    assert len(DeepSpeedDataLoader(ds, batch_size=16, drop_last=False)) == 2


def test_eval_route_is_sequential():
    ds, x, y = make_ds()
    dl = DeepSpeedDataLoader(ds, batch_size=8, route=ROUTE_EVAL)
    xb, yb = next(iter(dl))
    np.testing.assert_array_equal(yb, np.arange(8))
    np.testing.assert_array_equal(xb, x[:8])


def test_train_route_shuffles_and_epochs_differ():
    ds, _, _ = make_ds()
    dl = DeepSpeedDataLoader(ds, batch_size=64, route=ROUTE_TRAIN, seed=7)
    (_, y1), = list(dl)             # epoch 0 (full consumption bumps epoch)
    (_, y2), = list(dl)             # epoch 1
    assert not np.array_equal(y1, y2)
    assert set(y1.tolist()) == set(range(64))
    # set_epoch makes shuffles reproducible
    dl.set_epoch(0)
    _, y1b = next(iter(dl))
    np.testing.assert_array_equal(y1, y1b)


def test_batches_sharded_over_data_axis():
    mesh = topology.make_mesh()  # 8-way data
    ds, _, _ = make_ds()
    dl = DeepSpeedDataLoader(ds, batch_size=16, mesh=mesh)
    xb, yb = next(iter(dl))
    assert isinstance(xb, jax.Array)
    assert xb.sharding.spec == P(topology.DATA_AXIS)
    # each device holds 16/8 = 2 samples
    assert xb.addressable_shards[0].data.shape == (2, 4)


def test_tput_timer_hook():
    class Timer:
        count = 0
        def start(self):
            self.count += 1

    ds, _, _ = make_ds()
    t = Timer()
    dl = DeepSpeedDataLoader(ds, batch_size=16, tput_timer=t)
    list(dl)
    assert t.count == len(dl)


def test_custom_collate_fn():
    ds, _, _ = make_ds()
    dl = DeepSpeedDataLoader(
        ds, batch_size=4,
        collate_fn=lambda samples: {"n": len(samples)})
    assert next(iter(dl)) == {"n": 4}


def test_file_dataset_roundtrip(tmp_path):
    from deepspeed_tpu.data import FileDataset
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 100, size=(32, 16)).astype(np.int32)
    w = rng.normal(size=(32, 4)).astype(np.float32)
    d = FileDataset.save(str(tmp_path / "ds"), ids=ids, w=w)
    fds = FileDataset(d)
    assert len(fds) == 32
    a, b = fds[5]
    np.testing.assert_array_equal(a, ids[5])
    np.testing.assert_array_equal(b, w[5])
    # the collate fast path streams through the native gather
    ga, gb = fds.collate_gather(np.array([3, 1, 2]))
    np.testing.assert_array_equal(ga, ids[[3, 1, 2]])
    np.testing.assert_array_equal(gb, w[[3, 1, 2]])
    # memmap-backed: the big fields are not materialised at open
    assert isinstance(fds.arrays[0], np.memmap)


def test_file_dataset_through_loader(tmp_path):
    from deepspeed_tpu.data import FileDataset
    ids = np.arange(64, dtype=np.int32).reshape(16, 4)
    d = FileDataset.save(str(tmp_path / "ds"), ids=ids)
    dl = DeepSpeedDataLoader(FileDataset(d), batch_size=4, route="eval",
                             num_workers=1)
    got = np.concatenate(list(dl))
    np.testing.assert_array_equal(got, ids)


def test_device_prefetch_places_on_producer():
    # with device_prefetch the yielded leaves are already sharded
    # jax.Arrays (the host->device copy happened on the producer thread)
    import jax
    from jax.sharding import Mesh

    ds, _, _ = make_ds()
    mesh = Mesh(np.array(jax.devices()).reshape(-1, 1, 1, 1),
                ("data", "pipe", "seq", "model"))
    dl = DeepSpeedDataLoader(ds, batch_size=8, mesh=mesh, num_workers=1,
                             device_prefetch=True)
    batch = next(iter(dl))
    leaf = jax.tree_util.tree_leaves(batch)[0]
    assert isinstance(leaf, jax.Array)
    assert "data" in str(leaf.sharding.spec)


def test_state_dict_mid_epoch_resume():
    """Resumable iterator (docs/resilience.md): a fresh loader restored
    from a mid-epoch state_dict yields EXACTLY the batches the interrupted
    run never consumed — same epoch permutation, same tail, then the next
    epoch reshuffles on schedule."""
    ds, _, _ = make_ds()
    ref = DeepSpeedDataLoader(ds, batch_size=16, route=ROUTE_TRAIN, seed=9)
    ref_batches = list(ref) + list(ref)              # epochs 0 + 1

    dl = DeepSpeedDataLoader(ds, batch_size=16, route=ROUTE_TRAIN, seed=9)
    it = iter(dl)
    consumed = [next(it) for _ in range(2)]
    for got, want in zip(consumed, ref_batches[:2]):
        np.testing.assert_array_equal(got[1], want[1])
    state = dl.state_dict()
    assert state == {"epoch": 0, "batch": 2, "seed": 9}
    del it                                           # interrupted mid-epoch

    resumed = DeepSpeedDataLoader(ds, batch_size=16, route=ROUTE_TRAIN,
                                  seed=123)          # seed restored below
    resumed.load_state_dict(state)
    tail = list(resumed) + list(resumed)             # rest of epoch 0 + 1
    assert len(tail) == 2 + 4
    for got, want in zip(tail, ref_batches[2:]):
        np.testing.assert_array_equal(got[1], want[1])
    # epoch rollover resets the position
    assert resumed.state_dict() == {"epoch": 2, "batch": 0, "seed": 9}


def test_state_dict_prefetched_path():
    """The producer-thread path tracks the same yielded-batch position."""
    ds, _, _ = make_ds()
    a = DeepSpeedDataLoader(ds, batch_size=16, seed=4, num_workers=1)
    it = iter(a)
    next(it), next(it), next(it)
    state = a.state_dict()
    assert state["batch"] == 3
    del it

    b = DeepSpeedDataLoader(ds, batch_size=16, seed=4, num_workers=1)
    b.load_state_dict(state)
    ref = DeepSpeedDataLoader(ds, batch_size=16, seed=4)
    np.testing.assert_array_equal(list(b)[0][1], list(ref)[3][1])


def test_load_state_dict_rejects_foreign_position():
    ds, _, _ = make_ds()
    dl = DeepSpeedDataLoader(ds, batch_size=16)
    import pytest
    with pytest.raises(ValueError, match="outside this loader's epoch"):
        dl.load_state_dict({"epoch": 0, "batch": 99, "seed": 0})


def test_build_mlm_arrays_recipe_properties(tmp_path):
    from deepspeed_tpu import tokenization as tok
    text = ("the quick brown fox jumps over the lazy dog . " * 300)
    words = sorted(set(text.split()))
    vocab = tok.Vocab(list(tok.SPECIAL_TOKENS) + words)
    tokenizer = tok.BertTokenizer(vocab)
    fields = tok.build_mlm_arrays([text], tokenizer, seq_len=32,
                                  max_predictions=5, seed=1, n_samples=8)
    ids, mask = fields["input_ids"], fields["input_mask"]
    pos, mids = fields["masked_positions"], fields["masked_ids"]
    wts = fields["masked_weights"]
    assert ids.shape == (8, 32) and pos.shape == (8, 5)
    cls_id, sep_id = tokenizer.cls_id, tokenizer.sep_id
    mask_id = vocab.id(tok.MASK_TOKEN)
    for i in range(8):
        L = int(mask[i].sum())
        assert ids[i, 0] == cls_id and ids[i, L - 1] == sep_id
        n_pred = int(wts[i].sum())
        assert 1 <= n_pred <= 5
        for j in range(n_pred):
            p = pos[i, j]
            assert 0 < p < L - 1                  # never CLS/SEP
            assert mids[i, j] != 0                # original token recorded
        # ~80% of masked positions actually carry [MASK]
    masked_frac = float(np.mean(
        (np.take_along_axis(ids, pos, axis=1) == mask_id)[wts > 0]))
    assert 0.5 < masked_frac <= 1.0
