"""Optimizers as pure pytree transforms (Adam, AdamW, LAMB, SGD).

TPU-native equivalents of the reference's base optimizers: apex FusedAdam
(consumed at /root/reference/deepspeed/pt/deepspeed_light.py:474-475) and the
fused-LAMB CUDA kernel (/root/reference/csrc/fused_lamb_cuda_kernel.cu).  The
CUDA kernels exist to fuse moment updates + norms + the weight update into one
launch; under XLA the same fusion falls out of ``jit`` — the Pallas variant in
``ops/pallas_lamb.py`` exists for the cases XLA's scheduler doesn't fuse
(single flat-buffer update with two global reductions).

Semantics preserved exactly from the reference kernels:

* moments: ``m = b1*m + (1-b1)*g/scale``; ``v = b2*v + (1-b2)*(g/scale)^2``
  (kernel part1, fused_lamb_cuda_kernel.cu:243-248) — no bias correction in
  the moments themselves.
* ``denom = sqrt(v) + eps`` (eps_mode 1, the python wrapper's default
  ``eps_inside_sqrt=False``, deepspeed_fused_lamb.py:75) or ``sqrt(v+eps)``
  (mode 0).
* bias-corrected step size computed once per step on the host side of the
  kernel: ``lr * sqrt(1-b2^t)/(1-b1^t)`` (fused_lamb_cuda_kernel.cu:396-404).
* LAMB trust ratio per parameter tensor:
  ``clamp(||w||/||update||, min_coeff, max_coeff)`` with 1.0 when either norm
  is zero (kernel part3, fused_lamb_cuda_kernel.cu:319-329); defaults
  max_coeff=10.0, min_coeff=0.01 (deepspeed_fused_lamb.py:56-58).
* ``update = m/denom + weight_decay * p`` (L2-style decay inside the update,
  matching the kernel); AdamW uses decoupled decay instead.

All update functions are jit-safe pure functions over fp32 leaves, usable
per-leaf (normal path) or on ZeRO-partitioned flat buffers (Adam family; the
reference likewise restricts ZeRO to Adam, deepspeed_light.py:450-457, because
LAMB's per-tensor trust ratio doesn't survive flattening).
"""

from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class OptimizerState(NamedTuple):
    step: jnp.ndarray  # i32 [] — shared across leaves (reference state['step'])
    m: Any             # pytree like params (exp_avg)
    v: Any             # pytree like params (exp_avg_sq); None for SGD


def _zeros_like_tree(params):
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """Base: hyperparameters are static fields; ``lr``/betas may be overridden
    per step (the LR scheduler's param_group mutation path).

    ``use_pallas``: None = auto (fused Pallas kernels on TPU for leaves of at
    least one tile), True/False = force.  The Pallas path is the
    ``csrc/fused_lamb_cuda`` equivalent (ops/pallas_optim.py)."""
    lr: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    bias_correction: bool = True
    eps_inside_sqrt: bool = False  # eps_mode 0 if True (kernel adamMode_t)
    use_pallas: Optional[bool] = None
    name: str = "base"

    # whether update() consumes beta1/beta2 — engine param-group validation
    # rejects per-group 'betas' for optimizers that would silently drop them
    uses_betas = True

    def init(self, params) -> OptimizerState:
        return OptimizerState(step=jnp.zeros((), jnp.int32),
                              m=_zeros_like_tree(params),
                              v=_zeros_like_tree(params))

    # -- helpers ----------------------------------------------------------
    def _step_size(self, lr, step, beta1, beta2):
        """Host-side step size of the kernel launcher
        (fused_lamb_cuda_kernel.cu:396-404).  Uses the per-step betas so
        momentum cycling (OneCycle) keeps bias correction consistent with the
        moment update."""
        if self.bias_correction:
            bc1 = 1.0 - beta1 ** step
            bc2 = 1.0 - beta2 ** step
            return lr * jnp.sqrt(bc2) / bc1
        return jnp.asarray(lr, jnp.float32)

    def _moments(self, g, m, v, beta1, beta2, combined_scale):
        sg = g.astype(jnp.float32) / combined_scale
        m_new = beta1 * m + (1.0 - beta1) * sg
        v_new = beta2 * v + (1.0 - beta2) * sg * sg
        return m_new, v_new

    def _denom(self, v):
        if self.eps_inside_sqrt:
            return jnp.sqrt(v + self.eps)
        return jnp.sqrt(v) + self.eps

    def update(self, params, grads, state: OptimizerState, *,
               lr: Optional[float] = None,
               beta1: Optional[float] = None,
               beta2: Optional[float] = None,
               weight_decay: Optional[float] = None,
               combined_scale=1.0) -> Tuple[Any, OptimizerState]:
        raise NotImplementedError

    @staticmethod
    def _is_scalar_hyper(h) -> bool:
        """One shared scalar test (None / python number / 0-d array)."""
        return (h is None or isinstance(h, (int, float))
                or getattr(h, "ndim", 1) == 0)

    @staticmethod
    def _hyper_leaves(val, treedef, n):
        """A hyperparameter (lr/beta1/beta2/weight_decay) may be a scalar
        (all leaves share it) or a pytree matching params (per-leaf values —
        the engine's param-group path, reference torch param groups carrying
        arbitrary hypers, deepspeed_fused_lamb.py:77-100).  Returns a flat
        list of per-leaf scalars (None = use the optimizer's default)."""
        if Optimizer._is_scalar_hyper(val):
            return [val] * n
        return treedef.flatten_up_to(val)

    def _resolve(self, lr_leaf, b1_leaf, b2_leaf, wd_leaf):
        """Per-leaf hypers with the optimizer's static fields as defaults."""
        return (self.lr if lr_leaf is None else lr_leaf,
                self.beta1 if b1_leaf is None else b1_leaf,
                self.beta2 if b2_leaf is None else b2_leaf,
                self.weight_decay if wd_leaf is None else wd_leaf)

    def _flat_hypers(self, params, grads, state, lr, beta1, beta2,
                     weight_decay):
        """Flatten params/grads/moments and the four hypers together."""
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        n = len(flat_p)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = (treedef.flatten_up_to(state.m)
                  if state.m is not None else [None] * n)
        flat_v = (treedef.flatten_up_to(state.v)
                  if state.v is not None else [None] * n)
        hy = zip(self._hyper_leaves(lr, treedef, n),
                 self._hyper_leaves(beta1, treedef, n),
                 self._hyper_leaves(beta2, treedef, n),
                 self._hyper_leaves(weight_decay, treedef, n))
        return treedef, list(zip(flat_p, flat_g, flat_m, flat_v, hy))


@dataclasses.dataclass(frozen=True)
class Adam(Optimizer):
    """FusedAdam equivalent (apex semantics: L2 decay folded into the
    update)."""
    name: str = "adam"
    decoupled_decay: bool = False

    def update(self, params, grads, state, *, lr=None, beta1=None, beta2=None,
               weight_decay=None, combined_scale=1.0):
        from deepspeed_tpu.ops import pallas_optim as pk

        step = state.step + 1
        # shared across every leaf (one pow/sqrt chain, not one per leaf —
        # the boundary step is a fixed per-optimizer-step cost gas cannot
        # amortize, so trace-size/kernel-count hygiene here matters)
        step_f = step.astype(jnp.float32)

        def leaf(p, g, m, v, hy):
            if g is None:
                return p, m, v
            lr_l, b1, b2, wd = self._resolve(*hy)
            step_size = self._step_size(lr_l, step_f, b1, b2)
            # per-ELEMENT hyper arrays (ZeRO x param_groups expands
            # vec[gid] over the flat partition) take the jnp path — the
            # Pallas kernel is compiled for scalar hypers.  Known trade:
            # grouped ZeRO loses the fused update on the flat buffer; a
            # kernel variant taking a gid vector would recover it.
            scalar_hy = all(self._is_scalar_hyper(h)
                            for h in (lr_l, b1, b2, wd))
            if scalar_hy and pk.should_use_pallas(p.size, self.use_pallas):
                return pk.fused_adam_update(
                    p, g, m, v, beta1=b1, beta2=b2, eps=self.eps,
                    weight_decay=wd,
                    combined_scale=combined_scale, step_size=step_size,
                    lr=lr_l, eps_inside_sqrt=self.eps_inside_sqrt,
                    decoupled_decay=self.decoupled_decay,
                    interpret=not pk.pallas_available())
            m_new, v_new = self._moments(g, m, v, b1, b2, combined_scale)
            upd = m_new / self._denom(v_new)
            if self.decoupled_decay:
                p_new = p - step_size * upd - lr_l * wd * p
            else:
                p_new = p - step_size * (upd + wd * p)
            return p_new, m_new, v_new

        treedef, rows = self._flat_hypers(params, grads, state,
                                          lr, beta1, beta2, weight_decay)
        out = [leaf(*r) for r in rows]
        new_p = treedef.unflatten([o[0] for o in out])
        new_m = treedef.unflatten([o[1] for o in out])
        new_v = treedef.unflatten([o[2] for o in out])
        return new_p, OptimizerState(step=step, m=new_m, v=new_v)


@dataclasses.dataclass(frozen=True)
class AdamW(Adam):
    name: str = "adamw"
    decoupled_decay: bool = True


@dataclasses.dataclass(frozen=True)
class Lamb(Optimizer):
    """Fused-LAMB equivalent with per-tensor trust ratio
    (fused_lamb_cuda_kernel.cu part1-part3)."""
    name: str = "lamb"
    max_coeff: float = 10.0
    min_coeff: float = 0.01

    def update(self, params, grads, state, *, lr=None, beta1=None, beta2=None,
               weight_decay=None, combined_scale=1.0):
        from deepspeed_tpu.ops import pallas_optim as pk

        step = state.step + 1
        step_f = step.astype(jnp.float32)   # shared bias-correction input

        def leaf(p, g, m, v, hy):
            if g is None:
                return p, m, v
            lr_l, b1, b2, wd = self._resolve(*hy)
            step_size = self._step_size(lr_l, step_f, b1, b2)
            if pk.should_use_pallas(p.size, self.use_pallas):
                return pk.fused_lamb_update(
                    p, g, m, v, beta1=b1, beta2=b2, eps=self.eps,
                    weight_decay=wd,
                    combined_scale=combined_scale, step_size=step_size,
                    min_coeff=self.min_coeff, max_coeff=self.max_coeff,
                    eps_inside_sqrt=self.eps_inside_sqrt,
                    interpret=not pk.pallas_available())
            m_new, v_new = self._moments(g, m, v, b1, b2, combined_scale)
            upd = m_new / self._denom(v_new) + wd * p
            # two L2 reductions of kernel part1/part2
            w_norm = jnp.sqrt(jnp.sum(p.astype(jnp.float32) ** 2))
            u_norm = jnp.sqrt(jnp.sum(upd ** 2))
            # trust ratio with clamping (kernel part3 :319-329)
            coeff = jnp.where(
                (w_norm != 0.0) & (u_norm != 0.0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff),
                1.0)
            p_new = p - step_size * coeff * upd
            return p_new, m_new, v_new

        treedef, rows = self._flat_hypers(params, grads, state,
                                          lr, beta1, beta2, weight_decay)
        out = [leaf(*r) for r in rows]
        new_p = treedef.unflatten([o[0] for o in out])
        new_m = treedef.unflatten([o[1] for o in out])
        new_v = treedef.unflatten([o[2] for o in out])
        return new_p, OptimizerState(step=step, m=new_m, v=new_v)


@dataclasses.dataclass(frozen=True)
class Sgd(Optimizer):
    """torch.optim.SGD passthrough equivalent (momentum is a static field,
    not a per-step beta)."""
    name: str = "sgd"
    momentum: float = 0.0
    uses_betas = False

    def init(self, params) -> OptimizerState:
        m = _zeros_like_tree(params) if self.momentum > 0.0 else None
        return OptimizerState(step=jnp.zeros((), jnp.int32), m=m, v=None)

    def update(self, params, grads, state, *, lr=None, beta1=None, beta2=None,
               weight_decay=None, combined_scale=1.0):
        step = state.step + 1
        treedef, rows = self._flat_hypers(params, grads, state,
                                          lr, beta1, beta2, weight_decay)

        if self.momentum > 0.0:
            def leaf(p, g, m, _v, hy):
                if g is None:
                    return p, m
                lr_l, _, _, wd = self._resolve(*hy)
                sg = g.astype(jnp.float32) / combined_scale + wd * p
                m_new = self.momentum * m + sg
                return p - lr_l * m_new, m_new
            out = [leaf(*r) for r in rows]
            return (treedef.unflatten([o[0] for o in out]),
                    OptimizerState(step=step,
                                   m=treedef.unflatten([o[1] for o in out]),
                                   v=None))

        def leaf(p, g, _m, _v, hy):
            if g is None:
                return p
            lr_l, _, _, wd = self._resolve(*hy)
            sg = g.astype(jnp.float32) / combined_scale + wd * p
            return p - lr_l * sg

        new_p = treedef.unflatten([leaf(*r) for r in rows])
        return new_p, OptimizerState(step=step, m=None, v=None)


@dataclasses.dataclass(frozen=True)
class Lion(Optimizer):
    """Lion — EvoLved Sign Momentum (Chen et al. 2023, arXiv:2302.06675).

    Beyond-reference breadth: a TPU-popular optimizer with HALF of Adam's
    state (one momentum, no second moment).  Admitted under ZeRO-3, where
    the update runs per-leaf elementwise on local shards (the flat
    stage-1/2 layout keeps the reference's Adam-family guard —
    engine ZeRO guard; parity:
    tests/test_zero3.py::test_zero3_lion_matches_stage0).
    Update: ``u = sign(b1·m + (1-b1)·g); p -= lr·(u + wd·p);
    m = b2·m + (1-b2)·g``.  Decay is decoupled (AdamW-style) per the
    paper.  Under fp16 the combined unscale factor divides the gradient
    before both the sign interpolation and the momentum update; note the
    sign makes the UPDATE invariant to pure rescaling, so clipping only
    shifts the interpolation weighting — document-not-surprise.  Paper
    defaults: lr 1e-4 (use ~1/10 of the Adam lr), betas (0.9, 0.99)."""
    name: str = "lion"
    lr: float = 1e-4
    beta1: float = 0.9
    beta2: float = 0.99

    def init(self, params) -> OptimizerState:
        return OptimizerState(step=jnp.zeros((), jnp.int32),
                              m=_zeros_like_tree(params), v=None)

    def update(self, params, grads, state, *, lr=None, beta1=None, beta2=None,
               weight_decay=None, combined_scale=1.0):
        step = state.step + 1
        treedef, rows = self._flat_hypers(params, grads, state,
                                          lr, beta1, beta2, weight_decay)

        def leaf(p, g, m, _v, hy):
            if g is None:
                return p, m
            lr_l, b1, b2, wd = self._resolve(*hy)
            sg = g.astype(jnp.float32) / combined_scale
            u = jnp.sign(b1 * m + (1.0 - b1) * sg)
            p_new = p - lr_l * (u + wd * p)
            m_new = b2 * m + (1.0 - b2) * sg
            return p_new, m_new

        out = [leaf(*r) for r in rows]
        return (treedef.unflatten([o[0] for o in out]),
                OptimizerState(step=step,
                               m=treedef.unflatten([o[1] for o in out]),
                               v=None))


@dataclasses.dataclass(frozen=True)
class RMSprop(Optimizer):
    """torch.optim.RMSprop equivalent (no momentum/centered variants):
    ``v = alpha*v + (1-alpha)*g^2; p -= lr * g / (sqrt(v) + eps)``."""
    name: str = "rmsprop"
    alpha: float = 0.99
    eps: float = 1e-8
    uses_betas = False

    def init(self, params) -> OptimizerState:
        return OptimizerState(step=jnp.zeros((), jnp.int32), m=None,
                              v=_zeros_like_tree(params))

    def update(self, params, grads, state, *, lr=None, beta1=None, beta2=None,
               weight_decay=None, combined_scale=1.0):
        step = state.step + 1
        treedef, rows = self._flat_hypers(params, grads, state,
                                          lr, beta1, beta2, weight_decay)

        def leaf(p, g, _m, v, hy):
            if g is None:
                return p, v
            lr_l, _, _, wd = self._resolve(*hy)
            sg = g.astype(jnp.float32) / combined_scale + wd * p
            v_new = self.alpha * v + (1.0 - self.alpha) * sg * sg
            return p - lr_l * sg / (jnp.sqrt(v_new) + self.eps), v_new

        out = [leaf(*r) for r in rows]
        return (treedef.unflatten([o[0] for o in out]),
                OptimizerState(step=step, m=None,
                               v=treedef.unflatten([o[1] for o in out])))


@dataclasses.dataclass(frozen=True)
class Adagrad(Optimizer):
    """torch.optim.Adagrad equivalent:
    ``v += g^2; p -= lr * g / (sqrt(v) + eps)``."""
    name: str = "adagrad"
    eps: float = 1e-10
    uses_betas = False

    def init(self, params) -> OptimizerState:
        return OptimizerState(step=jnp.zeros((), jnp.int32), m=None,
                              v=_zeros_like_tree(params))

    def update(self, params, grads, state, *, lr=None, beta1=None, beta2=None,
               weight_decay=None, combined_scale=1.0):
        step = state.step + 1
        treedef, rows = self._flat_hypers(params, grads, state,
                                          lr, beta1, beta2, weight_decay)

        def leaf(p, g, _m, v, hy):
            if g is None:
                return p, v
            lr_l, _, _, wd = self._resolve(*hy)
            sg = g.astype(jnp.float32) / combined_scale + wd * p
            v_new = v + sg * sg
            return p - lr_l * sg / (jnp.sqrt(v_new) + self.eps), v_new

        out = [leaf(*r) for r in rows]
        return (treedef.unflatten([o[0] for o in out]),
                OptimizerState(step=step, m=None,
                               v=treedef.unflatten([o[1] for o in out])))


# --------------------------------------------------------------- extension
# The reference falls through to torch.optim.<name> for any optimizer it
# doesn't wrap (deepspeed_light.py:479-481); functional pytree optimizers
# have no torch registry to borrow, so third parties register factories here.
_REGISTRY: dict = {}


def register_optimizer(name: str, factory) -> None:
    """Register ``factory(**params_dict) -> Optimizer`` under a config
    ``optimizer.type`` name (case-insensitive)."""
    _REGISTRY[name.lower()] = factory


def from_config(name: str, params_dict: Optional[dict] = None) -> Optimizer:
    """Instantiate by config name (reference _configure_basic_optimizer,
    deepspeed_light.py:466-481).  Accepted params follow torch/apex spellings:
    lr, betas, eps, weight_decay, bias_correction, momentum,
    max_coeff/min_coeff (LAMB)."""
    p = dict(params_dict or {})
    kw = {}
    if "lr" in p:
        kw["lr"] = float(p.pop("lr"))
    if "betas" in p:
        b1, b2 = p.pop("betas")
        kw["beta1"], kw["beta2"] = float(b1), float(b2)
    for k in ("eps", "weight_decay"):
        if k in p:
            kw[k] = float(p.pop(k))
    if "bias_correction" in p:
        kw["bias_correction"] = bool(p.pop("bias_correction"))
    if "use_pallas" in p:   # None=auto, True/False=force (TPU fused kernels)
        up = p.pop("use_pallas")
        kw["use_pallas"] = None if up is None else bool(up)
    name_l = name.lower()
    if name_l == "adam":
        p.pop("max_grad_norm", None)
        return Adam(**kw)
    if name_l == "adamw":
        p.pop("max_grad_norm", None)
        return AdamW(**kw)
    if name_l == "lamb":
        for k in ("max_coeff", "min_coeff"):
            if k in p:
                kw[k] = float(p.pop(k))
        p.pop("max_grad_norm", None)
        p.pop("eps_inside_sqrt", None)
        return Lamb(**kw)
    if name_l == "sgd":
        if "momentum" in p:
            kw["momentum"] = float(p.pop("momentum"))
        return Sgd(**kw)
    if name_l == "lion":
        kw.pop("eps", None)
        p.pop("max_grad_norm", None)
        return Lion(**kw)
    if name_l == "rmsprop":
        if "alpha" in p:
            kw["alpha"] = float(p.pop("alpha"))
        if float(p.pop("momentum", 0) or 0) or p.pop("centered", False):
            raise ValueError(
                "RMSprop momentum/centered variants are not implemented — "
                "refusing to silently train with different dynamics")
        return RMSprop(**kw)
    if name_l == "adagrad":
        if float(p.pop("lr_decay", 0) or 0):
            raise ValueError("Adagrad lr_decay is not implemented")
        return Adagrad(**kw)
    if name_l in _REGISTRY:
        return _REGISTRY[name_l](**dict(params_dict or {}))
    raise ValueError(f"Unknown optimizer {name!r}")
