"""Scheduler-by-name registry: the reference instantiates any
torch.optim.lr_scheduler.* from config (deepspeed_light.py:351-354); here the
common ones are native equivalents validated against torch's own schedulers.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu import lr_schedules as S

torch = pytest.importorskip("torch")


class _Holder:
    def __init__(self, lr):
        self.param_groups = [{"lr": lr, "betas": (0.9, 0.999)}]


def _torch_opt(lr):
    p = torch.nn.Parameter(torch.zeros(1))
    return torch.optim.SGD([p], lr=lr)


@pytest.mark.parametrize("name,kwargs,torch_cls", [
    ("CosineAnnealingLR", {"T_max": 10, "eta_min": 1e-4},
     torch.optim.lr_scheduler.CosineAnnealingLR),
    ("StepLR", {"step_size": 3, "gamma": 0.5},
     torch.optim.lr_scheduler.StepLR),
    ("LinearLR", {"start_factor": 0.5, "total_iters": 4},
     torch.optim.lr_scheduler.LinearLR),
    ("ExponentialLR", {"gamma": 0.9},
     torch.optim.lr_scheduler.ExponentialLR),
])
def test_matches_torch(name, kwargs, torch_cls):
    lr = 0.1
    ours = S.SCHEDULES[name](_Holder(lr), **kwargs)
    topt = _torch_opt(lr)
    theirs = torch_cls(topt, **kwargs)
    got, want = [], []
    for _ in range(12):
        got.append(ours.optimizer.param_groups[0]["lr"])
        want.append(topt.param_groups[0]["lr"])
        ours.step()
        theirs.step()
    # torch chains multiplicatively (ExponentialLR accumulates fp error);
    # closed forms match to fp tolerance
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_state_dict_roundtrip():
    s1 = S.SCHEDULES["CosineAnnealingLR"](_Holder(0.1), T_max=10)
    for _ in range(5):
        s1.step()
    s2 = S.SCHEDULES["CosineAnnealingLR"](_Holder(0.1), T_max=10)
    s2.load_state_dict(s1.state_dict())
    s1.step()
    s2.step()
    assert s1.get_last_lr() == s2.get_last_lr()


def test_engine_config_by_torch_name():
    """A torch scheduler name in the JSON config resolves via the registry."""
    import jax
    from simple_model import SimpleModel, random_dataset

    model = SimpleModel(16)
    engine, _, _, sched = deepspeed_tpu.initialize(
        config={
            "train_batch_size": 16,
            "steps_per_print": 10 ** 6,
            "optimizer": {"type": "Adam", "params": {"lr": 0.1}},
            "scheduler": {"type": "StepLR",
                          "params": {"step_size": 2, "gamma": 0.5}},
        },
        model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(0)))
    assert isinstance(sched, S.StepLR)
    ds = random_dataset(128, 16)
    dl = iter(engine.deepspeed_io(ds))
    lrs = []
    for _ in range(5):
        batch = next(dl)
        loss = engine(*batch)
        engine.backward(loss)
        engine.step()
        lrs.append(engine.optimizer.param_groups[0]["lr"])
    # decays by gamma every step_size optimizer steps (torch StepLR counting:
    # lr(epoch) = base * gamma^(epoch // step_size), epoch = steps taken)
    np.testing.assert_allclose(lrs, [0.1, 0.05, 0.05, 0.025, 0.025],
                               rtol=1e-6)


def test_onecycle_stair_count_cli_overrides():
    """OneCycle stair-count CLI args flow into the config; -1 sentinels are
    dropped (reference deepspeed_lr_schedules.py:51-120)."""
    import argparse
    parser = argparse.ArgumentParser()
    S.add_tuning_arguments(parser)
    args = parser.parse_args([
        "--lr_schedule", "OneCycle",
        "--cycle_first_step_size", "100",
        "--cycle_first_stair_count", "7",
        "--cycle_second_stair_count", "9",
    ])
    cfg, err = S.get_config_from_args(args)
    assert err is None
    assert cfg["params"]["cycle_first_stair_count"] == 7
    assert cfg["params"]["cycle_second_stair_count"] == 9
    # unset sentinel dropped
    assert "cycle_second_step_size" not in cfg["params"]


def test_warmup_linear_decay_exp_recipe_schedule():
    # the bing_bert 16K-batch recipe schedule (WALLCLOCK.md): linear
    # warmup then decay_rate**(steps/decay_step)
    from deepspeed_tpu.lr_schedules import SCHEDULES
    opt = _Holder(0.0)
    s = SCHEDULES["warmup_linear_decay_exp"](
        opt, lr=4e-3, total_steps=1000, warmup_proportion=0.02,
        decay_rate=0.9, decay_step=100)
    lrs = []
    for _ in range(240):
        s.step()
        lrs.append(opt.param_groups[0]["lr"])
    # warmup: 20 linear steps up to lr
    assert abs(lrs[0] - 4e-3 / 20) < 1e-9
    assert abs(lrs[19] - 4e-3) < 1e-9
    # decay: one decay_step later lr has decayed by decay_rate
    assert abs(lrs[120] - 4e-3 * 0.9) / 4e-3 < 1e-6
    assert abs(lrs[220] - 4e-3 * 0.81) / 4e-3 < 1e-6
    # round-trips through state_dict
    s2 = SCHEDULES["warmup_linear_decay_exp"](
        _Holder(0.0), lr=4e-3, total_steps=1000,
        warmup_proportion=0.02, decay_rate=0.9, decay_step=100)
    s2.load_state_dict(s.state_dict())
    s2.step()
    s.step()
    assert s.get_last_lr() == s2.get_last_lr()
