"""Context parallelism composed with the rest of the engine: sp x tp,
sp x fused train_batch, and sp x ZeRO — the combinations the per-feature
tests don't cross (the driver's dryrun runs tp x sp x dp once; these pin the
numerics).
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import GPT2
from deepspeed_tpu.parallel.topology import make_mesh

# composition tier: 30-85 s of shard_map compiles per test — runs in the
# full suite/CI, excluded from `-m fast` (VERDICT r2 weak #6)
pytestmark = pytest.mark.slow


VOCAB, SEQ = 64, 16


def make_engine(sp=1, mp=1, zero=False, seed=7, **cfg_over):
    cfg = {
        "train_batch_size": 4,
        "steps_per_print": 10 ** 6,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
    }
    if zero:
        cfg["zero_optimization"] = True
        cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    cfg.update(cfg_over)
    model = GPT2.from_size("tiny", vocab_size=VOCAB, max_seq_len=SEQ,
                           num_layers=2, hidden_size=32, num_heads=4)
    n = 4 * sp * mp
    engine, _, _, _ = deepspeed_tpu.initialize(
        config=cfg, model=model,
        model_parameters=model.init_params(jax.random.PRNGKey(seed)),
        mesh=make_mesh(context_parallel_size=sp, model_parallel_size=mp,
                       devices=jax.devices()[:min(n, 8)]))
    return engine


def batches(steps):
    out = []
    for i in range(steps):
        rng = np.random.default_rng(i)
        toks = rng.integers(0, VOCAB, size=(4, SEQ)).astype(np.int32)
        labels = np.roll(toks, -1, axis=1)
        out.append((toks, labels))
    return out


def run_split(engine, data):
    losses = []
    for toks, labels in data:
        loss = engine(toks, labels)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


@pytest.mark.fast
def test_sp_without_batch_specs_rejected():
    """VERDICT r3 weak #2: the engine must not guess which batch dims are
    sequences — a model without batch_specs hard-errors under sp>1 instead
    of warning and heuristically sharding dim 1."""
    from deepspeed_tpu.config import DeepSpeedConfigError
    from simple_model import SimpleModel

    model = SimpleModel(hidden_dim=8)
    with pytest.raises(DeepSpeedConfigError, match="batch_specs"):
        deepspeed_tpu.initialize(
            config={"train_batch_size": 4, "steps_per_print": 10 ** 6,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}}},
            model=model,
            model_parameters=model.init_params(jax.random.PRNGKey(0)),
            mesh=make_mesh(context_parallel_size=2,
                           devices=jax.devices()[:4]))


def test_sp_with_tensor_parallel():
    """sp=2 x mp=2 must reproduce the sp=1 x mp=1 trajectory (fp32)."""
    data = batches(4)
    ref = run_split(make_engine(sp=1, mp=1), data)
    got = run_split(make_engine(sp=2, mp=2), data)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_sp_fused_train_batch():
    """The fused train_batch program agrees with the split API under sp=2."""
    data = batches(4)
    e1 = make_engine(sp=2)
    e2 = make_engine(sp=2)
    split = run_split(e1, data)
    fused = [float(e2.train_batch(b)) for b in data]
    np.testing.assert_allclose(fused, split, rtol=2e-5, atol=2e-6)


def test_sp_with_zero():
    """ZeRO partitioning under a sequence ring matches the sp=1 ZeRO run
    (fp16)."""
    data = batches(5)
    ref = run_split(make_engine(sp=1, zero=True), data)
    got = run_split(make_engine(sp=2, zero=True), data)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-3)


def test_sp_gas_scan():
    """Grad accumulation (lax.scan over micro-batches) under sp=2: fused
    path vs gas=1 equivalence on the summed batch."""
    data = batches(2)
    big = (np.concatenate([d[0] for d in data]),
           np.concatenate([d[1] for d in data]))
    e1 = make_engine(sp=2, train_batch_size=8,
                     gradient_accumulation_steps=2)
    e2 = make_engine(sp=2, train_batch_size=8)
    l1 = float(e1.train_batch(big))
    l2 = float(e2.train_batch(big))
    # same effective batch, same summed grads => same first update
    np.testing.assert_allclose(
        np.asarray(jax.tree_util.tree_leaves(e1.master)[0]),
        np.asarray(jax.tree_util.tree_leaves(e2.master)[0]),
        rtol=1e-5, atol=1e-6)
    assert np.isfinite(l1) and np.isfinite(l2)
